"""Trace loader tests (`testdata/src/lib.rs:50-59` analog) + oracle trace
replay with final-content assertion (the criterion benches' check,
`benches/yjs.rs:46`)."""
import pytest

from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.utils.testdata import load_testing_data, trace_path


def test_load_sveltecomponent():
    data = load_testing_data(trace_path("sveltecomponent"))
    assert data.start_content == ""
    assert len(data.txns) == 18_335
    assert data.num_patches() == 19_749
    assert len(data.end_content) == 18_451


# Slow tier since PR 17 (wall budget: ~21 s of the 870 s gate —
# full-corpus decompress + per-patch walk); corpus loading keeps
# tier-1 coverage via test_load_sveltecomponent and the automerge
# prefix replay below.
@pytest.mark.slow
def test_load_automerge_paper_counts():
    data = load_testing_data(trace_path("automerge-paper"))
    assert len(data.txns) == 259_778
    ins = sum(len(p.ins_content) for t in data.txns for p in t.patches)
    dels = sum(p.del_len for t in data.txns for p in t.patches)
    assert ins == 182_315
    assert dels == 77_463
    assert len(data.end_content) == 104_852


@pytest.mark.slow
def test_oracle_replays_sveltecomponent():
    data = load_testing_data(trace_path("sveltecomponent"))
    doc = ListCRDT(capacity=1 << 18)
    agent = doc.get_or_create_agent_id("trace")
    for txn in data.txns:
        for p in txn.patches:
            if p.del_len:
                doc.local_delete(agent, p.pos, p.del_len)
            if p.ins_content:
                doc.local_insert(agent, p.pos, p.ins_content)
    assert doc.to_string() == data.end_content
    doc.check()


def test_oracle_replays_automerge_paper_prefix():
    data = load_testing_data(trace_path("automerge-paper"))
    doc = ListCRDT(capacity=1 << 16)
    agent = doc.get_or_create_agent_id("trace")
    text = ""
    for txn in data.txns[:4000]:
        for p in txn.patches:
            if p.del_len:
                text = text[: p.pos] + text[p.pos + p.del_len:]
                doc.local_delete(agent, p.pos, p.del_len)
            if p.ins_content:
                text = text[: p.pos] + p.ins_content + text[p.pos:]
                doc.local_insert(agent, p.pos, p.ins_content)
    assert doc.to_string() == text
    doc.check()

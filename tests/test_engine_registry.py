"""The engine registry (``config.ENGINE_REGISTRY``) is the ONE source
of engine names (VERDICT r5 weak #6: ``rle-lanes-mixed`` was missing
from ``ENGINE_CHOICES`` while bench.py recorded rows under it).  These
tests hold the registry, bench.py, and README's tables to each other.
"""
import importlib
import os
import re

from text_crdt_rust_tpu.config import (
    ENGINE_CHOICES,
    ENGINE_REGISTRY,
    ENGINE_ROW_ALIASES,
    engines_for,
    lane_block_geometry,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _known(name: str) -> bool:
    if name in ENGINE_REGISTRY:
        return True
    if name in ENGINE_ROW_ALIASES:
        alias = ENGINE_ROW_ALIASES[name]
        return alias is None or alias in ENGINE_REGISTRY
    return False


def test_choices_derive_from_registry():
    assert ENGINE_CHOICES == tuple(ENGINE_REGISTRY)
    assert "rle-lanes-mixed" in ENGINE_REGISTRY  # the r5 drift


def test_registry_modules_import():
    for name, spec in ENGINE_REGISTRY.items():
        mod = importlib.import_module(
            f"text_crdt_rust_tpu.{spec['module']}")
        assert mod is not None, name


def test_bench_engine_rows_are_registered():
    """Every engine label bench.py records (literal strings passed to
    make_row) resolves through the registry or the alias map."""
    with open(os.path.join(ROOT, "bench.py")) as f:
        src = f.read()
    # make_row(config, engine, ...): literal engine labels only (the
    # args.engine call sites are constrained by ENGINE_CHOICES already).
    labels = re.findall(
        r"make_row\(\s*\"[^\"]+\",\s*\n?\s*\"([^\"]+)\"", src)
    labels += re.findall(r"make_row\(f\"[^\"]+\", \"([^\"]+)\"", src)
    assert labels, "no literal engine labels found — regex drifted?"
    for label in labels:
        assert _known(label), (
            f"bench.py records rows under engine {label!r} which is "
            f"neither in ENGINE_REGISTRY nor ENGINE_ROW_ALIASES")


def test_readme_engine_table_is_registered():
    """Every engine named in README's measured-results table resolves
    through the registry or the alias map."""
    with open(os.path.join(ROOT, "README.md")) as f:
        lines = f.readlines()
    seen = []
    for ln in lines:
        # Bench-table rows: | workload | engine | ops/s | vs |
        cells = [c.strip() for c in ln.split("|")]
        if len(cells) >= 5 and cells[3].endswith(("G", "M", "k", "×")):
            label = re.sub(r"\s*\(.*\)", "", cells[2]).strip()
            if label and not set(label) <= {"-"}:
                seen.append(label.replace(" ", "-"))
    assert seen, "README bench table not found — format drifted?"
    for label in seen:
        assert _known(label), (
            f"README names engine {label!r} which is neither in "
            f"ENGINE_REGISTRY nor ENGINE_ROW_ALIASES")


def test_engines_for_covers_streaming_configs():
    assert "rle-lanes" in engines_for("5")
    assert "rle-lanes-mixed" in engines_for("5r")
    assert set(engines_for("northstar")) == {"rle", "rle-hbm", "blocked",
                                             "hbm"}


def test_lane_block_geometry_rounds_up():
    cap, nb, nbt = lane_block_geometry(201, 64)
    assert (cap, nb, nbt) == (256, 4, 8)
    cap, nb, nbt = lane_block_geometry(1664, 64)
    assert (cap, nb, nbt) == (1664, 26, 26)

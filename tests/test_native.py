"""Differential tests: C++ native engine vs the Python oracle.

The native engine (order-statistic treap of RLE spans) must agree with the
item-granular oracle on every observable: text, canonical merged spans,
frontier, deletes log, double-deletes log. SURVEY §4's "dual oracle"
strategy.
"""
import random

import pytest

from text_crdt_rust_tpu import LocalOp
from text_crdt_rust_tpu.models.native import NativeListCRDT
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since, merge_into
from text_crdt_rust_tpu.utils.testdata import load_testing_data, trace_path

ALPHABET = "abcdefghijklmnop_"


def assert_equivalent(nat: NativeListCRDT, orc: ListCRDT):
    assert nat.to_string() == orc.to_string()
    assert len(nat) == len(orc)
    assert nat.doc_spans() == orc.doc_spans()
    assert nat.frontier == orc.frontier
    assert nat.deletes_entries() == [
        (e.op_order, e.target, e.length) for e in orc.deletes
    ]
    assert nat.double_deletes_entries() == [
        (e.target, e.length, e.excess) for e in orc.double_deletes
    ]


def test_native_smoke_matches_oracle():
    nat, orc = NativeListCRDT(), ListCRDT()
    for d in (nat, orc):
        a = d.get_or_create_agent_id("seph")
        d.local_insert(a, 0, "hi")
        d.local_insert(a, 1, "yooo")
        d.local_delete(a, 0, 3)
    assert_equivalent(nat, orc)


@pytest.mark.parametrize("seed", range(8))
def test_native_local_fuzz_vs_oracle(seed):
    rng = random.Random(seed)
    nat, orc = NativeListCRDT(), ListCRDT()
    na = nat.get_or_create_agent_id("seph")
    oa = orc.get_or_create_agent_id("seph")
    for step in range(400):
        doc_len = len(orc)
        if doc_len == 0 or rng.random() < 0.5:
            pos = rng.randint(0, doc_len)
            s = "".join(rng.choice(ALPHABET)
                        for _ in range(rng.randint(1, 3)))
            nat.local_insert(na, pos, s)
            orc.local_insert(oa, pos, s)
        elif rng.random() < 0.85:
            pos = rng.randint(0, doc_len - 1)
            span = rng.randint(1, min(8, doc_len - pos))
            nat.local_delete(na, pos, span)
            orc.local_delete(oa, pos, span)
        else:
            # Mixed txn: delete + insert at the same position.
            pos = rng.randint(0, doc_len - 1)
            span = rng.randint(1, min(4, doc_len - pos))
            s = "".join(rng.choice(ALPHABET)
                        for _ in range(rng.randint(1, 2)))
            op = LocalOp(pos=pos, ins_content=s, del_span=span)
            nat.apply_local_txn(na, [op])
            orc.apply_local_txn(oa, [op])
        if step % 37 == 0:
            assert_equivalent(nat, orc)
    assert_equivalent(nat, orc)
    orc.check()


@pytest.mark.parametrize("seed", range(6))
def test_native_remote_apply_matches_oracle(seed):
    """Concurrent 3-peer oracle history, streamed into a native doc via
    apply_remote_txn — exercises remote integrate, fragmented deletes and
    double deletes on the native engine."""
    rng = random.Random(5000 + seed)
    names = ["alice", "bob", "carol"]
    peers = []
    for nm in names:
        d = ListCRDT()
        d.get_or_create_agent_id(nm)
        peers.append(d)
    for _ in range(10):
        for d in peers:
            for _ in range(rng.randint(1, 3)):
                doc_len = len(d)
                if doc_len == 0 or rng.random() < 0.55:
                    pos = rng.randint(0, doc_len)
                    s = "".join(rng.choice(ALPHABET)
                                for _ in range(rng.randint(1, 2)))
                    d.local_insert(0, pos, s)
                else:
                    pos = rng.randint(0, doc_len - 1)
                    d.local_delete(0, pos,
                                   rng.randint(1, min(6, doc_len - pos)))
        i, j = rng.sample(range(3), 2)
        merge_into(peers[i], peers[j])
        merge_into(peers[j], peers[i])
    for _ in range(2):
        for i in range(3):
            for j in range(3):
                if i != j:
                    merge_into(peers[i], peers[j])

    # Stream peer 0's full history into both a fresh oracle and a fresh
    # native doc; all three must agree.
    txns = export_txns_since(peers[0], 0)
    nat, orc = NativeListCRDT(), ListCRDT()
    for t in txns:
        nat.apply_remote_txn(t)
        orc.apply_remote_txn(t)
    assert orc.to_string() == peers[0].to_string()
    assert_equivalent(nat, orc)


@pytest.mark.slow
def test_native_replays_sveltecomponent():
    data = load_testing_data(trace_path("sveltecomponent"))
    nat = NativeListCRDT()
    a = nat.get_or_create_agent_id("trace")
    pos, dels, ins_lens, cps = [], [], [], []
    for txn in data.txns:
        for p in txn.patches:
            pos.append(p.pos)
            dels.append(p.del_len)
            ins_lens.append(len(p.ins_content))
            cps.extend(ord(c) for c in p.ins_content)
    nat.replay_trace(a, pos, dels, ins_lens, cps)
    assert nat.to_string() == data.end_content


@pytest.mark.slow
def test_native_replays_automerge_paper():
    data = load_testing_data(trace_path("automerge-paper"))
    nat = NativeListCRDT()
    a = nat.get_or_create_agent_id("trace")
    pos, dels, ins_lens, cps = [], [], [], []
    for txn in data.txns:
        for p in txn.patches:
            pos.append(p.pos)
            dels.append(p.del_len)
            ins_lens.append(len(p.ins_content))
            cps.extend(ord(c) for c in p.ins_content)
    nat.replay_trace(a, pos, dels, ins_lens, cps)
    assert nat.to_string() == data.end_content
    assert len(nat) == len(data.end_content)

"""Tick-train-vs-serial equivalence (ISSUE 20 tentpole).

Tick trains (``ServeConfig.train_ticks`` > 1) buffer T ticks' op
tensors + prefill-delta scatters and replay them as ONE device
``lax.scan`` program, collapsing T dispatch overheads into one.  The
contract that makes the scheduler safe to ship: train length moves
WALL TIME ONLY — same-seed runs at any train length must emit
byte-identical logical trace streams (flow spans included), identical
green conservation audits, and identical logical counters, under 10%
faults, forced mid-run evict->restore, and a crash at a train boundary
(the PR 16 chaos harness).  Plus the fixed-shape discipline: train
lengths pad to a small power-of-two series so steady state never
recompiles, and the device overflow flag is defense in depth behind
the pending-aware host-mirror capacity gate.
"""
import dataclasses

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.ops import batch as B  # noqa: E402
from text_crdt_rust_tpu.ops import flat as F  # noqa: E402
from text_crdt_rust_tpu.ops import span_arrays as SA  # noqa: E402
from text_crdt_rust_tpu.serve.batcher import FlatLaneBackend  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402
from text_crdt_rust_tpu.serve.server import DocServer  # noqa: E402

LOGICAL_KEYS = ("item_ops_applied", "rejected_submissions",
                "drain_rounds")
LOGICAL_TICK_KEYS = ("steps_total", "steps_prefuse", "fused_rows_saved",
                     "ops_per_step", "device_compiles")
LOGICAL_SRV_KEYS = ("device_ticks", "device_steps", "evictions",
                    "restores", "admitted", "ckpt_bytes_written")


def _loadgen_run(train_ticks: int, docs: int = 8, ticks: int = 10):
    # The sanitizer rides the train arms: buffered tensors are held
    # across ticks, exactly the aliasing window it watches.
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=4,
                      pipeline_ticks=2, train_ticks=train_ticks,
                      trace_keep=True, sanitize_pipeline=train_ticks > 1,
                      flow_sample_mod=1)
    gen = ServeLoadGen(docs=docs, agents_per_doc=2, ticks=ticks,
                       events_per_tick=12, fault_rate=0.10, seed=7,
                       cfg=cfg)
    rep = gen.run()
    return rep, gen.server.tracer.logical_bytes()


def test_train_vs_serial_byte_identical_under_faults():
    """The tentpole contract: depths 1/2/4 under 10% faults — logical
    streams, flow census and the ledger-gated counters identical; only
    the dispatch economy (and wall) moves."""
    runs = {t: _loadgen_run(t) for t in (1, 2, 4)}
    rep_1, trace_1 = runs[1]
    for t, (rep, trace) in runs.items():
        assert rep["converged"], t
        assert trace == trace_1, \
            f"logical stream must be train-length-invariant (depth {t})"
        assert rep["flow"]["audit_ok"], rep["flow"]["findings"][:4]
        assert rep["flow"]["spans"] == rep_1["flow"]["spans"]
        assert rep["flow"]["ages_ticks"] == rep_1["flow"]["ages_ticks"]
        for key in LOGICAL_KEYS:
            assert rep[key] == rep_1[key], key
        for key in LOGICAL_TICK_KEYS:
            assert rep["tick_ms"][key] == rep_1["tick_ms"][key], key
        for key in LOGICAL_SRV_KEYS:
            assert rep["server"].get(key) == rep_1["server"].get(key), key
        assert rep["wire"] == rep_1["wire"]
        assert rep["train"]["ticks"] == t
    # Depth 1 is exactly the serial dispatch economy; deeper trains cut
    # dispatches/tick (partial flushes keep the small-shape cut < T).
    assert runs[1][0]["train"]["dispatch_cut_x"] == 1.0
    assert runs[1][0]["train"]["train_compiles"] == 0
    assert runs[4][0]["train"]["dispatch_cut_x"] > \
        runs[1][0]["train"]["dispatch_cut_x"]
    assert runs[4][0]["train"]["device_dispatches"] < \
        runs[1][0]["train"]["device_dispatches"]


def _direct_server_run(train_ticks: int):
    """Direct-server drive with a FORCED mid-run evict->restore while a
    train may be open — the residency boundary a buffered tick must not
    smear state across."""
    cfg = ServeConfig(engine="flat", num_shards=1, lanes_per_shard=2,
                      pipeline_ticks=2, train_ticks=train_ticks,
                      trace_keep=True, sanitize_pipeline=train_ticks > 1,
                      flow_sample_mod=1)
    server = DocServer(cfg)
    for d in range(3):
        server.admit_doc(f"doc{d}")
    for i in range(4):
        for d in range(3):
            server.submit_local(f"doc{d}", "alice", pos=0,
                                ins_content=f"t{i}d{d}x")
        server.tick()
    doc0 = server.doc_state("doc0")
    if doc0.resident:
        server.residency.evict(doc0)
    for i in range(3):
        for d in range(3):
            server.submit_local(f"doc{d}", "alice", pos=0,
                                ins_content=f"u{i}d{d}y")
        server.tick()
    server.drain()
    assert all(server.verify_doc(f"doc{d}") for d in range(3))
    strings = [server.doc_string(f"doc{d}") for d in range(3)]
    flow = server.flow_summary(expect_terminal=True)
    trace = server.tracer.logical_bytes()
    server.close_obs()
    return strings, flow, trace, server


def test_mid_run_evict_restore_equivalence():
    runs = {t: _direct_server_run(t) for t in (1, 2, 4)}
    strings_1, flow_1, trace_1, _ = runs[1]
    for t, (strings, flow, trace, srv) in runs.items():
        assert strings == strings_1, t
        assert trace == trace_1, t
        assert flow["audit_ok"]
        assert flow["spans"] == flow_1["spans"]
        ev = srv.counters.summary().get("evictions")
        assert ev == runs[1][3].counters.summary().get("evictions")
        assert ev >= 1


def test_recompile_guard_train_bucket_series():
    """Steady-state discipline: every compiled train key is (T-bucket,
    S-bucket) with T drawn from the power-of-two pad series and S from
    the step buckets — the compile set stays additive, bounded by
    |T buckets| x |S buckets| per backend."""
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=4,
                      train_ticks=4, trace_keep=True)
    gen = ServeLoadGen(docs=8, agents_per_doc=2, ticks=12,
                       events_per_tick=12, fault_rate=0.10, seed=7,
                       cfg=cfg)
    rep = gen.run()
    assert rep["converged"]
    t_series = {1, 2, 4}
    s_series = set(cfg.step_buckets)
    for b in gen.server.residency.backends:
        for (t_bkt, s_bkt) in b.train_shapes_seen:
            assert t_bkt in t_series, (t_bkt, s_bkt)
            assert s_bkt in s_series, (t_bkt, s_bkt)
        assert len(b.train_shapes_seen) <= len(t_series) * len(s_series)
    assert rep["train"]["train_compiles"] >= 1


def _insert_tick(i: int, ins: int, lmax: int = 4) -> B.OpTensors:
    """One single-lane [S=1, B=1] tick: a local insert of ``ins`` chars
    (integration details don't matter to the capacity flag — only the
    ins_len/order_advance column sums the bounds read)."""
    one = B.pad_ops(B.empty_ops(lmax), 1)
    one = dataclasses.replace(
        one,
        ins_len=np.full((1,), ins, np.uint32),
        order_advance=np.full((1,), ins, np.uint32),
        ins_order_start=np.full((1,), 1 + ins * i, np.uint32),
        rows_per_step=np.ones((1,), np.uint32))
    return B.stack_ops([one])


def test_capacity_flag_at_train_boundary():
    """The device overflow flag accumulates across ALL T ticks and
    reads true iff some tick exceeded the static bounds mid-train —
    same bounds as ``check_capacity_counts``, evaluated per tick."""
    docs = jax.tree.map(jnp.array,
                        SA.stack_docs(SA.make_flat_doc(8, 64), 1))
    ok = B.stack_ticks([_insert_tick(i, 4) for i in range(2)])
    out, flag = F.apply_train(docs, ok)
    assert not bool(flag)          # 8 chars == capacity 8: exactly fits
    assert int(np.asarray(out.n)[0]) == 8
    over = B.stack_ticks([_insert_tick(i, 4) for i in range(3)])
    _, flag = F.apply_train(docs, over)
    assert bool(flag)              # 12 chars > capacity 8, tick 3 of 3


def test_pending_aware_host_gate_refuses_overflow_trains():
    """The authoritative gate stays host-side: with ticks buffered in
    an open train, the mirror capacity check counts the PENDING column
    sums too, so a tick the serial loop would refuse is refused at the
    same logical position — the device flag never fires via serve."""
    be = FlatLaneBackend(lanes=1, capacity=8, order_capacity=64, lmax=4)
    be.set_train_ticks(4)
    be.apply(_insert_tick(0, 4))
    assert len(be._train_buf) == 1     # buffered, not dispatched
    with pytest.raises(AssertionError, match="capacity"):
        be.apply(_insert_tick(1, 8))   # 4 pending + 8 > 8
    be.apply(_insert_tick(1, 4))       # 4 + 4 == 8 still fits
    be.flush_train()
    assert int(be._n_host[0]) == 8
    assert not be._train_buf and not be._train_flags


def test_overflow_flag_raises_at_drain():
    """Defense in depth: a set train flag is a contract violation (the
    docs are corrupt, not merely full) and raises loudly at the drain
    instead of degrading."""
    be = FlatLaneBackend(lanes=1, capacity=8, order_capacity=64, lmax=4)
    be._train_flags.append(jnp.asarray(True))
    with pytest.raises(RuntimeError, match="overflow flag"):
        be._drain_train_flags(block=True)


def test_train_depth_clamps():
    """Backends opt in via max_train_ticks: flat device-prefill caps at
    8, flat host-prefill and the lanes backend stay serial (1); the
    batcher's effective length is the min across backends."""
    cfg = ServeConfig(engine="flat", num_shards=1, lanes_per_shard=2,
                      train_ticks=16)
    server = DocServer(cfg)
    assert server.batcher.train_ticks == 16
    assert server.batcher.effective_train_ticks() == 8
    server.close_obs()
    cfg_h = ServeConfig(engine="flat", num_shards=1, lanes_per_shard=2,
                        train_ticks=4, device_prefill=False)
    server_h = DocServer(cfg_h)
    assert server_h.batcher.effective_train_ticks() == 1
    server_h.close_obs()
    cfg_l = ServeConfig(engine="rle-lanes-mixed", lane_capacity=128,
                        lanes_block_k=8, order_capacity=512,
                        step_buckets=(8, 32), max_txn_len=32,
                        num_shards=1, lanes_per_shard=2, train_ticks=4)
    server_l = DocServer(cfg_l)
    assert server_l.batcher.effective_train_ticks() == 1
    server_l.close_obs()


def test_train_bucket_pow2_series():
    """Partial trains re-use bucketed programs: the pad series is the
    smallest power of two >= the flushed length."""
    for t, want in ((1, 1), (2, 2), (3, 4), (4, 4), (5, 8), (8, 8)):
        assert FlatLaneBackend._train_bucket(t) == want, t


def test_stack_ticks_train_major_shapes():
    """[S, B] ticks stack to a train-major [T, S, B] batch with dtypes
    and per-tick contents preserved."""
    ticks = [_insert_tick(i, 4) for i in range(3)]
    train = B.stack_ticks(ticks)
    for f in ("ins_len", "order_advance", "rows_per_step"):
        col = np.asarray(getattr(train, f))
        want = np.asarray(getattr(ticks[0], f))
        assert col.shape == (3,) + want.shape, f
        assert col.dtype == want.dtype, f
        for i in range(3):
            np.testing.assert_array_equal(
                col[i], np.asarray(getattr(ticks[i], f)), err_msg=f)


def test_stack_ticks_noop_pad_is_exact_noop():
    """The short-train pad contract ``_dispatch_train`` relies on: an
    all-zero tick appended to a train leaves the post-train device
    state bit-identical to the unpadded train."""
    docs = jax.tree.map(jnp.array,
                        SA.stack_docs(SA.make_flat_doc(8, 64), 1))
    tick = _insert_tick(0, 4)
    zero = jax.tree.map(lambda a: np.zeros_like(np.asarray(a)), tick)
    out1, flag1 = F.apply_train(docs, B.stack_ticks([tick]))
    out2, flag2 = F.apply_train(docs, B.stack_ticks([tick, zero]))
    assert not bool(flag1) and not bool(flag2)
    mismatch = jax.tree.map(
        lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)),
        out1, out2)
    assert all(jax.tree.leaves(mismatch))


def test_concat_deltas_none_handling():
    """No-insert ticks contribute nothing: all-None -> None (skip the
    scatter dispatch entirely), a single live delta passes through."""
    assert B.concat_deltas([None, None]) is None
    d = B.prefill_delta(_insert_tick(0, 4))
    assert d is not None
    assert B.concat_deltas([None, d, None]) is d


def test_concat_deltas_disjoint_concat_and_bucket():
    """Two per-tick deltas concatenate in tick order and re-pad to the
    shared scatter-bucket series (the train path draws from the SAME
    compiled scatter set as the serial path)."""
    d0 = B.prefill_delta(_insert_tick(0, 4))
    d1 = B.prefill_delta(_insert_tick(1, 4))
    cat = B.concat_deltas([d0, d1])
    assert cat.bucket == B.scatter_bucket(d0.bucket + d1.bucket)
    assert cat.bucket in {B.PREFILL_BUCKET_BASE * 4 ** k
                          for k in range(6)}
    pos = np.asarray(cat.ins_pos)
    np.testing.assert_array_equal(pos[..., :d0.bucket],
                                  np.asarray(d0.ins_pos))
    np.testing.assert_array_equal(
        pos[..., d0.bucket:d0.bucket + d1.bucket],
        np.asarray(d1.ins_pos))
    assert (pos[..., d0.bucket + d1.bucket:] == B.PREFILL_PAD).all()


def test_flush_train_empty_is_noop():
    """The pre-read sync point is safe to call with nothing buffered —
    no dispatch, no stats, no mirror movement."""
    be = FlatLaneBackend(lanes=1, capacity=8, order_capacity=64, lmax=4)
    be.set_train_ticks(4)
    before = dict(be.train_stats)
    be.flush_train()
    assert be.train_stats == before
    assert int(be._n_host[0]) == 0 and not be._train_flags


def test_train_summary_dispatch_economy_maths():
    """The ledger-gated ride-alongs are pure arithmetic over the
    logical dispatch counters (seed-deterministic, platform-free)."""
    be = FlatLaneBackend(lanes=1, capacity=8, order_capacity=64, lmax=4)
    be.set_train_ticks(4)
    be.train_stats.update(trains=2, ticks_sum=4, dispatches=3,
                          serial_equiv=8)
    s = be.train_summary()
    assert s["device_dispatches"] == 3
    assert s["dispatch_cut_x"] == round(8 / 3, 2)
    assert s["train_len"] == 2.0
    assert s["train_ticks"] == 4


def test_serial_path_unchanged_at_depth_one():
    """train_ticks=1 (the default) takes the exact pre-train serial
    path: no buffering, one tick -> immediate dispatch, mirrors advance
    by the tick's column sums, no train programs compiled."""
    be = FlatLaneBackend(lanes=1, capacity=8, order_capacity=64, lmax=4)
    assert be.train_ticks == 1
    be.apply(_insert_tick(0, 4))
    assert not be._train_buf and not be._train_flags
    assert int(be._n_host[0]) == 4
    assert int(be._next_order_host[0]) == 4
    assert be.train_summary()["dispatch_cut_x"] == 1.0
    assert be.train_summary()["train_compiles"] == 0


@pytest.mark.slow
def test_crash_at_train_boundary_recovery():
    """PR 16 interplay, loud half: kill the server right after a tick
    that closes a train (post-dispatch), recover from the journal,
    resume, and match an uncrashed same-seed twin byte for byte."""
    from text_crdt_rust_tpu.serve.chaos import run_crash_scenario

    cell = run_crash_scenario("post-dispatch", 4, ticks=10, docs=8,
                              agents_per_doc=2, events_per_tick=10,
                              seed=11, fault_rate=0.10, train_ticks=2)
    assert cell["identical"], (cell["digest"], cell["twin_digest"])
    assert cell["converged"] and cell["twin_converged"]
    assert cell["at_recovery_audit"]["audit_ok"]
    assert cell["final_audit"]["audit_ok"]


@pytest.mark.slow
def test_recovery_replays_across_train_lengths():
    """The journal-interplay satellite: a journal written at
    train_ticks=2 recovers sha-identical on a server configured at a
    DIFFERENT train length (4) — per-tick journal markers make train
    length a pure wall-clock knob end to end."""
    from text_crdt_rust_tpu.serve.chaos import run_crash_scenario

    cell = run_crash_scenario("post-dispatch", 4, ticks=10, docs=8,
                              agents_per_doc=2, events_per_tick=10,
                              seed=11, fault_rate=0.10, train_ticks=2,
                              recover_train_ticks=4)
    assert cell["identical"], (cell["digest"], cell["twin_digest"])
    assert cell["converged"] and cell["twin_converged"]
    assert cell["at_recovery_audit"]["audit_ok"]
    assert cell["final_audit"]["audit_ok"]

"""Fused multi-row insert steps (split-batch prepare, ISSUE 5).

The kevin worst case (`benches/yjs.rs:51-62`) is a backwards-contiguous
insert burst: every char lands at the same position, BEFORE the previous
one, so runs cannot merge and the unfused engines pay one device step
per character.  ``batch.compile_local_patches(fuse_w=W)`` compiles such
bursts into ONE ``rows_per_step=W`` step whose W pre-built rows the
``ops.rle`` / ``ops.rle_hbm`` splice lands in a single shift.

The correctness burden (same as the PR-2/4 blocked engines): fused and
unfused streams must be bit-identical — the final ``expand_runs`` order
sequence AND the merged by-order logs (``rle_to_flat``: origins, ranks,
chars) — against each other and the flat-engine oracle, because the
fused rows bake in origin chains the unfused path derives step-by-step.

Shapes are FIXED across seeds (pad to SMAX, one geometry) so the whole
file costs a handful of pallas interpret compiles, keeping tier-1
inside its budget.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import rle_hbm as RH
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import TestPatch

SMAX = 128     # fixed padded step count (all streams share one trace)
CAPF = 512     # run-row capacity
KF = 16        # block_k (tiny: fused steps hit leaf splits constantly)
FW = 6         # fuse width under test (<= KF//2 - 1 = 7)
GEOM = dict(capacity=CAPF, batch=8, block_k=KF, chunk=64, interpret=True)

DOC_FIELDS = ("signed", "ol_log", "or_log", "rank_log", "chars_log",
              "n", "next_order")


def _compile_pair(patches, fuse_w=FW, lmax=16):
    """(unfused, fused) op tensors of one patch stream, padded to SMAX."""
    ops_u, no_u = B.compile_local_patches(patches, lmax=lmax, dmax=None)
    ops_f, no_f = B.compile_local_patches(patches, lmax=lmax, dmax=None,
                                          fuse_w=fuse_w)
    assert no_u == no_f
    assert ops_u.num_steps <= SMAX and ops_f.num_steps <= SMAX, \
        "bump SMAX"
    return B.pad_ops(ops_u, SMAX), B.pad_ops(ops_f, SMAX)


def _assert_equivalent(ops_u, ops_f, res_u, res_f, content=None):
    assert np.array_equal(R.expand_runs(res_u), R.expand_runs(res_f)), \
        "fused expand_runs order sequence diverged from unfused"
    du = R.rle_to_flat(ops_u, res_u, capacity=1024)
    df = R.rle_to_flat(ops_f, res_f, capacity=1024)
    for f in DOC_FIELDS:
        assert np.array_equal(np.asarray(getattr(du, f)),
                              np.asarray(getattr(df, f))), f
    if content is not None:
        assert SA.to_string(df) == content
    return du, df


def burst_patches(rng, n):
    """Mixed stream: same-position insert bursts (prepend-heavy, the
    fusable shape) + forward typing + deletes.  Always OPENS with a
    full-width burst so every compiled stream carries rows_per_step ==
    FW (one static WMAX -> one kernel compile for the whole file)."""
    patches, content = [], ""
    for _ in range(FW):
        patches.append(TestPatch(0, 0, "s"))
        content = "s" + content
    while len(patches) < n:
        roll = rng.random()
        if roll < 0.45:
            pos = rng.randrange(len(content) + 1)
            L = rng.randint(1, 2)
            for _ in range(rng.randint(2, FW + 3)):
                s = "".join(rng.choice("abcdefgh") for _ in range(L))
                patches.append(TestPatch(pos, 0, s))
                content = content[:pos] + s + content[pos:]
        elif roll < 0.75:
            pos = rng.randrange(len(content) + 1)
            s = "".join(rng.choice("xyz")
                        for _ in range(rng.randint(1, 5)))
            patches.append(TestPatch(pos, 0, s))
            content = content[:pos] + s + content[pos:]
        elif content:
            pos = rng.randrange(len(content))
            d = min(rng.randint(1, 6), len(content) - pos)
            patches.append(TestPatch(pos, d, ""))
            content = content[:pos] + content[pos + d:]
    return patches, content


class TestFusedCompile:
    def test_burst_detection_and_chunking(self):
        patches = [TestPatch(3, 0, "ab")] * 7 + [TestPatch(0, 0, "q")]
        ops, _ = B.compile_local_patches(patches, lmax=8, fuse_w=4)
        # 7-burst of L=2 chunks at min(fuse_w, lmax//L)=4: [4, 3] + tail.
        assert ops.rows_per_step.tolist() == [4, 3, 1]
        assert ops.ins_len.tolist() == [8, 6, 1]
        assert B.fused_width(ops) == 4

    def test_w1_degenerate_is_todays_stream(self):
        # A burst-free stream compiles IDENTICALLY with fusion enabled.
        patches = [TestPatch(0, 0, "abc"), TestPatch(3, 0, "de"),
                   TestPatch(1, 2, ""), TestPatch(0, 0, "zz")]
        ops_u, _ = B.compile_local_patches(patches, lmax=8)
        ops_f, _ = B.compile_local_patches(patches, lmax=8, fuse_w=8)
        for name in ops_u.__dataclass_fields__:
            assert np.array_equal(np.asarray(getattr(ops_u, name)),
                                  np.asarray(getattr(ops_f, name))), name

    def test_fuse_respects_lmax(self):
        # lmax // L < 2 -> no fusion even for a perfect burst.
        patches = [TestPatch(0, 0, "abcde")] * 4
        ops, _ = B.compile_local_patches(patches, lmax=8, fuse_w=8)
        assert B.fused_width(ops) == 1
        assert ops.num_steps == 4

    def test_row_growth_bound_ops(self):
        patches = [TestPatch(0, 0, "x")] * 8
        ops, _ = B.compile_local_patches(patches, lmax=8, fuse_w=4)
        assert B.row_growth_bound_ops(ops) == 1 + 2 * (4 + 1)
        ops_u, _ = B.compile_local_patches(patches, lmax=8)
        assert B.row_growth_bound_ops(ops_u) == B.row_growth_bound(8)

    def test_unfused_engines_reject_fused_streams(self):
        patches = [TestPatch(0, 0, "x")] * 4
        ops, _ = B.compile_local_patches(patches, lmax=4, fuse_w=4)
        with pytest.raises(ValueError, match="fused"):
            F.apply_ops(SA.make_flat_doc(64), ops)
        # ...and the fused engines bound W by the one-split headroom.
        with pytest.raises(ValueError, match="headroom"):
            R.replay_local_rle(ops, capacity=64, batch=8, block_k=8,
                               chunk=32, interpret=True)

    def test_reject_message_derives_from_registry(self):
        # The reject error names the CURRENT fused engines from the ONE
        # registry — no hard-coded module list to rot (ISSUE 6).
        from text_crdt_rust_tpu.config import ENGINE_REGISTRY
        patches = [TestPatch(0, 0, "x")] * 4
        ops, _ = B.compile_local_patches(patches, lmax=4, fuse_w=4)
        fused = tuple(n for n, s in ENGINE_REGISTRY.items()
                      if s.get("fused_steps"))
        assert B.fused_engine_names() == fused
        with pytest.raises(ValueError) as ei:
            B.require_unfused(ops, "flat")
        for name in fused:
            assert name in str(ei.value)

    def test_registry_fused_flag(self):
        from text_crdt_rust_tpu.config import supports_fused_steps
        assert supports_fused_steps("rle")
        assert supports_fused_steps("rle-hbm")
        assert supports_fused_steps("rle-hbm-fused")  # row alias
        # ISSUE 6: the lanes engines grew the W-row splice.
        assert supports_fused_steps("rle-lanes")
        assert supports_fused_steps("rle-lanes-mixed")
        assert not supports_fused_steps("flat")
        assert not supports_fused_steps("native-cpp")


class TestFusedKernels:
    def test_kevin_shape_vmem_and_hbm(self):
        # Pure prepends: every step is a full-width fused splice; the
        # final doc order must read N-1..0 (orders reversed).
        n = 126  # a whole number of FW-wide bursts, <= SMAX unfused
        patches = [TestPatch(0, 0, "k")] * n
        ops_u, ops_f = _compile_pair(patches, fuse_w=FW, lmax=FW)
        want = np.arange(n, 0, -1, dtype=np.int32)
        for mk in (R.replay_local_rle, RH.replay_local_rle_hbm):
            res_u = mk(ops_u, **GEOM)
            res_f = mk(ops_f, **GEOM)
            du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                        content="k" * n)
            assert np.array_equal(R.expand_runs(res_f), want)
        # The point of the exercise: ~W x fewer device steps.
        live_u = int((np.asarray(ops_u.ins_len) > 0).sum())
        live_f = int((np.asarray(ops_f.ins_len) > 0).sum())
        assert live_f * FW == live_u

    def test_fused_boundary_exactly_at_block_split(self):
        # Fill slot 0 to KF-FW rows (prepends of distinct chars cannot
        # merge), then one full-width burst: r0 + FW + 1 > KF fires the
        # leaf split and the fused splice lands across the fresh block
        # boundary.  Unfused stream splits at a DIFFERENT row boundary —
        # the logical expansion must still match exactly.
        pre = KF - FW
        patches = [TestPatch(0, 0, "p")] * pre \
            + [TestPatch(0, 0, "b")] * FW + [TestPatch(0, 0, "t")]
        ops_u, ops_f = _compile_pair(patches, fuse_w=FW, lmax=FW)
        res_u = R.replay_local_rle(ops_u, **GEOM)
        res_f = R.replay_local_rle(ops_f, **GEOM)
        _assert_equivalent(ops_u, ops_f, res_u, res_f,
                           content="t" + "b" * FW + "p" * pre)
        assert int(np.asarray(res_f.meta)[0].max()) >= 2, \
            "burst never crossed a block split — geometry drifted"

    def test_fuzz_mixed_streams_bit_identity(self):
        # Mixed prepend/typing/delete streams at one fixed shape, VMEM
        # engine, vs the flat-engine per-keystroke oracle.  3 seeds in
        # tier-1 (the 794s-of-870s budget is nearly spent); the deep
        # sweep + the HBM ride-along run in ``slow``.
        for seed in range(3):
            rng = random.Random(seed)
            patches, content = burst_patches(rng, 60)
            ops_u, ops_f = _compile_pair(patches)
            res_u = R.replay_local_rle(ops_u, **GEOM)
            res_f = R.replay_local_rle(ops_f, **GEOM)
            du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                        content=content)
            ref = F.apply_ops(SA.make_flat_doc(1024), ops_u)
            assert SA.doc_spans(df) == SA.doc_spans(ref), seed

def _event_pair(patches, ranks=None, fuse_w=1, lmax=8):
    """Compile each patch as its OWN step stream (the serve-batcher
    shape: per-event compilation, the host coalescer never runs), then
    concat + one ``fuse_steps`` pass.  Returns (unfused, fused, stats).
    """
    streams, no = [], 0
    for p, rk in zip(patches, ranks or [0] * len(patches)):
        ops, no = B.compile_local_patches(
            [p], rank=rk, lmax=lmax, start_order=no)
        streams.append(ops)
    ops_u = B.concat_ops(streams)
    fused, st = B.fuse_steps(ops_u, fuse_w=fuse_w)
    return ops_u, fused, st


def _flat_pair_equal(ops_u, ops_f, capacity=256):
    """Both streams through the flat oracle; full doc state bit-equal.
    (W = 1 fused streams only — flat rejects multi-row steps.)"""
    du = F.apply_ops(SA.make_flat_doc(capacity), ops_u)
    df = F.apply_ops(SA.make_flat_doc(capacity), ops_f)
    for f in DOC_FIELDS:
        assert np.array_equal(np.asarray(getattr(du, f)),
                              np.asarray(getattr(df, f))), f
    return df


class TestFuseSteps:
    """The GENERALIZED step fuser (ISSUE 6): per-shape fusion rules +
    rejection fallbacks, host-level vs the flat oracle."""

    def test_typing_run_fuses_to_one_step(self):
        patches = [TestPatch(0, 0, "he"), TestPatch(2, 0, "ll"),
                   TestPatch(4, 0, "o")]
        ops_u, fused, st = _event_pair(patches)
        assert fused.num_steps == 1 and st.fused["typing"] == 2
        df = _flat_pair_equal(ops_u, fused)
        assert SA.to_string(df) == "hello"

    def test_backspace_and_forward_sweeps(self):
        typing = [TestPatch(i, 0, "a") for i in range(8)]
        back = [TestPatch(7 - i, 1, "") for i in range(4)]   # backspace
        fwd = [TestPatch(0, 1, "") for _ in range(3)]        # fwd delete
        ops_u, fused, st = _event_pair(typing + back + fwd)
        # typing -> 1, backspace sweep -> 1, forward sweep -> 1.
        assert fused.num_steps == 3
        assert st.fused["sweep"] == 5 and st.fused["typing"] == 7
        df = _flat_pair_equal(ops_u, fused)
        assert SA.to_string(df) == "a"

    def test_cross_agent_sweep_fuses(self):
        # Deletes log no rank -> different authors' contiguous deletes
        # fuse into one step.
        typing = [TestPatch(0, 0, "abcdef")]
        dels = [TestPatch(2, 1, ""), TestPatch(2, 1, "")]
        ops_u, fused, st = _event_pair(typing + dels, ranks=[0, 1, 2])
        assert st.fused["sweep"] == 1
        df = _flat_pair_equal(ops_u, fused)
        assert SA.to_string(df) == "abef"

    def test_replace_pair_fuses_cross_agent(self):
        # A pure delete + pure insert at the same position -> the ONE
        # dual-branch KIND_LOCAL row a compiled replace already is;
        # the delete's author logs nothing, so authors may differ.
        patches = [TestPatch(0, 0, "abcd"), TestPatch(1, 2, ""),
                   TestPatch(1, 0, "XY")]
        ops_u, fused, st = _event_pair(patches, ranks=[0, 1, 0])
        assert st.fused["replace"] == 1 and fused.num_steps == 2
        df = _flat_pair_equal(ops_u, fused)
        assert SA.to_string(df) == "aXYd"
        # The fused row fires BOTH branches in one step.
        both = (np.asarray(fused.del_len) > 0) \
            & (np.asarray(fused.ins_len) > 0)
        assert both.sum() == 1

    def test_cross_agent_insert_does_not_fuse(self):
        # Insert-bearing fusion merges rank attribution -> requires
        # equal ranks; a differing author falls back to its own step.
        patches = [TestPatch(0, 0, "ab"), TestPatch(2, 0, "cd")]
        ops_u, fused, st = _event_pair(patches, ranks=[0, 1])
        assert fused.num_steps == 2 and st.rows_saved == 0
        _flat_pair_equal(ops_u, fused)

    def test_overlap_rejection_falls_back(self):
        # An op whose position lands INSIDE the previous op's span (not
        # chaining at its tail) can never satisfy the contiguity rules
        # -> no fusion, byte-identical passthrough.
        patches = [TestPatch(0, 0, "abcd"), TestPatch(2, 0, "xy")]
        ops_u, fused, st = _event_pair(patches)
        assert st.rows_saved == 0
        for name in ops_u.__dataclass_fields__:
            assert np.array_equal(np.asarray(getattr(ops_u, name)),
                                  np.asarray(getattr(fused, name))), name

    def test_burst_detection_in_fuser_matches_compiler(self):
        # The fuser's backwards-burst rule reproduces the patch-level
        # kevin detector: same rows_per_step layout, same tensors.
        patches = [TestPatch(0, 0, "k")] * 6
        ops_c, _ = B.compile_local_patches(patches, lmax=6, fuse_w=6)
        ops_u, fused, st = _event_pair(patches, fuse_w=6, lmax=6)
        assert st.fused["burst"] == 5
        for name in ops_c.__dataclass_fields__:
            assert np.array_equal(np.asarray(getattr(ops_c, name)),
                                  np.asarray(getattr(fused, name))), name

    def test_remote_runs_fuse(self):
        # Chunked remote insert runs chain across steps (origin_left =
        # previous tail, shared origin_right, continued orders) and
        # contiguous remote delete targets sweep — both fuse; the
        # result replays bit-identically on the flat engine.
        from text_crdt_rust_tpu.common import (
            RemoteDel, RemoteId, RemoteIns, RemoteTxn)
        ROOT = RemoteId("ROOT", 0xFFFFFFFF)
        table = B.AgentTable(["p"])
        # txn 2 continues txn 1's run (origin_left = its tail, shared
        # origin_right, contiguous orders) — the typing-continuation
        # shape, fused ACROSS txns; then two order-contiguous deletes.
        txns = [
            RemoteTxn(RemoteId("p", 0), [ROOT], [
                RemoteIns(ROOT, ROOT, "abcd")]),
            RemoteTxn(RemoteId("p", 4), [RemoteId("p", 3)], [
                RemoteIns(RemoteId("p", 3), ROOT, "efgh")]),
            RemoteTxn(RemoteId("p", 8), [RemoteId("p", 7)], [
                RemoteDel(RemoteId("p", 1), 2),
                RemoteDel(RemoteId("p", 3), 2)]),
        ]
        ops_u, _ = B.compile_remote_txns(txns, table, lmax=8)
        fused, st = B.fuse_steps(ops_u)
        assert st.fused["remote_ins_run"] == 1
        assert st.fused["remote_del_run"] == 1
        _flat_pair_equal(ops_u, fused)

    def test_remote_runs_fuse_on_mixed_lanes(self):
        # The serve path applies fused remote rows via the MIXED lanes
        # kernels: the fused run's single YATA cursor walk and by-order
        # tables must match the unfused per-chunk steps ON THE KERNELS,
        # not just the flat oracle.  Lane 0 carries the unfused stream,
        # lane 1 the fused one, at tests/test_fuzz_blocked.py's fixed
        # geometry so tier-1 pays no extra kernel builds.
        from text_crdt_rust_tpu.common import (
            RemoteDel, RemoteId, RemoteIns, RemoteTxn)
        from text_crdt_rust_tpu.ops import rle_lanes as RL
        from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM
        ROOT = RemoteId("ROOT", 0xFFFFFFFF)
        table = B.AgentTable(["q", "p"])
        txns = [
            # A concurrent rival first: the fused 8-char run's
            # integrate cursor must scan/tiebreak past it exactly as
            # the two chunked 4-char steps would.
            RemoteTxn(RemoteId("q", 0), [ROOT], [
                RemoteIns(ROOT, ROOT, "QQ")]),
            RemoteTxn(RemoteId("p", 0), [ROOT], [
                RemoteIns(ROOT, ROOT, "abcd")]),
            RemoteTxn(RemoteId("p", 4), [RemoteId("p", 3)], [
                RemoteIns(RemoteId("p", 3), ROOT, "efgh")]),
            RemoteTxn(RemoteId("p", 8), [RemoteId("p", 7)], [
                RemoteDel(RemoteId("p", 1), 2),
                RemoteDel(RemoteId("p", 3), 2)]),
        ]
        ops_u, _ = B.compile_remote_txns(txns, table, lmax=8)
        fused, st = B.fuse_steps(ops_u)
        assert st.fused["remote_ins_run"] == 1
        assert st.fused["remote_del_run"] == 1
        stacked = B.stack_ops([B.pad_ops(ops_u, 64),
                               B.pad_ops(fused, 64)])
        kw = dict(capacity=128, order_capacity=256, chunk=32,
                  interpret=True)
        flat = RLM.replay_lanes_mixed(stacked, **kw)
        blk = RLM.replay_lanes_mixed_blocked(stacked, block_k=16, **kw)
        for res in (flat, blk):
            res.check()
            assert (RL.expand_lane(res, 0).tolist()
                    == RL.expand_lane(res, 1).tolist())
            for tab in ("oll", "orl"):
                t = np.asarray(getattr(res, tab))
                assert np.array_equal(t[:, 0], t[:, 1]), tab

    def test_fuser_respects_lmax(self):
        patches = [TestPatch(0, 0, "abc"), TestPatch(3, 0, "def")]
        ops_u, fused, st = _event_pair(patches, lmax=4)
        assert st.rows_saved == 0  # 3 + 3 > lmax 4: no merge

    def test_fuser_respects_dmax(self):
        # A stream chunked at compile-time dmax must not have its
        # delete runs re-merged past it (engines with a hard per-step
        # target cap reject wider runs).
        typing = [TestPatch(0, 0, "abcdefgh")]
        dels = [TestPatch(0, 2, ""), TestPatch(0, 2, ""),
                TestPatch(0, 2, "")]
        ops_u, no = B.compile_local_patches(typing + dels, lmax=8,
                                            dmax=2)
        fused, st = B.fuse_steps(ops_u, dmax=2)
        assert st.fused["sweep"] == 0  # 2 + 2 > dmax 2: no merge
        unbounded, st2 = B.fuse_steps(ops_u)
        assert st2.fused["sweep"] == 2  # no cap: one 6-target sweep
        for f in (fused, unbounded):
            df = _flat_pair_equal(ops_u, f, capacity=64)
            assert SA.to_string(df) == "gh"

    def test_compile_local_patches_fuse_shapes_all(self):
        # The fuse_shapes="all" hook == compile then fuse_steps.
        patches = [TestPatch(0, 0, "ab"), TestPatch(2, 0, "cd"),
                   TestPatch(0, 4, "")]
        ops_a, no_a = B.compile_local_patches(
            patches, lmax=8, fuse_shapes="all")
        ops_u, no_u = B.compile_local_patches(patches, lmax=8)
        fused, _ = B.fuse_steps(ops_u)
        assert no_a == no_u
        for name in ops_a.__dataclass_fields__:
            assert np.array_equal(np.asarray(getattr(ops_a, name)),
                                  np.asarray(getattr(fused, name))), name


class TestFusedKernelsGeneralized:
    def test_event_stream_shapes_bit_identity(self):
        # Mixed typing/sweep/replace/burst EVENT streams (one compiled
        # step per patch) fused at FW through the VMEM kernel at the
        # file's one fixed geometry, vs unfused + the flat oracle.
        rng = random.Random(11)
        patches, content = burst_patches(rng, 56)
        streams, no = [], 0
        for p in patches:
            ops, no = B.compile_local_patches([p], lmax=16,
                                              start_order=no)
            streams.append(ops)
        ops_u = B.concat_ops(streams)
        fused, st = B.fuse_steps(ops_u, fuse_w=FW)
        assert st.rows_saved > 0 and st.fused["burst"] > 0
        assert ops_u.num_steps <= SMAX and fused.num_steps <= SMAX
        ops_u = B.pad_ops(ops_u, SMAX)
        ops_f = B.pad_ops(fused, SMAX)
        res_u = R.replay_local_rle(ops_u, **GEOM)
        res_f = R.replay_local_rle(ops_f, **GEOM)
        du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                    content=content)
        ref = F.apply_ops(SA.make_flat_doc(1024), ops_u)
        assert SA.doc_spans(df) == SA.doc_spans(ref)


@pytest.mark.slow
class TestFusedDeep:
    def test_fuzz_hbm_ride_along(self):
        # Mixed streams through the HBM window engine (the kevin
        # engine); tier-1 already proves its fused splice on the kevin
        # shape in test_kevin_shape_vmem_and_hbm.
        for seed in range(2):
            rng = random.Random(100 + seed)
            patches, content = burst_patches(rng, 60)
            ops_u, ops_f = _compile_pair(patches)
            res_u = RH.replay_local_rle_hbm(ops_u, **GEOM)
            res_f = RH.replay_local_rle_hbm(ops_f, **GEOM)
            _assert_equivalent(ops_u, ops_f, res_u, res_f,
                               content=content)

    def test_fuzz_deep(self):
        for seed in range(4, 40):
            rng = random.Random(seed)
            patches, content = burst_patches(rng, 60)
            ops_u, ops_f = _compile_pair(patches)
            res_u = R.replay_local_rle(ops_u, **GEOM)
            res_f = R.replay_local_rle(ops_f, **GEOM)
            du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                        content=content)
            ref = F.apply_ops(SA.make_flat_doc(1024), ops_u)
            assert SA.doc_spans(df) == SA.doc_spans(ref), seed

    def test_fuzz_event_streams_deep(self):
        # Generalized-shape deep fuzz: event-granularity streams fused
        # by fuse_steps (typing/sweep/replace/burst mixes) vs unfused +
        # the flat oracle on the VMEM kernel.
        for seed in range(40, 70):
            rng = random.Random(seed)
            patches, content = burst_patches(rng, 56)
            streams, no = [], 0
            for p in patches:
                ops, no = B.compile_local_patches([p], lmax=16,
                                                  start_order=no)
                streams.append(ops)
            ops_u = B.concat_ops(streams)
            fused, _ = B.fuse_steps(ops_u, fuse_w=FW)
            ops_u = B.pad_ops(ops_u, SMAX)
            ops_f = B.pad_ops(fused, SMAX)
            res_u = R.replay_local_rle(ops_u, **GEOM)
            res_f = R.replay_local_rle(ops_f, **GEOM)
            du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                        content=content)
            ref = F.apply_ops(SA.make_flat_doc(1024), ops_u)
            assert SA.doc_spans(df) == SA.doc_spans(ref), seed

    def test_fuzz_lanes_engines_fused(self):
        # The lanes engines' new W-row splice: fused-vs-unfused per-lane
        # expansion + (mixed) by-order tables, blocked and un-blocked.
        from text_crdt_rust_tpu.ops import rle_lanes as RL
        from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM
        for seed in range(6):
            rng = random.Random(200 + seed)
            pair = [burst_patches(rng, 48) for _ in range(2)]
            ops_u = B.stack_ops([
                B.pad_ops(B.compile_local_patches(p, lmax=16)[0], SMAX)
                for p, _ in pair])
            ops_f = B.stack_ops([
                B.pad_ops(B.compile_local_patches(
                    p, lmax=16, fuse_w=FW)[0], SMAX)
                for p, _ in pair])
            lkw = dict(capacity=CAPF, chunk=64, interpret=True)
            ru = RL.replay_lanes(ops_u, **lkw)
            rf = RL.replay_lanes(ops_f, **lkw)
            for b in range(2):
                assert np.array_equal(
                    RL.expand_lane(ru, b), RL.expand_lane(rf, b)), seed
            bu = RL.make_replayer_lanes_blocked(
                ops_u, block_k=KF, **lkw)()
            bf = RL.make_replayer_lanes_blocked(
                ops_f, block_k=KF, **lkw)()
            bu.check()
            bf.check()
            for b in range(2):
                assert np.array_equal(RL.expand_lane_blocked(bu, b),
                                      RL.expand_lane_blocked(bf, b)), seed
            mu = RLM.replay_lanes_mixed(ops_u, **lkw)
            mf = RLM.replay_lanes_mixed(ops_f, **lkw)
            assert np.array_equal(np.asarray(mu.oll),
                                  np.asarray(mf.oll)), seed
            assert np.array_equal(np.asarray(mu.orl),
                                  np.asarray(mf.orl)), seed
            xu = RLM.replay_lanes_mixed_blocked(ops_u, block_k=KF, **lkw)
            xf = RLM.replay_lanes_mixed_blocked(ops_f, block_k=KF, **lkw)
            xu.check()
            xf.check()
            assert np.array_equal(np.asarray(xu.oll),
                                  np.asarray(xf.oll)), seed
            assert np.array_equal(np.asarray(xu.orl),
                                  np.asarray(xf.orl)), seed

    def test_trace_prefix_at_scale(self):
        # A real-trace prefix (automerge-paper) at event granularity
        # through the probe's identity path — the committed
        # perf/fused_traces_r9.json shape, bigger than the tier-1 smoke.
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "fused_trace_probe", "perf/fused_trace_probe.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        row = mod.identity_prefix("automerge-paper", 600, fuse_w=8)
        assert row["oracle_equal"], row

    def test_kevin_at_scale(self):
        # The acceptance shape: a long pure-prepend stream at the bench
        # fuse width, fused-vs-unfused on the HBM engine + the analytic
        # oracle (orders must read N-1..0).  5M is a silicon workload;
        # this is the largest CPU-interpret size that stays in budget.
        n = 8192
        w = 64
        patches = [TestPatch(0, 0, " ")] * n
        ops_u, _ = B.compile_local_patches(patches, lmax=w)
        ops_f, _ = B.compile_local_patches(patches, lmax=w, fuse_w=w)
        assert ops_f.num_steps == n // w
        kw = dict(capacity=((n * 21 // 10) // 256 + 1) * 256, batch=8,
                  block_k=256, chunk=128, interpret=True)
        res_u = RH.replay_local_rle_hbm(ops_u, **kw)
        res_f = RH.replay_local_rle_hbm(ops_f, **kw)
        want = np.arange(n, 0, -1, dtype=np.int32)
        assert np.array_equal(R.expand_runs(res_f), want)
        assert np.array_equal(R.expand_runs(res_u), want)

"""Fused multi-row insert steps (split-batch prepare, ISSUE 5).

The kevin worst case (`benches/yjs.rs:51-62`) is a backwards-contiguous
insert burst: every char lands at the same position, BEFORE the previous
one, so runs cannot merge and the unfused engines pay one device step
per character.  ``batch.compile_local_patches(fuse_w=W)`` compiles such
bursts into ONE ``rows_per_step=W`` step whose W pre-built rows the
``ops.rle`` / ``ops.rle_hbm`` splice lands in a single shift.

The correctness burden (same as the PR-2/4 blocked engines): fused and
unfused streams must be bit-identical — the final ``expand_runs`` order
sequence AND the merged by-order logs (``rle_to_flat``: origins, ranks,
chars) — against each other and the flat-engine oracle, because the
fused rows bake in origin chains the unfused path derives step-by-step.

Shapes are FIXED across seeds (pad to SMAX, one geometry) so the whole
file costs a handful of pallas interpret compiles, keeping tier-1
inside its budget.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import rle_hbm as RH
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import TestPatch

SMAX = 128     # fixed padded step count (all streams share one trace)
CAPF = 512     # run-row capacity
KF = 16        # block_k (tiny: fused steps hit leaf splits constantly)
FW = 6         # fuse width under test (<= KF//2 - 1 = 7)
GEOM = dict(capacity=CAPF, batch=8, block_k=KF, chunk=64, interpret=True)

DOC_FIELDS = ("signed", "ol_log", "or_log", "rank_log", "chars_log",
              "n", "next_order")


def _compile_pair(patches, fuse_w=FW, lmax=16):
    """(unfused, fused) op tensors of one patch stream, padded to SMAX."""
    ops_u, no_u = B.compile_local_patches(patches, lmax=lmax, dmax=None)
    ops_f, no_f = B.compile_local_patches(patches, lmax=lmax, dmax=None,
                                          fuse_w=fuse_w)
    assert no_u == no_f
    assert ops_u.num_steps <= SMAX and ops_f.num_steps <= SMAX, \
        "bump SMAX"
    return B.pad_ops(ops_u, SMAX), B.pad_ops(ops_f, SMAX)


def _assert_equivalent(ops_u, ops_f, res_u, res_f, content=None):
    assert np.array_equal(R.expand_runs(res_u), R.expand_runs(res_f)), \
        "fused expand_runs order sequence diverged from unfused"
    du = R.rle_to_flat(ops_u, res_u, capacity=1024)
    df = R.rle_to_flat(ops_f, res_f, capacity=1024)
    for f in DOC_FIELDS:
        assert np.array_equal(np.asarray(getattr(du, f)),
                              np.asarray(getattr(df, f))), f
    if content is not None:
        assert SA.to_string(df) == content
    return du, df


def burst_patches(rng, n):
    """Mixed stream: same-position insert bursts (prepend-heavy, the
    fusable shape) + forward typing + deletes.  Always OPENS with a
    full-width burst so every compiled stream carries rows_per_step ==
    FW (one static WMAX -> one kernel compile for the whole file)."""
    patches, content = [], ""
    for _ in range(FW):
        patches.append(TestPatch(0, 0, "s"))
        content = "s" + content
    while len(patches) < n:
        roll = rng.random()
        if roll < 0.45:
            pos = rng.randrange(len(content) + 1)
            L = rng.randint(1, 2)
            for _ in range(rng.randint(2, FW + 3)):
                s = "".join(rng.choice("abcdefgh") for _ in range(L))
                patches.append(TestPatch(pos, 0, s))
                content = content[:pos] + s + content[pos:]
        elif roll < 0.75:
            pos = rng.randrange(len(content) + 1)
            s = "".join(rng.choice("xyz")
                        for _ in range(rng.randint(1, 5)))
            patches.append(TestPatch(pos, 0, s))
            content = content[:pos] + s + content[pos:]
        elif content:
            pos = rng.randrange(len(content))
            d = min(rng.randint(1, 6), len(content) - pos)
            patches.append(TestPatch(pos, d, ""))
            content = content[:pos] + content[pos + d:]
    return patches, content


class TestFusedCompile:
    def test_burst_detection_and_chunking(self):
        patches = [TestPatch(3, 0, "ab")] * 7 + [TestPatch(0, 0, "q")]
        ops, _ = B.compile_local_patches(patches, lmax=8, fuse_w=4)
        # 7-burst of L=2 chunks at min(fuse_w, lmax//L)=4: [4, 3] + tail.
        assert ops.rows_per_step.tolist() == [4, 3, 1]
        assert ops.ins_len.tolist() == [8, 6, 1]
        assert B.fused_width(ops) == 4

    def test_w1_degenerate_is_todays_stream(self):
        # A burst-free stream compiles IDENTICALLY with fusion enabled.
        patches = [TestPatch(0, 0, "abc"), TestPatch(3, 0, "de"),
                   TestPatch(1, 2, ""), TestPatch(0, 0, "zz")]
        ops_u, _ = B.compile_local_patches(patches, lmax=8)
        ops_f, _ = B.compile_local_patches(patches, lmax=8, fuse_w=8)
        for name in ops_u.__dataclass_fields__:
            assert np.array_equal(np.asarray(getattr(ops_u, name)),
                                  np.asarray(getattr(ops_f, name))), name

    def test_fuse_respects_lmax(self):
        # lmax // L < 2 -> no fusion even for a perfect burst.
        patches = [TestPatch(0, 0, "abcde")] * 4
        ops, _ = B.compile_local_patches(patches, lmax=8, fuse_w=8)
        assert B.fused_width(ops) == 1
        assert ops.num_steps == 4

    def test_row_growth_bound_ops(self):
        patches = [TestPatch(0, 0, "x")] * 8
        ops, _ = B.compile_local_patches(patches, lmax=8, fuse_w=4)
        assert B.row_growth_bound_ops(ops) == 1 + 2 * (4 + 1)
        ops_u, _ = B.compile_local_patches(patches, lmax=8)
        assert B.row_growth_bound_ops(ops_u) == B.row_growth_bound(8)

    def test_unfused_engines_reject_fused_streams(self):
        from text_crdt_rust_tpu.ops import rle_lanes as RL
        patches = [TestPatch(0, 0, "x")] * 4
        ops, _ = B.compile_local_patches(patches, lmax=4, fuse_w=4)
        with pytest.raises(ValueError, match="fused"):
            F.apply_ops(SA.make_flat_doc(64), ops)
        with pytest.raises(ValueError, match="fused"):
            RL.replay_lanes(B.stack_ops([ops]), capacity=64,
                            interpret=True)
        # ...and the fused engines bound W by the one-split headroom.
        with pytest.raises(ValueError, match="headroom"):
            R.replay_local_rle(ops, capacity=64, batch=8, block_k=8,
                               chunk=32, interpret=True)

    def test_registry_fused_flag(self):
        from text_crdt_rust_tpu.config import supports_fused_steps
        assert supports_fused_steps("rle")
        assert supports_fused_steps("rle-hbm")
        assert supports_fused_steps("rle-hbm-fused")  # row alias
        assert not supports_fused_steps("flat")
        assert not supports_fused_steps("rle-lanes-mixed")
        assert not supports_fused_steps("native-cpp")


class TestFusedKernels:
    def test_kevin_shape_vmem_and_hbm(self):
        # Pure prepends: every step is a full-width fused splice; the
        # final doc order must read N-1..0 (orders reversed).
        n = 126  # a whole number of FW-wide bursts, <= SMAX unfused
        patches = [TestPatch(0, 0, "k")] * n
        ops_u, ops_f = _compile_pair(patches, fuse_w=FW, lmax=FW)
        want = np.arange(n, 0, -1, dtype=np.int32)
        for mk in (R.replay_local_rle, RH.replay_local_rle_hbm):
            res_u = mk(ops_u, **GEOM)
            res_f = mk(ops_f, **GEOM)
            du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                        content="k" * n)
            assert np.array_equal(R.expand_runs(res_f), want)
        # The point of the exercise: ~W x fewer device steps.
        live_u = int((np.asarray(ops_u.ins_len) > 0).sum())
        live_f = int((np.asarray(ops_f.ins_len) > 0).sum())
        assert live_f * FW == live_u

    def test_fused_boundary_exactly_at_block_split(self):
        # Fill slot 0 to KF-FW rows (prepends of distinct chars cannot
        # merge), then one full-width burst: r0 + FW + 1 > KF fires the
        # leaf split and the fused splice lands across the fresh block
        # boundary.  Unfused stream splits at a DIFFERENT row boundary —
        # the logical expansion must still match exactly.
        pre = KF - FW
        patches = [TestPatch(0, 0, "p")] * pre \
            + [TestPatch(0, 0, "b")] * FW + [TestPatch(0, 0, "t")]
        ops_u, ops_f = _compile_pair(patches, fuse_w=FW, lmax=FW)
        res_u = R.replay_local_rle(ops_u, **GEOM)
        res_f = R.replay_local_rle(ops_f, **GEOM)
        _assert_equivalent(ops_u, ops_f, res_u, res_f,
                           content="t" + "b" * FW + "p" * pre)
        assert int(np.asarray(res_f.meta)[0].max()) >= 2, \
            "burst never crossed a block split — geometry drifted"

    def test_fuzz_mixed_streams_bit_identity(self):
        # Mixed prepend/typing/delete streams at one fixed shape, VMEM
        # engine, vs the flat-engine per-keystroke oracle.  3 seeds in
        # tier-1 (the 794s-of-870s budget is nearly spent); the deep
        # sweep + the HBM ride-along run in ``slow``.
        for seed in range(3):
            rng = random.Random(seed)
            patches, content = burst_patches(rng, 60)
            ops_u, ops_f = _compile_pair(patches)
            res_u = R.replay_local_rle(ops_u, **GEOM)
            res_f = R.replay_local_rle(ops_f, **GEOM)
            du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                        content=content)
            ref = F.apply_ops(SA.make_flat_doc(1024), ops_u)
            assert SA.doc_spans(df) == SA.doc_spans(ref), seed

@pytest.mark.slow
class TestFusedDeep:
    def test_fuzz_hbm_ride_along(self):
        # Mixed streams through the HBM window engine (the kevin
        # engine); tier-1 already proves its fused splice on the kevin
        # shape in test_kevin_shape_vmem_and_hbm.
        for seed in range(2):
            rng = random.Random(100 + seed)
            patches, content = burst_patches(rng, 60)
            ops_u, ops_f = _compile_pair(patches)
            res_u = RH.replay_local_rle_hbm(ops_u, **GEOM)
            res_f = RH.replay_local_rle_hbm(ops_f, **GEOM)
            _assert_equivalent(ops_u, ops_f, res_u, res_f,
                               content=content)

    def test_fuzz_deep(self):
        for seed in range(4, 40):
            rng = random.Random(seed)
            patches, content = burst_patches(rng, 60)
            ops_u, ops_f = _compile_pair(patches)
            res_u = R.replay_local_rle(ops_u, **GEOM)
            res_f = R.replay_local_rle(ops_f, **GEOM)
            du, df = _assert_equivalent(ops_u, ops_f, res_u, res_f,
                                        content=content)
            ref = F.apply_ops(SA.make_flat_doc(1024), ops_u)
            assert SA.doc_spans(df) == SA.doc_spans(ref), seed

    def test_kevin_at_scale(self):
        # The acceptance shape: a long pure-prepend stream at the bench
        # fuse width, fused-vs-unfused on the HBM engine + the analytic
        # oracle (orders must read N-1..0).  5M is a silicon workload;
        # this is the largest CPU-interpret size that stays in budget.
        n = 8192
        w = 64
        patches = [TestPatch(0, 0, " ")] * n
        ops_u, _ = B.compile_local_patches(patches, lmax=w)
        ops_f, _ = B.compile_local_patches(patches, lmax=w, fuse_w=w)
        assert ops_f.num_steps == n // w
        kw = dict(capacity=((n * 21 // 10) // 256 + 1) * 256, batch=8,
                  block_k=256, chunk=128, interpret=True)
        res_u = RH.replay_local_rle_hbm(ops_u, **kw)
        res_f = RH.replay_local_rle_hbm(ops_f, **kw)
        want = np.arange(n, 0, -1, dtype=np.int32)
        assert np.array_equal(R.expand_runs(res_f), want)
        assert np.array_equal(R.expand_runs(res_u), want)

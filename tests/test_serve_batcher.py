"""serve/batcher.py: fixed-shape bucketed device ticks on the vmapped
flat engine — lane state bit-identical to the host oracles, capacity
overflow degrading (never asserting), agent onboarding re-basing ranks.
"""
import numpy as np
import pytest

from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.serve.batcher import make_lane_backend, oracle_signed
from text_crdt_rust_tpu.serve.server import DocServer

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def cfg(**kw):
    base = dict(num_shards=1, lanes_per_shard=4, lane_capacity=128,
                order_capacity=256, step_buckets=(8, 32), max_txn_len=32)
    base.update(kw)
    return ServeConfig(**base)


def assert_lanes_equal_oracles(srv):
    for doc_id, doc in srv.router.docs.items():
        assert srv.verify_doc(doc_id), f"{doc_id}: lane != oracle"


def test_backend_registry_validation():
    with pytest.raises(ValueError, match="unknown engine"):
        make_lane_backend("definitely-not-an-engine", lanes=2, capacity=64,
                          order_capacity=128, lmax=4)
    with pytest.raises(ValueError, match="no serve lane backend"):
        make_lane_backend("rle", lanes=2, capacity=64,
                          order_capacity=128, lmax=4)


def test_mixed_local_remote_ticks_lane_equals_oracle():
    srv = DocServer(cfg())
    for i in range(3):
        srv.admit_doc(f"d{i}")
    peer = ListCRDT()
    pa = peer.get_or_create_agent_id("peer")
    mark = 0
    for step in range(6):
        for i in range(3):
            srv.submit_local(f"d{i}", "ed", 0, ins_content=f"s{step}")
        peer.local_insert(pa, len(peer), "pq")
        if step % 2:
            peer.local_delete(pa, 0, 1)
        for t in export_txns_since(peer, mark):
            srv.submit_txn("d0", t)
        mark = peer.get_next_order()
        srv.tick()
    assert_lanes_equal_oracles(srv)
    # The device lane and oracle agree with an independent replay too.
    d0 = srv.doc_state("d0")
    assert d0.in_lane
    got = srv.residency.backends[0].lane_to_string(d0.lane)
    assert got == d0.oracle.to_string()


def test_tick_shapes_are_bucketed_no_recompile_growth():
    """Steady-state serving cycles a fixed set of compiled shapes: the
    backend sees at most one shape per configured step bucket no matter
    how ragged the tick sizes are."""
    srv = DocServer(cfg(step_buckets=(8, 32)))
    srv.admit_doc("d")
    rng = np.random.RandomState(0)
    for tick in range(12):
        for _ in range(int(rng.randint(1, 6))):
            srv.submit_local("d", "ed", 0, ins_content="ab")
        srv.tick()
    seen = srv.residency.backends[0].shapes_seen
    assert seen <= {8, 32}, seen
    assert_lanes_equal_oracles(srv)


def test_lane_overflow_degrades_to_host_oracle():
    """A doc outgrowing its lane keeps serving from the host oracle:
    lane freed, no assert, content still converges."""
    srv = DocServer(cfg(lane_capacity=48, order_capacity=96,
                        max_queue_per_doc=512))
    srv.admit_doc("d")
    for i in range(10):
        srv.submit_local("d", "ed", 0, ins_content="0123456789")
        srv.tick()
    doc = srv.doc_state("d")
    assert doc.degraded and not doc.in_lane
    assert srv.counters.get("lane_overflow_degraded") == 1
    assert len(srv.doc_string("d")) == 100
    # Further traffic still applies host-side.
    srv.submit_local("d", "ed", 0, ins_content="tail")
    srv.tick()
    assert srv.doc_string("d").startswith("tail")


def test_agent_onboarding_rebases_lane_ranks():
    """A new agent joining mid-stream changes the sorted-name ranks of
    existing agents; the lane's persisted rank log must re-base (the
    rank_remap epoch) or later same-origin tiebreaks diverge."""
    srv = DocServer(cfg())
    srv.admit_doc("d")
    # 'mmm' writes first; the lane's rank log bakes rank(mmm)=0.
    srv.submit_local("d", "mmm", 0, ins_content="base")
    srv.tick()
    # 'aaa' joins: sorted names now (aaa, mmm) -> rank(mmm) must become
    # 1 in the lane before concurrent-insert tiebreaks read it.
    t_a = RemoteTxn(id=RemoteId("aaa", 0), parents=[ROOT],
                    ops=[RemoteIns(ROOT, ROOT, "A")])
    # 'zzz' concurrent same-origin insert: tiebreak against BOTH.
    t_z = RemoteTxn(id=RemoteId("zzz", 0), parents=[ROOT],
                    ops=[RemoteIns(ROOT, ROOT, "Z")])
    srv.submit_txn("d", t_a)
    srv.tick()
    assert srv.counters.get("lane_rank_remaps") >= 1
    srv.submit_txn("d", t_z)
    srv.submit_local("d", "mmm", 0, ins_content="x")
    srv.tick()
    assert_lanes_equal_oracles(srv)
    # Cross-check against a one-shot oracle replay of the same history.
    twin = ListCRDT()
    doc = srv.doc_state("d")
    for t in export_txns_since(doc.oracle, 0):
        twin.apply_remote_txn(t)
    assert srv.doc_string("d") == twin.to_string()


def test_same_tick_onboarding_defers_epoch_boundary():
    """An agent-onboarding event queued BEHIND an old-agent edit in the
    same tick must not share that tick's compiled stream: the remap
    rewrites the lane's persisted ranks to the new epoch, but the
    already-compiled steps baked the old ranks in — prefiling them
    after the remap plants stale ranks under later same-origin
    tiebreaks (the latent divergence ISSUE 4's twin runs exposed).
    The batcher defers the onboarding event one tick instead."""
    srv = DocServer(cfg())
    srv.admit_doc("d")
    srv.submit_local("d", "mmm", 0, ins_content="base")
    srv.tick()
    # Same tick: an old-epoch edit ahead of a new agent's txn.
    srv.submit_local("d", "mmm", 0, ins_content="pre")
    t_a = RemoteTxn(id=RemoteId("aaa", 0), parents=[ROOT],
                    ops=[RemoteIns(ROOT, ROOT, "A")])
    srv.submit_txn("d", t_a)
    srv.tick()
    assert srv.counters.get("epoch_boundary_deferrals") >= 1
    # The deferred event lands next tick, in its own epoch.
    t_z = RemoteTxn(id=RemoteId("zzz", 0), parents=[ROOT],
                    ops=[RemoteIns(ROOT, ROOT, "Z")])
    srv.submit_txn("d", t_z)
    srv.submit_local("d", "mmm", 0, ins_content="x")
    srv.drain()
    assert_lanes_equal_oracles(srv)
    twin = ListCRDT()
    doc = srv.doc_state("d")
    for t in export_txns_since(doc.oracle, 0):
        twin.apply_remote_txn(t)
    assert srv.doc_string("d") == twin.to_string()


def test_oracle_signed_encoding():
    doc = ListCRDT()
    a = doc.get_or_create_agent_id("a")
    doc.local_insert(a, 0, "abc")
    doc.local_delete(a, 1, 1)
    want = np.asarray([1, -2, 3], dtype=np.int32)
    assert np.array_equal(oracle_signed(doc), want)


def test_tick_stream_fusion_counters_and_identity():
    """Generalized tick-stream fusion (ISSUE 6): a typing run + a
    backspace sweep + a replace submitted as SEPARATE events in one
    tick fuse into fewer device steps (per-event compilation would pay
    one step each), the lane stays bit-identical to the oracle, and
    ``tick_summary`` exports the fused-step counters."""
    srv = DocServer(cfg(fuse_steps=True, fuse_w=4))
    srv.admit_doc("d")
    for i in range(4):                       # typing run: h-e-l-o
        srv.submit_local("d", "ed", i, ins_content="helo"[i])
    srv.tick()
    for i in range(3):                       # backspace sweep
        srv.submit_local("d", "ed", 3 - i, del_len=1)
    srv.tick()
    srv.submit_local("d", "ed", 0, del_len=1)      # replace pair
    srv.submit_local("d", "ed", 0, ins_content="X")
    srv.tick()
    assert srv.doc_string("d") == "X"
    assert_lanes_equal_oracles(srv)
    ts = srv.tick_summary()
    assert ts["fused_rows_saved"] >= 3 + 2 + 1
    assert ts["steps_total"] < ts["steps_prefuse"]
    assert ts["ops_per_step"] > 1.0
    fs = srv.batcher.fuse_stats.fused
    assert fs["typing"] >= 3 and fs["sweep"] >= 2 and fs["replace"] >= 1


def test_fusion_off_is_per_event_steps():
    """fuse_steps=False keeps one compiled step per event (the pre-
    ISSUE-6 behavior) — and the final state is the same either way."""
    out = {}
    for fuse in (False, True):
        srv = DocServer(cfg(fuse_steps=fuse))
        srv.admit_doc("d")
        for i in range(4):
            srv.submit_local("d", "ed", i, ins_content="abcd"[i])
        srv.tick()
        assert_lanes_equal_oracles(srv)
        out[fuse] = srv.doc_string("d")
        saved = srv.tick_summary()["fused_rows_saved"]
        assert (saved > 0) == fuse
    assert out[False] == out[True] == "abcd"

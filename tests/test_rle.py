"""Tests for the RLE span algebra + flat containers.

Mirrors the reference's inline tests: `rle/simple_rle.rs:113-155`,
`list/double_delete.rs:109-139`, `list/txn.rs:62-92`, plus the
SplitableSpan invariant (`splitable_span.rs:10-16`) property-checked over
every span type.
"""
import copy

import pytest

from text_crdt_rust_tpu.utils.rle import (
    KCRDTSpan,
    KDeleteEntry,
    KDoubleDelete,
    KOrderSpan,
    Rle,
    TxnSpan,
    increment_delete_range,
)


SPAN_EXAMPLES = [
    KOrderSpan(seq=10, order=100, length=8),
    KCRDTSpan(order=100, agent=2, seq=10, length=8),
    KDeleteEntry(op_order=50, target=7, length=8),
    KDoubleDelete(target=40, length=8, excess=3),
    TxnSpan(order=64, length=8, shadow=2, parents=[63]),
]


@pytest.mark.parametrize("span", SPAN_EXAMPLES, ids=lambda s: type(s).__name__)
def test_splitable_span_invariant(span):
    # initial_len == at + rest.len and can_append(rest) (`splitable_span.rs:10-16`)
    for at in range(1, span.length):
        s = copy.deepcopy(span)
        initial_len = s.length
        rest = s.truncate(at)
        assert s.length == at
        assert s.length + rest.length == initial_len
        assert s.can_append(rest)
        s.append(rest)
        assert s.length == initial_len


def test_rle_find_at_offset():
    # (`simple_rle.rs:113-126` analog)
    rle = Rle()
    rle.append(KOrderSpan(seq=0, order=1000, length=2))
    assert rle.find(0) == (rle.entries[0], 0)
    assert rle.find(1) == (rle.entries[0], 1)
    assert rle.find(2) is None
    assert rle.get(1) == 1001


def test_rle_append_merges():
    rle = Rle()
    rle.append(KOrderSpan(seq=0, order=1000, length=2))
    rle.append(KOrderSpan(seq=2, order=1002, length=3))
    assert rle.num_entries() == 1
    assert rle.entries[0].length == 5
    # Non-contiguous: no merge.
    rle.append(KOrderSpan(seq=9, order=1009, length=1))
    assert rle.num_entries() == 2
    rle.check()


def test_rle_insert_neighbour_merge():
    # (`simple_rle.rs:128-155` analog)
    rle = Rle()
    rle.insert(KOrderSpan(seq=5, order=105, length=2))
    rle.insert(KOrderSpan(seq=0, order=100, length=2))
    assert rle.num_entries() == 2
    # Fill the gap: all three merge.
    rle.insert(KOrderSpan(seq=2, order=102, length=3))
    assert rle.num_entries() == 1
    assert rle.entries[0] == KOrderSpan(seq=0, order=100, length=7)


def test_txn_appends():
    # (`txn.rs:70-92`)
    a = TxnSpan(order=1000, length=10, shadow=500, parents=[999])
    b = TxnSpan(order=1010, length=5, shadow=500, parents=[1009])
    assert a.can_append(b)
    a.append(b)
    assert a == TxnSpan(order=1000, length=15, shadow=500, parents=[999])


def test_increment_delete_range_table():
    # Faithful port of the reference table test (`double_delete.rs:113-139`).
    dd = Rle()
    increment_delete_range(dd, 5, 3)
    assert dd.entries == [KDoubleDelete(5, 3, 1)]
    increment_delete_range(dd, 5, 3)
    assert dd.entries == [KDoubleDelete(5, 3, 2)]
    increment_delete_range(dd, 4, 2)
    assert dd.entries == [
        KDoubleDelete(4, 1, 1),
        KDoubleDelete(5, 1, 3),
        KDoubleDelete(6, 2, 2),
    ]
    increment_delete_range(dd, 7, 3)
    assert dd.entries == [
        KDoubleDelete(4, 1, 1),
        KDoubleDelete(5, 1, 3),
        KDoubleDelete(6, 1, 2),
        KDoubleDelete(7, 1, 3),
        KDoubleDelete(8, 2, 1),
    ]


def test_increment_delete_range_gap_merge():
    dd = Rle()
    increment_delete_range(dd, 0, 2)
    increment_delete_range(dd, 2, 2)  # adjacent, same excess: merges
    assert dd.entries == [KDoubleDelete(0, 4, 1)]
    increment_delete_range(dd, 10, 1)
    assert dd.num_entries() == 2
    # Spanning a gap and an existing entry.
    increment_delete_range(dd, 8, 4)
    assert dd.entries == [
        KDoubleDelete(0, 4, 1),
        KDoubleDelete(8, 2, 1),
        KDoubleDelete(10, 1, 2),
        KDoubleDelete(11, 1, 1),
    ]

"""HBM-state RLE engine vs the flat engine and string oracle.

Same differential battery as ``test_rle_engine`` (the two engines share
the in-block math by construction) plus the window-cache specifics: tiny
blocks force splits AND window misses on nearly every op, far-jump edits
force write-back/fetch churn, and the kevin shape pins the
prepend-amortization this engine exists for."""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import rle_hbm as RH
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    TestPatch,
    flatten_patches,
    load_testing_data,
    trace_path,
)

from test_device_flat import random_patches


def run_hbm(patches, capacity, block_k, merge=True, chunk=128):
    plist = B.merge_patches(patches) if merge else patches
    lmax = max([len(p.ins_content) for p in plist] + [1])
    ops, _ = B.compile_local_patches(plist, lmax=lmax, dmax=None)
    res = RH.replay_local_rle_hbm(ops, capacity=capacity, batch=8,
                                  block_k=block_k, chunk=chunk,
                                  interpret=True)
    return ops, R.rle_to_flat(ops, res)


def ref_doc(patches, capacity=1024):
    ops, _ = B.compile_local_patches(patches, lmax=16, dmax=None)
    return F.apply_ops(SA.make_flat_doc(capacity), ops)


class TestRleHbmReplay:
    def test_smoke(self):
        patches = [TestPatch(0, 0, "hello world"), TestPatch(5, 0, ","),
                   TestPatch(2, 3, "LLO"), TestPatch(0, 1, "H")]
        _, doc = run_hbm(patches, capacity=64, block_k=8)
        ref = ref_doc(patches, 64)
        assert SA.to_string(doc) == SA.to_string(ref) == "HeLLO, world"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.parametrize("seed", [7, 11, 99])
    @pytest.mark.parametrize("merge", [True, False])
    def test_random_vs_flat(self, seed, merge):
        rng = random.Random(seed)
        patches, content = random_patches(rng, 80)
        _, doc = run_hbm(patches, capacity=256, block_k=8, merge=merge)
        ref = ref_doc(patches, 512)
        assert SA.to_string(doc) == SA.to_string(ref) == content
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_kevin_shape_prepends(self):
        # The engine's raison d'etre: pure prepends split slot 0 over and
        # over; the kept half stays cached (no miss), the logical order
        # must keep the reversed doc order exact.
        patches = [TestPatch(0, 0, "ab") for _ in range(60)]
        _, doc = run_hbm(patches, capacity=256, block_k=8, merge=False)
        ref = ref_doc(patches, 256)
        assert SA.to_string(doc) == SA.to_string(ref) == "ab" * 60
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_far_jump_window_churn(self):
        # Alternating ends: nearly every op is a window miss (write-back
        # + fetch) and boundary inserts hit the next-slot DMA peek.
        patches = [TestPatch(0, 0, "abcdefgh")]
        for k in range(12):
            patches.append(TestPatch(0, 0, "xy"))
            patches.append(TestPatch(8 + 2 * k, 0, "pq"))
        _, doc = run_hbm(patches, capacity=128, block_k=8, merge=False)
        ref = ref_doc(patches, 128)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_delete_spanning_blocks(self):
        patches = [TestPatch(0, 0, "ab") for _ in range(24)]
        patches.append(TestPatch(2, 40, ""))
        _, doc = run_hbm(patches, capacity=128, block_k=8, merge=False)
        ref = ref_doc(patches, 128)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.slow
    def test_trace_prefix(self):
        data = load_testing_data(trace_path("automerge-paper"))
        patches = flatten_patches(data)[:400]
        _, doc = run_hbm(patches, capacity=256, block_k=16)
        ref = ref_doc(patches, 1024)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_block_exhaustion_flagged(self):
        patches = [TestPatch(0, 0, "ab") for _ in range(40)]
        ops, _ = B.compile_local_patches(patches, lmax=2, dmax=None)
        res = RH.replay_local_rle_hbm(ops, capacity=16, batch=8, block_k=8,
                                      chunk=128, interpret=True)
        with pytest.raises(RuntimeError, match="out of blocks"):
            res.check()

    def test_groups_divergent(self):
        rng = random.Random(404)
        opses, contents = [], []
        for gi in range(3):
            patches, content = random_patches(rng, 40 + 10 * gi)
            merged = B.merge_patches(patches)
            lmax = max(len(p.ins_content) for p in merged if p.ins_content)
            ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
            opses.append(ops)
            contents.append(content)
        run = RH.make_replayer_rle_hbm(opses, capacity=256, batch=8,
                                       block_k=8, chunk=128, interpret=True)
        results = run()
        for ops, res, content in zip(opses, results, contents):
            assert SA.to_string(R.rle_to_flat(ops, res)) == content


class TestVsVmemEngine:
    """Bit-equality of the two RLE engines on the same stream (shared
    math — any drift is a bug in the window/index plumbing)."""

    @pytest.mark.parametrize("seed", [3, 21])
    def test_equal_state(self, seed):
        rng = random.Random(seed)
        patches, _content = random_patches(rng, 100)
        merged = B.merge_patches(patches)
        lmax = max([len(p.ins_content) for p in merged] + [1])
        ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
        res_v = R.replay_local_rle(ops, capacity=256, batch=8, block_k=8,
                                   chunk=128, interpret=True)
        res_h = RH.replay_local_rle_hbm(ops, capacity=256, batch=8,
                                        block_k=8, chunk=128,
                                        interpret=True)
        np.testing.assert_array_equal(R.expand_runs(res_v),
                                      R.expand_runs(res_h))
        np.testing.assert_array_equal(np.asarray(res_v.ol),
                                      np.asarray(res_h.ol))
        np.testing.assert_array_equal(np.asarray(res_v.orr),
                                      np.asarray(res_h.orr))


class TestStoreOrigins:
    """store_origins=False (the kevin-5M memory mode: origin planes are
    5.1 GB at full scale) must leave final run state bit-identical and
    only empty out the per-op ``ol``/``orr`` outputs."""

    def test_final_state_identical(self):
        rng = random.Random(3)
        patches, content = random_patches(rng, 120)
        merged = B.merge_patches(patches)
        lmax = max(len(p.ins_content) for p in merged)
        ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
        kw = dict(capacity=256, batch=4, block_k=32, chunk=16,
                  interpret=True)
        full = RH.make_replayer_rle_hbm(ops, **kw)()
        slim = RH.make_replayer_rle_hbm(ops, store_origins=False, **kw)()
        full.check()
        slim.check()
        assert slim.ol.shape[0] == 0 and slim.orr.shape[0] == 0
        assert np.array_equal(np.asarray(full.ordp), np.asarray(slim.ordp))
        assert np.array_equal(np.asarray(full.lenp), np.asarray(slim.lenp))
        flat_full = R.expand_runs(full)
        flat_slim = R.expand_runs(slim)
        assert np.array_equal(flat_full, flat_slim)

"""Tier-1 differential fuzz: BLOCKED vs un-blocked lanes engines vs
the oracle (ISSUE-2 acceptance: >= 50 seeds per driver family inside
the tier-1 budget; the deep variants run under ``-m slow`` and in
``perf/fuzz_lanes_mixed.py`` / ``perf/fuzz_sp_remote.py``).

Every seed's streams pad to ONE fixed device shape, so all seeds share
a single trace per engine — the fixed-shape trick that makes a 50-seed
interpret-mode fuzz cost seconds, not hours.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle_lanes as RL
from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM
from text_crdt_rust_tpu.utils.randedit import make_storm, random_patches

SMAX = 64     # fixed padded step count (every stream must compile under)
CAPF = 128    # fixed run-row capacity
KF = 16       # block_k (tiny: every seed exercises splits)
OCAPF = 256   # fixed by-order table rows
LANES = 2


def _peer(rng, n, agent):
    doc = ListCRDT()
    a = doc.get_or_create_agent_id(agent)
    patches, _ = random_patches(rng, n)
    for p in patches:
        if p.del_len:
            doc.local_delete(a, p.pos, p.del_len)
        if p.ins_content:
            doc.local_insert(a, p.pos, p.ins_content)
    return doc


def _lane_stream(rng, seed):
    """One lane's txn stream: a random hard shape (the
    perf/fuzz_lanes_mixed generator, sized for the fixed SMAX)."""
    shape = rng.randrange(3)
    if shape == 0:  # two-peer merge
        txns = []
        for name in ("ann", "bob"):
            txns.extend(export_txns_since(
                _peer(rng, 5 + rng.randrange(6), name), 0))
        return txns
    if shape == 1:  # concurrent storm with cross-peer deletes
        txns, _ = make_storm(2, 2 + rng.randrange(2),
                             1 + rng.randrange(2), seed=seed,
                             del_prob=0.25 + rng.random() * 0.2)
        return txns
    # interleaved independent peers
    streams = [export_txns_since(_peer(rng, 4 + rng.randrange(5), n), 0)
               for n in ("kim", "lou")]
    out = []
    queues = [list(s) for s in streams]
    while any(queues):
        live = [q for q in queues if q]
        out.append(rng.choice(live).pop(0))
    return out


def _compile_fixed(lane_txns):
    opses = []
    for txns in lane_txns:
        table = B.AgentTable()
        for t in txns:
            table.add(t.id.agent)
            for op in t.ops:
                if hasattr(op, "id"):
                    table.add(op.id.agent)
        ops, _ = B.compile_remote_txns(txns, table, lmax=4, dmax=None)
        assert ops.num_steps <= SMAX, f"bump SMAX: {ops.num_steps}"
        opses.append(B.pad_ops(ops, SMAX))
    return B.stack_ops(opses)


def _one_round(seed):
    rng = random.Random(seed)
    lane_txns = [_lane_stream(rng, seed * 100 + k) for k in range(LANES)]
    stacked = _compile_fixed(lane_txns)
    kw = dict(capacity=CAPF, order_capacity=OCAPF, chunk=32,
              interpret=True)
    flat = RLM.replay_lanes_mixed(stacked, **kw)
    blk = RLM.replay_lanes_mixed_blocked(stacked, block_k=KF, **kw)
    flat.check()
    blk.check()
    for d, txns in enumerate(lane_txns):
        oracle = ListCRDT()
        for t in txns:
            oracle.apply_remote_txn(t)
        want = [(-1 if oracle.deleted[i] else 1)
                * (int(oracle.order[i]) + 1) for i in range(oracle.n)]
        assert RL.expand_lane(flat, d).tolist() == want, \
            f"seed {seed} lane {d} flat DIVERGED"
        assert RL.expand_lane(blk, d).tolist() == want, \
            f"seed {seed} lane {d} blocked DIVERGED"
    assert np.array_equal(np.asarray(flat.ol), np.asarray(blk.ol))
    assert np.array_equal(np.asarray(flat.orr), np.asarray(blk.orr))


class TestLanesMixedFuzz:
    def test_60_seeds_blocked_vs_flat_vs_oracle(self):
        for seed in range(60):
            _one_round(seed)

    @pytest.mark.slow
    def test_1000_more_seeds(self):
        """Deep-fuzz volume (ROADMAP #6): spend the x27.5 oracle-index
        speedup — 1,000+ hard-mode seeds for this surface per round
        (tier-1 keeps its 60-seed budget; the shared fixed device shape
        means the whole sweep reuses one compiled trace per engine)."""
        for seed in range(60, 1060):
            _one_round(seed)


class TestSpRemoteRideAlong:
    """The sharded SpDoc fuzz shape with the blocked/un-blocked lanes
    differential riding along (perf/fuzz_sp_remote's round, fixed device
    shapes).  SpDoc itself is exercised by tests/test_sp_apply.py and
    the perf driver; this tier-1 pass holds the lanes engines to the
    same streams."""

    def _round(self, seed):
        rng = random.Random(seed)
        oracle = ListCRDT()
        txns = (export_txns_since(_peer(rng, 6 + rng.randrange(8),
                                        "pa"), 0)
                + export_txns_since(_peer(rng, 6 + rng.randrange(8),
                                          "pb"), 0))
        for t in txns:
            oracle.apply_remote_txn(t)
        stacked = _compile_fixed([txns])
        want = [(-1 if oracle.deleted[i] else 1)
                * (int(oracle.order[i]) + 1) for i in range(oracle.n)]
        kw = dict(capacity=CAPF, order_capacity=OCAPF, chunk=32,
                  interpret=True)
        for name, res in (
            ("flat", RLM.replay_lanes_mixed(stacked, **kw)),
            ("blocked", RLM.replay_lanes_mixed_blocked(
                stacked, block_k=KF, **kw)),
        ):
            res.check()
            assert RL.expand_lane(res, 0).tolist() == want, \
                f"seed {seed} {name} DIVERGED"

    def test_50_seeds(self):
        for seed in range(40_000, 40_050):
            self._round(seed)

    @pytest.mark.slow
    def test_1000_more_seeds(self):
        """Deep-fuzz volume (ROADMAP #6) for the sp-remote surface:
        1,000 further seeds in the slow tier."""
        for seed in range(40_050, 41_050):
            self._round(seed)

    @pytest.mark.slow
    def test_500_more_seeds_round8(self):
        """Round-8 growth (ISSUE 5 satellite): a further fresh 500-seed
        range for the sp-remote ride-along, keeping this surface at
        parity with the blocked-lanes sweeps as rounds accumulate."""
        for seed in range(41_050, 41_550):
            self._round(seed)

"""Differential tests: flat JAX device engine vs the host oracle.

Mirrors the reference's test strategy (SURVEY §4): seeded random-edit
differential fuzz (`doc.rs:571-587`), local-vs-remote convergence
(`doc.rs:620-676`), trace replay with final-content assertions
(`benches/yjs.rs:46`), plus the N-peer concurrent-insert cases the
reference's missing `random_concurrency` test intended.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.common import ROOT_ORDER
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    TestPatch,
    flatten_patches,
    load_testing_data,
    trace_path,
)

ALPHABET = "abcdefghijklmnopqrstuvwxyz "


def random_patches(rng: random.Random, steps: int):
    """Seeded random edit stream (the `make_random_change` analog,
    `doc.rs:544-569`), tracked against a plain string."""
    content = ""
    patches = []
    for _ in range(steps):
        if not content or rng.random() < 0.6:
            pos = rng.randint(0, len(content))
            ins = "".join(rng.choice(ALPHABET)
                          for _ in range(rng.randint(1, 5)))
            patches.append(TestPatch(pos, 0, ins))
            content = content[:pos] + ins + content[pos:]
        else:
            pos = rng.randint(0, len(content) - 1)
            span = min(rng.randint(1, 4), len(content) - pos)
            patches.append(TestPatch(pos, span, ""))
            content = content[:pos] + content[pos + span:]
    return patches, content


def oracle_from_patches(patches, agent="oracle-agent"):
    doc = ListCRDT()
    a = doc.get_or_create_agent_id(agent)
    for p in patches:
        if p.del_len:
            doc.local_delete(a, p.pos, p.del_len)
        if p.ins_content:
            doc.local_insert(a, p.pos, p.ins_content)
    return doc


def assert_same_doc(doc: SA.FlatDoc, oracle: ListCRDT):
    assert int(doc.n) == oracle.n
    assert int(doc.next_order) == oracle.get_next_order()
    assert SA.to_string(doc) == oracle.to_string()
    assert SA.doc_spans(doc) == oracle.doc_spans()


class TestLocalReplay:
    def test_smoke_insert(self):
        patches = [TestPatch(0, 0, "hi there"), TestPatch(3, 0, "X")]
        ops, _ = B.compile_local_patches(patches)
        doc = F.apply_ops(SA.make_flat_doc(64), ops)
        assert SA.to_string(doc) == "hi Xthere"

    def test_smoke_delete(self):
        patches = [TestPatch(0, 0, "hi there"), TestPatch(1, 3, "")]
        ops, _ = B.compile_local_patches(patches)
        doc = F.apply_ops(SA.make_flat_doc(64), ops)
        assert SA.to_string(doc) == "hhere"
        # Tombstones stay in place (`span.rs:110-119`).
        assert int(doc.n) == 8

    @pytest.mark.parametrize("seed", [7, 11, 99])
    def test_random_vs_oracle(self, seed):
        rng = random.Random(seed)
        patches, content = random_patches(rng, 120)
        oracle = oracle_from_patches(patches)
        assert oracle.to_string() == content
        ops, next_order = B.compile_local_patches(patches, lmax=4)
        doc = F.apply_ops(SA.make_flat_doc(1024), ops)
        assert next_order == oracle.get_next_order()
        assert_same_doc(doc, oracle)

    def test_long_insert_chunking(self):
        # One patch much longer than lmax: chunked with chained origins.
        patches = [TestPatch(0, 0, "abcdefghij" * 4), TestPatch(5, 0, "XY")]
        oracle = oracle_from_patches(patches)
        ops, _ = B.compile_local_patches(patches, lmax=3)
        doc = F.apply_ops(SA.make_flat_doc(128), ops)
        assert_same_doc(doc, oracle)

    @pytest.mark.slow
    def test_trace_prefix_vs_oracle(self):
        data = load_testing_data(trace_path("sveltecomponent"))
        patches = flatten_patches(data)[:400]
        oracle = oracle_from_patches(patches)
        ops, _ = B.compile_local_patches(patches)
        doc = F.apply_ops(SA.make_flat_doc(4096), ops)
        assert_same_doc(doc, oracle)


class TestRemoteApply:
    def _device_from_txns(self, txns, capacity=2048, lmax=16):
        table = B.AgentTable()
        for t in txns:
            table.add(t.id.agent)
            for op in t.ops:
                if hasattr(op, "id"):
                    table.add(op.id.agent)
        ops, _ = B.compile_remote_txns(txns, table, lmax=lmax)
        return F.apply_ops(SA.make_flat_doc(capacity), ops)

    def _oracle_from_txns(self, txns):
        doc = ListCRDT()
        for t in txns:
            doc.apply_remote_txn(t)
        return doc

    def test_concurrent_root_inserts_tiebreak(self):
        # N peers concurrently insert at the very start: all share origins
        # (ROOT, ROOT); final order is the name tiebreak (`doc.rs:206-216`).
        from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
        txns = [
            RemoteTxn(
                id=RemoteId(name, 0), parents=[],
                ops=[RemoteIns(RemoteId("ROOT", 0xFFFFFFFF),
                               RemoteId("ROOT", 0xFFFFFFFF), text)],
            )
            for name, text in [("zed", "zz"), ("amy", "aa"), ("mia", "mm")]
        ]
        oracle = self._oracle_from_txns(txns)
        doc = self._device_from_txns(txns)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    @pytest.mark.parametrize("seed", [3, 21])
    def test_two_peer_random_merge(self, seed):
        rng = random.Random(seed)
        pa, _ = random_patches(rng, 60)
        pb, _ = random_patches(rng, 60)
        a = oracle_from_patches(pa, agent="peer-a")
        bdoc = oracle_from_patches(pb, agent="peer-b")
        txns = export_txns_since(a, 0) + export_txns_since(bdoc, 0)
        oracle = self._oracle_from_txns(txns)
        doc = self._device_from_txns(txns, capacity=2048, lmax=4)
        assert_same_doc(doc, oracle)

    def test_remote_delete_and_double_delete(self):
        from text_crdt_rust_tpu.common import (
            RemoteDel, RemoteId, RemoteIns, RemoteTxn)
        root = RemoteId("ROOT", 0xFFFFFFFF)
        base = RemoteTxn(id=RemoteId("amy", 0), parents=[],
                         ops=[RemoteIns(root, root, "abcdef")])
        # Two peers concurrently delete overlapping ranges of amy's run.
        d1 = RemoteTxn(id=RemoteId("bob", 0),
                       parents=[RemoteId("amy", 5)],
                       ops=[RemoteDel(RemoteId("amy", 1), 3)])
        d2 = RemoteTxn(id=RemoteId("cat", 0),
                       parents=[RemoteId("amy", 5)],
                       ops=[RemoteDel(RemoteId("amy", 2), 3)])
        txns = [base, d1, d2]
        oracle = self._oracle_from_txns(txns)
        doc = self._device_from_txns(txns, capacity=64)
        assert SA.to_string(doc) == oracle.to_string() == "af"
        assert_same_doc(doc, oracle)
        # Overlap counted once extra (`double_delete.rs:41-106`).
        assert [(e.target, e.length, e.excess)
                for e in oracle.double_deletes] == [(2, 2, 1)]

    def test_local_remote_convergence(self):
        # The reference's `remote_txns` convergence check (`doc.rs:620-676`):
        # the same logical history applied locally vs via remote txns.
        rng = random.Random(5)
        patches, _ = random_patches(rng, 80)
        local = oracle_from_patches(patches, agent="conv")
        txns = export_txns_since(local, 0)
        doc = self._device_from_txns(txns, capacity=1024)
        assert SA.to_string(doc) == local.to_string()
        assert SA.doc_spans(doc) == local.doc_spans()


class TestUpload:
    def test_oracle_roundtrip(self):
        # Warm-start path: host oracle -> device arrays -> same doc.
        rng = random.Random(23)
        patches, content = random_patches(rng, 60)
        oracle = oracle_from_patches(patches)
        table = B.AgentTable(["oracle-agent"])
        doc = SA.upload_oracle(oracle, 512, table.rank_of_agent())
        assert_same_doc(doc, oracle)
        # And keep editing on device from the uploaded state.
        more = [TestPatch(0, 0, "resumed:")]
        ops, _ = B.compile_local_patches(
            more, start_order=oracle.get_next_order())
        out = F.apply_ops(doc, ops)
        assert SA.to_string(out) == "resumed:" + content


class TestBatched:
    def test_tiled_identical_docs(self):
        rng = random.Random(13)
        patches, content = random_patches(rng, 50)
        ops, _ = B.compile_local_patches(patches, lmax=4)
        batched = B.tile_ops(ops, 4)
        docs = SA.stack_docs(SA.make_flat_doc(512), 4)
        out = F.apply_ops_batch(docs, batched)
        for i in range(4):
            one = jax_tree_index(out, i)
            assert SA.to_string(one) == content

    def test_ragged_stacked_docs(self):
        rng = random.Random(17)
        streams, contents = [], []
        for k in (20, 45, 70):
            patches, content = random_patches(random.Random(100 + k), k)
            ops, _ = B.compile_local_patches(patches, lmax=4)
            streams.append(ops)
            contents.append(content)
        batched = B.stack_ops(streams)
        docs = SA.stack_docs(SA.make_flat_doc(512), 3)
        out = F.apply_ops_batch(docs, batched)
        for i, content in enumerate(contents):
            assert SA.to_string(jax_tree_index(out, i)) == content


def jax_tree_index(tree, i):
    import jax
    return jax.tree.map(lambda x: x[i], tree)

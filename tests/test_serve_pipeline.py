"""Pipelined-vs-serial tick equivalence (ISSUE 12 tentpole).

The pipelined tick (``ServeConfig.pipeline_ticks`` > 1) defers the
per-tick device sync to a staged sync point so the next tick's host
work overlaps the in-flight device step.  The contract that makes the
refactor safe to ship default-on: pipelining moves WALL TIME ONLY —
same-seed runs with the pipeline on and off must emit byte-identical
logical trace streams (flow spans included), identical green
conservation audits, identical op-age distributions, and identical
logical counters (the same numbers ``bench.py --check-ledger`` gates,
which tier-1 runs against the shipped pipelined default).  Faults and
mid-run evict->restore ride along, because that is where a deferred
sync could plausibly leak state across the checkpoint boundary.
"""
import jax
import pytest

jax.config.update("jax_platforms", "cpu")

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402
from text_crdt_rust_tpu.serve.server import DocServer  # noqa: E402

# Counters that must not know whether the barrier was deferred — the
# same families the serve ledger cell pins.
LOGICAL_KEYS = ("item_ops_applied", "rejected_submissions",
                "drain_rounds")
LOGICAL_TICK_KEYS = ("steps_total", "steps_prefuse", "fused_rows_saved",
                     "ops_per_step", "device_compiles")
LOGICAL_SRV_KEYS = ("device_ticks", "device_steps", "evictions",
                    "restores", "admitted", "ckpt_bytes_written")


_LANES_CFG = dict(engine="rle-lanes-mixed", lane_capacity=128,
                  lanes_block_k=8, order_capacity=512,
                  step_buckets=(8, 32), max_txn_len=32)


def _loadgen_run(pipeline_ticks: int, engine: str = "flat",
                 docs: int = 8, ticks: int = 10):
    # sanitize_pipeline rides the PIPELINED arm (ISSUE 13: left on in
    # the serve tests): the byte-identity assert below then doubles as
    # the sanitized-vs-unsanitized logical-invisibility proof.
    kw = dict(_LANES_CFG) if engine == "rle-lanes-mixed" else \
        dict(engine="flat")
    cfg = ServeConfig(num_shards=2, lanes_per_shard=4,
                      pipeline_ticks=pipeline_ticks, trace_keep=True,
                      sanitize_pipeline=pipeline_ticks > 1,
                      flow_sample_mod=1, **kw)
    gen = ServeLoadGen(docs=docs, agents_per_doc=2, ticks=ticks,
                       events_per_tick=12, fault_rate=0.10, seed=7,
                       cfg=cfg)
    rep = gen.run()
    return rep, gen.server.tracer.logical_bytes()


def test_pipelined_vs_serial_byte_identical_under_faults():
    rep_p, trace_p = _loadgen_run(2)
    rep_s, trace_s = _loadgen_run(1)
    assert rep_s["converged"] and rep_p["converged"]
    assert trace_s == trace_p, "logical streams must be mode-invariant"
    # Flow provenance: green audits, identical census and ages.
    for rep in (rep_s, rep_p):
        assert rep["flow"]["audit_ok"], rep["flow"]["findings"][:4]
        assert rep["flow"]["spans"]["in_flight"] == 0
    assert rep_s["flow"]["spans"] == rep_p["flow"]["spans"]
    assert rep_s["flow"]["ages_ticks"] == rep_p["flow"]["ages_ticks"]
    assert rep_s["flow"]["by_class"] == rep_p["flow"]["by_class"]
    # The ledger-gated logical counters re-derive identically.
    for key in LOGICAL_KEYS:
        assert rep_s[key] == rep_p[key], key
    for key in LOGICAL_TICK_KEYS:
        assert rep_s["tick_ms"][key] == rep_p["tick_ms"][key], key
    for key in LOGICAL_SRV_KEYS:
        assert rep_s["server"].get(key) == rep_p["server"].get(key), key
    assert rep_s["wire"] == rep_p["wire"]
    # Mode shows ONLY where it should: the effective depth.
    assert rep_s["pipeline"]["ticks"] == 1
    assert rep_p["pipeline"]["ticks"] == 2


def _direct_server_run(pipeline_ticks: int, engine: str = "flat"):
    """Deterministic direct-server drive with a FORCED mid-run
    evict->restore while the pipeline holds an in-flight tick — the
    checkpoint boundary a deferred sync must not smear state across."""
    kw = dict(_LANES_CFG) if engine == "rle-lanes-mixed" else \
        dict(engine="flat")
    cfg = ServeConfig(num_shards=1, lanes_per_shard=2,
                      pipeline_ticks=pipeline_ticks, trace_keep=True,
                      sanitize_pipeline=pipeline_ticks > 1,
                      flow_sample_mod=1, **kw)
    server = DocServer(cfg)
    for d in range(3):
        server.admit_doc(f"doc{d}")
    for i in range(4):
        for d in range(3):
            server.submit_local(f"doc{d}", "alice", pos=0,
                                ins_content=f"t{i}d{d}x")
        server.tick()
    # Evict doc0 mid-run, straight after a tick whose device pass may
    # still be in flight; keep editing it so the next tick restores.
    doc0 = server.doc_state("doc0")
    if doc0.resident:
        server.residency.evict(doc0)
    for i in range(3):
        for d in range(3):
            server.submit_local(f"doc{d}", "alice", pos=0,
                                ins_content=f"u{i}d{d}y")
        server.tick()
    server.drain()
    assert all(server.verify_doc(f"doc{d}") for d in range(3))
    strings = [server.doc_string(f"doc{d}") for d in range(3)]
    flow = server.flow_summary(expect_terminal=True)
    trace = server.tracer.logical_bytes()
    server.close_obs()
    return strings, flow, trace, server


def test_mid_run_evict_restore_equivalence():
    strings_p, flow_p, trace_p, srv_p = _direct_server_run(2)
    strings_s, flow_s, trace_s, srv_s = _direct_server_run(1)
    assert strings_s == strings_p
    assert trace_s == trace_p
    assert flow_s["audit_ok"] and flow_p["audit_ok"]
    assert flow_s["spans"] == flow_p["spans"]
    ev_s = srv_s.counters.summary().get("evictions")
    assert ev_s == srv_p.counters.summary().get("evictions")
    assert ev_s >= 1  # the forced evict (LRU churn may add more)


def test_overlap_accounting_and_flush():
    _, _, _, server = _direct_server_run(2)
    tick_sum = server.tick_summary()
    assert tick_sum["pipeline_ticks"] == 2
    # The staged sync ran: windows accrued, and every applied event's
    # latency was stamped at (or before) the end-of-run flush.
    assert 0.0 < tick_sum["pipeline_overlap_frac"] <= 1.0
    assert len(server.batcher.latency_samples) > 0
    assert not server.batcher._inflight
    server.flush_pipeline()  # idempotent
    assert not server.batcher._inflight
    # Serial loop: depth 1 and an EXACT 0.0 overlap fraction — the
    # immediate sync accrues no window, so bookkeeping gaps can't
    # manufacture overlap (the documented contract the probe's
    # overlap_frac>0 acceptance gate leans on).
    _, _, _, serial = _direct_server_run(1)
    assert serial.tick_summary()["pipeline_ticks"] == 1
    assert serial.tick_summary()["pipeline_overlap_frac"] == 0.0


def test_lanes_backend_opts_into_depth_two():
    """ISSUE 14 (ROADMAP 7a): the blocked lanes backend's run-row
    true-up moved to a host-mirrored fixed-schedule model, so its
    barrier no longer feeds the capacity probes and it opts into depth
    2 — capped THERE, not at the config's deeper ask (the dispatch-edge
    sync is what guarantees its lagged true-up reads stay cheap)."""
    cfg = ServeConfig(num_shards=1, lanes_per_shard=2,
                      pipeline_ticks=4, **_LANES_CFG)
    server = DocServer(cfg)
    assert server.batcher.pipeline_ticks == 4
    assert server.batcher.effective_pipeline_ticks() == 2
    server.close_obs()


def test_lanes_pipelined_depth2_byte_identical_under_faults():
    """The ISSUE-14 acceptance arm: the LANES backend at depth 2 vs
    depth 1 under 10% faults — logical streams, flow census and the
    ledger-gated counters all byte-identical (the fixed-schedule row
    true-up is depth-invariant by construction; this pins it)."""
    rep_p, trace_p = _loadgen_run(2, engine="rle-lanes-mixed", docs=6,
                                  ticks=8)
    rep_s, trace_s = _loadgen_run(1, engine="rle-lanes-mixed", docs=6,
                                  ticks=8)
    assert rep_s["converged"] and rep_p["converged"]
    assert trace_s == trace_p, "lanes logical streams must be depth-invariant"
    assert rep_s["flow"]["spans"] == rep_p["flow"]["spans"]
    assert rep_s["flow"]["ages_ticks"] == rep_p["flow"]["ages_ticks"]
    for key in LOGICAL_KEYS:
        assert rep_s[key] == rep_p[key], key
    for key in LOGICAL_SRV_KEYS:
        assert rep_s["server"].get(key) == rep_p["server"].get(key), key
    assert rep_s["pipeline"]["ticks"] == 1
    assert rep_p["pipeline"]["ticks"] == 2
    assert rep_p["pipeline"]["overlap_frac"] > 0.0


@pytest.mark.slow
def test_lanes_mid_run_evict_restore_depth_equivalence():
    """The lanes backend's depth-2 evict->restore boundary: a forced
    mid-run evict while a tick may be in flight, then a restore (the
    per-lane blocked reseed) — strings, traces and flow census
    identical to the serial run (the residency-fresh mask keeps the
    lagged true-up from resurrecting pre-upload row counts).  Slow
    tier since PR 17 (wall budget: ~42 s): the evict->restore boundary
    keeps tier-1 coverage through the flat backend's train/pipeline
    equivalence tests (tests/test_serve_train.py) and the lanes
    pipelined depth-2 byte-identity test above."""
    strings_p, flow_p, trace_p, srv_p = _direct_server_run(
        2, engine="rle-lanes-mixed")
    strings_s, flow_s, trace_s, srv_s = _direct_server_run(
        1, engine="rle-lanes-mixed")
    assert strings_s == strings_p
    assert trace_s == trace_p
    assert flow_s["audit_ok"] and flow_p["audit_ok"]
    assert flow_s["spans"] == flow_p["spans"]
    ev = srv_s.counters.summary().get("evictions")
    assert ev == srv_p.counters.summary().get("evictions")
    assert ev >= 1


def test_depth_one_is_exactly_the_serial_loop():
    """pipeline_ticks=1 never leaves an entry in flight after a tick
    (the PR-3 barrier-every-tick shape, bit for bit)."""
    cfg = ServeConfig(engine="flat", num_shards=1, lanes_per_shard=2,
                      pipeline_ticks=1)
    server = DocServer(cfg)
    server.admit_doc("d")
    server.submit_local("d", "a", pos=0, ins_content="hi")
    server.tick()
    assert not server.batcher._inflight
    server.close_obs()

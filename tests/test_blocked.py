"""Blocked Pallas replay engine vs the flat engine and string oracle.

Runs in Pallas interpreter mode on CPU (the real kernel is exercised on
TPU by ``bench.py --engine blocked``, which asserts final content). Small
blocks force constant rebalancing, the analog of the reference's shrunken
debug node sizes that force splits under test (`range_tree/mod.rs:29-39`).
"""
import random

import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import blocked as BL
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    TestPatch,
    flatten_patches,
    load_testing_data,
    trace_path,
)

from test_device_flat import random_patches


def run_blocked(patches, capacity, block_k, lmax=4, chunk=128):
    ops, _ = B.compile_local_patches(patches, lmax=lmax, dmax=lmax)
    res = BL.replay_local(ops, capacity=capacity, batch=8,
                          block_k=block_k, chunk=chunk, interpret=True)
    return ops, BL.blocked_to_flat(ops, res)


class TestBlockedReplay:
    def test_smoke(self):
        patches = [TestPatch(0, 0, "hello world"), TestPatch(5, 0, ","),
                   TestPatch(2, 3, "LLO"), TestPatch(0, 1, "H")]
        ops, doc = run_blocked(patches, capacity=64, block_k=8)
        ref = F.apply_ops(SA.make_flat_doc(64), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == "HeLLO, world"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.parametrize("seed", [7, 11, 99])
    def test_random_vs_flat(self, seed):
        # Tiny blocks: every few inserts overflows a block and forces the
        # rebalance path (the node-split analog).
        rng = random.Random(seed)
        patches, content = random_patches(rng, 80)
        ops, doc = run_blocked(patches, capacity=512, block_k=16)
        ref = F.apply_ops(SA.make_flat_doc(512), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == content
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_delete_spanning_blocks(self):
        # One delete crossing several small blocks: the windowed walk
        # (`doc.rs:311-334` analog) plus compiler delete chunking.
        patches = [TestPatch(0, 0, "abcdefghijklmnopqrstuvwxyz")]
        patches += [TestPatch(2, 20, "")]
        ops, doc = run_blocked(patches, capacity=64, block_k=8)
        ref = F.apply_ops(SA.make_flat_doc(64), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == "abwxyz"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_prepend_heavy(self):
        # The "kevin" shape (`benches/yjs.rs:51-62`): always insert at 0 —
        # block 0 overflows over and over.
        patches = [TestPatch(0, 0, "ab") for _ in range(40)]
        ops, doc = run_blocked(patches, capacity=256, block_k=8)
        ref = F.apply_ops(SA.make_flat_doc(256), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == "ab" * 40
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.slow
    def test_trace_prefix(self):
        # automerge-paper: single-char typing, the bench workload shape
        # (sveltecomponent opens with a 3k-char paste — too big for
        # interpreter-mode block counts).
        data = load_testing_data(trace_path("automerge-paper"))
        patches = flatten_patches(data)[:400]
        ops, doc = run_blocked(patches, capacity=1024, block_k=32,
                               lmax=16)
        ref = F.apply_ops(SA.make_flat_doc(1024), ops)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_capacity_exhaustion_rejected(self):
        # The host-side precheck proves the rebalance fill limit can never
        # be exceeded mid-kernel (the kernel's err flag stays as
        # defense-in-depth), so an oversized stream is rejected up front.
        patches = [TestPatch(0, 0, "x" * 4) for _ in range(20)]
        ops, _ = B.compile_local_patches(patches, lmax=4, dmax=4)
        with pytest.raises(AssertionError, match="raise capacity"):
            BL.replay_local(ops, capacity=32, batch=8, block_k=8,
                            chunk=128, interpret=True)

"""Block-wise >HBM read scans vs a dense host reference.

``StreamedRuns`` must answer the two hot conversions identically to a
direct dense scan over the same run planes, for every tile boundary
alignment — the host-carried tile table plays the role sp_runs gives
the mesh axis, so its seams are where the bugs would live."""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.ops.stream_scan import StreamedRuns


def random_planes(rng, rows):
    """Run planes with live/tombstone/empty rows and dense orders."""
    ordp, lenp = [], []
    nxt = 0
    for _ in range(rows):
        ln = rng.randint(1, 9)
        sign = 1 if rng.random() < 0.7 else -1
        ordp.append(sign * (nxt + 1))
        lenp.append(ln)
        nxt += ln
    # sprinkle empty rows (capacity padding mid-plane is not legal in
    # the engines, but trailing empties are)
    ordp += [0, 0, 0]
    lenp += [0, 0, 0]
    return np.asarray(ordp, np.int32), np.asarray(lenp, np.int32), nxt


def dense_reference(ordp, lenp):
    """(live_total, rank->(row,off) map, order->pos map)."""
    live = 0
    rank_map = {}
    pos_map = {}
    for row, (o, ln) in enumerate(zip(ordp.tolist(), lenp.tolist())):
        if o == 0:
            continue
        start = abs(o) - 1
        for j in range(ln):
            if o > 0:
                live += 1
                rank_map[live] = (row, j + 1)
                pos_map[start + j] = live - 1
            else:
                pos_map[start + j] = -1
    return live, rank_map, pos_map


@pytest.mark.parametrize("tile", (8, 16, 64))
def test_matches_dense_reference(tile):
    rng = random.Random(11)
    ordp, lenp, total_orders = random_planes(rng, 37)
    sr = StreamedRuns(ordp, lenp, tile=tile)
    live, rank_map, pos_map = dense_reference(ordp, lenp)

    assert sr.live_total() == live
    for rank in range(1, live + 1):
        assert sr.position_of_live_rank(rank) == rank_map[rank], rank
    assert sr.position_of_live_rank(0) == (-1, 0)
    assert sr.position_of_live_rank(live + 1) == (-1, 0)
    for order in range(total_orders):
        assert sr.order_to_position(order) == pos_map[order], order
    assert sr.order_to_position(total_orders + 5) == -1


def test_single_tile_and_exact_boundary():
    ordp = np.asarray([1, -4, 6], np.int32)   # live[3] dead[2] live[2]
    lenp = np.asarray([3, 2, 2], np.int32)
    for tile in (8, 3, 1):
        sr = StreamedRuns(ordp, lenp, tile=tile)
        assert sr.live_total() == 5
        assert sr.position_of_live_rank(4) == (2, 1)
        assert sr.order_to_position(3) == -1      # tombstoned
        assert sr.order_to_position(5) == 3       # first char of run 3

"""Pipeline aliasing sanitizer (ISSUE 13 tentpole, runtime half).

The double-buffered tick (ISSUE 12) opened a hazard class with no
tooling watching it: host code mutating arrays an in-flight device
step still reads (JAX's CPU zero-copy conversion can alias the numpy
buffers the compiled step consumes).  ``ServeConfig.sanitize_pipeline``
CRC-fingerprints every dispatched tick's op tensors at the dispatch
edge and re-checks them at that entry's staged sync.  Contract:

- an injected host write to an in-flight tick's arrays fails LOUD,
  naming the tick, shard and array;
- a clean sanitized run is logically invisible: byte-identical trace
  stream, identical convergence, zero new events;
- cheap enough to leave on in the serve tests (the §18 overhead
  measurement rides perf/lint_sanitize_probe.py).
"""
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.serve.batcher import (  # noqa: E402
    PipelineAliasingError,
)
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402
from text_crdt_rust_tpu.serve.server import DocServer  # noqa: E402


def _server(pipeline_ticks=2, sanitize=True):
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=2,
                      pipeline_ticks=pipeline_ticks,
                      sanitize_pipeline=sanitize, trace_keep=True)
    srv = DocServer(cfg)
    for d in range(3):
        srv.admit_doc(f"doc{d}")
    return srv


def test_injected_race_fails_naming_tick_shard_array():
    srv = _server()
    srv.submit_local("doc0", "alice", pos=0, ins_content="hello")
    srv.tick()
    entry = srv.batcher._inflight[-1]
    assert entry["guards"], "dispatched tick must carry guards"
    guard = entry["guards"][0]
    # The host write racing the in-flight device step: stack_ops hands
    # the backend plain numpy arrays, so this is exactly the aliasing
    # surface.
    np.asarray(guard["arrays"].chars)[0] += 1
    with pytest.raises(PipelineAliasingError) as ei:
        srv.flush_pipeline()
    msg = str(ei.value)
    assert f"tick {entry['tick']}" in msg
    assert f"shard {guard['shard']}" in msg
    assert "'chars'" in msg
    srv.close_obs()


def test_race_detected_at_staged_sync_not_only_flush():
    """The mid-run spelling: the NEXT tick's staged sync (not an
    explicit flush) is where the re-check fires."""
    srv = _server()
    srv.submit_local("doc0", "alice", pos=0, ins_content="hello")
    srv.tick()
    guard = srv.batcher._inflight[-1]["guards"][0]
    np.asarray(guard["arrays"].pos)[0] += 3
    with pytest.raises(PipelineAliasingError, match="'pos'"):
        for _ in range(3):  # next device dispatch syncs the old entry
            srv.submit_local("doc0", "alice", pos=0, ins_content="x")
            srv.tick()
    srv.close_obs()


def test_clean_sanitized_run_checks_and_converges():
    srv = _server()
    for i in range(6):
        for d in range(3):
            srv.submit_local(f"doc{d}", "alice", pos=0,
                             ins_content=f"t{i}d{d}")
        srv.tick()
    srv.drain()
    assert all(srv.verify_doc(f"doc{d}") for d in range(3))
    assert srv.counters.summary()["sanitize_checks"] > 0
    srv.close_obs()


def test_sanitizer_active_in_serial_loop_too():
    srv = _server(pipeline_ticks=1)
    srv.submit_local("doc0", "alice", pos=0, ins_content="hi")
    srv.tick()
    assert srv.counters.summary()["sanitize_checks"] > 0
    srv.close_obs()


def _loadgen_run(sanitize: bool):
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=4,
                      pipeline_ticks=2, sanitize_pipeline=sanitize,
                      trace_keep=True, flow_sample_mod=1)
    gen = ServeLoadGen(docs=8, agents_per_doc=2, ticks=10,
                       events_per_tick=12, fault_rate=0.10, seed=7,
                       cfg=cfg)
    rep = gen.run()
    return rep, gen.server.tracer.logical_bytes()


def test_sanitizer_on_is_byte_identical_under_faults():
    """Same-seed sanitizer-on/off loadgen runs (faults + evictions):
    identical logical streams, identical convergence — detection must
    be free of logical side effects, or turning it on to debug a race
    would change the run being debugged."""
    rep_on, trace_on = _loadgen_run(True)
    rep_off, trace_off = _loadgen_run(False)
    assert rep_on["converged"] and rep_off["converged"]
    assert trace_on == trace_off
    assert rep_on["pipeline"]["sanitize"] is True
    assert rep_on["pipeline"]["sanitize_checks"] > 0
    assert rep_off["pipeline"]["sanitize_checks"] == 0
    assert rep_on["flow"]["spans"] == rep_off["flow"]["spans"]

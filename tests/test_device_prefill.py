"""Device-resident by-order logs (ISSUE 14 tentpole).

The flat serve backend now ships only the per-tick prefill SCATTER to
the device (``batch.prefill_delta`` -> ``flat.apply_prefill_delta``)
instead of round-tripping the four full [B, OCAP] logs through host
numpy (``batch.prefill_logs``).  The contract that makes the path safe
to ship default-on:

- **bit-identity**: both paths are projections of the same
  ``_prefill_scatter``, so every log (ol/or/rank/chars) and every
  downstream by-order table must be byte-equal across local, remote,
  mixed, fused (``rows_per_step`` > 1), stacked-ragged and tiled
  streams;
- **mode invisibility**: same-seed serve runs with device prefill on
  and off emit byte-identical logical streams, flow censuses and
  ledger counters, at pipeline depths 1 AND 2, under faults and a
  forced mid-run evict->restore;
- **zero full-log host reads** on the tick path (the O(state) cost and
  the hidden device sync are GONE, not just cheaper);
- **bounded compiles**: scatter lengths pad to geometric buckets, so
  steady state cycles a fixed scatter-program set next to the fixed
  step-bucket set.
"""
import random

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from text_crdt_rust_tpu.config import ServeConfig  # noqa: E402
from text_crdt_rust_tpu.models.oracle import ListCRDT  # noqa: E402
from text_crdt_rust_tpu.models.sync import export_txns_since  # noqa: E402
from text_crdt_rust_tpu.ops import batch as B  # noqa: E402
from text_crdt_rust_tpu.ops import flat as F  # noqa: E402
from text_crdt_rust_tpu.ops import span_arrays as SA  # noqa: E402
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen  # noqa: E402
from text_crdt_rust_tpu.serve.server import DocServer  # noqa: E402
from text_crdt_rust_tpu.utils.testdata import TestPatch  # noqa: E402

LOGS = ("ol_log", "or_log", "rank_log", "chars_log")
ALPHABET = "abcdefgh "


def assert_logs_equal(host_doc, dev_doc):
    for f in LOGS:
        assert np.array_equal(np.asarray(getattr(host_doc, f)),
                              np.asarray(getattr(dev_doc, f))), f


def random_local_stream(seed: int, steps: int = 18, lmax: int = 8):
    rng = random.Random(seed)
    content = ""
    patches = []
    for _ in range(steps):
        if not content or rng.random() < 0.65:
            pos = rng.randint(0, len(content))
            ins = "".join(rng.choice(ALPHABET)
                          for _ in range(rng.randint(1, 6)))
            patches.append(TestPatch(pos, 0, ins))
            content = content[:pos] + ins + content[pos:]
        else:
            pos = rng.randint(0, len(content) - 1)
            span = min(rng.randint(1, 3), len(content) - pos)
            patches.append(TestPatch(pos, span, ""))
            content = content[:pos] + content[pos + span:]
    ops, _ = B.compile_local_patches(patches, lmax=lmax)
    return ops


def mixed_remote_stream(seed: int, lmax: int = 8):
    """A remote/local MIXED compiled stream: two peers edit, their txn
    history compiles through ``compile_remote_txns`` (remote origins +
    remote delete target runs — the or/ol prefill subsets a pure local
    stream never exercises)."""
    rng = random.Random(seed)
    peer = ListCRDT()
    ids = [peer.get_or_create_agent_id(a) for a in ("amy", "bob")]
    for _ in range(14):
        a = rng.choice(ids)
        if not len(peer) or rng.random() < 0.7:
            peer.local_insert(a, rng.randint(0, len(peer)), "".join(
                rng.choice(ALPHABET) for _ in range(rng.randint(1, 5))))
        else:
            pos = rng.randint(0, len(peer) - 1)
            peer.local_delete(a, pos, min(rng.randint(1, 3),
                                          len(peer) - pos))
    txns = export_txns_since(peer, 0)
    table = B.AgentTable(["amy", "bob"])
    ops, _ = B.compile_remote_txns(txns, table, lmax=lmax)
    return ops


def fused_burst_stream(lmax: int = 8, w: int = 4):
    """The kevin prepend shape compiled with fuse_w > 1: W-row fused
    steps whose prefill chain breaks at every sub-run head."""
    patches = [TestPatch(0, 0, "xy") for _ in range(10)]
    ops, _ = B.compile_local_patches(patches, lmax=lmax, fuse_w=w)
    assert B.fused_width(ops) > 1
    return ops


def _both_paths(doc, ops):
    host = B.prefill_logs(doc, ops)
    dev = F.apply_prefill_delta(doc, B.prefill_delta(ops))
    return host, dev


@pytest.mark.parametrize("seed", range(6))
def test_local_stream_delta_equals_host_prefill(seed):
    ops = random_local_stream(seed)
    doc = SA.make_flat_doc(256, 512)
    assert_logs_equal(*_both_paths(doc, ops))


@pytest.mark.parametrize("seed", range(6))
def test_mixed_remote_stream_delta_equals_host_prefill(seed):
    ops = mixed_remote_stream(seed)
    doc = SA.make_flat_doc(256, 512)
    assert_logs_equal(*_both_paths(doc, ops))


def test_fused_stream_delta_equals_host_prefill():
    ops = fused_burst_stream()
    doc = SA.make_flat_doc(256, 512)
    assert_logs_equal(*_both_paths(doc, ops))


@pytest.mark.parametrize("seed", range(4))
def test_stacked_ragged_batch_delta_equals_host_prefill(seed):
    """The serve shape: ragged per-lane streams stacked [S, B] onto a
    batched doc — per-lane scatters, lane-local buckets."""
    import jax.numpy as jnp

    streams = [random_local_stream(seed * 10 + k, steps=4 + 3 * k)
               for k in range(3)] + [B.empty_ops(8)]
    stacked = B.stack_ops(streams)
    docs = jax.tree.map(jnp.array,
                        SA.stack_docs(SA.make_flat_doc(256, 512), 4))
    assert_logs_equal(*_both_paths(docs, stacked))


def test_tiled_batch_broadcast_delta_equals_host_prefill():
    """One stream tiled across B docs: the unbatched-delta broadcast
    path (config-2 shape)."""
    import jax.numpy as jnp

    ops = random_local_stream(3)
    docs = jax.tree.map(jnp.array,
                        SA.stack_docs(SA.make_flat_doc(256, 512), 3))
    host = B.prefill_logs(docs, B.tile_ops(ops, 3))
    dev = F.apply_prefill_delta(docs, B.prefill_delta(ops))
    assert_logs_equal(host, dev)


def test_full_apply_through_delta_matches_oracle_tables():
    """End to end through the step scan: delta-prefill + apply equals
    host-prefill + apply on the whole doc (signed body, by-order
    tables, string)."""
    ops = mixed_remote_stream(9)
    doc = SA.make_flat_doc(256, 512)
    via_host = F.apply_ops(doc, ops)  # prefill=True: the host path
    via_delta = F._apply_ops(
        F.apply_prefill_delta(doc, B.prefill_delta(ops)), ops,
        local_only=False)
    assert np.array_equal(np.asarray(via_host.signed),
                          np.asarray(via_delta.signed))
    assert_logs_equal(via_host, via_delta)
    assert SA.to_string(via_host) == SA.to_string(via_delta)
    assert SA.doc_spans(via_host) == SA.doc_spans(via_delta)


def test_empty_and_delete_only_streams_skip_the_scatter():
    """A stream with no inserts writes no log values: prefill_delta is
    None (no scatter program compiled) and the no-op passthrough leaves
    the doc untouched."""
    doc = SA.make_flat_doc(64, 128)
    assert B.prefill_delta(B.empty_ops(4)) is None
    ops, _ = B.compile_local_patches([TestPatch(0, 0, "abc")], lmax=4)
    doc2 = F.apply_ops(doc, ops)
    del_ops, _ = B.compile_local_patches([TestPatch(0, 2, "")], lmax=4,
                                         start_order=3)
    assert B.prefill_delta(del_ops) is None
    assert F.apply_prefill_delta(doc2, None) is doc2


def test_scatter_bucket_series_is_geometric_and_bounded():
    assert B.scatter_bucket(0) == B.PREFILL_BUCKET_BASE
    assert B.scatter_bucket(32) == 32
    assert B.scatter_bucket(33) == 128
    assert B.scatter_bucket(2048) == 2048
    # Any serve tick (S <= 128 steps x lmax 16) sees at most 4 buckets.
    buckets = {B.scatter_bucket(n) for n in range(0, 128 * 16 + 1, 7)}
    assert len(buckets) <= 4, buckets


# -- serve-level contracts ----------------------------------------------------


def _serve_run(device_prefill: bool, pipeline_ticks: int):
    cfg = ServeConfig(engine="flat", num_shards=2, lanes_per_shard=4,
                      device_prefill=device_prefill,
                      pipeline_ticks=pipeline_ticks, trace_keep=True,
                      flow_sample_mod=1)
    gen = ServeLoadGen(docs=8, agents_per_doc=2, ticks=8,
                       events_per_tick=12, fault_rate=0.10, seed=7,
                       cfg=cfg)
    rep = gen.run()
    assert rep["converged"], rep["mismatches"][:4]
    return rep, gen.server.tracer.logical_bytes()


def test_serve_delta_vs_host_prefill_byte_identical_both_depths():
    """The ISSUE-14 acceptance: same-seed logical streams, flow audits
    and ledger counters byte-identical delta-vs-host prefill at
    pipeline depths 1 and 2, under 10% faults.  Only the prefill byte
    economy itself may differ."""
    runs = {(dp, pt): _serve_run(dp, pt)
            for dp in (True, False) for pt in (1, 2)}
    traces = {k: t for k, (_, t) in runs.items()}
    assert len(set(traces.values())) == 1, \
        "logical streams must not know the prefill mode or depth"
    reps = {k: r for k, (r, _) in runs.items()}
    ref = reps[(True, 2)]
    for key, rep in reps.items():
        assert rep["flow"]["audit_ok"], rep["flow"]["findings"][:4]
        assert rep["flow"]["spans"] == ref["flow"]["spans"], key
        assert rep["flow"]["ages_ticks"] == ref["flow"]["ages_ticks"]
        for counter in ("device_ticks", "device_steps", "device_compiles",
                        "evictions", "restores", "admitted"):
            assert rep["server"].get(counter) == ref["server"].get(
                counter), (key, counter)
        assert rep["wire"] == ref["wire"], key
    # The byte economy is the only divergence: the delta path moves
    # >= 20x less than the full-log round trip and compiles a bounded
    # scatter set; the host path moves the full logs and compiles none.
    assert ref["prefill"]["device_prefill"]
    assert ref["prefill"]["bytes_cut_x"] >= 20.0, ref["prefill"]
    assert 1 <= ref["prefill"]["scatter_compiles"] <= 12
    host = reps[(False, 2)]["prefill"]
    assert not host["device_prefill"]
    assert host["bytes_cut_x"] == 1.0
    assert host["scatter_compiles"] == 0


def test_forced_evict_restore_mode_equivalence(tmp_path):
    """Delta-vs-host equivalence across a FORCED mid-run evict->restore
    (the host-mirror reset path: upload_lane must reseed the mirrored
    n/next_order exactly or the capacity check diverges later)."""
    outs = {}
    for dp in (True, False):
        cfg = ServeConfig(engine="flat", num_shards=1, lanes_per_shard=2,
                          device_prefill=dp, pipeline_ticks=2,
                          trace_keep=True, flow_sample_mod=1,
                          spool_dir=str(tmp_path / f"dp{dp}"))
        server = DocServer(cfg)
        for d in range(3):
            server.admit_doc(f"doc{d}")
        for i in range(4):
            for d in range(3):
                server.submit_local(f"doc{d}", "alice", pos=0,
                                    ins_content=f"t{i}d{d}x")
            server.tick()
        doc0 = server.doc_state("doc0")
        if doc0.resident:
            server.residency.evict(doc0)
        for i in range(3):
            for d in range(3):
                server.submit_local(f"doc{d}", "alice", pos=0,
                                    ins_content=f"u{i}d{d}y")
            server.tick()
        server.drain()
        assert all(server.verify_doc(f"doc{d}") for d in range(3))
        outs[dp] = ([server.doc_string(f"doc{d}") for d in range(3)],
                    server.tracer.logical_bytes(),
                    server.flow_summary(expect_terminal=True)["spans"])
        server.close_obs()
    assert outs[True] == outs[False]


def test_no_full_log_host_materialization_on_tick_path(monkeypatch):
    """With device_prefill on (the shipped default), the serve tick
    performs ZERO full-log host materializations: ``prefill_logs`` is
    never reached (this guards the acceptance criterion directly — a
    regression re-introducing the round trip trips the sentinel)."""
    def boom(*a, **kw):
        raise AssertionError(
            "batch.prefill_logs reached from the serve tick path with "
            "device_prefill on — the full-log host round trip is back")

    monkeypatch.setattr(B, "prefill_logs", boom)
    server = DocServer(ServeConfig(engine="flat", num_shards=1,
                                   lanes_per_shard=2))
    server.admit_doc("d")
    for i in range(3):
        server.submit_local("d", "a", pos=0, ins_content=f"hi{i}")
        server.tick()
    server.drain()
    assert server.verify_doc("d")
    assert server.doc_string("d").startswith("hi2")
    server.close_obs()


def test_scatter_recompile_guard_steady_state_bounded():
    """Varying per-tick insert volumes must not grow the compiled
    scatter set past the geometric bucket count: shapes_seen stays
    inside the step buckets AND scatter_shapes_seen inside the
    scatter-bucket series (the (S, scatter_bucket) steady-state
    contract)."""
    server = DocServer(ServeConfig(engine="flat", num_shards=1,
                                   lanes_per_shard=2,
                                   step_buckets=(8, 32),
                                   max_txn_len=32))
    server.admit_doc("d")
    rng = np.random.RandomState(0)
    for _ in range(14):
        for _ in range(int(rng.randint(1, 5))):
            n = int(rng.randint(1, 12))
            server.submit_local("d", "ed", 0, ins_content="x" * n)
        server.tick()
    backend = server.residency.backends[0]
    assert backend.shapes_seen <= {8, 32}, backend.shapes_seen
    legal = {B.PREFILL_BUCKET_BASE * 4 ** k for k in range(4)}
    assert backend.scatter_shapes_seen <= legal, \
        backend.scatter_shapes_seen
    assert len(backend.scatter_shapes_seen) <= 3
    assert server.verify_doc("d")
    server.close_obs()


def test_host_mirrored_capacity_check_matches_device_counts():
    """The device path's capacity check reads HOST mirrors, never the
    device: after ticks, evict->restore and clears, the mirrors must
    equal the device's n/next_order exactly."""
    server = DocServer(ServeConfig(engine="flat", num_shards=1,
                                   lanes_per_shard=2))
    server.admit_doc("d")
    for i in range(4):
        server.submit_local("d", "a", pos=0, ins_content=f"w{i}")
        server.tick()
    doc = server.doc_state("d")
    server.residency.evict(doc)
    server.submit_local("d", "a", pos=0, ins_content="back")
    server.tick()
    server.drain()
    backend = server.residency.backends[0]
    assert np.array_equal(backend._n_host,
                          np.asarray(backend.docs.n, dtype=np.int64))
    assert np.array_equal(
        backend._next_order_host,
        np.asarray(backend.docs.next_order, dtype=np.int64))
    server.close_obs()


def test_mirror_skip_injection_caught_by_runtime_and_lint(monkeypatch):
    """ISSUE 15 satellite (host-mirror desync coverage): patch ONE
    device-state write site — ``FlatLaneBackend.apply`` runs its
    delta-prefill scatter + step scan but SKIPS its paired host-mirror
    update — and assert BOTH guards name it:

    - runtime: the ``host-mirror == device-count`` check (the test
      above) goes false after one tick through the patched site;
    - static: tcrlint's TCR-M001 names the same method when the mirror
      updates are deleted from the source (the lint half, run here on
      a mutated copy of the real file so the two halves pin the SAME
      write site).
    """
    from text_crdt_rust_tpu.serve.batcher import FlatLaneBackend

    real_apply = FlatLaneBackend.apply

    def apply_skipping_mirrors(self, stacked):
        n_before = self._n_host.copy()
        next_before = self._next_order_host.copy()
        real_apply(self, stacked)
        # the seeded defect: the device advanced, the mirrors did not
        self._n_host[:] = n_before
        self._next_order_host[:] = next_before

    monkeypatch.setattr(FlatLaneBackend, "apply", apply_skipping_mirrors)
    server = DocServer(ServeConfig(engine="flat", num_shards=1,
                                   lanes_per_shard=2))
    server.admit_doc("d")
    server.submit_local("d", "a", pos=0, ins_content="drifted")
    server.tick()
    server.drain()
    backend = server.residency.backends[0]
    assert not np.array_equal(
        backend._n_host, np.asarray(backend.docs.n, dtype=np.int64)), \
        "runtime host-mirror==device-count check failed to see the skip"
    server.close_obs()

    # The static half: the same write site with its mirror updates
    # deleted from the SOURCE is a TCR-M001 naming the method.
    import os
    import tempfile

    from text_crdt_rust_tpu.analysis import run_lint

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rel = "text_crdt_rust_tpu/serve/batcher.py"
    src = open(os.path.join(repo, rel)).read()
    cut = ("        self._n_host += np.asarray(\n"
           "            stacked.ins_len, dtype=np.int64).sum(axis=0)\n"
           "        self._next_order_host += np.asarray(\n"
           "            stacked.order_advance, dtype=np.int64).sum(axis=0)\n")
    assert cut in src, "seeded-defect anchor drifted"
    with tempfile.TemporaryDirectory() as td:
        full = os.path.join(td, rel)
        os.makedirs(os.path.dirname(full))
        with open(full, "w") as f:
            f.write(src.replace(cut, ""))
        findings, _ = run_lint(
            td, [rel], allowlist_path=os.path.join(td, "a.json"),
            pins_path=os.path.join(td, "p.json"),
            shape_pins_path=os.path.join(td, "sp.json"))
    named = [f for f in findings if f.check == "TCR-M001"
             and f.scope == "FlatLaneBackend.apply"]
    assert named, [f.format() for f in findings]

"""Checkpoint corruption: truncated, bit-flipped, or version-mismatched
files must raise the typed ``CheckpointError`` — never crash with a
zip/json/numpy internals error, never load garbage (ISSUE 1 satellite).
"""
import json
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.models import ListCRDT
from text_crdt_rust_tpu.models.sync import merge_into
from text_crdt_rust_tpu.utils.checkpoint import (
    FORMAT_VERSION,
    CheckpointChain,
    CheckpointError,
    _meta_from_array,
    _meta_to_array,
    load_delta,
    load_doc,
    load_flat_doc,
    replay_chain,
    save_delta,
    save_doc,
    save_flat_doc,
)

from test_device_flat import oracle_from_patches, random_patches


def two_peer_doc(seed=3):
    rng = random.Random(seed)
    pa, _ = random_patches(rng, 40)
    pb, _ = random_patches(rng, 40)
    a = oracle_from_patches(pa, agent="peer-a")
    b = oracle_from_patches(pb, agent="peer-b")
    merge_into(a, b)
    return a


@pytest.fixture
def ckpt(tmp_path):
    doc = two_peer_doc()
    p = str(tmp_path / "doc.npz")
    save_doc(doc, p)
    return doc, p


class TestOracleCheckpointIntegrity:
    def test_valid_roundtrip_regression(self, ckpt):
        doc, p = ckpt
        back = load_doc(p)
        back.check()
        assert back.to_string() == doc.to_string()
        assert back.doc_spans() == doc.doc_spans()

    def test_truncations_refused(self, ckpt):
        _, p = ckpt
        raw = open(p, "rb").read()
        for frac in (0.0, 0.1, 0.5, 0.9, 0.999):
            open(p, "wb").write(raw[: int(len(raw) * frac)])
            with pytest.raises(CheckpointError):
                load_doc(p)

    def test_flipped_bytes_refused(self, ckpt):
        _, p = ckpt
        raw = open(p, "rb").read()
        rng = random.Random(0)
        offsets = set(range(64))                      # zip + meta headers
        offsets |= {rng.randrange(len(raw)) for _ in range(200)}
        for off in sorted(offsets):
            buf = bytearray(raw)
            buf[off] ^= 1 << rng.randrange(8)
            if bytes(buf) == raw:
                continue
            open(p, "wb").write(bytes(buf))
            try:
                back = load_doc(p)
            except CheckpointError:
                continue
            # A flip that numpy/zip tolerated (padding etc.) must still
            # have produced a bit-identical document, or it had to raise.
            ref = two_peer_doc()
            assert back.doc_spans() == ref.doc_spans(), (
                f"byte {off}: corrupted checkpoint loaded garbage")

    def test_wrong_format_version_refused(self, ckpt, tmp_path):
        _, p = ckpt
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        meta = _meta_from_array(arrays.pop("meta"))
        meta["version"] = FORMAT_VERSION + 7
        p2 = str(tmp_path / "future.npz")
        np.savez(p2, meta=_meta_to_array(meta), **arrays)
        with pytest.raises(CheckpointError, match="version"):
            load_doc(p2)

    def test_tampered_array_refused_by_content_crc(self, ckpt, tmp_path):
        """Rewrite one array (valid zip, valid meta) -> content CRC must
        catch it: zip-level CRCs alone would pass a re-zipped tamper."""
        _, p = ckpt
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        meta_arr = arrays.pop("meta")
        tampered = arrays["order"].copy()
        tampered[0] ^= 1
        arrays["order"] = tampered
        p2 = str(tmp_path / "tampered.npz")
        np.savez(p2, meta=meta_arr, **arrays)
        with pytest.raises(CheckpointError, match="CRC"):
            load_doc(p2)

    def test_not_a_zip_refused(self, tmp_path):
        p = str(tmp_path / "junk.npz")
        open(p, "wb").write(b"this is not a checkpoint at all")
        with pytest.raises(CheckpointError):
            load_doc(p)

    def test_missing_file_refused(self, tmp_path):
        with pytest.raises(CheckpointError):
            load_doc(str(tmp_path / "nope.npz"))

    def test_undecodable_meta_refused(self, ckpt, tmp_path):
        _, p = ckpt
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        arrays.pop("meta")
        p2 = str(tmp_path / "badmeta.npz")
        np.savez(p2, meta=np.frombuffer(b"{not json", dtype=np.uint8),
                 **arrays)
        with pytest.raises(CheckpointError, match="meta"):
            load_doc(p2)
        p3 = str(tmp_path / "nometa.npz")
        np.savez(p3, **arrays)
        with pytest.raises(CheckpointError, match="meta"):
            load_doc(p3)


class TestFlatCheckpointIntegrity:
    @pytest.fixture
    def flat_ckpt(self, tmp_path):
        from text_crdt_rust_tpu.ops import batch as B
        from text_crdt_rust_tpu.ops import flat as F
        from text_crdt_rust_tpu.ops import span_arrays as SA

        rng = random.Random(17)
        patches, content = random_patches(rng, 30)
        ops, _ = B.compile_local_patches(patches, lmax=4)
        doc = F.apply_ops(SA.make_flat_doc(256), ops)
        p = str(tmp_path / "flat.npz")
        save_flat_doc(doc, p)
        return content, p

    def test_roundtrip_then_truncation_refused(self, flat_ckpt):
        from text_crdt_rust_tpu.ops import span_arrays as SA

        content, p = flat_ckpt
        assert SA.to_string(load_flat_doc(p)) == content
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[: len(raw) // 2])
        with pytest.raises(CheckpointError):
            load_flat_doc(p)

    def test_kind_confusion_refused(self, flat_ckpt, tmp_path):
        _, p = flat_ckpt
        with pytest.raises(CheckpointError, match="kind"):
            load_doc(p)
        doc = two_peer_doc()
        p2 = str(tmp_path / "oracle.npz")
        save_doc(doc, p2)
        with pytest.raises(CheckpointError, match="kind"):
            load_flat_doc(p2)

    def test_flipped_bytes_refused(self, flat_ckpt):
        from text_crdt_rust_tpu.ops import span_arrays as SA

        content, p = flat_ckpt
        raw = open(p, "rb").read()
        rng = random.Random(1)
        for _ in range(80):
            off = rng.randrange(len(raw))
            buf = bytearray(raw)
            buf[off] ^= 1 << rng.randrange(8)
            if bytes(buf) == raw:
                continue
            open(p, "wb").write(bytes(buf))
            try:
                back = load_flat_doc(p)
            except CheckpointError:
                continue
            assert SA.to_string(back) == content, (
                f"byte {off}: corrupted flat checkpoint loaded garbage")


def _edit(doc, rng, k, agents=None):
    aids = agents or [doc.get_or_create_agent_id("peer-a")]
    for _ in range(k):
        aid = rng.choice(aids)
        n = len(doc)
        if n == 0 or rng.random() < 0.6:
            doc.local_insert(aid, rng.randint(0, n), "".join(
                rng.choice("abcdefgh") for _ in range(rng.randint(1, 4))))
        else:
            pos = rng.randint(0, n - 1)
            doc.local_delete(aid, pos, min(rng.randint(1, 3), n - pos))


class TestDeltaCheckpointChain:
    """ISSUE-7: incremental checkpoints — a warm save records only the
    ops since the referenced predecessor (columnar-encoded), the chain
    is CRC-linked end to end, and ANY broken link is a typed refusal."""

    def _chain(self, tmp_path, edits=(120, 40, 40), compact_ops=100000,
               compact_links=16, seed=3):
        rng = random.Random(seed)
        doc = ListCRDT()
        aids = [doc.get_or_create_agent_id(f"peer-{i}") for i in range(2)]
        chain = CheckpointChain(str(tmp_path / "doc"),
                                compact_ops=compact_ops,
                                compact_links=compact_links)
        infos = []
        for k in edits:
            _edit(doc, rng, k, aids)
            infos.append(chain.save(doc))
        return doc, chain, infos

    def test_delta_restore_identical_and_o_new_ops(self, tmp_path):
        from text_crdt_rust_tpu.models.sync import (
            export_txns_since,
            state_digest,
        )

        doc, chain, infos = self._chain(tmp_path)
        assert [i["kind"] for i in infos] == ["full", "delta", "delta"]
        # Warm saves scale with ops-since-last-save, not doc size.
        assert infos[1]["bytes"] < infos[0]["bytes"] / 3
        back = chain.load()
        back.check()
        assert back.to_string() == doc.to_string()
        assert back.doc_spans() == doc.doc_spans()
        assert state_digest(back) == state_digest(doc)
        assert export_txns_since(back, 0) == export_txns_since(doc, 0)

    def test_compaction_folds_chain(self, tmp_path):
        doc, chain, infos = self._chain(
            tmp_path, edits=(60,) + (20,) * 6, compact_links=3)
        kinds = [i["kind"] for i in infos]
        assert "delta" in kinds
        assert kinds.count("full") >= 2, "compaction never triggered"
        assert len(chain.links) < 3
        assert chain.load().to_string() == doc.to_string()

    def test_stale_base_refused(self, tmp_path):
        """The base file replaced by a DIFFERENT snapshot (even a valid
        one): every link names its predecessor's content CRC, so the
        load refuses instead of replaying onto the wrong state."""
        doc, chain, _ = self._chain(tmp_path)
        other = two_peer_doc(seed=99)
        save_doc(other, chain.base_path)
        with pytest.raises(CheckpointError, match="crc|chain|stale"):
            chain.load()

    def test_missing_base_and_missing_link_refused(self, tmp_path):
        import os

        doc, chain, _ = self._chain(tmp_path)
        link_path = chain.links[0]["path"]
        os.remove(chain.base_path)
        with pytest.raises(CheckpointError):
            chain.load()
        save_doc(doc, chain.base_path)  # base back, but now a link gone
        os.remove(link_path)
        with pytest.raises(CheckpointError):
            chain.load()

    def test_reordered_links_refused(self, tmp_path):
        doc, chain, _ = self._chain(tmp_path)
        paths = [link["path"] for link in chain.links]
        with pytest.raises(CheckpointError, match="chain|order|crc"):
            replay_chain(chain.base_path, list(reversed(paths)))

    def test_skipped_link_refused(self, tmp_path):
        doc, chain, _ = self._chain(tmp_path)
        with pytest.raises(CheckpointError, match="chain|order|crc|link"):
            replay_chain(chain.base_path, [chain.links[1]["path"]])

    def test_delta_truncation_and_bitflips_refused(self, tmp_path):
        doc, chain, _ = self._chain(tmp_path)
        p = chain.links[0]["path"]
        raw = open(p, "rb").read()
        for frac in (0.0, 0.3, 0.9, 0.999):
            open(p, "wb").write(raw[: int(len(raw) * frac)])
            with pytest.raises(CheckpointError):
                chain.load()
        rng = random.Random(2)
        for _ in range(60):
            off = rng.randrange(len(raw))
            buf = bytearray(raw)
            buf[off] ^= 1 << rng.randrange(8)
            if bytes(buf) == raw:
                continue
            open(p, "wb").write(bytes(buf))
            try:
                back = chain.load()
            except CheckpointError:
                continue
            assert back.doc_spans() == doc.doc_spans(), (
                f"byte {off}: corrupted delta replayed garbage")
        open(p, "wb").write(raw)
        assert chain.load().to_string() == doc.to_string()

    def test_corrupt_embedded_txn_stream_refused(self, tmp_path):
        """A zip/CRC-valid delta whose txns_blob is garbage: the wire
        decoder inside must reject typed (CheckpointError, not
        CodecError leaking through)."""
        import numpy as np

        doc, chain, _ = self._chain(tmp_path)
        p = chain.links[0]["path"]
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        meta = _meta_from_array(arrays.pop("meta"))
        blob = arrays["txns_blob"].copy()
        blob[len(blob) // 2] ^= 0xFF
        arrays["txns_blob"] = blob
        # Re-sign the content CRC so only the INNER wire CRC can catch it.
        from text_crdt_rust_tpu.utils.checkpoint import _save_npz

        meta.pop("crc")
        _save_npz(p, meta, arrays)
        with pytest.raises(CheckpointError, match="txn stream|corrupt"):
            chain.load()

    def test_version_mismatch_refused(self, tmp_path):
        import numpy as np

        doc, chain, _ = self._chain(tmp_path)
        p = chain.links[0]["path"]
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        meta = _meta_from_array(arrays.pop("meta"))
        meta["version"] = FORMAT_VERSION - 1
        np.savez(p, meta=_meta_to_array(meta), **arrays)
        with pytest.raises(CheckpointError, match="version"):
            load_delta(p)

    def test_delta_from_order_ahead_of_doc_refused(self, tmp_path):
        doc = two_peer_doc()
        with pytest.raises(CheckpointError, match="stale|ahead"):
            save_delta(doc, str(tmp_path / "d.npz"), base_crc=0,
                       prev_crc=0, from_order=doc.get_next_order() + 5)


class TestCheckpointUnderConcurrentTraffic:
    """ISSUE-3 satellite, at the utils/checkpoint + CausalBuffer level
    (no serve/ machinery): a doc checkpointed mid-stream while peers
    keep editing — their txns queue causally in a CausalBuffer — then
    restored and drained, must be bit-identical to an always-resident
    twin that applied the same stream without the round-trip."""

    def test_evicted_midstream_restores_bit_identical(self, tmp_path):
        from text_crdt_rust_tpu.models.sync import (
            agent_watermarks,
            export_txns_since,
            state_digest,
        )
        from text_crdt_rust_tpu.parallel.causal import CausalBuffer

        # Peer generates a delete-heavy stream, one txn chunk per edit.
        rng = random.Random(5)
        peer = ListCRDT()
        pa = peer.get_or_create_agent_id("peer")
        chunks, mark = [], 0
        for i in range(24):
            n = len(peer)
            if n == 0 or rng.random() < 0.6:
                peer.local_insert(pa, rng.randint(0, n), "ab")
            else:
                pos = rng.randint(0, n - 1)
                peer.local_delete(pa, pos, min(2, n - pos))
            chunks.append(export_txns_since(peer, mark))
            mark = peer.get_next_order()

        server = ListCRDT()
        twin = ListCRDT()
        buf = CausalBuffer()
        p = str(tmp_path / "evicted.npz")

        def deliver(doc, txns, buffer=None):
            if buffer is None:
                for t in txns:
                    doc.apply_remote_txn(t)
            else:
                for t in buffer.add_all(txns):
                    if doc is not None:
                        doc.apply_remote_txn(t)

        # First half applies live on both.
        for chunk in chunks[:12]:
            deliver(server, chunk, buf)
            deliver(twin, chunk)
        # Evict: serialize + drop; peers keep editing while out. The
        # buffer keeps accepting (watermarks survive the round-trip) but
        # releases accumulate unapplied.
        save_doc(server, p)
        server = None
        queued = []
        for chunk in chunks[12:]:
            for t in buf.add_all(chunk):
                queued.append(t)
            deliver(twin, chunk)
        assert queued, "nothing queued while evicted — test shape bug"
        # Restore + replay the queued releases.
        server = load_doc(p)
        server.check()
        deliver(server, queued)
        assert server.to_string() == twin.to_string()
        assert server.doc_spans() == twin.doc_spans()
        assert state_digest(server) == state_digest(twin)
        assert agent_watermarks(server) == agent_watermarks(twin)
        assert buf.pending == 0

    def test_delta_chain_restore_parity_with_full(self, tmp_path):
        """ISSUE-7: the same evict-midstream shape restored from a
        DELTA chain (base + two links) must land bit-identical to the
        full-checkpoint restore and the always-resident twin — with
        queued causal traffic replaying on top."""
        from text_crdt_rust_tpu.models.sync import (
            export_txns_since,
            state_digest,
        )
        from text_crdt_rust_tpu.parallel.causal import CausalBuffer

        rng = random.Random(8)
        peer = ListCRDT()
        pa = peer.get_or_create_agent_id("peer")
        chunks, mark = [], 0
        for i in range(30):
            n = len(peer)
            if n == 0 or rng.random() < 0.6:
                peer.local_insert(pa, rng.randint(0, n), "xy")
            else:
                pos = rng.randint(0, n - 1)
                peer.local_delete(pa, pos, min(2, n - pos))
            chunks.append(export_txns_since(peer, mark))
            mark = peer.get_next_order()

        server = ListCRDT()
        twin = ListCRDT()
        buf = CausalBuffer()
        chain = CheckpointChain(str(tmp_path / "doc"), compact_ops=100000)
        full_p = str(tmp_path / "full.npz")

        def feed(doc, txns):
            for t in txns:
                doc.apply_remote_txn(t)

        # Warm the chain: save, edit, save (base + delta), twice evicted.
        for lo, hi in ((0, 10), (10, 20)):
            for chunk in chunks[lo:hi]:
                feed(server, [t for t in buf.add_all(chunk)])
                feed(twin, chunk)
            chain.save(server)
        assert [bool(chain.links)] == [True]
        save_doc(server, full_p)
        server = None
        queued = []
        for chunk in chunks[20:]:
            queued.extend(buf.add_all(chunk))
            feed(twin, chunk)
        assert queued
        # Restore BOTH ways; replay the same queued traffic.
        via_chain = chain.load()
        via_full = load_doc(full_p)
        for doc in (via_chain, via_full):
            doc.check()
            feed(doc, queued)
        assert via_chain.to_string() == twin.to_string()
        assert via_chain.doc_spans() == via_full.doc_spans() \
            == twin.doc_spans()
        assert state_digest(via_chain) == state_digest(twin)
        assert export_txns_since(via_chain, 0) \
            == export_txns_since(via_full, 0)
        assert buf.pending == 0

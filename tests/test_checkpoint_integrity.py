"""Checkpoint corruption: truncated, bit-flipped, or version-mismatched
files must raise the typed ``CheckpointError`` — never crash with a
zip/json/numpy internals error, never load garbage (ISSUE 1 satellite).
"""
import json
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.models import ListCRDT
from text_crdt_rust_tpu.models.sync import merge_into
from text_crdt_rust_tpu.utils.checkpoint import (
    FORMAT_VERSION,
    CheckpointError,
    _meta_from_array,
    _meta_to_array,
    load_doc,
    load_flat_doc,
    save_doc,
    save_flat_doc,
)

from test_device_flat import oracle_from_patches, random_patches


def two_peer_doc(seed=3):
    rng = random.Random(seed)
    pa, _ = random_patches(rng, 40)
    pb, _ = random_patches(rng, 40)
    a = oracle_from_patches(pa, agent="peer-a")
    b = oracle_from_patches(pb, agent="peer-b")
    merge_into(a, b)
    return a


@pytest.fixture
def ckpt(tmp_path):
    doc = two_peer_doc()
    p = str(tmp_path / "doc.npz")
    save_doc(doc, p)
    return doc, p


class TestOracleCheckpointIntegrity:
    def test_valid_roundtrip_regression(self, ckpt):
        doc, p = ckpt
        back = load_doc(p)
        back.check()
        assert back.to_string() == doc.to_string()
        assert back.doc_spans() == doc.doc_spans()

    def test_truncations_refused(self, ckpt):
        _, p = ckpt
        raw = open(p, "rb").read()
        for frac in (0.0, 0.1, 0.5, 0.9, 0.999):
            open(p, "wb").write(raw[: int(len(raw) * frac)])
            with pytest.raises(CheckpointError):
                load_doc(p)

    def test_flipped_bytes_refused(self, ckpt):
        _, p = ckpt
        raw = open(p, "rb").read()
        rng = random.Random(0)
        offsets = set(range(64))                      # zip + meta headers
        offsets |= {rng.randrange(len(raw)) for _ in range(200)}
        for off in sorted(offsets):
            buf = bytearray(raw)
            buf[off] ^= 1 << rng.randrange(8)
            if bytes(buf) == raw:
                continue
            open(p, "wb").write(bytes(buf))
            try:
                back = load_doc(p)
            except CheckpointError:
                continue
            # A flip that numpy/zip tolerated (padding etc.) must still
            # have produced a bit-identical document, or it had to raise.
            ref = two_peer_doc()
            assert back.doc_spans() == ref.doc_spans(), (
                f"byte {off}: corrupted checkpoint loaded garbage")

    def test_wrong_format_version_refused(self, ckpt, tmp_path):
        _, p = ckpt
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        meta = _meta_from_array(arrays.pop("meta"))
        meta["version"] = FORMAT_VERSION + 7
        p2 = str(tmp_path / "future.npz")
        np.savez(p2, meta=_meta_to_array(meta), **arrays)
        with pytest.raises(CheckpointError, match="version"):
            load_doc(p2)

    def test_tampered_array_refused_by_content_crc(self, ckpt, tmp_path):
        """Rewrite one array (valid zip, valid meta) -> content CRC must
        catch it: zip-level CRCs alone would pass a re-zipped tamper."""
        _, p = ckpt
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        meta_arr = arrays.pop("meta")
        tampered = arrays["order"].copy()
        tampered[0] ^= 1
        arrays["order"] = tampered
        p2 = str(tmp_path / "tampered.npz")
        np.savez(p2, meta=meta_arr, **arrays)
        with pytest.raises(CheckpointError, match="CRC"):
            load_doc(p2)

    def test_not_a_zip_refused(self, tmp_path):
        p = str(tmp_path / "junk.npz")
        open(p, "wb").write(b"this is not a checkpoint at all")
        with pytest.raises(CheckpointError):
            load_doc(p)

    def test_missing_file_refused(self, tmp_path):
        with pytest.raises(CheckpointError):
            load_doc(str(tmp_path / "nope.npz"))

    def test_undecodable_meta_refused(self, ckpt, tmp_path):
        _, p = ckpt
        with np.load(p) as z:
            arrays = {k: z[k] for k in z.files}
        arrays.pop("meta")
        p2 = str(tmp_path / "badmeta.npz")
        np.savez(p2, meta=np.frombuffer(b"{not json", dtype=np.uint8),
                 **arrays)
        with pytest.raises(CheckpointError, match="meta"):
            load_doc(p2)
        p3 = str(tmp_path / "nometa.npz")
        np.savez(p3, **arrays)
        with pytest.raises(CheckpointError, match="meta"):
            load_doc(p3)


class TestFlatCheckpointIntegrity:
    @pytest.fixture
    def flat_ckpt(self, tmp_path):
        from text_crdt_rust_tpu.ops import batch as B
        from text_crdt_rust_tpu.ops import flat as F
        from text_crdt_rust_tpu.ops import span_arrays as SA

        rng = random.Random(17)
        patches, content = random_patches(rng, 30)
        ops, _ = B.compile_local_patches(patches, lmax=4)
        doc = F.apply_ops(SA.make_flat_doc(256), ops)
        p = str(tmp_path / "flat.npz")
        save_flat_doc(doc, p)
        return content, p

    def test_roundtrip_then_truncation_refused(self, flat_ckpt):
        from text_crdt_rust_tpu.ops import span_arrays as SA

        content, p = flat_ckpt
        assert SA.to_string(load_flat_doc(p)) == content
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[: len(raw) // 2])
        with pytest.raises(CheckpointError):
            load_flat_doc(p)

    def test_kind_confusion_refused(self, flat_ckpt, tmp_path):
        _, p = flat_ckpt
        with pytest.raises(CheckpointError, match="kind"):
            load_doc(p)
        doc = two_peer_doc()
        p2 = str(tmp_path / "oracle.npz")
        save_doc(doc, p2)
        with pytest.raises(CheckpointError, match="kind"):
            load_flat_doc(p2)

    def test_flipped_bytes_refused(self, flat_ckpt):
        from text_crdt_rust_tpu.ops import span_arrays as SA

        content, p = flat_ckpt
        raw = open(p, "rb").read()
        rng = random.Random(1)
        for _ in range(80):
            off = rng.randrange(len(raw))
            buf = bytearray(raw)
            buf[off] ^= 1 << rng.randrange(8)
            if bytes(buf) == raw:
                continue
            open(p, "wb").write(bytes(buf))
            try:
                back = load_flat_doc(p)
            except CheckpointError:
                continue
            assert SA.to_string(back) == content, (
                f"byte {off}: corrupted flat checkpoint loaded garbage")


class TestCheckpointUnderConcurrentTraffic:
    """ISSUE-3 satellite, at the utils/checkpoint + CausalBuffer level
    (no serve/ machinery): a doc checkpointed mid-stream while peers
    keep editing — their txns queue causally in a CausalBuffer — then
    restored and drained, must be bit-identical to an always-resident
    twin that applied the same stream without the round-trip."""

    def test_evicted_midstream_restores_bit_identical(self, tmp_path):
        from text_crdt_rust_tpu.models.sync import (
            agent_watermarks,
            export_txns_since,
            state_digest,
        )
        from text_crdt_rust_tpu.parallel.causal import CausalBuffer

        # Peer generates a delete-heavy stream, one txn chunk per edit.
        rng = random.Random(5)
        peer = ListCRDT()
        pa = peer.get_or_create_agent_id("peer")
        chunks, mark = [], 0
        for i in range(24):
            n = len(peer)
            if n == 0 or rng.random() < 0.6:
                peer.local_insert(pa, rng.randint(0, n), "ab")
            else:
                pos = rng.randint(0, n - 1)
                peer.local_delete(pa, pos, min(2, n - pos))
            chunks.append(export_txns_since(peer, mark))
            mark = peer.get_next_order()

        server = ListCRDT()
        twin = ListCRDT()
        buf = CausalBuffer()
        p = str(tmp_path / "evicted.npz")

        def deliver(doc, txns, buffer=None):
            if buffer is None:
                for t in txns:
                    doc.apply_remote_txn(t)
            else:
                for t in buffer.add_all(txns):
                    if doc is not None:
                        doc.apply_remote_txn(t)

        # First half applies live on both.
        for chunk in chunks[:12]:
            deliver(server, chunk, buf)
            deliver(twin, chunk)
        # Evict: serialize + drop; peers keep editing while out. The
        # buffer keeps accepting (watermarks survive the round-trip) but
        # releases accumulate unapplied.
        save_doc(server, p)
        server = None
        queued = []
        for chunk in chunks[12:]:
            for t in buf.add_all(chunk):
                queued.append(t)
            deliver(twin, chunk)
        assert queued, "nothing queued while evicted — test shape bug"
        # Restore + replay the queued releases.
        server = load_doc(p)
        server.check()
        deliver(server, queued)
        assert server.to_string() == twin.to_string()
        assert server.doc_spans() == twin.doc_spans()
        assert state_digest(server) == state_digest(twin)
        assert agent_watermarks(server) == agent_watermarks(twin)
        assert buf.pending == 0

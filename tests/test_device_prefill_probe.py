"""Tier-1 smoke for ``perf/device_prefill_probe.py`` (ISSUE 14
acceptance): the committed ``perf/device_prefill_r16.json`` is the full
200-doc run; this keeps the small-scale path green (sha256-identical
logical streams across all four {prefill mode} x {depth} arms, the
>= 20x prefill byte cut) so the JSON can't silently rot, and a
``slow``-tier run re-measures the committed claims at full scale.

Wall-based claims (the 5% regression bar) are asserted only against
the committed artifact and in the ``slow`` re-run — smoke walls on a
shared box are noise.
"""
import importlib.util
import json
import os

import pytest

PROBE = os.path.join("perf", "device_prefill_probe.py")
COMMITTED = os.path.join("perf", "device_prefill_r16.json")


def _load_probe():
    spec = importlib.util.spec_from_file_location("dpp", PROBE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_smoke_path_green():
    out = _load_probe().run_matrix(smoke=True, reps=1)
    acc = out["acceptance"]
    assert acc["streams_sha256_identical"], out["stream_sha256"]
    assert acc["logical_counters_identical"]
    # The byte cut is a logical (seed-deterministic) claim — gate it
    # at smoke scale too.
    assert acc["prefill_bytes_cut_x"] >= acc["bytes_cut_floor_x"]
    arms = out["arms"]
    assert arms["delta/depth2"]["device_prefill"]
    assert not arms["host/depth2"]["device_prefill"]
    assert arms["host/depth2"]["prefill_bytes_cut_x"] == 1.0
    assert arms["host/depth2"]["prefill_scatter_compiles"] == 0
    assert 1 <= arms["delta/depth2"]["prefill_scatter_compiles"] <= 12
    assert arms["delta/depth2"]["overlap_frac"] > 0.0
    assert arms["delta/depth1"]["overlap_frac"] == 0.0


def test_committed_device_prefill_json_claims():
    """The committed probe JSON's acceptance: all four arms
    sha256-identical, prefill bytes cut >= 20x at the 200-doc shape,
    delta-vs-host wall within the 5% bar at both depths."""
    with open(COMMITTED) as f:
        d = json.load(f)
    assert not d["smoke"], "committed JSON must be the full 200-doc run"
    assert d["workload"]["docs"] == 200
    acc = d["acceptance"]
    assert acc["pass"]
    assert acc["streams_sha256_identical"]
    assert len(set(d["stream_sha256"].values())) == 1
    assert acc["prefill_bytes_cut_x"] >= acc["bytes_cut_floor_x"]
    assert max(acc["wall_delta_pct"].values()) <= acc[
        "wall_regression_bar_pct"]
    # The shipped default (delta, depth 2) is the headline arm and its
    # byte economy matches the §19 cost model's shape: full-log bytes
    # are 2*4*OCAP*B*4 per shard-tick, scatter bytes are bucket-padded.
    arm = d["arms"]["delta/depth2"]
    assert arm["device_prefill"] and arm["pipeline_ticks"] == 2
    assert arm["prefill_bytes_full_per_tick"] == 2 * 4 * 1536 * 32 * 4
    assert arm["flow_audit_ok"]


@pytest.mark.slow
def test_probe_full_rerun_matches_committed_claims():
    out = _load_probe().run_matrix(smoke=False, reps=2)
    assert out["acceptance"]["pass"], out["acceptance"]

"""Test harness config: force an 8-device virtual CPU mesh.

The real benchmark path runs on the one attached TPU chip; tests validate
kernels and multi-chip sharding on a virtual CPU mesh exactly the way the
driver's ``dryrun_multichip`` does (see ``__graft_entry__.py``).

NOTE this environment pre-registers the TPU platform from sitecustomize at
interpreter startup (so ``JAX_PLATFORMS`` env is already consumed by the
time conftest runs); the supported override is
``jax.config.update("jax_platforms", ...)``, plus ``XLA_FLAGS`` for the
host-device count, which is read lazily when the CPU client is first
created.
"""
import glob
import os
import sys
import time

import pytest

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Tests are compile-dominated on the 1-core CI box (hundreds of distinct
# jitted programs, each compiled serially); backend optimization buys
# nothing for correctness — the kernels are exact integer ops and every
# suite pins bit-identity against the host oracle — so run the XLA
# backend at optimization level 0 here.  Measured ~27% off the tier-1
# wall (the 870s gate timeout had < 2% headroom).  Perf probes and
# bench.py do NOT inherit this: it is test-harness-only by construction
# (conftest), so recorded walls stay honest.
if "xla_backend_optimization_level" not in _flags:
    _flags = (_flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = _flags

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# --- tier-1 wall-time budget guard (ISSUE 3 satellite) -----------------------
# The tier-1 command runs under a 870s timeout (ROADMAP); when the suite
# creeps past ~720s the gate starts flaking on slow boxes before anyone
# notices a test belongs in `slow`.  The guard measures every `-m "not
# slow"` run and either warns LOUDLY (default) or fails the session
# (TCR_TIER1_BUDGET_FAIL=1).  Budget override: TCR_TIER1_BUDGET_S.

_TIER1_BUDGET_S = float(os.environ.get("TCR_TIER1_BUDGET_S", "720"))
_SESSION_T0 = time.time()


def _is_tier1(config) -> bool:
    return "not slow" in (config.getoption("-m") or "")


def _slowest_calls(terminalreporter, n: int = 15):
    """The session's ``n`` slowest test call phases, from the reports
    the terminal reporter already holds — so the budget warning can
    NAME the tests to demote instead of sending someone off to re-run
    with ``--durations``."""
    calls = []
    for reports in terminalreporter.stats.values():
        for rep in reports:
            if (getattr(rep, "when", None) == "call"
                    and hasattr(rep, "duration")):
                calls.append((rep.duration, rep.nodeid))
    calls.sort(reverse=True)
    return calls[:n]


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    wall = time.time() - _SESSION_T0
    if not _is_tier1(config):
        return
    tr = terminalreporter
    if wall <= _TIER1_BUDGET_S:
        tr.write_line(
            f"tier-1 wall time {wall:.0f}s (budget {_TIER1_BUDGET_S:.0f}s)")
        return
    tr.write_sep("=", "TIER-1 WALL-TIME BUDGET EXCEEDED")
    tr.write_line(
        f"tier-1 ('-m \"not slow\"') took {wall:.0f}s — over the "
        f"{_TIER1_BUDGET_S:.0f}s budget of the 870s gate timeout.\n"
        f"Move the heaviest new tests to the `slow` tier (pytest.ini) "
        f"before the tier-1 command starts flaking.  Set "
        f"TCR_TIER1_BUDGET_FAIL=1 to make this a hard failure, "
        f"TCR_TIER1_BUDGET_S to adjust the budget.", red=True, bold=True)
    slowest = _slowest_calls(terminalreporter)
    if slowest:
        tr.write_line("slowest 15 call phases (demotion candidates):",
                      bold=True)
        for dur, nodeid in slowest:
            tr.write_line(f"  {dur:7.2f}s  {nodeid}")


def pytest_sessionfinish(session, exitstatus):
    wall = time.time() - _SESSION_T0
    if (_is_tier1(session.config) and wall > _TIER1_BUDGET_S
            and os.environ.get("TCR_TIER1_BUDGET_FAIL")):
        session.exitstatus = 3  # pytest's "internal error"-class exit:
        #                         loud and unambiguous in CI logs


# --- flight-recorder attach on serve-test failures (ISSUE 8 satellite) ------
# With TCR_TRACE_DIR set, every DocServer built during the run writes
# its post-mortem bundles there (serve/server.py reads the env as the
# obs_dir default).  Any failing tests/test_serve_* test then gets the
# bundle paths attached to its pytest report section, so a tier-1
# failure ships its own post-mortem instead of just an assert message:
#
#     TCR_TRACE_DIR=/tmp/tcr_obs pytest tests/ -m 'not slow'


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    tdir = os.environ.get("TCR_TRACE_DIR")
    if not (tdir and rep.failed
            and os.path.basename(str(item.fspath)).startswith(
                ("test_serve_", "test_obs_"))):
        return
    # Only bundles written DURING this session: the dir is long-lived
    # and stale bundles from a previous run would mislead the triage.
    bundles = sorted(
        p for p in glob.glob(os.path.join(tdir, "**", "bundle_*.json"),
                             recursive=True)
        if os.path.getmtime(p) >= _SESSION_T0)
    rep.sections.append((
        "flight-recorder (TCR_TRACE_DIR)",
        "\n".join(bundles) if bundles
        else f"no post-mortem bundles under {tdir} from this session"))


def pytest_collection_modifyitems(config, items):
    """Deselect ``archival`` suites (superseded-engine differential
    references) unless the -m expression names them explicitly.  A
    collection hook instead of an ``addopts -m`` default: a user-passed
    ``-m slow`` would silently REPLACE the addopts expression and
    re-admit the archival suites (review r5)."""
    expr = config.getoption("-m") or ""
    if "archival" in expr:
        return
    keep, drop = [], []
    for item in items:
        (drop if "archival" in item.keywords else keep).append(item)
    if drop:
        config.hook.pytest_deselected(items=drop)
        items[:] = keep

"""Test harness config: force an 8-device virtual CPU mesh before JAX loads.

The real benchmark path runs on the one attached TPU chip; tests validate
multi-chip sharding on a virtual CPU mesh exactly the way the driver's
``dryrun_multichip`` does (see ``__graft_entry__.py``).
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Test harness config: force an 8-device virtual CPU mesh.

The real benchmark path runs on the one attached TPU chip; tests validate
kernels and multi-chip sharding on a virtual CPU mesh exactly the way the
driver's ``dryrun_multichip`` does (see ``__graft_entry__.py``).

NOTE this environment pre-registers the TPU platform from sitecustomize at
interpreter startup (so ``JAX_PLATFORMS`` env is already consumed by the
time conftest runs); the supported override is
``jax.config.update("jax_platforms", ...)``, plus ``XLA_FLAGS`` for the
host-device count, which is read lazily when the CPU client is first
created.
"""
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_collection_modifyitems(config, items):
    """Deselect ``archival`` suites (superseded-engine differential
    references) unless the -m expression names them explicitly.  A
    collection hook instead of an ``addopts -m`` default: a user-passed
    ``-m slow`` would silently REPLACE the addopts expression and
    re-admit the archival suites (review r5)."""
    expr = config.getoption("-m") or ""
    if "archival" in expr:
        return
    keep, drop = [], []
    for item in items:
        (drop if "archival" in item.keywords else keep).append(item)
    if drop:
        config.hook.pytest_deselected(items=drop)
        items[:] = keep

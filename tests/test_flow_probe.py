"""Tier-1 smoke for ``perf/flow_probe.py`` (ISSUE 11 acceptance): the
committed ``perf/flow_r13.json`` is produced by the probe's full
200-doc path; this keeps the small-scale path green (audit green at
full sampling, flow stream byte-identical, all arms converged) so the
JSON can't silently rot, and a ``slow``-tier run re-measures the
committed claims at full scale."""
import importlib.util
import json
import os

import pytest

PROBE = os.path.join("perf", "flow_probe.py")
COMMITTED = os.path.join("perf", "flow_r13.json")


def _load_probe():
    spec = importlib.util.spec_from_file_location("fp", PROBE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_smoke_path_green():
    out = _load_probe().run_matrix(smoke=True, reps=1)
    assert all(out["converged"].values())
    assert out["audit"]["full"]["ok"], out["audit"]["full"]["findings"]
    assert out["audit"]["full"]["spans"]["in_flight"] == 0
    assert out["audit"]["full"]["duplicates"] == 0
    assert out["trace_byte_identical_across_runs"]
    assert out["flow_events_full"] > out["flow_events_default"] > 0
    assert out["acceptance"]["floor_pct"] == 5.0


def test_committed_flow_json_claims():
    """The committed probe JSON's acceptance claims: conservation audit
    green over every span of the faulted 200-doc run (zero leaked /
    double-applied), full-flow streams byte-identical, default-sampling
    overhead under the §14 5% bar.  Structural re-validation is tier-1
    cheap; the full re-measurement is the probe CLI itself."""
    with open(COMMITTED) as f:
        d = json.load(f)
    assert not d["smoke"], "committed JSON must be the full 200-doc run"
    assert d["workload"]["docs"] == 200
    assert d["acceptance"]["pass"]
    assert d["audit"]["full"]["ok"]
    assert d["audit"]["full"]["spans"]["in_flight"] == 0
    assert d["audit"]["full"]["spans"]["emitted"] > 2000
    assert d["audit"]["full"]["duplicates"] == 0
    assert d["audit"]["full"]["leaks"] == 0
    assert d["overhead_pct"]["default"] < d["acceptance"]["floor_pct"]
    assert d["trace_byte_identical_across_runs"]
    assert all(d["converged"].values())
    # The age distribution is populated per band and fault class.
    assert d["ages_ticks"]["count"] == d["audit"]["full"]["spans"][
        "applied"]
    assert sum(v["count"] for v in d["age_by_class"].values()) == \
        d["ages_ticks"]["count"]
    assert sum(v["count"] for v in d["age_by_band"].values()) == \
        d["ages_ticks"]["count"]


@pytest.mark.slow
def test_probe_full_rerun_matches_committed_claims():
    """Re-measure at full scale (slow tier): the acceptance must
    reproduce on the current code, not just parse."""
    out = _load_probe().run_matrix(smoke=False, reps=2)
    assert out["acceptance"]["pass"], out

"""Committed-claims + smoke coverage for perf/lint_sanitize_probe.py.

Tier-1 keeps two cheap guarantees: the committed r15 JSON still claims
what PERF.md §18 cites (no silent drift between the doc and the
artifact), and the probe module itself still runs end to end at a tiny
shape.  The full 200-doc re-measure lives in ``slow``.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED = os.path.join(REPO, "perf", "lint_sanitize_r15.json")


def test_committed_probe_claims_hold():
    with open(COMMITTED) as f:
        r = json.load(f)
    assert r["ok"] is True
    assert r["claims"] == {
        "lint_gate_clean": True,
        "lint_under_10s": True,
        "sanitizer_under_5pct": True,
        "logical_stream_byte_identical": True,
    }
    assert r["byte_identical"] is True
    assert r["shape"]["docs"] == 200 and r["shape"]["ticks"] == 60
    assert r["sanitize_on"]["sanitize_checks"] > 0
    assert r["sanitize_off"]["sanitize_checks"] == 0
    assert r["sanitize_overhead_frac"] < 0.05
    assert r["lint"]["wall_s"] < 10.0 and r["lint"]["findings"] == 0


def test_probe_smoke_tiny_shape(tmp_path):
    out = tmp_path / "smoke.json"
    r = subprocess.run(
        [sys.executable, "perf/lint_sanitize_probe.py", "--docs", "6",
         "--ticks", "6", "--reps", "1", "--out", str(out)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-2000:])
    smoke = json.loads(out.read_text())
    assert smoke["byte_identical"] is True
    assert smoke["claims"]["lint_gate_clean"] is True


@pytest.mark.slow
def test_probe_full_shape_remeasure(tmp_path):
    out = tmp_path / "full.json"
    r = subprocess.run(
        [sys.executable, "perf/lint_sanitize_probe.py",
         "--out", str(out)],
        capture_output=True, text=True, timeout=1800, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-2000:])
    full = json.loads(out.read_text())
    assert full["ok"] is True

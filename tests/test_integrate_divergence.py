"""Pin the deliberate integrate deviation from the reference.

`/root/reference/src/list/doc.rs:214-215` re-pins ``scan_start = cursor``
on *every* scanning iteration of the YATA conflict walk. Yjs's
``Item.integrate`` keeps the insert-before point pinned at the FIRST
conflicting item unless the name tiebreak says "we go after" — and the
re-pinning rule is not convergent. All engines in this repo pin
``scan_start`` only on the false→true ``scanning`` transition
(``models/oracle.py:235-237``, ``native/tcr_engine.cpp``,
``ops/flat.py:109``).

The counterexample (the one claimed in the round-1 code comment, now
executable): three peers build a chain of items that all have
``origin_left == ROOT`` — D types "D" into the empty doc (origins
(ROOT, ROOT)), E inserts "E" at position 0 ((ROOT, D)), F inserts "F" at
position 0 ((ROOT, E)) — and a fourth peer A, whose name sorts *lowest*,
concurrently types "A" into the empty doc ((ROOT, ROOT)).

Integrating A last walks: F → eq-cursor conflict, A < F, different
origin_right ⇒ scanning, scan_start=0; E → same ⇒ reference re-pins
scan_start=1; D → same origin_right (ROOT) ⇒ break. Reference rule
inserts at 1 → "FAED". But with the other arrival order (D, A, E, F)
every rule gives "AFED" — so re-pinning does not converge. The pinned
rule inserts at 0 → "AFED" both ways.
"""
import pytest

from text_crdt_rust_tpu.common import (
    ROOT_ORDER,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from text_crdt_rust_tpu.models.native import NativeListCRDT
from text_crdt_rust_tpu.models.oracle import ListCRDT

ROOT = RemoteId("ROOT", ROOT_ORDER)


def _simulate(arrivals, repin: bool) -> str:
    """Minimal YATA integrate over (name, char, left, right) items, with
    the reference's re-pinning rule (``repin=True``, `doc.rs:183-222`) or
    the pinned fix. Origins name items by their char ('' = ROOT). The doc
    is a list of items; cursors are list indices."""
    doc = []  # (name, char, left, right)

    def cursor_after(origin_char):
        if origin_char == "":
            return 0
        return next(i for i, it in enumerate(doc) if it[1] == origin_char) + 1

    for item in arrivals:
        name, char, left, right = item
        cursor = cursor_after(left)
        left_cursor = cursor
        scan_start = cursor
        scanning = False
        while cursor < len(doc):
            o_name, o_char, o_left, o_right = doc[cursor]
            if o_char == right:
                break
            olc = cursor_after(o_left)
            if olc < left_cursor:
                break
            if olc == left_cursor:
                if name > o_name:
                    scanning = False
                elif right == o_right:
                    break
                else:
                    if repin or not scanning:
                        scan_start = cursor
                    scanning = True
            cursor += 1
        if scanning:
            cursor = scan_start
        doc.insert(cursor, item)
    return "".join(it[1] for it in doc)


# The four concurrent items of the counterexample. Causal deps: E after D,
# F after E; A independent.
ITEM_D = ("dan", "D", "", "")
ITEM_E = ("eve", "E", "", "D")
ITEM_F = ("fred", "F", "", "E")
ITEM_A = ("amy", "A", "", "")

ORDER_1 = [ITEM_D, ITEM_E, ITEM_F, ITEM_A]   # A integrates into the chain
ORDER_2 = [ITEM_D, ITEM_A, ITEM_E, ITEM_F]   # A arrives early


class TestScanStartRule:
    def test_reference_rule_not_convergent(self):
        # The reference's re-pinning rule gives different documents for the
        # two (both causally valid) arrival orders.
        got_1 = _simulate(ORDER_1, repin=True)
        got_2 = _simulate(ORDER_2, repin=True)
        assert got_1 == "FAED"
        assert got_2 == "AFED"
        assert got_1 != got_2   # the divergence this repo fixes

    def test_pinned_rule_convergent(self):
        assert _simulate(ORDER_1, repin=False) == "AFED"
        assert _simulate(ORDER_2, repin=False) == "AFED"


def _txns():
    return {
        "D": RemoteTxn(id=RemoteId("dan", 0), parents=[],
                       ops=[RemoteIns(ROOT, ROOT, "D")]),
        "E": RemoteTxn(id=RemoteId("eve", 0), parents=[RemoteId("dan", 0)],
                       ops=[RemoteIns(ROOT, RemoteId("dan", 0), "E")]),
        "F": RemoteTxn(id=RemoteId("fred", 0), parents=[RemoteId("eve", 0)],
                       ops=[RemoteIns(ROOT, RemoteId("eve", 0), "F")]),
        "A": RemoteTxn(id=RemoteId("amy", 0), parents=[],
                       ops=[RemoteIns(ROOT, ROOT, "A")]),
    }


ARRIVALS = [list("DEFA"), list("DAEF"), list("ADEF"), list("DEAF")]


class TestEnginesConverge:
    @pytest.mark.parametrize("engine", ["oracle", "native"])
    def test_all_arrival_orders_converge(self, engine):
        results = []
        for order in ARRIVALS:
            txns = _txns()
            doc = ListCRDT() if engine == "oracle" else NativeListCRDT()
            for key in order:
                doc.apply_remote_txn(txns[key])
            results.append(doc.to_string())
        assert all(r == "AFED" for r in results), results

    def test_flat_engine_converges(self):
        from text_crdt_rust_tpu.ops import batch as B
        from text_crdt_rust_tpu.ops import flat as F
        from text_crdt_rust_tpu.ops import span_arrays as SA

        for order in ARRIVALS:
            txns = _txns()
            table = B.AgentTable(["dan", "eve", "fred", "amy"])
            ops, _ = B.compile_remote_txns(
                [txns[k] for k in order], table, lmax=4)
            doc = F.apply_ops(SA.make_flat_doc(64), ops)
            assert SA.to_string(doc) == "AFED"

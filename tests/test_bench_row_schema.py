"""Bench-row exporter schema (ISSUE 8 satellite).

Every non-error row in the committed ``BENCH_ALL.json`` must validate
against ``bench.ROW_SCHEMA`` — the shared floor that keeps rows
comparable across re-records — and the write paths (``RowSink.add``,
``merge_config_rows``) must refuse shape-drifted rows instead of
silently splitting the table into incomparable halves."""
import json
import os

import pytest

from bench import (
    ROW_SCHEMA,
    ROW_SCHEMA_VERSION,
    merge_config_rows,
    validate_row,
)
from text_crdt_rust_tpu.obs.ledger import LEDGER_SCHEMA_VERSION


def row(**kw):
    """A schema-complete exporter row with overrides (the
    ``test_bench_rowsink.row`` fixture; tests/ is not a package, so the
    helper is duplicated rather than imported)."""
    r = {"schema_version": ROW_SCHEMA_VERSION,
         "ledger_version": LEDGER_SCHEMA_VERSION, "config": "cfg",
         "engine": "rle", "metric": "crdt_ops_per_sec_chip",
         "value": 1.0, "unit": "ops/s", "batch": 1, "ops": 1,
         "device_steps": 1, "mean_step_latency_us": 1.0,
         "hbm_bytes_accounted": 0, "hbm_bytes_measured": None,
         "vs_baseline": None, "baseline_ops_per_sec": None,
         "oracle_equal": True, "cfg_key": "k", "variant": "v"}
    r.update(kw)
    return r


def test_committed_bench_all_rows_validate():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_ALL.json")
    with open(path) as f:
        rows = json.load(f)
    assert rows, "committed BENCH_ALL.json is empty"
    for r in rows:
        validate_row(r)  # raises with the offending fields named
        if "error" not in r:
            assert r["schema_version"] == ROW_SCHEMA_VERSION


def test_additive_flow_fields_validate_without_schema_bump():
    """ISSUE 11 satellite: the serve/serve-lanes rows' flow_* fields
    (spans tracked, audit verdict, age percentiles in ticks) are
    ADDITIVE — the schema pins the floor, not the ceiling, so no
    row-schema major bump and old rows stay comparable."""
    extra = row(flow_spans=2880, flow_audit_ok=True,
                flow_age_p50_ticks=8, flow_age_p99_ticks=25)
    validate_row(extra)  # would raise on any floor violation
    assert extra["schema_version"] == ROW_SCHEMA_VERSION


def test_validate_rejects_missing_field():
    bad = row()
    del bad["metric"]
    with pytest.raises(ValueError, match="missing field 'metric'"):
        validate_row(bad)


def test_validate_rejects_type_drift():
    with pytest.raises(ValueError, match="'device_steps' has type str"):
        validate_row(row(device_steps="8"))


def test_validate_rejects_version_drift():
    with pytest.raises(ValueError, match="schema_version"):
        validate_row(row(schema_version=ROW_SCHEMA_VERSION + 1))


def test_validate_exempts_error_rows():
    validate_row({"config": "c", "error": "boom"})  # no raise


def test_schema_floor_matches_make_row():
    """Every required field is one ``bench.make_row`` emits — the
    schema can't demand what the exporter doesn't produce."""
    import inspect

    import bench

    src = inspect.getsource(bench.make_row)
    for field in ROW_SCHEMA:
        if field in ("cfg_key", "variant"):  # stamped by the sinks
            continue
        assert f'"{field}"' in src, (
            f"ROW_SCHEMA requires {field!r} but make_row never emits it")


def test_rows_carry_and_enforce_ledger_version(tmp_path):
    """ISSUE 10 satellite: rows are stamped with the cost-ledger schema
    they were recorded against, and ``--merge-rows`` refuses rows from
    a drifted ledger schema (their counters no longer mean what the
    committed ledger's do)."""
    validate_row(row())  # current stamp passes
    with pytest.raises(ValueError, match="ledger_version"):
        validate_row(row(ledger_version=LEDGER_SCHEMA_VERSION + 1))
    with pytest.raises(ValueError, match="missing field 'ledger_version'"):
        bad = row()
        del bad["ledger_version"]
        validate_row(bad)
    p = str(tmp_path / "all.json")
    with pytest.raises(ValueError, match="drifted cost-ledger schema"):
        merge_config_rows(
            p, "kevin", [row(ledger_version=LEDGER_SCHEMA_VERSION + 1)],
            "v")
    assert not os.path.exists(p)  # nothing written


def test_merge_rows_refuses_shape_drifted_rows(tmp_path):
    """The ISSUE-8 gate: ``--merge-rows`` must not merge a row that
    dropped schema fields (the silent-drift failure mode)."""
    p = str(tmp_path / "all.json")
    drifted = row(value=9)
    del drifted["device_steps"]
    with pytest.raises(ValueError, match="device_steps"):
        merge_config_rows(p, "kevin", [drifted], "v")
    assert not os.path.exists(p)  # nothing written

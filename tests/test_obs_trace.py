"""obs/trace + obs/registry (ISSUE 8): schema-checked events, the
same-seed byte-identity determinism guard, bounded histograms, and the
registry exporters.

The determinism guard is the load-bearing test: the serve twin-check's
cross-backend bit-identity proof relies on traffic generation being
server-state-independent, and the logical trace is now the most
sensitive detector of a violation — ANY nondeterminism (dict-order
drift, wall-clock leak into logical fields, backend-dependent event
timing) flips a byte.
"""
import json

import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.obs.registry import Histogram, MetricsRegistry, observe
from text_crdt_rust_tpu.obs.trace import (
    EVENT_SCHEMA,
    TRACE_SCHEMA_VERSION,
    WALL_KEY,
    Tracer,
    event_line,
    validate_event,
)
from text_crdt_rust_tpu.utils.metrics import Counters


def small_loadgen_run(seed=7, **cfg_kw):
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    cfg = ServeConfig(num_shards=1, lanes_per_shard=4, trace_keep=True,
                      **cfg_kw)
    gen = ServeLoadGen(docs=6, agents_per_doc=2, ticks=6,
                       events_per_tick=12, fault_rate=0.10, seed=seed,
                       cfg=cfg)
    rep = gen.run()
    assert rep["converged"], rep["mismatches"]
    return gen, rep


# ---------------------------------------------------------------- tracer --


def test_every_emitted_kind_is_schema_valid():
    """A full loadgen run emits only schema-valid events, the stream
    opens with the versioned header, and wall data stays under the
    reserved key."""
    gen, rep = small_loadgen_run()
    events = gen.server.tracer.events
    assert events[0]["k"] == "trace.header"
    assert events[0]["schema"] == TRACE_SCHEMA_VERSION
    kinds = {e["k"] for e in events}
    # The serving loop's core phases all show up in a faulted run.
    assert {"apply", "tick.drain", "tick.device", "tick.barrier",
            "device.compile", "codec.reject",
            "residency.evict", "residency.restore"} <= kinds
    for ev in events:
        validate_event(ev)  # would raise on any drift


def test_validate_event_refuses_drift():
    with pytest.raises(ValueError, match="unknown trace event kind"):
        validate_event({"i": 0, "t": 0, "k": "nonsense.kind"})
    with pytest.raises(ValueError, match="missing fields"):
        validate_event({"i": 0, "t": 0, "k": "apply", "doc": "d"})
    with pytest.raises(ValueError, match="missing envelope"):
        validate_event({"k": "trace.header", "schema": 1})


def test_same_seed_runs_emit_byte_identical_logical_traces():
    """THE determinism guard (ISSUE 8 satellite): two same-seed loadgen
    runs produce byte-identical logical JSONL streams once wall-clock
    fields are stripped — protecting the serve-loadgen determinism
    invariant the twin check depends on."""
    a, _ = small_loadgen_run()
    b, _ = small_loadgen_run()
    ba = a.server.tracer.logical_bytes()
    bb = b.server.tracer.logical_bytes()
    assert ba == bb
    # And the streams are non-trivial: applies, device passes, faults.
    assert a.server.tracer.seq > 50
    # Wall fields existed and were segregated, not absent.
    assert any(WALL_KEY in e for e in a.server.tracer.events)


def test_wall_fields_are_stripped_only_from_logical_lines():
    tr = Tracer(ring=8)
    ev = tr.event("tick.barrier", shard=0, wall={"ms": 1.25})
    full = event_line(ev)
    logical = event_line(ev, logical_only=True)
    assert '"w"' in full and '"ms"' in full
    assert '"w"' not in logical
    assert json.loads(logical)["shard"] == 0


def test_tracer_ring_is_bounded_and_filters():
    tr = Tracer(ring=16)
    for i in range(100):
        tr.event("apply", doc=f"d{i % 2}", ev="local", agent="a",
                 seq=i, n=1)
    assert len(tr.ring) == 16
    only_d1 = tr.last(8, doc="d1")
    assert only_d1 and all(e["doc"] == "d1" for e in only_d1)
    assert [e["i"] for e in only_d1] == sorted(e["i"] for e in only_d1)


def test_disabled_tracer_is_a_noop():
    tr = Tracer(enabled=False)
    assert tr.event("apply", doc="d", ev="local", agent="a",
                    seq=0, n=1) is None
    assert tr.seq == 0 and len(tr.ring) == 0


def test_trace_path_streams_jsonl(tmp_path):
    p = str(tmp_path / "t.jsonl")
    tr = Tracer(ring=8, path=p)
    tr.event("resync.round", wants=2)
    tr.close()
    lines = open(p).read().splitlines()
    assert len(lines) == 2  # header + event
    assert json.loads(lines[0])["schema"] == TRACE_SCHEMA_VERSION
    assert json.loads(lines[1])["wants"] == 2
    assert tr.segment_paths == [p]  # no rotation cap -> one segment


def test_stream_rotation_preserves_logical_byte_identity(tmp_path):
    """ISSUE 10 satellite: size-capped segment rollover — the
    concatenated segments must be BYTE-IDENTICAL (logical projection)
    to an unrotated same-event stream, and every segment except the
    last must respect the cap's between-events granularity (rotation
    never splits a line)."""
    p = str(tmp_path / "rot.jsonl")
    rot = Tracer(ring=8, path=p, rotate_bytes=400, keep_all=True)
    plain = Tracer(ring=8, keep_all=True)
    for tr in (rot, plain):
        for i in range(40):
            tr.set_tick(i // 4)
            tr.event("apply", doc=f"d{i % 3}", ev="local", agent="a",
                     seq=i, n=1, wall={"ms": float(i)})
    rot.close()
    assert len(rot.segment_paths) > 2  # the cap actually rotated
    assert rot.segment_paths[0] == p
    assert rot.segment_paths[1] == p + ".1"
    # Concatenated segments == the unrotated stream, byte for byte.
    concat = b"".join(open(s, "rb").read() for s in rot.segment_paths)
    lines = concat.decode().splitlines()
    assert [json.loads(ln) for ln in lines] == rot.events
    # Logical projection across the rollover boundary matches the
    # in-memory logical stream exactly.
    logical = "\n".join(
        event_line(ev, logical_only=True)
        for ev in (json.loads(ln) for ln in lines)) + "\n"
    assert logical.encode() == plain.logical_bytes()
    # Every non-final segment closed at/after the cap, never mid-line.
    import os
    for seg in rot.segment_paths[:-1]:
        assert os.path.getsize(seg) >= 400
        assert open(seg, "rb").read().endswith(b"\n")


def test_loadgen_rotated_segments_reload_via_analyze(tmp_path):
    """End to end: a rotated server trace reloads through
    ``obs.analyze.load_events`` as one stream, identical to the
    tracer's retained events."""
    from text_crdt_rust_tpu.obs import analyze as A

    p = str(tmp_path / "t.jsonl")
    gen, _rep = small_loadgen_run(trace_path=p, trace_rotate_bytes=2048)
    segs = gen.server.tracer.segment_paths
    assert len(segs) > 1
    events = A.load_events(segs)
    assert events == gen.server.tracer.events


# -------------------------------------------------------------- registry --


def test_histogram_bounded_decimation_is_deterministic():
    h = Histogram(cap=64)
    for v in range(1000):
        h.add(v)
    assert h.count == 1000 and len(h.samples) <= 64
    assert h.vmin == 0 and h.vmax == 999
    # Deterministic: a second identical series decimates identically.
    h2 = Histogram(cap=64)
    for v in range(1000):
        h2.add(v)
    assert h.samples == h2.samples
    # The subsample spans the series (not prefix-biased): p50 near 500.
    assert 300 <= h.quantiles()["p50"] <= 700


def test_registry_summary_and_exporters():
    reg = MetricsRegistry()
    reg.incr("frames", 3)
    reg.hiwater("queue_hw", 7)
    reg.gauge("docs_resident", 12)
    reg.sample("fill", 0.5)
    reg.sample("fill", 1.5)
    for v in (1.0, 2.0, 10.0):
        reg.histo("tick_ms", v)
    s = reg.summary()
    assert s["frames"] == 3 and s["queue_hw"] == 7
    assert s["docs_resident"] == 12
    assert s["fill_mean"] == 1.0 and s["fill_min"] == 0.5 \
        and s["fill_max"] == 1.5
    assert s["tick_ms_count"] == 3 and s["tick_ms_max"] == 10.0
    assert s["tick_ms_p50"] == 2.0

    jl = reg.to_jsonl().splitlines()
    head = json.loads(jl[0])
    assert head["meta"] == "metrics" and head["schema"] == 1
    by_name = {json.loads(ln)["name"]: json.loads(ln) for ln in jl[1:]}
    assert by_name["frames"]["type"] == "counter"
    assert by_name["tick_ms"]["type"] == "histogram"
    assert by_name["fill"]["min"] == 0.5

    prom = reg.prometheus_text()
    assert "# TYPE tcr_frames counter" in prom
    assert 'tcr_tick_ms{quantile="0.5"} 2.0' in prom
    assert "tcr_tick_ms_count 3" in prom


def test_observe_falls_back_to_sample_on_plain_counters():
    c = Counters()
    observe(c, "x", 2.0)
    observe(c, "x", 4.0)
    s = c.summary()
    assert s["x_mean"] == 3.0 and s["x_min"] == 2.0 and s["x_max"] == 4.0
    reg = MetricsRegistry()
    observe(reg, "x", 2.0)
    assert reg.histogram("x").count == 1


def test_prometheus_text_conformance_edge_cases():
    """ISSUE 10 satellite: names sanitize (incl. the leading-digit
    rule), label values escape, every metric gets one # HELP/# TYPE
    pair, and sanitize collisions don't emit duplicate TYPE lines."""
    from text_crdt_rust_tpu.obs.registry import (
        prom_escape_label,
        prom_name,
    )

    reg = MetricsRegistry()
    reg.incr("weird metric-name.v2", 5)    # spaces/dash/dot -> _
    reg.incr("weird_metric_name_v2", 7)    # collides post-sanitize
    reg.gauge("9starts_with_digit", 1.5)
    reg.histo("tick ms", 2.0)
    text = reg.prometheus_text(prefix="")
    lines = text.splitlines()
    # Names conform to [a-zA-Z_:][a-zA-Z0-9_:]*
    import re

    name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
    for ln in lines:
        if ln.startswith("#"):
            assert ln.startswith(("# HELP ", "# TYPE "))
            assert name_re.fullmatch(ln.split()[2])
        else:
            assert name_re.fullmatch(ln.split("{")[0].split()[0]), ln
    # Leading digit got guarded.
    assert any(ln.startswith("_9starts_with_digit ") for ln in lines)
    # Every # TYPE names a DISTINCT metric (the collision was suffixed).
    typed = [ln.split()[2] for ln in lines if ln.startswith("# TYPE")]
    assert len(typed) == len(set(typed))
    # Both colliding counters surfaced with their values.
    assert any(ln.endswith(" 5") for ln in lines)
    assert any(ln.endswith(" 7") for ln in lines)
    # One HELP per TYPE, adjacent.
    helps = [ln.split()[2] for ln in lines if ln.startswith("# HELP")]
    assert helps == typed
    # Label-value escaping helper: the three escape-worthy characters.
    assert prom_escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert prom_name("9x", prefix="") == "_9x"
    assert prom_name("a b.c", prefix="tcr") == "tcr_a_b_c"
    # The default-prefix output still parses as before.
    reg2 = MetricsRegistry()
    reg2.incr("frames", 3)
    t2 = reg2.prometheus_text()
    assert "# TYPE tcr_frames counter" in t2
    assert "# HELP tcr_frames" in t2
    assert "tcr_frames 3" in t2
    # One RAW name reused across metric kinds is a collision too: the
    # second emission gets a stable per-base ordinal suffix instead of
    # a duplicate # TYPE block.
    reg3 = MetricsRegistry()
    reg3.incr("x", 4)
    reg3.gauge("x", 2.5)
    t3 = reg3.prometheus_text()
    typed3 = [ln.split()[2] for ln in t3.splitlines()
              if ln.startswith("# TYPE")]
    assert typed3 == ["tcr_x", "tcr_x_1"]
    assert "tcr_x 4" in t3 and "tcr_x_1 2.5" in t3


def test_counters_sample_min_max_in_summary():
    """ISSUE 8 satellite: ``Counters.sample`` reports min/max alongside
    the mean (means alone hid the PR-6 ops_per_step skew)."""
    c = Counters()
    for v in (1.0, 1.0, 9.0):
        c.sample("ops_per_step", v)
    s = c.summary()
    assert s["ops_per_step_mean"] == pytest.approx(11 / 3)
    assert s["ops_per_step_min"] == 1.0
    assert s["ops_per_step_max"] == 9.0
    assert s["ops_per_step_samples"] == 3


# ------------------------------------------------- serve integration -----


def test_loadgen_report_obs_block_and_registry_flow():
    """Counters/histograms flow through ONE registry into the loadgen
    report (ISSUE 8 acceptance): the tick_ms block carries distribution
    keys, the obs block carries trace/bundle counts, and the server
    stats expose the registry's histogram summaries."""
    gen, rep = small_loadgen_run()
    assert rep["obs"]["trace_schema"] == TRACE_SCHEMA_VERSION
    assert rep["obs"]["trace_events"] > 0
    assert rep["obs"]["device_compiles"] >= 1
    # ISSUE 10 satellite: the recorder's bundle economy is first-class
    # report surface, and the written-FILE count (bundle_count, from
    # recorder.bundle_paths) agrees with the registry counter — two
    # independent sources.
    assert rep["obs"]["bundle_count"] == rep["obs"]["bundles_written"]
    assert "bundles_suppressed" in rep["obs"]
    assert "bundles_written" in rep["tick_ms"]
    assert "bundles_suppressed" in rep["tick_ms"]
    tick = rep["tick_ms"]
    assert "ops_per_step_p99" in tick and "ops_per_step_max" in tick
    srv = rep["server"]
    assert srv["tick_wall_ms_count"] == srv["tick_wall_ms_count"]
    assert any(k.startswith("device_step_wall_ms_b") for k in srv)
    # The registry exporters work on the live server.
    reg = gen.server.counters
    assert isinstance(reg, MetricsRegistry)
    assert "tcr_admitted" in reg.prometheus_text()


def test_schema_covers_exactly_the_emitted_kinds():
    """Every kind the serve stack emits is declared, and the schema
    doesn't accumulate dead kinds silently (drift guard both ways)."""
    gen, _ = small_loadgen_run()
    emitted = {e["k"] for e in gen.server.tracer.events}
    assert emitted <= set(EVENT_SCHEMA)

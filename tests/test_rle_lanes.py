"""Per-lane divergent RLE engine vs per-doc flat replays.

The r2 verdict's weak #4 bar: >= 256 DISTINCT streams in one launch,
diffed against per-doc flat replays — plus the warm-start chaining the
blocked engines lack (state carried across compiled chunks)."""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import rle_lanes as RL
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import TestPatch

from test_device_flat import random_patches


def compile_stack(streams, lmax=None):
    """Per-doc patch lists -> stacked [S, B] op tensors (+ next orders)."""
    if lmax is None:
        lmax = max([len(p.ins_content)
                    for ps in streams for p in ps] + [1])
    opses, nexts = [], []
    for ps in streams:
        ops, nxt = B.compile_local_patches(ps, lmax=lmax, dmax=None)
        opses.append(ops)
        nexts.append(nxt)
    return B.stack_ops(opses), nexts


class TestDivergentLanes:
    def test_two_divergent_docs(self):
        streams = [
            [TestPatch(0, 0, "hello"), TestPatch(5, 0, " world"),
             TestPatch(0, 1, "H")],
            [TestPatch(0, 0, "abc"), TestPatch(1, 1, "XY"),
             TestPatch(0, 0, "z")],
        ]
        stacked, _ = compile_stack(streams)
        res = RL.replay_lanes(stacked, capacity=32, chunk=8, interpret=True)
        assert SA.to_string(RL.lanes_to_flat(stacked, res, 0)) == "Hello world"
        assert SA.to_string(RL.lanes_to_flat(stacked, res, 1)) == "zaXYc"

    @pytest.mark.parametrize("seed", [7, 42])
    def test_many_divergent_vs_flat(self, seed):
        rng = random.Random(seed)
        streams, contents = [], []
        for _ in range(16):
            patches, content = random_patches(rng, 30 + rng.randint(0, 30))
            streams.append(patches)
            contents.append(content)
        stacked, _ = compile_stack(streams)
        res = RL.replay_lanes(stacked, capacity=256, chunk=16,
                              interpret=True)
        for d, (ps, content) in enumerate(zip(streams, contents)):
            doc = RL.lanes_to_flat(stacked, res, d)
            ops_d, _ = B.compile_local_patches(ps, lmax=16, dmax=None)
            ref = F.apply_ops(SA.make_flat_doc(512), ops_d)
            assert SA.to_string(doc) == SA.to_string(ref) == content
            assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_merged_streams_equivalent(self):
        rng = random.Random(5)
        streams, contents = [], []
        for _ in range(8):
            patches, content = random_patches(rng, 40)
            streams.append(B.merge_patches(patches))
            contents.append(content)
        stacked, _ = compile_stack(streams)
        res = RL.replay_lanes(stacked, capacity=256, chunk=16,
                              interpret=True)
        for d, content in enumerate(contents):
            assert SA.to_string(RL.lanes_to_flat(stacked, res, d)) == content

    def test_warm_start_chaining(self):
        # Two compiled chunks; chunk 2 resumes from chunk 1's device
        # state — the streaming shape the blocked engines can't run.
        rng = random.Random(9)
        docs = 8
        contents = [""] * docs
        chunk_streams = []
        for _ in range(2):
            streams = []
            for d in range(docs):
                patches = []
                for _ in range(20):
                    if not contents[d] or rng.random() < 0.6:
                        pos = rng.randint(0, len(contents[d]))
                        ins = rng.choice("abcd") * rng.randint(1, 3)
                        patches.append(TestPatch(pos, 0, ins))
                        contents[d] = (contents[d][:pos] + ins
                                       + contents[d][pos:])
                    else:
                        pos = rng.randint(0, len(contents[d]) - 1)
                        span = min(rng.randint(1, 3),
                                   len(contents[d]) - pos)
                        patches.append(TestPatch(pos, span, ""))
                        contents[d] = (contents[d][:pos]
                                       + contents[d][pos + span:])
                streams.append(patches)
            chunk_streams.append(streams)

        next_orders = [0] * docs
        state = None
        all_ops = []
        for streams in chunk_streams:
            opses = []
            for d, ps in enumerate(streams):
                ops, next_orders[d] = B.compile_local_patches(
                    ps, lmax=4, dmax=None, start_order=next_orders[d])
                opses.append(ops)
            stacked = B.stack_ops(opses)
            all_ops.append(stacked)
            run = RL.make_replayer_lanes(stacked, capacity=128, chunk=16,
                                         init=state, interpret=True)
            res = run()
            res.check()
            state = res.state()

        for d in range(docs):
            flat = RL.expand_lane(res, d)
            # Rebuild content: chars by order from both chunks' streams.
            chars = {}
            for stacked in all_ops:
                ilens = np.asarray(stacked.ins_len)[:, d]
                starts = np.asarray(stacked.ins_order_start)[:, d]
                cps = np.asarray(stacked.chars)[:, d]
                for s in range(len(ilens)):
                    for j in range(int(ilens[s])):
                        chars[int(starts[s]) + j] = chr(int(cps[s, j]))
            got = "".join(chars[int(o) - 1] for o in flat if o > 0)
            assert got == contents[d], f"doc {d} diverged after warm start"

    def test_warm_start_capacity_growth(self):
        # Streaming chunks may GROW row capacity (the round-5 bench
        # lever): chunk 2 at a larger capacity must zero-pad chunk 1's
        # planes and produce the same state as a flat-capacity chain.
        rng = random.Random(31)
        docs = 4
        streams1 = [random_patches(rng, 15)[0] for _ in range(docs)]
        stacked1, nexts = compile_stack(streams1)
        small = RL.make_replayer_lanes(stacked1, capacity=64, chunk=8,
                                       interpret=True)()
        small.check()
        streams2 = [random_patches(rng, 15)[0] for _ in range(docs)]
        opses = [B.compile_local_patches(ps, lmax=16, dmax=None,
                                         start_order=nx)[0]
                 for ps, nx in zip(streams2, nexts)]
        stacked2 = B.stack_ops(opses)
        grown = RL.make_replayer_lanes(stacked2, capacity=128, chunk=8,
                                       interpret=True)(small.state())
        grown.check()

        flat1 = RL.make_replayer_lanes(stacked1, capacity=128, chunk=8,
                                       interpret=True)()
        flat2 = RL.make_replayer_lanes(stacked2, capacity=128, chunk=8,
                                       interpret=True)(flat1.state())
        assert np.array_equal(np.asarray(grown.ordp),
                              np.asarray(flat2.ordp))
        assert np.array_equal(np.asarray(grown.lenp),
                              np.asarray(flat2.lenp))
        assert np.array_equal(np.asarray(grown.rows),
                              np.asarray(flat2.rows))

    def test_capacity_flag_per_lane(self):
        # Lane 1 overflows a tiny capacity; lane 0 stays legal.
        streams = [
            [TestPatch(0, 0, "ab")],
            [TestPatch(0, 0, "ab") for _ in range(20)],
        ]
        stacked, _ = compile_stack(streams)
        res = RL.replay_lanes(stacked, capacity=8, chunk=8, interpret=True)
        with pytest.raises(RuntimeError, match="lanes \\[1\\]"):
            res.check()

    def test_bad_delete_flag(self):
        streams = [[TestPatch(0, 0, "abc"), TestPatch(0, 10, "")]]
        stacked, _ = compile_stack(streams)
        res = RL.replay_lanes(stacked, capacity=16, chunk=8, interpret=True)
        with pytest.raises(RuntimeError, match="past the end"):
            res.check()


class TestLaneTiling:
    """The lane-block grid dimension (wide batches compile by tiling the
    lane axis; each lane block runs all chunks before the next starts)
    must be invisible: tiled and whole-batch replays produce identical
    state, origins, and flags — including across warm-started chunks."""

    def test_tiled_equals_whole_with_warm_start(self):
        rng = random.Random(99)
        nd = 8
        streams = [random_patches(rng, 40)[0] for _ in range(nd)]
        stacked, nexts = compile_stack(streams)
        cap = 256
        whole = RL.make_replayer_lanes(stacked, capacity=cap, chunk=8,
                                       interpret=True)()
        tiled = RL.make_replayer_lanes(stacked, capacity=cap, chunk=8,
                                       interpret=True, lane_tile=4)()
        whole.check()
        tiled.check()
        for a, b in ((whole.ordp, tiled.ordp), (whole.lenp, tiled.lenp),
                     (whole.rows, tiled.rows), (whole.ol, tiled.ol),
                     (whole.orr, tiled.orr)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

        streams2 = [random_patches(rng, 30)[0] for _ in range(nd)]
        opses = [B.compile_local_patches(ps, lmax=16, dmax=None,
                                         start_order=nx)[0]
                 for ps, nx in zip(streams2, nexts)]
        stacked2 = B.stack_ops(opses)
        w2 = RL.make_replayer_lanes(stacked2, capacity=cap, chunk=8,
                                    interpret=True)(whole.state())
        t2 = RL.make_replayer_lanes(stacked2, capacity=cap, chunk=8,
                                    interpret=True, lane_tile=2)(
                                        tiled.state())
        w2.check()
        t2.check()
        assert np.array_equal(np.asarray(w2.ordp), np.asarray(t2.ordp))
        assert np.array_equal(np.asarray(w2.lenp), np.asarray(t2.lenp))

    def test_lane_tile_picker(self):
        assert RL._lane_tile(8) == 8
        assert RL._lane_tile(512) == 512
        assert RL._lane_tile(1024) == 512
        assert RL._lane_tile(2048) == 512

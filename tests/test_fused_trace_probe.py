"""Tier-1 smoke for ``perf/fused_trace_probe.py`` (ISSUE 6 CI
satellite): the committed ``perf/fused_traces_r9.json`` is produced by
the probe's full path; this asserts its small-scale path stays green —
a real-trace prefix at event granularity, fused vs unfused, bit-exact
on all four fused-splice surfaces (rle / rle-hbm / blocked lanes /
blocked lanes-mixed) — so a kernel or fuser regression cannot land
while the JSON silently rots.

The smoke calls ``identity_prefix`` IN-PROCESS at the probe's own tight
geometry (a subprocess would re-pay the jax import; the suite's shared
512-row geometry was measured SLOWER here — fatter interpret replays
cost more than warm-cache builds save).  The probe's CLI and JSON
writer are exercised by the ``slow``-tier claims check below and by
``perf/when_up_r9.sh`` on silicon day.
"""
import importlib.util
import json
import os

import pytest

PROBE = os.path.join("perf", "fused_trace_probe.py")


def _load_probe():
    spec = importlib.util.spec_from_file_location("ftp", PROBE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# Slow tier since PR 17 (wall budget: ~23 s of the 870 s gate): the
# fused-splice surfaces keep tier-1 bit-exactness coverage in
# test_rle_fused / test_lanes_blocked; the full claims check below was
# always slow-tier.
@pytest.mark.slow
def test_probe_smoke_path_green():
    row = _load_probe().identity_prefix(
        "automerge-paper", 60, fuse_w=6, chunk=64)
    assert row["oracle_equal"]
    assert set(row["bit_identical"]) == {
        "rle", "rle-hbm", "rle-lanes-blocked", "rle-lanes-mixed-blocked"}
    assert all(row["bit_identical"].values())
    assert row["steps_fused"] < row["steps_unfused"]


@pytest.mark.slow
def test_committed_r9_json_claims_hold():
    """The committed probe JSON's headline claims re-checked against
    the CURRENT compiler+fuser (host arithmetic only — no replay): the
    full-trace step cut is reproducible and >= the acceptance floor —
    ``slow`` because it recompiles the full automerge trace (the tier-1
    budget keeps only the in-process smoke above)."""
    with open(os.path.join("perf", "fused_traces_r9.json")) as f:
        committed = json.load(f)
    assert committed["acceptance"]["pass"]
    mod = _load_probe()
    want = {c["trace"]: c for c in committed["full_trace_step_cut"]}
    cut = mod.full_trace_cut("automerge-paper",
                             committed["workload"]["fuse_w"])
    assert cut["steps_unfused"] == want["automerge-paper"]["steps_unfused"]
    assert cut["steps_fused"] == want["automerge-paper"]["steps_fused"]
    assert cut["step_reduction_x"] >= committed["acceptance"]["floor_x"]

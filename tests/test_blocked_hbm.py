"""HBM-resident blocked replay engine vs the flat engine and string oracle.

Interpreter-mode differential tests mirroring ``test_blocked.py``: tiny
blocks force constant window misses (DMA write-back + fetch) and global
rebalances, so the cache/ensure machinery is exercised on every few ops —
the analog of the reference's shrunken debug node sizes
(`range_tree/mod.rs:29-39`). The real kernel runs on TPU via
``bench.py --engine hbm``, which asserts full-trace final content.

The round-1 advisor found the SUP=64 super-block slicing crashed (or
silently mis-sliced) whenever NB was not a multiple of SUP; every test
here runs with NB << 64, pinning the NBp padding fix.
"""
import random

import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import blocked as BL
from text_crdt_rust_tpu.ops import blocked_hbm as BH
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    TestPatch,
    flatten_patches,
    load_testing_data,
    trace_path,
)

from test_device_flat import random_patches

# Superseded per-char engine: differential reference only; excluded
# from the default run (see pytest.ini / README engine lineup).
pytestmark = pytest.mark.archival


def run_hbm(patches, capacity, block_k, lmax=4, chunk=128):
    ops, _ = B.compile_local_patches(patches, lmax=lmax, dmax=lmax)
    res = BH.replay_local_hbm(ops, capacity=capacity, batch=8,
                              block_k=block_k, chunk=chunk, interpret=True)
    return ops, BL.blocked_to_flat(ops, res)


class TestHbmReplay:
    def test_smoke(self):
        patches = [TestPatch(0, 0, "hello world"), TestPatch(5, 0, ","),
                   TestPatch(2, 3, "LLO"), TestPatch(0, 1, "H")]
        ops, doc = run_hbm(patches, capacity=64, block_k=8)
        ref = F.apply_ops(SA.make_flat_doc(64), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == "HeLLO, world"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.parametrize("seed", [7, 11, 99])
    def test_random_vs_flat(self, seed):
        # Tiny blocks: block overflows force the DMA-staged rebalance, and
        # alternating edit positions force window cache misses.
        rng = random.Random(seed)
        patches, content = random_patches(rng, 80)
        ops, doc = run_hbm(patches, capacity=512, block_k=16)
        ref = F.apply_ops(SA.make_flat_doc(512), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == content
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_delete_spanning_blocks(self):
        patches = [TestPatch(0, 0, "abcdefghijklmnopqrstuvwxyz")]
        patches += [TestPatch(2, 20, "")]
        ops, doc = run_hbm(patches, capacity=64, block_k=8)
        ref = F.apply_ops(SA.make_flat_doc(64), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == "abwxyz"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_prepend_heavy(self):
        # The "kevin" shape: always insert at 0 — block 0 overflows over
        # and over, and the rebalance invalidates/refetches the window.
        patches = [TestPatch(0, 0, "ab") for _ in range(40)]
        ops, doc = run_hbm(patches, capacity=256, block_k=8)
        ref = F.apply_ops(SA.make_flat_doc(256), ops)
        assert SA.to_string(doc) == SA.to_string(ref) == "ab" * 40
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_far_jump_edits(self):
        # Edits alternating between the document's two ends: every op is a
        # window miss (write-back + fetch), plus boundary-crossing inserts
        # exercising the succ DMA peek.
        patches = [TestPatch(0, 0, "abcdefgh")]
        for k in range(12):
            patches.append(TestPatch(0, 0, "xy"))       # front
            patches.append(TestPatch(8 + 2 * k, 0, "pq"))  # near the back
        ops, doc = run_hbm(patches, capacity=128, block_k=8)
        ref = F.apply_ops(SA.make_flat_doc(128), ops)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.slow
    def test_trace_prefix(self):
        data = load_testing_data(trace_path("automerge-paper"))
        patches = flatten_patches(data)[:400]
        ops, doc = run_hbm(patches, capacity=1024, block_k=32, lmax=16)
        ref = F.apply_ops(SA.make_flat_doc(1024), ops)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_capacity_exhaustion_rejected(self):
        patches = [TestPatch(0, 0, "x" * 4) for _ in range(20)]
        ops, _ = B.compile_local_patches(patches, lmax=4, dmax=4)
        with pytest.raises(ValueError, match="raise capacity"):
            BH.replay_local_hbm(ops, capacity=32, batch=8, block_k=8,
                                chunk=128, interpret=True)


class TestGroupedStreams:
    """Doc groups: G DIVERGENT streams in one kernel launch (the config-3
    ragged mixed-corpus shape, VERDICT r1 item 5)."""

    def test_four_divergent_streams(self):
        rng = random.Random(404)
        streams, contents, opses = [], [], []
        for gi in range(4):
            patches, content = random_patches(rng, 40 + 10 * gi)
            ops, _ = B.compile_local_patches(patches, lmax=4, dmax=4)
            opses.append(ops)
            contents.append(content)
        run = BH.make_replayer_hbm(opses, capacity=512, batch=8,
                                   block_k=16, chunk=128, interpret=True)
        results = run()
        assert len(results) == 4
        for ops, res, content in zip(opses, results, contents):
            doc = BL.blocked_to_flat(ops, res)
            ref = F.apply_ops(SA.make_flat_doc(512), ops)
            assert SA.to_string(doc) == SA.to_string(ref) == content
            assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_ragged_lengths_and_rebalances(self):
        # Extremely ragged: a 4-patch stream next to a 120-patch one that
        # forces multiple rebalances; padding steps must be exact no-ops.
        rng = random.Random(77)
        short = [TestPatch(0, 0, "hi"), TestPatch(1, 1, "ey"),
                 TestPatch(0, 0, "O"), TestPatch(2, 1, "")]
        long_p, long_content = random_patches(rng, 120)
        ops_s, _ = B.compile_local_patches(short, lmax=4, dmax=4)
        ops_l, _ = B.compile_local_patches(long_p, lmax=4, dmax=4)
        run = BH.make_replayer_hbm([ops_s, ops_l], capacity=1024, batch=8,
                                   block_k=16, chunk=128, interpret=True)
        res_s, res_l = run()
        doc_s = BL.blocked_to_flat(ops_s, res_s)
        doc_l = BL.blocked_to_flat(ops_l, res_l)
        ref_s = F.apply_ops(SA.make_flat_doc(64), ops_s)
        assert SA.to_string(doc_s) == SA.to_string(ref_s)
        assert SA.to_string(doc_l) == long_content

"""Streaming apply: the device engine fed incrementally, N-peer convergence.

`BASELINE.json` config 5's shape: txns arrive over time (possibly out of
order), are released by the causal buffer, compiled in batches, and applied
to a persistent device document across multiple ``apply_ops`` calls — with
the host oracle tracking the same stream for equality.
"""
import random

from text_crdt_rust_tpu.models import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.parallel import CausalBuffer

from test_device_flat import (
    assert_same_doc,
    oracle_from_patches,
    random_patches,
)


def test_streaming_local_chunks_match_one_shot():
    # One edit stream compiled and applied in 3 chunks must equal the
    # single-shot replay (orders continue across calls).
    rng = random.Random(31)
    patches, content = random_patches(rng, 90)
    oracle = oracle_from_patches(patches)

    doc = SA.make_flat_doc(1024)
    start = 0
    for lo in range(0, 90, 30):
        ops, start = B.compile_local_patches(
            patches[lo:lo + 30], lmax=4, start_order=start)
        doc = F.apply_ops(doc, ops)
    assert_same_doc(doc, oracle)
    assert SA.to_string(doc) == content


def test_n_peer_shuffled_stream_device_convergence():
    # 3 peers edit independently; their txns arrive shuffled, pass through
    # the causal buffer, and are applied in released order to BOTH the
    # oracle and the device engine in batches of 4.
    rng = random.Random(47)
    peers = ["amy", "bob", "cat"]
    txns = []
    for name in peers:
        patches, _ = random_patches(rng, 40)
        txns.extend(export_txns_since(
            oracle_from_patches(patches, agent=name), 0))
    rng.shuffle(txns)

    buf = CausalBuffer()
    released = []
    for t in txns:
        released.extend(buf.add(t))
    assert buf.pending == 0

    oracle = ListCRDT()
    for t in released:
        oracle.apply_remote_txn(t)

    table = B.AgentTable(peers)
    assigner = None
    doc = SA.make_flat_doc(2048)
    for lo in range(0, len(released), 4):
        ops, assigner = B.compile_remote_txns(
            released[lo:lo + 4], table, assigner=assigner, lmax=4)
        doc = F.apply_ops(doc, ops)
    assert_same_doc(doc, oracle)


def test_peer_pair_cross_sync_device_matches_oracle():
    # Two peers sync through each other's exports mid-edit; the final
    # oracle history replayed onto the device engine matches.
    rng = random.Random(53)
    a = ListCRDT()
    b = ListCRDT()
    ia = a.get_or_create_agent_id("amy")
    ib = b.get_or_create_agent_id("bob")
    from text_crdt_rust_tpu.models.sync import merge_into

    a.local_insert(ia, 0, "hello ")
    merge_into(b, a)
    b.local_insert(ib, 6, "world")
    a.local_delete(ia, 0, 1)
    merge_into(a, b)
    merge_into(b, a)
    assert a.to_string() == b.to_string()

    txns = export_txns_since(a, 0)
    table = B.AgentTable(["amy", "bob"])
    ops, _ = B.compile_remote_txns(txns, table, lmax=4)
    doc = F.apply_ops(SA.make_flat_doc(256), ops)
    assert SA.to_string(doc) == a.to_string()
    assert SA.doc_spans(doc) == a.doc_spans()


def test_peer_onboarding_rank_epochs():
    """Two new peers join BETWEEN compiled epochs (r2 verdict weak #4: the
    AgentTable freeze blocked mid-stream onboarding). Registering "aa" and
    "ann" shifts every persisted rank by +2/+1, so chunk 2's concurrent
    same-position insert tiebreaks correctly only if the device's by-order
    rank log was re-based via rank_remap."""
    from text_crdt_rust_tpu.common import (
        ROOT_REMOTE_ID,
        RemoteId,
        RemoteIns,
        RemoteTxn,
    )
    from text_crdt_rust_tpu.ops.span_arrays import remap_rank_log

    def ins_txn(agent, seq, content, parents):
        return RemoteTxn(
            id=RemoteId(agent, seq), parents=parents,
            ops=[RemoteIns(ROOT_REMOTE_ID, ROOT_REMOTE_ID, content)])

    # Chunk 1: amy and zed insert concurrently at the document head.
    chunk1 = [
        ins_txn("amy", 0, "AA", [ROOT_REMOTE_ID]),
        ins_txn("zed", 0, "ZZ", [ROOT_REMOTE_ID]),
    ]
    # Chunk 2: ann (amy < ann < zed) inserts concurrently at the head.
    # True ranks after aa+ann join: aa=0 amy=1 ann=2 zed=3 — ann must land
    # between amy's and zed's spans. zed's STALE chunk-1 rank is 1 < 2,
    # which would wrongly keep the integrate scan going past zed.
    chunk2 = [ins_txn("ann", 0, "NN", [ROOT_REMOTE_ID])]

    oracle = ListCRDT()
    for t in chunk1 + chunk2:
        oracle.apply_remote_txn(t)
    assert oracle.to_string() == "AANNZZ"

    table = B.AgentTable(["amy", "zed"])
    ops1, assigner = B.compile_remote_txns(chunk1, table)
    doc = F.apply_ops(SA.make_flat_doc(256), ops1)

    # Epoch boundary: aa and ann join; ids append, ranks shuffle, the
    # persisted rank log re-bases.
    old_names = list(table.names)
    table.add("aa")
    table.add("ann")
    doc = remap_rank_log(doc, B.rank_remap(old_names, table))
    ops2, _ = B.compile_remote_txns(chunk2, table, assigner=assigner)
    doc = F.apply_ops(doc, ops2)

    assert_same_doc(doc, oracle)
    assert SA.to_string(doc) == "AANNZZ"


def test_peer_onboarding_without_remap_diverges():
    """The discriminating control: the same scenario with the remap
    SKIPPED places ann's insert past zed (stale rank 1 < ann's 2) —
    proving the epoch remap is load-bearing, not decorative."""
    from text_crdt_rust_tpu.common import (
        ROOT_REMOTE_ID,
        RemoteId,
        RemoteIns,
        RemoteTxn,
    )

    def ins_txn(agent, seq, content, parents):
        return RemoteTxn(
            id=RemoteId(agent, seq), parents=parents,
            ops=[RemoteIns(ROOT_REMOTE_ID, ROOT_REMOTE_ID, content)])

    chunk1 = [ins_txn("amy", 0, "AA", [ROOT_REMOTE_ID]),
              ins_txn("zed", 0, "ZZ", [ROOT_REMOTE_ID])]
    chunk2 = [ins_txn("ann", 0, "NN", [ROOT_REMOTE_ID])]

    table = B.AgentTable(["amy", "zed"])
    ops1, assigner = B.compile_remote_txns(chunk1, table)
    doc = F.apply_ops(SA.make_flat_doc(256), ops1)
    table.add("aa")
    table.add("ann")  # no remap: stale ranks persist in doc.rank_log
    ops2, _ = B.compile_remote_txns(chunk2, table, assigner=assigner)
    doc = F.apply_ops(doc, ops2)
    assert SA.to_string(doc) == "AAZZNN"  # wrong order, deterministically

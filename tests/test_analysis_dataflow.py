"""tcrlint v2 self-tests (ISSUE 15): the dataflow engine + the four
interprocedural check families + the incremental gate.

Same proof obligations as PR 12's per-family suite, now for flow-aware
checks: every family proven LOUD by seeded-defect injection (exit-1 /
finding naming the exact file:line + check id) and QUIET on the clean
tree — with the real serve files as the known-clean corpus (the
runtime sanitizer's sites), and real-file mutations (a mirror update
deleted from the committed ``FlatLaneBackend.apply``) as the seeded
defects.  Plus the incremental machinery: content-hash cache
hit/invalidation, ``--changed`` against a real git merge-base, and the
ruff-parity pin for the F401 fallback floor.
"""
import json
import os
import re
import shutil
import subprocess
import sys
import textwrap
import time

import pytest

from text_crdt_rust_tpu.analysis import run_lint
from text_crdt_rust_tpu.analysis.checks_shape import (
    SHAPE_PINS_PATH,
    harvest_contracts,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_tree(tmp_path, files, allow=None, shape_pins=None, **kw):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    allow_path = str(tmp_path / "allow.json")
    if allow is not None:
        (tmp_path / "allow.json").write_text(json.dumps({"allow": allow}))
    return run_lint(str(tmp_path), allowlist_path=allow_path,
                    pins_path=str(tmp_path / "pins.json"),
                    shape_pins_path=shape_pins or str(
                        tmp_path / "shape_pins.json"), **kw)


def the(findings, check):
    hits = [f for f in findings if f.check == check]
    assert hits, f"no {check} finding in {[f.format() for f in findings]}"
    return hits


def none_of(findings, check):
    hits = [f.format() for f in findings if f.check == check]
    assert not hits, hits


# ------------------------------------------------ the dataflow engine -------


def _flow(src, name):
    import ast

    from text_crdt_rust_tpu.analysis.dataflow import FunctionFlow

    tree = ast.parse(textwrap.dedent(src))
    for node in ast.walk(tree):
        if getattr(node, "name", None) == name:
            return FunctionFlow(node)
    raise AssertionError(name)


def test_cfg_loop_back_edge_reaches_earlier_statement():
    flow = _flow("""\
        def f(xs):
            for x in xs:
                a = 1
                b = 2
            return a
        """, "f")
    # stmts: for(0), a=1(1), b=2(2), return(3)
    reach = flow.reachable_from(1)
    assert 1 in reach and 2 in reach and 3 in reach  # via the back edge


def test_cfg_sync_statement_blocks_propagation():
    flow = _flow("""\
        def f(backend, s):
            backend.apply(s)
            backend.barrier()
            s.pos[0] = 1
        """, "f")
    from text_crdt_rust_tpu.analysis.checks_pipeline import _is_sync_stmt

    sync = {i for i, s in enumerate(flow.stmts) if _is_sync_stmt(s)}
    assert sync == {1}
    assert 2 not in flow.reachable_from(0, blocked=sync)


def test_reaching_defs_const_resolution():
    flow = _flow("""\
        def f(cond):
            a = 48
            b = 48 if cond else 7
            use(a)
            use(b)
        """, "f")
    import ast

    uses = [s for s in flow.stmts if isinstance(s, ast.Expr)]
    a_arg = uses[0].value.args[0]
    b_arg = uses[1].value.args[0]
    assert flow.const_int(a_arg, flow.index[uses[0]]) == 48
    # b's definition is not a plain literal binding -> unresolved
    assert flow.const_int(b_arg, flow.index[uses[1]]) is None


def test_const_resolution_refuses_conflicting_defs():
    flow = _flow("""\
        def f(cond):
            if cond:
                a = 8
            else:
                a = 48
            use(a)
        """, "f")
    import ast

    use = [s for s in flow.stmts if isinstance(s, ast.Expr)][0]
    assert flow.const_int(use.value.args[0], flow.index[use]) is None


def test_alias_closure_chases_stack_and_pad():
    flow = _flow("""\
        def f(streams, apply):
            per_lane = [pad_ops(s, 8) for s in streams]
            stacked = stack_ops(per_lane)
            apply(stacked)
        """, "f")
    import ast

    call = [s for s in flow.stmts if isinstance(s, ast.Expr)][-1]
    taint, containers = flow.alias_closure(
        call.value.args, flow.index[call])
    assert {"stacked", "per_lane", "streams"} <= taint
    assert "per_lane" in containers  # list-comp constructed


def test_summaries_mark_mutating_params():
    import ast

    from text_crdt_rust_tpu.analysis.dataflow import summarize_module

    tree = ast.parse(textwrap.dedent("""\
        import numpy as np


        def scrub(a, b):
            a[0] = 0
            return b


        def reader(a):
            return a.sum()


        class K:
            def touch(self):
                self._n_host[0] = 1
        """))
    s = summarize_module(tree)
    assert s["scrub"].mutated_params == ("a",)
    assert s["reader"].mutated_params == ()
    assert "_n_host" in s["K.touch"].writes_self_attrs


# ------------------------------------------- family TCR-P: pipeline escape --




def test_post_dispatch_mutation_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": "import numpy as np\n\n\n" + textwrap.dedent("""\
        def tick(backend, stacked):
            backend.apply(stacked)
            stacked.pos[0] = 7
        """)})
    f = the(findings, "TCR-P001")[0]
    assert (f.path, f.line) == ("mod.py", 6)
    assert "dispatched at line 5" in f.message


def test_mutation_after_staged_sync_passes(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": "import numpy as np\n\n\n" + textwrap.dedent("""\
        def tick(backend, stacked):
            backend.apply(stacked)
            backend.barrier()
            stacked.pos[0] = 7
        """)})
    none_of(findings, "TCR-P001")


def test_interprocedural_mutation_via_helper_flagged(tmp_path):
    """One-level call summaries: the mutation hides in a same-module
    helper the post-dispatch code hands the buffer to."""
    findings, _ = lint_tree(tmp_path, {"mod.py": "import numpy as np\n\n\n" + textwrap.dedent("""\
        def scrub(a):
            a[0] = 0


        def tick(backend, stacked):
            backend.apply(stacked)
            scrub(stacked.pos)
        """)})
    f = the(findings, "TCR-P001")[0]
    assert f.line == 10


def test_forward_alias_and_copyto_flagged(tmp_path):
    """A post-dispatch binding that aliases the dispatched buffer
    (subscript read) is tainted; np.copyto through it is a finding."""
    findings, _ = lint_tree(tmp_path, {"mod.py": "import numpy as np\n\n\n" + textwrap.dedent("""\
        def tick(backend, per_lane):
            stacked = stack_ops(per_lane)
            backend.apply(stacked)
            col = per_lane[0]
            np.copyto(col, 0)
        """)})
    f = the(findings, "TCR-P001")[0]
    assert f.line == 8


def test_loop_back_edge_mutation_flagged_once(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": "import numpy as np\n\n\n" + textwrap.dedent("""\
        def tick(backend, streams):
            for s in streams:
                backend.apply(s)
                s.chars.fill(0)
        """)})
    assert len(the(findings, "TCR-P001")) == 1


def test_container_slot_rebind_and_self_state_pass(tmp_path):
    """The two deliberate calibrations: dict/list slot rebinds are not
    array writes, and self-rooted bookkeeping is TCR-M's contract."""
    findings, _ = lint_tree(tmp_path, {"mod.py": "import numpy as np\n\n\n" + textwrap.dedent("""\
        def tick(self, backend, lane_streams):
            stacked = stack_ops(
                [pad_ops(s, 8) for s in lane_streams.values()])
            backend.apply(stacked)
            lane_streams[0] = None
            self.counters["ticks"] += 1
        """)})
    none_of(findings, "TCR-P001")


def test_real_serve_tick_is_the_known_clean_corpus():
    """The runtime sanitizer's known-clean sites (the real batcher +
    lanes backend, every dispatch edge of the serve tick) lint quiet —
    the seed corpus of ISSUE 15."""
    findings, _ = run_lint(
        REPO, ["text_crdt_rust_tpu/serve/batcher.py",
               "text_crdt_rust_tpu/serve/lanes_backend.py",
               "text_crdt_rust_tpu/ops/flat.py"])
    none_of(findings, "TCR-P001")


# ------------------------------------------- family TCR-M: mirror pairing ---


def _mutated_batcher(strip: str) -> str:
    src = open(os.path.join(
        REPO, "text_crdt_rust_tpu/serve/batcher.py")).read()
    assert strip in src, "seeded-defect anchor drifted"
    return src.replace(strip, "")


MIRROR_CUT = """\
        self._n_host += np.asarray(
            stacked.ins_len, dtype=np.int64).sum(axis=0)
        self._next_order_host += np.asarray(
            stacked.order_advance, dtype=np.int64).sum(axis=0)
"""

# The train-boundary mirror true-up inside _dispatch_train (ISSUE 20).
# Stripping it alongside MIRROR_CUT removes EVERY path from apply's
# device write to a mirror (direct and via the train_sync helper), so
# the M001 injection stays loud — and the same cut is the M003 seeded
# defect (the registered train_sync site no longer trues up).
TRAIN_SYNC_CUT = """\
        self._n_host = self._n_host + self._pending_n
        self._next_order_host = self._next_order_host + self._pending_o
"""


def test_mirror_skip_injection_named_by_lint(tmp_path):
    """ISSUE 15 satellite: the REAL FlatLaneBackend.apply with its
    host-mirror updates deleted — the lint names the device-write line
    and the check id (the static half; the runtime half lives in
    test_device_prefill.py).  Both mirror-advance sites go: the serial
    per-tick block AND the train-boundary true-up (which would
    otherwise excuse apply via the one-level helper rule)."""
    rel = "text_crdt_rust_tpu/serve/batcher.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    src = _mutated_batcher(MIRROR_CUT)
    assert TRAIN_SYNC_CUT in src, "train true-up anchor drifted"
    p.write_text(src.replace(TRAIN_SYNC_CUT, ""))
    findings, _ = run_lint(str(tmp_path), [rel],
                           allowlist_path=str(tmp_path / "a.json"),
                           pins_path=str(tmp_path / "p.json"),
                           shape_pins_path=str(tmp_path / "sp.json"))
    hits = the(findings, "TCR-M001")
    apply_hits = [f for f in hits if "FlatLaneBackend.apply" in f.message]
    assert apply_hits, [f.format() for f in hits]
    assert apply_hits[0].scope == "FlatLaneBackend.apply"
    assert "_n_host" in apply_hits[0].message


def test_train_sync_split_injection_named_by_lint(tmp_path):
    """ISSUE 20 satellite (loud half): the REAL batcher with the
    train-boundary mirror true-up deleted from _dispatch_train — the
    registered train_sync site no longer writes a mirror in its own
    body, and TCR-M003 names the method and the atomicity contract."""
    rel = "text_crdt_rust_tpu/serve/batcher.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(_mutated_batcher(TRAIN_SYNC_CUT))
    findings, _ = run_lint(str(tmp_path), [rel],
                           allowlist_path=str(tmp_path / "a.json"),
                           pins_path=str(tmp_path / "p.json"),
                           shape_pins_path=str(tmp_path / "sp.json"))
    hits = the(findings, "TCR-M003")
    assert hits, "train_sync cut not flagged"
    assert hits[0].scope == "FlatLaneBackend._dispatch_train"
    assert "atomic" in hits[0].message


def test_train_sync_delegation_flagged_even_when_m001_passes(tmp_path):
    """TCR-M003 is strictly stronger than M001 at the train boundary: a
    train_sync site that delegates its mirror true-up to a same-class
    helper passes M001's one-level rule but still fails M003 (the
    true-up must be in the SAME method as the device write)."""
    findings, _ = lint_tree(tmp_path, {
        "text_crdt_rust_tpu/serve/mod.py": """\
            class FlatLaneBackend:
                def _true_up(self):
                    self._n_host = self._n_host + self._pending_n

                def _dispatch_train(self):
                    self.docs = self.docs.at[0].set(0)
                    self._true_up()
            """})
    none_of(findings, "TCR-M001")
    hits = the(findings, "TCR-M003")
    assert hits and hits[0].scope == "FlatLaneBackend._dispatch_train"


def test_clean_tree_has_no_train_sync_findings():
    """ISSUE 20 satellite (quiet half): the committed batcher's
    _dispatch_train satisfies the atomic train_sync contract."""
    findings, _ = run_lint(
        REPO, ["text_crdt_rust_tpu/serve/batcher.py"])
    none_of(findings, "TCR-M003")


def test_clean_backends_pass_with_committed_allowlist():
    findings, _ = run_lint(
        REPO, ["text_crdt_rust_tpu/serve/batcher.py",
               "text_crdt_rust_tpu/serve/lanes_backend.py"])
    none_of(findings, "TCR-M001")
    none_of(findings, "TCR-M002")


def test_rank_only_rewrite_carries_a_scoped_grant():
    """remap_lane_ranks writes device state with NO mirror — correct by
    construction (occupancy untouched) and therefore exactly the shape
    that must be a justified allowlist grant, not silence."""
    from text_crdt_rust_tpu.analysis.tcrlint import load_allowlist

    grants = [e for e in load_allowlist()
              if e["check"] == "TCR-M001"
              and e["scope"] == "FlatLaneBackend.remap_lane_ranks"]
    assert grants and "rank" in grants[0]["why"].lower()


def test_unregistered_serve_backend_class_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {
        "text_crdt_rust_tpu/serve/newbackend.py": """\
            class ShinyLaneBackend:
                def clear_lane(self, b):
                    self.docs = self.docs.at[b].set(0)
            """})
    f = the(findings, "TCR-M002")[0]
    assert f.line == 3 and "MIRROR_CONTRACTS" in f.message


def test_mirror_paired_via_same_class_helper_passes(tmp_path):
    """One-level pairing: the mirror update may live in a helper
    method the write site calls."""
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        class FlatLaneBackend:
            def _bump(self, b):
                self._n_host[b] += 1

            def clear_lane(self, b):
                self.docs = self.docs.at[b].set(0)
                self._bump(b)
        """})
    none_of(findings, "TCR-M001")


# ------------------------------------------- family TCR-K: shape contracts --


def test_off_series_literal_and_const_prop_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        def stage(stream, pad_ops):
            bkt = 48
            ok = pad_ops(stream, 8)
            bad = pad_ops(stream, 48)
            worse = pad_ops(stream, bkt)
            dyn = pad_ops(stream, len(stream))
            return ok, bad, worse, dyn
        """}, shape_pins=SHAPE_PINS_PATH)
    hits = the(findings, "TCR-K001")
    assert [f.line for f in hits] == [4, 5]
    assert "step-bucket series" in hits[0].message


def test_off_series_scatter_bucket_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        def build(PrefillDelta, cols):
            good = PrefillDelta(*cols, bucket=128)
            bad = PrefillDelta(*cols, bucket=100)
            return good, bad
        """}, shape_pins=SHAPE_PINS_PATH)
    hits = the(findings, "TCR-K001")
    assert [f.line for f in hits] == [3]
    assert "scatter-bucket series" in hits[0].message


def test_shape_contracts_pin_matches_live_tree():
    """The committed SHAPE_CONTRACTS.json agrees with the harvested
    series — the shipped tree carries no unpinned shape drift, and the
    harvest itself sees the real surfaces."""
    live = harvest_contracts(REPO)
    pinned = json.load(open(SHAPE_PINS_PATH))["contracts"]
    assert live == pinned
    assert live["scatter-series"]["base"] == 32
    assert live["scatter-series"]["factor"] == 4
    assert live["step-buckets"]["buckets"] == [8, 32, 128]
    assert live["smem-op-columns"]["text_crdt_rust_tpu/ops/rle.py"] == 5


def test_shape_series_drift_without_repin_flagged(tmp_path):
    """Mutate a pinned series copy -> TCR-K002 naming the declaring
    file and demanding --update-pins in the same change."""
    pins = json.load(open(SHAPE_PINS_PATH))
    pins["contracts"]["step-buckets"]["buckets"] = [8, 32]
    mutated = tmp_path / "shape_pins.json"
    mutated.write_text(json.dumps(pins))
    findings, _ = run_lint(
        REPO, ["text_crdt_rust_tpu/analysis/checks_shape.py"],
        shape_pins_path=str(mutated))
    f = the(findings, "TCR-K002")[0]
    assert f.path == "text_crdt_rust_tpu/config.py"
    assert "--update-pins" in f.message


def test_update_pins_rewrites_shape_contracts(tmp_path):
    out = tmp_path / "shape_pins.json"
    findings, _ = run_lint(
        REPO, ["text_crdt_rust_tpu/analysis/checks_shape.py"],
        shape_pins_path=str(out), update_pins=True,
        pins_path=str(tmp_path / "schema_pins.json"))
    assert json.load(open(out))["contracts"] == \
        json.load(open(SHAPE_PINS_PATH))["contracts"]


# ------------------------------------------- family TCR-C: claims ----------


CLAIMS_TREE = {
    "README.md": """\
        # x
        ## Measured vs pending silicon
        | claim | status | evidence |
        |---|---|---|
        | good row | **measured** | `perf/real_r1.json` |
        | ghost row | **measured** | `perf/ghost_r9.json` |
        | sourceless | measured on CPU | trust me |
        | stale watcher | pending silicon | armed in `perf/when_up_r3.sh` |

        ## History
        `perf/when_up_r3.sh` named in narrative is exempt by design.
        """,
    "PERF.md": "see `perf/missing_probe.py`\n",
    "perf/real_r1.json": "{}",
    "perf/when_up_r3.sh": "#!/bin/sh\n",
    "perf/when_up_r9.sh": "#!/bin/sh\n",
}


def test_claims_findings_name_rotted_evidence(tmp_path):
    findings, _ = lint_tree(tmp_path, dict(CLAIMS_TREE))
    c1 = the(findings, "TCR-C001")
    assert {(f.path, f.line) for f in c1} == {("README.md", 6),
                                             ("PERF.md", 1)}
    c3 = the(findings, "TCR-C003")
    assert {f.line for f in c3} == {6, 7}
    c2 = the(findings, "TCR-C002")
    assert [(f.path, f.line) for f in c2] == [("README.md", 8)]
    assert "when_up_r9" in c2[0].message  # names the current watcher


def test_claims_clean_when_artifacts_committed(tmp_path):
    tree = dict(CLAIMS_TREE)
    tree["README.md"] = """\
        # x
        ## Measured vs pending silicon
        | claim | status | evidence |
        |---|---|---|
        | good row | **measured** | `perf/real_r1.json` |
        | armed | pending silicon | armed in `perf/when_up_r9.sh` |
        """
    tree["PERF.md"] = "see `perf/real_r1.json`\n"
    findings, _ = lint_tree(tmp_path, tree)
    for check in ("TCR-C001", "TCR-C002", "TCR-C003"):
        none_of(findings, check)


def test_real_repo_claims_are_consistent():
    """The shipped README/PERF cite only committed artifacts and the
    current recovery watcher (the first TCR-C audit fixed four stale
    when_up references in the claims table)."""
    from text_crdt_rust_tpu.analysis.checks_claims import check_claims

    assert [f.format() for f in check_claims(REPO)] == []


# ------------------------------------------- incremental: cache + changed ---


def test_cache_second_run_hits_and_mutation_invalidates(tmp_path):
    files = {"mod.py": "X = 1\n", "other.py": "Y = 2\n"}
    _, s1 = lint_tree(tmp_path, files, use_cache=True)
    assert s1["cache"] == {"hits": 0, "misses": 2}
    _, s2 = lint_tree(tmp_path, {}, use_cache=True)
    assert s2["cache"] == {"hits": 2, "misses": 0}
    (tmp_path / "mod.py").write_text("X = 3\n")
    _, s3 = lint_tree(tmp_path, {}, use_cache=True)
    assert s3["cache"] == {"hits": 1, "misses": 1}


def test_cache_reuses_findings_faithfully(tmp_path):
    files = {"mod.py": "import time\n\n\ndef f():\n"
                       "    return time.time()\n"}
    f1, _ = lint_tree(tmp_path, files, use_cache=True)
    f2, s2 = lint_tree(tmp_path, {}, use_cache=True)
    assert s2["cache"]["hits"] == 1
    assert [f.format() for f in f1] == [f.format() for f in f2]


def test_cache_invalidated_by_allowlist_change(tmp_path):
    """The config digest folds in the allowlist: granting a finding
    must not serve the stale cached verdict."""
    files = {"mod.py": "import time\n\n\ndef f():\n"
                       "    return time.time()\n"}
    f1, _ = lint_tree(tmp_path, files, use_cache=True)
    assert the(f1, "TCR-W001")
    f2, s2 = lint_tree(
        tmp_path, {}, use_cache=True,
        allow=[{"check": "TCR-W001", "path": "mod.py", "scope": "f",
                "why": "test probe grant for the cache invalidation"}],
        check_stale_allowlist=False)
    assert s2["cache"]["misses"] == 1  # digest changed -> re-lint
    none_of(f2, "TCR-W001")


def _git(cwd, *args):
    return subprocess.run(["git", "-C", str(cwd), *args],
                          capture_output=True, text=True, check=True)


def test_changed_files_against_a_real_merge_base(tmp_path):
    """--changed in a scratch git repo: only the edited file is
    selected, and the CLI lints exactly it."""
    if shutil.which("git") is None:
        pytest.skip("no git in container")
    repo = tmp_path / "r"
    repo.mkdir()
    _git(repo, "init", "-q", "-b", "main")
    _git(repo, "config", "user.email", "t@t")
    _git(repo, "config", "user.name", "t")
    (repo / "clean.py").write_text("A = 1\n")
    (repo / "dirty.py").write_text("B = 2\n")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (repo / "dirty.py").write_text(
        "import time\n\n\ndef f():\n    return time.time()\n")
    from text_crdt_rust_tpu.analysis.tcrlint import changed_files

    assert changed_files(str(repo)) == ["dirty.py"]
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--root", str(repo), "--changed", "HEAD", "--no-cache",
         "--allowlist", str(repo / "none.json"),
         "--pins", str(repo / "none_pins.json"),
         "--shape-pins", str(repo / "none_shape.json"),
         "--json", "dirty.py", "clean.py"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    out = json.loads(r.stdout)
    assert r.returncode == 1
    assert out["stats"]["files"] == 1  # clean.py not re-linted
    assert any("dirty.py:5: TCR-W001" in f for f in out["findings"])


def test_changed_mode_without_git_falls_back_to_full(tmp_path):
    (tmp_path / "mod.py").write_text("A = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--root", str(tmp_path), "--changed", "--no-cache",
         "--allowlist", str(tmp_path / "none.json"),
         "--pins", str(tmp_path / "none_pins.json"),
         "--shape-pins", str(tmp_path / "none_shape.json"),
         "--json", "mod.py"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    out = json.loads(r.stdout)
    assert out["stats"]["files"] == 1
    assert "fell back" in out["stats"]["mode"]


# ------------------------------------------- ruff F401 parity (satellite) ---


F401_FIXTURE = {
    "pkg/__init__.py": "from .mod_a import used_fn\n",
    "pkg/mod_a.py": """\
        import json
        import os  # noqa
        import sys
        from collections import OrderedDict, deque

        __all__ = ["deque"]


        def used_fn():
            return sys.argv
        """,
    "pkg/mod_b.py": "import zlib\n\nCRC = zlib.crc32(b'x')\n",
}

#: The pinned F401 floor on the fixture tree: (path, line, name).
#: __init__.py is exempt (re-export surface; mirrored in the ruff run
#: by pyproject's per-file-ignores), the noqa line is honored, __all__
#: membership is a use.
F401_EXPECTED = {
    ("pkg/mod_a.py", 1, "json"),
    ("pkg/mod_a.py", 4, "OrderedDict"),
}


def _fallback_findings(tmp_path):
    findings, _ = run_lint(str(tmp_path),
                           allowlist_path=str(tmp_path / "a.json"),
                           pins_path=str(tmp_path / "p.json"),
                           shape_pins_path=str(tmp_path / "sp.json"))
    out = set()
    for f in findings:
        if f.check != "TCR-F401":
            continue
        m = re.match(r"'([^']+)'", f.message)
        out.add((f.path, f.line, m.group(1)))
    return out


def test_f401_fallback_floor_is_pinned(tmp_path):
    """The container-dependent gate floor, pinned: the built-in
    fallback reports EXACTLY this finding set on the seeded fixture."""
    for rel, src in F401_FIXTURE.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    assert _fallback_findings(tmp_path) == F401_EXPECTED


def test_f401_fallback_matches_ruff_when_installed(tmp_path):
    """Parity with the real ruff F401 on the same fixture — the half
    that only runs where ruff exists; the pinned-floor test above
    keeps the contract checkable in ruff-less containers."""
    if shutil.which("ruff") is None:
        pytest.skip("ruff not installed — floor pinned by the "
                    "fallback test")
    for rel, src in F401_FIXTURE.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    r = subprocess.run(
        ["ruff", "check", "--isolated", "--select", "F401",
         "--per-file-ignores", "__init__.py:F401",
         "--output-format", "concise", "."],
        capture_output=True, text=True, cwd=tmp_path, timeout=120)
    got = set()
    for line in r.stdout.splitlines():
        m = re.match(r"(.+?):(\d+):\d+: F401 .*`([^`]+)`", line)
        if m:
            name = m.group(3).split(".")[-1]
            got.add((m.group(1).replace(os.sep, "/"),
                     int(m.group(2)), name))
    assert got == F401_EXPECTED


# ------------------------------------------- the incremental tier-1 gate ----


def test_lint_gate_incremental_under_budget():
    """ISSUE 15 acceptance: the tier-1 gate's incremental mode —
    ``--changed`` against the merge-base, warm cache — exits 0 on the
    clean tree in < 15 s (the full-tree clean proof lives in
    test_analysis_lint.py's gate test).  ``TCR_LINT_FULL=1`` is the
    weekly-style fallback knob: it drops ``--changed`` and forces the
    full walk through this same gate."""
    argv = [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
            "--json"]
    if not os.environ.get("TCR_LINT_FULL"):
        argv.insert(-1, "--changed")
    t0 = time.perf_counter()
    r = subprocess.run(argv, capture_output=True, text=True,
                       timeout=120, cwd=REPO)
    wall = time.perf_counter() - t0
    assert r.returncode == 0, (r.stdout[-4000:], r.stderr[-2000:])
    out = json.loads(r.stdout)
    assert out["ok"]
    assert wall < 15, f"incremental gate took {wall:.1f}s (budget 15s)"


def test_lint_gate_loud_through_cli_on_v2_families(tmp_path):
    """ONE violating tree exercises all four v2 families through the
    real CLI: exit 1, each finding file:line-named on stdout."""
    (tmp_path / "perf").mkdir()
    (tmp_path / "bad.py").write_text(textwrap.dedent("""\
        def tick(backend, stacked, pad_ops):
            backend.apply(stacked)
            stacked.pos[0] = 7
            return pad_ops(stacked, 48)
        """))
    (tmp_path / "README.md").write_text(textwrap.dedent("""\
        ## Measured vs pending silicon
        | claim | status | evidence |
        |---|---|---|
        | ghost | **measured** | `perf/ghost.json` |
        """))
    (tmp_path / "text_crdt_rust_tpu" / "serve").mkdir(parents=True)
    (tmp_path / "text_crdt_rust_tpu" / "serve" / "nb.py").write_text(
        textwrap.dedent("""\
            class NewBackend:
                def seed(self, b):
                    self.state = self.state.at[b].set(0)
            """))
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--root", str(tmp_path), "--no-cache",
         "--allowlist", str(tmp_path / "none.json"),
         "--pins", str(tmp_path / "none_pins.json"),
         "--shape-pins", SHAPE_PINS_PATH,
         "bad.py", "text_crdt_rust_tpu"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "bad.py:3: TCR-P001" in r.stdout
    assert "bad.py:4: TCR-K001" in r.stdout
    assert "README.md:4: TCR-C001" in r.stdout
    assert "README.md:4: TCR-C003" in r.stdout
    assert "nb.py:3: TCR-M002" in r.stdout


def test_sync_inside_a_branch_does_not_mask_other_branches(tmp_path):
    """Review hardening: a compound statement CONTAINING a sync call in
    one branch is not itself a sync — the mutation on the other branch
    still races the dispatch and must stay loud (only the bare sync
    statement blocks its own successors)."""
    findings, _ = lint_tree(tmp_path, {"mod.py": textwrap.dedent("""\
        def tick(backend, stacked, flag):
            backend.apply(stacked)
            if flag:
                backend.barrier()
            else:
                stacked.pos[0] = 1
        """)})
    f = the(findings, "TCR-P001")[0]
    assert f.line == 6
    # ...and the straight-line sync still kills propagation: the same
    # mutation AFTER the if (both paths joined past a barrier on one
    # side only) is still reachable via the else path.
    findings2, _ = lint_tree(tmp_path, {"mod2.py": textwrap.dedent("""\
        def tick(backend, stacked):
            backend.apply(stacked)
            backend.barrier()
            stacked.pos[0] = 1
        """)})
    none_of([f for f in findings2 if f.path == "mod2.py"], "TCR-P001")


def test_changed_mode_summary_source_edit_forces_full_walk(tmp_path):
    """Review hardening: a changed interprocedural summary source
    (ops/flat.py & co) can induce findings in UNCHANGED dependents, so
    --changed must widen to the full walk, not lint the source alone."""
    if shutil.which("git") is None:
        pytest.skip("no git in container")
    repo = tmp_path / "r"
    (repo / "text_crdt_rust_tpu" / "ops").mkdir(parents=True)
    _git(repo, "init", "-q", "-b", "main")
    _git(repo, "config", "user.email", "t@t")
    _git(repo, "config", "user.name", "t")
    (repo / "text_crdt_rust_tpu" / "ops" / "flat.py").write_text("A = 1\n")
    (repo / "dependent.py").write_text("B = 2\n")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (repo / "text_crdt_rust_tpu" / "ops" / "flat.py").write_text("A = 3\n")
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--root", str(repo), "--changed", "HEAD", "--no-cache",
         "--allowlist", str(repo / "none.json"),
         "--pins", str(repo / "none_pins.json"),
         "--shape-pins", str(repo / "none_shape.json"),
         "--json", "text_crdt_rust_tpu", "dependent.py"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    out = json.loads(r.stdout)
    assert "summary source" in out["stats"]["mode"]
    assert out["stats"]["files"] == 2  # the full target set, not 1


def test_try_else_block_is_flow_reachable(tmp_path):
    """Review hardening: the try body falls through to its else block
    (which runs exactly when no exception fired) — a post-dispatch
    mutation there must not be a CFG orphan."""
    findings, _ = lint_tree(tmp_path, {"mod.py": textwrap.dedent("""\
        def tick(backend, stacked):
            try:
                backend.apply(stacked)
            except ValueError:
                pass
            else:
                stacked.pos[0] = 1
        """)})
    f = the(findings, "TCR-P001")[0]
    assert f.line == 7


def test_keyword_shape_argument_checked_like_positional(tmp_path):
    """Review hardening: pad_ops' keyword spelling (num_steps=) goes
    through the same TCR-K001 resolution as the positional form."""
    findings, _ = lint_tree(tmp_path, {"mod.py": textwrap.dedent("""\
        def stage(stream, pad_ops):
            ok = pad_ops(stream, num_steps=32)
            bad = pad_ops(stream, num_steps=48)
            return ok, bad
        """)}, shape_pins=SHAPE_PINS_PATH)
    hits = the(findings, "TCR-K001")
    assert [f.line for f in hits] == [3]


def test_changed_with_bad_explicit_base_is_a_usage_error():
    """Review hardening: a typo'd --changed BASE exits 2 with a usage
    error instead of silently full-walking with a wrong diagnosis."""
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--changed", "no-such-ref-xyz", "--no-cache", "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 2
    assert "usage error" in r.stderr and "no-such-ref-xyz" in r.stderr

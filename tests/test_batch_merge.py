"""merge_patches semantics: the RLE-coalesced op stream must be
indistinguishable from the per-keystroke stream — same final content, same
spans (orders + tombstones), same order accounting. The merge is the
op-stream analog of the reference's in-tree merge fast paths
(`mutations.rs:57-109`); nothing about the CRDT result may change."""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.common import LocalOp
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.utils.testdata import (
    TestPatch,
    flatten_patches,
    load_testing_data,
    trace_path,
)


def replay_oracle(patches):
    doc = ListCRDT(capacity=256)
    agent = doc.get_or_create_agent_id("A")
    for p in patches:
        doc.apply_local_txn(agent, [LocalOp(p.pos, p.ins_content, p.del_len)])
    return doc


def assert_equivalent(patches):
    merged = B.merge_patches(patches)
    a = replay_oracle(patches)
    b = replay_oracle(merged)
    assert a.to_string() == b.to_string()
    assert a.doc_spans() == b.doc_spans()
    assert a.get_next_order() == b.get_next_order()
    return merged


def typing_run(pos, text):
    return [TestPatch(pos + i, 0, c) for i, c in enumerate(text)]


def backspace_run(pos, n):
    return [TestPatch(pos - i, 1, "") for i in range(1, n + 1)]


def test_typing_run_collapses():
    patches = typing_run(0, "hello world")
    merged = assert_equivalent(patches)
    assert len(merged) == 1
    assert merged[0] == TestPatch(0, 0, "hello world")


def test_backspace_run_collapses():
    patches = typing_run(0, "abcdef") + backspace_run(6, 3)
    merged = assert_equivalent(patches)
    assert merged == [TestPatch(0, 0, "abcdef"), TestPatch(3, 3, "")]


def test_forward_delete_run_collapses():
    patches = typing_run(0, "abcdef") + [TestPatch(1, 1, "")] * 3
    merged = assert_equivalent(patches)
    assert merged == [TestPatch(0, 0, "abcdef"), TestPatch(1, 3, "")]


def test_mixed_patch_breaks_runs():
    patches = typing_run(0, "abc") + [TestPatch(1, 1, "XY")] + \
        typing_run(2, "zz")
    merged = assert_equivalent(patches)
    # The replace patch can't merge with either neighbor run.
    assert len(merged) == 3


def test_discontiguous_inserts_stay_separate():
    patches = [TestPatch(0, 0, "aa"), TestPatch(0, 0, "bb")]
    merged = assert_equivalent(patches)
    assert len(merged) == 2


def test_random_stream_equivalence():
    rng = random.Random(7)
    content_len = 0
    patches = []
    for _ in range(800):
        r = rng.random()
        if content_len == 0 or r < 0.5:
            pos = rng.randint(0, content_len)
            ins = rng.choice("abcdefgh")
            patches.append(TestPatch(pos, 0, ins))
            content_len += 1
        else:
            pos = rng.randint(0, content_len - 1)
            patches.append(TestPatch(pos, 1, ""))
            content_len -= 1
    merged = assert_equivalent(patches)
    assert len(merged) < len(patches)


def test_trace_prefix_equivalence():
    data = load_testing_data(trace_path("automerge-paper"))
    patches = flatten_patches(data)[:4000]
    merged = assert_equivalent(patches)
    assert len(merged) * 4 < len(patches)  # real traces compress well


def test_order_accounting_preserved():
    data = load_testing_data(trace_path("automerge-paper"))
    patches = flatten_patches(data)[:4000]
    merged = B.merge_patches(patches)
    ops_a, next_a = B.compile_local_patches(patches, lmax=16)
    ops_b, next_b = B.compile_local_patches(merged, lmax=128)
    assert next_a == next_b
    assert (int(np.asarray(ops_a.order_advance, np.int64).sum())
            == int(np.asarray(ops_b.order_advance, np.int64).sum()))


def test_merge_does_not_mutate_input():
    patches = typing_run(0, "abc")
    snapshot = [TestPatch(p.pos, p.del_len, p.ins_content) for p in patches]
    B.merge_patches(patches)
    assert patches == snapshot

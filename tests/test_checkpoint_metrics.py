"""Checkpoint/resume round-trips and metrics sanity."""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.models import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since, merge_into
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.checkpoint import (
    load_doc,
    load_flat_doc,
    save_doc,
    save_flat_doc,
)
from text_crdt_rust_tpu.utils.metrics import (
    Throughput,
    doc_stats,
    memory_stats,
)
from text_crdt_rust_tpu.utils.testdata import TestPatch

from test_device_flat import oracle_from_patches, random_patches


def two_peer_doc(seed=3):
    rng = random.Random(seed)
    pa, _ = random_patches(rng, 60)
    pb, _ = random_patches(rng, 60)
    a = oracle_from_patches(pa, agent="peer-a")
    b = oracle_from_patches(pb, agent="peer-b")
    merge_into(a, b)
    return a


class TestOracleCheckpoint:
    def test_roundtrip_bit_identical(self, tmp_path):
        doc = two_peer_doc()
        p = str(tmp_path / "doc.npz")
        save_doc(doc, p)
        back = load_doc(p)
        back.check()
        assert back.to_string() == doc.to_string()
        assert back.doc_spans() == doc.doc_spans()
        assert back.frontier == doc.frontier
        assert list(back.deletes) == list(doc.deletes)
        assert list(back.double_deletes) == list(doc.double_deletes)
        assert list(back.txns) == list(doc.txns)
        assert list(back.client_with_order) == list(doc.client_with_order)
        assert [cd.name for cd in back.client_data] == [
            cd.name for cd in doc.client_data]

    def test_resume_keeps_editing_and_merging(self, tmp_path):
        # A restored doc must keep full CRDT function: local edits, export,
        # merge — the logs are the state (SURVEY §5).
        doc = two_peer_doc()
        p = str(tmp_path / "doc.npz")
        save_doc(doc, p)
        back = load_doc(p)

        a = back.get_or_create_agent_id("peer-a")
        back.local_insert(a, 0, "resumed:")
        other = ListCRDT()
        for t in export_txns_since(back, 0):
            other.apply_remote_txn(t)
        assert other.to_string() == back.to_string()
        assert other.to_string().startswith("resumed:")

    def test_device_warm_start_from_checkpoint(self, tmp_path):
        doc = two_peer_doc()
        p = str(tmp_path / "doc.npz")
        save_doc(doc, p)
        back = load_doc(p)
        table = B.AgentTable([cd.name for cd in back.client_data])
        flat = SA.upload_oracle(back, 1024, table.rank_of_agent())
        assert SA.to_string(flat) == doc.to_string()
        assert SA.doc_spans(flat) == doc.doc_spans()


class TestFlatDocCheckpoint:
    def test_roundtrip_and_resume_on_device(self, tmp_path):
        rng = random.Random(17)
        patches, content = random_patches(rng, 60)
        ops, next_order = B.compile_local_patches(patches, lmax=4)
        doc = F.apply_ops(SA.make_flat_doc(512), ops)
        p = str(tmp_path / "flat.npz")
        save_flat_doc(doc, p)
        back = load_flat_doc(p)
        assert SA.to_string(back) == content
        assert SA.doc_spans(back) == SA.doc_spans(doc)
        # Resume editing on device from the restored state.
        more, _ = B.compile_local_patches(
            [TestPatch(0, 0, "hi ")], start_order=next_order)
        out = F.apply_ops(back, more)
        assert SA.to_string(out) == "hi " + content


class TestMetrics:
    def test_doc_stats_oracle_vs_flat_agree(self):
        rng = random.Random(5)
        patches, _ = random_patches(rng, 80)
        oracle = oracle_from_patches(patches)
        ops, _ = B.compile_local_patches(patches, lmax=4)
        flat = F.apply_ops(SA.make_flat_doc(1024), ops)
        so, sf = doc_stats(oracle), doc_stats(flat)
        for k in ("items", "live", "tombstones", "merged_spans"):
            assert so[k] == sf[k], k
        assert so["compaction"] == pytest.approx(sf["compaction"])
        hist = so["span_histogram"]
        assert sum(hist.values()) == so["merged_spans"]

    def test_memory_stats(self):
        doc = two_peer_doc()
        m = memory_stats(doc)
        assert m["total_bytes"] == sum(m["columns"].values())
        assert m["efficient_bytes"] == 16 * doc_stats(doc)["merged_spans"]

    def test_throughput_meter(self):
        meter = Throughput()
        with meter.measure(ops=100):
            pass
        meter.add(900, 0.1)
        s = meter.summary()
        assert s["ops"] == 1000
        assert s["samples"] == 2
        assert meter.ops_per_sec > 0


class TestRunStats:
    def test_run_stats_on_rle_result(self):
        from text_crdt_rust_tpu.ops import rle as R
        from text_crdt_rust_tpu.utils.metrics import run_stats
        from text_crdt_rust_tpu.utils.testdata import TestPatch

        patches = [TestPatch(0, 0, "hello world"), TestPatch(5, 0, ","),
                   TestPatch(2, 3, "LLO"), TestPatch(0, 1, "H")]
        merged = B.merge_patches(patches)
        ops, _ = B.compile_local_patches(merged, lmax=16, dmax=None)
        res = R.replay_local_rle(ops, capacity=64, batch=8, block_k=8,
                                 chunk=16, interpret=True)
        st = run_stats(res)
        # Cross-check against the expanded per-char state.
        flat = R.expand_runs(res)
        assert st["chars"] == len(flat)
        assert st["live_chars"] == int((flat > 0).sum())
        assert st["run_rows"] == st["live_rows"] + st["tombstone_rows"]
        assert st["blocks_used"] >= 1
        assert 0 < st["block_fill"] <= 1
        assert st["chars_per_run"] > 1  # runs actually compress
        assert sum(st["run_histogram"].values()) == st["run_rows"]

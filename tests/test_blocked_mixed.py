"""Mixed-stream blocked engine (remote ops in-kernel) vs flat and oracle.

Interpreter-mode differential tests. Tiny blocks force rebalances between
remote lookups, exercising the stale-ordblk fallback search and its
self-healing; the scenarios mirror ``test_device_flat.TestRemoteApply``
(the `doc.rs:242-348` apply paths) plus the config-4 concurrent-insert
storm shape.
"""
import random

import pytest

from text_crdt_rust_tpu.common import (
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import blocked as BL
from text_crdt_rust_tpu.ops import blocked_mixed as BM
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import TestPatch

from test_device_flat import (
    oracle_from_patches,
    random_patches,
)

# Superseded per-char engine: differential reference only; excluded
# from the default run (see pytest.ini / README engine lineup).
pytestmark = pytest.mark.archival

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def replay_txns(txns, capacity, block_k=16, lmax=4, chunk=128):
    table = B.AgentTable()
    for t in txns:
        table.add(t.id.agent)
        for op in t.ops:
            if hasattr(op, "id"):
                table.add(op.id.agent)
    ops, _ = B.compile_remote_txns(txns, table, lmax=lmax, dmax=16)
    res = BM.replay_mixed(ops, capacity=capacity, batch=8,
                          block_k=block_k, chunk=chunk, interpret=True)
    return BL.blocked_to_flat(ops, res)


def oracle_txns(txns):
    doc = ListCRDT()
    for t in txns:
        doc.apply_remote_txn(t)
    return doc


class TestMixedLocal:
    def test_local_stream_matches_blocked(self):
        # KIND_LOCAL handling must stay bit-identical to ops.blocked.
        rng = random.Random(13)
        patches, content = random_patches(rng, 60)
        ops, _ = B.compile_local_patches(patches, lmax=4, dmax=4)
        res = BM.replay_mixed(ops, capacity=512, batch=8, block_k=16,
                              chunk=128, interpret=True)
        doc = BL.blocked_to_flat(ops, res)
        ref = BL.replay_local(ops, capacity=512, batch=8, block_k=16,
                              chunk=128, interpret=True)
        ref_doc = BL.blocked_to_flat(ops, ref)
        assert SA.to_string(doc) == SA.to_string(ref_doc) == content
        assert SA.doc_spans(doc) == SA.doc_spans(ref_doc)


class TestMixedRemote:
    def test_concurrent_root_inserts_tiebreak(self):
        # Config-4 storm shape: peers insert at the same point with the
        # same origins; order = the name tiebreak (`doc.rs:206-216`).
        txns = [
            RemoteTxn(id=RemoteId(name, 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, text)])
            for name, text in [("zed", "zz"), ("amy", "aa"), ("mia", "mm")]
        ]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=64, block_k=8)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    @pytest.mark.parametrize("seed", [3, 21])
    def test_two_peer_random_merge(self, seed):
        rng = random.Random(seed)
        pa, _ = random_patches(rng, 40)
        pb, _ = random_patches(rng, 40)
        a = oracle_from_patches(pa, agent="peer-a")
        bdoc = oracle_from_patches(pb, agent="peer-b")
        txns = export_txns_since(a, 0) + export_txns_since(bdoc, 0)
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=512, block_k=16)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_remote_delete_fragmented_and_double(self):
        base = RemoteTxn(id=RemoteId("amy", 0), parents=[],
                         ops=[RemoteIns(ROOT, ROOT, "abcdef")])
        d1 = RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 5)],
                       ops=[RemoteDel(RemoteId("amy", 1), 3)])
        d2 = RemoteTxn(id=RemoteId("cat", 0), parents=[RemoteId("amy", 5)],
                       ops=[RemoteDel(RemoteId("amy", 2), 3)])
        txns = [base, d1, d2]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=64, block_k=8)
        assert SA.to_string(doc) == oracle.to_string() == "af"
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_local_remote_convergence(self):
        # The reference's `remote_txns` convergence check (`doc.rs:620-676`).
        rng = random.Random(5)
        patches, _ = random_patches(rng, 60)
        local = oracle_from_patches(patches, agent="conv")
        txns = export_txns_since(local, 0)
        doc = replay_txns(txns, capacity=512, block_k=16)
        assert SA.to_string(doc) == local.to_string()
        assert SA.doc_spans(doc) == local.doc_spans()

    def test_storm_interleaved_peers(self):
        # N peers typing concurrently at interleaved positions, merged into
        # one causal stream — rebalances hit between remote integrations,
        # exercising the stale-index fallback + heal.
        rng = random.Random(99)
        peers = []
        for name in ("ada", "bea", "cyd", "dot"):
            patches, _ = random_patches(rng, 25)
            peers.append(oracle_from_patches(patches, agent=name))
        txns = []
        for p in peers:
            txns.extend(export_txns_since(p, 0))
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=1024, block_k=16)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_long_remote_delete_chunked(self):
        # A delete run longer than dmax=16 must chunk and still converge.
        base = RemoteTxn(id=RemoteId("amy", 0), parents=[],
                         ops=[RemoteIns(ROOT, ROOT, "x" * 50)])
        kill = RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 49)],
                         ops=[RemoteDel(RemoteId("amy", 5), 40)])
        txns = [base, kill]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=128, block_k=32, lmax=16)
        assert SA.to_string(doc) == oracle.to_string() == "x" * 10
        assert SA.doc_spans(doc) == oracle.doc_spans()

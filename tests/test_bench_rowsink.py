"""RowSink persistence/resume semantics (bench.py's crash-safety layer).

The driver's round-end `python bench.py` must never lose finished rows
to a mid-suite crash, resume into a different workload shape, or erase
rows it can't reuse — the exact failure modes that cost round 3 its
headline (VERDICT r3 weak #1/#2)."""
import json
import os

import pytest

from bench import RowSink


def read(path):
    with open(path) as f:
        return json.load(f)


def test_rows_persist_as_they_complete(tmp_path):
    p = str(tmp_path / "b.json")
    sink = RowSink(p, resume=False, variant="v1")
    sink.add("northstar", {"config": "ns", "value": 1.0})
    assert [r["cfg_key"] for r in read(p)] == ["northstar"]
    sink.add("2", [{"config": "a"}, {"config": "b"}])
    assert len(read(p)) == 3  # flushed after every config


def test_resume_skips_clean_rows_same_variant(tmp_path):
    p = str(tmp_path / "b.json")
    s1 = RowSink(p, resume=False, variant="v1")
    s1.add("northstar", {"config": "ns", "value": 1.0})
    s1.add("2", {"config": "c2", "error": "boom"})

    s2 = RowSink(p, resume=True, variant="v1")
    assert s2.done_keys == {"northstar"}     # error rows re-run
    s2.add("2", {"config": "c2", "value": 2.0})
    rows = read(p)
    assert {r["cfg_key"] for r in rows} == {"northstar", "2"}
    # the clean rerun replaced the error row
    c2 = [r for r in rows if r["cfg_key"] == "2"]
    assert len(c2) == 1 and "error" not in c2[0]


def test_resume_rejects_other_variant_but_preserves_rows(tmp_path):
    """A smoke row must not satisfy a full-size resume, and resuming
    with different flags must not erase results it can't reuse."""
    p = str(tmp_path / "b.json")
    s1 = RowSink(p, resume=False, variant="smoke=True")
    s1.add("northstar", {"config": "ns", "value": 1.0})

    s2 = RowSink(p, resume=True, variant="smoke=False")
    assert s2.done_keys == set()
    s2.add("northstar", {"config": "ns", "value": 9.0})
    rows = read(p)
    assert len(rows) == 2  # both variants on disk
    variants = {r["variant"] for r in rows}
    assert variants == {"smoke=True", "smoke=False"}


def test_superseded_rows_survive_until_rerun_records(tmp_path):
    """Crash window: a same-variant error row scheduled for re-run must
    stay in the file until its config ACTUALLY re-records — a crash
    before then must not have erased the only trace of the failure."""
    p = str(tmp_path / "b.json")
    s1 = RowSink(p, resume=False, variant="v1")
    s1.add("northstar", {"config": "ns", "value": 1.0})
    s1.add("2", {"config": "c2", "error": "boom"})

    s2 = RowSink(p, resume=True, variant="v1")
    # Simulate the suite completing a DIFFERENT config first, then
    # crashing: the old error row must still be on disk.
    s2.add("3", {"config": "c3", "value": 3.0})
    rows = read(p)
    assert any(r.get("cfg_key") == "2" and "error" in r for r in rows)
    # Once config 2 re-records, the stale error row is superseded.
    s2.add("2", {"config": "c2", "value": 2.0})
    c2 = [r for r in read(p) if r["cfg_key"] == "2"]
    assert len(c2) == 1 and "error" not in c2[0]


def test_flush_is_atomic(tmp_path):
    """flush writes tmp-then-rename; a reader never sees a torn file."""
    p = str(tmp_path / "b.json")
    sink = RowSink(p, resume=False, variant="v")
    sink.add("k", {"config": "x"})
    assert not os.path.exists(p + ".tmp")
    read(p)  # parses

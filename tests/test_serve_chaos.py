"""serve/chaos (ISSUE 16): deterministic crash injection, byzantine
traffic, and the flash-crowd scenario.

Every kill phase must recover to logical streams byte-identical to an
uncrashed same-seed twin, with the crash-boundary conservation audit
green — and the audit must be PROVEN loud by the journal-record-drop
injection (a silent hole the CRC chain cannot see).  The byzantine and
flash-crowd scenarios pin the admission edge's behavior under hostile
and pathological traffic: typed refusals, counted, never a panic.
"""
import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.serve import journal as J
from text_crdt_rust_tpu.serve.chaos import (PHASES, run_crash_scenario,
                                            run_crash_matrix)
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

SMALL = dict(ticks=8, docs=6, agents_per_doc=2, events_per_tick=10,
             seed=7, fault_rate=0.10, num_shards=2, lanes_per_shard=2)


def _assert_green(cell):
    assert cell["identical"], \
        f"recovered digest diverged from twin: {cell['digest']} " \
        f"vs {cell['twin_digest']}"
    assert cell["converged"] and cell["twin_converged"]
    assert cell["at_recovery_audit"]["audit_ok"], \
        cell["at_recovery_audit"]["findings"]
    assert cell["final_audit"]["audit_ok"], cell["final_audit"]["findings"]


@pytest.mark.parametrize("phase", PHASES)
def test_crash_phase_recovers_byte_identical(phase):
    kw = dict(SMALL)
    crash_tick = 3
    if phase == "mid-ckpt":
        # A checkpoint can only be torn once eviction pressure has
        # written one: more docs than lanes, crash later in the run.
        kw.update(ticks=9, docs=8, events_per_tick=12)
        crash_tick = 4
    cell = run_crash_scenario(phase, crash_tick, **kw)
    _assert_green(cell)
    assert cell["recover"]["ops"] > 0
    if phase in ("mid-journal", "mid-ckpt"):
        # The torn file must exist and be refused loudly, not absorbed.
        assert cell["torn"]
    if phase == "mid-journal":
        assert cell["recover"]["refusals"] >= 1


def test_crash_single_shard_torn_marker():
    """One shard means NO surviving duplicate of the torn TICK marker:
    recovery must re-derive the crashed tick live from the queued op
    records."""
    cell = run_crash_scenario("mid-journal", 3, ticks=8, docs=6,
                              agents_per_doc=2, events_per_tick=10,
                              seed=7, fault_rate=0.10, num_shards=1,
                              lanes_per_shard=4)
    _assert_green(cell)


def test_crash_clean_channel():
    """fault_rate 0: no anti-entropy traffic to mask recovery bugs."""
    cell = run_crash_scenario("post-dispatch", 3,
                              **{**SMALL, "fault_rate": 0.0})
    _assert_green(cell)


def test_journal_record_drop_is_loud():
    """THE loudness proof: rewrite the journal without one op record,
    CRCs re-chained so the storage layer cannot tell — the at-recovery
    conservation audit must report the hole as a crash-leak.  (The
    content digest would NOT catch this: the resumed anti-entropy cycle
    heals it, which is exactly why the audit runs first.)"""
    cell = run_crash_scenario("post-dispatch", 4,
                              **{**SMALL, "ticks": 9},
                              drop_record_kind=J.REC_TXNS, run_twin=False)
    assert cell["dropped_seq"] is not None
    audit = cell["at_recovery_audit"]
    assert not audit["audit_ok"], \
        "a silently dropped journal record went unnoticed"
    assert any(f["kind"] == "crash-leak" for f in audit["findings"])


@pytest.mark.slow
def test_crash_matrix_small():
    out = run_crash_matrix(crash_tick=3, ticks=9, docs=8,
                           agents_per_doc=2, events_per_tick=12, seed=7)
    assert out["ok"], {k: v for k, v in out["cells"].items()
                       if not v["green"]}


# -- byzantine traffic -------------------------------------------------------


def test_byzantine_traffic_rejected_typed_and_counted(tmp_path):
    """Every byzantine frame is either refused with a typed error
    (counted as a rejection) or absorbed as a duplicate — the tick loop
    never panics, the run still converges, and legitimate traffic is
    untouched (same-seed reports match a byzantine-free run op for op)."""
    kw = dict(docs=6, agents_per_doc=2, ticks=8, events_per_tick=10,
              seed=11, fault_rate=0.10)
    cfg = ServeConfig(num_shards=2, lanes_per_shard=2)
    clean = ServeLoadGen(cfg=cfg, **kw).run()
    assert clean["converged"]
    cfg2 = ServeConfig(num_shards=2, lanes_per_shard=2)
    gen = ServeLoadGen(cfg=cfg2, byzantine=0.5, **kw)
    report = gen.run()
    assert report["converged"], report["mismatches"]
    byz = report["byzantine"]
    assert byz["sent"] > 0
    assert byz["sent"] == byz["rejected"] + byz["absorbed"], \
        "a byzantine frame vanished untyped (neither refused nor absorbed)"
    assert byz["rejected"] > 0
    # The byzantine rng is a separate stream: the legitimate workload
    # is byte-identical, so the servers converge to the same ops.
    assert report["wire"]["ops_replicated"] == clean["wire"]["ops_replicated"]
    assert report["item_ops_applied"] == clean["item_ops_applied"]
    # Refusals were typed at the admission/codec edge, and the flight
    # recorder saw the first of each class instead of a panic.
    srv = report["server"]
    rejected = sum(v for k, v in srv.items()
                   if k.startswith("rejected_") and isinstance(v, int))
    assert rejected >= byz["rejected"]


def test_byzantine_with_journal_recovers(tmp_path):
    """Byzantine garbage must never reach the journal (only ADMITTED
    inputs are logged): a recovery after a hostile run replays clean."""
    from text_crdt_rust_tpu.serve.chaos import logical_stream_digest
    from text_crdt_rust_tpu.serve.server import DocServer
    cfg = ServeConfig(num_shards=2, lanes_per_shard=2,
                      journal_dir=str(tmp_path / "journal"),
                      spool_dir=str(tmp_path / "spool"))
    gen = ServeLoadGen(cfg=cfg, docs=4, agents_per_doc=2, ticks=6,
                       events_per_tick=8, seed=11, fault_rate=0.10,
                       byzantine=0.5)
    report = gen.run()
    assert report["converged"]
    want = logical_stream_digest(gen.server)
    cfg2 = ServeConfig(num_shards=2, lanes_per_shard=2,
                       journal_dir=cfg.journal_dir,
                       spool_dir=cfg.spool_dir)
    server2 = DocServer(cfg2)
    stats = server2.recover()
    assert stats["refusals"] == 0
    assert logical_stream_digest(server2) == want
    server2.close_obs()


# -- flash crowd -------------------------------------------------------------


def test_flash_crowd_survives_and_converges():
    """From the flash tick on, 90% of traffic slams one doc: lane
    overflow + residency thrash on the hot doc.  The run must converge
    at full fault rate — degrade to the host oracle if the lane
    overflows, never assert."""
    cfg = ServeConfig(num_shards=1, lanes_per_shard=2, lane_capacity=192,
                      order_capacity=384)
    gen = ServeLoadGen(cfg=cfg, docs=8, agents_per_doc=2, ticks=12,
                       events_per_tick=16, seed=11, fault_rate=0.10,
                       flash_crowd=(4, 2))
    report = gen.run()
    assert report["converged"], report["mismatches"]
    # The crowd concentrated: the hot doc absorbed most post-flash ops.
    hot = gen.worlds[2 % len(gen.worlds)]
    sizes = sorted(len(w.twin) for w in gen.worlds)
    assert len(hot.twin) == sizes[-1], \
        "flash crowd never concentrated on the hot doc"


def test_flash_crowd_preflash_identical():
    """The remap draws its rng AFTER the base picks: ticks before the
    flash point are byte-identical to a plain run."""
    kw = dict(docs=6, agents_per_doc=2, ticks=4, events_per_tick=10,
              seed=17, fault_rate=0.0)
    plain = ServeLoadGen(cfg=ServeConfig(num_shards=1, lanes_per_shard=6),
                         **kw).run()
    flash = ServeLoadGen(cfg=ServeConfig(num_shards=1, lanes_per_shard=6),
                         flash_crowd=(4, 0), **kw).run()
    assert plain["converged"] and flash["converged"]
    assert plain["wire"]["ops_replicated"] == flash["wire"]["ops_replicated"]
    assert plain["item_ops_applied"] == flash["item_ops_applied"]

"""Pin the 1026x-critical launch geometries (VERDICT r3 next #8).

The northstar row needs batch=256 / block_k=128 to compile and run; r2
lost 40% of its headline to a silent regression to batch 128.  These
interpret-mode tests pin the kernel CONSTRUCT mix at the big-batch lane
counts (reduced capacity — interpreter cost scales with capacity*batch,
and Mosaic-level compile coverage is ``perf/compile_pin.py``'s job on
the real chip).
"""
import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import TestPatch


def _patches():
    # Insert runs, a split, deletes incl. a boundary split — every
    # kernel path the northstar trace exercises.
    return [
        TestPatch(0, 0, "hello world"),
        TestPatch(5, 0, ", there"),
        TestPatch(2, 3, "LLO"),
        TestPatch(0, 1, "H"),
        TestPatch(4, 6, ""),
    ]


@pytest.mark.parametrize("batch", (256, 384))
def test_northstar_geometry_lanes_interpret(batch):
    """Pin the kernel CONSTRUCT MIX at the big-batch lane counts (the
    256-lane recorded row and the 384-lane measured-capacity geometry).
    Capacity stays tiny here — interpret cost scales with
    capacity*batch; the real 20,992/32,768-row shapes are exercised on
    chip by perf/sweep_r4.py and bench.py."""
    patches = _patches()
    merged = B.merge_patches(patches)
    ops, _ = B.compile_local_patches(merged, lmax=16, dmax=None)
    run = R.make_replayer_rle(ops, capacity=256, batch=batch, block_k=128,
                              chunk=64, interpret=True)
    res = run()
    want = ""
    for p in patches:
        want = want[:p.pos] + p.ins_content + want[p.pos + p.del_len:]
    got = SA.to_string(R.rle_to_flat(ops, res))
    assert got == want
    # Every lane must hold identical state (catches lane-indexing bugs
    # above the first 128/256 lanes).
    ordp = np.asarray(res.ordp)
    assert (ordp == ordp[:, :1]).all()


def test_config2_geometry_interpret():
    # Config 2's shape: block_k 256, batch 128 (the VMEM-bound config).
    patches = _patches()
    merged = B.merge_patches(patches)
    ops, _ = B.compile_local_patches(merged, lmax=16, dmax=None)
    run = R.make_replayer_rle(ops, capacity=512, batch=128, block_k=256,
                              chunk=64, interpret=True)
    got = SA.to_string(R.rle_to_flat(ops, run()))
    want = ""
    for p in patches:
        want = want[:p.pos] + p.ins_content + want[p.pos + p.del_len:]
    assert got == want

"""serve/router.py: frames -> per-doc causal queues; gap handling and
REQUEST emission inherited from the PR 1 stack."""
from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import (
    agent_watermarks,
    export_txns_since,
    state_digest,
)
from text_crdt_rust_tpu.net import codec
from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.serve.server import DocServer

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def cfg(**kw):
    base = dict(num_shards=2, lanes_per_shard=2, lane_capacity=128,
                order_capacity=256, step_buckets=(8, 32), max_txn_len=32)
    base.update(kw)
    return ServeConfig(**base)


def peer_history(n=3):
    """A small single-author history as ONE wire-ready txn per edit
    (exported per edit — a whole-history export would RLE-merge the
    linear spans into a single txn and defeat gap tests)."""
    doc = ListCRDT()
    a = doc.get_or_create_agent_id("amy")
    out, mark = [], 0
    for i in range(n):
        doc.local_insert(a, i, chr(ord("a") + i))
        out.extend(export_txns_since(doc, mark))
        mark = doc.get_next_order()
    assert len(out) == n
    return out, doc


def test_out_of_order_frames_buffer_then_release_in_order():
    srv = DocServer(cfg())
    srv.admit_doc("d")
    txns, src = peer_history(3)
    # Deliver txn 2 first: it must buffer (gap), not apply.
    srv.submit_frame("d", codec.encode_txns(txns[2:3]))
    doc = srv.doc_state("d")
    assert doc.buffer.pending == 1 and not doc.events
    # The server owes a REQUEST naming the gap.
    req = srv.poll_request_frame("d")
    kind, wants, _ = codec.decode_frame(req)
    assert kind == codec.KIND_REQUEST and wants == {"amy": 0}
    # Backfill arrives; everything releases, in causal order.
    srv.submit_frame("d", codec.encode_txns(txns[0:2]))
    assert doc.buffer.pending == 0 and len(doc.events) == 3
    srv.tick()
    assert srv.doc_string("d") == src.to_string()
    assert srv.poll_request_frame("d") is None


def test_duplicate_frames_dedup():
    srv = DocServer(cfg())
    srv.admit_doc("d")
    txns, src = peer_history(2)
    frame = codec.encode_txns(txns)
    srv.submit_frame("d", frame)
    srv.submit_frame("d", frame)   # exact duplicate delivery
    srv.tick()
    assert srv.doc_string("d") == src.to_string()
    assert srv.doc_state("d").buffer.duplicates_dropped > 0


def test_digest_reveals_fully_dropped_agent():
    """An agent whose EVERY frame was lost is invisible to the causal
    buffer; only the digest gossip can name the gap."""
    srv = DocServer(cfg())
    srv.admit_doc("d")
    _, src = peer_history(3)
    assert srv.poll_request_frame("d") is None
    srv.submit_frame("d", codec.encode_digest(
        agent_watermarks(src), state_digest(src)))
    req = srv.poll_request_frame("d")
    kind, wants, _ = codec.decode_frame(req)
    assert wants == {"amy": 0}


def test_request_frames_are_served_from_the_oracle():
    srv = DocServer(cfg())
    srv.admit_doc("d")
    txns, src = peer_history(3)
    srv.submit_frame("d", codec.encode_txns(txns))
    srv.tick()
    # A fresh replica asks for everything from seq 0.
    out = srv.submit_frame("d", codec.encode_request({"amy": 0}))
    assert out, "REQUEST not served"
    replica = ListCRDT()
    for frame in out:
        kind, value, _ = codec.decode_frame(frame)
        assert kind == codec.KIND_TXNS
        for t in value:
            replica.apply_remote_txn(t)
    assert replica.to_string() == src.to_string()


def test_shard_assignment_is_stable_and_balanced():
    srv = DocServer(cfg(num_shards=2))
    for i in range(8):
        srv.admit_doc(f"d{i}")
    shards = [srv.router.shard_lane(f"d{i}")[0] for i in range(8)]
    assert sorted(set(shards)) == [0, 1]
    assert abs(shards.count(0) - shards.count(1)) <= 1
    # Stable across traffic.
    srv.submit_local("d3", "e", 0, ins_content="hi")
    srv.tick()
    assert srv.router.shard_lane("d3")[0] == shards[3]


def test_invalid_reference_txn_rejected_not_crash():
    """A structurally-valid txn whose origin names a nonexistent item
    must be dropped typed-and-counted, never an oracle assert."""
    srv = DocServer(cfg())
    srv.admit_doc("d")
    bad = RemoteTxn(
        id=RemoteId("mallory", 0), parents=[ROOT],
        ops=[RemoteIns(RemoteId("ghost", 5), ROOT, "x")])
    srv.submit_frame("d", codec.encode_txns([bad]))
    srv.tick()
    assert srv.counters.get("txns_rejected") == 1
    assert srv.doc_string("d") == ""
    # An honest txn still lands afterwards.
    txns, src = peer_history(2)
    srv.submit_frame("d", codec.encode_txns(txns))
    srv.tick()
    assert srv.doc_string("d") == src.to_string()


def test_frame_admission_is_all_or_nothing():
    """A mid-frame admission refusal must leave nothing enqueued
    (two-phase check-then-ingest; the AdmissionError contract)."""
    import pytest

    from text_crdt_rust_tpu.serve.admission import AdmissionError

    srv = DocServer(cfg(max_queue_per_doc=2))
    srv.admit_doc("d")
    txns, _ = peer_history(4)
    with pytest.raises(AdmissionError) as e:
        srv.submit_frame("d", codec.encode_txns(txns))  # 4 txns > bound 2
    assert e.value.reason == "queue-full"
    assert srv.doc_state("d").pending() == 0, "partial frame enqueued"
    assert srv.counters.get("admitted") == 0


def test_latency_stamped_at_admission_not_release():
    """A txn held in the causal buffer keeps its ORIGINAL admission
    stamp: the buffer wait is inside admission->applied latency."""
    import time

    srv = DocServer(cfg())
    srv.admit_doc("d")
    txns, _ = peer_history(2)
    srv.submit_frame("d", codec.encode_txns(txns[1:2]))  # gap: buffers
    t_blocked = time.perf_counter()
    time.sleep(0.05)
    srv.submit_frame("d", codec.encode_txns(txns[0:1]))  # releases both
    doc = srv.doc_state("d")
    assert len(doc.events) == 2
    # txn 1 (second event, released by the backfill) was admitted BEFORE
    # the sleep; its stamp must predate the backfill submission.
    assert doc.events[1].t_submit <= t_blocked
    assert doc.events[0].t_submit > t_blocked


def test_rejected_events_do_not_count_as_applied():
    """Rejected txns and invalid local edits are dequeued but feed
    neither ops_applied nor the latency samples."""
    srv = DocServer(cfg())
    srv.admit_doc("d")
    bad = RemoteTxn(
        id=RemoteId("mallory", 0), parents=[ROOT],
        ops=[RemoteIns(RemoteId("ghost", 5), ROOT, "xyz")])
    srv.submit_txn("d", bad)
    stats = srv.tick()
    assert stats["ops_applied"] == 0 and stats["events_applied"] == 0
    assert srv.batcher.latency_samples == []
    assert srv.counters.get("txns_rejected") == 1

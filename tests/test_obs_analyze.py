"""obs/analyze (ISSUE 10): phase breakdown, hot-doc/fusion tables,
recompile timeline, two-trace logical diff and the Chrome trace-event
export — all against the COMMITTED trace fixture
(``tests/data/obs_trace_fixture.jsonl``, a tiny seeded loadgen run)
and its golden outputs, so any analytics drift shows as a golden diff
rather than a silent behavior change."""
import json
import os
import subprocess
import sys

from text_crdt_rust_tpu.obs import analyze as A
from text_crdt_rust_tpu.obs.trace import WALL_KEY, validate_event

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(HERE, "data", "obs_trace_fixture.jsonl")
FIXTURE_B = os.path.join(HERE, "data", "obs_trace_fixture_b.jsonl")
GOLDEN = os.path.join(HERE, "data", "obs_trace_fixture_golden.json")


def events():
    return A.load_events([FIXTURE])


def golden():
    with open(GOLDEN) as f:
        return json.load(f)


def test_fixture_is_schema_valid():
    evs = events()
    assert evs[0]["k"] == "trace.header"
    for ev in evs:
        validate_event(ev)
    # The fixture exercises every analytics surface.
    kinds = {e["k"] for e in evs}
    assert {"apply", "tick.fuse", "device.compile",
            "tick.device", "tick.barrier"} <= kinds


def test_phase_breakdown_matches_golden():
    d = A.phase_breakdown(events())
    assert d == golden()["phases"]
    # Structural floor independent of the golden: all five phases
    # reported, shares sum to ~100 where wall exists.
    assert set(d["phases"]) == set(A.PHASES)
    assert d["ticks"] > 0
    assert abs(sum(p["share_pct"] for p in d["phases"].values())
               - 100.0) < 0.5


def test_hotdocs_fuse_recompiles_match_golden():
    g = golden()
    assert A.hot_docs(events(), top=5) == g["hotdocs"]
    fuse = A.fusion_table(events(), top=5)
    assert fuse == g["fuse"]
    assert fuse["rows_saved"] == fuse["steps_in"] - fuse["steps_out"]
    rec = A.recompile_timeline(events())
    assert rec == g["recompiles"]
    assert rec["compiles"] >= 1
    # Steady state: the fixture's compiles are all warm-up ticks.
    assert rec["last_compile_tick"] <= rec["run_last_tick"]


def test_two_trace_diff_names_first_diverging_event():
    a, b = events(), A.load_events([FIXTURE_B])
    assert A.trace_diff(a, a) is None
    d = A.trace_diff(a, b)
    assert d == golden()["diff_vs_b"]
    assert d["fields"] == ["n"]
    assert d["a"]["k"] == "apply"
    assert d["index"] == d["a"]["i"]  # logical seq == stream index here


def test_diff_ignores_wall_and_catches_length_drift():
    a = events()
    walled = [dict(e) for e in a]
    for e in walled:
        e[WALL_KEY] = {"ms": 123.0}  # pure wall noise
    assert A.trace_diff(a, walled) is None
    d = A.trace_diff(a, a[:-1])
    assert d["only_in"] == "a" and d["index"] == len(a) - 1


def test_chrome_export_is_valid_trace_event_json(tmp_path):
    doc = A.chrome_trace(events())
    # Round-trippable JSON with the trace-event envelope.
    doc = json.loads(json.dumps(doc))
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    spans = 0
    for te in doc["traceEvents"]:
        assert "name" in te and "ph" in te and "pid" in te
        assert te["ph"] in ("X", "i", "M")
        if te["ph"] != "M":
            assert isinstance(te["ts"], (int, float))
        if te["ph"] == "X":
            spans += 1
            assert te["dur"] >= 0
    assert spans >= 4  # the measured wall spans survived the export
    # Wall spans sit on the LOGICAL tick axis (tick * pitch).
    first_span = next(t for t in doc["traceEvents"] if t["ph"] == "X")
    assert first_span["ts"] >= A.CHROME_TICK_US  # tick 1+


def test_load_events_reads_bundles_and_segment_lists(tmp_path):
    """The same analytics run over flight-recorder bundle JSONs (their
    ``events`` list is the trace schema) and over rotated segment
    lists, concatenating in order."""
    evs = events()
    bundle = str(tmp_path / "bundle_x.json")
    with open(bundle, "w") as f:
        json.dump({"schema_version": 1, "reason": "divergence",
                   "events": evs[:10]}, f, indent=1)
    assert A.load_events([bundle]) == evs[:10]
    # Two "segments" (a split of the fixture) reload as one stream.
    seg1, seg2 = str(tmp_path / "t.jsonl"), str(tmp_path / "t.jsonl.1")
    lines = open(FIXTURE).read().splitlines()
    with open(seg1, "w") as f:
        f.write("\n".join(lines[:20]) + "\n")
    with open(seg2, "w") as f:
        f.write("\n".join(lines[20:]) + "\n")
    assert A.load_events([seg1, seg2]) == evs


def test_load_events_keeps_prefix_of_crash_truncated_segment(tmp_path):
    """A process dying mid-write leaves a partial final line — exactly
    the artifact a post-mortem reads.  load_events must return the
    valid prefix, not refuse the file."""
    full = open(FIXTURE).read()
    lines = full.splitlines()
    trunc = str(tmp_path / "trunc.jsonl")
    with open(trunc, "w") as f:
        f.write("\n".join(lines[:30]) + "\n" + lines[30][:17])
    evs = A.load_events([trunc])
    assert evs == events()[:30]
    # Same tolerance mid-file (a flipped byte): valid prefix survives.
    corrupt = str(tmp_path / "corrupt.jsonl")
    with open(corrupt, "w") as f:
        f.write("\n".join(lines[:10]) + "\n{not json}\n"
                + "\n".join(lines[10:]) + "\n")
    assert A.load_events([corrupt]) == events()[:10]


def test_cli_end_to_end(tmp_path):
    """The CLI surface: phases + diff (exit 1 on divergence) + chrome
    file output, one subprocess each on the tiny fixture."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(HERE)
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.obs.analyze",
         "phases", FIXTURE, "--json"],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env)
    assert r.returncode == 0, r.stderr[-800:]
    assert json.loads(r.stdout) == golden()["phases"]
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.obs.analyze",
         "diff", FIXTURE, FIXTURE_B],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env)
    assert r.returncode == 1
    assert "first divergence at event" in r.stdout
    out = str(tmp_path / "chrome.json")
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.obs.analyze",
         "chrome", FIXTURE, "-o", out],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env)
    assert r.returncode == 0
    with open(out) as f:
        assert json.load(f)["traceEvents"]


def test_stall_budget_names_top_phase():
    """ISSUE 12 satellite: the one-number stall headline derives from
    the breakdown (top phase by total wall + its share)."""
    d = A.phase_breakdown(events())
    b = A.stall_budget(d)
    assert b["phase"] in A.PHASES
    assert b["wall_ms"] == d["phases"][b["phase"]]["wall_ms"]
    assert b["wall_ms"] == max(r["wall_ms"] for r in d["phases"].values())
    assert b["share_pct"] == d["phases"][b["phase"]]["share_pct"]
    empty = A.stall_budget(A.phase_breakdown([]))
    assert empty["phase"] is None and empty["wall_ms"] == 0.0


def test_overlap_report_accounting():
    """ISSUE 12: host-vs-device occupancy from synthetic events with
    known walls — stall, window, host and dispatch sums are exact, and
    overlap_frac = win / (win + stall)."""
    evs = [
        {"i": 0, "t": 1, "k": "tick.drain", "shard": 0, "events": 3,
         "steps": 5, WALL_KEY: {"ms": 4.0}},
        {"i": 1, "t": 1, "k": "residency.evict", "doc": "d", "ckpt":
         "delta", "bytes": 10, WALL_KEY: {"ms": 2.0}},
        {"i": 2, "t": 1, "k": "tick.device", "shard": 0, "bucket": 8,
         "lanes": 1, "steps": 5, WALL_KEY: {"ms": 1.0}},
        {"i": 3, "t": 1, "k": "tick.barrier", "shard": 0,
         WALL_KEY: {"ms": 3.0, "win": 9.0}},
        {"i": 4, "t": 2, "k": "tick.drain", "shard": 0, "events": 1,
         "steps": 1, WALL_KEY: {"ms": 6.0}},
        {"i": 5, "t": 2, "k": "tick.barrier", "shard": 0,
         WALL_KEY: {"ms": 1.0, "win": 7.0}},
    ]
    d = A.overlap_report(evs)
    assert d["ticks"] == 2
    assert d["host_ms"] == 12.0      # drain 4+6 + evict 2
    assert d["dispatch_ms"] == 1.0
    assert d["stall_ms"] == 4.0
    assert d["win_ms"] == 16.0
    assert d["overlap_frac"] == round(16.0 / 20.0, 4)
    assert d["idle_gap_ms"]["max"] == 3.0
    assert d["worst_ticks"][0]["tick"] == 1
    # Serial traces (no "win" key) read frac 0 over pure stall.
    serial = A.overlap_report([
        {"i": 0, "t": 1, "k": "tick.barrier", "shard": 0,
         WALL_KEY: {"ms": 5.0}}])
    assert serial["overlap_frac"] == 0.0
    assert serial["stall_share_pct"] == 100.0


def test_overlap_cli_runs(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.obs.analyze",
         "overlap", FIXTURE, "--json"],
        capture_output=True, text=True)
    assert out.returncode == 0
    d = json.loads(out.stdout)
    assert {"ticks", "overlap_frac", "idle_gap_ms"} <= set(d)
    budget = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.obs.analyze",
         "phases", FIXTURE, "--stall-budget"],
        capture_output=True, text=True)
    assert budget.returncode == 0
    assert "stall budget:" in budget.stdout

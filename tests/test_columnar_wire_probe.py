"""perf/columnar_wire_probe.py: the ISSUE-7 bytes-cut proof stays
runnable (tier-1 smoke at a tiny shape) and the committed claims stay
consistent with the checked-in JSON (slow tier re-reads the artifact).
"""
import json
import os

import pytest

import perf.columnar_wire_probe as probe


# Slow tier since PR 17 (wall budget: ~25 s of the 870 s gate): wire
# codec correctness keeps tier-1 coverage in test_net_codec /
# test_net_faults; the committed-claims check below was always slow.
@pytest.mark.slow
def test_probe_smoke_matrix_holds():
    """The probe's small-scale path: every cell converges, the op
    counts match across protocol generations, and the columnar wire
    ships fewer txn bytes than the row wire in every cell."""
    out = probe.run_matrix(smoke=True)
    assert out["claims"]["all_converged"]
    for cell, data in out["cells"].items():
        assert data["bytes_per_op_columnar"] < data["bytes_per_op_row"], cell
        v1 = data["runs"]["row"]
        v2 = data["runs"]["columnar"]
        assert v1["wire"]["ops_replicated"] == v2["wire"]["ops_replicated"]
        # Delta checkpoints engage wherever re-evictions happened.
        if v2["ckpt_saves_delta"]:
            assert 0 < data["ckpt_delta_bytes_per_evict"] \
                < data["ckpt_full_bytes_per_evict"]


@pytest.mark.slow
def test_committed_probe_claims():
    """The checked-in perf/columnar_wire_r10.json meets the ISSUE-7
    floors it claims (the acceptance bar, re-validated from the
    artifact, not the code)."""
    path = os.path.join(os.path.dirname(__file__), "..", "perf",
                        "columnar_wire_r10.json")
    with open(path) as f:
        out = json.load(f)
    claims = out["claims"]
    assert claims["wire_cut_meets_floor"]
    assert claims["wire_cut_headline_x"] >= claims["floor_x"]
    assert claims["ckpt_cut_meets_floor"]
    assert claims["all_converged"]
    # The headline numbers trace back to real cells.
    assert claims["wire_cut_headline_x"] in \
        claims["wire_bytes_cut_x"].values()

"""serve/loadgen.py: the closed-loop serving fuzz — Zipf traffic, fault
injection, forced evictions — must end bit-identical everywhere.

Tier-1 runs a compressed shape (fewer docs/ticks, lanes sized so
eviction pressure is guaranteed); the ``slow`` tier runs the full
ISSUE-3 acceptance shape (>=200 docs, >=3 agents/doc, >=20 evictions,
10% per-class faults).
"""
import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen


def run_and_check(**kw):
    gen = ServeLoadGen(**kw)
    report = gen.run()
    assert report["converged"], report["mismatches"]
    return report


def test_loadgen_converges_with_faults_and_evictions():
    cfg = ServeConfig(num_shards=1, lanes_per_shard=6, lane_capacity=256,
                      order_capacity=512)
    report = run_and_check(
        docs=24, agents_per_doc=3, ticks=14, events_per_tick=16,
        zipf_alpha=1.1, fault_rate=0.10, local_prob=0.25, seed=11,
        cfg=cfg)
    srv = report["server"]
    assert srv["evictions"] >= 5, "lane pressure too low to test eviction"
    assert srv["restores"] >= 5
    assert srv["rejected_frame_rejected"] > 0, "faults never injected?"
    assert report["latency_us"]["samples"] > 0
    assert 0 < srv["batch_fill_ratio_mean"] <= 1


def test_loadgen_clean_channel_seeds_differ():
    """No faults, different seed: still converges (the checker is not
    fault-dependent) and rejects nothing at the codec layer."""
    cfg = ServeConfig(num_shards=2, lanes_per_shard=4)
    report = run_and_check(
        docs=12, agents_per_doc=2, ticks=8, events_per_tick=10,
        fault_rate=0.0, seed=23, cfg=cfg)
    assert report["server"].get("rejected_frame_rejected", 0) == 0


@pytest.mark.slow
def test_loadgen_acceptance_shape():
    """The ISSUE-3 acceptance criterion, verbatim: >=200 docs, >=3
    agents/doc, Zipf popularity forcing >=20 evictions, 10% per-class
    fault injection — every doc bit-identical to its host-oracle twin.
    """
    cfg = ServeConfig(num_shards=2, lanes_per_shard=16)
    report = run_and_check(
        docs=200, agents_per_doc=3, ticks=60, events_per_tick=48,
        zipf_alpha=1.1, fault_rate=0.10, local_prob=0.25, seed=7,
        cfg=cfg)
    assert report["server"]["evictions"] >= 20

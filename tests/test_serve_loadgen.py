"""serve/loadgen.py: the closed-loop serving fuzz — Zipf traffic, fault
injection, forced evictions — must end bit-identical everywhere.

Tier-1 runs a compressed shape (fewer docs/ticks, lanes sized so
eviction pressure is guaranteed); the ``slow`` tier runs the full
ISSUE-3 acceptance shape (>=200 docs, >=3 agents/doc, >=20 evictions,
10% per-class faults).
"""
import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen


def run_and_check(**kw):
    gen = ServeLoadGen(**kw)
    report = gen.run()
    assert report["converged"], report["mismatches"]
    return report


def test_loadgen_converges_with_faults_and_evictions():
    cfg = ServeConfig(num_shards=1, lanes_per_shard=6, lane_capacity=256,
                      order_capacity=512)
    report = run_and_check(
        docs=24, agents_per_doc=3, ticks=14, events_per_tick=16,
        zipf_alpha=1.1, fault_rate=0.10, local_prob=0.25, seed=11,
        cfg=cfg)
    srv = report["server"]
    assert srv["evictions"] >= 5, "lane pressure too low to test eviction"
    assert srv["restores"] >= 5
    assert srv["rejected_frame_rejected"] > 0, "faults never injected?"
    assert report["latency_us"]["samples"] > 0
    assert 0 < srv["batch_fill_ratio_mean"] <= 1


def test_loadgen_clean_channel_seeds_differ():
    """No faults, different seed: still converges (the checker is not
    fault-dependent) and rejects nothing at the codec layer."""
    cfg = ServeConfig(num_shards=2, lanes_per_shard=4)
    report = run_and_check(
        docs=12, agents_per_doc=2, ticks=8, events_per_tick=10,
        fault_rate=0.0, seed=23, cfg=cfg)
    assert report["server"].get("rejected_frame_rejected", 0) == 0


def test_wire_ckpt_matrix_small():
    """ISSUE-7: row/full vs columnar/delta on the same seed — both
    converge bit-identically to their twins, the byte counters are
    exported, the replicated op count is identical (traffic is
    protocol-independent), and the v2 wire ships fewer txn bytes."""
    reports = {}
    for wire, ckpt in (("row", "full"), ("columnar", "delta")):
        cfg = ServeConfig(num_shards=1, lanes_per_shard=6,
                          lane_capacity=256, order_capacity=512,
                          wire_format=wire, ckpt_format=ckpt)
        reports[wire] = run_and_check(
            docs=16, agents_per_doc=3, ticks=16, events_per_tick=16,
            zipf_alpha=1.1, fault_rate=0.10, seed=11, cfg=cfg)
    row, col = reports["row"], reports["columnar"]
    assert row["wire"]["format"] == "row"
    assert col["wire"]["format"] == "columnar"
    assert row["wire"]["ops_replicated"] == col["wire"]["ops_replicated"]
    assert 0 < col["wire"]["txn_bytes"] < row["wire"]["txn_bytes"]
    assert col["wire"]["bytes_per_op"] < row["wire"]["bytes_per_op"]
    # Delta checkpoints: the first evict of a doc is a full base, warm
    # re-evictions are deltas; both kinds must appear under this much
    # lane pressure, and the byte counters must flow into the report.
    assert row["ckpt"]["saves_full"] > 0 and row["ckpt"]["saves_delta"] == 0
    assert col["ckpt"]["saves_delta"] > 0
    assert col["ckpt"]["bytes_written"] > 0
    assert "wire_bytes_in" in reports["columnar"]["tick_ms"] or \
        "wire_bytes_in" in reports["columnar"]["server"]


def test_typing_workload_converges():
    """The typing workload (cursor runs — the real-editing shape) on
    the columnar+delta path, twin-checked."""
    cfg = ServeConfig(num_shards=1, lanes_per_shard=6, lane_capacity=384,
                      order_capacity=768)
    report = run_and_check(
        docs=12, agents_per_doc=3, ticks=12, events_per_tick=12,
        fault_rate=0.10, seed=5, cfg=cfg, workload="typing")
    assert report["wire"]["workload"] == "typing"
    assert report["wire"]["txn_bytes"] > 0


@pytest.mark.slow
def test_loadgen_acceptance_shape():
    """The ISSUE-3 acceptance criterion, verbatim: >=200 docs, >=3
    agents/doc, Zipf popularity forcing >=20 evictions, 10% per-class
    fault injection — every doc bit-identical to its host-oracle twin.
    """
    cfg = ServeConfig(num_shards=2, lanes_per_shard=16)
    report = run_and_check(
        docs=200, agents_per_doc=3, ticks=60, events_per_tick=48,
        zipf_alpha=1.1, fault_rate=0.10, local_prob=0.25, seed=7,
        cfg=cfg)
    assert report["server"]["evictions"] >= 20

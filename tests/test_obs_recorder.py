"""obs/recorder (ISSUE 8): typed-failure post-mortem bundles and the
injected-divergence acceptance test.

The acceptance bar: a deliberately injected divergence — one decoded
txn byte flipped PAST the CRC check (i.e., corruption the wire codec
cannot see, the class of bug only the twin check catches) — must
produce a post-mortem bundle that names the exact logical tick, doc,
and apply event where the twin first diverged."""
import dataclasses
import glob
import json
import os

import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.net import codec
from text_crdt_rust_tpu.obs.recorder import FlightRecorder, first_divergence
from text_crdt_rust_tpu.obs.registry import MetricsRegistry
from text_crdt_rust_tpu.obs.trace import Tracer
from text_crdt_rust_tpu.serve.admission import AdmissionError
from text_crdt_rust_tpu.serve.server import DocServer


def small_server(tmp_path, **cfg_kw):
    cfg = ServeConfig(num_shards=1, lanes_per_shard=2,
                      obs_dir=str(tmp_path / "obs"), **cfg_kw)
    return DocServer(cfg)


def peer_history():
    """A small single-agent history + its export."""
    peer = ListCRDT()
    aid = peer.get_or_create_agent_id("alice")
    peer.local_insert(aid, 0, "hello ")
    peer.local_insert(aid, 6, "world")
    return export_txns_since(peer, 0)


# ------------------------------------------- the acceptance scenario -----


def test_injected_divergence_postmortem_names_tick_doc_event(tmp_path):
    """Flip one decoded txn byte past CRC; the bundle must name the
    exact logical tick, doc, and event where the twin first diverged."""
    srv = small_server(tmp_path)
    srv.admit_doc("d0")
    twin = ListCRDT()
    txns = peer_history()
    for t in txns:
        twin.apply_remote_txn(t)

    # Encode -> decode (CRC VALIDATES) -> tamper the decoded content ->
    # submit: corruption the codec provably cannot catch.
    frame = codec.encode_txns(txns)
    kind, decoded, _ = codec.decode_frame(frame)
    assert kind == codec.KIND_TXNS
    t0, op = decoded[0], decoded[0].ops[0]
    flip = 7  # 'o' of "world" -> seq 7 within alice's txn
    bad = (op.ins_content[:flip]
           + chr(ord(op.ins_content[flip]) ^ 0x1)
           + op.ins_content[flip + 1:])
    decoded[0] = dataclasses.replace(
        t0, ops=[dataclasses.replace(op, ins_content=bad)])
    for t in decoded:
        srv.submit_txn("d0", t)
    srv.tick()
    srv.drain()
    assert srv.doc_string("d0") != twin.to_string()

    path = srv.recorder.on_divergence(
        "d0", srv.doc_state("d0").oracle, twin)
    bundle = json.load(open(path))
    assert bundle["schema_version"] == 1
    assert bundle["reason"] == "divergence"
    assert bundle["doc"] == "d0"
    fd = bundle["first_divergence"]
    # The exact diverging item, named peer-portably.
    assert (fd["agent"], fd["seq"]) == ("alice", flip)
    assert fd["server"]["char"] != fd["twin"]["char"]
    # ... joined to the apply event: the txn applied on logical tick 1,
    # and the trace event index points into the recorded stream.
    ae = bundle["apply_event"]
    assert ae is not None and ae["tick"] == 1
    assert ae["agent"] == "alice"
    assert ae["seq"] <= flip < ae["seq"] + ae["n"]
    assert any(e["i"] == ae["event"] and e["k"] == "apply"
               for e in bundle["events"])
    # Counters + compiled-step metadata rode along.
    assert bundle["counters"]["admitted"] >= 1
    assert bundle["compiled_step_meta"]["tick"] == 1


def test_first_divergence_walk_cases():
    a, b = ListCRDT(), ListCRDT()
    ai = a.get_or_create_agent_id("x")
    bi = b.get_or_create_agent_id("x")
    a.local_insert(ai, 0, "abc")
    b.local_insert(bi, 0, "abc")
    assert first_divergence(a, b) is None
    b.local_insert(bi, 3, "d")  # length drift
    fd = first_divergence(a, b)
    assert fd["only_in"] == "twin" and fd["item_index"] == 3


# ------------------------------------------------ typed-failure triggers --


def test_codec_failure_dumps_one_bounded_bundle(tmp_path):
    srv = small_server(tmp_path)
    srv.admit_doc("d0")
    frame = bytearray(codec.encode_txns(peer_history()))
    frame[len(frame) // 2] ^= 0xFF  # CRC now fails
    for _ in range(3):
        with pytest.raises(AdmissionError):
            srv.submit_frame("d0", bytes(frame))
    bundles = glob.glob(os.path.join(str(tmp_path / "obs"), "*.json"))
    assert len(bundles) == 1  # first failure dumps, later ones counted
    b = json.load(open(bundles[0]))
    assert b["reason"] == "codec" and b["doc"] == "d0"
    assert "CRC mismatch" in b["detail"]
    # The offending frame's length+CRC were logged pre-decode.
    assert any(f["len"] == len(frame) for f in b["recent_frames"])
    s = srv.counters.summary()
    assert s["obs_failures_codec"] == 3
    assert s["bundles_suppressed"] == 2


def test_checkpoint_failure_dumps_bundle(tmp_path):
    srv = small_server(tmp_path, ckpt_format="full")
    srv.admit_doc("d0")
    srv.submit_local("d0", "editor", 0, 0, "some text")
    srv.tick()
    doc = srv.doc_state("d0")
    path = srv.residency.evict(doc)
    with open(path, "r+b") as f:  # corrupt the checkpoint
        f.seek(30)
        f.write(b"\xff" * 8)
    from text_crdt_rust_tpu.utils.checkpoint import CheckpointError

    with pytest.raises(CheckpointError):
        srv.residency.restore(doc, tick_no=5)
    bundles = glob.glob(os.path.join(str(tmp_path / "obs"),
                                     "*checkpoint.json"))
    assert len(bundles) == 1
    b = json.load(open(bundles[0]))
    assert b["doc"] == "d0" and b["tick"] == 5


def test_degrade_dumps_bundle_with_doc_stats(tmp_path):
    srv = small_server(tmp_path, lane_capacity=16, order_capacity=48)
    srv.admit_doc("d0")
    srv.submit_local("d0", "editor", 0, 0, "x" * 100)  # beyond capacity
    srv.tick()
    doc = srv.doc_state("d0")
    assert doc.degraded
    bundles = glob.glob(os.path.join(str(tmp_path / "obs"),
                                     "*degrade.json"))
    assert len(bundles) == 1
    b = json.load(open(bundles[0]))
    assert b["doc"] == "d0"
    assert b["doc_stats"]["items"] >= 100


def test_causal_gap_dumps_bundle(tmp_path):
    from text_crdt_rust_tpu.common import (
        ROOT_REMOTE_ID,
        RemoteId,
        RemoteIns,
        RemoteTxn,
    )
    from text_crdt_rust_tpu.net.session import CausalGapError, ResyncSession

    reg = MetricsRegistry()
    tracer = Tracer(ring=32)
    rec = FlightRecorder(tracer, reg, str(tmp_path / "obs"))
    sess = ResyncSession(ListCRDT(), retry_limit=2, backoff_cap=1,
                         counters=reg, tracer=tracer, recorder=rec)
    # A txn whose predecessor never arrives: seq 5 with a gap below.
    gap_txn = RemoteTxn(RemoteId("ghost", 5), [], [
        RemoteIns(ROOT_REMOTE_ID, ROOT_REMOTE_ID, "zz")])
    sess.buffer.add(gap_txn)
    with pytest.raises(CausalGapError):
        for _ in range(32):
            sess.poll()
    bundles = glob.glob(os.path.join(str(tmp_path / "obs"),
                                     "*causal-gap.json"))
    assert len(bundles) == 1
    b = json.load(open(bundles[0]))
    assert b["wanted"] == {"ghost": 0}
    # The resync rounds leading up to the failure are in the ring.
    assert any(e["k"] == "resync.round" for e in b["events"])


def test_lane_mismatch_dumps_divergence_bundle(tmp_path):
    """Twin/lane bit-identity mismatch trigger: corrupt a device lane
    behind the residency layer's back; verify_lane must dump."""
    import jax
    import jax.numpy as jnp

    srv = small_server(tmp_path)
    srv.admit_doc("d0")
    srv.submit_local("d0", "editor", 0, 0, "hello")
    srv.tick()
    doc = srv.doc_state("d0")
    assert doc.in_lane
    backend = srv.residency.backends[doc.shard]
    backend.docs = dataclasses.replace(
        backend.docs,
        signed=backend.docs.signed.at[doc.lane, 0].set(
            jnp.int32(99999)))
    assert not srv.verify_doc("d0")
    bundles = glob.glob(os.path.join(str(tmp_path / "obs"),
                                     "*divergence.json"))
    assert len(bundles) == 1
    assert json.load(open(bundles[0]))["doc"] == "d0"


def test_tick_summary_surfaces_bundle_counts(tmp_path):
    """ISSUE 10 satellite: ``DocServer.tick_summary`` carries the
    flight-recorder bundle economy (written + suppressed) as additive
    keys, so a summary consumer sees 'this run failed the same way N
    times' without grepping the obs dir."""
    srv = small_server(tmp_path)
    srv.admit_doc("d0")
    ts = srv.tick_summary()
    assert ts["bundles_written"] == 0
    assert ts["bundles_suppressed"] == 0
    frame = bytearray(codec.encode_txns(peer_history()))
    frame[len(frame) // 2] ^= 0xFF  # CRC fails -> codec bundle
    for _ in range(3):
        with pytest.raises(AdmissionError):
            srv.submit_frame("d0", bytes(frame))
    ts = srv.tick_summary()
    assert ts["bundles_written"] == 1       # first failure dumped
    assert ts["bundles_suppressed"] == 2    # repeats counted
    assert ts["bundles_written"] == len(srv.recorder.bundle_paths)
    # The same keys flow through stats() (the loadgen report's source).
    st = srv.stats()
    assert st["tick_ms_bundles_written"] == 1
    assert st["tick_ms_bundles_suppressed"] == 2


def test_bundle_budget_is_per_reason(tmp_path):
    reg = MetricsRegistry()
    rec = FlightRecorder(None, reg, str(tmp_path / "obs"))
    assert rec.on_failure("codec", "a") is not None
    assert rec.on_failure("codec", "b") is None  # budget spent
    assert rec.on_failure("degrade", "c") is not None  # separate class
    assert reg.summary()["bundles_written"] == 2

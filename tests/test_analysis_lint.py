"""tcrlint self-tests (ISSUE 13): per-family injection + the tier-1 gate.

Two proof obligations per check family:

- **injection**: a minimal violating snippet written to a temp tree
  makes the lint exit 1 naming that exact file:line and check id;
- **clean pass**: the sanctioned spelling of the same code passes.

Plus the gate itself: ONE subprocess runs the full lint (tcrlint +
ruff-or-fallback, the shared entry point) over the real package and
must exit 0 — so a determinism hazard fails tier-1 CI with a named
finding, not a flaky fuzz seed three PRs later (the ``--check-ledger``
gate pattern).
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from text_crdt_rust_tpu.analysis import run_lint
from text_crdt_rust_tpu.analysis.checks_schema import surface_state
from text_crdt_rust_tpu.analysis.tcrlint import load_allowlist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_tree(tmp_path, files, allow=None):
    """Write ``files`` ({rel: source}) into a temp tree and lint it
    in-process (no committed allowlist/pins unless provided)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    allow_path = str(tmp_path / "allow.json")
    if allow is not None:
        (tmp_path / "allow.json").write_text(json.dumps({"allow": allow}))
    return run_lint(str(tmp_path), allowlist_path=allow_path,
                    pins_path=str(tmp_path / "pins.json"))


def the(findings, check):
    hits = [f for f in findings if f.check == check]
    assert hits, f"no {check} finding in {[f.format() for f in findings]}"
    return hits


# ---------------------------------------------- family 1: wall-clock --------


def test_wallclock_leak_named_by_file_and_line(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        import time


        def emit(tracer):
            tracer_field = time.time()
            return tracer_field
        """})
    f = the(findings, "TCR-W001")[0]
    assert (f.path, f.line) == ("mod.py", 5)
    assert f.scope == "emit"


def test_wallclock_from_import_and_datetime(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        from time import perf_counter
        import datetime


        def f():
            return perf_counter(), datetime.datetime.now()
        """})
    assert len(the(findings, "TCR-W001")) == 2


def test_wallclock_allowlisted_scope_passes(tmp_path):
    findings, _ = lint_tree(
        tmp_path,
        {"mod.py": "import time\n\n\ndef probe():\n"
                   "    return time.perf_counter()\n"},
        allow=[{"check": "TCR-W001", "path": "mod.py", "scope": "probe",
                "why": "test probe"}])
    assert not [f for f in findings if f.check == "TCR-W001"]


def test_stale_allowlist_entry_is_a_finding(tmp_path):
    findings, _ = lint_tree(
        tmp_path, {"mod.py": "X = 1\n"},
        allow=[{"check": "TCR-W001", "path": "mod.py", "scope": "gone",
                "why": "stale"}])
    assert the(findings, "TCR-A001")


def test_unjustified_allowlist_entry_refused(tmp_path):
    with pytest.raises(ValueError, match="justification"):
        lint_tree(tmp_path, {"mod.py": "X = 1\n"},
                  allow=[{"check": "TCR-W001", "path": "mod.py",
                          "scope": "f", "why": ""}])


# ---------------------------------------------- family 2: determinism -------


def test_builtin_hash_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        def key(x):
            return hash(x) % 16
        """})
    f = the(findings, "TCR-D001")[0]
    assert (f.path, f.line) == ("mod.py", 2)


def test_set_iteration_flagged_sorted_passes(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        def emit(names):
            for n in set(names):
                print(n)
            ordered = list({1, 2, 3})
            fine = sorted(set(names))
            count = len(set(names))
            return ordered, fine, count
        """})
    hits = the(findings, "TCR-D002")
    assert [f.line for f in hits] == [2, 4]


def test_unsorted_listdir_flagged_sorted_passes(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        import glob
        import os


        def walk(d):
            bad = os.listdir(d)
            worse = glob.glob(d + "/*.npz")
            good = sorted(os.listdir(d))
            return bad, worse, good
        """})
    hits = the(findings, "TCR-D003")
    assert [f.line for f in hits] == [6, 7]


def test_unseeded_randomness_flagged_seeded_passes(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        import random

        import numpy as np


        def gen():
            rng = random.Random(7)          # fine: seeded instance
            a = rng.random()
            b = random.random()             # global state
            c = np.random.rand(3)           # legacy global
            d = np.random.default_rng(7)    # fine: seeded
            e = np.random.default_rng()     # entropy-seeded
            return a, b, c, d, e
        """})
    hits = the(findings, "TCR-D004")
    assert [f.line for f in hits] == [9, 10, 12]


# ---------------------------------------------- family 3: schema drift ------


def test_unknown_trace_kind_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        def f(tracer):
            tracer.event("tick.drain", shard=0, events=1, steps=1)
            tracer.event("bogus.kind", x=1)
        """})
    f = the(findings, "TCR-S001")[0]
    assert f.line == 3 and "bogus.kind" in f.message


def test_unknown_ledger_family_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        from text_crdt_rust_tpu.obs.ledger import metric

        GOOD = metric(1, "steps")
        BAD = metric(1, "nonsense")
        """})
    f = the(findings, "TCR-S002")[0]
    assert f.line == 4 and "nonsense" in f.message


def test_schema_drift_without_version_bump_flagged(tmp_path):
    """Real-repo S003: a pin whose fingerprint disagrees while the
    version agrees = someone edited the field set without bumping."""
    pins = json.load(open(
        os.path.join(REPO, "text_crdt_rust_tpu/analysis/SCHEMA_PINS.json")))
    pins["pins"]["trace-events"]["fingerprint"] ^= 0xDEAD
    mutated = tmp_path / "pins.json"
    mutated.write_text(json.dumps(pins))
    findings, _ = run_lint(
        REPO, ["text_crdt_rust_tpu/obs/trace.py"],
        pins_path=str(mutated))
    f = the(findings, "TCR-S003")[0]
    assert f.path == "text_crdt_rust_tpu/obs/trace.py"
    assert "without" in f.message or "still" in f.message


def test_schema_pins_match_live_surfaces():
    """The committed pins agree with the live field sets — i.e. the
    shipped tree carries no unpinned schema drift."""
    pins = json.load(open(
        os.path.join(REPO, "text_crdt_rust_tpu/analysis/SCHEMA_PINS.json")))
    from text_crdt_rust_tpu.analysis.checks_schema import SURFACES

    assert {s["name"] for s in SURFACES} == set(pins["pins"])
    for s in SURFACES:
        st = surface_state(REPO, s)
        pin = pins["pins"][s["name"]]
        assert st["fingerprint"] == pin["fingerprint"], s["name"]
        assert st["version"] == pin["version"], s["name"]


# ---------------------------------------------- family 4: recompile ---------


def test_uncached_kernel_build_flagged_cached_passes(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        import functools

        import jax
        from jax.experimental import pallas as pl


        def build_bad(k, shape):
            call = pl.pallas_call(k, out_shape=shape)
            return jax.jit(lambda a: call(a))


        @functools.lru_cache(maxsize=32)
        def _build_call(k, shape):
            call = pl.pallas_call(k, out_shape=shape)
            return jax.jit(lambda a: call(a))


        top_level = jax.jit(abs)


        @jax.jit
        def decorated(x):
            return x
        """})
    assert [f.line for f in the(findings, "TCR-R001")] == [8]
    assert [f.line for f in the(findings, "TCR-R002")] == [9]


# ---------------------------------------------- family 6: exceptions --------


def test_silent_swallow_in_serve_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"serve/mod.py": """\
        def ingest(frame):
            try:
                return frame.decode()
            except ValueError:
                pass
        """})
    hits = the(findings, "TCR-X001")
    assert hits[0].path == "serve/mod.py"
    assert hits[0].line == 4
    assert "ValueError" in hits[0].message


def test_swallow_outside_serve_net_not_flagged(tmp_path):
    findings, _ = lint_tree(tmp_path, {"ops/mod.py": """\
        def probe(x):
            try:
                return x()
            except ValueError:
                pass
        """})
    assert not [f for f in findings if f.check == "TCR-X001"]


def test_reported_handlers_pass(tmp_path):
    """Every sanctioned discipline: re-raise, typed conversion (raised
    OR constructed by value), notifier call, rejection recorder, and
    the inline-tally AugAssign."""
    findings, _ = lint_tree(tmp_path, {"net/mod.py": """\
        class WireError(Exception):
            pass


        def a(frame):
            try:
                return frame.decode()
            except ValueError:
                raise WireError("bad frame")


        def b(frame, counters):
            try:
                return frame.decode()
            except ValueError:
                counters.incr("frames_rejected")


        def c(frame, stats):
            try:
                return frame.decode()
            except ValueError:
                stats["rejected"] += 1


        def d(frame, router):
            try:
                return frame.decode()
            except ValueError as e:
                router.reject_frame(str(e))


        def e(frame):
            try:
                return frame.decode(), None
            except ValueError as exc:
                return None, WireError(str(exc))
        """})
    assert not [f for f in findings if f.check == "TCR-X001"]


def test_swallow_allowlist_grantable(tmp_path):
    findings, _ = lint_tree(tmp_path, {"serve/mod.py": """\
        def skip_foreign(names):
            out = []
            for n in names:
                try:
                    out.append(int(n))
                except ValueError:
                    continue
            return out
        """}, allow=[{"check": "TCR-X001", "path": "serve/mod.py",
                      "scope": "skip_foreign",
                      "why": "filename-pattern filter, not an op-path fault"}])
    assert not [f for f in findings if f.check == "TCR-X001"]


# ---------------------------------------------- ruff fallback ---------------


def test_unused_import_flagged_noqa_passes(tmp_path):
    findings, _ = lint_tree(tmp_path, {"mod.py": """\
        import json
        import os  # noqa: F401
        import sys

        print(sys.argv)
        """})
    hits = the(findings, "TCR-F401")
    assert [f.line for f in hits] == [1]
    assert "json" in hits[0].message


# ---------------------------------------------- the committed allowlist -----


def test_committed_allowlist_loads_and_every_entry_justified():
    entries = load_allowlist()
    assert entries, "the audited allowlist ships non-empty"
    for e in entries:
        assert len(e["why"]) > 20, f"thin justification: {e}"


# ---------------------------------------------- the tier-1 gate -------------


def test_lint_gate_clean_tree_exits_zero():
    """THE tier-1 lint gate, full-tree flavor: the shared entry point
    (tcrlint v2 + ruff or its fallback) over the shipped package must
    be clean — the authoritative clean-tree proof behind the
    ``--changed`` incremental gate (test_analysis_dataflow.py, which
    honors the ``TCR_LINT_FULL=1`` weekly-style knob to force this
    flavor there too).  Budget: < 15 s wall (ISSUE 15 acceptance;
    measured ~3 s cold, ~0.3 s cache-warm)."""
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    wall = time.perf_counter() - t0
    assert r.returncode == 0, (r.stdout[-4000:], r.stderr[-2000:])
    out = json.loads(r.stdout)
    assert out["ok"] and not out["findings"]
    assert out["stats"]["files"] > 50  # the whole package walked
    assert wall < 15, f"lint gate took {wall:.1f}s (15s budget)"


def test_lint_gate_fails_loud_on_all_four_families(tmp_path):
    """The other half of the gate contract (ISSUE 13 acceptance): ONE
    violating tree exercises every check family through the real CLI,
    which exits 1 with each file:line-named finding on stdout."""
    (tmp_path / "bad.py").write_text(textwrap.dedent("""\
        import time

        import jax
        from jax.experimental import pallas as pl


        def leak():
            return time.time()


        def key(x):
            return hash(x)


        def emit(names):
            return list(set(names))


        def build(k, shape):
            call = pl.pallas_call(k, out_shape=shape)
            return jax.jit(lambda a: call(a))
        """))
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--root", str(tmp_path), "--allowlist",
         str(tmp_path / "none.json"), "--pins",
         str(tmp_path / "none_pins.json"), "bad.py"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "bad.py:8: TCR-W001" in r.stdout     # wall-clock leak
    assert "bad.py:12: TCR-D001" in r.stdout    # builtin hash()
    assert "bad.py:16: TCR-D002" in r.stdout    # set-order hazard
    assert "bad.py:20: TCR-R001" in r.stdout    # uncached kernel build
    assert "bad.py:21: TCR-R002" in r.stdout


def test_lint_gate_fails_loud_on_schema_drift(tmp_path):
    """Family 3 through the CLI: a fingerprint/version disagreement on
    a real surface exits 1 naming the surface file."""
    pins = json.load(open(
        os.path.join(REPO, "text_crdt_rust_tpu/analysis/SCHEMA_PINS.json")))
    pins["pins"]["bench-row"]["fingerprint"] ^= 0xBEEF
    mutated = tmp_path / "pins.json"
    mutated.write_text(json.dumps(pins))
    r = subprocess.run(
        [sys.executable, "-m", "text_crdt_rust_tpu.analysis.lint",
         "--pins", str(mutated), "text_crdt_rust_tpu/analysis"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "bench.py" in r.stdout and "TCR-S003" in r.stdout
    assert "bump the version" in r.stdout

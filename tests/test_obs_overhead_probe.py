"""Tier-1 smoke for ``perf/obs_overhead_probe.py`` (ISSUE 8 satellite):
the committed ``perf/obs_overhead_r11.json`` is produced by the probe's
full 200-doc path; this keeps the small-scale path green (converged on
both arms, trace byte-identity held, acceptance fields present) so the
JSON can't silently rot, and a ``slow``-tier check re-validates the
committed file's claims structurally."""
import json
import os
import importlib.util

import pytest

PROBE = os.path.join("perf", "obs_overhead_probe.py")
COMMITTED = os.path.join("perf", "obs_overhead_r11.json")


def _load_probe():
    spec = importlib.util.spec_from_file_location("oop", PROBE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_smoke_path_green():
    out = _load_probe().run_matrix(smoke=True, reps=1)
    assert out["converged"] == {"off": True, "on": True}
    assert out["trace_byte_identical_across_runs"]
    assert out["trace_events"] > 100
    assert "overhead_pct" in out and "loop_wall_s" in out
    assert out["acceptance"]["floor_pct"] == 5.0


def test_committed_overhead_json_claims():
    """The committed probe JSON's acceptance claims: tracing-on wall
    within 5% of tracing-off at the 200-doc shape, traces
    byte-identical, both arms converged. Structural re-validation is
    tier-1 cheap; the full re-measurement is the probe CLI itself."""
    with open(COMMITTED) as f:
        d = json.load(f)
    assert not d["smoke"], "committed JSON must be the full 200-doc run"
    assert d["workload"]["docs"] == 200
    assert d["acceptance"]["pass"]
    assert d["overhead_pct"] < d["acceptance"]["floor_pct"]
    assert d["trace_byte_identical_across_runs"]
    assert all(d["converged"].values())


@pytest.mark.slow
def test_probe_full_rerun_matches_committed_claims():
    """Re-measure at full scale (slow tier): the acceptance must
    reproduce on the current code, not just parse."""
    out = _load_probe().run_matrix(smoke=False, reps=2)
    assert out["acceptance"]["pass"], out

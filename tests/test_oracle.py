"""Oracle engine tests.

Mirrors the reference's doc tests (`list/doc.rs:513-677`): smoke,
deletes_merged, the seeded randomized differential test against a plain
string, and the local-vs-remote convergence test — plus the N-peer
randomized concurrent merge test the reference lost
(`.vscode/launch.json:11-12` mentions a vanished `random_concurrency`
binary; SURVEY §4 calls for restoring it).
"""
import random

import pytest

from text_crdt_rust_tpu import (
    LocalOp,
    ROOT_REMOTE_ID,
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import (
    export_txns_since,
    merge_into,
    remote_frontier,
)

ALPHABET = "abcdefghijklmnop_"


def random_str(rng: random.Random, length: int) -> str:
    return "".join(rng.choice(ALPHABET) for _ in range(length))


def make_random_change(doc: ListCRDT, text: str, agent: int,
                       rng: random.Random) -> str:
    """(`doc.rs:544-569` analog, string instead of rope as the oracle)"""
    doc_len = len(doc)
    insert_weight = 0.55 if doc_len < 100 else 0.45
    if doc_len == 0 or rng.random() < insert_weight:
        pos = rng.randint(0, doc_len)
        content = random_str(rng, rng.randint(1, 3))
        text = text[:pos] + content + text[pos:]
        doc.local_insert(agent, pos, content)
    else:
        pos = rng.randint(0, doc_len - 1)
        span = rng.randint(1, min(10, doc_len - pos))
        text = text[:pos] + text[pos + span:]
        doc.local_delete(agent, pos, span)
    return text


def test_smoke():
    # (`doc.rs:522-532`)
    doc = ListCRDT()
    doc.get_or_create_agent_id("seph")
    doc.local_insert(0, 0, "hi")
    doc.local_insert(0, 1, "yooo")
    doc.local_delete(0, 0, 3)
    # "hi" → "hyoooi" → delete "hyo" → "ooi"
    assert doc.to_string() == "ooi"
    assert len(doc) == 3
    doc.check()


def test_deletes_merged():
    # (`doc.rs:589-601`)
    doc = ListCRDT()
    doc.get_or_create_agent_id("seph")
    doc.local_insert(0, 0, "abc")
    doc.local_delete(0, 0, 1)
    doc.local_delete(0, 0, 1)
    doc.local_delete(0, 0, 1)
    assert doc.to_string() == ""
    # Three separate delete txns, targets 0,1,2 with op orders 3,4,5:
    # the deletes log RLE-merges them into one entry.
    assert doc.deletes.num_entries() == 1
    e = doc.deletes.entries[0]
    assert (e.op_order, e.target, e.length) == (3, 0, 3)
    doc.check()


def test_multi_op_txn():
    doc = ListCRDT()
    doc.get_or_create_agent_id("seph")
    doc.local_insert(0, 0, "aaaa")
    # One txn: delete 2 at pos 1, insert "xy" at pos 1.
    doc.apply_local_txn(0, [LocalOp(pos=1, ins_content="xy", del_span=2)])
    assert doc.to_string() == "axya"
    assert doc.txns.num_entries() <= 2
    doc.check()


def test_random_single_document():
    # (`doc.rs:571-587`)
    rng = random.Random(7)
    doc = ListCRDT()
    agent = doc.get_or_create_agent_id("seph")
    text = ""
    for _ in range(1000):
        text = make_random_change(doc, text, agent, rng)
        assert doc.to_string() == text
        assert len(doc) == len(text)
    # Single-agent linear history compacts to single RLE entries
    # (`doc.rs:585-586`).
    assert doc.client_data[0].item_orders.num_entries() == 1
    assert doc.client_with_order.num_entries() == 1
    doc.check()


def root_id():
    return ROOT_REMOTE_ID


def test_remote_txns_convergence():
    # (`doc.rs:620-676`)
    doc_remote = ListCRDT()
    doc_remote.apply_remote_txn(RemoteTxn(
        id=RemoteId("seph", 0),
        parents=[root_id()],
        ops=[RemoteIns(origin_left=root_id(), origin_right=root_id(),
                       ins_content="hi")],
    ))
    assert doc_remote.to_string() == "hi"

    doc_local = ListCRDT()
    doc_local.get_or_create_agent_id("seph")
    doc_local.local_insert(0, 0, "hi")

    assert doc_remote.frontier == doc_local.frontier
    assert doc_remote.txns == doc_local.txns
    assert doc_remote.to_string() == doc_local.to_string()
    assert doc_remote.deletes == doc_local.deletes

    doc_remote.apply_remote_txn(RemoteTxn(
        id=RemoteId("seph", 2),
        parents=[RemoteId("seph", 1)],
        ops=[RemoteDel(id=RemoteId("seph", 0), len=2)],
    ))
    doc_local.local_delete(0, 0, 2)

    assert doc_remote.frontier == doc_local.frontier
    assert doc_remote.txns == doc_local.txns
    assert doc_remote.to_string() == doc_local.to_string()
    assert doc_remote.deletes == doc_local.deletes
    doc_remote.check()


def test_concurrent_inserts_name_tiebreak():
    """Two peers insert at the same spot concurrently: Yjs tiebreak orders
    by agent *name* (`doc.rs:204-217`), and both peers converge."""
    a = ListCRDT()
    a.get_or_create_agent_id("alice")
    a.local_insert(0, 0, "AA")

    b = ListCRDT()
    b.get_or_create_agent_id("bob")
    b.local_insert(0, 0, "BB")

    merge_into(a, b)
    merge_into(b, a)
    assert a.to_string() == b.to_string()
    # Name order: "alice" < "bob" → alice's run first.
    assert a.to_string() == "AABB"
    assert remote_frontier(a) == remote_frontier(b)


def test_double_delete_convergence():
    """Both peers delete the same char concurrently — idempotent via the
    double-deletes log (`double_delete.rs:6-9`)."""
    a = ListCRDT()
    a.get_or_create_agent_id("alice")
    a.local_insert(0, 0, "xyz")
    b = ListCRDT()
    merge_into(b, a)
    assert b.to_string() == "xyz"

    a.local_delete(0, 1, 1)
    b_agent = b.get_or_create_agent_id("bob")
    b.local_delete(b_agent, 1, 1)

    merge_into(a, b)
    merge_into(b, a)
    assert a.to_string() == b.to_string() == "xz"
    assert a.double_deletes.num_entries() == 1
    assert b.double_deletes.num_entries() == 1
    assert a.double_deletes.entries[0].excess == 1


def test_export_roundtrip_mixed_ops():
    src = ListCRDT()
    src.get_or_create_agent_id("seph")
    src.local_insert(0, 0, "hello world")
    src.local_delete(0, 2, 3)
    src.apply_local_txn(0, [LocalOp(pos=4, ins_content="XY", del_span=2)])

    dst = ListCRDT()
    n = merge_into(dst, src)
    assert n == len(export_txns_since(src, 0))
    assert dst.to_string() == src.to_string()
    assert dst.deletes == src.deletes
    assert remote_frontier(dst) == remote_frontier(src)


def test_incremental_sync_splits_partial_spans():
    src = ListCRDT()
    src.get_or_create_agent_id("seph")
    src.local_insert(0, 0, "abc")
    dst = ListCRDT()
    merge_into(dst, src)
    # src types more (linear history merges into the same txn span).
    src.local_insert(0, 3, "def")
    src.local_insert(0, 0, "!")
    merge_into(dst, src)
    assert dst.to_string() == src.to_string() == "!abcdef"


@pytest.mark.parametrize("seed", range(6))
def test_random_concurrency_n_peers(seed):
    """The reference's missing `random_concurrency` test (SURVEY §4): N peers
    make seeded random edits, sync pairwise at random, and must converge."""
    rng = random.Random(1000 + seed)
    names = ["alice", "bob", "carol"]
    peers = []
    texts = []
    for name in names:
        d = ListCRDT()
        d.get_or_create_agent_id(name)
        peers.append(d)
        texts.append("")

    for _round in range(12):
        for i, d in enumerate(peers):
            for _ in range(rng.randint(1, 4)):
                texts[i] = make_random_change(d, texts[i], 0, rng)
                assert d.to_string() == texts[i]
        # Random pairwise sync.
        i, j = rng.sample(range(len(peers)), 2)
        merge_into(peers[i], peers[j])
        merge_into(peers[j], peers[i])
        texts[i] = peers[i].to_string()
        texts[j] = peers[j].to_string()
        assert texts[i] == texts[j]
        for d in peers:
            d.check()

    # Full mesh sync to convergence.
    for _ in range(2):
        for i in range(len(peers)):
            for j in range(len(peers)):
                if i != j:
                    merge_into(peers[i], peers[j])
    final = peers[0].to_string()
    for d in peers[1:]:
        assert d.to_string() == final
    assert len({frozenset(remote_frontier(d)) for d in peers}) == 1

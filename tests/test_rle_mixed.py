"""Mixed-stream RLE run engine (remote ops on run rows) vs oracle.

Interpreter-mode differential tests. Tiny blocks (block_k=8) force leaf
SPLITS between remote lookups, exercising the stale-ordblk fallback and
self-heal on the run representation; the scenarios mirror
``test_blocked_mixed`` (the `doc.rs:242-348` apply paths) plus the
config-4 concurrent-insert storm and cross-engine local equality with
``ops.rle``.
"""
import random

import pytest

# Heavy interpret-mode matrix: slow tier (VERDICT weak #7).  Tier-1
# keeps rle-mixed coverage via test_rle_mixed_fast.TestTier1Smoke and
# the blocked-lanes fuzz.
pytestmark = pytest.mark.slow

from text_crdt_rust_tpu.common import (  # noqa: E402
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import rle_mixed as RM
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.randedit import make_storm

from test_device_flat import (
    oracle_from_patches,
    random_patches,
)

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def replay_txns(txns, capacity, block_k=8, lmax=4, chunk=128, dmax=16):
    table = B.AgentTable()
    for t in txns:
        table.add(t.id.agent)
        for op in t.ops:
            if hasattr(op, "id"):
                table.add(op.id.agent)
    ops, _ = B.compile_remote_txns(txns, table, lmax=lmax, dmax=dmax)
    res = RM.replay_mixed_rle(ops, capacity=capacity, batch=8,
                              block_k=block_k, chunk=chunk, interpret=True)
    return R.rle_to_flat(ops, res)


def oracle_txns(txns):
    doc = ListCRDT()
    for t in txns:
        doc.apply_remote_txn(t)
    return doc


class TestMixedRleLocal:
    def test_local_stream_matches_rle(self):
        # KIND_LOCAL handling must stay bit-identical to ops.rle.
        rng = random.Random(13)
        patches, content = random_patches(rng, 60)
        merged = B.merge_patches(patches)
        ops, _ = B.compile_local_patches(merged, lmax=8, dmax=None)
        res = RM.replay_mixed_rle(ops, capacity=256, batch=8, block_k=8,
                                  chunk=128, interpret=True)
        doc = R.rle_to_flat(ops, res)
        ref = R.replay_local_rle(ops, capacity=256, batch=8, block_k=8,
                                 chunk=128, interpret=True)
        ref_doc = R.rle_to_flat(ops, ref)
        assert SA.to_string(doc) == SA.to_string(ref_doc) == content
        assert SA.doc_spans(doc) == SA.doc_spans(ref_doc)


class TestMixedRleRemote:
    def test_concurrent_root_inserts_tiebreak(self):
        # Config-4 storm shape: peers insert at the same point with the
        # same origins; order = the name tiebreak (`doc.rs:206-216`).
        txns = [
            RemoteTxn(id=RemoteId(name, 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, text)])
            for name, text in [("zed", "zz"), ("amy", "aa"), ("mia", "mm")]
        ]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=64, block_k=8)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    @pytest.mark.parametrize("seed", [3, 21])
    def test_two_peer_random_merge(self, seed):
        rng = random.Random(seed)
        pa, _ = random_patches(rng, 40)
        pb, _ = random_patches(rng, 40)
        a = oracle_from_patches(pa, agent="peer-a")
        bdoc = oracle_from_patches(pb, agent="peer-b")
        txns = export_txns_since(a, 0) + export_txns_since(bdoc, 0)
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=512, block_k=8)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_order_contiguous_unchained_no_merge(self):
        # Round-5 regression: three single-char root inserts get
        # order-contiguous orders (0,1,2); zed's char must NOT merge
        # into amy's run (its origin_left is ROOT, not amy), else the
        # YATA run-skip hides it from mid's scan and the doc diverges
        # (was: "azm" instead of "amz").
        txns = [
            RemoteTxn(id=RemoteId(n, 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, t)])
            for n, t in [("amy", "a"), ("zed", "z"), ("mid", "m")]
        ]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=64, block_k=8)
        assert SA.to_string(doc) == oracle.to_string() == "amz"
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_remote_delete_fragmented_and_double(self):
        base = RemoteTxn(id=RemoteId("amy", 0), parents=[],
                         ops=[RemoteIns(ROOT, ROOT, "abcdef")])
        d1 = RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 5)],
                       ops=[RemoteDel(RemoteId("amy", 1), 3)])
        d2 = RemoteTxn(id=RemoteId("cat", 0), parents=[RemoteId("amy", 5)],
                       ops=[RemoteDel(RemoteId("amy", 2), 3)])
        txns = [base, d1, d2]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=64, block_k=8)
        assert SA.to_string(doc) == oracle.to_string() == "af"
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_local_remote_convergence(self):
        # The reference's `remote_txns` convergence check (`doc.rs:620-676`).
        rng = random.Random(5)
        patches, _ = random_patches(rng, 60)
        local = oracle_from_patches(patches, agent="conv")
        txns = export_txns_since(local, 0)
        doc = replay_txns(txns, capacity=512, block_k=8)
        assert SA.to_string(doc) == local.to_string()
        assert SA.doc_spans(doc) == local.doc_spans()

    def test_storm_interleaved_peers(self):
        # N peers typing concurrently at interleaved positions, merged into
        # one causal stream — splits hit between remote integrations,
        # exercising the stale-index fallback + heal on run rows.
        rng = random.Random(99)
        peers = []
        for name in ("ada", "bea", "cyd", "dot"):
            patches, _ = random_patches(rng, 25)
            peers.append(oracle_from_patches(patches, agent=name))
        txns = []
        for p in peers:
            txns.extend(export_txns_since(p, 0))
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=1024, block_k=8)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    @pytest.mark.parametrize("dmax", [16, None])
    def test_long_remote_delete(self, dmax):
        # A 40-target delete both dmax-chunked and UNCHUNKED (the
        # one-pass interval delete takes any length in one step) must
        # converge; the unchunked form spans multiple 16-row blocks,
        # exercising the plane-wide flip + slot-count gather.
        base = RemoteTxn(id=RemoteId("amy", 0), parents=[],
                         ops=[RemoteIns(ROOT, ROOT, "x" * 50)])
        kill = RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 49)],
                         ops=[RemoteDel(RemoteId("amy", 5), 40)])
        txns = [base, kill]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=128, block_k=16, lmax=16,
                          dmax=dmax)
        assert SA.to_string(doc) == oracle.to_string() == "x" * 10
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_unchunked_delete_spans_many_fragmented_blocks(self):
        # Interleave two peers' typing so amy's chars are fragmented
        # across blocks, then delete amy's whole range unchunked: full
        # covers flip plane-wide in ONE step while bob's interleaved
        # chars survive.
        txns = []
        for k in range(12):
            txns.append(RemoteTxn(
                id=RemoteId("amy", 2 * k), parents=[],
                ops=[RemoteIns(ROOT if k == 0 else RemoteId("amy", 2 * k - 1),
                               ROOT, "aa")]))
        for k in range(12):
            txns.append(RemoteTxn(
                id=RemoteId("bob", k), parents=[],
                ops=[RemoteIns(ROOT if k == 0 else RemoteId("bob", k - 1),
                               RemoteId("amy", 2 * k), "B")]))
        txns.append(RemoteTxn(
            id=RemoteId("cat", 0), parents=[RemoteId("amy", 23)],
            ops=[RemoteDel(RemoteId("amy", 2), 20)]))
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=128, block_k=8, lmax=4,
                          dmax=None)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_delete_inside_merged_run_then_insert(self):
        # Insert into the middle of a TOMBSTONE run: the raw-position
        # splice must preserve the dead tail's sign/start (the
        # `_insert_splice_raw` negative-run fix-up).
        base = RemoteTxn(id=RemoteId("amy", 0), parents=[],
                         ops=[RemoteIns(ROOT, ROOT, "abcdefgh")])
        kill = RemoteTxn(id=RemoteId("amy", 8), parents=[RemoteId("amy", 7)],
                         ops=[RemoteDel(RemoteId("amy", 2), 4)])
        # bob saw only the base: inserts between d (amy,3) and e (amy,4),
        # both of which are now tombstones.
        mid = RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 7)],
                        ops=[RemoteIns(RemoteId("amy", 3),
                                       RemoteId("amy", 4), "XY")])
        txns = [base, kill, mid]
        oracle = oracle_txns(txns)
        doc = replay_txns(txns, capacity=64, block_k=8)
        assert SA.to_string(doc) == oracle.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_config4_storm_oracle(self):
        # The bench config-4 workload shape end-to-end.
        txns, receiver = make_storm(4, 6, 2, seed=7)
        oracle = oracle_txns(txns)
        assert oracle.to_string() == receiver.to_string()
        doc = replay_txns(txns, capacity=512, block_k=8, lmax=8)
        assert SA.to_string(doc) == receiver.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    def test_config4_delete_heavy_storm_oracle(self):
        # The bench delete-heavy variant: peers merge earlier rounds
        # and delete cross-peer spans (remote deletes, double deletes)
        # between the concurrent inserts.
        txns, receiver = make_storm(4, 8, 3, seed=7, del_prob=0.4)
        kinds = {type(op).__name__ for t in txns for op in t.ops}
        assert "RemoteDel" in kinds, "variant generated no deletes"
        oracle = oracle_txns(txns)
        assert oracle.to_string() == receiver.to_string()
        doc = replay_txns(txns, capacity=1024, block_k=8, lmax=8)
        assert SA.to_string(doc) == receiver.to_string()
        assert SA.doc_spans(doc) == oracle.doc_spans()

    @pytest.mark.parametrize("seed", [1, 17])
    def test_n_peer_random_interleavings_converge(self, seed):
        # SURVEY §4's missing `random_concurrency` test, on the device
        # engine: N peers editing independently, their txn streams
        # applied in DIFFERENT causally-valid interleavings, must
        # converge to one content — and match the oracle under the same
        # interleaving.
        rng = random.Random(seed)
        streams = []
        for name in ("kim", "lou", "max"):
            patches, _ = random_patches(rng, 20)
            streams.append(export_txns_since(
                oracle_from_patches(patches, agent=name), 0))

        def interleave(order_rng):
            queues = [list(s) for s in streams]
            out = []
            while any(queues):
                live = [q for q in queues if q]
                out.append(order_rng.choice(live).pop(0))
            return out

        results = []
        for k in range(2):
            txns = interleave(random.Random(seed * 100 + k))
            oracle = oracle_txns(txns)
            doc = replay_txns(txns, capacity=1024, block_k=8)
            assert SA.to_string(doc) == oracle.to_string()
            assert SA.doc_spans(doc) == oracle.doc_spans()
            results.append(SA.to_string(doc))
        assert results[0] == results[1], "interleavings diverged"

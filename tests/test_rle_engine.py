"""RLE run-blocked engine vs the flat engine and string oracle.

Interpreter-mode differential tests in the ``test_blocked_hbm`` mold:
tiny blocks (block_k as low as 8 RUNS) force constant leaf SPLITS — the
engine's replacement for the global rebalance — so the logical-block-order
machinery is exercised on every few ops, the analog of the reference's
shrunken debug node sizes (`range_tree/mod.rs:29-39`). Streams are
compiled through ``merge_patches`` (the production path) AND raw, so both
run-granular and per-keystroke ops hit the kernel.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    TestPatch,
    flatten_patches,
    load_testing_data,
    trace_path,
)

from test_device_flat import random_patches


def run_rle(patches, capacity, block_k, merge=True, chunk=128):
    plist = B.merge_patches(patches) if merge else patches
    lmax = max([len(p.ins_content) for p in plist] + [1])
    ops, _ = B.compile_local_patches(plist, lmax=lmax, dmax=None)
    res = R.replay_local_rle(ops, capacity=capacity, batch=8,
                             block_k=block_k, chunk=chunk, interpret=True)
    return ops, R.rle_to_flat(ops, res)


def ref_doc(patches, capacity=1024):
    """Flat-engine reference on the UNMERGED per-keystroke stream."""
    ops, _ = B.compile_local_patches(patches, lmax=16, dmax=None)
    return F.apply_ops(SA.make_flat_doc(capacity), ops)


class TestRleReplay:
    def test_smoke(self):
        patches = [TestPatch(0, 0, "hello world"), TestPatch(5, 0, ","),
                   TestPatch(2, 3, "LLO"), TestPatch(0, 1, "H")]
        _, doc = run_rle(patches, capacity=64, block_k=8)
        ref = ref_doc(patches, 64)
        assert SA.to_string(doc) == SA.to_string(ref) == "HeLLO, world"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.parametrize("seed", [7, 11, 99])
    @pytest.mark.parametrize("merge", [True, False])
    def test_random_vs_flat(self, seed, merge):
        rng = random.Random(seed)
        patches, content = random_patches(rng, 80)
        _, doc = run_rle(patches, capacity=256, block_k=8, merge=merge)
        ref = ref_doc(patches, 512)
        assert SA.to_string(doc) == SA.to_string(ref) == content
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_mid_run_split_insert(self):
        # One long run, then an insert strictly inside it: 3-way splice.
        patches = [TestPatch(0, 0, "abcdefghij"), TestPatch(5, 0, "XY")]
        _, doc = run_rle(patches, capacity=64, block_k=8)
        assert SA.to_string(doc) == "abcdeXYfghij"

    def test_delete_three_way_split(self):
        # Delete strictly inside one run: head + tombstone + tail rows.
        patches = [TestPatch(0, 0, "abcdefghij"), TestPatch(3, 4, "")]
        _, doc = run_rle(patches, capacity=64, block_k=8)
        ref = ref_doc(patches, 64)
        assert SA.to_string(doc) == SA.to_string(ref) == "abchij"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_delete_spanning_blocks(self):
        # Many tiny runs (discontiguous inserts), then one delete across
        # several blocks: boundary splits in two different blocks.
        patches = []
        for _ in range(24):
            patches.append(TestPatch(0, 0, "ab"))
        patches.append(TestPatch(2, 40, ""))
        _, doc = run_rle(patches, capacity=128, block_k=8, merge=False)
        ref = ref_doc(patches, 128)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_insert_before_tombstones(self):
        # Insert at a position whose successor is a tombstone: the raw
        # successor (doc.rs:452 — not skipped) feeds origin_right.
        patches = [TestPatch(0, 0, "abcdef"), TestPatch(2, 2, ""),
                   TestPatch(2, 0, "XY")]
        _, doc = run_rle(patches, capacity=64, block_k=8)
        ref = ref_doc(patches, 64)
        assert SA.to_string(doc) == SA.to_string(ref) == "abXYef"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_insert_at_zero_before_leading_tombstone(self):
        patches = [TestPatch(0, 0, "abc"), TestPatch(0, 2, ""),
                   TestPatch(0, 0, "Z")]
        _, doc = run_rle(patches, capacity=64, block_k=8)
        ref = ref_doc(patches, 64)
        assert SA.to_string(doc) == SA.to_string(ref) == "Zc"
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_prepend_heavy_splits(self):
        # kevin shape: every insert at pos 0 — runs can't merge, slot 0
        # splits over and over; logical order must stay consistent.
        patches = [TestPatch(0, 0, "ab") for _ in range(40)]
        _, doc = run_rle(patches, capacity=256, block_k=8, merge=False)
        ref = ref_doc(patches, 256)
        assert SA.to_string(doc) == SA.to_string(ref) == "ab" * 40
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_append_merge_compresses(self):
        # Order-contiguous typing compiled UNMERGED must still compress
        # into one device run via the in-kernel append fast path.
        patches = [TestPatch(i, 0, "x") for i in range(50)]
        ops, _ = B.compile_local_patches(patches, lmax=1, dmax=None)
        res = R.replay_local_rle(ops, capacity=64, batch=8, block_k=8,
                                 chunk=128, interpret=True)
        rows_used = int(np.asarray(res.rows).sum(axis=0)[0])
        assert rows_used == 1  # 50 keystrokes -> one run row
        assert SA.to_string(R.rle_to_flat(ops, res)) == "x" * 50

    def test_far_jump_edits(self):
        patches = [TestPatch(0, 0, "abcdefgh")]
        for k in range(12):
            patches.append(TestPatch(0, 0, "xy"))
            patches.append(TestPatch(8 + 2 * k, 0, "pq"))
        _, doc = run_rle(patches, capacity=128, block_k=8, merge=False)
        ref = ref_doc(patches, 128)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    @pytest.mark.slow
    def test_trace_prefix(self):
        data = load_testing_data(trace_path("automerge-paper"))
        patches = flatten_patches(data)[:400]
        _, doc = run_rle(patches, capacity=256, block_k=16)
        ref = ref_doc(patches, 1024)
        assert SA.to_string(doc) == SA.to_string(ref)
        assert SA.doc_spans(doc) == SA.doc_spans(ref)

    def test_block_exhaustion_flagged(self):
        # Discontiguous runs overflow a tiny capacity: the kernel must
        # raise the block-capacity flag, not corrupt state.
        patches = [TestPatch(0, 0, "ab") for _ in range(40)]
        ops, _ = B.compile_local_patches(patches, lmax=2, dmax=None)
        res = R.replay_local_rle(ops, capacity=16, batch=8, block_k=8,
                                 chunk=128, interpret=True)
        with pytest.raises(RuntimeError, match="out of blocks"):
            res.check()

    def test_bad_delete_flagged(self):
        patches = [TestPatch(0, 0, "abc"), TestPatch(0, 10, "")]
        ops, _ = B.compile_local_patches(patches, lmax=4, dmax=None)
        res = R.replay_local_rle(ops, capacity=32, batch=8, block_k=8,
                                 chunk=128, interpret=True)
        with pytest.raises(RuntimeError, match="past the end"):
            res.check()


class TestRleGroups:
    def test_divergent_streams(self):
        rng = random.Random(404)
        opses, contents = [], []
        for gi in range(3):
            patches, content = random_patches(rng, 40 + 10 * gi)
            merged = B.merge_patches(patches)
            lmax = max(len(p.ins_content) for p in merged if p.ins_content)
            ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
            opses.append(ops)
            contents.append(content)
        run = R.make_replayer_rle(opses, capacity=256, batch=8,
                                  block_k=8, chunk=128, interpret=True)
        results = run()
        assert len(results) == 3
        for ops, res, content in zip(opses, results, contents):
            assert SA.to_string(R.rle_to_flat(ops, res)) == content


class TestExpandRuns:
    def test_signs_and_orders(self):
        patches = [TestPatch(0, 0, "abcd"), TestPatch(1, 2, "")]
        ops, _ = B.compile_local_patches(
            B.merge_patches(patches), lmax=4, dmax=None)
        res = R.replay_local_rle(ops, capacity=32, batch=8, block_k=8,
                                 chunk=128, interpret=True)
        flat = R.expand_runs(res)
        # orders 0..3 in doc order; chars b,c (orders 1,2) tombstoned.
        assert list(flat) == [1, -2, -3, 4]


class TestVsNativeEngine:
    """Direct device<->C++ bit-equality (SURVEY §4: CPU<->TPU equality of
    order arrays + tombstone signs per batch): the rle engine's canonical
    spans must equal the native engine's on a real trace prefix."""

    def test_trace_prefix_spans_equal_native(self):
        from text_crdt_rust_tpu.models.native import NativeListCRDT

        data = load_testing_data(trace_path("automerge-paper"))
        patches = flatten_patches(data)[:600]
        _, doc = run_rle(patches, capacity=512, block_k=16)

        nd = NativeListCRDT()
        agent = nd.get_or_create_agent_id("bench")
        cps = np.frombuffer(
            "".join(p.ins_content for p in patches).encode("utf-32-le"),
            np.uint32)
        nd.replay_trace(agent, [p.pos for p in patches],
                        [p.del_len for p in patches],
                        [len(p.ins_content) for p in patches], cps)
        from text_crdt_rust_tpu.ops import span_arrays as SA2
        assert SA2.doc_spans(doc) == nd.doc_spans()
        assert SA2.to_string(doc) == nd.to_string()

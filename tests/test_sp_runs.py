"""Sequence-parallel run lookups vs a host reference (8-dev CPU mesh).

One document's RLE run rows sharded over sp=8; the two hot conversions
(`README.md:20-26`) must return exactly what a single-host walk over the
same runs returns, for every live rank and a sweep of orders — including
runs that straddle shard boundaries and shards that are all tombstones.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.parallel import make_mesh
from text_crdt_rust_tpu.parallel.sp_runs import make_sp_ops, shard_runs
from text_crdt_rust_tpu.utils.testdata import (
    flatten_patches,
    load_testing_data,
    trace_path,
)


def runs_from_patches(patches):
    """(ordp, lenp) planes via the kernel-exact host simulation."""
    from text_crdt_rust_tpu.ops.rle import simulate_run_rows

    # simulate_run_rows mirrors the kernel but returns counts; rebuild
    # the run list with the same walk.
    runs = []
    next_order = 0
    for p in B.merge_patches(patches):
        if p.del_len:
            rem, before, i = p.del_len, 0, 0
            while rem > 0 and i < len(runs):
                o, l, live = runs[i]
                lv = l if live else 0
                cs = min(max(p.pos - before, 0), lv)
                ce = min(max(p.pos + rem - before, 0), lv)
                cov = ce - cs
                if cov > 0:
                    parts = []
                    if cs > 0:
                        parts.append((o, cs, True))
                    parts.append((o + cs, cov, False))
                    if ce < l:
                        parts.append((o + ce, l - ce, True))
                    runs[i:i + 1] = parts
                    i += len(parts)
                    rem -= cov
                else:
                    i += 1
                before += lv - cov
            next_order += p.del_len
        il = len(p.ins_content)
        if il:
            st = next_order
            if p.pos == 0:
                runs.insert(0, (st, il, True))
            else:
                before = 0
                for i, (o, l, live) in enumerate(runs):
                    lv = l if live else 0
                    if before + lv >= p.pos:
                        off = p.pos - before
                        if off == l and live and st == o + l:
                            runs[i] = (o, l + il, True)
                        elif off == lv:
                            runs.insert(i + 1, (st, il, True))
                        else:
                            runs[i:i + 1] = [(o, off, True),
                                             (st, il, True),
                                             (o + off, l - off, True)]
                        break
                    before += lv
            next_order += il
    ordp = np.asarray([(o + 1) if live else -(o + 1)
                       for o, l, live in runs], np.int32)
    lenp = np.asarray([l for o, l, live in runs], np.int32)
    _ = simulate_run_rows  # imported to keep the mirror source adjacent
    return ordp, lenp


def host_lookups(ordp, lenp):
    """Reference walks: per-char doc order and live positions."""
    chars = []  # (order, live) per char in doc order
    for o, l in zip(ordp, lenp):
        start = abs(int(o)) - 1
        live = o > 0
        for j in range(int(l)):
            chars.append((start + j, bool(live)))
    live_chars = [c for c in chars if c[1]]
    return chars, live_chars


@pytest.fixture(scope="module")
def sharded():
    data = load_testing_data(trace_path("sveltecomponent"))
    patches = flatten_patches(data)[:1200]
    ordp, lenp = runs_from_patches(patches)
    mesh = make_mesh(dp=1, sp=8)
    o_dev, l_dev = shard_runs(ordp, lenp, mesh)
    return ordp, lenp, make_sp_ops(mesh), o_dev, l_dev


class TestSpRuns:
    def test_live_prefix_total(self, sharded):
        ordp, lenp, ops, o_dev, l_dev = sharded
        _, total = ops.live_prefix(o_dev, l_dev)
        want = int(np.where(ordp > 0, lenp, 0).sum())
        assert int(total) == want

    def test_position_of_live_rank_sweep(self, sharded):
        ordp, lenp, ops, o_dev, l_dev = sharded
        chars, live_chars = host_lookups(ordp, lenp)
        n_live = len(live_chars)
        # Host expectation: rank -> (global run row, offset) by walking
        # run rows and counting live chars.
        rng = random.Random(3)
        ranks = sorted(rng.sample(range(1, n_live + 1), 40)) + [1, n_live]
        for rank in ranks:
            row, off = ops.position_of_live_rank(o_dev, l_dev, rank)
            row, off = int(row), int(off)
            # Decode via the padded planes the device saw.
            o_pad = np.asarray(o_dev)
            l_pad = np.asarray(l_dev)
            assert o_pad[row] > 0, (rank, row)
            assert 1 <= off <= l_pad[row]
            # The char at that (row, off) is the rank'th live char.
            lv = np.where(o_pad > 0, l_pad, 0)
            live_before = int(lv[:row].sum()) + (off - 1)
            assert live_before == rank - 1

    def test_order_to_position_sweep(self, sharded):
        ordp, lenp, ops, o_dev, l_dev = sharded
        chars, _ = host_lookups(ordp, lenp)
        pos_of = {}
        live_seen = 0
        for order, live in chars:
            pos_of[order] = live_seen if live else -1
            live_seen += live
        rng = random.Random(5)
        orders = rng.sample(sorted(pos_of), 40)
        for order in orders:
            got = int(ops.order_to_position(o_dev, l_dev, order))
            assert got == pos_of[order], (order, got, pos_of[order])
        # Unknown order -> -1.
        assert int(ops.order_to_position(o_dev, l_dev, 10**8)) == -1

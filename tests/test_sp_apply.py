"""Sequence-parallel MUTATION vs the single-device engine (8-dev mesh).

One document sharded sp=8; a local-edit stream applied through
``parallel.sp_apply`` must produce exactly the char sequence (orders +
tombstone signs + content) the single-device run simulation and the
string oracle produce — including inserts at shard boundaries, deletes
spanning several shards, origin parity with ``ops.rle``, and the
capacity error path.  Long-lived docs load a row-balanced snapshot first
(``SpDoc.load``): a fresh sharded doc owns every rank in shard 0.
"""
import random

import jax
import numpy as np
import pytest

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.parallel import make_mesh
from text_crdt_rust_tpu.parallel.sp_apply import SpDoc
from text_crdt_rust_tpu.utils.randedit import random_patches
from text_crdt_rust_tpu.utils.testdata import TestPatch


def sp_doc(shard_rows=64, sp=8):
    mesh = make_mesh(sp=sp)
    return SpDoc(mesh, shard_rows)


def expected(patches):
    s = ""
    for p in patches:
        s = s[:p.pos] + p.ins_content + s[p.pos + p.del_len:]
    return s


def apply_patches(doc, patches, lmax=8, start_order=0):
    ops, nxt = B.compile_local_patches(
        B.merge_patches(patches), lmax=lmax, dmax=None,
        start_order=start_order)
    doc.apply_stream(ops)
    return ops, nxt


def sim_runs(patches, start_order=0):
    """(ordp, lenp, next_order) run planes via the kernel-exact host
    walk (the ``ops.rle.simulate_run_rows`` algebra)."""
    runs = []
    next_order = start_order
    for p in B.merge_patches(patches):
        if p.del_len:
            rem, before, i = p.del_len, 0, 0
            while rem > 0 and i < len(runs):
                o, l, live = runs[i]
                lv = l if live else 0
                cs = min(max(p.pos - before, 0), lv)
                ce = min(max(p.pos + rem - before, 0), lv)
                cov = ce - cs
                if cov > 0:
                    parts = []
                    if cs > 0:
                        parts.append((o, cs, True))
                    parts.append((o + cs, cov, False))
                    if ce < l:
                        parts.append((o + ce, l - ce, True))
                    runs[i:i + 1] = parts
                    i += len(parts)
                    rem -= cov
                else:
                    i += 1
                before += lv - cov
            next_order += p.del_len
        il = len(p.ins_content)
        if il:
            st = next_order
            if p.pos == 0:
                runs.insert(0, (st, il, True))
            else:
                before = 0
                for i, (o, l, live) in enumerate(runs):
                    lv = l if live else 0
                    if before + lv >= p.pos:
                        off = p.pos - before
                        if off == l and live and st == o + l:
                            runs[i] = (o, l + il, True)
                        elif off == lv:
                            runs.insert(i + 1, (st, il, True))
                        else:
                            runs[i:i + 1] = [(o, off, True), (st, il, True),
                                             (o + off, l - off, True)]
                        break
                    before += lv
            next_order += il
    ordp = np.asarray([(o + 1) if live else -(o + 1)
                       for o, _, live in runs], np.int32)
    lenp = np.asarray([l for _, l, _ in runs], np.int32)
    return ordp, lenp, next_order


def expand(ordp, lenp):
    if len(ordp) == 0:
        return np.zeros(0, np.int32)
    o = ordp.astype(np.int64)
    ln = lenp.astype(np.int64)
    base = np.repeat(np.abs(o), ln)
    within = np.arange(int(ln.sum())) - np.repeat(np.cumsum(ln) - ln, ln)
    return (np.repeat(np.sign(o), ln) * (base + within)).astype(np.int32)


def sim_flat(patches):
    o, l, _ = sim_runs(patches)
    return expand(o, l)


class TestSpApply:
    def test_insert_only_prepends_fresh_doc(self):
        # A fresh sharded doc: every rank lives in shard 0 (no
        # redistribution); prepend runs must match the simulation.
        doc = sp_doc(shard_rows=128)
        patches = [TestPatch(0, 0, "ab")] * 50
        ops, _ = apply_patches(doc, patches)
        np.testing.assert_array_equal(doc.expand(), sim_flat(patches))
        assert doc.to_string([ops]) == expected(patches)

    @pytest.mark.parametrize("seed", [7, 23, 41])
    def test_loaded_doc_random_stream(self, seed):
        # The long-context shape: a distributed snapshot (load), then a
        # random edit stream applied SHARDED; state must equal the
        # single-walk simulation over the whole history.
        rng = random.Random(seed)
        p1, c1 = random_patches(rng, 80)
        o1, l1, nxt = sim_runs(p1)
        doc = sp_doc(shard_rows=64)
        doc.load(o1, l1)
        np.testing.assert_array_equal(doc.expand(), expand(o1, l1))

        p2 = []
        content = c1
        for _ in range(60):
            if not content or rng.random() < 0.5:
                pos = rng.randint(0, len(content))
                ins = "".join(rng.choice("xyz")
                              for _ in range(rng.randint(1, 3)))
                p2.append(TestPatch(pos, 0, ins))
                content = content[:pos] + ins + content[pos:]
            else:
                pos = rng.randint(0, len(content) - 1)
                span = min(rng.randint(1, 3), len(content) - pos)
                p2.append(TestPatch(pos, span, ""))
                content = content[:pos] + content[pos + span:]
        apply_patches(doc, p2, start_order=nxt)
        np.testing.assert_array_equal(doc.expand(), sim_flat(p1 + p2))

    def test_wide_delete_spans_shards(self):
        # A loaded doc spread over all 8 shards, then one delete covering
        # most of it — several shards retire spans in the SAME step.
        rng = random.Random(3)
        p1, c1 = random_patches(rng, 80)
        o1, l1, nxt = sim_runs(p1)
        assert len(o1) >= 16, "need enough runs to spread"
        doc = sp_doc(shard_rows=64)
        doc.load(o1, l1)
        span = len(c1) - 4
        p2 = [TestPatch(2, span, ""), TestPatch(1, 0, "Q")]
        apply_patches(doc, p2, start_order=nxt)
        np.testing.assert_array_equal(doc.expand(), sim_flat(p1 + p2))

    def test_origins_match_single_device_engine(self):
        # The discovered origins (the CRDT metadata remote peers need)
        # must equal ops.rle's for the same second-epoch steps.
        from text_crdt_rust_tpu.ops import rle as R

        rng = random.Random(9)
        p1, _ = random_patches(rng, 50)
        p2, _ = ([], None)
        o1, l1, nxt = sim_runs(p1)
        ops1, _ = B.compile_local_patches(B.merge_patches(p1), lmax=8,
                                          dmax=None)
        content = expected(p1)
        p2 = []
        for _ in range(40):
            pos = rng.randint(0, len(content))
            ins = rng.choice(["uv", "w"])
            p2.append(TestPatch(pos, 0, ins))
            content = content[:pos] + ins + content[pos:]
        ops2, _ = B.compile_local_patches(B.merge_patches(p2), lmax=8,
                                          dmax=None, start_order=nxt)

        doc = sp_doc(shard_rows=64)
        doc.load(o1, l1)
        doc.apply_stream(ops2)

        combined = jax.tree.map(
            lambda a, b: np.concatenate([np.asarray(a), np.asarray(b)]),
            ops1, ops2)
        res = R.replay_local_rle(combined, capacity=256, batch=8,
                                 block_k=8, chunk=128, interpret=True)
        ol_ref = np.asarray(res.ol)[:, 0]
        or_ref = np.asarray(res.orr)[:, 0]
        starts = np.asarray(combined.ins_order_start, np.int64)
        ilens = np.asarray(combined.ins_len, np.int64)
        s0 = ops1.num_steps
        for s in range(s0, combined.num_steps):
            if ilens[s] > 0:
                st = int(starts[s])
                assert doc.ol_log[st] == int(ol_ref[s]), f"step {s}"
                assert doc.or_log[st] == int(or_ref[s]), f"step {s}"

    def test_capacity_error_raises(self):
        doc = sp_doc(shard_rows=8)
        # 50 prepend runs all land in shard 0 (capacity 8) -> must flag.
        patches = [TestPatch(0, 0, "ab"), TestPatch(0, 0, "xy")] * 25
        with pytest.raises(RuntimeError, match="capacity"):
            apply_patches(doc, patches)

    def test_bad_delete_raises(self):
        doc = sp_doc(shard_rows=32)
        with pytest.raises(RuntimeError, match="end of the document"):
            apply_patches(doc, [TestPatch(0, 0, "ab"), TestPatch(0, 5, "")])

    def test_auto_reshard_on_capacity(self):
        # Phase 1 packs 6 runs into shard 0 (a fresh SpDoc owns every
        # rank there).  Phase 2's spread inserts would overflow shard
        # 0's 8-row budget; with auto_reshard the capacity flag
        # triggers an even rebalance + one retry, after which the same
        # stream's inserts land on different shards and fit (VERDICT r4
        # next #8).  The retry replays from the pre-stream state, so
        # the final doc must still equal the full-history simulation.
        mesh = make_mesh(sp=8)
        doc = SpDoc(mesh, 8, auto_reshard=True)
        p1, content = [], ""
        for k in range(6):  # alternate ends so runs can't merge
            pos = 0 if k % 2 else len(content)
            p1.append(TestPatch(pos, 0, "ab"))
            content = content[:pos] + "ab" + content[pos:]
        _, nxt = apply_patches(doc, p1)
        assert int(np.asarray(doc.rows)[0]) >= 5  # all packed in shard 0
        p2 = [TestPatch(pos, 0, "Q") for pos in (1, 3, 5, 7, 9, 11)]
        apply_patches(doc, p2, start_order=nxt)
        np.testing.assert_array_equal(doc.expand(), sim_flat(p1 + p2))
        # Capacity without auto_reshard must still raise.
        doc2 = SpDoc(mesh, 8)
        with pytest.raises(RuntimeError, match="capacity"):
            apply_patches(doc2, p1 + p2)


def oracle_signed(oracle):
    return [(-1 if oracle.deleted[i] else 1) * (int(oracle.order[i]) + 1)
            for i in range(oracle.n)]


def compile_remote(txns, lmax=4):
    table = B.AgentTable()
    for t in txns:
        table.add(t.id.agent)
        for op in t.ops:
            if hasattr(op, "id"):
                table.add(op.id.agent)
    ops, _ = B.compile_remote_txns(txns, table, lmax=lmax, dmax=None)
    return ops


class TestSpRemote:
    """Sharded REMOTE integrate + delete (r4 verdict missing #4): the
    sp-sharded apply must equal the oracle and the single-device
    ``ops.rle_mixed`` engine on the same streams."""

    def _oracle(self, txns):
        from text_crdt_rust_tpu.models.oracle import ListCRDT
        doc = ListCRDT()
        for t in txns:
            doc.apply_remote_txn(t)
        return doc

    def test_concurrent_root_inserts_tiebreak(self):
        from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
        ROOT = RemoteId("ROOT", 0xFFFFFFFF)
        txns = [
            RemoteTxn(id=RemoteId(n, 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, t)])
            for n, t in [("zed", "zz"), ("amy", "aa"), ("mia", "mm")]
        ]
        doc = sp_doc(shard_rows=16)
        doc.apply_stream(compile_remote(txns))
        assert doc.expand().tolist() == oracle_signed(self._oracle(txns))

    def test_order_contiguous_unchained_no_merge(self):
        # The round-5 merge-chain regression, sharded: zed's char must
        # not merge into amy's run (origin_left is ROOT, not amy).
        from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
        ROOT = RemoteId("ROOT", 0xFFFFFFFF)
        txns = [
            RemoteTxn(id=RemoteId(n, 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, t)])
            for n, t in [("amy", "a"), ("zed", "z"), ("mid", "m")]
        ]
        doc = sp_doc(shard_rows=16)
        doc.apply_stream(compile_remote(txns))
        oracle = self._oracle(txns)
        assert oracle.to_string() == "amz"
        assert doc.expand().tolist() == oracle_signed(oracle)

    def test_fragmented_and_double_delete(self):
        from text_crdt_rust_tpu.common import (
            RemoteDel, RemoteId, RemoteIns, RemoteTxn)
        ROOT = RemoteId("ROOT", 0xFFFFFFFF)
        txns = [
            RemoteTxn(id=RemoteId("amy", 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, "abcdef")]),
            RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 5)],
                      ops=[RemoteDel(RemoteId("amy", 1), 3)]),
            RemoteTxn(id=RemoteId("cat", 0), parents=[RemoteId("amy", 5)],
                      ops=[RemoteDel(RemoteId("amy", 2), 3)]),
            RemoteTxn(id=RemoteId("bob", 3), parents=[RemoteId("amy", 5)],
                      ops=[RemoteIns(RemoteId("amy", 2),
                                     RemoteId("amy", 3), "XY")]),
        ]
        doc = sp_doc(shard_rows=16)
        doc.apply_stream(compile_remote(txns))
        assert doc.expand().tolist() == oracle_signed(self._oracle(txns))

    # Seed 3 is slow-tier (ISSUE 11 budget satellite: ~15 s of
    # interpret compile); seed 21 stays as the tier-1 representative,
    # and test_fuzz_blocked's 50-seed sp-remote ride-along covers the
    # surface in breadth.
    @pytest.mark.parametrize("seed", [
        pytest.param(3, marks=pytest.mark.slow), 21])
    def test_two_peer_merge_matches_rle_mixed(self, seed):
        # The VERDICT bar: sp-sharded remote apply equal to the
        # single-device rle_mixed engine's output on the same stream.
        from text_crdt_rust_tpu.models.sync import export_txns_since
        from text_crdt_rust_tpu.ops import rle as R
        from text_crdt_rust_tpu.ops import rle_mixed as RM
        from text_crdt_rust_tpu.ops import span_arrays as SA
        from test_device_flat import oracle_from_patches, random_patches

        rng = random.Random(seed)
        pa, _ = random_patches(rng, 30)
        pb, _ = random_patches(rng, 30)
        a = oracle_from_patches(pa, agent="peer-a")
        b = oracle_from_patches(pb, agent="peer-b")
        txns = export_txns_since(a, 0) + export_txns_since(b, 0)
        # rle_mixed needs dmax-chunked deletes; recompile for it.
        table = B.AgentTable()
        for t in txns:
            table.add(t.id.agent)
            for op in t.ops:
                if hasattr(op, "id"):
                    table.add(op.id.agent)
        ops_rm, _ = B.compile_remote_txns(txns, table, lmax=4, dmax=16)
        res = RM.replay_mixed_rle(ops_rm, capacity=512, batch=8,
                                  block_k=8, chunk=128, interpret=True)
        flat = R.rle_to_flat(ops_rm, res)
        cols = SA.download(flat)
        want = [(-1 if cols["deleted"][i] else 1)
                * (int(cols["order"][i]) + 1)
                for i in range(len(cols["order"]))]

        # Streamed in chunks with auto_reshard: a fresh SpDoc packs
        # every rank into shard 0; the between-chunk rebalance spreads
        # the rows so later chunks' probes cross shards for real.
        mesh = make_mesh(sp=8)
        # One 10-txn chunk can add ~50 rows to a single shard (a fresh
        # doc owns every rank in shard 0); 128 gives the pre-rebalance
        # buildup room while still forcing a mid-history rebalance.
        doc = SpDoc(mesh, 128, auto_reshard=True)
        table2 = B.AgentTable()
        for t in txns:
            table2.add(t.id.agent)
            for op in t.ops:
                if hasattr(op, "id"):
                    table2.add(op.id.agent)
        assigner = None
        for at in range(0, len(txns), 10):
            ops_c, assigner = B.compile_remote_txns(
                txns[at:at + 10], table2, assigner=assigner, lmax=4,
                dmax=None)
            doc.apply_stream(ops_c)
        assert doc.expand().tolist() == want
        assert want == oracle_signed(self._oracle(txns))

    def test_mixed_local_then_remote_stream(self):
        # Local ops and remote ops in ONE stream (all four dispatch
        # branches), vs the oracle applying the same logical edits.
        from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
        from text_crdt_rust_tpu.models.oracle import ListCRDT
        ROOT = RemoteId("ROOT", 0xFFFFFFFF)

        oracle = ListCRDT()
        me = oracle.get_or_create_agent_id("me")
        oracle.local_insert(me, 0, "hello world")
        oracle.local_delete(me, 2, 3)
        txn = RemoteTxn(id=RemoteId("peer", 0), parents=[],
                        ops=[RemoteIns(ROOT, ROOT, "Q")])
        oracle.apply_remote_txn(txn)

        ops_local, nxt = B.compile_local_patches(
            [TestPatch(0, 0, "hello world"), TestPatch(2, 3, "")],
            lmax=16, dmax=None)
        table = B.AgentTable(["me", "peer"])
        assigner = B.OrderAssigner(table)
        assigner.assign(table.id_of("me"), 0, nxt)
        ops_remote, _ = B.compile_remote_txns([txn], table,
                                              assigner=assigner,
                                              lmax=16, dmax=None)
        import jax as _jax
        combined = _jax.tree.map(
            lambda x, y: np.concatenate([np.asarray(x), np.asarray(y)]),
            ops_local, ops_remote)
        doc = sp_doc(shard_rows=32)
        doc.apply_stream(combined)
        assert doc.expand().tolist() == oracle_signed(oracle)

    def test_snapshot_load_tables_then_remote(self):
        # The documented snapshot path: build a doc on one SpDoc,
        # transfer (runs + by-order tables) to a FRESH SpDoc via
        # load/load_tables, then apply REMOTE ops that probe the
        # pre-snapshot history — must equal the oracle.
        from text_crdt_rust_tpu.common import (
            RemoteDel, RemoteId, RemoteIns, RemoteTxn)
        from text_crdt_rust_tpu.models.oracle import ListCRDT

        ROOT = RemoteId("ROOT", 0xFFFFFFFF)
        base = [RemoteTxn(id=RemoteId("amy", 0), parents=[],
                          ops=[RemoteIns(ROOT, ROOT, "hello world")])]
        later = [
            RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 10)],
                      ops=[RemoteIns(RemoteId("amy", 4),
                                     RemoteId("amy", 5), "XY"),
                           RemoteDel(RemoteId("amy", 0), 3)]),
        ]
        oracle = ListCRDT()
        for t in base + later:
            oracle.apply_remote_txn(t)

        src = sp_doc(shard_rows=32)
        table = B.AgentTable()
        for t in base + later:
            table.add(t.id.agent)
            for op in t.ops:
                if hasattr(op, "id"):
                    table.add(op.id.agent)
        ops_base, assigner = B.compile_remote_txns(base, table,
                                                   lmax=16, dmax=None)
        src.apply_stream(ops_base)

        dst = sp_doc(shard_rows=32)
        o, ln = src.runs()
        dst.load(o, ln)
        dst.load_tables(np.asarray(src.oll), np.asarray(src.orl),
                        np.asarray(src.rkl))
        ops_later, _ = B.compile_remote_txns(later, table,
                                             assigner=assigner,
                                             lmax=16, dmax=None)
        dst.apply_stream(ops_later)
        assert dst.expand().tolist() == oracle_signed(oracle)

    def test_missing_order_raises(self):
        from text_crdt_rust_tpu.common import (
            RemoteDel, RemoteId, RemoteIns, RemoteTxn)
        ROOT = RemoteId("ROOT", 0xFFFFFFFF)
        txns = [RemoteTxn(id=RemoteId("a", 0), parents=[],
                          ops=[RemoteIns(ROOT, ROOT, "ab")]),
                RemoteTxn(id=RemoteId("a", 2), parents=[],
                          ops=[RemoteIns(RemoteId("a", 1), ROOT, "cd")])]
        ops = compile_remote(txns)
        import jax as _jax
        ops = _jax.tree.map(lambda a: np.asarray(a).copy(), ops)
        ops.origin_left[1] = 90  # absent order
        doc = sp_doc(shard_rows=16)
        with pytest.raises(RuntimeError, match="order lookup missed"):
            doc.apply_stream(ops)

"""serve/lanes_backend.py: the blocked O(NB+K) lanes engine behind the
serve LaneBackend surface — persistent per-tick state, per-lane
residency writes, rank remap on blocked state, run-row capacity
degradation (ISSUE 4 tentpole).

Every test shares ONE kernel geometry (lanes=4, capacity=128, K=8,
OCAP=512, buckets (8, 32)) so the whole file pays two kernel compiles,
not two per test.
"""
import numpy as np
import pytest

from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
from text_crdt_rust_tpu.config import ServeConfig, engines_for
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since, state_digest
from text_crdt_rust_tpu.serve.batcher import make_lane_backend, oracle_signed
from text_crdt_rust_tpu.serve.lanes_backend import LanesMixedLaneBackend
from text_crdt_rust_tpu.serve.server import DocServer

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def cfg(**kw):
    base = dict(engine="rle-lanes-mixed", num_shards=1, lanes_per_shard=4,
                lane_capacity=128, lanes_block_k=8, order_capacity=512,
                step_buckets=(8, 32), max_txn_len=32)
    base.update(kw)
    return ServeConfig(**base)


def assert_lanes_equal_oracles(srv):
    for doc_id in srv.router.docs:
        assert srv.verify_doc(doc_id), f"{doc_id}: lane != oracle"


def test_registry_dispatch_builds_lanes_backend():
    assert "rle-lanes-mixed" in engines_for("serve")
    b = make_lane_backend("rle-lanes-mixed", lanes=4, capacity=128,
                          order_capacity=512, lmax=4, block_k=8)
    assert isinstance(b, LanesMixedLaneBackend)
    assert b.engine == "rle-lanes-mixed"
    assert b.NB == 16 and b.block_k == 8
    # The flat path still dispatches through the registry too.
    f = make_lane_backend("flat", lanes=4, capacity=128,
                          order_capacity=512, lmax=4)
    assert f.engine == "flat"


def test_mixed_local_remote_ticks_lane_equals_oracle():
    """The flat batcher test, engine-swapped: per-tick staged local AND
    remote ops on the blocked backend stay bit-identical to the host
    oracles across every tick."""
    srv = DocServer(cfg())
    for i in range(3):
        srv.admit_doc(f"d{i}")
    peer = ListCRDT()
    pa = peer.get_or_create_agent_id("peer")
    mark = 0
    for step in range(6):
        for i in range(3):
            srv.submit_local(f"d{i}", "ed", 0, ins_content=f"s{step}")
        peer.local_insert(pa, len(peer), "pq")
        if step % 2:
            peer.local_delete(pa, 0, 1)
        for t in export_txns_since(peer, mark):
            srv.submit_txn("d0", t)
        mark = peer.get_next_order()
        srv.tick()
        assert_lanes_equal_oracles(srv)


@pytest.mark.slow
def test_tick_shapes_are_bucketed_no_recompile_growth():
    """Steady-state serving cycles a fixed set of compiled shapes: the
    blocked backend sees at most one shape per configured step bucket,
    exactly as the flat backend asserts.  Slow tier since PR 17 (wall
    budget: ~30 s of the 870 s gate); the recompile-guard property
    keeps tier-1 coverage via the flat backend's step/scatter/train
    bucket guards (test_device_prefill, test_serve_train)."""
    srv = DocServer(cfg())
    srv.admit_doc("d")
    rng = np.random.RandomState(0)
    for _tick in range(10):
        for _ in range(int(rng.randint(1, 6))):
            srv.submit_local("d", "ed", 0, ins_content="ab")
        srv.tick()
    seen = srv.residency.backends[0].shapes_seen
    assert seen <= {8, 32}, seen
    assert_lanes_equal_oracles(srv)


def test_evict_restore_replay_matches_resident_twin(tmp_path):
    """The residency invariant on the lanes backend: evict mid-stream,
    peers keep editing while the doc is out, a touch restores (the
    per-lane blocked seeding path) and replays — bit-identical to an
    always-resident twin server, device lane included."""
    src = ListCRDT()
    a = src.get_or_create_agent_id("amy")
    mark = 0
    chunks = []
    for i in range(8):
        src.local_insert(a, len(src) // 2, f"<{i}>")
        if i % 3 == 2 and len(src) > 4:
            src.local_delete(a, 1, 2)
        chunks.append(export_txns_since(src, mark))
        mark = src.get_next_order()

    srv = DocServer(cfg(spool_dir=str(tmp_path / "a")))
    twin = DocServer(cfg(spool_dir=str(tmp_path / "b")))
    for s in (srv, twin):
        s.admit_doc("d")
    for chunk in chunks[:4]:
        for t in chunk:
            srv.submit_txn("d", t)
            twin.submit_txn("d", t)
        srv.tick(); twin.tick()
    doc = srv.doc_state("d")
    assert doc.in_lane
    srv.residency.evict(doc)
    for chunk in chunks[4:]:
        for t in chunk:
            srv.submit_txn("d", t)
            twin.submit_txn("d", t)
        twin.tick()
    assert doc.evicted and len(doc.events) > 0
    srv.tick()
    assert doc.resident and not doc.evicted
    srv.drain(); twin.drain()
    assert srv.doc_string("d") == src.to_string()
    assert srv.doc_string("d") == twin.doc_string("d")
    assert (state_digest(doc.oracle)
            == state_digest(twin.doc_state("d").oracle))
    assert srv.verify_doc("d") and twin.verify_doc("d")


def _fragment(srv, doc_id, edits=14):
    """Drive single-char prepends (each its own run — no merge) so the
    lane's blocks SPLIT and the split forward pointers arm."""
    for i in range(edits):
        srv.submit_local(doc_id, "ed", 0, ins_content="abcdefgh"[i % 8])
        srv.tick()


def test_remap_on_lane_with_split_forward_pointers():
    """The PR 2 self-healing path under an epoch re-base: fragment one
    lane until its blocks split (fwd pointers armed, hint entries going
    stale), onboard a new agent (rank remap on the blocked state), then
    land concurrent same-origin inserts whose tiebreak reads the
    remapped ranks through hint-guided probes."""
    srv = DocServer(cfg())
    srv.admit_doc("d")
    # 'mmm' writes first; rank(mmm)=0 accumulates in the lane's table.
    srv.submit_local("d", "mmm", 0, ins_content="base")
    srv.tick()
    _fragment(srv, "d")
    backend = srv.residency.backends[0]
    doc = srv.doc_state("d")
    assert doc.in_lane
    fwd = np.asarray(backend._state[10])[:, doc.lane]
    assert (fwd >= 0).any(), "no block ever split — workload too small"
    # 'aaa' joins: sorted ranks shift; the lane's accumulated rank table
    # must re-base before the tiebreaks below read it.
    t_a = RemoteTxn(id=RemoteId("aaa", 0), parents=[ROOT],
                    ops=[RemoteIns(ROOT, ROOT, "A")])
    t_z = RemoteTxn(id=RemoteId("zzz", 0), parents=[ROOT],
                    ops=[RemoteIns(ROOT, ROOT, "Z")])
    srv.submit_txn("d", t_a)
    srv.tick()
    assert srv.counters.get("lane_rank_remaps") >= 1
    srv.submit_txn("d", t_z)
    srv.submit_local("d", "mmm", 0, ins_content="x")
    srv.tick()
    assert_lanes_equal_oracles(srv)
    # Cross-check against a one-shot oracle replay of the same history.
    twin = ListCRDT()
    for t in export_txns_since(srv.doc_state("d").oracle, 0):
        twin.apply_remote_txn(t)
    assert srv.doc_string("d") == twin.to_string()


def test_evict_restore_after_splits_reseeds_bit_identical(tmp_path):
    """Upload-path seeding of a lane whose pre-eviction device state
    had split blocks and stale hints: the reseeded packed state must
    read back bit-identical to the oracle."""
    srv = DocServer(cfg(spool_dir=str(tmp_path)))
    srv.admit_doc("d")
    _fragment(srv, "d", edits=12)
    doc = srv.doc_state("d")
    pre_evict = oracle_signed(doc.oracle)
    srv.residency.evict(doc)
    srv.submit_local("d", "ed", 0, ins_content="Z")
    srv.tick()
    assert doc.resident and doc.in_lane
    got = srv.residency.backends[0].lane_signed(doc.lane)
    assert np.array_equal(got, oracle_signed(doc.oracle))
    # The reseeded body is the pre-eviction body plus the one prepended
    # char (same chars, shifted one position right).
    assert len(got) == len(pre_evict) + 1
    assert np.array_equal(got[1:], pre_evict)
    assert srv.verify_doc("d")


def test_run_row_overflow_degrades_to_host_oracle():
    """A doc whose RUN-ROW count outgrows the blocked lane budget keeps
    serving from the host oracle: lane freed, no assert, content still
    converges (the flat overflow contract, run-row unit)."""
    srv = DocServer(cfg(max_queue_per_doc=512))
    srv.admit_doc("d")
    backend = srv.residency.backends[0]
    budget = backend.row_budget
    assert budget > 0
    # Single-char prepends never merge: run rows == edits.
    for i in range(budget + 6):
        srv.submit_local("d", "ed", 0, ins_content="x")
        if i % 8 == 7:
            srv.tick()
    srv.drain(max_ticks=128)
    doc = srv.doc_state("d")
    assert doc.degraded and not doc.in_lane
    assert srv.counters.get("lane_overflow_degraded") >= 1
    assert len(srv.doc_string("d")) == budget + 6
    srv.submit_local("d", "ed", 0, ins_content="tail")
    srv.tick()
    assert srv.doc_string("d").startswith("tail")


def test_replace_step_growth_counts_both_branches():
    """A compiled local REPLACE step carries a delete AND an insert in
    ONE device step; each active branch can splice +2 rows, so the
    capacity probes must budget 4 for it — a 2/step bound would make
    the kernel's out-of-blocks flag reachable from ``submit_local``."""
    from text_crdt_rust_tpu.ops import batch as B
    from text_crdt_rust_tpu.utils.testdata import TestPatch

    backend = make_lane_backend("rle-lanes-mixed", lanes=2, capacity=128,
                                order_capacity=512, lmax=4, block_k=8)
    ops, _ = B.compile_local_patches([TestPatch(2, 3, "xy")], lmax=4,
                                     start_order=10)
    assert ops.num_steps == 1
    assert int(backend._stream_growth(ops.del_len, ops.ins_len)) == 4
    # And end-to-end: replace edits through the serve surface stay
    # bit-identical on the lanes backend.
    srv = DocServer(cfg())
    srv.admit_doc("d")
    srv.submit_local("d", "ed", 0, ins_content="abcdefgh")
    srv.tick()
    srv.submit_local("d", "ed", 2, del_len=3, ins_content="XY")
    srv.submit_local("d", "ed", 0, del_len=1, ins_content="z")
    srv.drain()
    assert srv.doc_string("d") == "zbXYfgh"
    assert_lanes_equal_oracles(srv)


def test_small_loadgen_on_lanes_backend_converges():
    """A compressed closed loop (faults + forced evictions) on the
    lanes backend: every doc bit-identical to its twin, every lane to
    its oracle. The full 200-doc acceptance shape runs in ``slow``."""
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    gen = ServeLoadGen(docs=10, agents_per_doc=2, ticks=8,
                       events_per_tick=10, zipf_alpha=1.1,
                       fault_rate=0.10, local_prob=0.25, seed=11,
                       cfg=cfg(lanes_per_shard=4))
    report = gen.run()
    assert report["converged"], report["mismatches"]
    assert report["server"]["evictions"] >= 1
    assert report["tick_ms"]["samples"] > 0


@pytest.mark.slow
def test_loadgen_acceptance_shape_lanes_vs_flat_twin():
    """The ISSUE-4 acceptance run: 200 docs x 3 agents, 10% per-class
    faults, evictions forced — on the lanes backend, bit-identical
    per doc to a FlatLaneBackend twin run of the same seed AND to the
    host oracles, with shapes_seen bounded by the step buckets."""
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    reports = {}
    strings = {}
    for engine in ("rle-lanes-mixed", "flat"):
        scfg = ServeConfig(engine=engine, num_shards=2, lanes_per_shard=16)
        gen = ServeLoadGen(docs=200, agents_per_doc=3, ticks=60,
                           events_per_tick=48, zipf_alpha=1.1,
                           fault_rate=0.10, local_prob=0.25, seed=7,
                           cfg=scfg)
        reports[engine] = gen.run()
        assert reports[engine]["converged"], reports[engine]["mismatches"]
        assert reports[engine]["server"]["evictions"] >= 20
        strings[engine] = {w.doc_id: gen.server.doc_string(w.doc_id)
                           for w in gen.worlds}
        if engine == "rle-lanes-mixed":
            for b in gen.server.residency.backends:
                assert b.shapes_seen <= set(scfg.step_buckets), \
                    b.shapes_seen
    assert strings["rle-lanes-mixed"] == strings["flat"]

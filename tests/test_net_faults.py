"""Fault-injection fuzz: sync through a hostile channel must converge.

ISSUE 1 acceptance: two-peer sync through a channel with drop / dup /
reorder / truncate / bit-flip at 10% each converges to bit-identical
``doc_spans``/frontier on the oracle AND at least one device engine
(`ops.flat`) across ≥50 tier-1 seeds (≥500 in the ``slow`` variant),
with retries/rejections visible in metrics counters — and zero uncaught
exceptions anywhere in the pipeline.

Every seed is deterministic: the edit stream, the fault rolls, and the
protocol's backoff clock are all seeded/logical, so a failure replays
exactly.
"""
import random

import pytest

from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import (
    agent_watermarks,
    export_txns_since,
    remote_frontier,
    state_digest,
)
from text_crdt_rust_tpu.net import FaultSpec, FaultyChannel, ResyncSession
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA

FAULTS = FaultSpec.all(0.10)
EDIT_ROUNDS = 8
EDITS_PER_ROUND = 3
MAX_ROUNDS = 150

# One fixed device shape -> one jit compile shared by every seed.
SMAX = 384
CAP = 512
LMAX = 8

ALPHABET = "abcdefghij KLMNO.xyz"


def seeded_edits(rng: random.Random, doc: ListCRDT, agent: int,
                 n: int) -> None:
    for _ in range(n):
        ln = len(doc)
        if ln and rng.random() < 0.35:
            pos = rng.randrange(ln)
            doc.local_delete(agent, pos, min(1 + rng.randrange(3), ln - pos))
        else:
            pos = rng.randrange(ln + 1)
            text = "".join(rng.choice(ALPHABET)
                           for _ in range(1 + rng.randrange(4)))
            doc.local_insert(agent, pos, text)


def converged(docs) -> bool:
    d0 = state_digest(docs[0])
    w0 = agent_watermarks(docs[0])
    return all(state_digest(d) == d0 and agent_watermarks(d) == w0
               for d in docs[1:])


def pump_two_peer(seed: int, faults: FaultSpec = FAULTS,
                  max_rounds: int = MAX_ROUNDS,
                  wires: tuple = ("row", "row")):
    """Run one seeded two-peer faulty sync to convergence; returns the
    sessions + channels for metric assertions. ``wires`` picks each
    peer's TXNS encoding (decode negotiates on the version byte, so
    mixed fleets interoperate)."""
    rng = random.Random(seed)
    da, db = ListCRDT(), ListCRDT()
    aa = da.get_or_create_agent_id(f"alice-{seed}")
    ab = db.get_or_create_agent_id(f"bob-{seed}")
    sa = ResyncSession(da, wire=wires[0])
    sb = ResyncSession(db, wire=wires[1])
    ch_ab = FaultyChannel(faults, seed=seed * 2 + 1)
    ch_ba = FaultyChannel(faults, seed=seed * 2 + 2)

    for rnd in range(max_rounds):
        if rnd < EDIT_ROUNDS:
            seeded_edits(rng, da, aa, EDITS_PER_ROUND)
            seeded_edits(rng, db, ab, EDITS_PER_ROUND)
        for f in sa.poll():
            ch_ab.send(f)
        for f in sb.poll():
            ch_ba.send(f)
        for m in ch_ab.drain():
            for r in sb.receive(m):
                ch_ba.send(r)
        for m in ch_ba.drain():
            for r in sa.receive(m):
                ch_ab.send(r)
        if rnd >= EDIT_ROUNDS and converged([da, db]):
            break
    else:
        pytest.fail(
            f"seed {seed}: no convergence in {max_rounds} rounds; "
            f"missing A={sa.buffer.missing()} B={sb.buffer.missing()}")
    return sa, sb, ch_ab, ch_ba


def assert_oracle_convergence(sa: ResyncSession, sb: ResyncSession) -> None:
    da, db = sa.doc, sb.doc
    da.check()
    db.check()
    assert da.to_string() == db.to_string()
    assert remote_frontier(da) == remote_frontier(db)
    # Orders are peer-local, so cross-peer doc_spans compare in remote-id
    # space: (agent, seq, deleted) per item, in converged document order.
    def portable(doc):
        return [(doc.order_to_remote_id(int(doc.order[i])),
                 bool(doc.deleted[i])) for i in range(doc.n)]
    assert portable(da) == portable(db)
    assert not sa.divergence_detected and not sb.divergence_detected


OCAP_LANES = 1024  # fixed by-order table rows for the lanes ride-along


def assert_device_convergence(doc: ListCRDT) -> None:
    """Replay the converged history through the flat device engine AND
    the per-lane mixed engines (blocked + un-blocked): bit-identical
    state vs this peer's oracle.  Every shape is fixed (SMAX/CAP/OCAP)
    so all seeds share one trace per engine."""
    from text_crdt_rust_tpu.ops import rle_lanes as RL
    from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM

    table = B.AgentTable(sorted(cd.name for cd in doc.client_data))
    txns = export_txns_since(doc, 0)
    ops, _ = B.compile_remote_txns(txns, table, lmax=LMAX)
    assert ops.num_steps <= SMAX, f"bump SMAX: {ops.num_steps}"
    flat = F.apply_ops(SA.make_flat_doc(CAP), B.pad_ops(ops, SMAX))
    assert SA.doc_spans(flat) == doc.doc_spans()
    assert SA.to_string(flat) == doc.to_string()

    # ISSUE-2 ride-along: the blocked lanes engines must survive the
    # fault-injection mesh bit-identically too.
    import numpy as np

    adv = int(np.asarray(ops.order_advance, dtype=np.int64).sum())
    assert adv + ops.lmax <= OCAP_LANES, f"bump OCAP_LANES: {adv}"
    stacked = B.stack_ops([B.pad_ops(ops, SMAX)])
    want = [(-1 if doc.deleted[i] else 1) * (int(doc.order[i]) + 1)
            for i in range(doc.n)]
    kw = dict(capacity=CAP, order_capacity=OCAP_LANES, chunk=128,
              interpret=True)
    for res in (RLM.replay_lanes_mixed(stacked, **kw),
                RLM.replay_lanes_mixed_blocked(stacked, block_k=64,
                                               **kw)):
        res.check()
        assert RL.expand_lane(res, 0).tolist() == want


def _fuzz_seed_range(seeds):
    total = {"frames_rejected": 0, "range_retries": 0,
             "duplicates_dropped": 0}
    faults_seen = {"dropped": 0, "truncated": 0, "bitflipped": 0,
                   "duplicated": 0, "reordered": 0}
    for seed in seeds:
        sa, sb, ch_ab, ch_ba = pump_two_peer(seed)
        assert_oracle_convergence(sa, sb)
        assert_device_convergence(sa.doc)
        for s in (sa, sb):
            for k in total:
                if k == "duplicates_dropped":
                    total[k] += s.buffer.duplicates_dropped
                else:
                    total[k] += s.counters.get(k)
        for ch in (ch_ab, ch_ba):
            for k in faults_seen:
                faults_seen[k] += ch.counters[k]
    # The channel actually injected every fault class, and the sessions
    # both saw the damage (rejections) and recovered (retries, dups).
    for k, v in faults_seen.items():
        assert v > 0, f"fault class {k} never fired over {len(seeds)} seeds"
    assert total["frames_rejected"] > 0
    assert total["range_retries"] > 0
    assert total["duplicates_dropped"] > 0


class TestTwoPeerFuzz:
    def test_smoke_50_seeds(self):
        """Tier-1: 50 seeds through 10%-everything channels."""
        _fuzz_seed_range(range(50))

    @pytest.mark.slow
    def test_full_500_seeds(self):
        _fuzz_seed_range(range(500))

    @pytest.mark.slow
    def test_deep_500_more_seeds(self):
        """Deep-fuzz volume (ROADMAP #6): grow the net-mesh surface
        toward parity with the 1,000+-seed blocked-lanes sweeps —
        500 further two-peer seeds on a fresh range."""
        _fuzz_seed_range(range(500, 1000))

    def test_faultless_channel_converges_fast(self):
        sa, sb, _, _ = pump_two_peer(
            9999, faults=FaultSpec(), max_rounds=EDIT_ROUNDS + 4)
        assert_oracle_convergence(sa, sb)
        assert sa.counters.get("frames_rejected") == 0
        assert sb.counters.get("frames_rejected") == 0

    def test_mixed_wire_smoke_10_seeds(self):
        """ISSUE-7 ride-along: one peer on the row wire, one on the
        columnar wire — version negotiation makes a mixed fleet
        converge through the same 10%-everything fault classes."""
        for seed in range(10):
            sa, sb, _, _ = pump_two_peer(seed, wires=("row", "columnar"))
            assert_oracle_convergence(sa, sb)
            assert sa.counters.get("wire_txn_bytes_sent") > 0
            assert sb.counters.get("wire_txn_bytes_sent") > 0

    @pytest.mark.slow
    def test_mixed_wire_100_seeds(self):
        """Deep mixed-wire sweep (both orientations), device engines
        included."""
        for seed in range(1000, 1050):
            wires = ("row", "columnar") if seed % 2 else ("columnar", "row")
            sa, sb, _, _ = pump_two_peer(seed, wires=wires)
            assert_oracle_convergence(sa, sb)
            assert_device_convergence(sa.doc)


class TestNPeerFuzz:
    def _pump_mesh(self, seed: int, n_peers: int = 3,
                   max_rounds: int = MAX_ROUNDS):
        """Full mesh: one session per directed (peer, neighbor) edge, all
        sessions of a peer sharing its doc (watermark sync keeps their
        causal buffers consistent)."""
        rng = random.Random(seed)
        docs, agents = [], []
        for p in range(n_peers):
            d = ListCRDT()
            agents.append(d.get_or_create_agent_id(f"peer{p}-{seed}"))
            docs.append(d)
        sess = {}
        chan = {}
        for i in range(n_peers):
            for j in range(n_peers):
                if i != j:
                    sess[i, j] = ResyncSession(docs[i])
                    chan[i, j] = FaultyChannel(
                        FAULTS, seed=seed * 100 + i * 10 + j)
        for rnd in range(max_rounds):
            if rnd < EDIT_ROUNDS:
                for p in range(n_peers):
                    seeded_edits(rng, docs[p], agents[p], 2)
            for (i, j), s in sess.items():
                for f in s.poll():
                    chan[i, j].send(f)
            for (i, j), ch in chan.items():
                for m in ch.drain():
                    for r in sess[j, i].receive(m):
                        chan[j, i].send(r)
            if rnd >= EDIT_ROUNDS and converged(docs):
                return docs
        pytest.fail(f"seed {seed}: {n_peers}-peer mesh did not converge")

    def test_three_peer_mesh_10_seeds(self):
        for seed in range(10):
            docs = self._pump_mesh(seed)
            for d in docs:
                d.check()
            texts = {d.to_string() for d in docs}
            assert len(texts) == 1
            fronts = {frozenset(remote_frontier(d)) for d in docs}
            assert len(fronts) == 1
            assert_device_convergence(docs[0])

    @pytest.mark.slow
    def test_three_peer_mesh_50_seeds(self):
        for seed in range(10, 60):
            docs = self._pump_mesh(seed)
            texts = {d.to_string() for d in docs}
            assert len(texts) == 1

    @pytest.mark.slow
    def test_three_peer_mesh_190_more_seeds(self):
        """Deep-fuzz volume (ROADMAP #6): the mesh surface is the
        costliest per seed (6 directed sessions), so it grows in
        larger strides per round — 190 further seeds here (60..250
        cumulative) toward the 1,000-seed blocked-lanes parity."""
        for seed in range(60, 250):
            docs = self._pump_mesh(seed)
            texts = {d.to_string() for d in docs}
            assert len(texts) == 1

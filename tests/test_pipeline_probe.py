"""Tier-1 smoke for ``perf/pipeline_probe.py`` (ISSUE 12 acceptance):
the committed ``perf/pipeline_r14.json`` is the full 200-doc run; this
keeps the small-scale path green (serial-vs-pipelined byte-identity,
overlap accrued, audits green) so the JSON can't silently rot, and a
``slow``-tier run re-measures the committed claims at full scale.

Wall-based claims (the 5% regression bar) are asserted only against
the committed artifact and in the ``slow`` re-run — smoke walls on a
shared box are noise.
"""
import importlib.util
import json
import os

import pytest

PROBE = os.path.join("perf", "pipeline_probe.py")
COMMITTED = os.path.join("perf", "pipeline_r14.json")


def _load_probe():
    spec = importlib.util.spec_from_file_location("pp", PROBE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# Slow tier since PR 17 (wall budget: ~37 s of the 870 s gate): the
# probe-smoke pattern keeps tier-1 representatives in the device-
# prefill, flow, and lint-sanitize probe smokes; pipelined serve
# byte-identity itself stays tier-1 in test_serve_pipeline /
# test_serve_train.
@pytest.mark.slow
def test_probe_smoke_path_green():
    out = _load_probe().run_matrix(smoke=True, reps=1)
    p = out["pipeline"]
    assert p["logical_streams_byte_identical"]
    assert p["flow_reports_identical"]
    assert p["serial"]["pipeline_ticks"] == 1
    assert p["serial"]["overlap_frac"] == 0.0
    assert p["pipelined"]["pipeline_ticks"] == 2
    assert p["pipelined"]["overlap_frac"] > 0.0
    assert out["defaults"]["audit_ok"]
    # Every nagle arm converged with a green audit, and the sweep is
    # monotone where it must be: the smallest window's clean-remote
    # p50 is no worse than the biggest's.
    arms = out["nagle_sweep"]
    assert all(a["audit_ok"] for a in arms.values())
    keys = list(arms)
    assert arms[keys[-1]]["clean_p50"] <= arms[keys[0]]["clean_p50"]
    # lmax sweep: larger chunks never need MORE device steps.
    lx = out["lmax_sweep"]
    assert lx["32"]["steps_total"] <= lx["16"]["steps_total"] \
        <= lx["8"]["steps_total"]


def test_committed_pipeline_json_claims():
    """The committed probe JSON's acceptance: byte-identical modes,
    overlap > 0 within the 5% wall bar, and the Nagle sweep's
    clean-remote op-age cut (p50 <= 6 at the shipped default, from
    ~12-13 at the old 64-txn window)."""
    with open(COMMITTED) as f:
        d = json.load(f)
    assert not d["smoke"], "committed JSON must be the full 200-doc run"
    assert d["workload"]["docs"] == 200
    assert d["acceptance"]["pass"]
    p = d["pipeline"]
    assert p["logical_streams_byte_identical"]
    assert p["flow_reports_identical"]
    assert p["pipelined"]["overlap_frac"] > 0.0
    assert p["wall_delta_pct"] <= d["acceptance"][
        "wall_regression_bar_pct"]
    assert d["acceptance"]["clean_p50_before"] >= 12
    assert d["acceptance"]["clean_p50_shipped"] <= d["acceptance"][
        "clean_p50_floor_ticks"]
    # The shipped defaults row matches a swept arm's logical numbers.
    key = f"{d['defaults']['nagle_txns']}/{d['defaults']['nagle_rounds']}"
    assert key in d["nagle_sweep"]
    assert d["nagle_sweep"][key]["clean_p50"] == d["defaults"][
        "clean_p50"]


@pytest.mark.slow
def test_probe_full_rerun_matches_committed_claims():
    out = _load_probe().run_matrix(smoke=False, reps=2)
    assert out["acceptance"]["pass"], out

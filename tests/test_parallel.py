"""Multi-chip sharding + causal streaming tests (virtual 8-device CPU mesh).

Validates the same path the driver's ``dryrun_multichip`` exercises: real
dp/sp shardings over a ``jax.sharding.Mesh``, one full apply step, results
bit-equal to the unsharded engine and the host oracle.
"""
import random

import jax
import pytest

from text_crdt_rust_tpu.common import (
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.parallel import (
    CausalBuffer,
    make_mesh,
    make_sharded_apply,
    shard_docs,
    shard_ops,
)
from text_crdt_rust_tpu.parallel.mesh import make_sharded_apply_1doc

from test_device_flat import (
    jax_tree_index,
    oracle_from_patches,
    random_patches,
)


class TestMesh:
    def test_devices_available(self):
        assert len(jax.devices()) == 8, (
            "conftest must force an 8-device CPU mesh")

    @pytest.mark.parametrize("dp,sp", [(8, 1), (4, 2), (2, 4)])
    def test_sharded_batch_matches_unsharded(self, dp, sp):
        rng = random.Random(31)
        patches, content = random_patches(rng, 40)
        ops, _ = B.compile_local_patches(patches, lmax=4)
        batch = 8
        batched = B.tile_ops(ops, batch)
        docs = SA.stack_docs(
            B.prefill_logs(SA.make_flat_doc(256), ops), batch)

        mesh = make_mesh(dp=dp, sp=sp)
        sharded_docs = shard_docs(docs, mesh)
        sharded_ops = shard_ops(batched, mesh)
        apply_fn = make_sharded_apply(mesh, donate=False)
        out = apply_fn(sharded_docs, sharded_ops)

        ref = F.apply_ops_batch(docs, batched)
        for i in range(batch):
            a = jax_tree_index(out, i)
            b = jax_tree_index(ref, i)
            assert SA.to_string(a) == SA.to_string(b) == content
            assert SA.doc_spans(a) == SA.doc_spans(b)

    def test_fresh_docs_without_manual_prefill(self):
        # Regression (ADVICE r1): the sharded apply must prefill the
        # by-order logs itself — a fresh make_flat_doc applied without
        # prefilled logs returns NUL chars and wrong tiebreak ranks.
        rng = random.Random(61)
        patches, content = random_patches(rng, 30)
        ops, _ = B.compile_local_patches(patches, lmax=4)
        batch = 8
        batched = B.tile_ops(ops, batch)
        docs = SA.stack_docs(SA.make_flat_doc(256), batch)  # NOT prefilled

        mesh = make_mesh(dp=4, sp=2)
        apply_fn = make_sharded_apply(mesh, donate=False)
        out = apply_fn(shard_docs(docs, mesh), shard_ops(batched, mesh))
        assert SA.to_string(jax_tree_index(out, 0)) == content

    def test_seq_parallel_one_doc(self):
        # Long-context path: ONE document's item axis sharded over all 8
        # chips (SURVEY §5 long-context row).
        rng = random.Random(41)
        patches, content = random_patches(rng, 60)
        ops, _ = B.compile_local_patches(patches, lmax=4)
        mesh = make_mesh(dp=1, sp=8)
        doc = shard_docs(
            B.prefill_logs(SA.make_flat_doc(512), ops), mesh, batched=False)
        apply_fn = make_sharded_apply_1doc(mesh)
        out = apply_fn(doc, shard_ops(ops, mesh, batched=False))
        assert SA.to_string(out) == content

    def test_remote_ops_sharded(self):
        # The YATA integrate while_loop must also compile under sharding.
        rng = random.Random(51)
        pa, _ = random_patches(rng, 30)
        pb, _ = random_patches(rng, 30)
        a = oracle_from_patches(pa, agent="peer-a")
        bdoc = oracle_from_patches(pb, agent="peer-b")
        txns = export_txns_since(a, 0) + export_txns_since(bdoc, 0)
        oracle = ListCRDT()
        for t in txns:
            oracle.apply_remote_txn(t)

        table = B.AgentTable(["peer-a", "peer-b"])
        ops, _ = B.compile_remote_txns(txns, table, lmax=4)
        batch = 4
        batched = B.tile_ops(ops, batch)
        docs = SA.stack_docs(
            B.prefill_logs(SA.make_flat_doc(512), ops), batch)
        mesh = make_mesh(dp=4, sp=2)
        out = make_sharded_apply(mesh, donate=False)(
            shard_docs(docs, mesh), shard_ops(batched, mesh))
        for i in range(batch):
            one = jax_tree_index(out, i)
            assert SA.to_string(one) == oracle.to_string()


def _txn(agent, seq, parents, text, left=None):
    root = RemoteId("ROOT", 0xFFFFFFFF)
    return RemoteTxn(
        id=RemoteId(agent, seq), parents=parents,
        ops=[RemoteIns(left or root, root, text)],
    )


class TestCausalBuffer:
    def test_in_order_passthrough(self):
        buf = CausalBuffer()
        t0 = _txn("amy", 0, [], "aa")
        t1 = _txn("amy", 2, [RemoteId("amy", 1)], "bb",
                  left=RemoteId("amy", 1))
        assert buf.add(t0) == [t0]
        assert buf.add(t1) == [t1]
        assert buf.pending == 0

    def test_reorder_released_in_causal_order(self):
        buf = CausalBuffer()
        t0 = _txn("amy", 0, [], "aa")
        t1 = _txn("amy", 2, [RemoteId("amy", 1)], "bb",
                  left=RemoteId("amy", 1))
        assert buf.add(t1) == []          # arrives first, held
        assert buf.pending == 1
        assert buf.add(t0) == [t0, t1]    # unblocks both, in causal order
        assert buf.pending == 0

    def test_cross_agent_parent_dependency(self):
        buf = CausalBuffer()
        base = _txn("amy", 0, [], "aa")
        child = _txn("bob", 0, [RemoteId("amy", 1)], "bb",
                     left=RemoteId("amy", 1))
        assert buf.add(child) == []       # parent unknown
        assert buf.missing() == [RemoteId("amy", 0)]
        assert buf.add(base) == [base, child]

    def test_duplicates_dropped(self):
        buf = CausalBuffer()
        t0 = _txn("amy", 0, [], "aa")
        assert buf.add(t0) == [t0]
        assert buf.add(t0) == []          # replayed delivery

    def test_blocked_duplicates_not_buffered(self):
        # Re-delivery of a still-blocked txn must not grow the buffer.
        buf = CausalBuffer()
        child = _txn("bob", 0, [RemoteId("amy", 1)], "bb",
                     left=RemoteId("amy", 1))
        for _ in range(5):
            assert buf.add(child) == []
        assert buf.pending == 1
        base = _txn("amy", 0, [], "aa")
        assert buf.add(base) == [base, child]
        assert buf.pending == 0

    def test_partially_known_txn_split_not_dropped(self):
        # Regression: a re-sync can deliver ONE txn covering seqs the buffer
        # already released plus new ones (the source's txns RLE merges
        # linear history, `txn.rs:38-42`). The unknown suffix must be
        # released, not silently dropped as a duplicate.
        src = ListCRDT()
        a = src.get_or_create_agent_id("amy")
        src.local_insert(a, 0, "aa")
        early = export_txns_since(src, 0)
        src.local_insert(a, 2, "bb")
        merged = export_txns_since(src, 0)   # one txn covering seqs 0..4
        assert len(merged) == 1

        buf = CausalBuffer()
        dst = ListCRDT()
        for t in buf.add_all(early) + buf.add(merged[0]):
            dst.apply_remote_txn(t)
        assert buf.pending == 0
        assert buf.missing() == []
        assert dst.to_string() == "aabb"

    def test_same_id_redelivery_keeps_longer(self):
        # Two deliveries share id (amy,0) — an early export and a later
        # RLE-merged one covering more seqs (`txn.rs:38-42`). The longer
        # one supersedes the shorter in the buffer.
        root = RemoteId("ROOT", 0xFFFFFFFF)
        zed = _txn("zed", 0, [], "z")
        t0 = RemoteTxn(
            id=RemoteId("amy", 0), parents=[RemoteId("zed", 0)],
            ops=[RemoteIns(root, root, "aa")])
        t01 = RemoteTxn(
            id=RemoteId("amy", 0), parents=[RemoteId("zed", 0)],
            ops=[RemoteIns(root, root, "aa"),
                 RemoteIns(RemoteId("amy", 1), root, "bb")])

        expected = ListCRDT()
        for t in (zed, t01):
            expected.apply_remote_txn(t)

        buf = CausalBuffer()
        assert buf.add(t0) == []     # parent (zed,0) unknown
        assert buf.add(t01) == []    # same id: replaces the shorter t0
        assert buf.pending == 1
        out = buf.add(zed)
        assert [(t.id.agent, t.id.seq) for t in out] == [
            ("zed", 0), ("amy", 0)]
        dst = ListCRDT()
        for t in out:
            dst.apply_remote_txn(t)
        assert dst.to_string() == expected.to_string()

    def test_pending_txn_retrimmed_when_watermark_moves(self):
        # A pending txn (distinct id) partially overlapped by a merged
        # delivery that releases first: the pending one must be re-trimmed
        # to its unknown suffix, not dropped.
        from text_crdt_rust_tpu.common import split_txn_suffix
        root = RemoteId("ROOT", 0xFFFFFFFF)
        zed = _txn("zed", 0, [], "z")
        t_merged = RemoteTxn(
            id=RemoteId("amy", 0), parents=[],
            ops=[RemoteIns(root, root, "aa"),
                 RemoteIns(RemoteId("amy", 1), root, "bb")])   # seqs 0..4
        t_late = RemoteTxn(
            id=RemoteId("amy", 2), parents=[RemoteId("zed", 0)],
            ops=[RemoteIns(RemoteId("amy", 1), root, "bb"),
                 RemoteIns(RemoteId("amy", 3), root, "cc")])   # seqs 2..6

        buf = CausalBuffer()
        assert buf.add(t_late) == []       # gap + unknown parent
        out = buf.add(t_merged)            # covers 0..4; t_late trims to 4..6
        assert [(t.id.agent, t.id.seq) for t in out] == [("amy", 0),
                                                         ("amy", 4)]
        assert buf.pending == 0

        expected = ListCRDT()
        for t in (t_merged, split_txn_suffix(t_late, 2)):
            expected.apply_remote_txn(t)
        dst = ListCRDT()
        for t in out:
            dst.apply_remote_txn(t)
        assert dst.to_string() == expected.to_string() == "aabbcc"

    def test_random_shuffle_replays_whole_history(self):
        rng = random.Random(77)
        patches, content = random_patches(rng, 50)
        src = oracle_from_patches(patches, agent="shuf")
        txns = export_txns_since(src, 0)
        shuffled = txns[:]
        rng.shuffle(shuffled)
        buf = CausalBuffer()
        dst = ListCRDT()
        applied = 0
        for t in shuffled:
            for ready in buf.add(t):
                dst.apply_remote_txn(ready)
                applied += 1
        assert buf.pending == 0
        assert applied == len(txns)
        assert dst.to_string() == content


class TestShardedScale:
    """r2 verdict weak #7: beyond tiny smokes — a real trace prefix, a
    remote-op storm, and an sp-sharded doc whose items actually span
    shard boundaries, all on the virtual 8-device mesh."""

    @pytest.mark.slow
    def test_sharded_trace_prefix(self):
        from text_crdt_rust_tpu.utils.testdata import (
            flatten_patches, load_testing_data, trace_path)

        data = load_testing_data(trace_path("automerge-paper"))
        patches = flatten_patches(data)[:2000]
        want = ""
        for p in patches:
            want = want[:p.pos] + p.ins_content + want[p.pos + p.del_len:]
        ops, _ = B.compile_local_patches(patches, lmax=8)
        mesh = make_mesh(dp=2, sp=4)
        batch = 4
        docs = SA.stack_docs(SA.make_flat_doc(4096), batch)
        docs = shard_docs(docs, mesh)
        apply_fn = make_sharded_apply(mesh, donate=False)
        out = apply_fn(docs, shard_ops(B.tile_ops(ops, batch), mesh))
        jax.block_until_ready(out.signed)
        for d in range(batch):
            assert SA.to_string(jax_tree_index(out, d)) == want

    def test_sharded_remote_storm(self):
        from text_crdt_rust_tpu.utils.randedit import make_storm

        txns, receiver = make_storm(4, 20, 3, seed=11)
        want = receiver.to_string()
        table = B.AgentTable(sorted({t.id.agent for t in txns}))
        ops, _ = B.compile_remote_txns(txns, table, lmax=8)
        mesh = make_mesh(dp=4, sp=2)
        batch = 4
        docs = SA.stack_docs(SA.make_flat_doc(1024), batch)
        docs = shard_docs(docs, mesh)
        apply_fn = make_sharded_apply(mesh, donate=False)
        out = apply_fn(docs, shard_ops(B.tile_ops(ops, batch), mesh))
        jax.block_until_ready(out.signed)
        for d in range(batch):
            assert SA.to_string(jax_tree_index(out, d)) == want

    def test_sp_doc_items_span_shards(self):
        # One doc, sp=8 over capacity 1024: 128 rows per shard. The edit
        # stream grows the doc past 128 raw items, so items occupy
        # multiple shards and every position scan crosses shard carries.
        rng = random.Random(73)
        patches, content = random_patches(rng, 400)
        assert len(content) > 1024 // 8, len(content)  # >= 2 shards live
        ops, _ = B.compile_local_patches(patches, lmax=4)
        oracle = oracle_from_patches(patches)
        mesh = make_mesh(dp=1, sp=8)
        doc = shard_docs(
            B.prefill_logs(SA.make_flat_doc(1024, 4096), ops), mesh,
            batched=False)
        apply_fn = make_sharded_apply_1doc(mesh)
        out = apply_fn(doc, shard_ops(ops, mesh, batched=False))
        jax.block_until_ready(out.signed)
        assert SA.to_string(out) == content == oracle.to_string()
        assert SA.doc_spans(out) == oracle.doc_spans()

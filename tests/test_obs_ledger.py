"""Cost ledger + regression gate (ISSUE 10): the committed
``perf/COST_LEDGER.json`` validates and covers the acceptance floor,
``bench.py --check-ledger`` re-derives every cpu cell deterministically,
and an injected drift fails the gate LOUD with the metric named.

The end-to-end gate run uses a mutated copy of the committed ledger and
asserts the diff list contains EXACTLY the injected metric — which
simultaneously proves (a) every other committed metric re-derived
bit-for-logical-bit (the clean gate would pass), and (b) the gate fails
with a precise name on drift (the drift-injection acceptance), for the
price of one subprocess."""
import json
import os
import subprocess
import sys

import pytest

from text_crdt_rust_tpu.obs.ledger import (
    LEDGER_SCHEMA_VERSION,
    METRIC_FAMILIES,
    cpu_cell_names,
    diff_cell,
    diff_ledger,
    families_covered,
    load_ledger,
    metric,
    validate_ledger,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER = os.path.join(REPO, "perf", "COST_LEDGER.json")


# ------------------------------------------------- committed artifact ----


def test_committed_ledger_validates_and_covers_acceptance_floor():
    led = load_ledger(LEDGER)
    validate_ledger(led)  # raises naming violations
    assert led["schema_version"] == LEDGER_SCHEMA_VERSION
    # ISSUE 10 acceptance: >= 6 metric families across at least the
    # serve, serve-lanes, fused-trace and sp cells.
    assert {"serve", "serve-lanes", "fused-trace", "sp"} <= set(
        led["cells"])
    fams = families_covered(led)
    assert len(fams) >= 6, fams
    assert fams <= set(METRIC_FAMILIES)
    # The cpu cells are the wall-clock-free gate's surface.
    assert set(cpu_cell_names(led)) >= {"serve", "serve-lanes",
                                        "fused-trace", "sp"}
    # Headline invariants the ledger now pins: the sp ICI cost model
    # and the blocked-lanes touched-row economy.
    assert led["cells"]["sp"]["metrics"][
        "collectives_per_step"]["v"] == 124
    assert led["cells"]["serve-lanes"]["metrics"][
        "touched_rows_ratio"]["v"] >= 5


def test_committed_ledger_has_no_wall_metrics_in_cpu_cells():
    """The ledger is a LOGICAL cost contract: wall-clock belongs only
    to device cells (silicon re-record)."""
    led = load_ledger(LEDGER)
    for name in cpu_cell_names(led):
        for mname, m in led["cells"][name]["metrics"].items():
            assert m["family"] != "wall", f"{name}.{mname}"


# ------------------------------------------------------- diff engine ----


def _cell(**metrics):
    return {"kind": "cpu", "workload": {"pin": 1}, "metrics": metrics}


def test_exact_metric_drift_is_named():
    a = _cell(steps=metric(10, "steps"))
    b = _cell(steps=metric(11, "steps"))
    diffs = diff_cell("c", a, b)
    assert len(diffs) == 1
    assert "c.steps" in diffs[0] and "11 != committed 10" in diffs[0]


def test_banded_metric_allows_tolerance_and_catches_escape():
    a = _cell(flops=metric(1000.0, "hlo", tol=0.5))
    assert diff_cell("c", a, _cell(flops=metric(1400.0, "hlo",
                                                tol=0.5))) == []
    diffs = diff_cell("c", a, _cell(flops=metric(1501.0, "hlo",
                                                 tol=0.5)))
    assert len(diffs) == 1 and "outside 1000" in diffs[0]


def test_missing_and_extra_metrics_are_both_drift():
    a = _cell(steps=metric(10, "steps"), gone=metric(1, "steps"))
    b = _cell(steps=metric(10, "steps"), new=metric(2, "steps"))
    diffs = diff_cell("c", a, b)
    assert any("c.gone" in d and "no longer derives" in d for d in diffs)
    assert any("c.new" in d and "never recorded" in d for d in diffs)


def test_diff_ledger_judges_only_derived_cells():
    led = {"cells": {"a": _cell(x=metric(1, "steps")),
                     "dev": {"kind": "device", "workload": {},
                             "metrics": {"w": metric(9, "wall",
                                                     tol=1.0)}}}}
    ok, diffs = diff_ledger(led, {"a": _cell(x=metric(1, "steps"))})
    assert ok and not diffs  # the device cell is not judged
    ok, diffs = diff_ledger(led, {"b": _cell(x=metric(1, "steps"))})
    assert not ok and "committed ledger does not carry" in diffs[0]


def test_validate_ledger_refuses_drifted_schema():
    with pytest.raises(ValueError, match="schema_version"):
        validate_ledger({"schema_version": LEDGER_SCHEMA_VERSION + 1,
                         "cells": {"c": _cell(x=metric(1, "steps"))}})
    with pytest.raises(ValueError, match="unknown family"):
        validate_ledger({"schema_version": LEDGER_SCHEMA_VERSION,
                         "cells": {"c": _cell(
                             x={"v": 1, "family": "nonsense"})}})
    with pytest.raises(ValueError, match="no cells"):
        validate_ledger({"schema_version": LEDGER_SCHEMA_VERSION})


# ------------------------------------------- the gate, end to end -------


def test_check_ledger_gate_rederives_cells_and_fails_loud(tmp_path):
    """ONE subprocess proves both acceptance bars: every cpu-cell
    metric except the injected one re-derives EXACTLY (so the clean
    gate passes), and the injected counter drift fails the gate with
    the metric named (so the gate fails loud)."""
    led = load_ledger(LEDGER)
    led["cells"]["serve"]["metrics"]["steps_total"]["v"] += 1
    mutated = str(tmp_path / "mutated_ledger.json")
    with open(mutated, "w") as f:
        json.dump(led, f)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "bench.py", "--check-ledger",
         "--ledger", mutated],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert r.returncode == 1, (r.stdout, r.stderr[-2000:])
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["ledger_ok"] is False
    assert sorted(out["cells_checked"]) == sorted(cpu_cell_names(led))
    # Exactly the injected metric drifted — everything else matched.
    assert len(out["diffs"]) == 1
    assert "serve.steps_total" in out["diffs"][0]
    assert "LEDGER DRIFT: serve.steps_total" in r.stderr


def test_check_ledger_refuses_device_cells(tmp_path):
    """Asking the CPU gate for a device cell is a usage error (exit 2),
    not a silent skip — device cells wait for the silicon re-record."""
    import argparse

    import bench as bench_mod

    led = load_ledger(LEDGER)
    led["cells"]["fake-dev"] = {"kind": "device", "workload": {"p": 1},
                                "metrics": {"w": metric(1, "wall",
                                                        tol=1.0)}}
    mutated = str(tmp_path / "with_device_cell.json")
    with open(mutated, "w") as f:
        json.dump(led, f)
    args = argparse.Namespace(ledger=mutated, cells="fake-dev")
    # Refusal happens before any derivation, so this is in-process
    # cheap (no jax work).
    assert bench_mod.run_ledger_check(args) == 2

"""Per-lane divergent MIXED engine (remote ops on per-lane run state)
vs oracle.

Interpreter-mode differential tests.  Every lane carries a DIFFERENT
stream — the production sync shape the lockstep ``rle_mixed`` engine
can't run (VERDICT r4 missing #2) — including per-lane remote YATA
integrations, fragmented/double deletes, mixed local+remote lanes in
the same step, and warm-started chunk chaining.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.common import (
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle_lanes as RL
from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM

from test_device_flat import oracle_from_patches, random_patches

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def compile_txn_lanes(lane_txns, lmax=4, dmax=None):
    """Per-lane RemoteTxn lists -> stacked [S, B] op tensors."""
    opses = []
    for txns in lane_txns:
        table = B.AgentTable()
        for t in txns:
            table.add(t.id.agent)
            for op in t.ops:
                if hasattr(op, "id"):
                    table.add(op.id.agent)
        ops, _ = B.compile_remote_txns(txns, table, lmax=lmax, dmax=dmax)
        opses.append(ops)
    return B.stack_ops(opses)


def oracle_txns(txns):
    doc = ListCRDT()
    for t in txns:
        doc.apply_remote_txn(t)
    return doc


def lane_signed(res, d):
    return RL.expand_lane(res, d).tolist()


def oracle_signed(doc):
    return [(-1 if doc.deleted[i] else 1) * (int(doc.order[i]) + 1)
            for i in range(doc.n)]


def lane_string(stacked, res, d):
    """Lane content from device state + the stream's compile-time chars."""
    chars = {}
    ilens = np.asarray(stacked.ins_len)[:, d]
    starts = np.asarray(stacked.ins_order_start)[:, d]
    cps = np.asarray(stacked.chars)[:, d]
    for s in np.nonzero(ilens)[0]:
        for j in range(int(ilens[s])):
            chars[int(starts[s]) + j] = chr(int(cps[s, j]))
    return "".join(chars[int(o) - 1]
                   for o in RL.expand_lane(res, d) if o > 0)


def assert_lane_equals_oracle(stacked, res, d, oracle):
    assert lane_signed(res, d) == oracle_signed(oracle), f"lane {d}"
    assert lane_string(stacked, res, d) == oracle.to_string(), f"lane {d}"


class TestDivergentRemoteLanes:
    def test_two_lanes_different_tiebreaks(self):
        # Lane 0 and lane 1 get DIFFERENT concurrent-insert storms; the
        # name tiebreak must resolve per lane (`doc.rs:206-216`).
        lane_txns = [
            [RemoteTxn(id=RemoteId(n, 0), parents=[],
                       ops=[RemoteIns(ROOT, ROOT, t)])
             for n, t in [("zed", "zz"), ("amy", "aa"), ("mia", "mm")]],
            [RemoteTxn(id=RemoteId(n, 0), parents=[],
                       ops=[RemoteIns(ROOT, ROOT, t)])
             for n, t in [("bob", "b"), ("eve", "ee"), ("cat", "c")]],
        ]
        stacked = compile_txn_lanes(lane_txns)
        res = RLM.replay_lanes_mixed(stacked, capacity=64, chunk=8,
                                     interpret=True)
        res.check()
        for d, txns in enumerate(lane_txns):
            assert_lane_equals_oracle(stacked, res, d, oracle_txns(txns))

    @pytest.mark.parametrize("seed", [3, 21])
    def test_divergent_two_peer_merges(self, seed):
        # Each lane replays a DIFFERENT two-peer merge.
        rng = random.Random(seed)
        lane_txns = []
        for _ in range(4):
            pa, _ = random_patches(rng, 25)
            pb, _ = random_patches(rng, 25)
            a = oracle_from_patches(pa, agent="peer-a")
            b = oracle_from_patches(pb, agent="peer-b")
            lane_txns.append(export_txns_since(a, 0)
                             + export_txns_since(b, 0))
        stacked = compile_txn_lanes(lane_txns)
        res = RLM.replay_lanes_mixed(stacked, capacity=512, chunk=16,
                                     interpret=True)
        res.check()
        for d, txns in enumerate(lane_txns):
            assert_lane_equals_oracle(stacked, res, d, oracle_txns(txns))

    def test_fragmented_and_double_delete_lanes(self):
        # Lane 0: fragmented + concurrent double delete; lane 1: a long
        # chunked delete (> dmax targets); lane 2: delete-then-insert
        # into the tombstone (the sign-preserving raw splice).
        l0 = [
            RemoteTxn(id=RemoteId("amy", 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, "abcdef")]),
            RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 5)],
                      ops=[RemoteDel(RemoteId("amy", 1), 3)]),
            RemoteTxn(id=RemoteId("cat", 0), parents=[RemoteId("amy", 5)],
                      ops=[RemoteDel(RemoteId("amy", 2), 3)]),
        ]
        l1 = [
            RemoteTxn(id=RemoteId("amy", 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, "x" * 50)]),
            RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 49)],
                      ops=[RemoteDel(RemoteId("amy", 5), 40)]),
        ]
        l2 = [
            RemoteTxn(id=RemoteId("amy", 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, "abcdefgh")]),
            RemoteTxn(id=RemoteId("amy", 8), parents=[RemoteId("amy", 7)],
                      ops=[RemoteDel(RemoteId("amy", 2), 4)]),
            RemoteTxn(id=RemoteId("bob", 0), parents=[RemoteId("amy", 7)],
                      ops=[RemoteIns(RemoteId("amy", 3),
                                     RemoteId("amy", 4), "XY")]),
        ]
        lane_txns = [l0, l1, l2]
        stacked = compile_txn_lanes(lane_txns, lmax=16)
        res = RLM.replay_lanes_mixed(stacked, capacity=128, chunk=16,
                                     interpret=True)
        res.check()
        oracles = [oracle_txns(t) for t in lane_txns]
        assert oracles[0].to_string() == "af"
        assert oracles[1].to_string() == "x" * 10
        for d in range(3):
            assert_lane_equals_oracle(stacked, res, d, oracles[d])

    def test_mixed_local_and_remote_lanes_same_step(self):
        # Lane 0 applies LOCAL ops while lane 1 applies REMOTE ops in the
        # SAME kernel steps — all four dispatch branches masked per lane.
        rng = random.Random(11)
        patches, content = random_patches(rng, 30)
        local_ops, _ = B.compile_local_patches(
            B.merge_patches(patches), lmax=8, dmax=None)

        pa, _ = random_patches(rng, 20)
        a = oracle_from_patches(pa, agent="peer-a")
        txns = export_txns_since(a, 0)
        table = B.AgentTable()
        for t in txns:
            table.add(t.id.agent)
        remote_ops, _ = B.compile_remote_txns(txns, table, lmax=8, dmax=16)

        stacked = B.stack_ops([local_ops, remote_ops])
        res = RLM.replay_lanes_mixed(stacked, capacity=256, chunk=16,
                                     interpret=True)
        res.check()
        assert lane_string(stacked, res, 0) == content
        assert_lane_equals_oracle(stacked, res, 1, oracle_txns(txns))

    def test_local_lanes_match_rle_lanes_engine(self):
        # Pure-local stacked streams: state must equal ops.rle_lanes.
        rng = random.Random(7)
        streams = [random_patches(rng, 30 + rng.randint(0, 20))[0]
                   for _ in range(8)]
        lmax = max(len(p.ins_content) for ps in streams for p in ps) or 1
        opses = [B.compile_local_patches(ps, lmax=lmax, dmax=None)[0]
                 for ps in streams]
        stacked = B.stack_ops(opses)
        res = RLM.replay_lanes_mixed(stacked, capacity=256, chunk=16,
                                     interpret=True)
        ref = RL.replay_lanes(stacked, capacity=256, chunk=16,
                              interpret=True)
        res.check()
        ref.check()
        for a, b in ((res.ordp, ref.ordp), (res.lenp, ref.lenp),
                     (res.rows, ref.rows), (res.ol, ref.ol),
                     (res.orr, ref.orr)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("seed", [1, 17])
    def test_n_peer_interleavings_converge_per_lane(self, seed):
        # Each LANE applies a different causally-valid interleaving of
        # the same three peer streams; all lanes must converge to one
        # content and match the oracle under their own interleaving.
        rng = random.Random(seed)
        streams = []
        for name in ("kim", "lou", "max"):
            patches, _ = random_patches(rng, 15)
            streams.append(export_txns_since(
                oracle_from_patches(patches, agent=name), 0))

        def interleave(order_rng):
            queues = [list(s) for s in streams]
            out = []
            while any(queues):
                live = [q for q in queues if q]
                out.append(order_rng.choice(live).pop(0))
            return out

        lane_txns = [interleave(random.Random(seed * 100 + k))
                     for k in range(4)]
        stacked = compile_txn_lanes(lane_txns)
        res = RLM.replay_lanes_mixed(stacked, capacity=512, chunk=16,
                                     interpret=True)
        res.check()
        contents = []
        for d, txns in enumerate(lane_txns):
            oracle = oracle_txns(txns)
            assert_lane_equals_oracle(stacked, res, d, oracle)
            contents.append(oracle.to_string())
        assert len(set(contents)) == 1, "interleavings diverged"


class TestWarmStartChaining:
    def test_remote_chunks_resume_on_device(self):
        # A peer's edit log split into two compiled chunks; chunk 2
        # resumes from chunk 1's device state (tables carried via the
        # sentinel merge) — the config-5 streaming shape with REMOTE ops.
        rng = random.Random(42)
        docs = 4
        lane_peers = []
        for d in range(docs):
            patches, _ = random_patches(rng, 40)
            lane_peers.append(oracle_from_patches(
                patches, agent=f"peer{d}"))
        lane_txns = [export_txns_since(p, 0) for p in lane_peers]
        halves = [(t[: len(t) // 2], t[len(t) // 2:]) for t in lane_txns]

        tables = [B.AgentTable() for _ in range(docs)]
        assigners = [None] * docs

        def compile_chunk(which):
            opses = []
            for d in range(docs):
                txns = halves[d][which]
                for t in txns:
                    tables[d].add(t.id.agent)
                ops, assigners[d] = B.compile_remote_txns(
                    txns, tables[d], assigner=assigners[d], lmax=4,
                    dmax=16)
                opses.append(ops)
            return B.stack_ops(opses)

        c0 = compile_chunk(0)
        run0 = RLM.make_replayer_lanes_mixed(
            c0, capacity=256, order_capacity=512, chunk=16,
            interpret=True)
        r0 = run0()
        r0.check()

        c1 = compile_chunk(1)
        # Host-accumulated full rank table across both chunks.
        _, _, rkl0 = RLM.lane_tables(c0, 512)
        _, _, rkl1 = RLM.lane_tables(c1, 512)
        rkl = np.where(rkl1 != 0, rkl1, rkl0)
        run1 = RLM.make_replayer_lanes_mixed(
            c1, capacity=256, order_capacity=512, chunk=16,
            init=r0.state(), rkl=rkl, interpret=True)
        r1 = run1()
        r1.check()

        both = [np.concatenate([np.asarray(getattr(c0, f)),
                                np.asarray(getattr(c1, f))])
                for f in ("ins_len", "ins_order_start", "chars")]

        class Joined:
            ins_len, ins_order_start, chars = both

        for d in range(docs):
            oracle = oracle_txns(lane_txns[d])
            assert lane_signed(r1, d) == oracle_signed(oracle), f"lane {d}"
            assert (lane_string(Joined, r1, d)
                    == oracle.to_string()), f"lane {d}"


class TestCausalBufferIntegration:
    def test_out_of_order_arrival_through_buffer(self):
        # The production receive pipeline end-to-end: per-lane remote
        # txns arrive OUT OF ORDER, parallel.causal buffers them to a
        # valid causal order, the compiler + per-lane engine apply
        # them; result must equal the oracle applying the in-order
        # stream (the `doc.rs:246-247` TODO, wired to the round-5
        # engine).
        from text_crdt_rust_tpu.parallel.causal import CausalBuffer

        rng = random.Random(404)
        lane_txns = []
        for d in range(3):
            pa, _ = random_patches(rng, 20)
            pb, _ = random_patches(rng, 15)
            txns = (export_txns_since(
                        oracle_from_patches(pa, agent="ann"), 0)
                    + export_txns_since(
                        oracle_from_patches(pb, agent="bob"), 0))
            lane_txns.append(txns)

        ordered_lanes = []
        for txns in lane_txns:
            shuffled = list(txns)
            rng.shuffle(shuffled)
            buf = CausalBuffer()
            released = buf.add_all(shuffled)
            assert buf.pending == 0, buf.missing()
            ordered_lanes.append(released)

        stacked = compile_txn_lanes(ordered_lanes)
        res = RLM.replay_lanes_mixed(stacked, capacity=512, chunk=16,
                                     interpret=True)
        res.check()
        for d, released in enumerate(ordered_lanes):
            # Against the released order AND the ORIGINAL in-order
            # stream: a buffer that silently dropped a txn would agree
            # with itself but not with the pre-shuffle ground truth.
            assert len(released) == len(lane_txns[d])
            assert_lane_equals_oracle(stacked, res, d,
                                      oracle_txns(released))
            want = oracle_txns(lane_txns[d]).to_string()
            assert oracle_txns(released).to_string() == want


class TestNPeerFuzz:
    @pytest.mark.parametrize("seed", [5, 29])
    def test_divergent_lane_storms_fuzz(self, seed):
        # Per-lane storms with deletes (the config-4 delete-heavy
        # generator) on DIFFERENT seeds per lane — the widest random
        # coverage of the unified engine's remote surface.
        from text_crdt_rust_tpu.utils.randedit import make_storm

        lane_txns = []
        for k in range(3):
            txns, receiver = make_storm(3, 5, 2, seed=seed * 10 + k,
                                        del_prob=0.3)
            lane_txns.append((txns, receiver))
        stacked = compile_txn_lanes([t for t, _ in lane_txns], lmax=4)
        res = RLM.replay_lanes_mixed(stacked, capacity=512, chunk=16,
                                     interpret=True)
        res.check()
        for d, (txns, receiver) in enumerate(lane_txns):
            oracle = oracle_txns(txns)
            assert oracle.to_string() == receiver.to_string()
            assert_lane_equals_oracle(stacked, res, d, oracle)


class TestCapacityGrowth:
    def test_remote_chunks_grow_capacity(self):
        # Chunked remote streaming with GROWING row + order capacities
        # (the round-5 bench lever) must equal the flat-capacity chain.
        rng = random.Random(77)
        docs = 3
        peers = [oracle_from_patches(random_patches(rng, 30)[0],
                                     agent=f"p{d}") for d in range(docs)]
        lane_txns = [export_txns_since(p, 0) for p in peers]
        halves = [(t[: len(t) // 2], t[len(t) // 2:]) for t in lane_txns]

        def compile_chunk(which, tables, assigners):
            opses = []
            for d in range(docs):
                for t in halves[d][which]:
                    tables[d].add(t.id.agent)
                ops, assigners[d] = B.compile_remote_txns(
                    halves[d][which], tables[d], assigner=assigners[d],
                    lmax=4, dmax=None)
                opses.append(ops)
            return B.stack_ops(opses)

        def chain(caps, ocaps):
            tables = [B.AgentTable() for _ in range(docs)]
            assigners = [None] * docs
            c0 = compile_chunk(0, tables, assigners)
            r0 = RLM.make_replayer_lanes_mixed(
                c0, capacity=caps[0], order_capacity=ocaps[0], chunk=16,
                interpret=True)()
            r0.check()
            c1 = compile_chunk(1, tables, assigners)
            r1 = RLM.make_replayer_lanes_mixed(
                c1, capacity=caps[1], order_capacity=ocaps[1], chunk=16,
                init=r0.state(), interpret=True)()
            r1.check()
            return r1

        grown = chain((64, 128), (64, 128))
        flat = chain((128, 128), (128, 128))
        for f in ("ordp", "lenp", "rows"):
            a = np.asarray(getattr(grown, f))
            b = np.asarray(getattr(flat, f))
            assert np.array_equal(a, b[: a.shape[0]]), f


class TestLaneTiling:
    def test_tiled_equals_whole_mixed(self):
        # The bench runs 2048 lanes as 256-wide tiles; the lane-block
        # grid axis must be invisible for the MIXED kernel too —
        # including the by-order table state and a warm-started chunk.
        rng = random.Random(61)
        lane_txns = []
        for d in range(8):
            pa, _ = random_patches(rng, 20)
            peer = oracle_from_patches(pa, agent=f"p{d}")
            lane_txns.append(export_txns_since(peer, 0))
        stacked = compile_txn_lanes(lane_txns)
        kw = dict(capacity=256, order_capacity=256, chunk=16,
                  interpret=True)
        whole = RLM.make_replayer_lanes_mixed(stacked, **kw)()
        tiled = RLM.make_replayer_lanes_mixed(stacked, lane_tile=4,
                                              **kw)()
        whole.check()
        tiled.check()
        for f in ("ordp", "lenp", "rows", "ol", "orr", "oll", "orl"):
            a = np.asarray(getattr(whole, f))
            b = np.asarray(getattr(tiled, f))
            assert np.array_equal(a, b), f

        w2 = RLM.make_replayer_lanes_mixed(stacked, init=whole.state(),
                                           **kw)()
        t2 = RLM.make_replayer_lanes_mixed(stacked, lane_tile=2,
                                           init=tiled.state(), **kw)()
        # Re-applying known seqs is invalid CRDT-wise, but both runs see
        # identical inputs, so tiling must still be invisible — for the
        # carried by-order tables too (a third chunk would read them).
        for f in ("ordp", "lenp", "rows", "oll", "orl"):
            assert np.array_equal(np.asarray(getattr(w2, f)),
                                  np.asarray(getattr(t2, f))), f


class TestErrorFlags:
    def test_capacity_flag_per_lane(self):
        lane_txns = [
            [RemoteTxn(id=RemoteId("a", 0), parents=[],
                       ops=[RemoteIns(ROOT, ROOT, "ab")])],
            [RemoteTxn(id=RemoteId("a", 2 * k), parents=[],
                       ops=[RemoteIns(
                           ROOT if k == 0 else RemoteId("a", 2 * k - 1),
                           ROOT, "ab")])
             for k in range(30)],
        ]
        # Interleave each insert with a delete so runs can't merge and
        # lane 1 overflows an 8-row capacity.
        l1 = []
        for k, t in enumerate(lane_txns[1]):
            l1.append(t)
            if k % 2 == 0:
                l1.append(RemoteTxn(
                    id=RemoteId("b", k // 2), parents=[],
                    ops=[RemoteDel(RemoteId("a", 2 * k), 1)]))
        lane_txns[1] = l1
        stacked = compile_txn_lanes(lane_txns)
        res = RLM.replay_lanes_mixed(stacked, capacity=8, chunk=8,
                                     interpret=True)
        with pytest.raises(RuntimeError, match="lanes \\[1\\]"):
            res.check()

    def test_remote_delete_capacity_flag(self):
        # Review r5 regression: a remote delete's partial-run splits add
        # rows, so capacity is gated per op (rows + 2*npart > CAP) — at
        # 8 rows capacity the 4th interior delete would overflow and
        # pltpu.roll would silently wrap the plane.
        txns = [RemoteTxn(id=RemoteId("amy", 0), parents=[],
                          ops=[RemoteIns(ROOT, ROOT, "aaaaaaaa")])]
        for k, s in enumerate((1, 3, 5, 6)):
            txns.append(RemoteTxn(
                id=RemoteId("bob", k), parents=[],
                ops=[RemoteDel(RemoteId("amy", s), 1)]))
        stacked = compile_txn_lanes([txns], lmax=8)
        res = RLM.replay_lanes_mixed(stacked, capacity=8, chunk=8,
                                     interpret=True)
        with pytest.raises(RuntimeError, match="lanes \\[0\\]"):
            res.check()

    def test_missing_order_flag(self):
        # An op referencing an order never inserted on this lane.
        lane_txns = [[
            RemoteTxn(id=RemoteId("a", 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, "ab")]),
        ]]
        stacked = compile_txn_lanes(lane_txns)
        # Corrupt the stream: point a delete at an absent order.
        import jax

        stacked = jax.tree.map(lambda a: np.asarray(a).copy(), stacked)
        stacked.kind[0, 0] = B.KIND_REMOTE_DEL
        stacked.del_target[0, 0] = 90
        stacked.del_len[0, 0] = 1
        stacked.ins_len[0, 0] = 0
        res = RLM.replay_lanes_mixed(stacked, capacity=16, chunk=8,
                                     interpret=True)
        # The one-pass delete reports absent targets through the
        # covered-total check (err row 1), not the order-lookup flag.
        with pytest.raises(RuntimeError, match="past the end"):
            res.check()

    def test_missing_origin_order_flag(self):
        # A remote insert whose origin_left order was never inserted on
        # this lane must raise the order-lookup flag (err row 2) from
        # the YATA scan's cursor resolution.
        lane_txns = [[
            RemoteTxn(id=RemoteId("a", 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, "ab")]),
            RemoteTxn(id=RemoteId("a", 2), parents=[],
                      ops=[RemoteIns(RemoteId("a", 1), ROOT, "cd")]),
        ]]
        stacked = compile_txn_lanes(lane_txns)
        import jax

        stacked = jax.tree.map(lambda a: np.asarray(a).copy(), stacked)
        stacked.origin_left[1, 0] = 90  # absent order
        res = RLM.replay_lanes_mixed(stacked, capacity=16, chunk=8,
                                     interpret=True)
        with pytest.raises(RuntimeError, match="order lookup missed"):
            res.check()

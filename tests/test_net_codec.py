"""Binary wire codec: round-trip fidelity and hard corruption rejection.

The codec contract (ISSUE 1 acceptance): encode→decode round-trips
arbitrary exported histories bit-identically, and EVERY single-byte
corruption of a valid frame is rejected with ``CodecError`` — never an
uncaught exception. Host-only (no JAX involved on this layer).
"""
import random

import pytest

from text_crdt_rust_tpu.common import (
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
    validate_remote_txn,
)
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since, merge_into
from text_crdt_rust_tpu.net import codec
from text_crdt_rust_tpu.net.codec import (
    CodecError,
    decode_frame,
    decode_frames,
    encode_digest,
    encode_request,
    encode_txns,
)
from text_crdt_rust_tpu.utils.randedit import random_patches


def seeded_doc(seed: int, steps: int = 15, peers: int = 1) -> ListCRDT:
    """A small seeded document; multi-peer seeds exercise merged
    multi-agent histories (string table with several names)."""
    rng = random.Random(seed)
    docs = []
    for p in range(peers):
        doc = ListCRDT()
        agent = doc.get_or_create_agent_id(f"peer-{seed}-{p}")
        patches, _ = random_patches(rng, steps)
        for patch in patches:
            if patch.del_len:
                doc.local_delete(agent, patch.pos, patch.del_len)
            if patch.ins_content:
                doc.local_insert(agent, patch.pos, patch.ins_content)
        docs.append(doc)
    base = docs[0]
    for other in docs[1:]:
        merge_into(base, other)
    return base


class TestRoundTrip:
    def test_200_seeded_docs_bit_identical(self):
        """Acceptance: ≥200 seeded docs round-trip bit-identically."""
        for seed in range(200):
            doc = seeded_doc(seed, steps=12, peers=1 + seed % 3)
            txns = export_txns_since(doc, 0)
            frame = encode_txns(txns)
            kind, back, consumed = decode_frame(frame)
            assert kind == codec.KIND_TXNS
            assert consumed == len(frame)
            assert back == txns, f"seed {seed} round-trip mismatch"

    def test_decoded_history_rebuilds_identical_doc(self):
        doc = seeded_doc(7, steps=40, peers=2)
        txns = export_txns_since(doc, 0)
        _, back, _ = decode_frame(encode_txns(txns))
        rebuilt = ListCRDT()
        for t in back:
            rebuilt.apply_remote_txn(t)
        assert rebuilt.to_string() == doc.to_string()
        assert rebuilt.doc_spans() == doc.doc_spans()

    def test_unicode_content(self):
        txns = [RemoteTxn(
            RemoteId("ünïcode-agent", 0), [RemoteId("ROOT", 0xFFFFFFFF)],
            [RemoteIns(RemoteId("ROOT", 0xFFFFFFFF),
                       RemoteId("ROOT", 0xFFFFFFFF), "héllo 世界 🚀")],
        )]
        _, back, _ = decode_frame(encode_txns(txns))
        assert back == txns

    def test_empty_batch_and_stream_of_frames(self):
        f0 = encode_txns([])
        f1 = encode_request({"alice": 5, "bob": 0})
        f2 = encode_digest({"alice": 9}, 0xDEADBEEF)
        out = decode_frames(f0 + f1 + f2)
        assert out[0] == (codec.KIND_TXNS, [])
        assert out[1] == (codec.KIND_REQUEST, {"alice": 5, "bob": 0})
        assert out[2] == (codec.KIND_DIGEST, ({"alice": 9}, 0xDEADBEEF))

    def test_delete_ops_round_trip(self):
        txns = [RemoteTxn(
            RemoteId("a", 4), [RemoteId("a", 3)],
            [RemoteDel(RemoteId("b", 10), 7)],
        )]
        _, back, _ = decode_frame(encode_txns(txns))
        assert back == txns


class TestCorruptionRejection:
    """Every single-byte corruption must raise CodecError — nothing else."""

    def _frame(self, seed=3, steps=10, peers=2):
        doc = seeded_doc(seed, steps=steps, peers=peers)
        return encode_txns(export_txns_since(doc, 0))

    def test_every_single_byte_value_corruption_rejected(self):
        """Exhaustive: every byte position × every wrong byte value
        (a small frame keeps the 255 × len decode sweep fast)."""
        frame = self._frame(steps=4, peers=1)
        for i in range(len(frame)):
            orig = frame[i]
            for val in range(256):
                if val == orig:
                    continue
                buf = bytearray(frame)
                buf[i] = val
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))

    def test_bitflips_across_many_frames(self):
        for seed in range(20):
            frame = self._frame(seed)
            rng = random.Random(seed)
            for _ in range(32):
                i = rng.randrange(len(frame))
                buf = bytearray(frame)
                buf[i] ^= 1 << rng.randrange(8)
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))

    def test_every_truncation_rejected(self):
        frame = self._frame()
        for cut in range(len(frame)):
            with pytest.raises(CodecError):
                decode_frame(frame[:cut])

    def test_control_frame_corruption_rejected(self):
        for frame in (encode_request({"alice": 3}),
                      encode_digest({"alice": 3, "bob": 9}, 123456)):
            for i in range(len(frame)):
                buf = bytearray(frame)
                buf[i] ^= 0x40
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))


class TestStructuralValidation:
    """CRC-valid frames with malformed bodies are still rejected."""

    def test_unknown_kind(self):
        with pytest.raises(CodecError, match="kind"):
            decode_frame(codec._frame(bytes([99])))

    def test_unknown_op_tag(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1)      # one txn
        codec._write_varint(body, 0)      # agent idx
        codec._write_varint(body, 0)      # seq
        codec._write_varint(body, 0)      # no parents
        codec._write_varint(body, 1)      # one op
        body.append(7)                    # bogus tag
        with pytest.raises(CodecError, match="tag"):
            decode_frame(codec._frame(bytes(body)))

    def test_agent_index_out_of_range(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1)
        codec._write_varint(body, 5)      # agent idx 5, table has 1
        codec._write_varint(body, 0)
        with pytest.raises(CodecError, match="agent index"):
            decode_frame(codec._frame(bytes(body)))

    def test_trailing_garbage_rejected(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, [])
        codec._write_varint(body, 0)
        body += b"\x00\x00"               # junk after the batch
        with pytest.raises(CodecError, match="trailing"):
            decode_frame(codec._frame(bytes(body)))

    def test_oversized_varint_rejected(self):
        body = bytes([codec.KIND_TXNS]) + b"\xff" * 11
        with pytest.raises(CodecError, match="varint"):
            decode_frame(codec._frame(body))

    def test_oversized_agent_name_rejected_both_sides(self):
        """Agent names are capped (4 KiB): an unbounded name would be
        applied and then crash the digest/gossip path downstream. The
        ENCODER fails fast (emitting it would poison the re-request
        cycle: every compliant peer rejects the frame forever), and the
        DECODER rejects a non-compliant sender's frame."""
        txns = [RemoteTxn(
            RemoteId("x" * 70000, 0), [RemoteId("ROOT", 0xFFFFFFFF)],
            [RemoteIns(RemoteId("ROOT", 0xFFFFFFFF),
                       RemoteId("ROOT", 0xFFFFFFFF), "hi")],
        )]
        with pytest.raises(CodecError, match="cap"):
            encode_txns(txns)
        with pytest.raises(CodecError, match="cap"):
            encode_request({"x" * 70000: 0})
        # Hand-built frame from a non-compliant sender.
        body = bytearray([codec.KIND_TXNS])
        raw = ("y" * 70000).encode("utf-8")
        codec._write_varint(body, 1)        # one table entry
        codec._write_varint(body, len(raw))
        body += raw
        codec._write_varint(body, 0)        # zero txns
        with pytest.raises(CodecError, match="cap"):
            decode_frame(codec._frame(bytes(body)))

    def test_huge_delete_length_rejected(self):
        """An unchecked 2^60 delete length would poison the receiver's
        per-agent watermark (seq + len) forever."""
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a", "b"])
        codec._write_varint(body, 1)
        codec._write_varint(body, 0)      # author a
        codec._write_varint(body, 0)      # seq 0
        codec._write_varint(body, 0)      # no parents
        codec._write_varint(body, 1)      # one op
        body.append(1)                    # RemoteDel
        codec._write_varint(body, 1)      # target agent b
        codec._write_varint(body, 0)      # target seq
        codec._write_varint(body, 1 << 60)
        with pytest.raises(CodecError, match="u32"):
            decode_frame(codec._frame(bytes(body)))

    def test_zero_length_txn_rejected(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1)
        codec._write_varint(body, 0)      # agent
        codec._write_varint(body, 0)      # seq
        codec._write_varint(body, 0)      # no parents
        codec._write_varint(body, 0)      # NO ops -> invalid txn
        with pytest.raises(CodecError, match="invalid txn"):
            decode_frame(codec._frame(bytes(body)))

    def test_empty_input_rejected(self):
        with pytest.raises(CodecError):
            decode_frame(b"")

    def test_validate_remote_txn_guards(self):
        with pytest.raises(ValueError):
            validate_remote_txn(RemoteTxn(RemoteId("ROOT", 0), [], []))
        with pytest.raises(ValueError):
            validate_remote_txn(RemoteTxn(RemoteId("a", 0), [], []))
        with pytest.raises(ValueError):
            validate_remote_txn(RemoteTxn(
                RemoteId("a", 0), [],
                [RemoteDel(RemoteId("b", 0), 0)]))

"""Binary wire codec: round-trip fidelity and hard corruption rejection.

The codec contract (ISSUE 1 acceptance, extended to the columnar v2
frames by ISSUE 7): encode→decode round-trips arbitrary exported
histories bit-identically — on BOTH wire formats, interchangeably — and
EVERY single-byte corruption of a valid frame is rejected with
``CodecError`` — never an uncaught exception. Host-only (no JAX
involved on this layer).
"""
import random

import pytest

from text_crdt_rust_tpu.common import (
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
    validate_remote_txn,
)
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since, merge_into
from text_crdt_rust_tpu.net import codec, columnar
from text_crdt_rust_tpu.net.codec import (
    CodecError,
    decode_frame,
    decode_frames,
    encode_digest,
    encode_request,
    encode_txns,
)
from text_crdt_rust_tpu.utils.randedit import random_patches


def seeded_doc(seed: int, steps: int = 15, peers: int = 1) -> ListCRDT:
    """A small seeded document; multi-peer seeds exercise merged
    multi-agent histories (string table with several names)."""
    rng = random.Random(seed)
    docs = []
    for p in range(peers):
        doc = ListCRDT()
        agent = doc.get_or_create_agent_id(f"peer-{seed}-{p}")
        patches, _ = random_patches(rng, steps)
        for patch in patches:
            if patch.del_len:
                doc.local_delete(agent, patch.pos, patch.del_len)
            if patch.ins_content:
                doc.local_insert(agent, patch.pos, patch.ins_content)
        docs.append(doc)
    base = docs[0]
    for other in docs[1:]:
        merge_into(base, other)
    return base


class TestRoundTrip:
    def test_200_seeded_docs_bit_identical(self):
        """Acceptance: ≥200 seeded docs round-trip bit-identically."""
        for seed in range(200):
            doc = seeded_doc(seed, steps=12, peers=1 + seed % 3)
            txns = export_txns_since(doc, 0)
            frame = encode_txns(txns)
            kind, back, consumed = decode_frame(frame)
            assert kind == codec.KIND_TXNS
            assert consumed == len(frame)
            assert back == txns, f"seed {seed} round-trip mismatch"

    def test_decoded_history_rebuilds_identical_doc(self):
        doc = seeded_doc(7, steps=40, peers=2)
        txns = export_txns_since(doc, 0)
        _, back, _ = decode_frame(encode_txns(txns))
        rebuilt = ListCRDT()
        for t in back:
            rebuilt.apply_remote_txn(t)
        assert rebuilt.to_string() == doc.to_string()
        assert rebuilt.doc_spans() == doc.doc_spans()

    def test_unicode_content(self):
        txns = [RemoteTxn(
            RemoteId("ünïcode-agent", 0), [RemoteId("ROOT", 0xFFFFFFFF)],
            [RemoteIns(RemoteId("ROOT", 0xFFFFFFFF),
                       RemoteId("ROOT", 0xFFFFFFFF), "héllo 世界 🚀")],
        )]
        _, back, _ = decode_frame(encode_txns(txns))
        assert back == txns

    def test_empty_batch_and_stream_of_frames(self):
        f0 = encode_txns([])
        f1 = encode_request({"alice": 5, "bob": 0})
        f2 = encode_digest({"alice": 9}, 0xDEADBEEF)
        out = decode_frames(f0 + f1 + f2)
        assert out[0] == (codec.KIND_TXNS, [])
        assert out[1] == (codec.KIND_REQUEST, {"alice": 5, "bob": 0})
        assert out[2] == (codec.KIND_DIGEST, ({"alice": 9}, 0xDEADBEEF))

    def test_delete_ops_round_trip(self):
        txns = [RemoteTxn(
            RemoteId("a", 4), [RemoteId("a", 3)],
            [RemoteDel(RemoteId("b", 10), 7)],
        )]
        _, back, _ = decode_frame(encode_txns(txns))
        assert back == txns


class TestCorruptionRejection:
    """Every single-byte corruption must raise CodecError — nothing else."""

    def _frame(self, seed=3, steps=10, peers=2):
        doc = seeded_doc(seed, steps=steps, peers=peers)
        return encode_txns(export_txns_since(doc, 0))

    def test_every_single_byte_value_corruption_rejected(self):
        """Exhaustive: every byte position × every wrong byte value
        (a small frame keeps the 255 × len decode sweep fast)."""
        frame = self._frame(steps=4, peers=1)
        for i in range(len(frame)):
            orig = frame[i]
            for val in range(256):
                if val == orig:
                    continue
                buf = bytearray(frame)
                buf[i] = val
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))

    def test_bitflips_across_many_frames(self):
        for seed in range(20):
            frame = self._frame(seed)
            rng = random.Random(seed)
            for _ in range(32):
                i = rng.randrange(len(frame))
                buf = bytearray(frame)
                buf[i] ^= 1 << rng.randrange(8)
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))

    def test_every_truncation_rejected(self):
        frame = self._frame()
        for cut in range(len(frame)):
            with pytest.raises(CodecError):
                decode_frame(frame[:cut])

    def test_control_frame_corruption_rejected(self):
        for frame in (encode_request({"alice": 3}),
                      encode_digest({"alice": 3, "bob": 9}, 123456)):
            for i in range(len(frame)):
                buf = bytearray(frame)
                buf[i] ^= 0x40
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))


class TestStructuralValidation:
    """CRC-valid frames with malformed bodies are still rejected."""

    def test_unknown_kind(self):
        with pytest.raises(CodecError, match="kind"):
            decode_frame(codec._frame(bytes([99])))

    def test_unknown_op_tag(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1)      # one txn
        codec._write_varint(body, 0)      # agent idx
        codec._write_varint(body, 0)      # seq
        codec._write_varint(body, 0)      # no parents
        codec._write_varint(body, 1)      # one op
        body.append(7)                    # bogus tag
        with pytest.raises(CodecError, match="tag"):
            decode_frame(codec._frame(bytes(body)))

    def test_agent_index_out_of_range(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1)
        codec._write_varint(body, 5)      # agent idx 5, table has 1
        codec._write_varint(body, 0)
        with pytest.raises(CodecError, match="agent index"):
            decode_frame(codec._frame(bytes(body)))

    def test_trailing_garbage_rejected(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, [])
        codec._write_varint(body, 0)
        body += b"\x00\x00"               # junk after the batch
        with pytest.raises(CodecError, match="trailing"):
            decode_frame(codec._frame(bytes(body)))

    def test_oversized_varint_rejected(self):
        body = bytes([codec.KIND_TXNS]) + b"\xff" * 11
        with pytest.raises(CodecError, match="varint"):
            decode_frame(codec._frame(body))

    def test_oversized_agent_name_rejected_both_sides(self):
        """Agent names are capped (4 KiB): an unbounded name would be
        applied and then crash the digest/gossip path downstream. The
        ENCODER fails fast (emitting it would poison the re-request
        cycle: every compliant peer rejects the frame forever), and the
        DECODER rejects a non-compliant sender's frame."""
        txns = [RemoteTxn(
            RemoteId("x" * 70000, 0), [RemoteId("ROOT", 0xFFFFFFFF)],
            [RemoteIns(RemoteId("ROOT", 0xFFFFFFFF),
                       RemoteId("ROOT", 0xFFFFFFFF), "hi")],
        )]
        with pytest.raises(CodecError, match="cap"):
            encode_txns(txns)
        with pytest.raises(CodecError, match="cap"):
            encode_request({"x" * 70000: 0})
        # Hand-built frame from a non-compliant sender.
        body = bytearray([codec.KIND_TXNS])
        raw = ("y" * 70000).encode("utf-8")
        codec._write_varint(body, 1)        # one table entry
        codec._write_varint(body, len(raw))
        body += raw
        codec._write_varint(body, 0)        # zero txns
        with pytest.raises(CodecError, match="cap"):
            decode_frame(codec._frame(bytes(body)))

    def test_huge_delete_length_rejected(self):
        """An unchecked 2^60 delete length would poison the receiver's
        per-agent watermark (seq + len) forever."""
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a", "b"])
        codec._write_varint(body, 1)
        codec._write_varint(body, 0)      # author a
        codec._write_varint(body, 0)      # seq 0
        codec._write_varint(body, 0)      # no parents
        codec._write_varint(body, 1)      # one op
        body.append(1)                    # RemoteDel
        codec._write_varint(body, 1)      # target agent b
        codec._write_varint(body, 0)      # target seq
        codec._write_varint(body, 1 << 60)
        with pytest.raises(CodecError, match="u32"):
            decode_frame(codec._frame(bytes(body)))

    def test_zero_length_txn_rejected(self):
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1)
        codec._write_varint(body, 0)      # agent
        codec._write_varint(body, 0)      # seq
        codec._write_varint(body, 0)      # no parents
        codec._write_varint(body, 0)      # NO ops -> invalid txn
        with pytest.raises(CodecError, match="invalid txn"):
            decode_frame(codec._frame(bytes(body)))

    def test_empty_input_rejected(self):
        with pytest.raises(CodecError):
            decode_frame(b"")

    def test_validate_remote_txn_guards(self):
        with pytest.raises(ValueError):
            validate_remote_txn(RemoteTxn(RemoteId("ROOT", 0), [], []))
        with pytest.raises(ValueError):
            validate_remote_txn(RemoteTxn(RemoteId("a", 0), [], []))
        with pytest.raises(ValueError):
            validate_remote_txn(RemoteTxn(
                RemoteId("a", 0), [],
                [RemoteDel(RemoteId("b", 0), 0)]))


class TestColumnarRoundTrip:
    """The v2 per-column delta wire decodes to EXACTLY what the row
    codec round-trips — the formats are interchangeable on the wire."""

    def test_cross_format_200_seeded_docs(self):
        """ISSUE-7 property fuzz: seeded batches round-trip byte-for-
        byte equal after decode on both wire formats."""
        for seed in range(200):
            doc = seeded_doc(seed, steps=12, peers=1 + seed % 3)
            txns = export_txns_since(doc, 0)
            for frame in (encode_txns(txns), columnar.encode_txns(txns)):
                kind, back, consumed = decode_frame(frame)
                assert kind == codec.KIND_TXNS
                assert consumed == len(frame)
                assert back == txns, f"seed {seed} round-trip mismatch"

    def test_mixed_format_frame_stream(self):
        """Row and columnar frames interleave on one connection; the
        version byte negotiates per frame."""
        doc = seeded_doc(11, steps=20, peers=2)
        txns = export_txns_since(doc, 0)
        half = len(txns) // 2
        stream = (encode_txns(txns[:half])
                  + columnar.encode_txns(txns[half:]))
        out = decode_frames(stream)
        assert [k for k, _ in out] == [codec.KIND_TXNS] * 2
        assert out[0][1] + out[1][1] == txns

    def test_unicode_and_empty(self):
        txns = [RemoteTxn(
            RemoteId("ünïcode-agent", 0), [RemoteId("ROOT", 0xFFFFFFFF)],
            [RemoteIns(RemoteId("ROOT", 0xFFFFFFFF),
                       RemoteId("ROOT", 0xFFFFFFFF), "héllo 世界 🚀")],
        )]
        _, back, _ = decode_frame(columnar.encode_txns(txns))
        assert back == txns
        _, back, _ = decode_frame(columnar.encode_txns([]))
        assert back == []

    def test_stream_chunking(self):
        doc = seeded_doc(5, steps=40, peers=3)
        txns = export_txns_since(doc, 0)
        stream = columnar.encode_txns_stream(txns, per_frame=7)
        got = []
        for kind, value in decode_frames(stream):
            assert kind == codec.KIND_TXNS
            got.extend(value)
        assert got == txns

    def test_mux_round_trip_and_chunking(self):
        batches = []
        for d in range(12):
            doc = seeded_doc(100 + d, steps=10, peers=1 + d % 2)
            batches.append((f"doc-{d}", export_txns_since(doc, 0)))
        want = [(i, t) for i, (_, ts) in enumerate(batches) for t in ts]
        frame = columnar.encode_mux(batches)
        kind, groups, consumed = decode_frame(frame)
        assert kind == codec.KIND_TXNS_MUX and consumed == len(frame)
        flat = [(d, t) for d, ts in groups for t in ts]
        assert flat == [(batches[i][0], t) for i, t in want]
        # Chunked stream splits mid-doc; per-doc txn order must hold.
        stream = columnar.encode_mux_stream(batches, per_frame=13)
        got = []
        for kind, groups in decode_frames(stream):
            assert kind == codec.KIND_TXNS_MUX
            got.extend((d, t) for d, ts in groups for t in ts)
        assert got == flat
        # Empty mux frame round-trips.
        _, empty, _ = decode_frame(columnar.encode_mux([]))
        assert empty == []

    def test_deflated_body_round_trip(self):
        """A frame big enough to win whole-body DEFLATE still decodes
        bit-identically (flags bit 0 path)."""
        doc = seeded_doc(3, steps=120, peers=3)
        txns = export_txns_since(doc, 0)
        frame = columnar.encode_txns(txns)
        assert frame[2 + _varint_len(frame)] in (0, 1)
        _, back, _ = decode_frame(frame)
        assert back == txns


def _varint_len(frame):
    """Bytes the outer length varint occupies (frame[2:...])."""
    n = 0
    while frame[2 + n] & 0x80:
        n += 1
    return n + 1


class TestColumnarCorruption:
    """The PR-1 hard-rejection contract, bit for bit, on v2 frames."""

    def _frame(self, seed=3, steps=4, peers=1):
        doc = seeded_doc(seed, steps=steps, peers=peers)
        return columnar.encode_txns(export_txns_since(doc, 0))

    def _mux_frame(self):
        batches = []
        for d in range(3):
            doc = seeded_doc(40 + d, steps=3, peers=1)
            batches.append((f"doc-{d}", export_txns_since(doc, 0)))
        return columnar.encode_mux(batches)

    def test_every_single_byte_value_corruption_rejected(self):
        """Exhaustive: every byte position × every wrong byte value on
        a small single-doc columnar frame."""
        frame = self._frame()
        for i in range(len(frame)):
            orig = frame[i]
            for val in range(256):
                if val == orig:
                    continue
                buf = bytearray(frame)
                buf[i] = val
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))

    def test_every_single_byte_value_corruption_rejected_mux(self):
        frame = self._mux_frame()
        rng = random.Random(0)
        positions = set(range(24)) | {rng.randrange(len(frame))
                                      for _ in range(40)}
        for i in sorted(positions):
            orig = frame[i]
            for val in range(256):
                if val == orig:
                    continue
                buf = bytearray(frame)
                buf[i] = val
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))

    def test_every_truncation_rejected_incl_mid_column_chunk(self):
        """Every cut point — which sweeps truncation mid-column-chunk,
        mid-name-table, and mid-CRC — must reject, on both the plain
        and the deflated-body frame shapes."""
        small = self._frame()
        doc = seeded_doc(9, steps=120, peers=3)
        big = columnar.encode_txns(export_txns_since(doc, 0))
        for frame in (small, big, self._mux_frame()):
            for cut in range(len(frame)):
                with pytest.raises(CodecError):
                    decode_frame(frame[:cut])

    def test_flipped_version_byte_typed_never_misdecodes(self):
        """A flipped version byte — with or without a fixed-up CRC — is
        a typed error, never a silent mis-decode as the other format."""
        for seed in range(50):
            doc = seeded_doc(seed, steps=6, peers=1 + seed % 2)
            txns = export_txns_since(doc, 0)
            for frame, flip_to in ((encode_txns(txns), 2),
                                   (columnar.encode_txns(txns), 1),
                                   (columnar.encode_txns(txns), 3)):
                buf = bytearray(frame)
                buf[1] = flip_to
                # CRC catches the bare flip...
                with pytest.raises(CodecError):
                    decode_frame(bytes(buf))
                # ...and a CRC-fixed flip must still reject on body
                # structure (or decode to the SAME txns, never others).
                import struct
                body = bytes(buf[:-4])
                fixed = body + struct.pack("<I", codec.crc32c(body))
                try:
                    _, back, _ = decode_frame(fixed)
                except CodecError:
                    continue
                assert back == txns, (
                    f"seed {seed}: version flip {frame[1]}->{flip_to} "
                    f"mis-decoded")

    def test_structural_rejections(self):
        # Unknown flags bits.
        body = bytearray([codec.KIND_TXNS, 0x82])
        with pytest.raises(CodecError, match="flags"):
            decode_frame(codec._frame(bytes(body), version=2))
        # Control frames are not defined for version 2.
        with pytest.raises(CodecError, match="not defined"):
            decode_frame(codec._frame(bytes([codec.KIND_REQUEST, 0]),
                                      version=2))
        # Mux kind is not defined for version 1.
        with pytest.raises(CodecError, match="kind"):
            decode_frame(codec._frame(bytes([codec.KIND_TXNS_MUX, 0])))
        # DOC column is unknown in a single-doc body.
        body = bytearray([codec.KIND_TXNS, 0])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 0)      # zero txns
        codec._write_varint(body, 1)      # one chunk
        body.append(columnar.DOC << 1)    # doc column, raw
        codec._write_varint(body, 0)
        with pytest.raises(CodecError, match="column id"):
            decode_frame(codec._frame(bytes(body), version=2))

    def test_column_overrun_and_shortfall_rejected(self):
        """Runs must land EXACTLY on the declared value count."""
        def frame_with_tagruns(runs):
            body = bytearray([codec.KIND_TXNS, 0])
            codec._write_names(body, ["a"])
            codec._write_varint(body, 1)      # one txn
            codec._write_varint(body, 1)      # one chunk
            chunk = bytearray()
            for run_len, residual in runs:
                codec._write_varint(chunk, run_len)
                codec._write_varint(chunk, residual)
            body.append(columnar.T_NOPS << 1)
            codec._write_varint(body, len(chunk))
            body += chunk
            return codec._frame(bytes(body), version=2)

        with pytest.raises(CodecError, match="overrun"):
            decode_frame(frame_with_tagruns([(5, 0)]))   # 5 values for 1
        with pytest.raises(CodecError, match="expected"):
            decode_frame(frame_with_tagruns([]))         # 0 values... but
        # absent chunk = all-zero prediction is fine — an EMPTY chunk is
        # the shortfall case only when values were declared:
        # (empty chunk body, expected 1 -> rejected above)

    def test_adversarial_count_caps(self):
        """A tiny CRC-valid frame declaring huge counts must hit the
        allocation caps, not allocate."""
        body = bytearray([codec.KIND_TXNS, 0])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1 << 40)    # absurd txn count
        with pytest.raises(CodecError, match="cap"):
            decode_frame(codec._frame(bytes(body), version=2))
        # Huge op count via an RLE run (the row codec can bound counts
        # by payload length; the columnar decoder needs explicit caps).
        body = bytearray([codec.KIND_TXNS, 0])
        codec._write_names(body, ["a"])
        codec._write_varint(body, 1 << 16)    # txns at the cap exactly
        codec._write_varint(body, 1)
        chunk = bytearray()
        codec._write_varint(chunk, 1 << 16)
        codec._write_varint(chunk, 2 << 1)    # zigzag(+2): 3 ops per txn
        body.append(columnar.T_NOPS << 1)
        codec._write_varint(body, len(chunk))
        body += chunk
        with pytest.raises(CodecError, match="cap|exceed"):
            decode_frame(codec._frame(bytes(body), version=2))

    def test_surrogate_content_rejected_both_sides(self):
        txn = RemoteTxn(
            RemoteId("a", 0), [RemoteId("ROOT", 0xFFFFFFFF)],
            [RemoteIns(RemoteId("ROOT", 0xFFFFFFFF),
                       RemoteId("ROOT", 0xFFFFFFFF), "\ud800")])
        with pytest.raises(CodecError, match="surrogate"):
            columnar.encode_txns([txn])

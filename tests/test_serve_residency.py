"""serve/residency.py: the evict -> checkpoint -> restore lifecycle.

The load-bearing invariant (ISSUE 3 satellite): a doc evicted
mid-stream, edited-by-peers while out, restored, and drained is
bit-identical to an always-resident twin that saw the same ops.
"""
import os

import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since, state_digest
from text_crdt_rust_tpu.net import codec
from text_crdt_rust_tpu.serve.server import DocServer
from text_crdt_rust_tpu.utils.checkpoint import CheckpointError


def cfg(tmp_path, **kw):
    base = dict(num_shards=1, lanes_per_shard=2, lane_capacity=256,
                order_capacity=512, step_buckets=(8, 32), max_txn_len=32,
                spool_dir=str(tmp_path))
    base.update(kw)
    return ServeConfig(**base)


def peer_stream(n_txns, agent="amy"):
    doc = ListCRDT()
    a = doc.get_or_create_agent_id(agent)
    mark = 0
    chunks = []
    for i in range(n_txns):
        doc.local_insert(a, len(doc) // 2, f"<{i}>")
        if i % 3 == 2 and len(doc) > 4:
            doc.local_delete(a, 1, 2)
        chunks.append(export_txns_since(doc, mark))
        mark = doc.get_next_order()
    return chunks, doc


def test_evict_restore_while_peers_edit_matches_resident_twin(tmp_path):
    """Evict mid-stream; peers keep editing while the doc is out (their
    txns queue causally); a touch restores and replays; final state is
    bit-identical (string AND digest AND device lane) to a twin server
    that never evicted."""
    chunks, src = peer_stream(8)
    srv = DocServer(cfg(tmp_path, spool_dir=str(tmp_path / "a")))
    twin = DocServer(cfg(tmp_path, spool_dir=str(tmp_path / "b")))
    for s in (srv, twin):
        s.admit_doc("d")

    # First half applies on both; both lane-resident.
    for chunk in chunks[:4]:
        for t in chunk:
            srv.submit_txn("d", t)
            twin.submit_txn("d", t)
        srv.tick(); twin.tick()
    doc = srv.doc_state("d")
    assert doc.in_lane

    # Force the eviction mid-stream (the LRU path exercises the same
    # call; forcing makes the window deterministic).
    path = srv.residency.evict(doc)
    assert os.path.exists(path) and doc.evicted and not doc.resident

    # Peers edit while the doc is out: txns queue, nothing crashes.
    for chunk in chunks[4:]:
        for t in chunk:
            srv.submit_txn("d", t)
            twin.submit_txn("d", t)
        twin.tick()
    assert doc.evicted and len(doc.events) > 0

    # The touch (queued events) restores at the next tick and replays.
    srv.tick()
    assert doc.resident and not doc.evicted
    srv.drain(); twin.drain()

    assert srv.counters.get("evictions") == 1
    assert srv.counters.get("restores") == 1
    assert srv.doc_string("d") == src.to_string()
    assert srv.doc_string("d") == twin.doc_string("d")
    assert (state_digest(doc.oracle)
            == state_digest(twin.doc_state("d").oracle))
    assert srv.verify_doc("d") and twin.verify_doc("d")


def test_local_touch_restores_evicted_doc(tmp_path):
    srv = DocServer(cfg(tmp_path))
    srv.admit_doc("d")
    srv.submit_local("d", "ed", 0, ins_content="hello")
    srv.tick()
    doc = srv.doc_state("d")
    srv.residency.evict(doc)
    # A local edit is a touch: restore + apply on the next tick.
    srv.submit_local("d", "ed", 0, ins_content="ok ")
    srv.tick()
    assert srv.doc_string("d") == "ok hello"
    assert srv.counters.get("restores") == 1


def test_lru_evicts_coldest_lane_doc(tmp_path):
    srv = DocServer(cfg(tmp_path, lanes_per_shard=2))
    for i in range(3):
        srv.admit_doc(f"d{i}")
    srv.submit_local("d0", "e", 0, ins_content="a")
    srv.tick()
    srv.submit_local("d1", "e", 0, ins_content="b")
    srv.tick()
    # Both lanes held; d2's traffic must steal d0 (the coldest).
    srv.submit_local("d2", "e", 0, ins_content="c")
    srv.tick()
    assert srv.doc_state("d2").in_lane
    assert srv.doc_state("d0").evicted
    assert srv.doc_state("d1").in_lane
    # d0 comes back on touch, bit-identical.
    srv.submit_local("d0", "e", 1, ins_content="z")
    srv.tick()
    assert srv.doc_string("d0") == "az"
    for i in range(3):
        assert srv.verify_doc(f"d{i}")


def test_corrupt_checkpoint_refuses_to_restore(tmp_path):
    srv = DocServer(cfg(tmp_path))
    srv.admit_doc("d")
    srv.submit_local("d", "e", 0, ins_content="precious")
    srv.tick()
    doc = srv.doc_state("d")
    path = srv.residency.evict(doc)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:len(raw) // 2])  # truncated: must refuse whole
    with pytest.raises(CheckpointError):
        srv.residency.restore(doc)
    assert doc.evicted  # refused whole: no partial state loaded


def test_request_frames_deferred_while_evicted(tmp_path):
    """A REQUEST for an evicted doc is a touch + a retry, not a crash."""
    srv = DocServer(cfg(tmp_path))
    srv.admit_doc("d")
    srv.submit_local("d", "e", 0, ins_content="hi")
    srv.tick()
    srv.residency.evict(srv.doc_state("d"))
    out = srv.submit_frame("d", codec.encode_request({"e": 0}))
    assert out == []
    assert srv.counters.get("requests_deferred_evicted") == 1

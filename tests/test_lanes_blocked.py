"""BLOCKED per-lane engines vs the un-blocked engines and the oracle.

The ISSUE-2 tentpole bar: the K-row-block restructure of
``rle_lanes`` / ``rle_lanes_mixed`` must be BIT-IDENTICAL to the
un-blocked kernels — same expanded per-char state, same per-op origins,
same by-order tables — across splits (tiny K forces them), warm-started
chunk chains with growing capacities, lane tiling, and every remote
shape the mixed engine runs.  Interpreter mode.
"""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.common import (
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle_lanes as RL
from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM

from test_device_flat import oracle_from_patches, random_patches
from test_rle_lanes import compile_stack
from test_rle_lanes_mixed import (
    compile_txn_lanes,
    oracle_signed,
    oracle_txns,
)

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def assert_same_doc(ref, blk, docs):
    """Blocked and un-blocked results describe the same documents and
    emitted origins."""
    for d in range(docs):
        assert (RL.expand_lane(ref, d).tolist()
                == RL.expand_lane(blk, d).tolist()), f"lane {d}"
    assert np.array_equal(np.asarray(ref.ol), np.asarray(blk.ol))
    assert np.array_equal(np.asarray(ref.orr), np.asarray(blk.orr))


class TestBlockedLocalLanes:
    @pytest.mark.parametrize("seed,block_k", [
        (7, 16), pytest.param(42, 8, marks=pytest.mark.slow)])
    def test_divergent_vs_unblocked(self, seed, block_k):
        rng = random.Random(seed)
        streams = [random_patches(rng, 30 + rng.randint(0, 20))[0]
                   for _ in range(8)]
        stacked, _ = compile_stack(streams)
        ref = RL.replay_lanes(stacked, capacity=256, chunk=16,
                              interpret=True)
        blk = RL.make_replayer_lanes_blocked(
            stacked, capacity=256, block_k=block_k, chunk=16,
            interpret=True)()
        ref.check()
        blk.check()
        # Tiny K must actually exercise splits or the test is vacuous.
        assert int(np.asarray(blk.nlog).max()) > 1
        assert_same_doc(ref, blk, 8)

    def test_warm_start_growing_capacity(self):
        rng = random.Random(31)
        docs = 4
        nexts = [0] * docs
        state = refstate = None
        for cap in (64, 128, 192):
            streams = [random_patches(rng, 15)[0] for _ in range(docs)]
            opses = []
            for d, ps in enumerate(streams):
                ops, nexts[d] = B.compile_local_patches(
                    ps, lmax=8, dmax=None, start_order=nexts[d])
                opses.append(ops)
            stacked = B.stack_ops(opses)
            blk = RL.make_replayer_lanes_blocked(
                stacked, capacity=cap, block_k=16, chunk=16,
                interpret=True)(state)
            blk.check()
            state = blk.state()
            ref = RL.make_replayer_lanes(
                stacked, capacity=cap, chunk=16,
                interpret=True)(refstate)
            ref.check()
            refstate = ref.state()
        assert_same_doc(ref, blk, docs)

    def test_tiled_equals_whole(self):
        rng = random.Random(99)
        streams = [random_patches(rng, 25)[0] for _ in range(8)]
        stacked, _ = compile_stack(streams)
        kw = dict(capacity=128, block_k=16, chunk=8, interpret=True)
        whole = RL.make_replayer_lanes_blocked(stacked, **kw)()
        tiled = RL.make_replayer_lanes_blocked(stacked, lane_tile=4,
                                               **kw)()
        whole.check()
        tiled.check()
        for f in ("ordp", "lenp", "nlog", "blkord", "rws", "liv", "ol",
                  "orr"):
            assert np.array_equal(np.asarray(getattr(whole, f)),
                                  np.asarray(getattr(tiled, f))), f

    def test_out_of_blocks_flag_per_lane(self):
        # Lane 1 outgrows a 2-block capacity (inserts interleaved with
        # deletes so runs can't merge); lane 0 stays legal.
        from text_crdt_rust_tpu.utils.testdata import TestPatch

        busy = []
        for k in range(24):
            busy.append(TestPatch(0, 0, "ab"))
            if k % 2:
                busy.append(TestPatch(1, 1, ""))
        streams = [[TestPatch(0, 0, "ab")], busy]
        stacked, _ = compile_stack(streams)
        res = RL.make_replayer_lanes_blocked(
            stacked, capacity=16, block_k=8, chunk=8, interpret=True)()
        with pytest.raises(RuntimeError, match="lanes \\[1\\]"):
            res.check()

    def test_bad_delete_flag(self):
        from text_crdt_rust_tpu.utils.testdata import TestPatch

        streams = [[TestPatch(0, 0, "abc"), TestPatch(0, 10, "")]]
        stacked, _ = compile_stack(streams)
        res = RL.make_replayer_lanes_blocked(
            stacked, capacity=16, block_k=8, chunk=8, interpret=True)()
        with pytest.raises(RuntimeError, match="past the end"):
            res.check()


class TestBlockedMixedLanes:
    # Both seeds are slow-tier (ISSUE 11 budget satellite: ~16 s of
    # interpret compile each): the tier-1 representative of the
    # blocked-mixed differential surface is test_fuzz_blocked's
    # 60-seed blocked-vs-flat-vs-oracle sweep, which covers two-peer
    # merge streams at a fraction of the wall.
    @pytest.mark.parametrize("seed", [
        pytest.param(3, marks=pytest.mark.slow),
        pytest.param(21, marks=pytest.mark.slow)])
    def test_two_peer_merges_vs_unblocked_and_oracle(self, seed):
        rng = random.Random(seed)
        lane_txns = []
        for _ in range(3):
            pa, _ = random_patches(rng, 20)
            pb, _ = random_patches(rng, 20)
            a = oracle_from_patches(pa, agent="peer-a")
            b = oracle_from_patches(pb, agent="peer-b")
            lane_txns.append(export_txns_since(a, 0)
                             + export_txns_since(b, 0))
        stacked = compile_txn_lanes(lane_txns)
        ref = RLM.replay_lanes_mixed(stacked, capacity=256, chunk=16,
                                     interpret=True)
        blk = RLM.replay_lanes_mixed_blocked(
            stacked, capacity=256, block_k=16, chunk=16, interpret=True)
        ref.check()
        blk.check()
        assert int(np.asarray(blk.nlog).max()) > 1
        for d, txns in enumerate(lane_txns):
            want = oracle_signed(oracle_txns(txns))
            assert RL.expand_lane(blk, d).tolist() == want, f"lane {d}"
        assert_same_doc(ref, blk, len(lane_txns))
        for f in ("oll", "orl"):
            assert np.array_equal(np.asarray(getattr(ref, f)),
                                  np.asarray(getattr(blk, f))), f

    @pytest.mark.slow
    def test_storms_with_deletes(self):
        from text_crdt_rust_tpu.utils.randedit import make_storm

        lane_txns = [make_storm(3, 5, 2, seed=50 + k, del_prob=0.35)[0]
                     for k in range(3)]
        stacked = compile_txn_lanes(lane_txns, lmax=4)
        ref = RLM.replay_lanes_mixed(stacked, capacity=256, chunk=16,
                                     interpret=True)
        blk = RLM.replay_lanes_mixed_blocked(
            stacked, capacity=256, block_k=8, chunk=16, interpret=True)
        ref.check()
        blk.check()
        for d, txns in enumerate(lane_txns):
            want = oracle_signed(oracle_txns(txns))
            assert RL.expand_lane(blk, d).tolist() == want, f"lane {d}"
        assert_same_doc(ref, blk, len(lane_txns))

    @pytest.mark.slow
    def test_long_remote_delete_spans_blocks(self):
        # A 40-char interval delete crosses several 8-row blocks: full
        # covers flip plane-wide, both endpoint runs 3-way-split in
        # their own blocks; plus a double delete for idempotency.
        l0 = [
            RemoteTxn(id=RemoteId("amy", 0), parents=[],
                      ops=[RemoteIns(ROOT, ROOT, "x" * 50)]),
            RemoteTxn(id=RemoteId("bob", 0),
                      parents=[RemoteId("amy", 49)],
                      ops=[RemoteDel(RemoteId("amy", 5), 40)]),
            RemoteTxn(id=RemoteId("cat", 0),
                      parents=[RemoteId("amy", 49)],
                      ops=[RemoteDel(RemoteId("amy", 3), 10)]),
        ]
        # Fragment the run first so the interval covers MANY runs.
        l1 = [RemoteTxn(id=RemoteId("amy", 0), parents=[],
                        ops=[RemoteIns(ROOT, ROOT, "abcdefgh")])]
        for k, s in enumerate((1, 3, 5)):
            l1.append(RemoteTxn(
                id=RemoteId("bob", k), parents=[],
                ops=[RemoteDel(RemoteId("amy", s), 1)]))
        l1.append(RemoteTxn(id=RemoteId("cat", 0), parents=[],
                            ops=[RemoteDel(RemoteId("amy", 1), 6)]))
        lane_txns = [l0, l1]
        stacked = compile_txn_lanes(lane_txns, lmax=50)
        ref = RLM.replay_lanes_mixed(stacked, capacity=128, chunk=16,
                                     interpret=True)
        blk = RLM.replay_lanes_mixed_blocked(
            stacked, capacity=128, block_k=8, chunk=16, interpret=True)
        ref.check()
        blk.check()
        for d, txns in enumerate(lane_txns):
            want = oracle_signed(oracle_txns(txns))
            assert RL.expand_lane(blk, d).tolist() == want, f"lane {d}"
        assert_same_doc(ref, blk, 2)

    @pytest.mark.slow
    def test_mixed_local_and_remote_lanes_same_step(self):
        rng = random.Random(11)
        patches, content = random_patches(rng, 25)
        local_ops, _ = B.compile_local_patches(
            B.merge_patches(patches), lmax=8, dmax=None)
        pa, _ = random_patches(rng, 18)
        a = oracle_from_patches(pa, agent="peer-a")
        txns = export_txns_since(a, 0)
        table = B.AgentTable()
        for t in txns:
            table.add(t.id.agent)
        remote_ops, _ = B.compile_remote_txns(txns, table, lmax=8,
                                              dmax=16)
        stacked = B.stack_ops([local_ops, remote_ops])
        ref = RLM.replay_lanes_mixed(stacked, capacity=256, chunk=16,
                                     interpret=True)
        blk = RLM.replay_lanes_mixed_blocked(
            stacked, capacity=256, block_k=16, chunk=16, interpret=True)
        ref.check()
        blk.check()
        assert_same_doc(ref, blk, 2)

    @pytest.mark.slow
    def test_warm_start_chunks_grow_capacity(self):
        rng = random.Random(42)
        docs = 3
        peers = [oracle_from_patches(random_patches(rng, 30)[0],
                                     agent=f"p{d}") for d in range(docs)]
        lane_txns = [export_txns_since(p, 0) for p in peers]
        halves = [(t[: len(t) // 2], t[len(t) // 2:])
                  for t in lane_txns]
        tables = [B.AgentTable() for _ in range(docs)]
        assigners = [None] * docs

        def compile_chunk(which):
            opses = []
            for d in range(docs):
                for t in halves[d][which]:
                    tables[d].add(t.id.agent)
                ops, assigners[d] = B.compile_remote_txns(
                    halves[d][which], tables[d],
                    assigner=assigners[d], lmax=4, dmax=None)
                opses.append(ops)
            return B.stack_ops(opses)

        c0 = compile_chunk(0)
        r0 = RLM.make_replayer_lanes_mixed_blocked(
            c0, capacity=128, block_k=16, order_capacity=512, chunk=16,
            interpret=True)()
        r0.check()
        c1 = compile_chunk(1)
        _, _, rkl0 = RLM.lane_tables(c0, 512)
        _, _, rkl1 = RLM.lane_tables(c1, 512)
        rkl = np.where(rkl1 != 0, rkl1, rkl0)
        r1 = RLM.make_replayer_lanes_mixed_blocked(
            c1, capacity=256, block_k=16, order_capacity=512,
            init=r0.state(), rkl=rkl, chunk=16, interpret=True)()
        r1.check()
        for d in range(docs):
            want = oracle_signed(oracle_txns(lane_txns[d]))
            assert RL.expand_lane(r1, d).tolist() == want, f"lane {d}"

    def test_remote_delete_out_of_blocks_is_clean_noop(self):
        # A remote delete whose endpoint split cannot be housed (table
        # full) must flag AND leave the lane untouched — the blocked
        # twin of the un-blocked tight-gate regression.
        txns = [RemoteTxn(id=RemoteId("amy", 0), parents=[],
                          ops=[RemoteIns(ROOT, ROOT, "aaaaaaaa")])]
        for k, s in enumerate((1, 3, 5, 6)):
            txns.append(RemoteTxn(
                id=RemoteId("bob", k), parents=[],
                ops=[RemoteDel(RemoteId("amy", s), 1)]))
        stacked = compile_txn_lanes([txns], lmax=8)
        res = RLM.replay_lanes_mixed_blocked(
            stacked, capacity=8, block_k=8, chunk=8, interpret=True)
        with pytest.raises(RuntimeError, match="lanes \\[0\\]"):
            res.check()

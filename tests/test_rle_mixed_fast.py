"""The vectorized YATA conflict scan (`ops/rle_mixed.py` integrate_fast)
must be BIT-IDENTICAL to the serial run-walk it replaces, on every
window shape — siblings, split pieces, tombstones, merge-appended runs
— falling back to the serial loop (via its flag) wherever its
classification cannot prove the window trivial.  Reference semantics:
`/root/reference/src/list/doc.rs:183-222` with the pinned-scan_start
rule (tests/test_integrate_divergence.py)."""
import random

import numpy as np
import pytest

from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import export_txns_since
from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import rle as R
from text_crdt_rust_tpu.ops import rle_mixed as RM
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.randedit import make_storm


def replay_both(txns, capacity, block_k=8, lmax=4, chunk=128, dmax=None):
    """(fast_flat, serial_flat) for one txn stream; tiny blocks force
    splits so the aux planes' motion paths are all exercised."""
    table = B.AgentTable()
    for t in txns:
        table.add(t.id.agent)
        for op in t.ops:
            if hasattr(op, "id"):
                table.add(op.id.agent)
    ops, _ = B.compile_remote_txns(txns, table, lmax=lmax, dmax=dmax)
    outs = []
    for fast in (True, False):
        res = RM.replay_mixed_rle(ops, capacity=capacity, batch=8,
                                  block_k=block_k, chunk=chunk,
                                  interpret=True, fast_integrate=fast)
        res.check()
        outs.append(R.rle_to_flat(ops, res))
    return outs


def oracle_txns(txns):
    doc = ListCRDT()
    for t in txns:
        doc.apply_remote_txn(t)
    return doc


def assert_fast_exact(txns, capacity=512):
    fast, serial = replay_both(txns, capacity)
    want = oracle_txns(txns).to_string()
    assert SA.to_string(serial) == want
    assert SA.to_string(fast) == want
    assert np.array_equal(np.asarray(fast.signed),
                          np.asarray(serial.signed))


@pytest.mark.slow
class TestTier1Smoke:
    """Representative of the fast-vs-serial property (the full matrix
    below also runs under ``-m slow``): one small storm with deletes
    through both scan paths, bit-identical and oracle-equal.  Demoted
    from tier-1 (PR 17 wall budget: ~47 s, the suite was brushing the
    870 s gate timeout); the fast scan path keeps tier-1 coverage
    through the ``test_rle_lanes_mixed`` tiling/growth suites and the
    serve-lanes backend tests, which drive the same engine."""

    def test_small_delete_storm(self):
        txns, receiver = make_storm(3, 4, 2, seed=7, del_prob=0.3)
        fast, serial = replay_both(txns, capacity=256, chunk=32)
        want = oracle_txns(txns).to_string()
        assert want == receiver.to_string()
        assert SA.to_string(serial) == want
        assert SA.to_string(fast) == want
        assert np.array_equal(np.asarray(fast.signed),
                              np.asarray(serial.signed))


@pytest.mark.slow
class TestFastIntegrate:
    def test_insert_storm(self):
        # The config-4 shape: every window run is a ROOT-origin sibling.
        txns, receiver = make_storm(4, 8, 3, seed=7)
        assert_fast_exact(txns)
        assert oracle_txns(txns).to_string() == receiver.to_string()

    def test_delete_heavy_storm(self):
        # Splits + tombstones inside scan windows (chain pieces, the
        # -2 origin-right sentinel, full/partial covers).
        txns, receiver = make_storm(4, 10, 3, seed=11, del_prob=0.4)
        assert_fast_exact(txns)
        assert oracle_txns(txns).to_string() == receiver.to_string()

    @pytest.mark.parametrize("seed", range(6))
    def test_two_peer_random_merge(self, seed):
        # Random concurrent edits with periodic cross-merges: windows
        # contain descendants, split tails, and mid-run cursors.
        rng = random.Random(400 + seed)
        a_doc, b_doc = ListCRDT(), ListCRDT()
        a = a_doc.get_or_create_agent_id("amy")
        b = b_doc.get_or_create_agent_id("bob")
        marks = {"amy": 0, "bob": 0}
        flat = []

        def edit(doc, agent, r):
            n = len(doc)
            if n == 0 or r.random() < 0.6:
                pos = r.randint(0, n)
                doc.local_insert(agent, pos, "".join(
                    r.choice("abcdef") for _ in range(r.randint(1, 3))))
            else:
                pos = r.randint(0, n - 1)
                doc.local_delete(agent, pos,
                                 min(r.randint(1, 3), n - pos))

        applied = {"amy": set(), "bob": set()}
        for round_ in range(6):
            for doc, agent, name in ((a_doc, a, "amy"), (b_doc, b, "bob")):
                for _ in range(rng.randint(1, 4)):
                    edit(doc, agent, rng)
                txns = export_txns_since(doc, marks[name])
                flat.extend(txns)
            # cross-merge everything so far (valid causal order), then
            # re-mark so merged remote ops are never re-exported.
            for doc, me in ((a_doc, "amy"), (b_doc, "bob")):
                for t in flat:
                    key = (t.id.agent, t.id.seq)
                    if t.id.agent != me and key not in applied[me]:
                        applied[me].add(key)
                        doc.apply_remote_txn(t)
            marks["amy"] = a_doc.get_next_order()
            marks["bob"] = b_doc.get_next_order()
        assert_fast_exact(flat, capacity=1024)

    def test_merge_appended_or_divergence_window(self):
        # Regression guard for the stale-orp hole: agent Q's second txn
        # merge-appends into its first run (chain), a split later
        # separates them, and a concurrent sibling probes the piece.
        q_doc = ListCRDT()
        q = q_doc.get_or_create_agent_id("quin")
        q_doc.local_insert(q, 0, "XY")          # txn1: run [XY]
        t1 = export_txns_since(q_doc, 0)
        m = q_doc.get_next_order()
        q_doc.local_insert(q, 2, "Z")           # txn2: appends, chains
        t2 = export_txns_since(q_doc, m)

        c_doc = ListCRDT()
        c = c_doc.get_or_create_agent_id("cara")
        for t in t1:                            # cara sees txn1 only
            c_doc.apply_remote_txn(t)
        m3 = c_doc.get_next_order()
        c_doc.local_insert(c, 1, "a")           # between X and Y
        t3 = export_txns_since(c_doc, m3)

        # Receiver integrates in both causal orders.
        for stream in ([*t1, *t2, *t3], [*t1, *t3, *t2]):
            assert_fast_exact(stream, capacity=256)

    def test_split_tail_requalifies_as_sibling(self):
        # ADVICE r5 item 3: an insert-split used to poison the tail's
        # aux origin-right with -2, forcing the serial walk forever on
        # any window holding it.  The tail's TRUE origin-right is now
        # read from the orl table at split time, so a later concurrent
        # sibling probing a window that contains the split tail must
        # classify it exactly (same tiebreak outcome as the serial
        # walk and the oracle), in both causal orders.
        def typed(name, see, edit):
            doc = ListCRDT()
            agent = doc.get_or_create_agent_id(name)
            for t in see:
                doc.apply_remote_txn(t)
            m = doc.get_next_order()
            edit(doc, agent)
            return export_txns_since(doc, m)

        # mmm types "ab", APPENDS "cd" (merge-appends into one run
        # [abcd]; c's table origin-right is ROOT, the head's is not),
        # then SPLITS at 2 with "Q" -> [ab][Q][cd].  The tail [cd]'s
        # head chains to b, and its orl entry (ROOT) differs from the
        # head run's — exactly the "unknowable from the head" case.
        t1 = typed("mmm", [], lambda d, g: d.local_insert(g, 0, "ab"))
        t2 = typed("mmm", t1, lambda d, g: d.local_insert(g, 2, "cd"))
        t3 = typed("mmm", [*t1, *t2],
                   lambda d, g: d.local_insert(g, 2, "Q"))
        # Concurrent peers who saw ONLY "ab" insert after b with
        # origin_right ROOT: their scan windows run to the doc end and
        # contain the split tail as a SIBLING (origin_left == b ==
        # the tail head's) — zzz outranks mmm (scan continues past),
        # aaa ranks below with a matching origin-right (breaks AT the
        # tail), covering both tiebreak arms of the repaired path.
        t4 = typed("zzz", t1, lambda d, g: d.local_insert(g, 2, "z"))
        t5 = typed("aaa", t1, lambda d, g: d.local_insert(g, 2, "a"))
        for stream in ([*t1, *t2, *t3, *t4, *t5],
                       [*t1, *t2, *t4, *t3, *t5],
                       [*t1, *t2, *t5, *t4, *t3]):
            assert_fast_exact(stream, capacity=256)

    def test_pseudo_breaker_beats_stale_window_kss(self):
        # Review r5 regression: the pseudo candidate (mid-run char at
        # cursor0) BREAKS the scan (rank > mine, same origin_right),
        # while the window still holds a higher-ranked different-
        # origin-right sibling (kss).  kss was reduced against the
        # pre-pseudo kfb; the winner must be the pseudo's cursor0, not
        # the stale kss run.
        def typed(name, see, edit):
            doc = ListCRDT()
            agent = doc.get_or_create_agent_id(name)
            for t in see:
                doc.apply_remote_txn(t)
            m = doc.get_next_order()
            edit(doc, agent)
            return export_txns_since(doc, m)

        t1 = typed("mmm", [], lambda d, g: d.local_insert(g, 0, "X"))
        t2 = typed("mmm", t1, lambda d, g: d.local_insert(g, 1, "Y"))
        # ppp saw only X: W after X (ol=X, or=ROOT) — my SGO window run.
        t3 = typed("ppp", t1, lambda d, g: d.local_insert(g, 1, "W"))
        # zzz saw X and W: z between them (ol=X, or=W) — the SGN run.
        t4 = typed("zzz", [*t1, *t3],
                   lambda d, g: d.local_insert(g, 1, "z"))
        # aaa (lowest rank) saw only X: a after X (ol=X, or=ROOT); at
        # the receiver its scan window starts MID-RUN at Y (chained
        # into X's run, rank mmm > aaa, or ROOT == mine -> break).
        t5 = typed("aaa", t1, lambda d, g: d.local_insert(g, 1, "a"))
        assert_fast_exact([*t1, *t2, *t3, *t4, *t5], capacity=256)

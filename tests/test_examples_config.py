"""CLI drivers (`examples/simple.rs` / `stats.rs` analogs), the config
layer, the rope text-only baseline, and the batched FlatDoc checkpoint
(the config-5 resync path that r2 shipped broken — save_flat_doc crashed
on any stack_docs batch)."""
import numpy as np
import pytest

from text_crdt_rust_tpu.config import EngineConfig, SoakConfig, StatsConfig
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.checkpoint import load_flat_doc, save_flat_doc


class TestConfigLayer:
    def test_soak_from_args(self):
        cfg = SoakConfig.from_args(["--edits", "500", "--seed", "3",
                                    "--oracle", "100"])
        assert (cfg.edits, cfg.seed, cfg.oracle_steps) == (500, 3, 100)

    def test_stats_from_args(self):
        cfg = StatsConfig.from_args(["--trace", "rustcode"])
        assert cfg.trace == "rustcode" and cfg.engine == "native"

    def test_engine_defaults(self):
        cfg = EngineConfig()
        assert cfg.engine == "rle" and cfg.batch == 128


class TestSoakCli:
    def test_small_soak_runs(self, capsys):
        from text_crdt_rust_tpu.examples.soak import main

        rc = main(["--edits", "3000", "--oracle", "300", "--seed", "11"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "oracle prefix OK" in out
        assert "content OK" in out


class TestSyncStreamCli:
    def test_small_sync_stream_runs(self, capsys):
        from text_crdt_rust_tpu.examples.sync_stream import main

        rc = main(["--docs", "3", "--chunks", "2",
                   "--ops-per-chunk", "8", "--seed", "3"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "docs == oracle" in out
        assert "every chunk oracle-checked" in out


class TestStatsCli:
    @pytest.mark.parametrize("engine", ["native", "oracle"])
    def test_stats_runs(self, engine, capsys):
        from text_crdt_rust_tpu.examples.stats import main

        rc = main(["--trace", "sveltecomponent", "--engine", engine])
        assert rc == 0
        out = capsys.readouterr().out
        assert "final content OK" in out
        assert "merged spans" in out


class TestRopeBaseline:
    def test_rope_matches_splice_oracle(self):
        from text_crdt_rust_tpu.models.native import rope_replay
        from text_crdt_rust_tpu.utils.randedit import random_patches
        import random

        patches, content = random_patches(random.Random(5), 400)
        pos = [p.pos for p in patches]
        dels = [p.del_len for p in patches]
        il = [len(p.ins_content) for p in patches]
        cps = np.frombuffer("".join(p.ins_content for p in patches)
                            .encode("utf-32-le"), np.uint32)
        n, got = rope_replay(pos, dels, il, cps)
        assert got == content
        assert n == len(content)

    def test_rope_growth_with_delete_insert_patch(self):
        # Regression (r3 review): a patch that deletes AND inserts while
        # forcing buffer growth used the pre-delete live count, injecting
        # del_len NUL codepoints at the gap.
        from text_crdt_rust_tpu.models.native import rope_replay

        cps = np.frombuffer(("a" * 4096 + "b" * 10).encode("utf-32-le"),
                            np.uint32)
        n, content = rope_replay([0, 0], [0, 2], [4096, 10], cps)
        assert n == 4104
        assert content == "b" * 10 + "a" * 4094

    def test_rope_rejects_bad_patch(self):
        from text_crdt_rust_tpu.models.native import rope_replay

        with pytest.raises(RuntimeError, match="out of range"):
            rope_replay([5], [0], [1], np.asarray([65], np.uint32))


class TestBatchedCheckpoint:
    def test_roundtrip_batch(self, tmp_path):
        docs = SA.stack_docs(SA.make_flat_doc(64), 4)
        path = str(tmp_path / "batch.npz")
        save_flat_doc(docs, path)
        back = load_flat_doc(path)
        assert back.signed.shape == docs.signed.shape
        assert back.n.shape == docs.n.shape
        np.testing.assert_array_equal(np.asarray(back.signed),
                                      np.asarray(docs.signed))

    def test_roundtrip_unbatched(self, tmp_path):
        doc = SA.make_flat_doc(64)
        path = str(tmp_path / "one.npz")
        save_flat_doc(doc, path)
        back = load_flat_doc(path)
        assert back.n.shape == ()


class TestSimulateRunRows:
    def test_matches_trace_measurement(self):
        from text_crdt_rust_tpu.ops import batch as B
        from text_crdt_rust_tpu.ops.rle import simulate_run_rows
        from text_crdt_rust_tpu.utils.testdata import (
            flatten_patches, load_testing_data, trace_path)

        data = load_testing_data(trace_path("sveltecomponent"))
        merged = B.merge_patches(flatten_patches(data))
        peak, final = simulate_run_rows(merged)
        assert final == 7022  # measured once, pinned (r3 PERF.md)
        assert peak == final

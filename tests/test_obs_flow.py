"""obs/flow (ISSUE 11): per-op provenance spans, the conservation
audit, op-age distributions, and the layer plumb-throughs (frame ids,
span-carrying rejects, fused-super-step attribution, the divergence
bundle's flow-path join).

The load-bearing pair: (1) the faulted loadgen run terminally accounts
EVERY emitted span (zero leaked / double-applied) — conservation as a
gated invariant, not folklore; (2) the leak-injection harness proves
the audit fails LOUD naming the span, so a green audit means
something."""
import json

import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.obs import analyze as A
from text_crdt_rust_tpu.obs.flow import (
    FlowTracker,
    _merge,
    _subtract,
    agent_sampled,
    audit_spans,
    flow_report,
    spans_from_events,
)
from text_crdt_rust_tpu.obs.trace import Tracer, validate_event


def flow_run(seed=7, sample_mod=1, workload="scatter", **cfg_kw):
    """The small faulted loadgen at full flow sampling (the
    ``test_obs_trace.small_loadgen_run`` shape + flow)."""
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    cfg = ServeConfig(num_shards=1, lanes_per_shard=4,
                      flow_sample_mod=sample_mod, **cfg_kw)
    gen = ServeLoadGen(docs=6, agents_per_doc=2, ticks=6,
                       events_per_tick=12, fault_rate=0.10, seed=seed,
                       cfg=cfg, workload=workload)
    rep = gen.run()
    assert rep["converged"], rep["mismatches"]
    return gen, rep


@pytest.fixture(scope="module")
def run_pair():
    return flow_run()


# ------------------------------------------------- interval helpers -----


def test_interval_merge_and_subtract():
    assert _merge([(5, 8), (0, 3), (2, 6)]) == [(0, 8)]
    assert _merge([(0, 2), (4, 6)]) == [(0, 2), (4, 6)]
    assert _subtract([(0, 10)], [(2, 4), (6, 8)]) == [
        (0, 2), (4, 6), (8, 10)]
    assert _subtract([(0, 4)], [(0, 4)]) == []
    assert _subtract([(0, 4)], []) == [(0, 4)]


def test_agent_sampling_is_deterministic_and_total_at_mod_1():
    assert agent_sampled("anyone", 1)
    assert not agent_sampled("anyone", 0)
    for mod in (2, 4, 16):
        names = [f"d{i:04d}.a{j}" for i in range(40) for j in range(3)]
        picks = [n for n in names if agent_sampled(n, mod)]
        assert picks == [n for n in names if agent_sampled(n, mod)]
        assert 0 < len(picks) < len(names)


# ------------------------------------------- the conservation audit -----


def test_faulted_loadgen_conserves_every_span(run_pair):
    """The tentpole acceptance at small scale: 10% drops / dups /
    reorders / truncations / bit-flips, and after the anti-entropy
    drain every emitted op span is terminally accounted."""
    gen, rep = run_pair
    f = rep["flow"]
    assert f["audit_ok"], f["findings"]
    assert f["spans"]["in_flight"] == 0
    assert f["duplicates"] == 0 and f["leaks"] == 0
    assert f["spans"]["emitted"] > 50
    assert (f["spans"]["applied"] + f["spans"]["rejected"]
            == f["spans"]["emitted"])
    # The flow ledger agrees with the server's own typed counters:
    # every invalid-position local drop is a rejected span.
    assert f["spans"]["rejected"] == rep["server"]["events_invalid"]
    # Ages exist and are logical ticks.
    assert f["ages_ticks"]["count"] == f["applies"]["device"] + \
        f["applies"]["host"]
    assert f["ages_ticks"]["p99"] >= f["ages_ticks"]["p50"] >= 0
    # Every emitted flow event validates against the trace schema.
    for ev in gen.server.flow.records:
        validate_event(ev)


def test_leak_injection_fails_loud_naming_the_span(run_pair):
    """Remove one span's terminal apply -> the audit names exactly that
    (doc, agent, seq) range with its last-known location."""
    gen, _rep = run_pair
    records = gen.server.flow.records
    victim = next(r for r in records
                  if r["k"] == "flow.apply" and "lk" not in r)
    injected = [r for r in records if r is not victim]
    rep = flow_report(injected, expect_terminal=True)
    assert not rep["audit_ok"]
    leak = next(f for f in rep["findings"] if f["kind"] == "leak")
    assert leak["doc"] == victim["doc"]
    assert leak["agent"] == victim["agent"]
    assert leak["seq"] >= victim["seq"]
    assert "last seen at" in leak["detail"]


def test_duplicate_apply_fails_loud(run_pair):
    gen, _rep = run_pair
    records = gen.server.flow.records
    victim = next(r for r in records
                  if r["k"] == "flow.apply" and "lk" not in r)
    rep = flow_report(records + [dict(victim)], expect_terminal=True)
    assert not rep["audit_ok"]
    dup = next(f for f in rep["findings"]
               if f["kind"] == "duplicate-apply")
    assert dup["doc"] == victim["doc"]
    assert dup["agent"] == victim["agent"]
    assert "applied twice" in dup["detail"]


def test_phantom_apply_is_a_finding():
    tr = Tracer(ring=8, keep_all=True)
    flow = FlowTracker(tr, sample_mod=1)
    flow.applied("d0", "ghost", 0, 4, "device")
    findings = audit_spans(spans_from_events(flow.records))
    assert findings and findings[0]["kind"] == "phantom-apply"
    assert "never emitted" in findings[0]["detail"]


def test_in_flight_spans_name_their_location():
    """The third terminal state: in-flight-at-shutdown spans carry a
    NAMED location derived from their last lifecycle stage."""
    from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn

    tr = Tracer(ring=8, keep_all=True)
    flow = FlowTracker(tr, sample_mod=1)
    root = RemoteId("ROOT", 0)

    def txn(agent, seq, n):
        return RemoteTxn(RemoteId(agent, seq), [root],
                         [RemoteIns(root, root, "x" * n)])

    flow.emit_txns("d0", [txn("a", 0, 3)])              # emitted only
    flow.emit_txns("d0", [txn("b", 0, 2)])
    flow.framed("d0", [txn("b", 0, 2)], frame=7)        # framed
    flow.emit_txns("d0", [txn("c", 4, 2)])
    flow.framed("d0", [txn("c", 4, 2)], frame=8)
    flow.buffered("d0", txn("c", 4, 2), "held")         # causal gap
    # Non-strict mode: in-flight is a counted state, not a finding.
    rep = flow_report(flow.records, expect_terminal=False)
    assert rep["audit_ok"]
    assert rep["spans"]["in_flight"] == 3
    # Strict (end-of-run) mode: each leak names its location.
    rep = flow_report(flow.records, expect_terminal=True)
    assert not rep["audit_ok"]
    locs = {f["agent"]: f["detail"] for f in rep["findings"]}
    assert "network" in locs["a"]
    assert "admission" in locs["b"]
    assert "causal-buffer" in locs["c"]


def test_local_apply_counts_once_in_flow_events():
    """Review fix: an lk apply is indexed both by ordinal (to close
    the emission) and by realized seq (for the interval audit) — the
    census must count it ONCE."""
    tr = Tracer(ring=8, keep_all=True)
    flow = FlowTracker(tr, sample_mod=1)
    lk = flow.emit_local("d0", "editor", 3)
    flow.applied("d0", "editor", 0, 3, "host", lk=lk)
    rep = flow_report(flow.records, expect_terminal=True)
    assert rep["audit_ok"]
    assert rep["flow_events"] == 2  # emit + apply, not 3


def test_truncated_retention_refuses_to_certify(monkeypatch):
    """Review fix: in-process retention is bounded (the PR-8 ring
    discipline) — a tracker that hit its cap must refuse to claim a
    clean audit and point at the offline trace path."""
    tr = Tracer(ring=8, keep_all=True)
    flow = FlowTracker(tr, sample_mod=1, max_records=2)
    for seq in range(3):
        flow.applied("d0", "a", seq, 1, "host")
    assert flow.truncated and len(flow.records) == 2
    rep = flow.report()
    assert not rep["audit_ok"]
    assert rep["findings"][0]["kind"] == "records-truncated"
    assert "analyze.py flow --audit" in rep["findings"][0]["detail"]


def test_local_spans_conserve_and_leak_loud():
    tr = Tracer(ring=8, keep_all=True)
    flow = FlowTracker(tr, sample_mod=1)
    lk0 = flow.emit_local("d0", "editor", 3)
    flow.applied("d0", "editor", 0, 3, "host", lk=lk0)
    lk1 = flow.emit_local("d0", "editor", 2)
    flow.rejected("d0", "editor", "invalid-position", lk=lk1)
    assert flow_report(flow.records,
                       expect_terminal=True)["audit_ok"]
    lk2 = flow.emit_local("d0", "editor", 1)
    assert lk2 == 2
    rep = flow_report(flow.records, expect_terminal=True)
    assert not rep["audit_ok"]
    f = rep["findings"][0]
    assert f["kind"] == "local-leak" and "lk=2" in f["detail"]


# ------------------------------------- eviction / restore conservation --


def test_evict_restore_replay_is_not_a_duplicate_apply(run_pair):
    """The small shape evicts and restores (6 docs on 4 lanes); the
    delta-chain restore REPLAYS checkpointed ops internally — which
    must re-create state, never re-apply it into the flow ledger.  The
    audit stays green across every evict->restore cycle AND the
    residency conservation pairs match exactly."""
    gen, rep = run_pair
    assert rep["server"]["restores"] > 0, "shape stopped exercising restore"
    assert rep["flow"]["audit_ok"]
    events = gen.server.flow.records
    evicts = [e for e in events if e["k"] == "residency.evict"]
    restores = [e for e in events if e["k"] == "residency.restore"]
    assert evicts and restores
    assert all("n" in e and "orders" in e for e in evicts + restores)


def test_tampered_restore_count_is_an_audit_finding(run_pair):
    """A restore replay that re-applied history would inflate the
    restored doc's item/order counts — inject exactly that and the
    audit names the doc."""
    gen, _rep = run_pair
    events = [dict(e) for e in gen.server.flow.records]
    victim = next(e for e in events
                  if e["k"] == "residency.restore" and "n" in e)
    victim["n"] += 5  # "the replay applied 5 items twice"
    findings = audit_spans(spans_from_events(events))
    bad = [f for f in findings if f["kind"] == "evict-restore-mismatch"]
    assert bad and bad[0]["doc"] == victim["doc"]
    assert "re-apply" in bad[0]["detail"]


# ------------------------------------------------- rotated segments -----


def test_audit_over_rotated_segments_with_mid_span_boundary(tmp_path):
    """ISSUE 11 satellite: a span whose lifecycle straddles a segment
    rollover reassembles through ``analyze.load_events`` — the offline
    audit equals the in-process one, byte for byte."""
    p = str(tmp_path / "flow.jsonl")
    gen, rep = flow_run(trace_path=p, trace_rotate_bytes=4096)
    segs = gen.server.tracer.segment_paths
    assert len(segs) > 2, "rotation cap never hit — shrink rotate_bytes"
    events = A.load_events(segs)
    offline = flow_report(events, expect_terminal=True)
    assert offline["audit_ok"], offline["findings"]
    # The offline census equals the in-process flow block exactly.
    inproc = dict(rep["flow"])
    inproc.pop("sample_mod")
    assert offline == inproc
    # At least one span's lifecycle crosses a segment boundary (the
    # boundary-mid-span case the satellite names).
    import itertools

    seg_of = {}
    for si, seg in enumerate(segs):
        for line in open(seg):
            ev = json.loads(line)
            if ev.get("k", "").startswith("flow.") and "seq" in ev:
                key = (ev["doc"], ev["agent"], ev["seq"])
                seg_of.setdefault(key, set()).add(si)
    assert any(len(s) > 1 for s in seg_of.values()), \
        "no span straddled a rotation boundary"
    del itertools


def test_analyze_flow_cli_audit_exit_codes(tmp_path, capsys):
    p = str(tmp_path / "t.jsonl")
    gen, _rep = flow_run(trace_path=p)
    assert A.main(["flow", p, "--audit"]) == 0
    out = capsys.readouterr()
    assert "conservation audit OK" in out.err
    # Tamper: drop the last flow.apply line -> exit 1 naming the span.
    lines = open(p).read().splitlines()
    drop = max(i for i, ln in enumerate(lines)
               if '"k":"flow.apply"' in ln)
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write("\n".join(ln for i, ln in enumerate(lines)
                          if i != drop) + "\n")
    assert A.main(["flow", bad, "--audit"]) == 1
    out = capsys.readouterr()
    assert "CONSERVATION AUDIT FAILED" in out.err
    victim = json.loads(lines[drop])
    assert victim["agent"] in out.err


# ------------------------------------------------- layer plumb-throughs --


def test_sampled_subset_is_end_to_end_complete():
    """Per-AGENT sampling keeps every tracked span complete, so the
    audit holds at any mod — the property that lets the shipped
    default sample and still mean something."""
    gen, rep = flow_run(sample_mod=4)
    f = rep["flow"]
    assert 0 < f["spans"]["emitted"]
    assert f["audit_ok"], f["findings"]
    assert f["spans"]["in_flight"] == 0
    agents = {r["agent"] for r in gen.server.flow.records
              if r["k"].startswith("flow.")}
    assert all(agent_sampled(a, 4) for a in agents)


def test_admission_reject_event_carries_offending_span(run_pair):
    """ISSUE 11 satellite: admission rejects name the (agent, seq)
    range, not just the reason class."""
    from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
    from text_crdt_rust_tpu.serve.admission import AdmissionError
    from text_crdt_rust_tpu.serve.server import DocServer

    srv = DocServer(ServeConfig(num_shards=1, lanes_per_shard=2,
                                trace_keep=True, max_txn_len=4,
                                flow_sample_mod=1))
    srv.admit_doc("d0")
    root = RemoteId("ROOT", 0)
    big = RemoteTxn(RemoteId("spammer", 7), [root],
                    [RemoteIns(root, root, "x" * 64)])
    with pytest.raises(AdmissionError):
        srv.submit_txn("d0", big)
    ev = next(e for e in srv.tracer.events
              if e["k"] == "admission.reject")
    assert ev["agent"] == "spammer" and ev["seq"] == 7
    assert ev["n"] == 64 and ev["doc"] == "d0"
    # And the span's flow ledger shows the typed terminal rejection.
    fr = next(e for e in srv.flow.records if e["k"] == "flow.reject")
    assert fr["agent"] == "spammer" and fr["seq"] == 7
    srv.close_obs()


def test_codec_reject_carries_span_for_invalid_txn(monkeypatch):
    """A CRC-valid frame whose txn fails structural validation: the
    codec.reject event names the offending (agent, seq) range."""
    from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
    from text_crdt_rust_tpu.net import codec
    from text_crdt_rust_tpu.serve.admission import AdmissionError
    from text_crdt_rust_tpu.serve.server import DocServer

    root = RemoteId("ROOT", 0)
    bad = RemoteTxn(RemoteId("evil", 3), [],  # no parents: invalid
                    [RemoteIns(root, root, "hi")])
    monkeypatch.setattr(codec, "validate_remote_txn", lambda t: None)
    frame = codec.encode_txns([bad])
    monkeypatch.undo()
    with pytest.raises(codec.CodecError) as ei:
        codec.decode_frame(frame)
    assert ei.value.agent == "evil" and ei.value.seq == 3
    assert ei.value.n == 2

    srv = DocServer(ServeConfig(num_shards=1, lanes_per_shard=2,
                                trace_keep=True))
    srv.admit_doc("d0")
    with pytest.raises(AdmissionError):
        srv.submit_frame("d0", frame)
    ev = next(e for e in srv.tracer.events if e["k"] == "codec.reject")
    assert ev["agent"] == "evil" and ev["seq"] == 3 and ev["n"] == 2
    srv.close_obs()


def test_frame_id_is_stored_crc_and_deterministic():
    from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
    from text_crdt_rust_tpu.net import codec

    root = RemoteId("ROOT", 0)
    txn = RemoteTxn(RemoteId("a", 0), [root],
                    [RemoteIns(root, root, "hello")])
    frame = codec.encode_txns([txn])
    kind, value, off, info = codec.decode_frame_ex(frame)
    assert kind == codec.KIND_TXNS and off == len(frame)
    assert info.length == len(frame)
    import struct

    assert info.crc == struct.unpack("<I", frame[-4:])[0]
    # Same bytes -> same frame id (the dup-delivery property).
    assert codec.decode_frame_ex(frame)[3].crc == info.crc


def test_fused_super_step_attribution():
    """Typing runs fuse; their spans' flow.apply records name the
    fused super-step that absorbed them (fstep / fn)."""
    gen, rep = flow_run(workload="typing")
    assert rep["flow"]["audit_ok"]
    fused = [r for r in gen.server.flow.records
             if r["k"] == "flow.apply" and "fstep" in r]
    assert fused, "typing workload produced no fused attribution"
    assert all(r["fn"] >= 1 and r["fstep"] >= 0 for r in fused)


def test_buffer_pressure_drop_emits_flow_event():
    from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
    from text_crdt_rust_tpu.parallel.causal import CausalBuffer

    root = RemoteId("ROOT", 0)
    dropped = []
    buf = CausalBuffer(max_pending=1)
    buf.on_drop = dropped.append
    # Two far-future txns: the second offer evicts the farthest.
    for seq in (10, 20):
        buf.add(RemoteTxn(RemoteId("a", seq), [root],
                          [RemoteIns(root, root, "x")]))
    assert len(dropped) == 1 and dropped[0].id.seq == 20
    # The eviction chose the offer itself (farthest gap): the status
    # must say so — "buffered" here would stamp a held event after
    # on_drop already recorded the drop (review fix).
    assert buf.last_offer == "dropped"
    assert buf.pending == 1


def test_divergence_bundle_joins_flow_path(tmp_path):
    """ISSUE 11 satellite: the divergence post-mortem names the
    diverged op's FULL path, not just the first diverging event."""
    gen, _rep = flow_run(obs_dir=str(tmp_path))
    world = gen.worlds[0]
    doc = gen.server.doc_state(world.doc_id)
    # Manufacture a divergence: one more server edit the twin never
    # observes, then walk the first-divergence join.
    gen.server.submit_local(world.doc_id, "rogue-editor", 0, 0, "Z")
    gen.server.drain()
    path = gen.server.recorder.on_divergence(
        world.doc_id, doc.oracle, world.twin,
        detail="test-manufactured divergence")
    assert path is not None
    bundle = json.load(open(path))
    fd = bundle["first_divergence"]
    assert fd["agent"] == "rogue-editor"
    flow_path = bundle["flow_path"]
    assert flow_path, "bundle carries no flow path"
    assert {e["k"] for e in flow_path} >= {"flow.apply"}
    assert all(e["agent"] == "rogue-editor" for e in flow_path)
    gen.server.close_obs()


def test_flow_path_includes_local_span_lk_records():
    """Review fix: a local span's journey starts at its lk-keyed
    emission — the divergence bundle's flow_path must include it, not
    just the seq-carrying apply."""
    from text_crdt_rust_tpu.obs.recorder import FlightRecorder
    from text_crdt_rust_tpu.utils.metrics import Counters

    tr = Tracer(ring=64, keep_all=True)
    rec = FlightRecorder(tr, Counters(), "/tmp/unused_obs")
    flow = FlowTracker(tr, sample_mod=1)
    lk = flow.emit_local("d0", "editor", 3)
    flow.applied("d0", "editor", 5, 3, "device", lk=lk)
    path = rec.flow_path("d0", "editor", 6)
    kinds = [e["k"] for e in path]
    assert kinds == ["flow.emit", "flow.apply"]
    assert path[0]["lk"] == lk and "seq" not in path[0]


def test_chrome_export_links_flow_spans_with_arrows():
    gen, _rep = flow_run(trace_keep=True)
    doc = A.chrome_trace(gen.server.tracer.events)
    phases = [e for e in doc["traceEvents"] if e.get("ph") in "stf"]
    assert phases, "no flow arrows emitted"
    by_id = {}
    for e in phases:
        by_id.setdefault(e["id"], []).append(e["ph"])
    for fid, phs in by_id.items():
        assert phs[0] == "s" and phs[-1] == "f", (fid, phs)
    # Finish arrows bind to slice ends (Perfetto's bp rule).
    assert all(e.get("bp") == "e" for e in phases if e["ph"] == "f")
    # Flow lifecycle events render as (sub-µs) DURATION slices, not
    # instants: the chrome format binds s/t/f arrows to an enclosing
    # slice on the same pid/tid/ts — an instant would drop the arrow.
    lifecycle = [e for e in doc["traceEvents"]
                 if str(e.get("name", "")).startswith("flow.")]
    assert lifecycle
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in lifecycle)


def test_flow_block_determinism_across_runs():
    """The flow census — being a pure function of the logical stream —
    is byte-deterministic across same-seed runs at full sampling."""
    _g1, rep1 = flow_run()
    _g2, rep2 = flow_run()
    assert rep1["flow"] == rep2["flow"]

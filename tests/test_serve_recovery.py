"""serve/journal + DocServer.recover (ISSUE 16): the write-ahead input
log and its re-execution recovery path.

The journal is a FULL input log — every state-mutating call that
crosses the admission edge, in order — and recovery re-executes it
through the normal admission -> buffer -> batcher path.  The tests here
pin the storage contract (CRC-chained records, torn tails refused with
a typed error naming segment and offset, valid prefix always
recovered), the end-to-end byte-identity of a recovered server, and the
batcher's crash-path bugfix (a typed error mid-tick drains the
in-flight pipeline instead of leaking staged syncs).
"""
import os

import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.serve import journal as J
from text_crdt_rust_tpu.serve.chaos import logical_stream_digest
from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen
from text_crdt_rust_tpu.serve.server import DocServer


# -- journal storage contract ------------------------------------------------


def _small_journal(tmp_path):
    """A one-shard journal with a handful of mixed records; returns
    (dir, baseline records)."""
    d = str(tmp_path / "jr")
    jr = J.Journal(d, num_shards=1)
    jr.admit(0, "docA")
    jr.frame(0, "docA", b"\x07payload")
    jr.local(0, "docA", "editor", 3, 1, "xy", 0)
    jr.tick(1)
    jr.admit(0, "docB")
    jr.poll(0, "docB")
    jr.tick(2)
    jr.close()
    records, errors = J.scan(d)
    assert not errors
    return d, records


def test_journal_roundtrip_order_and_bodies(tmp_path):
    d, records = _small_journal(tmp_path)
    kinds = [r.kind for r in records]
    assert kinds == [J.REC_ADMIT, J.REC_FRAME, J.REC_LOCAL, J.REC_TICK,
                     J.REC_ADMIT, J.REC_POLL, J.REC_TICK]
    assert [r.seq for r in records] == list(range(7))
    assert records[0].body.decode() == "docA"
    doc_id, data = J.decode_frame_body(records[1].body)
    assert (doc_id, data) == ("docA", b"\x07payload")
    assert J.decode_local_body(records[2].body) == \
        ("docA", "editor", 3, 1, "xy", 0)


def test_journal_reopen_continues_seq_and_segments(tmp_path):
    """A post-recovery journal must never reuse sequence numbers or
    clobber existing segments."""
    d, records = _small_journal(tmp_path)
    top = records[-1].seq
    jr = J.Journal(d, num_shards=1)
    jr.admit(0, "docC")
    jr.close()
    records2, errors = J.scan(d)
    assert not errors
    assert records2[-1].seq == top + 1
    assert records2[-1].body.decode() == "docC"
    assert len({r.segment for r in records2}) == 2, \
        "reopen must open a NEW segment, not append to the old one"


def test_journal_torn_tail_truncation_sweep(tmp_path):
    """A power cut can land mid-write at ANY byte: for every truncation
    point inside the final record, the scanner recovers the valid
    prefix exactly and refuses the tail with a typed error naming the
    segment and offset."""
    d, records = _small_journal(tmp_path)
    last = records[-1]
    seg = last.segment
    size = os.path.getsize(seg)
    assert size > last.offset
    pristine = open(seg, "rb").read()
    for cut in range(last.offset + 1, size):
        with open(seg, "wb") as fh:
            fh.write(pristine[:cut])
        got, errors = J.scan(d)
        assert [r.seq for r in got] == [r.seq for r in records[:-1]], \
            f"valid prefix lost at cut={cut}"
        assert len(errors) == 1
        err = errors[0]
        assert isinstance(err, J.JournalError)
        assert err.segment == seg
        assert err.offset == last.offset
    # Truncation exactly at the record boundary is a clean EOF.
    with open(seg, "wb") as fh:
        fh.write(pristine[:last.offset])
    got, errors = J.scan(d)
    assert not errors and len(got) == len(records) - 1
    with open(seg, "wb") as fh:
        fh.write(pristine)


def test_journal_bitflip_sweep(tmp_path):
    """Flip one bit at every byte of the final record: the CRC chain
    (or the framing validators) must refuse the record — never load
    corrupt bytes, never lose the valid prefix, never crash."""
    d, records = _small_journal(tmp_path)
    last = records[-1]
    seg = last.segment
    pristine = open(seg, "rb").read()
    for at in range(last.offset, len(pristine)):
        mutated = bytearray(pristine)
        mutated[at] ^= 0x01
        with open(seg, "wb") as fh:
            fh.write(bytes(mutated))
        got, errors = J.scan(d)
        assert [r.seq for r in got] == [r.seq for r in records[:-1]], \
            f"prefix corrupted by flip at {at}"
        assert errors, f"flip at byte {at} went undetected"
        assert all(isinstance(e, J.JournalError) for e in errors)
        assert errors[0].segment == seg
    with open(seg, "wb") as fh:
        fh.write(pristine)


def test_journal_reopen_repairs_torn_tail(tmp_path):
    """The double-crash hole: reopening a journal whose tail is torn
    must truncate the tear to the valid prefix (quarantining the torn
    bytes), or the NEXT scan would refuse the stale torn segment and
    drop every post-recovery segment of that shard behind it."""
    d, records = _small_journal(tmp_path)
    last = records[-1]
    size = os.path.getsize(last.segment)
    with open(last.segment, "r+b") as fh:
        fh.truncate(last.offset + max(1, (size - last.offset) // 2))
    jr = J.Journal(d, num_shards=1)  # post-crash reopen: repairs
    assert [e.offset for e in jr.repair_errors] == [last.offset]
    jr.admit(0, "docC")              # the post-recovery durable record
    jr.close()
    got, errors = J.scan(d)          # what crash #2's recovery sees
    assert not errors, "post-repair scan must be clean"
    assert got[-1].body.decode() == "docC", \
        "post-recovery record lost behind the stale torn segment"
    assert [r.seq for r in got[:-1]] == [r.seq for r in records[:-1]]
    assert got[-1].seq == got[-2].seq + 1
    # Forensics survive: the torn bytes moved to a .refused sidecar.
    assert os.path.exists(last.segment + ".refused")


def test_journal_repair_quarantines_dead_segments(tmp_path):
    """A refused EARLY segment ends its shard's recoverable stream;
    repair must quarantine the later (never-replayed) segments too, so
    a post-repair scan cannot resurface records recovery never saw."""
    d = str(tmp_path / "jr")
    jr = J.Journal(d, num_shards=1, rotate_bytes=1)  # rotate every tick
    for i, doc in enumerate(("docA", "docB", "docC")):
        jr.admit(0, doc)
        jr.tick(i + 1)
    jr.close()
    records, errors = J.scan(d)
    assert not errors
    segs = sorted({r.segment for r in records})
    assert len(segs) == 3, "shape bug: expected one segment per tick"
    pristine = open(segs[0], "rb").read()
    with open(segs[0], "wb") as fh:  # corrupt the FIRST segment
        fh.write(b"XXXX" + pristine[4:])
    jr2 = J.Journal(d, num_shards=1, rotate_bytes=1)
    assert len(jr2.repair_errors) == 3  # 1 refused + 2 dropped behind it
    jr2.admit(0, "docD")
    jr2.close()
    got, errors = J.scan(d)
    assert not errors
    assert [r.body.decode() for r in got if r.kind == J.REC_ADMIT] == \
        ["docD"], "dead segments must not resurface after repair"
    for seg in segs:
        assert os.path.exists(seg + ".refused")


def test_journal_header_corruption_refused(tmp_path):
    d, records = _small_journal(tmp_path)
    seg = records[0].segment
    pristine = open(seg, "rb").read()
    with open(seg, "wb") as fh:
        fh.write(b"XXXX" + pristine[4:])
    got, errors = J.scan(d)
    assert not got
    assert errors and "magic" in errors[0].reason


# -- end-to-end recovery -----------------------------------------------------


def _journaled_run(tmp_path, **kw):
    cfg = ServeConfig(num_shards=2, lanes_per_shard=2,
                      journal_dir=str(tmp_path / "journal"),
                      spool_dir=str(tmp_path / "spool"))
    gen = ServeLoadGen(cfg=cfg, **kw)
    report = gen.run()
    assert report["converged"], report["mismatches"]
    return cfg, gen


def test_recovery_clean_shutdown_byte_identical(tmp_path):
    """Re-executing the full input log of a COMPLETED run reproduces
    every doc byte-for-byte — content, CRDT state digest, and the
    control-plane wants a poll would serve."""
    cfg, gen = _journaled_run(tmp_path, docs=6, agents_per_doc=2,
                              ticks=6, events_per_tick=10, seed=13,
                              fault_rate=0.10)
    want = logical_stream_digest(gen.server)
    cfg2 = ServeConfig(num_shards=2, lanes_per_shard=2,
                       journal_dir=cfg.journal_dir,
                       spool_dir=cfg.spool_dir)
    server2 = DocServer(cfg2)
    stats = server2.recover()
    assert stats["refusals"] == 0
    assert stats["docs"] == 6
    assert stats["ops"] > 0 and stats["ticks"] > 0
    assert logical_stream_digest(server2) == want
    # Replay went through the normal path: the audit invariants held.
    assert stats["shard_mismatches"] == 0
    assert stats["local_gaps"] == 0
    server2.close_obs()


def test_recovery_refuses_on_nonempty_server(tmp_path):
    cfg, gen = _journaled_run(tmp_path, docs=2, agents_per_doc=2,
                              ticks=3, events_per_tick=6, seed=3)
    with pytest.raises(AssertionError):
        gen.server.recover()


def test_recovery_without_journal_refused(tmp_path):
    cfg = ServeConfig(num_shards=1, lanes_per_shard=2,
                      spool_dir=str(tmp_path / "spool"))
    server = DocServer(cfg)
    with pytest.raises(AssertionError):
        server.recover()
    server.close_obs()


def test_recovery_journal_bytes_counted(tmp_path):
    cfg, gen = _journaled_run(tmp_path, docs=4, agents_per_doc=2,
                              ticks=5, events_per_tick=8, seed=5,
                              fault_rate=0.10)
    c = gen.server.counters
    assert c.get("journal_bytes") > 0
    assert c.get("journal_records") > 0
    assert c.get("journal_ops") > 0


def test_recovery_crash_recover_crash_recover(tmp_path):
    """Double-crash end-to-end: ops accepted AFTER a recovery from a
    torn journal must survive the NEXT crash.  Before reopen-time
    repair, the stale torn segment made the second scan drop every
    post-recovery segment of its shard — fsynced records vanished."""
    from text_crdt_rust_tpu.serve.chaos import tear_last_record

    cfg, gen = _journaled_run(tmp_path, docs=4, agents_per_doc=2,
                              ticks=5, events_per_tick=8, seed=11,
                              fault_rate=0.10)
    # Crash #1: a power cut mid-append tears shard 0's final record.
    assert tear_last_record(cfg.journal_dir, shard=0) is not None
    cfg2 = ServeConfig(num_shards=2, lanes_per_shard=2,
                       journal_dir=cfg.journal_dir,
                       spool_dir=cfg.spool_dir)
    server2 = DocServer(cfg2)
    stats2 = server2.recover()
    assert stats2["refusals"] >= 1, "the torn tail must refuse loudly"
    # Post-recovery traffic: journaled, flushed, fsynced at each tick.
    doc_id = sorted(server2.router.docs)[0]
    for i in range(3):
        server2.submit_local(doc_id, "survivor", 0, 0, f"post{i} ")
        server2.tick()
    server2.flush_pipeline()
    want = logical_stream_digest(server2)
    # Crash #2: abandon server2 — no close, no drain, no final fsync.
    server3 = DocServer(ServeConfig(num_shards=2, lanes_per_shard=2,
                                    journal_dir=cfg.journal_dir,
                                    spool_dir=cfg.spool_dir))
    stats3 = server3.recover()
    assert stats3["refusals"] == 0, \
        "crash #1's reopen repaired the journal; #2 must scan clean"
    assert stats3["locals_replayed"] >= stats2["locals_replayed"] + 3, \
        "post-recovery local edits lost by the second recovery"
    server3.flush_pipeline()
    assert logical_stream_digest(server3) == want
    server3.close_obs()


# -- the batcher crash-path bugfix -------------------------------------------


class _InjectedFault(Exception):
    """A typed mid-tick error (stands in for CodecError & friends)."""


def test_batcher_flushes_pipeline_on_midtick_error(tmp_path):
    """ISSUE 16 bugfix regression: a typed error raised mid-tick at
    pipeline depth 2 must drain/sync the in-flight entries on the way
    out — staged syncs and flow spans must not leak (the conservation
    audit stays green), and the server must survive to finish the run."""
    cfg = ServeConfig(num_shards=1, lanes_per_shard=4,
                      pipeline_ticks=2, flow_sample_mod=1,
                      spool_dir=str(tmp_path / "spool"))
    gen = ServeLoadGen(cfg=cfg, docs=4, agents_per_doc=2, ticks=8,
                       events_per_tick=10, seed=7, fault_rate=0.0)
    gen.start()
    gen.run_ticks(0, 4)
    batcher = gen.server.batcher
    assert batcher.effective_pipeline_ticks() >= 2, \
        "shape too small to put the pipeline in flight"
    real_drain = batcher._drain_doc

    def dying_drain(*a, **kw):
        raise _InjectedFault("injected mid-tick fault at depth 2")

    batcher._drain_doc = dying_drain
    with pytest.raises(_InjectedFault):
        gen.run_tick(4)
    # THE fix: the unwind drained the pipeline — nothing in flight.
    assert batcher._inflight == [], \
        "mid-tick error leaked in-flight pipeline entries"
    batcher._drain_doc = real_drain
    # The server survives: finish the run and hold the flow audit green.
    gen.run_ticks(5, 8)
    report = gen.finalize()
    assert report["converged"], report["mismatches"]
    assert report["flow"]["audit_ok"], report["flow"]["findings"]

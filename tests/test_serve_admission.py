"""serve/admission.py: typed backpressure — queue bounds, token
buckets, frame rejection. Every refusal is an ``AdmissionError`` with a
machine-readable reason and a counter; server state is untouched."""
import pytest

from text_crdt_rust_tpu.config import ServeConfig
from text_crdt_rust_tpu.serve.admission import (
    REASON_DOC_UNKNOWN,
    REASON_FRAME_REJECTED,
    REASON_QUEUE_FULL,
    REASON_RATE_LIMITED,
    AdmissionControl,
    AdmissionError,
    TokenBucket,
)
from text_crdt_rust_tpu.serve.server import DocServer


def small_cfg(**kw) -> ServeConfig:
    base = dict(num_shards=1, lanes_per_shard=2, lane_capacity=128,
                order_capacity=256, step_buckets=(8, 32), max_txn_len=32)
    base.update(kw)
    return ServeConfig(**base)


def test_token_bucket_refills_on_logical_ticks():
    b = TokenBucket(capacity=10, refill=2)
    assert b.take(10, tick=0)          # full at birth
    assert not b.take(1, tick=0)       # dry
    assert not b.take(5, tick=1)       # one tick = 2 tokens
    assert b.take(2, tick=1)
    assert b.take(10, tick=100)        # refill caps at capacity


def test_admission_reasons_and_counters():
    ac = AdmissionControl(max_queue_per_doc=2, max_queue_global=3,
                          max_txn_len=8)
    ac.admit("d", "a", 4, doc_pending=0, tick=1)
    with pytest.raises(AdmissionError) as e:
        ac.admit("d", "a", 9, doc_pending=0, tick=1)
    assert e.value.reason == REASON_FRAME_REJECTED
    with pytest.raises(AdmissionError) as e:
        ac.admit("d", "a", 1, doc_pending=2, tick=1)
    assert e.value.reason == REASON_QUEUE_FULL
    ac.enqueued(); ac.enqueued(); ac.enqueued()
    with pytest.raises(AdmissionError) as e:
        ac.admit("d2", "a", 1, doc_pending=0, tick=1)
    assert e.value.reason == REASON_QUEUE_FULL
    ac.dequeued(3)
    ac.admit("d2", "a", 1, doc_pending=0, tick=1)
    s = ac.counters.summary()
    assert s["admitted"] == 2
    assert s["rejected_frame_rejected"] == 1
    assert s["rejected_queue_full"] == 2


def test_rate_limit_is_per_agent():
    ac = AdmissionControl(max_queue_per_doc=99, max_queue_global=99,
                          max_txn_len=99, rate_capacity=4, rate_refill=0)
    ac.admit("d", "hot", 4, doc_pending=0, tick=1)
    with pytest.raises(AdmissionError) as e:
        ac.admit("d", "hot", 1, doc_pending=0, tick=1)
    assert e.value.reason == REASON_RATE_LIMITED
    # A different agent is unaffected: one hot client cannot starve.
    ac.admit("d", "cold", 4, doc_pending=0, tick=1)


def test_server_rejects_unknown_doc_and_corrupt_frames():
    srv = DocServer(small_cfg())
    with pytest.raises(AdmissionError) as e:
        srv.submit_frame("never-admitted", b"\xc7junk")
    assert e.value.reason == REASON_DOC_UNKNOWN

    srv.admit_doc("d")
    with pytest.raises(AdmissionError) as e:
        srv.submit_frame("d", b"\x00garbage frame")
    assert e.value.reason == REASON_FRAME_REJECTED
    assert srv.counters.get("rejected_frame_rejected") == 1
    # The refusal left no queued state behind.
    assert srv.doc_state("d").pending() == 0


def test_server_rejects_oversize_local_edit():
    srv = DocServer(small_cfg())
    srv.admit_doc("d")
    with pytest.raises(AdmissionError) as e:
        srv.submit_local("d", "a", 0, ins_content="x" * 33)
    assert e.value.reason == REASON_FRAME_REJECTED

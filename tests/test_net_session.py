"""Resync session layer: gap re-request with backoff, bounded buffering,
divergence detection, and graceful device-engine degradation.

Protocol failures here must be *typed and recoverable*: ``CodecError``
rejections are counted and re-covered, an unrecoverable gap raises
``CausalGapError``, and device capacity overflow falls back to the host
oracle — never an assert on the serving path (ISSUE 1 tentpole §3).
"""
import random

import pytest

from text_crdt_rust_tpu.common import RemoteId, RemoteIns, RemoteTxn
from text_crdt_rust_tpu.models.oracle import ListCRDT
from text_crdt_rust_tpu.models.sync import (
    agent_watermarks,
    export_txns_since,
    state_digest,
)
from text_crdt_rust_tpu.net import codec
from text_crdt_rust_tpu.net.session import (
    CausalGapError,
    DeviceMirror,
    ResyncSession,
)
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.parallel.causal import CausalBuffer
from text_crdt_rust_tpu.utils.metrics import causal_buffer_stats

ROOT = RemoteId("ROOT", 0xFFFFFFFF)


def mk_txn(agent: str, seq: int, text: str, parents=None) -> RemoteTxn:
    return RemoteTxn(
        RemoteId(agent, seq), list(parents or [ROOT]),
        [RemoteIns(ROOT, ROOT, text)],
    )


def editing_peer(name: str, steps: int = 12, seed: int = 0):
    rng = random.Random(seed)
    doc = ListCRDT()
    agent = doc.get_or_create_agent_id(name)
    for _ in range(steps):
        pos = rng.randrange(len(doc) + 1)
        doc.local_insert(agent, pos, rng.choice("abcdef") * 2)
    return doc


def clean_sync(src_doc: ListCRDT, dst: ResyncSession) -> None:
    """Deliver src's full history to dst through the codec, no faults."""
    txns = export_txns_since(src_doc, 0)
    for i in range(0, len(txns), 4):
        dst.receive(codec.encode_txns(txns[i:i + 4]))


class TestCausalBufferIntrospection:
    """Satellite: pending count, watermark gaps, duplicate-drop counter."""

    def test_duplicate_and_gap_counters(self):
        buf = CausalBuffer()
        assert buf.add(mk_txn("a", 0, "xx")) != []
        assert buf.add(mk_txn("a", 0, "xx")) == []   # full duplicate
        assert buf.duplicates_dropped == 1
        # Gap: seq 4 with watermark 2 blocks.
        assert buf.add(mk_txn("a", 4, "yy")) == []
        assert buf.pending == 1
        assert buf.high_water == 1
        stats = causal_buffer_stats(buf)
        assert stats["pending"] == 1
        assert stats["duplicates_dropped"] == 1
        assert stats["watermarks"] == {"a": 2}
        assert stats["agent_gaps"]["a"]["gap"] == 2
        assert stats["agent_gaps"]["a"]["blocked"] == 1
        assert [r.agent for r in buf.missing()] == ["a"]

    def test_bounded_buffer_evicts_farthest_and_rerequests(self):
        buf = CausalBuffer(max_pending=2)
        buf.add(mk_txn("a", 0, "xx"))            # released, wm=2
        buf.add(mk_txn("a", 10, "b1"))           # gap 8
        buf.add(mk_txn("a", 4, "b2"))            # gap 2
        assert buf.pending == 2
        buf.add(mk_txn("a", 30, "b3"))           # gap 28 -> evicted itself
        assert buf.pending == 2
        assert buf.evictions == 1
        assert buf.high_water == 3
        # The nearest-to-ready txns survived; the gap is still reported
        # so the session re-requests (eviction costs a retransmit only).
        held = sorted(t.id.seq for t in buf._pending)
        assert held == [4, 10]
        assert buf.missing()[0] == RemoteId("a", 2)

    def test_evicting_sole_pending_txn_keeps_gap_visible(self):
        buf = CausalBuffer(max_pending=1)
        buf.add(mk_txn("a", 4, "b1"))            # blocked, sole pending
        buf.add(mk_txn("b", 9, "b2"))            # evicts (a,4): gap 4 > ?
        evicted_agent = ({"a", "b"}
                         - {t.id.agent for t in buf._pending}).pop()
        # The evicted agent's gap must STILL be reported so the session
        # re-requests it, even with no pending txn left for that agent.
        assert any(r.agent == evicted_agent for r in buf.missing())
        # Redelivery from seq 0 closes it and retires the record.
        released = buf.add_all(
            [mk_txn(evicted_agent, s, "xy") for s in range(0, 12, 2)])
        assert released
        assert all(r.agent != evicted_agent for r in buf.missing())

    def test_batch_watermark_advance_drains_once(self):
        buf = CausalBuffer()
        # Pending txn of agent b parented on a's progress; doc applied
        # both agents' history out-of-band (sibling session).
        t = mk_txn("b", 5, "zz", parents=[RemoteId("a", 1)])
        assert buf.add(t) == []
        released = buf.advance_watermarks({"a": 2, "b": 5})
        assert released == [t]
        assert buf.watermarks()["b"] == 7


class TestBackoffAndGapError:
    def _gapped_session(self, **kw):
        doc = ListCRDT()
        s = ResyncSession(doc, **kw)
        # Deliver a txn with a missing predecessor: seq 2 while wm is 0.
        s.receive(codec.encode_txns([mk_txn("ghost", 2, "zz")]))
        assert s.buffer.pending == 1
        return s

    def test_rerequest_backoff_is_capped_exponential(self):
        s = self._gapped_session(backoff_base=1, backoff_cap=8,
                                 retry_limit=32)
        request_ticks = []
        for tick in range(1, 40):
            for frame in s.poll():
                kind, value, _ = codec.decode_frame(frame)
                if kind == codec.KIND_REQUEST:
                    request_ticks.append(tick)
                    assert value == {"ghost": 0}
        gaps = [b - a for a, b in zip(request_ticks, request_ticks[1:])]
        # Delays double 1,2,4,8 then stay capped at 8.
        assert gaps[:4] == [1, 2, 4, 8]
        assert all(g == 8 for g in gaps[4:])
        assert s.counters.get("range_retries") == len(request_ticks)

    def test_gap_outliving_retries_raises_typed_error(self):
        s = self._gapped_session(retry_limit=3, backoff_cap=1)
        with pytest.raises(CausalGapError) as ei:
            for _ in range(20):
                s.poll()
        assert ei.value.missing == {"ghost": 0}
        assert ei.value.attempts == 3

    def test_gap_closed_by_redelivery_clears_schedule(self):
        s = self._gapped_session(backoff_cap=1)
        s.poll()
        s.receive(codec.encode_txns([mk_txn("ghost", 0, "aa")]))
        assert s.buffer.pending == 0
        # Both runs are ROOT/ROOT siblings from the same agent: the YATA
        # scan breaks at the equal-origin-right sibling, so the later-seq
        # run ("zz") lands first.
        assert s.doc.to_string() == "zzaa"
        assert s._requests == {} or s.poll() is not None
        # No further REQUEST frames once the gap is closed.
        frames = [codec.decode_frame(f)[0] for f in s.poll()]
        assert codec.KIND_REQUEST not in frames

    def test_progressing_backfill_resets_attempt_budget(self):
        """A long lossy backfill keeps a gap open for many polls, but the
        watermark advances between asks — that must NOT accumulate toward
        CausalGapError (only a gap that never moves is unrecoverable)."""
        s = ResyncSession(ListCRDT(), retry_limit=3, backoff_cap=1)
        # A far-future txn keeps the gap visible for the whole backfill.
        s.receive(codec.encode_txns(
            [mk_txn("ghost", 1000, "zz",
                    parents=[RemoteId("ghost", 999)])]))
        for step in range(12):
            # Drip txn seq 2*step (len 2) per poll: the gap's from_seq
            # advances every ask, so the attempt budget keeps resetting.
            s.receive(codec.encode_txns(
                [mk_txn("ghost", 2 * step, "ab",
                        parents=[ROOT] if step == 0
                        else [RemoteId("ghost", 2 * step - 1)])]))
            s.poll()   # 12 asks total with retry_limit=3: never raises
        assert s.counters.get("range_retries") == 12
        assert s.buffer.watermarks()["ghost"] == 24

    def test_unknown_reference_rejected_typed_not_crash(self):
        """A well-formed (valid-CRC) txn whose delete targets an agent we
        have never heard of must be rejected and counted — the causal
        buffer only checks parents, and the oracle would hard-assert."""
        from text_crdt_rust_tpu.common import RemoteDel
        s = ResyncSession(ListCRDT())
        evil = RemoteTxn(RemoteId("mallory", 0), [ROOT],
                         [RemoteDel(RemoteId("nobody", 50), 1)])
        assert s.receive(codec.encode_txns([evil])) == []
        assert s.counters.get("txns_rejected") == 1
        assert s.protocol_error
        assert s.doc.n == 0
        # The session keeps working for honest peers afterwards.
        s.receive(codec.encode_txns([mk_txn("honest", 0, "ok")]))
        assert s.doc.to_string() == "ok"

    def test_self_referencing_txn_rejected_not_crash(self):
        """A txn deleting its OWN op's seq (or origin-chaining forward)
        names no document item — must reject typed, not assert."""
        from text_crdt_rust_tpu.common import RemoteDel
        s = ResyncSession(ListCRDT())
        # Delete of the txn's own (not-an-insert) seq 0.
        evil1 = RemoteTxn(RemoteId("e1", 0), [ROOT],
                          [RemoteDel(RemoteId("e1", 0), 1)])
        # Insert whose origin points FORWARD into the same txn.
        evil2 = RemoteTxn(RemoteId("e2", 0), [ROOT],
                          [RemoteIns(RemoteId("e2", 1), ROOT, "xx")])
        # Delete of own delete-op seqs (ins at 0..2, del op ids 2..3,
        # targeting seq 2 = the delete op itself, not an item).
        evil3 = RemoteTxn(RemoteId("e3", 0), [ROOT],
                          [RemoteIns(ROOT, ROOT, "ab"),
                           RemoteDel(RemoteId("e3", 2), 1)])
        for evil in (evil1, evil2, evil3):
            assert s.receive(codec.encode_txns([evil])) == []
        assert s.counters.get("txns_rejected") == 3
        # Legitimate intra-txn chains still apply: insert then delete of
        # the chars the same txn inserted.
        ok = RemoteTxn(RemoteId("good", 0), [ROOT],
                       [RemoteIns(ROOT, ROOT, "abc"),
                        RemoteDel(RemoteId("good", 1), 1)])
        s.receive(codec.encode_txns([ok]))
        assert s.doc.to_string() == "ac"

    def test_rejected_txn_rolls_back_watermark_for_honest_redelivery(self):
        """Rejecting a released txn must NOT burn its (agent, seq): the
        buffer watermark rolls back so an honest redelivery applies and
        the gap stays visible to the re-request cycle meanwhile."""
        from text_crdt_rust_tpu.common import RemoteDel
        s = ResyncSession(ListCRDT())
        evil = RemoteTxn(RemoteId("m", 0), [ROOT],
                         [RemoteDel(RemoteId("nobody", 5), 1)])
        s.receive(codec.encode_txns([evil]))
        assert s.counters.get("txns_rejected") == 1
        assert s.buffer.watermarks().get("m", 0) == 0   # rolled back
        # An honest peer's digest advertising m@2 now yields a want.
        s.receive(codec.encode_digest({"m": 2}, 0))
        assert s._wanted() == {"m": 0}
        # Honest redelivery of the REAL m@0 applies (not deduped).
        s.receive(codec.encode_txns([mk_txn("m", 0, "ok")]))
        assert s.doc.to_string() == "ok"
        assert agent_watermarks(s.doc)["m"] == 2

    def test_dependent_of_rejected_txn_also_rejected_not_crash(self):
        """A txn parented on a rejected txn must be rejected too (its
        parent maps to no order), not crash the oracle."""
        from text_crdt_rust_tpu.common import RemoteDel
        s = ResyncSession(ListCRDT())
        evil = RemoteTxn(RemoteId("m", 0), [ROOT],
                         [RemoteDel(RemoteId("nobody", 5), 2)])
        child = mk_txn("c", 0, "hi", parents=[RemoteId("m", 1)])
        assert s.receive(codec.encode_txns([evil, child])) == []
        assert s.counters.get("txns_rejected") == 2
        assert s.doc.n == 0
        assert s.buffer.watermarks().get("c", 0) == 0

    def test_successor_of_rejected_txn_rejected_by_seq_gate(self):
        """After a same-agent rejection rolls the watermark back, a
        successor in the SAME released batch that references nothing of
        the rejected txn must still be rejected (seq out of order against
        the doc), not crash the oracle's in-order assert."""
        from text_crdt_rust_tpu.common import RemoteDel
        s = ResyncSession(ListCRDT())
        bad = RemoteTxn(RemoteId("x", 0), [ROOT],
                        [RemoteDel(RemoteId("nobody", 5), 1)])
        succ = RemoteTxn(RemoteId("x", 1), [ROOT],
                         [RemoteIns(ROOT, ROOT, "hi")])
        assert s.receive(codec.encode_txns([bad, succ])) == []
        assert s.counters.get("txns_rejected") == 2
        assert s.doc.n == 0
        # Honest full redelivery from seq 0 recovers both slots.
        s.receive(codec.encode_txns([mk_txn("x", 0, "a"),
                                     mk_txn("x", 1, "b",
                                            parents=[RemoteId("x", 0)])]))
        assert agent_watermarks(s.doc)["x"] == 2

    def test_origin_naming_delete_op_seq_rejected(self):
        """A delete op's consumed seq maps to an order but names no body
        item — an origin pointing at it must be rejected, not crash
        raw_index_of_order."""
        s = ResyncSession(ListCRDT())
        # Build known history: y inserts "ab" (seqs 0-1), deletes 1 char
        # (delete op consumes seq 2) -> watermark 3.
        y = s.doc.get_or_create_agent_id("y")
        s.doc.local_insert(y, 0, "ab")
        s.doc.local_delete(y, 0, 1)
        evil = RemoteTxn(RemoteId("m", 0), [ROOT],
                         [RemoteIns(RemoteId("y", 2), ROOT, "zz")])
        assert s.receive(codec.encode_txns([evil])) == []
        assert s.counters.get("txns_rejected") == 1
        # Origins naming REAL items (seq 1, even tombstoned seq 0) apply.
        ok = RemoteTxn(RemoteId("m", 0), [ROOT],
                       [RemoteIns(RemoteId("y", 0), ROOT, "zz")])
        s.receive(codec.encode_txns([ok]))
        assert "zz" in s.doc.to_string()

    def test_parentless_txn_rejected_at_codec(self):
        """A parentless txn would plant a second root in the time DAG;
        the codec refuses to decode (and encode) it."""
        from text_crdt_rust_tpu.net.codec import CodecError
        body = bytearray([codec.KIND_TXNS])
        codec._write_names(body, ["m"])
        codec._write_varint(body, 1)
        codec._write_varint(body, 0)   # author m
        codec._write_varint(body, 0)   # seq 0
        codec._write_varint(body, 0)   # NO parents
        codec._write_varint(body, 1)   # one op
        body.append(0)                 # RemoteIns
        codec._write_varint(body, 0); codec._write_varint(body, 0)
        codec._write_varint(body, 0); codec._write_varint(body, 0)
        codec._write_str(body, "hi")
        with pytest.raises(CodecError, match="parents"):
            codec.decode_frame(codec._frame(bytes(body)))

    def test_corrupt_frame_counted_not_raised(self):
        s = self._gapped_session()
        assert s.receive(b"\x00garbage") == []
        assert s.receive(b"") == []
        assert s.counters.get("frames_rejected") == 2


class TestDigestsAndDivergence:
    def test_digest_reveals_fully_dropped_agent(self):
        """Every TXNS frame from a peer lost: the causal buffer sees no
        gap (nothing pending), only the digest exchange reveals it."""
        peer = editing_peer("alice", steps=6)
        s = ResyncSession(ListCRDT(), backoff_cap=1)
        assert s.buffer.missing() == []
        s.receive(codec.encode_digest(
            agent_watermarks(peer), state_digest(peer)))
        frames = s.poll()
        reqs = [v for f in frames
                for k, v, _ in [codec.decode_frame(f)]
                if k == codec.KIND_REQUEST]
        assert reqs and reqs[0] == {"alice": 0}

    def test_request_served_and_convergence(self):
        peer = editing_peer("alice", steps=6)
        serving = ResyncSession(peer)
        s = ResyncSession(ListCRDT())
        responses = serving.receive(codec.encode_request({"alice": 0}))
        assert responses
        for r in responses:
            s.receive(r)
        assert s.doc.to_string() == peer.to_string()
        assert serving.counters.get("requests_served") == 1

    def test_divergence_detected_on_equal_watermarks(self):
        peer = editing_peer("alice", steps=6)
        s = ResyncSession(ListCRDT())
        clean_sync(peer, s)
        assert state_digest(s.doc) == state_digest(peer)
        # Corrupt the replica out-of-band: flip a tombstone. Same op set
        # (watermarks equal), different state -> divergence, not silence.
        s.doc.deleted[0] = not s.doc.deleted[0]
        s.receive(codec.encode_digest(
            agent_watermarks(peer), state_digest(peer)))
        assert s.divergence_detected
        assert s.counters.get("divergence_detected") == 1

    def test_no_false_divergence_while_behind(self):
        peer = editing_peer("alice", steps=6)
        s = ResyncSession(ListCRDT())
        s.receive(codec.encode_digest(
            agent_watermarks(peer), state_digest(peer)))
        assert not s.divergence_detected


class TestDeviceMirror:
    def test_mirror_tracks_oracle_bit_identically(self):
        peer = editing_peer("alice", steps=10)
        mirror = DeviceMirror(capacity=256, agents=("alice",))
        s = ResyncSession(ListCRDT(), mirror=mirror)
        clean_sync(peer, s)
        assert not mirror.degraded
        assert SA.doc_spans(mirror.doc) == s.doc.doc_spans()
        assert SA.to_string(mirror.doc) == s.doc.to_string()
        assert s.device_doc is mirror.doc

    def test_capacity_overflow_degrades_to_oracle(self):
        peer = editing_peer("alice", steps=10)
        mirror = DeviceMirror(capacity=8, agents=("alice",))
        s = ResyncSession(ListCRDT(), mirror=mirror)
        clean_sync(peer, s)                    # no exception anywhere
        assert mirror.degraded
        assert "overflow" in mirror.degrade_reason
        assert s.counters.get("device_degraded") == 1
        # Oracle stays the source of truth and keeps serving.
        assert s.doc.to_string() == peer.to_string()
        assert s.device_doc is s.doc

    def test_unregistered_agent_degrades_not_asserts(self):
        peer = editing_peer("mallory", steps=4)
        mirror = DeviceMirror(capacity=256, agents=("alice",))
        s = ResyncSession(ListCRDT(), mirror=mirror)
        clean_sync(peer, s)
        assert mirror.degraded
        assert "mallory" in mirror.degrade_reason
        assert s.doc.to_string() == peer.to_string()

"""North-star benchmark: automerge-paper replay tiled across a doc batch.

Replays a prefix of the automerge-paper editing trace (the
`benches/yjs.rs:32-49` workload) across ``--batch`` identical documents on
the device engine, all docs advanced per step by one vmapped+scanned apply
kernel. Reports aggregate CRDT ops/sec/chip.

Baseline: 0.29 M ops/s single-core on the native C++ engine replaying the
full trace (BASELINE.md, measured); ``vs_baseline`` is the ratio against
that row. Prints exactly ONE JSON line on stdout.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    flatten_patches,
    load_testing_data,
    trace_path,
)

CPU_BASELINE_OPS_PER_SEC = 290_000.0  # BASELINE.md automerge-paper row


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def expected_content(patches) -> str:
    s = ""
    for p in patches:
        s = s[:p.pos] + p.ins_content + s[p.pos + p.del_len:]
    return s


def bench_blocked(args, ops, patches, n_ops, capacity) -> None:
    """One-kernel blocked replay (``ops.blocked``): docs ride the lane
    dimension (batch is in units of 128 lanes). Timed over several runs —
    device round-trip latency on the tunneled chip (~70ms) would otherwise
    swamp the kernel."""
    from text_crdt_rust_tpu.ops import blocked as BL

    batch = max(128, (args.batch // 128) * 128)
    # Headroom: rebalance degrades as fill -> K-lmax; 2x keeps fill <= K/2.
    cap = capacity * 2
    block_k = min(args.block_k, cap // 2)  # small prefixes: >= 2 blocks
    log(f"blocked engine: batch {batch} (128-lane units), capacity {cap}, "
        f"block_k {block_k}")
    run = BL.make_replayer(
        ops, capacity=cap, batch=batch,
        block_k=block_k, chunk=args.chunk)

    log("compiling...")
    t0 = time.perf_counter()
    res = run()
    res.check()  # forces completion
    log(f"first run (incl. compile): {time.perf_counter() - t0:.2f}s")

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        res = run()
    res.check()
    wall = (time.perf_counter() - t0) / reps

    want = expected_content(patches)
    doc = BL.blocked_to_flat(ops, res)
    got = SA.to_string(doc)
    assert got == want, "blocked replay diverged from string oracle"

    total_ops = n_ops * batch
    ops_per_sec = total_ops / wall
    log(f"wall {wall:.3f}s/run (avg of {reps}), {total_ops} ops -> "
        f"{ops_per_sec:,.0f} ops/s")
    print(json.dumps({
        "metric": "crdt_ops_per_sec_chip",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / CPU_BASELINE_OPS_PER_SEC, 3),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default="automerge-paper")
    ap.add_argument("--patches", type=int, default=30000,
                    help="trace prefix length (full trace: 0)")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--lmax", type=int, default=16)
    ap.add_argument("--engine", choices=("flat", "blocked"),
                    default="blocked")
    ap.add_argument("--block-k", type=int, default=256)
    ap.add_argument("--chunk", type=int, default=1024)
    args = ap.parse_args()

    dev = jax.devices()[0]
    log(f"device: {dev.platform} {dev.device_kind}")

    data = load_testing_data(trace_path(args.trace))
    patches = flatten_patches(data)
    if args.patches:
        patches = patches[:args.patches]
    n_ops = len(patches)
    ins_total = sum(len(p.ins_content) for p in patches)
    capacity = 1 << int(np.ceil(np.log2(max(ins_total, 64))))
    dmax = args.lmax if args.engine == "blocked" else None
    ops, _ = B.compile_local_patches(patches, lmax=args.lmax, dmax=dmax)
    steps = ops.num_steps
    log(f"{args.trace}[:{n_ops}] -> {steps} device steps, "
        f"capacity {capacity}, batch {args.batch}")

    if args.engine == "blocked":
        return bench_blocked(args, ops, patches, n_ops, capacity)

    # Identical docs share one op stream: vmap with in_axes=None keeps the
    # uploaded stream at [S, ...] (no host-side tiling, ~MBs not GBs). The
    # stream is pure local edits, so the remote paths compile out.
    vstep = jax.vmap(partial(F.step, local_only=True), in_axes=(0, None))

    @jax.jit
    def replay(docs, ops):
        def body(d, op):
            return vstep(d, op), None

        out, _ = jax.lax.scan(body, docs, ops)
        return out

    base = B.prefill_logs(SA.make_flat_doc(capacity), ops)
    F._check_capacity(base, ops)
    docs = SA.stack_docs(base, args.batch)
    ops = jax.device_put(ops)
    docs = jax.device_put(docs)

    log("compiling...")
    t0 = time.perf_counter()
    out = replay(docs, ops)
    jax.block_until_ready(out)
    t_first = time.perf_counter() - t0
    log(f"first run (incl. compile): {t_first:.2f}s")

    t0 = time.perf_counter()
    out = replay(docs, ops)
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0

    # Correctness: every doc must equal the plain-string replay
    # (`benches/yjs.rs:46` asserts final length each iteration).
    want = expected_content(patches)
    got = SA.to_string(jax.tree.map(lambda x: x[0], out))
    assert got == want, "device replay diverged from string oracle"
    assert int(np.asarray(out.n).min()) == int(np.asarray(out.n).max())

    total_ops = n_ops * args.batch
    ops_per_sec = total_ops / wall
    log(f"wall {wall:.3f}s, {total_ops} ops -> {ops_per_sec:,.0f} ops/s")

    print(json.dumps({
        "metric": "crdt_ops_per_sec_chip",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / CPU_BASELINE_OPS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()

"""North-star benchmark: automerge-paper replay tiled across a doc batch.

Replays the automerge-paper editing trace — by default the FULL 259,778
patches, the `benches/yjs.rs:32-49` workload with its final-content
assertion (`yjs.rs:46`) — across ``--batch`` identical documents on a
device engine. Reports aggregate CRDT ops/sec/chip.

``vs_baseline`` is an EQUAL-WORKLOAD ratio: the native C++ engine
(``models.native``, the CPU reference stand-in) replays the *same* patch
list single-core at bench time, so the denominator always matches the
numerator's workload (full trace or ``--patches`` prefix).

Prints exactly ONE JSON line on stdout; everything else goes to stderr.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.testdata import (
    flatten_patches,
    load_testing_data,
    trace_path,
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def expected_content(patches) -> str:
    s = ""
    for p in patches:
        s = s[:p.pos] + p.ins_content + s[p.pos + p.del_len:]
    return s


def measure_cpu_baseline(patches, reps: int = 3) -> float:
    """Single-core ops/s of the native C++ engine on the SAME workload
    (fills the BASELINE.md row at bench time; best of ``reps``)."""
    from text_crdt_rust_tpu.models.native import NativeListCRDT

    pos = [p.pos for p in patches]
    dels = [p.del_len for p in patches]
    ilens = [len(p.ins_content) for p in patches]
    cps = np.frombuffer(
        "".join(p.ins_content for p in patches).encode("utf-32-le"),
        dtype=np.uint32)
    best = float("inf")
    for _ in range(reps):
        doc = NativeListCRDT()
        agent = doc.get_or_create_agent_id("bench")
        t0 = time.perf_counter()
        doc.replay_trace(agent, pos, dels, ilens, cps)
        best = min(best, time.perf_counter() - t0)
    want = expected_content(patches)
    got = doc.to_string()
    assert got == want, "native baseline replay diverged from string oracle"
    return len(patches) / best


def emit(n_ops, batch, wall, steps, hbm_bytes, baseline_ops, extra=None):
    total_ops = n_ops * batch
    ops_per_sec = total_ops / wall
    log(f"wall {wall:.3f}s/run, {total_ops} ops -> {ops_per_sec:,.0f} ops/s "
        f"(baseline {baseline_ops:,.0f} ops/s single-core, same workload)")
    row = {
        "metric": "crdt_ops_per_sec_chip",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / baseline_ops, 3),
        "p50_step_latency_us": round(wall / steps * 1e6, 3),
        "hbm_bytes": int(hbm_bytes),
        "ops": int(n_ops),
        "batch": int(batch),
    }
    if extra:
        row.update(extra)
    print(json.dumps(row))


def bench_blocked(args, ops, patches, n_ops, capacity, baseline_ops) -> None:
    """One-kernel blocked replay: docs ride the lane dimension (batch in
    units of 128 lanes). ``--engine blocked`` holds the document in VMEM
    (caps near ~50k rows); ``--engine hbm`` keeps state in HBM with a
    DMA'd VMEM window, so the FULL trace fits. Timed over several runs —
    device round-trip latency on the tunneled chip (~70ms) would otherwise
    swamp the kernel."""
    from text_crdt_rust_tpu.ops import blocked as BL
    from text_crdt_rust_tpu.ops import blocked_hbm as BH

    batch = max(128, (args.batch // 128) * 128)
    # Headroom: rebalance degrades as fill -> K-lmax; 2x keeps fill <= K/2.
    cap = capacity * 2
    block_k = min(args.block_k, cap // 2)  # small prefixes: >= 2 blocks
    log(f"{args.engine} engine: batch {batch} (128-lane units), "
        f"capacity {cap}, block_k {block_k}")
    if args.engine == "hbm":
        run = BH.make_replayer_hbm(
            ops, capacity=cap, batch=batch,
            block_k=block_k, chunk=args.chunk, interpret=args.interpret)
        # state + tmp (HBM-resident) + origin outputs
        hbm_bytes = (2 * cap + block_k) * batch * 4 \
            + 2 * ops.num_steps * batch * 4
    else:
        run = BL.make_replayer(
            ops, capacity=cap, batch=batch,
            block_k=block_k, chunk=args.chunk, interpret=args.interpret)
        hbm_bytes = cap * batch * 4 + 2 * ops.num_steps * batch * 4

    log("compiling...")
    t0 = time.perf_counter()
    res = run()
    res.check()  # forces completion
    log(f"first run (incl. compile): {time.perf_counter() - t0:.2f}s")

    reps = args.reps
    t0 = time.perf_counter()
    for _ in range(reps):
        res = run()
    res.check()
    wall = (time.perf_counter() - t0) / reps

    want = expected_content(patches)
    doc = BL.blocked_to_flat(ops, res)
    got = SA.to_string(doc)
    assert got == want, f"{args.engine} replay diverged from string oracle"

    emit(n_ops, batch, wall, ops.num_steps, hbm_bytes, baseline_ops,
         extra={"engine": args.engine, "reps": reps})


def bench_flat(args, ops, patches, n_ops, capacity, baseline_ops) -> None:
    # Identical docs share one op stream: vmap with in_axes=None keeps the
    # uploaded stream at [S, ...] (no host-side tiling, ~MBs not GBs). The
    # stream is pure local edits, so the remote paths compile out.
    vstep = jax.vmap(partial(F.step, local_only=True), in_axes=(0, None))

    @jax.jit
    def replay(docs, ops):
        def body(d, op):
            return vstep(d, op), None

        out, _ = jax.lax.scan(body, docs, ops)
        return out

    base = B.prefill_logs(SA.make_flat_doc(capacity), ops)
    F._check_capacity(base, ops)
    docs = SA.stack_docs(base, args.batch)
    ops = jax.device_put(ops)
    docs = jax.device_put(docs)

    log("compiling...")
    t0 = time.perf_counter()
    out = replay(docs, ops)
    jax.block_until_ready(out)
    log(f"first run (incl. compile): {time.perf_counter() - t0:.2f}s")

    t0 = time.perf_counter()
    out = replay(docs, ops)
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0

    # Correctness: every doc must equal the plain-string replay
    # (`benches/yjs.rs:46` asserts final length each iteration).
    want = expected_content(patches)
    got = SA.to_string(jax.tree.map(lambda x: x[0], out))
    assert got == want, "device replay diverged from string oracle"
    assert int(np.asarray(out.n).min()) == int(np.asarray(out.n).max())

    hbm_bytes = sum(
        np.asarray(x).nbytes for x in jax.tree.leaves(docs))
    emit(n_ops, args.batch, wall, ops.num_steps, hbm_bytes, baseline_ops,
         extra={"engine": "flat"})


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default="automerge-paper")
    ap.add_argument("--patches", type=int, default=0,
                    help="trace prefix length (0 = FULL trace, the "
                         "`benches/yjs.rs` workload)")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--lmax", type=int, default=16)
    ap.add_argument("--engine", choices=("flat", "blocked", "hbm"),
                    default="hbm")
    ap.add_argument("--block-k", type=int, default=512)
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (logic check, not a perf "
                         "number; implies --interpret for blocked/hbm)")
    ap.add_argument("--interpret", action="store_true",
                    help="run Pallas kernels in interpreter mode")
    args = ap.parse_args()

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        args.interpret = True

    dev = jax.devices()[0]
    log(f"device: {dev.platform} {dev.device_kind}")

    data = load_testing_data(trace_path(args.trace))
    patches = flatten_patches(data)
    if args.patches:
        patches = patches[:args.patches]
    n_ops = len(patches)
    ins_total = sum(len(p.ins_content) for p in patches)
    capacity = 1 << int(np.ceil(np.log2(max(ins_total, 64))))
    dmax = args.lmax if args.engine in ("blocked", "hbm") else None
    ops, _ = B.compile_local_patches(patches, lmax=args.lmax, dmax=dmax)
    steps = ops.num_steps
    log(f"{args.trace}[:{n_ops}] -> {steps} device steps, "
        f"capacity {capacity}, batch {args.batch}")

    log("measuring single-core CPU baseline on the same workload...")
    baseline_ops = measure_cpu_baseline(patches)
    log(f"native C++ single-core: {baseline_ops:,.0f} ops/s")

    if args.engine in ("blocked", "hbm"):
        return bench_blocked(args, ops, patches, n_ops, capacity,
                             baseline_ops)
    return bench_flat(args, ops, patches, n_ops, capacity, baseline_ops)


if __name__ == "__main__":
    main()

"""Benchmark suite: the five BASELINE configs + kevin, on real TPU.

Default run = the NORTH STAR: the full automerge-paper trace
(`benches/yjs.rs:32-49`, final-content asserted) tiled across ``--batch``
identical documents on the RLE run-blocked engine (``ops.rle``), fed the
RLE-merged op stream. ``--config all`` runs the
whole BASELINE.json table and writes it to ``BENCH_ALL.json``:

1. automerge-paper single-doc replay — the CPU reference path (our
   native C++ engine), plus the TPU north-star row.
2. ``random_edits`` workload, identical docs batched in the lane dim.
3. ragged mixed corpus (rustcode + sveltecomponent) — divergent doc
   GROUPS on the rle engine's grid dimension.
4. N-peer concurrent-insert storm (tiebreak-heavy) — remote ops on the
   mixed blocked engine.
5. streaming apply, delete-heavy, per-doc DIVERGENT streams on the
   per-lane rle engine, warm-started across chunks with checkpoint
   resync.
kevin: 5M single-char prepends (`benches/yjs.rs:51-62`) on the native
   engine AND at full 5M scale on the HBM-state RLE engine (leaf
   splits amortize the prepend worst case; batch 128, origins not
   stored — see cfg_kevin's HBM math).

Every row reports ops/sec/chip, ``mean_step_latency_us`` (wall / device
steps), accounted + measured HBM bytes, slope-fit timing fields (see
``time_run``), an oracle-equality flag, and an EQUAL-WORKLOAD
``vs_baseline`` (the native C++ engine replays the same logical workload
single-core at bench time).

Prints exactly ONE JSON line (the north-star row) on stdout; everything
else goes to stderr / BENCH_ALL.json.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import time
from functools import partial

import jax
import numpy as np

from text_crdt_rust_tpu.ops import batch as B
from text_crdt_rust_tpu.ops import flat as F
from text_crdt_rust_tpu.ops import span_arrays as SA
from text_crdt_rust_tpu.utils.randedit import make_storm, random_patches
from text_crdt_rust_tpu.utils.testdata import (
    TestPatch,
    flatten_patches,
    load_testing_data,
    trace_path,
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ----------------------------------------------------- cold-start probe --


_PROBE_CODE = (
    "import jax, numpy as np, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "x = jnp.ones((128, 128), jnp.bfloat16)\n"
    "print(d[0].platform, float(np.asarray(x @ x)[0, 0]))\n"
)


def probe_device(max_tries: int = 5, timeout_base: float = 300.0):
    """Verify the device backend cold-starts and a tiny matmul completes,
    in a SUBPROCESS, with bounded retry/backoff (VERDICT r3 weak #1: one
    axon init failure zeroed the whole round's headline).

    A subprocess is the only safe probe shape here: a failed/hung init
    inside THIS process would poison its cached jax backend, and a wedged
    tunnel (a known hazard after mid-compile kills) can take ~10 min to
    recover — later tries therefore wait longer before giving up.
    """
    for t in range(max_tries):
        timeout = min(timeout_base * (t + 1), 900.0)
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                               capture_output=True, text=True,
                               timeout=timeout)
            if r.returncode == 0:
                log(f"device probe ok: {r.stdout.strip()}")
                return
            tail = (r.stderr or "").strip().splitlines()[-1:]
            log(f"device probe failed (try {t + 1}/{max_tries}): {tail}")
        except subprocess.TimeoutExpired:
            log(f"device probe timed out after {timeout:.0f}s "
                f"(try {t + 1}/{max_tries}); tunnel may be recovering")
        if t + 1 < max_tries:
            delay = 30.0 * (t + 1)
            log(f"  retrying in {delay:.0f}s")
            time.sleep(delay)
    raise RuntimeError(
        f"device probe failed after {max_tries} tries; backend is down")


def init_devices(max_tries: int = 3):
    """``jax.devices()`` with in-process retry: the subprocess probe
    proves the backend CAN start, but this process's own init can still
    lose a race with a recovering tunnel."""
    for t in range(max_tries):
        try:
            return jax.devices()
        except RuntimeError as e:
            log(f"jax.devices() failed (try {t + 1}/{max_tries}): {e}")
            if t + 1 >= max_tries:
                raise
            time.sleep(30.0 * (t + 1))


# -- bench row exporter schema (ISSUE 8 satellite) ----------------------------
# Every non-error row BENCH_ALL.json carries must validate against this
# floor: ``--merge-rows`` and the RowSink refuse shape-drifted rows at
# write time, and ``tests/test_bench_row_schema.py`` validates the
# committed table — so a silent field rename or type drift can't split
# the table into incomparable halves (the scattered-dicts failure mode
# the obs/ registry exists to end).  Extra per-config fields are fine;
# the schema pins the shared floor, not the ceiling.
from text_crdt_rust_tpu.obs.ledger import LEDGER_SCHEMA_VERSION

ROW_SCHEMA_VERSION = 1

# Oldest cost-ledger schema whose row counters still MEAN the same
# thing: ledger v2 only ADDED the "recovery" metric family (ISSUE 16),
# so rows stamped v1 remain valid.  A breaking ledger change (a family
# renamed/removed, a counter redefined) must raise this floor to the
# new version so stale rows are refused again; the when_up watcher
# re-stamps rows at the current version on every silicon re-record.
LEDGER_COMPAT_FLOOR = 1

ROW_SCHEMA = {
    "schema_version": (int,),
    # The cost-ledger schema the row was recorded against (ISSUE 10):
    # rows and ledger must agree on what the counters MEAN, so
    # --merge-rows refuses rows stamped by a drifted ledger schema.
    "ledger_version": (int,),
    "cfg_key": (str,),
    "variant": (str,),
    "config": (str,),
    "engine": (str,),
    "metric": (str,),
    "value": (int, float),
    "unit": (str,),
    "batch": (int,),
    "ops": (int,),
    "device_steps": (int,),
    "mean_step_latency_us": (int, float),
    "hbm_bytes_accounted": (int,),
    "hbm_bytes_measured": (int, type(None)),
    "vs_baseline": (int, float, type(None)),
    "baseline_ops_per_sec": (int, float, type(None)),
    "oracle_equal": (bool, type(None)),
}


def validate_row(row: dict) -> None:
    """Raise ``ValueError`` naming every schema violation in one bench
    row. Error placeholder rows (``"error"`` key) are exempt — they
    carry a crash record, not metrics."""
    if "error" in row:
        return
    problems = []
    for field, types in ROW_SCHEMA.items():
        if field not in row:
            problems.append(f"missing field {field!r}")
        elif not isinstance(row[field], types):
            problems.append(
                f"field {field!r} has type "
                f"{type(row[field]).__name__}, wants "
                f"{'/'.join(t.__name__ for t in types)}")
    if not problems and row["schema_version"] != ROW_SCHEMA_VERSION:
        problems.append(
            f"schema_version {row['schema_version']} != "
            f"{ROW_SCHEMA_VERSION} (re-record through this exporter)")
    if not problems and (row["ledger_version"] < LEDGER_COMPAT_FLOOR
                         or row["ledger_version"] > LEDGER_SCHEMA_VERSION):
        problems.append(
            f"ledger_version {row['ledger_version']} outside "
            f"[{LEDGER_COMPAT_FLOOR}, {LEDGER_SCHEMA_VERSION}] (row "
            f"counters were recorded against a drifted cost-ledger "
            f"schema; re-record)")
    if problems:
        raise ValueError(
            f"bench row {row.get('config')!r} violates the exporter "
            f"schema: {'; '.join(problems)}")


class RowSink:
    """Persist bench rows to ``path`` AS THEY COMPLETE (VERDICT r3 next
    #1: a crash mid-suite must not lose finished rows), and support
    ``--resume`` (skip configs whose rows are already recorded clean
    UNDER THE SAME workload-shaping flags — a smoke row must not resume
    into a full-size suite)."""

    def __init__(self, path: str, resume: bool, variant: str):
        self.path = path
        self.variant = variant
        self.rows = []
        self.kept = []  # prior rows of OTHER variants: preserved on
        #                 flush (resuming with different flags must not
        #                 erase the results it can't reuse)
        self.pending = {}  # cfg_key -> superseded same-variant rows,
        #                    dropped only when the key re-records
        self.done_keys = set()
        if resume and os.path.exists(path):
            with open(path) as f:
                prior = json.load(f)
            by_key = {}
            for row in prior:
                by_key.setdefault(row.get("cfg_key"), []).append(row)
            for key, rows in by_key.items():
                if key and all("error" not in r
                               and r.get("variant") == variant
                               for r in rows):
                    self.rows.extend(rows)
                    self.done_keys.add(key)
                else:
                    # Preserve rows this resume can't regenerate (other
                    # variants) unconditionally. Same-variant error/
                    # mixed rows are SUPERSEDED by the re-run, but only
                    # once it actually happens: they stay in the file
                    # (via ``pending``) until add() records their key,
                    # so a crash before that point loses nothing.
                    self.kept.extend(r for r in rows
                                     if r.get("variant") != variant)
                    same = [r for r in rows
                            if r.get("variant") == variant]
                    if key and same:
                        self.pending[key] = same
                    elif same:
                        # Keyless (legacy / hand-edited) same-variant
                        # rows have no cfg_key for add() to supersede:
                        # keep them outright, never silently erase.
                        self.kept.extend(same)
            log(f"resume: {len(self.done_keys)} configs already recorded "
                f"clean in {path}: {sorted(self.done_keys)}; "
                f"{len(self.kept)} other-variant rows preserved; "
                f"{len(self.pending)} same-variant error/mixed configs "
                f"scheduled for re-run (their old rows kept until then)")

    def add(self, key: str, out):
        for row in (out if isinstance(out, list) else [out]):
            row["cfg_key"] = key
            row["variant"] = self.variant
            validate_row(row)  # shape-drifted rows fail at write time
            self.rows.append(row)
        self.pending.pop(key, None)  # the re-run supersedes them now
        self.flush()

    def flush(self):
        tmp = self.path + ".tmp"
        stale = [r for rows in self.pending.values() for r in rows]
        with open(tmp, "w") as f:
            json.dump(self.rows + self.kept + stale, f, indent=1)
        os.replace(tmp, self.path)


def merge_config_rows(path, key, rows, variant, smoke=False):
    """Merge a single-config run's rows into the ``--config all`` table
    (``--merge-rows``): the fresh rows REPLACE every prior row of that
    ``cfg_key`` — the same supersede-by-re-record semantics the
    when_up_* recovery scripts implement by dropping the key before a
    ``--resume`` suite, without hand-editing the JSON.

    Refuses workload-shape downgrades (RowSink's variant rule, applied
    per key): a --smoke run never overwrites full-size rows, and the
    prior rows' ``config`` labels (which embed the workload scale,
    e.g. ``kevin_tpu_5000000``) must all reappear in the fresh rows —
    so re-records at equal workload supersede freely (including under
    a new engine strategy / variant string), while a shrunken
    ``--kevin-n`` run cannot silently destroy the hours-long silicon
    rows.  Error rows are superseded unconditionally."""
    prior = []
    if os.path.exists(path):
        with open(path) as f:
            prior = json.load(f)
    old = [r for r in prior if r.get("cfg_key") == key]
    old_clean = [r for r in old if "error" not in r]
    if smoke and any("smoke=True" not in (r.get("variant") or "")
                     for r in old_clean):
        raise SystemExit(
            f"--merge-rows refused: {path} holds full-size rows for "
            f"cfg_key {key!r} and this is a --smoke run (drop the rows "
            f"by hand if you really mean to supersede them)")
    # Downgrade guard only: full-size rows must reappear label-for-label;
    # prior SMOKE rows are superseded freely (a full run upgrading over a
    # smoke row is the point of the re-record).
    old_full = [r for r in old_clean
                if "smoke=True" not in (r.get("variant") or "")]
    missing = ({r.get("config") for r in old_full}
               - {r.get("config") for r in rows})
    if missing:
        raise SystemExit(
            f"--merge-rows refused: this run produced no replacement "
            f"for prior {key!r} rows {sorted(missing)} — a different "
            f"workload shape must not silently erase recorded rows "
            f"(drop them by hand to supersede deliberately)")
    for row in rows:
        row["cfg_key"] = key
        row["variant"] = variant
        # Schema gate (ISSUE 8): a shape-drifted single-config re-record
        # must not merge into the table it can no longer be compared to.
        validate_row(row)
    kept = [r for r in prior if r.get("cfg_key") != key]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(kept + rows, f, indent=1)
    os.replace(tmp, path)
    log(f"merged {len(rows)} fresh {key!r} rows into {path} "
        f"(replaced {len(old)} prior)")


def expected_content(patches) -> str:
    s = ""
    for p in patches:
        s = s[:p.pos] + p.ins_content + s[p.pos + p.del_len:]
    return s


# ---------------------------------------------------------------- native --


#: Per-run samples of the last native baseline, keyed by caller-visible
#: denominator — ``make_row`` folds the active entry into its row so the
#: committed artifact carries the spread, not just the headline (VERDICT
#: r4 weak #4: a single best-of-run sample under unknown machine load
#: made vs_baseline swing ±40%).
_BASELINE_STATS: dict = {}


def _baseline_samples(run_once, n_ops: int, reps: int):
    """MEDIAN-of-``reps`` single-core baseline with a load guard.

    Best-of rewarded lucky samples; median is robust to one noisy run
    in either direction.  A high 1-minute loadavg (other work sharing
    the cores) is recorded in the row and warned about rather than
    silently denominating the headline.
    """
    loadavg = os.getloadavg()[0] if hasattr(os, "getloadavg") else -1.0
    ncpu = os.cpu_count() or 1
    if loadavg > ncpu * 0.5:
        log(f"WARNING: loadavg {loadavg:.1f} on {ncpu} cpus while "
            f"measuring the CPU baseline; the denominator may be "
            f"depressed and vs_baseline inflated")
    samples = []
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_once()
        samples.append(time.perf_counter() - t0)
    med = sorted(samples)[len(samples) // 2]
    ops = n_ops / med
    _BASELINE_STATS.clear()
    _BASELINE_STATS.update({
        "baseline_samples_ops_per_sec": [round(n_ops / s, 1)
                                         for s in samples],
        "baseline_loadavg_1m": round(loadavg, 2),
    })
    return ops, out


def native_replay(patches, reps: int = 5):
    """(ops/s, final_string) of the native C++ engine on a local-edit
    patch list, single core, median of ``reps`` (load-guarded)."""
    from text_crdt_rust_tpu.models.native import NativeListCRDT

    pos = [p.pos for p in patches]
    dels = [p.del_len for p in patches]
    ilens = [len(p.ins_content) for p in patches]
    cps = np.frombuffer(
        "".join(p.ins_content for p in patches).encode("utf-32-le"),
        dtype=np.uint32)

    def run_once():
        doc = NativeListCRDT()
        agent = doc.get_or_create_agent_id("bench")
        doc.replay_trace(agent, pos, dels, ilens, cps)
        return doc

    ops, doc = _baseline_samples(run_once, len(patches), reps)
    return ops, doc.to_string()


def native_remote_replay(txns, reps: int = 5):
    """(char-ops/s, final_string) for a RemoteTxn stream on the native
    engine (hot path #2, `doc.rs:242-348`), single core, median of
    ``reps`` (load-guarded)."""
    from text_crdt_rust_tpu.models.native import NativeListCRDT

    n_ops = sum(sum(getattr(op, "len", len(getattr(op, "ins_content", "")))
                    for op in t.ops) for t in txns)

    def run_once():
        doc = NativeListCRDT()
        for t in txns:
            doc.apply_remote_txn(t)
        return doc

    ops, doc = _baseline_samples(run_once, n_ops, reps)
    return ops, doc.to_string()


# ------------------------------------------------------------------ rows --


def measured_device_bytes():
    """Live device allocation (bytes, reason) from the runtime (VERDICT
    r2 weak #5 / r5 missing #3: report measured memory where the backend
    exposes it, a reason note where it doesn't). One shared
    implementation: ``utils.metrics.measured_hbm_bytes``."""
    from text_crdt_rust_tpu.utils.metrics import measured_hbm_bytes

    return measured_hbm_bytes()


def make_row(config, engine, n_ops, batch, wall, steps, hbm_bytes,
             base_ops, oracle_equal, **extra):
    total = n_ops * batch
    ops_per_sec = total / wall
    measured, measured_note = measured_device_bytes()
    row = {
        "schema_version": ROW_SCHEMA_VERSION,
        "ledger_version": LEDGER_SCHEMA_VERSION,
        "config": config,
        "engine": engine,
        "metric": "crdt_ops_per_sec_chip",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / base_ops, 3) if base_ops else None,
        "baseline_ops_per_sec": round(base_ops, 1) if base_ops else None,
        # Honest telemetry: the in-kernel steps are not individually
        # timed, so this is the MEAN step latency (wall / device steps),
        # named as such (r2 verdict weak #5 fix).
        "mean_step_latency_us": round(wall / steps * 1e6, 3),
        "device_steps": int(steps),
        # Fused-step accounting (ISSUE 6), present in EVERY row:
        # ``steps_total`` = device steps actually run (post-fusion),
        # ``steps_fused`` = op rows folded into earlier steps (0 on
        # unfused configs).  Configs that fuse pass the real counts
        # (and the per-shape histogram) via **extra, overriding these.
        "steps_total": int(steps),
        "steps_fused": 0,
        "hbm_bytes_accounted": int(hbm_bytes),
        "hbm_bytes_measured": measured,
        "ops": int(n_ops),
        "batch": int(batch),
        "oracle_equal": bool(oracle_equal),
    }
    if measured is None:
        # null + a reason beats a silently absent stat (VERDICT next #5).
        row["hbm_bytes_measured_note"] = measured_note
    row.update(_BASELINE_STATS)  # sample spread + loadavg of the denominator
    _BASELINE_STATS.clear()  # consume-once: rows without their own
    #                          baseline call must not inherit stale stats
    row.update(extra)
    log(f"[{config}] {ops_per_sec:,.0f} ops/s "
        f"(x{row['vs_baseline']} vs native single-core), "
        f"oracle_equal={oracle_equal}")
    return row


def sync(res):
    # jax.block_until_ready does NOT reliably await execution on the
    # tunnel-attached chip; a tiny value download (8 x batch ints) is
    # the only dependable barrier.
    for r in (res if isinstance(res, list) else [res]):
        np.asarray(r.err)


def time_run(run, reps):
    t0 = time.perf_counter()
    res = run()
    first = time.perf_counter() - t0
    log(f"  first run (incl. compile): {first:.2f}s")
    sync(res)  # drain before timing

    def batch_wall(n):
        t0 = time.perf_counter()
        res = None
        for _ in range(n):
            # Drop the previous dispatch's result reference before
            # enqueuing the next: dispatches stay pipelined (the runtime
            # holds buffers until each completes), but Python no longer
            # pins N result sets live — at kevin scale one set is
            # ~10 GiB and two pinned sets exhaust HBM.
            del res
            res = run()
        sync(res)
        return time.perf_counter() - t0, res

    # Throughput: kernels serialize on the one TensorCore, so the wall of
    # an N-dispatch batch is N*kernel + C, with C the constant host/tunnel
    # overhead (~65ms RTT on this remote-attached chip). A two-point
    # slope removes C exactly; a naive total/reps would fold it in and
    # understate throughput, per-rep syncs would pay C every rep and
    # understate it 2-3x. reps < 4 (deliberately slow worst cases, e.g.
    # kevin) skips the fit and reports the conservative RTT-inclusive wall.
    if reps < 4:
        # Drop the warm-up result BEFORE re-dispatching: at kevin scale
        # one result set is ~10 GiB of HBM planes, and two live sets
        # exhaust the chip.
        del res
        t1, res = batch_wall(reps)
        wall = t1 / reps
        _force(res)
        return res, wall, {
            "slope_fit_runs": None,
            "blocking_run_ms_incl_host_rtt": round(t1 / reps * 1e3, 3),
        }
    n1 = max(2, reps // 4)
    n2 = max(n1 + 4, reps)
    del res  # same two-live-result-sets hazard as the reps < 4 branch
    t1, res = batch_wall(n1)
    del res  # and again between the two fit points
    t2, res = batch_wall(n2)
    wall = (t2 - t1) / (n2 - n1)
    if wall <= 0:  # timing noise swamped the fit; fall back (conservative)
        wall = t2 / n2
    # Latency: blocking dispatch + hard sync, labeled as including the
    # host round-trip (the number a caller awaiting a single batch
    # observes). 5 samples -> p50, the BASELINE.json latency metric.
    samples = []
    for _ in range(5):
        del res
        t0 = time.perf_counter()
        res = run()
        sync(res)
        samples.append(time.perf_counter() - t0)
    _force(res)
    dist = {
        "slope_fit_runs": [n1, n2],
        "host_overhead_ms": round((t1 - n1 * wall) * 1e3, 3),
        "blocking_run_ms_incl_host_rtt": round(samples[0] * 1e3, 3),
        "p50_blocking_run_ms_incl_host_rtt": round(
            sorted(samples)[len(samples) // 2] * 1e3, 3),
    }
    return res, wall, dist


def _force(res):
    if isinstance(res, list):
        for r in res:
            r.check()
    else:
        res.check()


# --------------------------------------------------------------- configs --


def cfg_northstar(args):
    """Full automerge-paper trace x batch identical docs.

    Default engine = ``rle``: the run-blocked VMEM engine consuming the
    RLE-merged op stream (`ops.batch.merge_patches`) — 10,712 device
    steps over ~13k run rows for the 259,778-patch trace. ``vs_baseline``
    stays equal-workload: the native C++ engine replays the ORIGINAL
    per-patch stream, and ``ops`` counts original patches.
    """
    from text_crdt_rust_tpu.config import engines_for
    from text_crdt_rust_tpu.ops import blocked as BL
    from text_crdt_rust_tpu.ops import blocked_hbm as BH
    from text_crdt_rust_tpu.ops import rle as R

    if args.engine not in engines_for("northstar"):
        raise ValueError(
            f"northstar does not implement engine {args.engine!r} "
            f"(choose one of {engines_for('northstar')})")
    data = load_testing_data(trace_path(args.trace))
    patches = flatten_patches(data)
    if args.patches:
        patches = patches[:args.patches]
    n_ops = len(patches)
    ins_total = sum(len(p.ins_content) for p in patches)
    # Default geometry (rle): 512 lanes at the measured-optimum capacity
    # 20,992 (r5 sweep). A user-supplied LARGER --capacity falls back to
    # 256 lanes: 512-lane planes exceed the VMEM budget at 32k+ rows
    # (PERF.md §5).
    _rle_cap = args.capacity or 20992
    batch = args.batch or (
        (512 if _rle_cap <= 20992 else 256)
        if args.engine == "rle" else 128)

    base_ops, base_str = native_replay(patches)
    # Full-trace ground truth is shipped with the corpus; the O(n^2)
    # splice oracle only runs for prefixes (r2 verdict weak #6).
    want = data.end_content if not args.patches else expected_content(patches)
    assert base_str == want

    fstats = None
    if args.engine in ("rle", "rle-hbm"):
        from text_crdt_rust_tpu.ops import rle_hbm as RH

        merged = B.merge_patches(patches)
        lmax = max([len(p.ins_content) for p in merged] + [1])
        ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
        # Generalized step fusion (ISSUE 6): fold the shapes the host
        # coalescer cannot reach — replace pairs (delete+insert at one
        # position land as ONE dual-branch step) and backwards insert
        # bursts (W-row fused splices) — on the fused-splice engines.
        # --fuse-w 1 disables; default 8 honors every K's headroom.
        from text_crdt_rust_tpu.config import supports_fused_steps
        fuse_w = args.fuse_w or 8
        if fuse_w > 1 and supports_fused_steps(args.engine):
            ops, fstats = B.fuse_steps(ops, fuse_w=fuse_w)
        # K=128 x 512 lanes x capacity 20,992 is the measured optimum
        # (r5 sweep, committed as perf/sweep_r4.json — written by
        # perf/sweep_r4.py: 3.80G ops/s vs 2.63G at the old 256x32768);
        # the HBM variant holds 1024+ lanes (verdict item 2's batch bar)
        # and G doc GROUPS multiply the concurrent-document count to the
        # 10k of the north-star statement in ONE kernel launch.
        groups = max(args.groups, 1)
        stream = [ops] * groups if groups > 1 else ops
        if args.engine == "rle-hbm":
            block_k = 512
            capacity = args.capacity or 32768
            capacity = ((capacity + block_k - 1) // block_k) * block_k
            maker = partial(RH.make_replayer_rle_hbm, block_k=block_k)
        else:
            block_k = 128
            capacity = args.capacity or 20992  # RUN rows, not chars
            capacity = ((capacity + block_k - 1) // block_k) * block_k
            maker = partial(R.make_replayer_rle, block_k=block_k)
        log(f"[northstar] {args.trace}[:{n_ops}] -> {ops.num_steps} merged "
            f"steps, capacity {capacity} runs, batch {batch} x {groups} "
            f"group(s), engine {args.engine}")
        run = maker(stream, capacity=capacity, batch=batch,
                    chunk=args.chunk, interpret=args.interpret)
        hbm = groups * (2 * capacity * batch * 4
                        + 2 * ops.num_steps * batch * 4)
        if groups > 1:
            def to_flat(ops_, res_list):
                # Verify EVERY group's doc 0 (identical streams).
                docs = [R.rle_to_flat(ops_, r) for r in res_list]
                for d in docs[1:]:
                    assert SA.to_string(d) == SA.to_string(docs[0])
                return docs[0]
        else:
            to_flat = R.rle_to_flat
    else:
        capacity = 2 << int(np.ceil(np.log2(max(ins_total, 64))))
        ops, _ = B.compile_local_patches(patches, lmax=args.lmax,
                                         dmax=args.lmax)
        block_k = min(args.block_k, capacity // 2)
        log(f"[northstar] {args.trace}[:{n_ops}] -> {ops.num_steps} steps, "
            f"capacity {capacity}, batch {batch}, engine {args.engine}")
        if args.engine == "hbm":
            run = BH.make_replayer_hbm(ops, capacity=capacity, batch=batch,
                                       block_k=block_k, chunk=args.chunk,
                                       interpret=args.interpret)
            hbm = (2 * capacity + block_k) * batch * 4 \
                + 2 * ops.num_steps * batch * 4
        else:
            run = BL.make_replayer(ops, capacity=capacity, batch=batch,
                                   block_k=block_k, chunk=args.chunk,
                                   interpret=args.interpret)
            hbm = capacity * batch * 4 + 2 * ops.num_steps * batch * 4
        to_flat = BL.blocked_to_flat
    res, wall, dist = time_run(run, args.reps)
    got = SA.to_string(to_flat(ops, res))
    ok = got == want
    if not ok and not args.lax_check:
        raise AssertionError("northstar replay diverged from string oracle")
    groups = getattr(args, "groups", 1) if args.engine.startswith("rle") \
        else 1
    steps = ops.num_steps * max(groups, 1)
    fuse_extra = {}
    if fstats is not None:
        fuse_extra = {"steps_fused": fstats.rows_saved * max(groups, 1),
                      "steps_prefuse": fstats.steps_in * max(groups, 1),
                      "fuse_shapes": dict(fstats.fused),
                      "fuse_w": args.fuse_w or 8}
    return make_row("northstar_automerge_paper_full", args.engine, n_ops,
                    batch * max(groups, 1), wall, steps, hbm, base_ops, ok,
                    reps=args.reps, **fuse_extra, **dist)


def cfg_1_cpu(args):
    """Config 1: single-doc full-trace replay on the CPU reference path,
    plus the text-only rope lower bound (`benches/ropey.rs:12-38`)."""
    from text_crdt_rust_tpu.models.native import rope_replay

    data = load_testing_data(trace_path("automerge-paper"))
    patches = flatten_patches(data)
    base_ops, got = native_replay(patches)
    wall = len(patches) / base_ops
    crdt_row = make_row("config1_automerge_paper_cpu", "native-cpp",
                        len(patches), 1, wall, len(patches), 0, base_ops,
                        got == data.end_content)

    # Pre-convert once: list->ndarray conversion is ~15x the replay
    # itself and must not pollute the timed region.
    pos = np.asarray([p.pos for p in patches], np.uint32)
    dels = np.asarray([p.del_len for p in patches], np.uint32)
    il = np.asarray([len(p.ins_content) for p in patches], np.uint32)
    cps = np.frombuffer("".join(p.ins_content for p in patches)
                        .encode("utf-32-le"), np.uint32)
    _n, content = rope_replay(pos, dels, il, cps)  # warm + verify
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        rope_replay(pos, dels, il, cps, want_content=False)
        best = min(best, time.perf_counter() - t0)
    rope_row = make_row("config1_rope_text_only_lower_bound", "gap-buffer",
                        len(patches), 1, best, len(patches), 0,
                        len(patches) / best, content == data.end_content,
                        note="no CRDT metadata; the bound CRDT rows are "
                             "judged against (benches/ropey.rs)")
    return [crdt_row, rope_row]


def _compile_rle(patches, lmax_cap=512):
    """Merged-stream compile + sim-sized run capacity for the rle engine.
    Long inserts chunk at ``lmax_cap``; the in-kernel append-merge fuses
    the chained chunks back into one device run."""
    from text_crdt_rust_tpu.ops import rle as R

    merged = B.merge_patches(patches)
    lmax = min(max([len(p.ins_content) for p in merged] + [1]), lmax_cap)
    ops, _ = B.compile_local_patches(merged, lmax=lmax, dmax=None)
    peak, _final = R.simulate_run_rows(merged)
    capacity = ((int(peak * 2.5) + 255) // 256) * 256
    return ops, max(capacity, 512)


def cfg_2(args):
    """Config 2: random_edits stream, identical docs in the lane dim.

    Random-position edits barely merge (factor ~1) — this config is the
    fragmentation stress: runs stay short, so it measures the rle
    engine's splice/split machinery, not the merge win.
    """
    from text_crdt_rust_tpu.ops import rle as R

    steps = 2000 if args.smoke else 20000
    # Random edits need ~60k run rows; at >128 lanes the two VMEM planes
    # blow the 110MB budget, so this config pins 128.
    batch = min(args.batch, 128) if args.batch else 128
    patches, content = random_patches(random.Random(42), steps)
    base_ops, base_str = native_replay(patches)
    assert base_str == content

    ops, capacity = _compile_rle(patches)
    run = R.make_replayer_rle(ops, capacity=capacity, batch=batch,
                              block_k=256,
                              chunk=128 if args.smoke else 1024,
                              interpret=args.interpret)
    hbm = 2 * capacity * batch * 4 + 2 * ops.num_steps * batch * 4
    res, wall, dist = time_run(run, args.reps)
    got = SA.to_string(R.rle_to_flat(ops, res))
    return make_row("config2_random_edits_identical_docs", "rle",
                    len(patches), batch, wall, ops.num_steps, hbm,
                    base_ops, got == content, **dist)


def cfg_3(args):
    """Config 3: ragged mixed corpus (rustcode + sveltecomponent) as
    divergent doc groups on the rle engine's grid dimension."""
    from text_crdt_rust_tpu.ops import rle as R

    names = ("rustcode", "sveltecomponent")
    datas = [load_testing_data(trace_path(n)) for n in names]
    all_patches = [flatten_patches(d) for d in datas]
    if args.smoke:
        all_patches = [p[:400] for p in all_patches]
    opses, wants = [], []
    capacity = 512
    for p, d in zip(all_patches, datas):
        ops, cap = _compile_rle(p)
        opses.append(ops)
        capacity = max(capacity, cap)
        wants.append(d.end_content if not args.smoke else
                     expected_content(p))

    base_total = 0.0
    group_stats = {}
    for name, ps, want in zip(names, all_patches, wants):
        ops_s, got = native_replay(ps)
        assert got == want
        base_total += ops_s
        group_stats[name] = dict(_BASELINE_STATS)
    base_avg = base_total / len(all_patches)
    # The row's denominator averages the groups; record EVERY group's
    # sample spread, not just the last call's (consume-once would
    # otherwise leave sveltecomponent's samples beside the averaged
    # denominator — review r5).
    _BASELINE_STATS.clear()
    _BASELINE_STATS["baseline_samples_by_group"] = group_stats

    batch3 = args.batch or 128
    run = R.make_replayer_rle(opses, capacity=capacity,
                              batch=batch3, block_k=256,
                              chunk=128 if args.smoke else 1024,
                              interpret=args.interpret)
    hbm = 2 * len(opses) * capacity * batch3 * 4
    results, wall, dist = time_run(run, args.reps)
    ok = True
    for ops, res, want in zip(opses, results, wants):
        got = SA.to_string(R.rle_to_flat(ops, res))
        ok = ok and (got == want)
    n_ops = sum(len(p) for p in all_patches)
    steps = sum(o.num_steps for o in opses)
    return make_row("config3_ragged_mixed_corpus", "rle-groups", n_ops,
                    batch3, wall, steps, hbm, base_avg, ok,
                    groups=list(names), **dist)


def cfg_4(args):
    """Config 4: N-peer concurrent-insert storm (tiebreak-heavy remote
    ops) on the mixed RLE run engine (`doc.rs:242-348` on run rows —
    the r3 verdict's missing #1). ``--engine blocked-mixed`` selects the
    round-3 per-char engine for comparison."""
    from text_crdt_rust_tpu.ops import blocked as BL
    from text_crdt_rust_tpu.ops import blocked_mixed as BM
    from text_crdt_rust_tpu.ops import rle as R
    from text_crdt_rust_tpu.ops import rle_mixed as RM

    n_peers, rounds, run_len = (4, 10, 2) if args.smoke else (16, 200, 4)
    txns, receiver = make_storm(n_peers, rounds, run_len, seed=7)
    want = receiver.to_string()
    base_ops, base_str = native_remote_replay(txns)
    assert base_str == want

    table = B.AgentTable(sorted({t.id.agent for t in txns}))
    ops, _ = B.compile_remote_txns(txns, table, lmax=min(16, run_len * 2),
                                   dmax=16)
    total_chars = n_peers * rounds * run_len
    # Suite-wide --engine values cfg_4 doesn't distinguish (rle-hbm,
    # blocked, ...) fall back to the default run engine rather than
    # failing the whole config.
    def run_storm_rle_mixed(config, ops_, want_, n_ops_, base_ops_,
                            batch4, **extra):
        """One rle-mixed storm measurement -> a bench row (shared by the
        insert storm and the delete-heavy variant so the capacity
        heuristic and replayer kwargs cannot drift apart)."""
        # Run capacity: every storm op splices <= 3 rows; 2x headroom.
        block_k = 128
        capacity = ((max(int(ops_.num_steps * 3), 256) + block_k - 1)
                    // block_k) * block_k
        run = RM.make_replayer_rle_mixed(
            ops_, capacity=capacity, batch=batch4, block_k=block_k,
            chunk=128 if args.smoke else 1024, interpret=args.interpret)
        res, wall, dist = time_run(run, args.reps)
        got = SA.to_string(R.rle_to_flat(ops_, res))
        return make_row(config, "rle-mixed", n_ops_, batch4, wall,
                        ops_.num_steps, 2 * capacity * batch4 * 4,
                        base_ops_, got == want_,
                        peers=n_peers, rounds=rounds, **extra, **dist)

    if args.engine == "blocked-mixed":
        # The per-char blocked engine is VMEM-bound at 128 lanes.
        batch4 = min(args.batch, 128) if args.batch else 128
        capacity = 2 << int(np.ceil(np.log2(max(total_chars, 256))))
        block_k = min(256, capacity // 2)
        run = BM.make_replayer_mixed(ops, capacity=capacity, batch=batch4,
                                     block_k=block_k,
                                     chunk=128 if args.smoke else 1024,
                                     interpret=args.interpret)
        res, wall, dist = time_run(run, args.reps)
        got = SA.to_string(BL.blocked_to_flat(ops, res))
        return make_row("config4_concurrent_insert_storm",
                        "blocked-mixed", total_chars, batch4, wall,
                        ops.num_steps, 2 * capacity * batch4 * 4,
                        base_ops, got == want,
                        peers=n_peers, rounds=rounds, **dist)

    # The run engine's planes (~9.6k rows) fit 512 lanes — and its step
    # cost is dominated by lane-independent sequencing (scalar table
    # reads, lane reductions), so wider batches are nearly free.
    batch4 = args.batch or 128
    row = run_storm_rle_mixed("config4_concurrent_insert_storm", ops,
                              want, total_chars, base_ops, batch4)

    # Delete-heavy remote variant (VERDICT r4 next #3: the remote
    # delete path — fragmentation walk, double deletes — had never
    # been benched): ~35% of peer rounds merge earlier history and
    # delete a cross-peer span instead of inserting.
    dtxns, dreceiver = make_storm(n_peers, rounds, run_len, seed=7,
                                  del_prob=0.35)
    dwant = dreceiver.to_string()
    dbase_ops, dbase_str = native_remote_replay(dtxns)
    assert dbase_str == dwant
    dtable = B.AgentTable(sorted({t.id.agent for t in dtxns}))
    dops, _ = B.compile_remote_txns(dtxns, dtable,
                                    lmax=min(16, run_len * 2),
                                    dmax=None)  # one-pass interval delete
    d_chars = sum(sum(getattr(op, "len",
                              len(getattr(op, "ins_content", "")))
                      for op in t.ops) for t in dtxns)
    drow = run_storm_rle_mixed("config4_delete_heavy_storm", dops,
                               dwant, d_chars, dbase_ops, batch4,
                               del_prob=0.35)
    return [row, drow]


def _stream_loop(runners, resync_every, ckpt_path, state_keys):
    """The config-5 streaming loop shared by the local and remote
    variants: device-resident state chained across chunks, segment
    barriers (a tiny err download is the only reliable completion fence
    on the tunnel), EVERY chunk's result check()ed at a barrier (err_ref
    re-zeroes per run, so skipping one would discard its flags), and
    checkpoint resync OFF the timed apply path.  ``state_keys`` names
    the engine's ``state()`` tuple fields for the .npz round-trip.
    Returns (last_res, wall_s, ckpt_ms, resyncs)."""
    state = None
    wall = 0.0
    ckpt_ms = 0.0
    resyncs = 0
    pending = []
    t0 = time.perf_counter()
    for ci, run in enumerate(runners):
        res = run(state)
        state = res.state()
        pending.append(res)
        if (ci + 1) % resync_every == 0 and ci + 1 < len(runners):
            np.asarray(res.err)
            wall += time.perf_counter() - t0
            tc = time.perf_counter()
            for r_ in pending:
                r_.check()
            pending.clear()
            arrs = [np.asarray(x) for x in res.state()]
            np.savez(ckpt_path, **dict(zip(state_keys, arrs)))
            z = np.load(ckpt_path)
            state = tuple(z[k] for k in state_keys)
            ckpt_ms += (time.perf_counter() - tc) * 1e3
            resyncs += 1
            t0 = time.perf_counter()
    np.asarray(res.err)  # final hard sync closes the last segment
    wall += time.perf_counter() - t0
    for r_ in pending:
        r_.check()
    return res, wall, ckpt_ms, resyncs


def _step_latency_pass(runners, chunk_steps):
    """Per-step latency DISTRIBUTION for the streaming configs (VERDICT
    next #5): one extra warm re-chain with a hard sync per chunk; each
    sample is (blocking chunk wall incl. host RTT) / real steps.  Off
    the timed throughput loop — per-chunk syncs would serialize the
    pipelining the timed loop exists to measure."""
    samples = []
    state = None
    for run, steps in zip(runners, chunk_steps):
        t0 = time.perf_counter()
        res = run(state)
        np.asarray(res.err)
        samples.append((time.perf_counter() - t0) / max(steps, 1) * 1e6)
        state = res.state()
    ss = sorted(samples)
    return {
        "p50_step_latency_us_blocking_incl_rtt":
            round(ss[len(ss) // 2], 3),
        "p99_step_latency_us_blocking_incl_rtt":
            round(ss[min(len(ss) - 1, int(round((len(ss) - 1) * 0.99)))],
                  3),
        "step_latency_chunk_samples_us": [round(s, 3) for s in samples],
    }


def cfg_5(args):
    """Config 5: streaming apply over per-doc DIVERGENT streams,
    delete-heavy, with periodic host<->device checkpoint resync.

    Engine: ``ops.rle_lanes`` — B distinct documents advance one op each
    per kernel step.  Round-4 fixes (VERDICT r3 next #3): lane state is
    DEVICE-RESIDENT across chunks (``LanesResult.state()`` feeds the
    next chunk's ``run(state)`` with no download), chunk dispatches are
    pipelined (async; one hard sync per resync segment), and checkpoint
    save/load runs at ``StreamConfig.resync_every`` cadence OFF the
    timed apply path (reported separately as ``checkpoint_ms``).
    """
    from text_crdt_rust_tpu.config import StreamConfig
    from text_crdt_rust_tpu.ops import rle_lanes as RL

    n_docs = 16 if args.smoke else 2048
    chunks = 3 if args.smoke else 8
    steps_per_chunk = 30 if args.smoke else 100
    stream_cfg = StreamConfig(resync_every=2 if args.smoke else 4)
    rngs = [random.Random(1000 + d) for d in range(n_docs)]
    contents = [""] * n_docs

    def next_chunk():
        streams = []
        for d in range(n_docs):
            patches, content = _continue_patches(
                rngs[d], contents[d], steps_per_chunk, ins_prob=0.45)
            contents[d] = content
            streams.append(patches)
        return streams

    all_chunks = [next_chunk() for _ in range(chunks)]

    # GROWING per-chunk capacity from the engine's row invariant
    # (batch.row_growth_bound: <= 2 rows per compiled step) — early
    # chunks run on planes ~1/4 the final size.  The BLOCKED engine
    # keeps K fixed and grows NB with the capacity (the ISSUE-2 block
    # refactor), so each chunk's descent is over NB block sums + one
    # K-row block instead of the whole plane.  Each distinct capacity
    # compiles its own kernel (one-time, pre-warmed below); warm starts
    # zero-pad planes and tables up.
    from text_crdt_rust_tpu.config import lane_block_geometry
    K5 = args.lanes_block_k
    caps = [max(lane_block_geometry(
                B.row_growth_bound(steps_per_chunk * (c + 1)), K5)[0],
                4 * K5) for c in range(chunks)]
    capacity = caps[-1]

    flat0 = [p for ch in all_chunks for p in ch[0]]
    base_ops, base_str = native_replay(flat0)
    assert base_str == contents[0]

    lmax = max((len(p.ins_content) for ch in all_chunks for ps in ch
                for p in ps), default=1) or 1
    ckpt = os.path.join(tempfile.mkdtemp(prefix="tcr_bench_"), "resync.npz")
    next_orders = [0] * n_docs
    n_ops = 0
    steps = 0
    stacked_all = []
    runners = []
    for streams in all_chunks:
        opses = []
        for d, patches in enumerate(streams):
            ops, next_orders[d] = B.compile_local_patches(
                patches, lmax=lmax, dmax=None,
                start_order=next_orders[d])
            opses.append(ops)
            n_ops += len(patches)
        stacked = B.stack_ops(opses)
        stacked_all.append(stacked)
        steps += stacked.num_steps
        runners.append(RL.make_replayer_lanes_blocked(
            stacked, capacity=caps[len(runners)], block_k=K5, chunk=128,
            interpret=args.interpret))

    # Warm with ONE full untimed streaming pass: each runner from the
    # EMPTY init only warms the chunk kernels — the timed loop also
    # runs ``_grow_state``'s pad ops on each PREVIOUS chunk's shapes,
    # and with growing capacities every chunk boundary is a distinct
    # shape pair whose first compile would otherwise land inside the
    # timed wall (the r5 re-record's 6.07ms/step vs the kernel's real
    # ~0.36ms, perf/cfg5_probe.py).
    wstate = None
    for r in runners:
        wres = r(wstate)
        wstate = wres.state()
    np.asarray(wres.err)

    res, wall, ckpt_ms, resyncs = _stream_loop(
        runners, stream_cfg.resync_every, ckpt,
        ("ordp", "lenp", "nlog", "blkord", "rws", "liv"))
    lat = _step_latency_pass(
        runners, [s.num_steps for s in stacked_all])

    ok = True
    for d in range(0, n_docs, max(1, n_docs // 8)):
        flat = RL.expand_lane(res, d)
        chars = {}
        for stacked in stacked_all:
            ilens = np.asarray(stacked.ins_len)[:, d]
            starts = np.asarray(stacked.ins_order_start)[:, d]
            cps = np.asarray(stacked.chars)[:, d]
            for s in np.nonzero(ilens)[0]:
                il = int(ilens[s])
                st = int(starts[s])
                for j in range(il):
                    chars[st + j] = chr(int(cps[s, j]))
        got = "".join(chars[int(o) - 1] for o in flat if o > 0)
        ok = ok and (got == contents[d])
    hbm = 2 * capacity * n_docs * 4 + 2 * steps * n_docs * 4
    return make_row("config5_streaming_divergent_resync", "rle-lanes",
                    n_ops, 1, wall, steps, hbm, base_ops, ok,
                    docs=n_docs, chunks=chunks, capacity=capacity,
                    layout="blocked", lanes_block_k=K5,
                    checkpoint_ms=round(ckpt_ms, 1), resyncs=resyncs,
                    resync_every=stream_cfg.resync_every, **lat)


class _PeerSynth:
    """Fast single-author CRDT peer: turns local patches into a VALID
    RemoteTxn stream (ids exist, seqs dense, delete targets split per
    seq-contiguous run) without the O(doc) oracle replay cost.  For a
    single author, order == seq; origins are the neighboring LIVE ids —
    any intervening tombstones only shift the integrate cursor across
    invisible chars, so the receiver's CONTENT matches the string sim
    (the oracle cross-check in cfg_5_remote verifies exactly this).
    """

    def __init__(self, agent: str):
        self.agent = agent
        self.ids: list = []   # live char ids (seqs) in doc order
        self.seq = 0

    def _rid(self, seq):
        from text_crdt_rust_tpu.common import RemoteId
        if seq is None:
            return RemoteId("ROOT", 0xFFFFFFFF)
        return RemoteId(self.agent, seq)

    def apply(self, patches):
        """-> RemoteTxns for this patch chunk (one txn per patch)."""
        from text_crdt_rust_tpu.common import (
            RemoteDel, RemoteIns, RemoteTxn)
        out = []
        for p in patches:
            ops = []
            seq0 = self.seq
            if p.del_len:
                victims = self.ids[p.pos: p.pos + p.del_len]
                del self.ids[p.pos: p.pos + p.del_len]
                run_start, run_len = victims[0], 1
                for v in victims[1:]:
                    if v == run_start + run_len:
                        run_len += 1
                    else:
                        ops.append(RemoteDel(self._rid(run_start), run_len))
                        run_start, run_len = v, 1
                ops.append(RemoteDel(self._rid(run_start), run_len))
                self.seq += p.del_len
            if p.ins_content:
                il = len(p.ins_content)
                left = self.ids[p.pos - 1] if p.pos > 0 else None
                right = (self.ids[p.pos]
                         if p.pos < len(self.ids) else None)
                ops.append(RemoteIns(self._rid(left), self._rid(right),
                                     p.ins_content))
                self.ids[p.pos:p.pos] = range(self.seq, self.seq + il)
                self.seq += il
            out.append(RemoteTxn(id=self._rid(seq0), parents=[], ops=ops))
        return out


def cfg_5_remote(args):
    """Config 5, REMOTE variant: per-doc DIVERGENT RemoteTxn streams on
    the unified per-lane mixed engine (``ops.rle_lanes_mixed``) — the
    production sync shape (thousands of different documents, each
    applying its own peer's remote ops, `doc.rs:242-348` per lane), the
    r4 verdict's missing #2.  Delete-heavy, streamed in chunks with
    device-resident state (runs + by-order tables) across chunks and
    checkpoint resync off the timed path.  Streams are single-author
    per doc (no tiebreak storms — that is config 4's axis); ``ops``
    counts CHARS (ins chars + delete targets) to match
    ``native_remote_replay``'s equal-workload denominator.
    """
    from text_crdt_rust_tpu.config import StreamConfig
    from text_crdt_rust_tpu.models.oracle import ListCRDT as Oracle
    from text_crdt_rust_tpu.ops import rle_lanes as RL
    from text_crdt_rust_tpu.ops import rle_lanes_mixed as RLM

    n_docs = 16 if args.smoke else 2048
    chunks = 3 if args.smoke else 8
    steps_per_chunk = 30 if args.smoke else 100
    stream_cfg = StreamConfig(resync_every=2 if args.smoke else 4)
    lmax = 4
    rngs = [random.Random(7000 + d) for d in range(n_docs)]
    contents = [""] * n_docs
    synths = [_PeerSynth(f"peer{d}") for d in range(n_docs)]
    all_txns = [[] for _ in range(n_docs)]

    chunk_txns = []
    for _ in range(chunks):
        per_doc = []
        for d in range(n_docs):
            patches, contents[d] = _continue_patches(
                rngs[d], contents[d], steps_per_chunk, ins_prob=0.45)
            txns = synths[d].apply(patches)
            all_txns[d].extend(txns)
            per_doc.append(txns)
        chunk_txns.append(per_doc)

    base_ops, base_str = native_remote_replay(all_txns[0])
    assert base_str == contents[0], "peer stream does not reproduce " \
        "the string sim (synthesizer bug)"

    tables = [B.AgentTable([f"peer{d}"]) for d in range(n_docs)]
    assigners = [None] * n_docs
    opses_by_chunk = []
    n_char_ops = 0
    for per_doc in chunk_txns:
        opses = []
        for d, txns in enumerate(per_doc):
            ops, assigners[d] = B.compile_remote_txns(
                txns, tables[d], assigner=assigners[d], lmax=lmax,
                dmax=None)  # one-pass interval delete: no chunking
            opses.append(ops)
            n_char_ops += sum(
                sum(getattr(op, "len",
                            len(getattr(op, "ins_content", "")))
                    for op in t.ops) for t in txns)
        opses_by_chunk.append(opses)

    # Equal shapes across chunks -> one compiled kernel per geometry
    # (pad every chunk's stacked stream to the suite-wide max step
    # count; padded steps are exact no-ops).
    stacked_all = [B.stack_ops(o) for o in opses_by_chunk]
    real_steps = [s.num_steps for s in stacked_all]  # pre-padding maxima
    smax = ((max(real_steps) + 127) // 128) * 128
    stacked_all = [jax.tree.map(np.asarray, B.pad_ops(s, smax))
                   for s in stacked_all]

    # GROWING per-chunk capacities (see cfg_5), bounded by COMPILED
    # device steps, not patches: a single <=4-char positional delete can
    # compile into up to 4 KIND_REMOTE_DEL steps (one per target order
    # run, batch.py target_runs), and every device step adds <= 2 rows
    # (batch.row_growth_bound; pre-padding counts — padded no-op steps
    # add no rows).  Blocked layout: K fixed, NB grows with capacity.
    from text_crdt_rust_tpu.config import lane_block_geometry
    K5 = args.lanes_block_k
    cum_steps = np.cumsum(real_steps)
    caps = [max(lane_block_geometry(B.row_growth_bound(int(cs)), K5)[0],
                4 * K5) for cs in cum_steps]
    capacity = caps[-1]
    ocaps = [((lmax * steps_per_chunk * (c + 1) + lmax + 7) // 8) * 8
             for c in range(chunks)]
    ocap = ocaps[-1]
    steps = 0
    runners = []
    for ci, stacked in enumerate(stacked_all):
        steps += stacked.kind.shape[0]
        runners.append(RLM.make_replayer_lanes_mixed_blocked(
            stacked, capacity=caps[ci], block_k=K5,
            order_capacity=ocaps[ci],
            chunk=128, lane_tile=min(256, n_docs),
            interpret=args.interpret))

    # Warm with ONE full untimed streaming pass (see cfg_5: the grow-
    # state pad ops at every distinct chunk-boundary shape pair must
    # compile off the timed path, not just the chunk kernels).
    wstate = None
    for r in runners:
        wres = r(wstate)
        wstate = wres.state()
    np.asarray(wres.err)

    ckpt = os.path.join(tempfile.mkdtemp(prefix="tcr_bench_"), "resync.npz")
    res, wall, ckpt_ms, resyncs = _stream_loop(
        runners, stream_cfg.resync_every, ckpt,
        ("ordp", "lenp", "nlog", "blkord", "rws", "liv", "raw",
         "oll", "orl", "ordblk", "fwd"))
    lat = _step_latency_pass(runners, real_steps)

    ok = True
    for d in range(0, n_docs, max(1, n_docs // 8)):
        oracle = Oracle()
        for t in all_txns[d]:
            oracle.apply_remote_txn(t)
        want_signed = [(-1 if oracle.deleted[i] else 1)
                       * (int(oracle.order[i]) + 1)
                       for i in range(oracle.n)]
        got_signed = RL.expand_lane(res, d).tolist()
        ok = ok and got_signed == want_signed \
            and oracle.to_string() == contents[d]
    hbm = (2 * capacity + 2 * ocap) * n_docs * 4
    return make_row("config5_streaming_remote_divergent",
                    "rle-lanes-mixed", n_char_ops, 1, wall, steps, hbm,
                    base_ops, ok,
                    docs=n_docs, chunks=chunks, capacity=capacity,
                    order_capacity=ocap,
                    layout="blocked", lanes_block_k=K5,
                    checkpoint_ms=round(ckpt_ms, 1), resyncs=resyncs,
                    resync_every=stream_cfg.resync_every, **lat)


def cfg_serve(args):
    """Config serve: the continuous-batching document server under the
    seeded closed-loop load generator (`serve/loadgen.py`) — Zipf doc
    popularity forcing evictions, 10% per-class fault injection on
    remote frames, mixed local/remote traffic.  The row records
    sustained applied item-ops/s, batch fill ratio, eviction/restore
    counts, docs resident vs total, and the p50/p99 admission->applied
    latency; ``oracle_equal`` is the ISSUE-3 acceptance bar (every doc
    bit-identical to its host-oracle twin AND every device lane
    bit-identical to its oracle).  ``--engine`` is wired through the
    registry: any engine with a ``serve`` backend runs the same loop
    (``--engine rle-lanes-mixed`` serves from the blocked O(NB+K)
    kernels; the dedicated ``serve-lanes`` config additionally proves
    flat-twin bit-identity and records the step-cost ratio)."""
    from text_crdt_rust_tpu.config import ServeConfig, engines_for
    from text_crdt_rust_tpu.serve.loadgen import ServeLoadGen

    # Fall back to the ServeConfig default (flat, the measured
    # reference backend) — NOT engines_for("serve")[0], which follows
    # registry dict order and silently flipped when rle-lanes-mixed
    # registered for serve.
    engine = args.engine if args.engine in engines_for("serve") \
        else ServeConfig().engine
    docs, ticks, events = (24, 10, 16) if args.smoke else (200, 60, 48)

    # ISSUE 7: the SAME seeded loadgen on both protocol generations —
    # v1 (row frames per event, full-snapshot evictions) vs v2
    # (windowed doc-multiplexed columnar frames, delta-chain
    # evictions).  The primary row is the v2 run; the v1 run's byte
    # counters ride along as the bytes-per-op comparison.
    reports = {}
    for wire, ckpt in (("row", "full"), ("columnar", "delta")):
        scfg = ServeConfig(engine=engine, num_shards=2, lanes_per_shard=16,
                           wire_format=wire, ckpt_format=ckpt,
                           train_ticks=2)
        gen = ServeLoadGen(docs=docs, agents_per_doc=3, ticks=ticks,
                           events_per_tick=events, zipf_alpha=1.1,
                           fault_rate=0.10, local_prob=0.25, seed=7,
                           cfg=scfg)
        reports[wire] = gen.run()
    report = reports["columnar"]
    row_wire = reports["row"]["wire"]
    col_wire = report["wire"]
    full_evict = reports["row"]["server"].get(
        "ckpt_full_bytes_per_evict_mean", 0.0)
    delta_evict = report["server"].get(
        "ckpt_delta_bytes_per_evict_mean", 0.0)
    srv = report["server"]
    lanes = scfg.num_shards * scfg.lanes_per_shard
    hbm = scfg.num_shards * scfg.lanes_per_shard * (
        scfg.lane_capacity + 4 * scfg.order_capacity) * 4
    return make_row(
        "config_serve_continuous_batching", engine,
        report["item_ops_applied"], 1, report["device_ticks_wall_s"],
        max(srv.get("device_steps", 1), 1), hbm, None,
        report["converged"],
        docs=docs, agents_per_doc=3, ticks=ticks, lanes_total=lanes,
        docs_in_lane=srv["docs_in_lane"],
        docs_host_only=srv["docs_host_only"],
        docs_evicted=srv["docs_evicted"],
        docs_degraded=srv.get("docs_degraded", 0),
        evictions=srv.get("evictions", 0),
        restores=srv.get("restores", 0),
        batch_fill_ratio=srv.get("batch_fill_ratio_mean", 0.0),
        frames_rejected=srv.get("rejected_frame_rejected", 0),
        p50_admission_to_applied_us=report["latency_us"]["p50"],
        p99_admission_to_applied_us=report["latency_us"]["p99"],
        tick_p50_ms=report["tick_ms"]["p50"],
        tick_p99_ms=report["tick_ms"]["p99"],
        steps_fused=report["tick_ms"].get("fused_rows_saved", 0),
        steps_prefuse=report["tick_ms"].get("steps_prefuse", 0),
        ops_per_step=report["tick_ms"].get("ops_per_step", 1.0),
        # ISSUE 8: distribution keys (not just means) + trace counters,
        # all flowing from the server's one MetricsRegistry.
        ops_per_step_p99=report["tick_ms"].get("ops_per_step_p99", 0.0),
        ops_per_step_max=report["tick_ms"].get("ops_per_step_max", 0.0),
        device_compiles=report["obs"]["device_compiles"],
        trace_events=report["obs"]["trace_events"],
        obs_bundles=report["obs"]["bundles_written"],
        # ISSUE 11: per-op provenance ride-along (additive fields — the
        # row schema pins the floor, not the ceiling): spans tracked at
        # the shipped sampling default, the conservation-audit verdict,
        # and op-age-at-apply percentiles in logical ticks.
        flow_spans=(report.get("flow") or {}).get(
            "spans", {}).get("emitted", 0),
        flow_audit_ok=(report.get("flow") or {}).get("audit_ok"),
        flow_age_p50_ticks=(report.get("flow") or {}).get(
            "ages_ticks", {}).get("p50", 0),
        flow_age_p99_ticks=(report.get("flow") or {}).get(
            "ages_ticks", {}).get("p99", 0),
        # ISSUE 12: pipelined-tick + Nagle-window ride-alongs (additive
        # fields): how much device-sync demand the staged sync hid, and
        # the emission window the run shipped under.
        pipeline_ticks=(report.get("pipeline") or {}).get("ticks", 1),
        pipeline_overlap_frac=(report.get("pipeline") or {}).get(
            "overlap_frac", 0.0),
        # ISSUE 14: device-resident prefill ride-alongs (additive
        # fields): whether the run shipped scatter deltas instead of
        # full-log round trips, and the per-tick byte cut.
        device_prefill=(report.get("prefill") or {}).get(
            "device_prefill", False),
        prefill_bytes_per_tick=(report.get("prefill") or {}).get(
            "bytes_per_tick", 0.0),
        prefill_bytes_cut_x=(report.get("prefill") or {}).get(
            "bytes_cut_x", 0.0),
        prefill_scatter_compiles=(report.get("prefill") or {}).get(
            "scatter_compiles", 0),
        # ISSUE 20: tick-train ride-alongs (additive fields): the train
        # length the run shipped under and the realized device-dispatch
        # cut vs the serial one-dispatch-per-tick loop (partial flushes
        # at residency boundaries keep it below the depth ceiling).
        train_ticks=(report.get("train") or {}).get("ticks", 1),
        dispatch_cut_x=(report.get("train") or {}).get(
            "dispatch_cut_x", 1.0),
        nagle_txns=col_wire.get("nagle_txns"),
        nagle_rounds=col_wire.get("nagle_rounds"),
        wire_format=col_wire["format"],
        ckpt_format=report["ckpt"]["format"],
        wire_bytes_total=col_wire["txn_bytes"],
        bytes_per_op=col_wire["bytes_per_op"],
        bytes_per_op_row_wire=row_wire["bytes_per_op"],
        wire_bytes_cut_x=round(
            row_wire["bytes_per_op"] / max(col_wire["bytes_per_op"], 1e-9),
            2),
        ckpt_bytes_per_evict=delta_evict,
        ckpt_bytes_per_evict_full=full_evict,
        ckpt_evict_bytes_cut_x=round(
            full_evict / max(delta_evict, 1e-9), 2) if delta_evict else 0.0,
        row_wire_converged=reports["row"]["converged"],
        fault_rate=0.10, zipf_alpha=1.1,
        note="closed-loop serving: ops/s counts applied CRDT item-ops "
             "end-to-end through admission/causal-buffer/batch ticks, "
             "not raw kernel throughput; no equal-workload native "
             "baseline is defined for the serving loop; byte counters "
             "compare the v2 wire/ckpt run against a same-seed v1 run")


def cfg_serve_lanes(args):
    """Config serve-lanes (ISSUE 4): the continuous-batching document
    server on the BLOCKED ``rle-lanes-mixed`` lane backend, proven two
    ways by ``perf/blocked_lanes_sim.py --serve`` in a subprocess (the
    sp_bench pattern — the probe owns its own jax platform config):
    bit-identity (the same seeded loadgen on the lanes backend AND a
    flat-backend twin, every doc byte-identical across backends and to
    the host oracles) and step cost (the loadgen tick trace replayed
    through the kernel-exact blocked cost model vs the flat engine's
    whole-[CAP]-plane model)."""
    cmd = [sys.executable,
           os.path.join("perf", "blocked_lanes_sim.py"), "--serve"]
    if args.smoke:
        cmd.append("--smoke")
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=5400)
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    if r.returncode not in (0, 1) or not lines:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        raise RuntimeError(f"serve-lanes probe failed: {tail}")
    out = json.loads(lines[-1])
    rep = out["per_engine"]["rle-lanes-mixed"]
    w = out["workload"]
    # State bytes per lane: 2 run planes + 4 block tables + fwd + the
    # 3 by-order tables (oll/orl/ordblk), i32 each; geometry comes from
    # the probe's own workload report, not re-stated literals.
    hbm = (w["num_shards"] * w["lanes_per_shard"]
           * (2 * w["lane_capacity"] + 5 * w["NBT"]
              + 3 * w["order_capacity"]) * 4)
    ok = rep["converged"] and out["bit_identical_flat_vs_lanes"]
    return make_row(
        "config_serve_lanes_blocked_backend", "rle-lanes-mixed",
        rep["item_ops_applied"], 1, rep["device_ticks_wall_s"],
        max(rep["device_steps"], 1), hbm, None, ok,
        docs=w["docs"], ticks=w["ticks"], block_k=w["block_k"],
        nb=w["NB"], bit_identical_flat_twin=out[
            "bit_identical_flat_vs_lanes"],
        touched_rows_per_step_flat=out["touched_rows_per_step"]["flat"],
        touched_rows_per_step_lanes=out["touched_rows_per_step"][
            "lanes_blocked"],
        touched_rows_ratio=out["touched_rows_per_step"]["ratio"],
        pass_traffic_ratio=out["pass_traffic_per_step"]["ratio"],
        splits=out["splits"], hint_misses=out["hint_misses"],
        tick_p50_ms=rep["tick_ms"]["p50"],
        tick_p99_ms=rep["tick_ms"]["p99"],
        steps_fused=rep["tick_ms"].get("fused_rows_saved", 0),
        steps_prefuse=rep["tick_ms"].get("steps_prefuse", 0),
        ops_per_step=rep["tick_ms"].get("ops_per_step", 1.0),
        ops_per_step_p99=rep["tick_ms"].get("ops_per_step_p99", 0.0),
        ops_per_step_max=rep["tick_ms"].get("ops_per_step_max", 0.0),
        device_compiles=(rep.get("obs") or {}).get("device_compiles", 0),
        trace_events=(rep.get("obs") or {}).get("trace_events", 0),
        flow_spans=(rep.get("flow") or {}).get(
            "spans", {}).get("emitted", 0),
        flow_audit_ok=(rep.get("flow") or {}).get("audit_ok"),
        flow_age_p50_ticks=(rep.get("flow") or {}).get(
            "ages_ticks", {}).get("p50", 0),
        flow_age_p99_ticks=(rep.get("flow") or {}).get(
            "ages_ticks", {}).get("p99", 0),
        pipeline_ticks=(rep.get("pipeline") or {}).get("ticks", 1),
        pipeline_overlap_frac=(rep.get("pipeline") or {}).get(
            "overlap_frac", 0.0),
        # ISSUE 14 ride-alongs: the lanes backend's by-order tables are
        # device-resident already (only ranks host-merge), so
        # device_prefill reads False and the byte fields stay 0 — the
        # additive fields keep the serve/serve-lanes rows comparable.
        device_prefill=(rep.get("prefill") or {}).get(
            "device_prefill", False),
        prefill_bytes_per_tick=(rep.get("prefill") or {}).get(
            "bytes_per_tick", 0.0),
        prefill_bytes_cut_x=(rep.get("prefill") or {}).get(
            "bytes_cut_x", 0.0),
        prefill_scatter_compiles=(rep.get("prefill") or {}).get(
            "scatter_compiles", 0),
        nagle_txns=(rep.get("wire") or {}).get("nagle_txns"),
        nagle_rounds=(rep.get("wire") or {}).get("nagle_rounds"),
        p50_admission_to_applied_us=rep["latency_us"]["p50"],
        p99_admission_to_applied_us=rep["latency_us"]["p99"],
        evictions=rep["evictions"], restores=rep["restores"],
        wire_format=(rep.get("wire") or {}).get("format"),
        ckpt_format=(rep.get("ckpt") or {}).get("format"),
        wire_bytes_total=(rep.get("wire") or {}).get("txn_bytes"),
        bytes_per_op=(rep.get("wire") or {}).get("bytes_per_op"),
        ckpt_bytes_per_evict=rep.get("ckpt_delta_bytes_per_evict"),
        note=out["note"])


def cfg_sp(args):
    """Config sp: the sequence-parallel sharded engine (VERDICT r5
    missing #5): automerge-paper replay on ``SpDoc`` at virtual sp=8
    with an explicit collectives-per-op count, plus sp=1 parity vs
    ``ops/rle``.  Runs in a subprocess (`perf/sp_bench.py`) because the
    sp mesh needs the host-platform device count baked in before the
    CPU client initializes."""
    cmd = [sys.executable, os.path.join("perf", "sp_bench.py")]
    if args.smoke:
        cmd.append("--smoke")
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=1800)
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        raise RuntimeError(f"sp_bench subprocess failed: {tail}")
    rows = []
    for line in r.stdout.strip().splitlines():
        sub = json.loads(line)
        label = sub.pop("label")
        wall = sub.pop("wall_s")
        n_ops = sub.pop("ops")
        steps = sub.pop("device_steps")
        hbm = sub.pop("hbm_bytes_accounted")
        ok = sub.pop("oracle_equal")
        sub.pop("ops_per_sec", None)  # make_row recomputes the headline
        rows.append(make_row(label, "sp-apply", n_ops, 1, wall, steps,
                             hbm, None, ok, **sub))
    return rows


def _continue_patches(rng, content, steps, ins_prob):
    """random_patches continued from existing content."""
    patches = []
    for _ in range(steps):
        if not content or rng.random() < ins_prob:
            pos = rng.randint(0, len(content))
            ins = "".join(rng.choice("abcdefgh ")
                          for _ in range(rng.randint(1, 4)))
            patches.append(TestPatch(pos, 0, ins))
            content = content[:pos] + ins + content[pos:]
        else:
            pos = rng.randint(0, len(content) - 1)
            span = min(rng.randint(1, 4), len(content) - pos)
            patches.append(TestPatch(pos, span, ""))
            content = content[:pos] + content[pos + span:]
    return patches, content


def cfg_kevin(args):
    """kevin (`benches/yjs.rs:51-62`): 5M single-char prepends on the
    native engine AND on the HBM-state RLE engine (full scale, VERDICT
    r3 next #5), whose logical-block splits amortize the pure-prepend
    worst case (no global rebalance — the round-2 blocker, PERF.md §3).

    HBM math at 5M prepends: capacity = 5M * 2.1 (splits leave blocks
    half full) ~= 10.5M run rows; 2 planes * 10.5M * 128 lanes * 4 B =
    10.75 GB. The lane dim must be a whole 128-wide tile (Mosaic rejects
    64-lane HBM-plane slices), so batch stays 128 and the per-op origin
    outputs — 5.1 GB on their own at this scale — are dropped via
    ``store_origins=False`` (verification reads final state via
    ``expand_runs``, which never needs them). block_k=2048 keeps the
    logical-block tables at ~5k entries instead of 20k."""
    from text_crdt_rust_tpu.config import BatchConfig, supports_fused_steps
    from text_crdt_rust_tpu.ops import rle as R
    from text_crdt_rust_tpu.ops import rle_hbm as RH

    n_native = 50_000 if args.smoke else 5_000_000
    from text_crdt_rust_tpu.models.native import NativeListCRDT
    pos = np.zeros(n_native, np.uint32)
    dels = np.zeros(n_native, np.uint32)
    il = np.ones(n_native, np.uint32)
    cps = np.full(n_native, ord(" "), np.uint32)

    def kevin_once():
        doc = NativeListCRDT()
        a = doc.get_or_create_agent_id("kevin")
        doc.replay_trace(a, pos, dels, il, cps)
        return doc

    # Median-of-3 (each run is ~3s at 5M; the load guard + recorded
    # samples carry the round-5 baseline policy, see _baseline_samples).
    cpu_ops, doc = _baseline_samples(kevin_once, n_native,
                                     1 if args.smoke else 3)
    cpu_row = make_row(f"kevin_cpu_{n_native}", "native-cpp", n_native, 1,
                       n_native / cpu_ops, n_native, 0, cpu_ops,
                       len(doc) == n_native)

    n_tpu = 2048 if args.smoke else args.kevin_n
    patches = [TestPatch(0, 0, " ")] * n_tpu
    # Split-batch prepare (ISSUE 5): the whole workload is ONE
    # backwards-contiguous burst, so at width W the 5M prepends compile
    # to ~5M/W fused multi-row steps — the per-character device-step
    # tax (the last 4x to the 100x bar) gone at the compile stage.
    # W must honor the engines' one-split headroom (W <= K//2 - 1).
    bc = BatchConfig(fuse_w=args.fuse_w or (8 if args.smoke else 64))
    bc.lmax = max(bc.fuse_w, 1)  # single-char bursts: W rows of L=1
    assert supports_fused_steps("rle-hbm") or bc.fuse_w == 1
    ops, _ = B.compile_local_patches(patches, lmax=bc.lmax,
                                     dmax=bc.dmax, fuse_w=bc.fuse_w)
    fuse_w = bc.fuse_w
    # One run row per prepend (runs cannot merge backwards); splits leave
    # blocks half full, so size ~2.1x rows.
    big = n_tpu > 2_000_000
    block_k = 64 if args.smoke else (2048 if big else 512)
    capacity = ((int(n_tpu * 2.1) + block_k - 1) // block_k) * block_k
    batchk = args.batch or 128
    run = RH.make_replayer_rle_hbm(ops, capacity=capacity,
                                   batch=batchk, block_k=block_k,
                                   chunk=128 if args.smoke else 1024,
                                   interpret=args.interpret,
                                   store_origins=not big)
    res, wall, dist = time_run(run, 1)
    flat = R.expand_runs(res)
    got_len = len(flat)
    # Prepends reverse insertion order: orders must read N-1..0.
    order_ok = got_len == n_tpu and bool(
        (flat == np.arange(n_tpu, 0, -1, dtype=np.int32)).all())
    label = "rle-hbm-fused" if fuse_w > 1 else "rle-hbm"
    tpu_row = make_row(f"kevin_tpu_{n_tpu}", label, n_tpu, batchk,
                       wall, ops.num_steps,
                       2 * capacity * batchk * 4,
                       cpu_ops, got_len == n_tpu and order_ok,
                       fuse_w=fuse_w,
                       steps_fused=n_tpu - ops.num_steps,
                       steps_prefuse=n_tpu,
                       fuse_shapes={"burst": n_tpu - ops.num_steps},
                       **dist)
    return [cpu_row, tpu_row]


# ---------------------------------------------------------- ledger gate --


def run_ledger_check(args) -> int:
    """``--check-ledger`` (ISSUE 10): re-derive the committed cost
    ledger's cpu cells at their pinned shapes and fail with a NAMED
    per-metric diff on drift.  Wall-clock-free: every gated metric is a
    logical counter (same-seed deterministic) or a banded static-HLO
    cost, so this runs on any CPU box — the tier-1 suite runs it, which
    means CPU CI guards TPU-relevant cost invariants on every PR."""
    from text_crdt_rust_tpu.obs.ledger import (
        cpu_cell_names,
        diff_ledger,
        load_ledger,
        validate_ledger,
    )

    # The probe owns the derivations (and the sp cell's virtual-mesh
    # XLA_FLAGS setup, applied at import before the CPU client exists).
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "perf"))
    import cost_ledger_probe as probe

    committed = load_ledger(args.ledger)
    validate_ledger(committed)
    cheap = cpu_cell_names(committed)
    want = args.cells.split(",") if args.cells else cheap
    not_cpu = [c for c in want if c not in cheap]
    if not_cpu:
        log(f"--check-ledger refused: cells {not_cpu} are not cpu "
            f"cells of {args.ledger} (device cells need silicon — "
            f"perf/when_up_r11.sh re-records them)")
        return 2
    # A committed cpu cell the probe no longer knows IS drift (a cell
    # rename/removal without a re-record) — report it as a named
    # finding, don't crash on the derive call.
    diffs = [f"{c}: committed as a cpu cell but the probe no longer "
             f"derives it (re-record perf/COST_LEDGER.json)"
             for c in want if c not in probe.CPU_CELLS]
    fresh = probe.derive_cells([c for c in want if c in probe.CPU_CELLS])
    ok, cell_diffs = diff_ledger(committed, fresh)
    diffs.extend(cell_diffs)
    ok = not diffs
    for d in diffs:
        log(f"LEDGER DRIFT: {d}")
    n_metrics = sum(len(c["metrics"]) for c in fresh.values())
    if ok:
        log(f"cost ledger OK: {len(fresh)} cells / {n_metrics} metrics "
            f"re-derived bit-for-logical-bit against {args.ledger}")
    print(json.dumps({"ledger_ok": ok, "ledger": args.ledger,
                      "cells_checked": sorted(fresh),
                      "metrics_checked": n_metrics, "diffs": diffs}))
    return 0 if ok else 1


# ------------------------------------------------------------------ main --


def main() -> None:
    from text_crdt_rust_tpu.config import ENGINE_CHOICES

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="northstar",
                    choices=("northstar", "1", "2", "3", "4", "5", "5r",
                             "kevin", "serve", "serve-lanes", "sp",
                             "all"))
    ap.add_argument("--trace", default="automerge-paper")
    ap.add_argument("--patches", type=int, default=0,
                    help="northstar trace prefix (0 = FULL trace)")
    ap.add_argument("--batch", type=int, default=0,
                    help="identical-doc lanes (0 = per-config default: "
                         "northstar 512 at capacity <= 20992 else 256, "
                         "others 128)")
    ap.add_argument("--lmax", type=int, default=16)
    ap.add_argument("--engine", choices=ENGINE_CHOICES, default="rle")
    ap.add_argument("--groups", type=int, default=1,
                    help="northstar doc groups (rle engines; docs = "
                         "batch x groups in one launch)")
    ap.add_argument("--kevin-n", type=int, default=5_000_000,
                    help="kevin TPU prepend count (default = the full "
                         "reference workload, benches/yjs.rs:51-62)")
    ap.add_argument("--fuse-w", type=int, default=0,
                    help="fused burst width: kevin's split-batch "
                         "prepare (0 = default 64 full / 8 smoke) and "
                         "northstar's generalized fuse_steps pass "
                         "(0 = default 8); 1 = unfused everywhere")
    ap.add_argument("--merge-rows", action="store_true",
                    help="with a single --config: merge the produced "
                         "rows into --out (replacing that cfg_key's "
                         "prior rows) instead of print-only")
    ap.add_argument("--capacity", type=int, default=0,
                    help="rle engine run-row capacity (0 = default 20992 "
                         "for rle, 32768 for rle-hbm; rounded up to a "
                         "block_k multiple)")
    ap.add_argument("--block-k", type=int, default=512)
    ap.add_argument("--lanes-block-k", type=int, default=64,
                    help="K (rows per block) for the blocked per-lane "
                         "engines, configs 5/5r")
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU backend (logic check; implies "
                         "--interpret --smoke)")
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny workload sizes (CI / CPU logic checks)")
    ap.add_argument("--lax-check", action="store_true")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the subprocess device probe (tests)")
    ap.add_argument("--resume", action="store_true",
                    help="with --config all: keep clean rows already in "
                         "--out, re-run only missing/error configs")
    ap.add_argument("--out", default="BENCH_ALL.json")
    ap.add_argument("--check-ledger", action="store_true",
                    help="re-derive the committed cost ledger's cpu "
                         "cells (perf/COST_LEDGER.json) and exit "
                         "nonzero with named per-metric diffs on drift "
                         "— the wall-clock-free perf regression gate")
    ap.add_argument("--ledger", default="perf/COST_LEDGER.json",
                    help="ledger artifact for --check-ledger")
    ap.add_argument("--cells", default=None,
                    help="with --check-ledger: comma-separated cell "
                         "subset (default: every cpu cell)")
    args = ap.parse_args()

    if args.check_ledger:
        # CPU-only by construction (the whole point); never probes the
        # device backend.
        jax.config.update("jax_platforms", "cpu")
        raise SystemExit(run_ledger_check(args))

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        args.interpret = True
        args.smoke = True
        args.reps = 1
    elif not args.no_probe:
        probe_device()

    dev = init_devices()[0]
    log(f"device: {dev.platform} {dev.device_kind}")

    fns = {
        "northstar": cfg_northstar,
        "1": cfg_1_cpu,
        "2": cfg_2,
        "3": cfg_3,
        "4": cfg_4,
        "5": cfg_5,
        "5r": cfg_5_remote,
        "kevin": cfg_kevin,
        "serve": cfg_serve,
        "serve-lanes": cfg_serve_lanes,
        "sp": cfg_sp,
    }
    variant = (f"smoke={args.smoke},engine={args.engine},"
               f"batch={args.batch},groups={args.groups},"
               f"kevin_n={args.kevin_n},patches={args.patches},"
               f"fuse_w={args.fuse_w}")
    if args.config != "all":
        out = fns[args.config](args)
        rows = out if isinstance(out, list) else [out]
        if args.merge_rows:
            merge_config_rows(args.out, args.config, rows, variant,
                              smoke=args.smoke)
        print(json.dumps(rows[0]))
        if len(rows) > 1:
            log(json.dumps(rows[1:]))
        return

    sink = RowSink(args.out, resume=args.resume, variant=variant)
    # Priority order, not numeric order: if the tunnel drops mid-suite
    # (rounds 3-5 all lost device windows), the verdict-critical rows
    # must already be on disk — northstar first, then the
    # three-rounds-missing kevin, the unverified-lever configs, and the
    # CPU-capable serve/sp/1 configs last (they need no TPU at all).
    for key in ("northstar", "kevin", "4", "5r", "5", "2", "3",
                "serve", "serve-lanes", "sp", "1"):
        if key in sink.done_keys:
            log(f"=== config {key} === (resumed from {args.out})")
            continue
        log(f"=== config {key} ===")
        try:
            sink.add(key, fns[key](args))
        except Exception as e:  # keep the suite going; record the failure
            log(f"config {key} FAILED: {type(e).__name__}: {e}")
            sink.add(key, {"config": key,
                           "error": f"{type(e).__name__}: {e}"})
    log(f"wrote {len(sink.rows)} rows to {args.out}")
    star = next((r for r in sink.rows
                 if r.get("config", "").startswith("northstar")
                 and "error" not in r), sink.rows[0])
    print(json.dumps(star))


if __name__ == "__main__":
    main()

"""TCR-D00x: determinism hazards.

Four hazard shapes, each one a way a run stops being a pure function
of its seed:

- **TCR-D001** builtin ``hash()``: salted per process since Python 3.3
  (PYTHONHASHSEED), so any value derived from it differs across runs.
  Stable digests exist (``zlib.crc32``, ``hashlib``) — use those.
- **TCR-D002** order-sensitive set iteration: ``for x in {...}`` /
  ``set(...)``, or ``list``/``tuple``/``enumerate``/``join`` over a
  set expression.  Set iteration order is insertion-and-hash dependent;
  anything it feeds (serialization, trace emission, frame order) drifts
  across processes.  ``sorted(set(...))`` and order-free consumers
  (``len``/``sum``/``min``/``max``/``any``/``all``/set algebra) pass.
- **TCR-D003** unsorted directory walks: ``os.listdir`` / ``glob.glob``
  / ``iglob`` / ``Path.glob`` / ``iterdir`` / ``scandir`` return OS
  order — checkpoint-chain walks and obs-segment walks must wrap them
  in ``sorted(...)`` *directly* (a sort three lines later is invisible
  to the lint and to the next reader).
- **TCR-D004** unseeded global randomness: module-level ``random.*`` /
  ``np.random.*`` draws share interpreter-global state no seed in this
  repo controls.  Seeded instances (``random.Random(seed)``,
  ``np.random.default_rng(seed)``, ``RandomState(seed)``) pass.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from .tcrlint import FileContext, Finding, dotted_name

#: Consumers for which the argument's iteration order cannot matter.
ORDER_FREE = {"sorted", "len", "sum", "min", "max", "any", "all",
              "frozenset", "set"}

#: Order-sensitive consumers of an iterable argument.
ORDER_SENSITIVE = {"list", "tuple", "enumerate", "iter", "zip", "map"}

DIR_WALKS = {"os.listdir": "os.listdir", "glob.glob": "glob.glob",
             "glob.iglob": "glob.iglob", "os.scandir": "os.scandir"}
DIR_WALK_METHODS = {"glob", "rglob", "iterdir"}  # pathlib spellings

#: ``random`` module-level draw functions (not the Random class).
RANDOM_FNS = {"random", "randint", "randrange", "choice", "choices",
              "shuffle", "sample", "uniform", "gauss", "betavariate",
              "expovariate", "getrandbits", "randbytes", "triangular"}

SEEDED_NP = {"default_rng", "RandomState", "Generator", "SeedSequence",
             "PCG64", "Philox"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "set")


def _consumer(ctx: FileContext, node: ast.AST) -> Optional[str]:
    """Name of the call directly consuming ``node`` as an argument."""
    parent = ctx.parent_of(node)
    if (isinstance(parent, ast.Call) and node in parent.args
            and isinstance(parent.func, ast.Name)):
        return parent.func.id
    # "".join(set_expr) — attribute call consumer.
    if (isinstance(parent, ast.Call) and node in parent.args
            and isinstance(parent.func, ast.Attribute)):
        return parent.func.attr
    return None


def _check_set_order(ctx: FileContext, out: List[Finding]) -> None:
    for node in ast.walk(ctx.tree):
        if not _is_set_expr(node):
            continue
        parent = ctx.parent_of(node)
        # for x in {…} / comprehension iteration.
        if ((isinstance(parent, (ast.For, ast.AsyncFor))
             and parent.iter is node)
                or (isinstance(parent, ast.comprehension)
                    and parent.iter is node)):
            out.append(ctx.finding(
                "TCR-D002", node,
                "iteration over a set — order is hash/insertion "
                "dependent; wrap in sorted(...) before it can feed "
                "serialization, trace or frame order"))
            continue
        consumer = _consumer(ctx, node)
        if consumer in ORDER_SENSITIVE or consumer == "join":
            out.append(ctx.finding(
                "TCR-D002", node,
                f"{consumer}(<set>) materializes set order — wrap in "
                f"sorted(...) (order-free reducers like len/sum/min "
                f"pass unflagged)"))


def _check_dir_walks(ctx: FileContext, out: List[Finding]) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        label = DIR_WALKS.get(name or "")
        if (label is None and isinstance(node.func, ast.Attribute)
                and node.func.attr in DIR_WALK_METHODS
                and name is not None
                and name.split(".")[0] not in ("glob", "os")):
            # p.glob(...) / p.iterdir() — pathlib spelling; the root
            # guard keeps glob.glob from double-reporting here.
            label = f"<path>.{node.func.attr}"
        if label is None:
            continue
        if _consumer(ctx, node) == "sorted":
            continue
        out.append(ctx.finding(
            "TCR-D003", node,
            f"{label}(...) returns OS order — wrap the call directly "
            f"in sorted(...); checkpoint-chain and obs-segment walks "
            f"must not depend on filesystem enumeration order"))


def _check_randomness(ctx: FileContext, out: List[Finding]) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        parts = name.split(".")
        # random.<draw>() on the MODULE (seeded instances have a
        # non-"random" root: self.rng.choice, rng.random, ...).
        if (parts[0] == "random" and len(parts) == 2
                and parts[1] in RANDOM_FNS):
            out.append(ctx.finding(
                "TCR-D004", node,
                f"module-global random.{parts[1]}() is unseeded shared "
                f"state — draw from a random.Random(seed) instance"))
        elif parts[0] == "random" and parts[-1] == "seed":
            out.append(ctx.finding(
                "TCR-D004", node,
                "random.seed() mutates interpreter-global state — use "
                "a random.Random(seed) instance instead"))
        # np.random.<fn>() legacy global (np.random.default_rng(seed)
        # and the seeded constructors pass).
        elif (len(parts) >= 3 and parts[-2] == "random"
              and parts[0] in ("np", "numpy")
              and parts[-1] not in SEEDED_NP):
            out.append(ctx.finding(
                "TCR-D004", node,
                f"legacy numpy global RNG {name}() — use "
                f"np.random.default_rng(seed)"))
        elif (len(parts) >= 3 and parts[-2] == "random"
              and parts[0] in ("np", "numpy")
              and parts[-1] in ("default_rng", "RandomState")
              and not node.args and not node.keywords):
            out.append(ctx.finding(
                "TCR-D004", node,
                f"{name}() without a seed is entropy-seeded — pass an "
                f"explicit seed"))


def _check_hash(ctx: FileContext, out: List[Finding]) -> None:
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "hash"):
            out.append(ctx.finding(
                "TCR-D001", node,
                "builtin hash() is salted per process "
                "(PYTHONHASHSEED) — use zlib.crc32 or hashlib for any "
                "value that outlives the interpreter"))


def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    _check_hash(ctx, out)
    _check_set_order(ctx, out)
    _check_dir_walks(ctx, out)
    _check_randomness(ctx, out)
    return out

"""tcrlint — project-invariant static analysis (ISSUE 13 tentpole).

Every load-bearing contract in this repo — byte-identical logical
trace streams (PERF.md §14), exact cost-ledger re-derivation (§15),
YATA convergence, the hard-rejection codec discipline — is a
*determinism* contract, and determinism bugs are the kind tests catch
three PRs after they ship (a new wall-clock read leaking into a trace
field only fails when someone diffs two runs).  ``tcrlint`` moves the
enforcement to lint time: an AST pass over the package with one module
per check family, a committed allowlist for the audited intentional
sites, and a tier-1 gate so a violation fails CI with a file:line
finding, not a flaky fuzz seed later.

Check families (one module each):

==========================  ================================================
``checks_wallclock``        TCR-W001: wall-clock reads (``time.time``,
                            ``perf_counter``, ``datetime.now``) outside the
                            audited obs/perf sites — wall time may feed
                            obs ``"w"`` fields and perf probes, NEVER a
                            logical trace field, ledger metric, bench-row
                            logical field, or wire byte
``checks_determinism``      TCR-D001 builtin ``hash()`` (per-process salt),
                            TCR-D002 order-sensitive set iteration,
                            TCR-D003 unsorted ``os.listdir``/``glob`` walks,
                            TCR-D004 unseeded global randomness
``checks_schema``           TCR-S001 trace kinds missing from EVENT_SCHEMA,
                            TCR-S002 ledger metrics with unregistered
                            families, TCR-S003 schema field-set drift
                            without the matching version bump (pinned
                            fingerprints, ``SCHEMA_PINS.json``)
``checks_recompile``        TCR-R001 ``pallas_call`` / TCR-R002 ``jax.jit``
                            build sites that are neither lru-cached nor
                            module-level (the ``_build_call`` pattern) —
                            dynamic-shape retrace leaks
``checks_pyflakes``         TCR-F401 unused module-level imports — the
                            built-in fallback for the ruff baseline when
                            ruff is not installed
==========================  ================================================

**v2 — interprocedural dataflow families** (ISSUE 15): ``dataflow.py``
grows per-function CFGs, reaching definitions, alias closures and
one-level call summaries over the stdlib ``ast``; four flow-aware
check families consume them:

==========================  ================================================
``checks_pipeline``         TCR-P001: dispatch-buffer escape — a host
                            write that may alias a buffer handed to
                            ``backend.apply``/the flat jits before its
                            staged sync (the static twin of the PR-12
                            runtime aliasing sanitizer, which stays on
                            as defense-in-depth)
``checks_mirror``           TCR-M001 a device-state write site without
                            its paired host-mirror update (the PR-13
                            capacity-contract model), TCR-M002 a serve
                            backend class with device writes missing
                            from ``MIRROR_CONTRACTS``
``checks_shape``            TCR-K001 a static call-site shape off the
                            declared bucket series, TCR-K002 series
                            drift vs the pinned ``SHAPE_CONTRACTS.json``
                            (refreshed via ``--update-pins``)
``checks_claims``           TCR-C001 a cited ``perf/`` artifact that
                            does not exist, TCR-C002 a superseded
                            ``when_up_r*.sh`` in README's claims table,
                            TCR-C003 a "measured" claims row with no
                            committed source
==========================  ================================================

CLI: ``python -m text_crdt_rust_tpu.analysis.lint`` (exit 1 with
file:line-named findings; ``--changed`` for the incremental tier-1
mode, content-hash cached under ``.tcrlint_cache/``).  Allowlist:
``LINT_ALLOWLIST.json`` next to this file — every entry names
(check, path, scope) plus a one-line justification, and a stale entry
(matching nothing) is itself a finding, so the allowlist can only
shrink or be re-justified.
"""
from .tcrlint import (  # noqa: F401
    ALLOWLIST_PATH,
    PINS_PATH,
    Finding,
    changed_files,
    load_allowlist,
    run_lint,
)

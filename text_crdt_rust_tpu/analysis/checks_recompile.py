"""TCR-R00x: recompile hazards — uncached kernel/jit build sites.

A ``pl.pallas_call`` or ``jax.jit(lambda ...)`` constructed inside a
plain function builds a FRESH traced program every call: on CPU
interpret that re-trace dominates fixed-shape suites (the PR-6 finding
that took tier-1 from 779s to 712s when ``ops/rle.py`` adopted the
``_build_call`` pattern), and on TPU it is a 5-30s Mosaic recompile
per call — the dynamic-shape leak the serve batcher's step buckets
exist to prevent.  The sanctioned shapes are:

- a module-level ``jax.jit`` (built once at import), or a ``@jax.jit``
  / ``@partial(jax.jit, ...)`` decorator (jax caches per shape);
- a build site inside a function decorated ``@functools.lru_cache``
  keyed by the static shape tuple — the ``_build_call`` pattern every
  shipped kernel module uses;
- an audited one-shot builder, allowlisted with its justification.

**TCR-R001** flags uncached ``pallas_call`` sites, **TCR-R002**
uncached ``jax.jit(...)`` call sites (decorator usage never flags).
"""
from __future__ import annotations

import ast
from typing import List

from .tcrlint import FileContext, Finding, dotted_name

CACHING_DECORATORS = {"lru_cache", "cache"}


def _is_cached(ctx: FileContext, node: ast.AST) -> bool:
    """True when any enclosing function is lru-cached."""
    for fn in ctx.enclosing_functions(node):
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = dotted_name(target) or ""
            if name.split(".")[-1] in CACHING_DECORATORS:
                return True
    return False


def _in_decorator(ctx: FileContext, node: ast.AST) -> bool:
    """True when ``node`` sits inside a decorator expression."""
    cur = node
    parent = ctx.parent_of(cur)
    while parent is not None:
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            return cur in parent.decorator_list
        cur, parent = parent, ctx.parent_of(parent)
    return False


def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        leaf = name.split(".")[-1]
        if leaf == "pallas_call":
            if not _is_cached(ctx, node):
                out.append(ctx.finding(
                    "TCR-R001", node,
                    "pallas_call built outside an lru-cached builder — "
                    "every kernel build site must be shape-keyed "
                    "(@functools.lru_cache on a _build_call(shape...) "
                    "function) or it re-traces/recompiles per call"))
        elif name in ("jax.jit", "jit") and leaf == "jit":
            if _in_decorator(ctx, node):
                continue  # @partial(jax.jit, ...) / @jax.jit — cached by jax
            if not ctx.enclosing_functions(node):
                continue  # module level: built once at import
            if not _is_cached(ctx, node):
                out.append(ctx.finding(
                    "TCR-R002", node,
                    "jax.jit(...) constructed inside an uncached "
                    "function — each call builds a fresh jit object "
                    "that re-traces; cache the build by static shape "
                    "(the _build_call pattern) or allowlist the "
                    "audited one-shot builder"))
    return out

"""TCR-P001: dispatch-buffer escape analysis — the static twin of the
runtime pipeline aliasing sanitizer (ISSUE 15).

The pipelined tick (PR 11) made a whole class of bug *possible*: the
batcher hands ``stack_ops``-built op tensors to ``backend.apply`` and
lets the device step stay in flight through the next host tick — and on
CPU, JAX's zero-copy conversion means the compiled step reads the SAME
numpy buffers host code still holds.  A host write into any of those
buffers between dispatch and that entry's staged sync silently corrupts
the in-flight step.  PR 12's sanitizer catches this at RUNTIME by
CRC-fingerprinting the dispatched tensors; this check catches it at
LINT time by escape analysis:

1. a **dispatch site** is a call that hands buffers to the device
   asynchronously — ``<...backend...>.apply(stream)``, the flat
   engine's module-level jits (``_apply_ops``/``_apply_ops_batch``/
   ``apply_prefill_delta``/``_scatter_delta*``) and the blocked kernel
   builder (``make_replayer_lanes_mixed_blocked``);
2. the dispatched buffer's **alias closure** (``dataflow.
   alias_closure``: reaching definitions chased through the
   pad/stack/concat/asarray family) is tainted;
3. any statement **reachable after the dispatch without passing a
   sync** (``barrier``/``block_until_ready``/``flush_pipeline``/
   ``_sync_entry``/``_sync_shard_inflight``/``_block_token`` — sync
   statements kill propagation in the CFG walk, loop back edges
   included) that writes THROUGH a tainted name is a finding:
   subscript stores and aug-assigns on tainted roots, ndarray in-place
   mutator methods, ``np.copyto``-family calls, or a call handing a
   tainted buffer to a summarized function that mutates that parameter
   (one interprocedural level, ``dataflow.summarize_module``).

Calibrations that keep the clean tree quiet (each one deliberate):
``self``-rooted state is excluded (that discipline is TCR-M's); a
tainted name whose every reaching definition constructs a fresh host
container (dict/list literal) may take subscript stores — that rebinds
a slot, not array storage; unknown callees are assumed alias-pure (the
one-level summary horizon — the runtime sanitizer stays on as
defense-in-depth for exactly what a lint cannot see).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .dataflow import (
    MUTATOR_FNS,
    MUTATOR_METHODS,
    FnSummary,
    FunctionFlow,
    call_leaf,
    expr_roots,
    iter_functions,
    stmt_calls,
)
from .tcrlint import FileContext, Finding

CHECK = "TCR-P001"

#: Module-level / attribute-leaf callables that enqueue device work on
#: their tensor arguments.
DISPATCH_FNS = {"_apply_ops", "_apply_ops_batch", "apply_prefill_delta",
                "_scatter_delta", "_scatter_delta_batch",
                "make_replayer_lanes_mixed_blocked"}

#: ``<recv>.apply(stream)`` dispatches when the receiver smells like a
#: lane backend (the serve surface).  Receiver-name heuristic on
#: purpose: ``mirror.apply`` (net/session's synchronous DeviceMirror)
#: and pandas-style ``.apply`` must not taint.
DISPATCH_METHOD = "apply"
DISPATCH_RECEIVERS = ("backend",)

#: Calls that complete in-flight device work: the staged sync family.
SYNC_CALLS = {"barrier", "block_until_ready", "flush_pipeline",
              "_sync_entry", "_sync_shard_inflight", "_block_token",
              "sync_all"}


def _is_dispatch(call: ast.Call) -> Optional[List[ast.AST]]:
    """The dispatched-buffer argument expressions when ``call`` is a
    dispatch site, else None."""
    leaf = call_leaf(call)
    if leaf in DISPATCH_FNS:
        args = list(call.args) + [k.value for k in call.keywords]
        return args
    if (leaf == DISPATCH_METHOD
            and isinstance(call.func, ast.Attribute)):
        recv = call.func.value
        recv_name = ""
        if isinstance(recv, ast.Name):
            recv_name = recv.id
        elif isinstance(recv, ast.Attribute):
            recv_name = recv.attr
        if any(h in recv_name.lower() for h in DISPATCH_RECEIVERS):
            return list(call.args)
    return None


def _is_sync_stmt(stmt: ast.stmt) -> bool:
    """Only a statement that ITSELF performs the sync call blocks
    propagation — compound statements contribute their headers alone
    (``_own_exprs``), so an ``if``/``for`` that merely CONTAINS a sync
    in one branch does not mask mutations on its other branches (the
    sync statements inside are their own CFG nodes and block their own
    successors)."""
    return any(call_leaf(c) in SYNC_CALLS for c in _own_calls(stmt))


def _own_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a statement evaluates ITSELF — compound
    statements (For/If/While/With/Try) contribute only their headers,
    their bodies are separate CFG statements (walking the whole subtree
    here would double-report every nested mutation at the header)."""
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.While, ast.If)):
        return [stmt.test]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Try)):
        return []
    return [stmt]


def _own_calls(stmt: ast.stmt) -> List[ast.Call]:
    out: List[ast.Call] = []
    for expr in _own_exprs(stmt):
        out.extend(stmt_calls(expr))
    return out


def _subscript_base(node: ast.AST) -> Optional[ast.AST]:
    """Innermost non-subscript base of a subscript chain."""
    if not isinstance(node, ast.Subscript):
        return None
    cur = node.value
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    return cur


def _mutations(stmt: ast.stmt, taint: Set[str], containers: Set[str],
               summaries: Dict[str, FnSummary]) -> List[ast.AST]:
    """Nodes in ``stmt`` that write through a tainted buffer."""
    hits: List[ast.AST] = []
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, ast.Subscript):
            base = _subscript_base(t)
            # a plain-name container slot store rebinds, array-safe;
            # anything deeper (attr/subscript chains) writes storage.
            if (isinstance(base, ast.Name) and base.id in containers
                    and isinstance(t.value, ast.Name)):
                continue
            if base is not None and expr_roots(base) & taint:
                hits.append(t)
        elif isinstance(t, ast.Name) and isinstance(stmt, ast.AugAssign):
            if t.id in taint and t.id not in containers:
                hits.append(t)
    for call in _own_calls(stmt):
        leaf = call_leaf(call)
        if (leaf in MUTATOR_METHODS
                and isinstance(call.func, ast.Attribute)):
            recv = call.func.value
            roots = expr_roots(recv)
            if roots & taint and not roots <= containers:
                hits.append(call)
                continue
        if leaf in MUTATOR_FNS and call.args:
            if expr_roots(call.args[0]) & taint:
                hits.append(call)
                continue
        summary = summaries.get(leaf)
        if summary is not None and summary.mutated_params:
            for idx, arg in enumerate(call.args):
                if summary.mutates(idx) and expr_roots(arg) & taint:
                    hits.append(call)
                    break
            for kw in call.keywords:
                if (kw.arg in summary.mutated_params
                        and expr_roots(kw.value) & taint):
                    hits.append(call)
                    break
    return hits


def check(ctx: FileContext,
          summaries: Optional[Dict[str, FnSummary]] = None
          ) -> List[Finding]:
    from .dataflow import summarize_module

    # This module's own defs overlay the cross-module summary map: a
    # same-file helper is the nearest (and most precise) resolution of
    # a leaf-name callee.
    merged = dict(summaries or {})
    merged.update(summarize_module(ctx.tree))
    summaries = merged
    out: List[Finding] = []
    for qual, fn in iter_functions(ctx.tree):
        # cheap pre-filter: any dispatch call at all?
        disp_calls = [c for c in stmt_calls(fn)
                      if _is_dispatch(c) is not None]
        if not disp_calls:
            continue
        flow = FunctionFlow(fn)
        sync_idx = {i for i, s in enumerate(flow.stmts)
                    if _is_sync_stmt(s)}
        reported: Set[int] = set()
        for call in disp_calls:
            args = _is_dispatch(call)
            at = flow.stmt_of(call, ctx.parents)
            if at is None or not args:
                continue
            taint, containers = flow.alias_closure(args, at)
            if not taint:
                continue
            # the dispatch statement itself runs before the flight
            # starts; everything CFG-reachable after it (minus sync-
            # killed paths) races the in-flight step.
            reach = flow.reachable_from(at, blocked=sync_idx)
            # Forward alias propagation: a POST-dispatch binding whose
            # RHS may share tainted storage (``col = stacked.pos``) is
            # itself tainted — small fixpoint over the reachable set.
            for _round in range(5):
                grew = False
                for i in sorted(reach):
                    stmt = flow.stmts[i]
                    if not isinstance(stmt, ast.Assign):
                        continue
                    if not expr_roots(stmt.value) & taint:
                        continue
                    for t in stmt.targets:
                        for name in sorted(
                                FunctionFlow._bound_names_of_target(t)):
                            if name not in taint:
                                taint.add(name)
                                grew = True
                if not grew:
                    break
            for i in sorted(reach):
                if i in reported:
                    continue
                hits = _mutations(flow.stmts[i], taint, containers,
                                  summaries)
                if hits:
                    reported.add(i)
                    out.append(ctx.finding(
                        CHECK, hits[0],
                        f"host write into a buffer dispatched at line "
                        f"{getattr(call, 'lineno', '?')} "
                        f"({qual}) may race the in-flight device step "
                        f"— move the write past the staged sync, copy "
                        f"the buffer before dispatch, or justify an "
                        f"allowlist grant (the runtime sanitizer "
                        f"would raise PipelineAliasingError here)"))
    return out

"""TCR-C00x: perf-claims consistency — docs vs committed artifacts
(ISSUE 15).

The repo's evidence discipline says a measured number is only a claim
when its artifact is committed (README "Measured vs pending silicon",
PERF.md cost-model sections, the ``perf/*_r*.json`` probes).  Claims
rot structurally: a probe JSON gets superseded and renamed, a
recovery-watcher script (``when_up_r*.sh``) gets replaced by the next
round's, and the prose keeps citing the old name.  Nothing executes
markdown, so no test catches it — a docs cross-check does:

- **TCR-C001** — a ``perf/<file>`` reference in README.md / PERF.md
  that does not exist on disk: the cited evidence is gone (deleted,
  renamed, or never committed).
- **TCR-C002** — inside README's "Measured vs pending silicon" claims
  section ONLY, a reference to a superseded ``perf/when_up_r<K>.sh``
  when a higher-round watcher exists: each round's watcher supersedes
  the last (it replays the whole re-record chain), so a claims row
  pointing at an old one advertises a recovery path that will not
  re-record today's rows.  Historical narrative elsewhere (PERF.md's
  append-only sections, README's round-by-round notes) legitimately
  names its era's script and is exempt by design.
- **TCR-C003** — a row of that claims table whose status column says
  "measured" but whose row cites NO committed artifact (no existing
  ``perf/*`` file, ``BENCH_ALL.json`` or ``COST_LEDGER.json``): a
  measured number with no committed source.

Pure project-level pass (markdown is not walked by the .py file
iterator); temp trees without the doc files skip silently.
"""
from __future__ import annotations

import os
import re
from typing import List, Optional, Tuple

from .tcrlint import Finding

DOC_FILES = ("README.md", "PERF.md")

CLAIMS_HEADING = "## Measured vs pending silicon"

_PERF_REF = re.compile(r"perf/[A-Za-z0-9_\-]+\.(?:json|sh|py|log)")
_WHEN_UP = re.compile(r"perf/when_up_r(\d+)[a-z]?\.sh")
_ARTIFACT = re.compile(r"(perf/[A-Za-z0-9_\-]+\.(?:json|log)|"
                       r"BENCH_ALL\.json|COST_LEDGER\.json)")


def _claims_region(lines: List[str]) -> Optional[Tuple[int, int]]:
    """[start, end) line span (0-based) of the README claims section."""
    start = None
    for i, line in enumerate(lines):
        if start is None:
            if line.strip() == CLAIMS_HEADING:
                start = i
        elif line.startswith("## "):
            return (start, i)
    return (start, len(lines)) if start is not None else None


def _latest_when_up(root: str) -> Optional[int]:
    perf = os.path.join(root, "perf")
    if not os.path.isdir(perf):
        return None
    best = None
    for fn in sorted(os.listdir(perf)):
        m = re.fullmatch(r"when_up_r(\d+)[a-z]?\.sh", fn)
        if m:
            k = int(m.group(1))
            best = k if best is None else max(best, k)
    return best


def check_claims(root: str) -> List[Finding]:
    out: List[Finding] = []
    latest = _latest_when_up(root)
    for doc in DOC_FILES:
        path = os.path.join(root, doc)
        if not os.path.exists(path):
            continue
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        # C001: every perf/ file reference must exist.
        for i, line in enumerate(lines):
            for m in _PERF_REF.finditer(line):
                if not os.path.exists(os.path.join(root, m.group(0))):
                    out.append(Finding(
                        check="TCR-C001", path=doc, line=i + 1,
                        scope="<doc>",
                        message=f"cites {m.group(0)} which does not "
                                f"exist — the evidence artifact was "
                                f"renamed, superseded or never "
                                f"committed; fix the reference or "
                                f"commit the artifact"))
        if doc != "README.md":
            continue
        region = _claims_region(lines)
        if region is None:
            continue
        start, end = region
        for i in range(start, end):
            line = lines[i]
            # C002: superseded recovery watcher inside the claims table.
            if latest is not None:
                for m in _WHEN_UP.finditer(line):
                    if int(m.group(1)) < latest:
                        out.append(Finding(
                            check="TCR-C002", path=doc, line=i + 1,
                            scope="<doc>",
                            message=f"claims row cites superseded "
                                    f"{m.group(0)} — the current "
                                    f"recovery watcher is "
                                    f"perf/when_up_r{latest}.sh (each "
                                    f"round's watcher replays the "
                                    f"whole re-record chain); point "
                                    f"the claim at it"))
            # C003: a "measured" row must cite a committed artifact.
            cells = [c.strip() for c in line.split("|")]
            if len(cells) < 4 or not line.lstrip().startswith("|"):
                continue
            status = cells[2].lower()
            if "measured" not in status or cells[1] in ("claim", "---"):
                continue
            cited = [m.group(1) for m in _ARTIFACT.finditer(line)]
            committed = [c for c in cited
                         if os.path.exists(os.path.join(root, c))]
            if not committed:
                out.append(Finding(
                    check="TCR-C003", path=doc, line=i + 1,
                    scope="<doc>",
                    message=f"claims row {cells[1][:60]!r} is marked "
                            f"measured but cites no committed "
                            f"artifact (perf/*.json, perf/*.log, "
                            f"BENCH_ALL.json or COST_LEDGER.json) — "
                            f"commit the source or mark the row "
                            f"pending"))
    return out

"""TCR-X001: no silent exception swallowing on the serving path.

The serving stack's error discipline (ISSUE 3, re-affirmed by every
robustness PR since): a fault is either **re-raised** (or converted to
a typed error), **counted** (a metrics counter or an explicit tally),
or **reported** (a trace event / flight-recorder notification).  A
``try/except`` under ``serve/`` or ``net/`` that does none of these is
a black hole — the byzantine loadgen class and the crash harness both
exist to prove faults are LOUD, and a swallowing handler un-proves it
one call site at a time.

A handler passes when its body (recursively) contains any of:

- a ``raise`` statement (re-raise or typed conversion);
- a notifier call: ``.incr`` / ``.hiwater`` / ``.sample`` / ``.event``
  / ``.on_failure`` / ``.on_divergence`` (the metrics registry, the
  tracer, and the flight recorder — the repo's three reporting
  surfaces), a ``logging``-style ``.warning``/``.error``/
  ``.exception``, or a rejection recorder (any method whose name
  contains ``reject`` — the router's flow-span rejection path);
- a typed-error CONSTRUCTION (a call to a ``*Error`` name) — the
  by-value conversion idiom of scanners that return ``(records,
  typed_error)`` instead of raising mid-stream;
- an augmented assignment (``stats["x"] += 1``, ``self.rejections += 1``
  — the inline-tally idiom recovery and the loadgen use).

Anything else is a finding; deliberate swallows (a filename-pattern
filter skipping foreign files, a harness catching its own injected
kill signal) are granted in ``LINT_ALLOWLIST.json`` with a
justification, like every other check.
"""
from __future__ import annotations

import ast
from typing import List

from .tcrlint import FileContext, Finding

#: Only the serving path carries the loud-fault contract; ops/ kernels
#: and analysis tooling have their own disciplines.
TARGET_DIRS = ("/serve/", "/net/")

#: Method names whose call counts as "the fault was reported".
NOTIFY_CALLS = {"incr", "hiwater", "sample", "event", "on_failure",
                "on_divergence", "warning", "error", "exception"}


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.AugAssign)):
            return True
        if not isinstance(node, ast.Call):
            continue
        if (isinstance(node.func, ast.Attribute)
                and (node.func.attr in NOTIFY_CALLS
                     or "reject" in node.func.attr)):
            return True
        # Typed conversion by value: constructing SomethingError to
        # hand upward (the scan() ``(records, error)`` idiom).
        if (isinstance(node.func, ast.Name)
                and node.func.id.endswith("Error")):
            return True
    return False


def _caught_name(handler: ast.ExceptHandler) -> str:
    if handler.type is None:
        return "<bare except>"
    try:
        return ast.unparse(handler.type)
    except Exception:
        return "<exception>"


def check(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    if not any(d in "/" + ctx.rel for d in TARGET_DIRS):
        return out
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _handler_reports(node):
            continue
        out.append(ctx.finding(
            "TCR-X001", node,
            f"except {_caught_name(node)}: handler neither re-raises, "
            f"raises a typed error, counts, nor notifies the "
            f"tracer/recorder — a swallowed fault on the serving path "
            f"(grant deliberate swallows in the allowlist)"))
    return out

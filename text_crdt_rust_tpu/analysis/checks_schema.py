"""TCR-S00x: schema-drift cross-checks.

The repo's versioned-artifact discipline (modeled on Automerge's binary
format, PAPERS.md) says: every emitted kind/metric/row validates
against a declared schema, and a schema change ships with a version
bump.  Two of those cross-checks are per-call-site:

- **TCR-S001** every string-literal kind passed to ``.event(...)`` /
  ``.span(...)`` must exist in ``obs.trace.EVENT_SCHEMA`` — an emit
  site for an undeclared kind would raise at runtime *if* that path
  runs in tests; the lint catches it before any path runs.
- **TCR-S002** every string-literal family passed to
  ``obs.ledger.metric(value, family)`` must be a registered
  ``METRIC_FAMILIES`` member.

And one is project-level (**TCR-S003**): the *field sets* of the
schema surfaces — ``EVENT_SCHEMA``, bench's ``ROW_SCHEMA``,
``METRIC_FAMILIES``, the codec's frame kinds — are fingerprinted
(CRC32 over the AST of the literal, so comments and formatting don't
churn it) and pinned in ``SCHEMA_PINS.json`` together with their
version constants.  Editing a surface without touching its version is
a finding; bumping the version requires re-pinning via
``--update-pins`` in the same PR, which puts the new fingerprint in
the diff where a reviewer sees it.
"""
from __future__ import annotations

import ast
import json
import os
import zlib
from typing import List, Optional

from .tcrlint import FileContext, Finding

#: The pinned schema surfaces: where each field-set literal lives and
#: which version constant must move when it does.
SURFACES = (
    {"name": "trace-events", "file": "text_crdt_rust_tpu/obs/trace.py",
     "literals": ("EVENT_SCHEMA",), "version": "TRACE_SCHEMA_VERSION"},
    {"name": "bench-row", "file": "bench.py",
     "literals": ("ROW_SCHEMA",), "version": "ROW_SCHEMA_VERSION"},
    {"name": "ledger-families",
     "file": "text_crdt_rust_tpu/obs/ledger.py",
     "literals": ("METRIC_FAMILIES",), "version": "LEDGER_SCHEMA_VERSION"},
    {"name": "wire-kinds", "file": "text_crdt_rust_tpu/net/codec.py",
     "literals": ("MAGIC", "_FRAME_VERSIONS", "KIND_TXNS", "KIND_REQUEST",
                  "KIND_DIGEST", "KIND_TXNS_MUX"),
     "version": "FRAME_VERSION_COLUMNAR"},
)


def _trace_kinds() -> set:
    from ..obs.trace import EVENT_SCHEMA

    return set(EVENT_SCHEMA)


def _ledger_families() -> set:
    from ..obs.ledger import METRIC_FAMILIES

    return set(METRIC_FAMILIES)


def check(ctx: FileContext) -> List[Finding]:
    kinds = _trace_kinds()
    families = _ledger_families()
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr in ("event", "span") and node.args:
            arg = node.args[0]
            if (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                    and arg.value not in kinds):
                out.append(ctx.finding(
                    "TCR-S001", node,
                    f"trace kind {arg.value!r} is not declared in "
                    f"obs.trace.EVENT_SCHEMA — declare its required "
                    f"fields (and bump TRACE_SCHEMA_VERSION if the "
                    f"stream contract changes)"))
    # metric(value, "family") — imported bare or as ledger.metric.
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname != "metric" or len(node.args) < 2:
            continue
        fam = node.args[1]
        if (isinstance(fam, ast.Constant) and isinstance(fam.value, str)
                and fam.value not in families):
            out.append(ctx.finding(
                "TCR-S002", node,
                f"ledger metric family {fam.value!r} is not registered "
                f"in obs.ledger.METRIC_FAMILIES"))
    return out


# -- TCR-S003: pinned schema fingerprints -------------------------------------


def _module_assign(tree: ast.Module, name: str) -> Optional[ast.AST]:
    """The value node of a module-level ``name = <literal>`` (or
    annotated) assignment."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return node.value
        elif (isinstance(node, ast.AnnAssign)
              and isinstance(node.target, ast.Name)
              and node.target.id == name and node.value is not None):
            return node.value
    return None


def surface_state(root: str, surface: dict) -> Optional[dict]:
    """Current ``{"version", "fingerprint", "line"}`` of one surface;
    None when its file is absent under ``root`` (temp trees)."""
    path = os.path.join(root, surface["file"])
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=surface["file"])
    dumps: List[str] = []
    line = 1
    for lit in surface["literals"]:
        value = _module_assign(tree, lit)
        if value is None:
            dumps.append(f"<missing:{lit}>")
        else:
            dumps.append(ast.dump(value))
            line = value.lineno
    version_node = _module_assign(tree, surface["version"])
    version = (version_node.value
               if isinstance(version_node, ast.Constant) else None)
    fp = zlib.crc32("\n".join(dumps).encode()) & 0xFFFFFFFF
    return {"version": version, "fingerprint": fp, "line": line}


def check_pins(root: str, pins_path: str,
               update: bool = False) -> List[Finding]:
    """Compare every surface's live fingerprint/version against the
    committed pins; with ``update=True`` rewrite the pins instead."""
    present = [(s, surface_state(root, s)) for s in SURFACES]
    present = [(s, st) for s, st in present if st is not None]
    if not present:
        return []  # a temp tree with no schema surfaces: nothing to pin
    pins_rel = os.path.relpath(pins_path, root).replace(os.sep, "/")
    if update:
        pins = {s["name"]: {"version": st["version"],
                            "fingerprint": st["fingerprint"],
                            "file": s["file"]}
                for s, st in present}
        with open(pins_path, "w") as f:
            json.dump({"comment":
                       "tcrlint TCR-S003 schema pins — regenerate with "
                       "python -m text_crdt_rust_tpu.analysis.lint "
                       "--update-pins (commit alongside any schema "
                       "change + version bump)",
                       "pins": pins}, f, indent=1, sort_keys=True)
            f.write("\n")
        return []
    if not os.path.exists(pins_path):
        return [Finding(
            check="TCR-S003", path=pins_rel, line=1, scope="<pins>",
            message="schema pins file missing — run the lint with "
                    "--update-pins and commit it")]
    with open(pins_path) as f:
        pins = json.load(f)["pins"]
    out: List[Finding] = []
    for s, st in present:
        pin = pins.get(s["name"])
        if pin is None:
            out.append(Finding(
                check="TCR-S003", path=pins_rel, line=1, scope="<pins>",
                message=f"surface {s['name']!r} has no pin — run "
                        f"--update-pins and commit the diff"))
            continue
        if st["fingerprint"] == pin["fingerprint"]:
            # Version moved with no field change is still a re-pin
            # moment (the pin records the pairing).
            if st["version"] != pin["version"]:
                out.append(Finding(
                    check="TCR-S003", path=s["file"], line=st["line"],
                    scope="<module>",
                    message=f"{s['name']}: {s['version']} bumped "
                            f"{pin['version']} -> {st['version']} — "
                            f"refresh the pin (--update-pins) in this "
                            f"same change"))
            continue
        if st["version"] == pin["version"]:
            out.append(Finding(
                check="TCR-S003", path=s["file"], line=st["line"],
                scope="<module>",
                message=f"{s['name']}: field set changed "
                        f"(fingerprint {pin['fingerprint']} -> "
                        f"{st['fingerprint']}) but {s['version']} is "
                        f"still {st['version']} — bump the version and "
                        f"re-pin (--update-pins)"))
        else:
            out.append(Finding(
                check="TCR-S003", path=s["file"], line=st["line"],
                scope="<module>",
                message=f"{s['name']}: schema and version both moved "
                        f"({pin['version']} -> {st['version']}) — "
                        f"refresh the pin (--update-pins) so the new "
                        f"pairing is committed"))
    return out

"""TCR-F401: unused module-level imports (the ruff fallback).

The ruff baseline (``pyproject.toml [tool.ruff]``) is the third-party
half of the tier-1 lint gate, but this container may not ship ruff and
the gate must not silently weaken when it is absent — so the most
load-bearing pyflakes rule (F401, unused imports: the one that hides
real dead code and stale dependencies) has a built-in AST
implementation.  When ruff IS installed the CLI runs it too; this
module keeps the floor either way.

Scope is deliberately narrow to stay false-positive-free:

- module-level ``import``/``from import`` only (function-local imports
  are often lazy-load-by-design here — jax, dataclasses — and cheap to
  eyeball);
- ``__init__.py`` files are exempt (re-export surface);
- a ``# noqa`` on the import line is honored (ruff parity);
- ``__all__`` membership counts as a use.
"""
from __future__ import annotations

import ast
from typing import Dict, List

from .tcrlint import FileContext, Finding


def _used_names(tree: ast.Module) -> set:
    used: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # the chain root is collected via its Name node anyway
            pass
    # __all__ = ["name", ...] re-exports.
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)):
            for elt in ast.walk(node.value):
                if (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    used.add(elt.value)
    return used


def check(ctx: FileContext) -> List[Finding]:
    if ctx.rel.endswith("__init__.py"):
        return []
    binds: Dict[str, ast.AST] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                binds[name] = node
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                binds[alias.asname or alias.name] = node
    if not binds:
        return []
    used = _used_names(ctx.tree)
    out: List[Finding] = []
    for name, node in sorted(binds.items(),
                             key=lambda kv: kv[1].lineno):
        if name in used:
            continue
        line = ctx.lines[node.lineno - 1] if (
            node.lineno - 1 < len(ctx.lines)) else ""
        if "noqa" in line:
            continue
        out.append(ctx.finding(
            "TCR-F401", node,
            f"{name!r} imported but unused"))
    return out

"""The tcrlint engine: file walking, AST context, allowlist, runner.

Design points (shared by every check module):

- **Findings name file:line + check id** — the CLI prints
  ``path:line: TCR-X000 message`` and exits 1, so a violation reads
  like a compiler error, not a style nag.
- **The allowlist is scoped, not line-pinned.**  Entries match
  ``(check, path, scope)`` where scope is the dotted enclosing
  class/function chain (``ContinuousBatcher.tick``; ``<module>`` for
  module level, ``*`` for the whole file).  Line numbers churn on every
  edit; scopes only churn when the audited code actually moves — and a
  *stale* entry (matching nothing anymore) is itself a finding
  (TCR-A001), so dead grants cannot accumulate.
- **Deterministic by construction**: files walk sorted, findings sort
  by (path, line, check) — the lint's own output is diffable, which is
  what lets the self-test pin exact findings.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Committed allowlist + schema pins live next to the engine.
ALLOWLIST_PATH = os.path.join(os.path.dirname(__file__),
                              "LINT_ALLOWLIST.json")
PINS_PATH = os.path.join(os.path.dirname(__file__), "SCHEMA_PINS.json")

#: Directories never walked (build junk; native/ holds generated .so
#: trees; spool dirs can appear under a dev checkout).
SKIP_DIRS = {"__pycache__", ".git", "build", ".pytest_cache", "node_modules"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding; sorts by (path, line, check) for stable output."""

    check: str       # "TCR-W001"
    path: str        # root-relative, forward slashes
    line: int
    scope: str       # dotted enclosing defs, "<module>" at top level
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.check} {self.message}"

    def sort_key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.check)


class FileContext:
    """Parsed module + the scope/parent maps the checks share."""

    def __init__(self, rel: str, source: str, tree: ast.Module):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.scopes: Dict[ast.AST, str] = {}
        self._annotate()

    def _annotate(self) -> None:
        def walk(node: ast.AST, scope: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                self.scopes[child] = ".".join(scope) or "<module>"
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    walk(child, scope + [child.name])
                else:
                    walk(child, scope)

        walk(self.tree, [])

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(node, "<module>")

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def enclosing_functions(self, node: ast.AST) -> List[ast.FunctionDef]:
        """Innermost-first chain of enclosing function defs."""
        out: List[ast.FunctionDef] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def finding(self, check: str, node: ast.AST, message: str) -> Finding:
        return Finding(check=check, path=self.rel,
                       line=getattr(node, "lineno", 1),
                       scope=self.scope_of(node), message=message)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# -- file walking -------------------------------------------------------------


def iter_py_files(root: str, paths: Optional[Sequence[str]] = None
                  ) -> Iterable[str]:
    """Root-relative .py paths under ``paths`` (files or directories),
    sorted — the lint practices the determinism it preaches."""
    targets = [os.path.join(root, p) for p in paths] if paths else [root]
    out: List[str] = []
    for target in targets:
        if os.path.isfile(target):
            out.append(os.path.relpath(target, root))
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    return sorted(dict.fromkeys(p.replace(os.sep, "/") for p in out))


# -- allowlist ----------------------------------------------------------------


def load_allowlist(path: str = ALLOWLIST_PATH) -> List[dict]:
    """Entries ``{"check", "path", "scope", "why"}``; ``scope`` ``"*"``
    grants the whole file.  Every field is required — an unjustified
    grant is refused at load time."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data["allow"] if isinstance(data, dict) else data
    for e in entries:
        missing = [k for k in ("check", "path", "scope", "why")
                   if not e.get(k)]
        if missing:
            raise ValueError(
                f"allowlist entry {e!r} missing {missing} — every grant "
                f"needs a check id, a path, a scope and a justification")
    return entries


def _entry_matches(entry: dict, finding: Finding) -> bool:
    if entry["check"] != finding.check or entry["path"] != finding.path:
        return False
    if entry["scope"] == "*":
        return True
    # Exact scope, or a grant on an enclosing scope ("Cls" covers
    # "Cls.method"): audits grant functions or whole classes, and a
    # nested helper inside an audited function is the same audit.
    return (finding.scope == entry["scope"]
            or finding.scope.startswith(entry["scope"] + "."))


def apply_allowlist(findings: List[Finding], entries: List[dict],
                    allowlist_rel: str,
                    check_stale: bool = True) -> List[Finding]:
    """Filter allowlisted findings; a stale entry (granting nothing this
    run) becomes a TCR-A001 finding on the allowlist file itself.
    ``check_stale=False`` for partial-tree lints, where an unused grant
    just means its file wasn't walked."""
    used = [False] * len(entries)
    kept: List[Finding] = []
    for f in findings:
        granted = False
        for i, e in enumerate(entries):
            if _entry_matches(e, f):
                used[i] = True
                granted = True
        if not granted:
            kept.append(f)
    for i, e in enumerate(entries):
        if check_stale and not used[i]:
            kept.append(Finding(
                check="TCR-A001", path=allowlist_rel, line=1,
                scope="<allowlist>",
                message=(f"stale allowlist entry: {e['check']} "
                         f"{e['path']}::{e['scope']} matched no finding "
                         f"— delete it or re-justify")))
    return kept


# -- runner -------------------------------------------------------------------


def _check_modules():
    from . import (checks_determinism, checks_pyflakes, checks_recompile,
                   checks_schema, checks_wallclock)

    return (checks_wallclock, checks_determinism, checks_schema,
            checks_recompile, checks_pyflakes)


def run_lint(root: str, paths: Optional[Sequence[str]] = None, *,
             allowlist_path: str = ALLOWLIST_PATH,
             pins_path: str = PINS_PATH,
             update_pins: bool = False,
             check_stale_allowlist: Optional[bool] = None
             ) -> Tuple[List[Finding], dict]:
    """Lint ``paths`` (default: the whole root) and return
    ``(findings, stats)``.  Findings are sorted and allowlist-filtered;
    ``stats`` counts files/raw findings per check for the CLI summary.
    """
    from . import checks_schema

    modules = _check_modules()
    raw: List[Finding] = []
    files = list(iter_py_files(root, paths))
    skipped: List[str] = []
    for rel in files:
        full = os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            raw.append(Finding(check="TCR-P001", path=rel,
                               line=getattr(e, "lineno", 1) or 1,
                               scope="<module>",
                               message=f"unparseable: {e}"))
            skipped.append(rel)
            continue
        ctx = FileContext(rel, source, tree)
        for mod in modules:
            raw.extend(mod.check(ctx))
    # Project-level pass: schema fingerprints vs the committed pins.
    raw.extend(checks_schema.check_pins(root, pins_path,
                                        update=update_pins))

    entries = load_allowlist(allowlist_path)
    allowlist_rel = os.path.relpath(allowlist_path, root).replace(
        os.sep, "/")
    if check_stale_allowlist is None:
        # Default: stale-grant findings only on full-tree lints — a
        # partial lint never walked most granted files.
        check_stale_allowlist = paths is None
    findings = apply_allowlist(sorted(raw, key=Finding.sort_key),
                               entries, allowlist_rel,
                               check_stale=check_stale_allowlist)
    findings.sort(key=Finding.sort_key)
    per_check: Dict[str, int] = {}
    for f in findings:
        per_check[f.check] = per_check.get(f.check, 0) + 1
    stats = {"files": len(files), "skipped": skipped,
             "raw_findings": len(raw), "findings": len(findings),
             "allow_entries": len(entries), "per_check": per_check}
    return findings, stats

"""The tcrlint engine: file walking, AST context, allowlist, runner.

Design points (shared by every check module):

- **Findings name file:line + check id** — the CLI prints
  ``path:line: TCR-X000 message`` and exits 1, so a violation reads
  like a compiler error, not a style nag.
- **The allowlist is scoped, not line-pinned.**  Entries match
  ``(check, path, scope)`` where scope is the dotted enclosing
  class/function chain (``ContinuousBatcher.tick``; ``<module>`` for
  module level, ``*`` for the whole file).  Line numbers churn on every
  edit; scopes only churn when the audited code actually moves — and a
  *stale* entry (matching nothing anymore) is itself a finding
  (TCR-A001), so dead grants cannot accumulate.
- **Deterministic by construction**: files walk sorted, findings sort
  by (path, line, check) — the lint's own output is diffable, which is
  what lets the self-test pin exact findings.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import subprocess
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Committed allowlist + schema pins live next to the engine.
ALLOWLIST_PATH = os.path.join(os.path.dirname(__file__),
                              "LINT_ALLOWLIST.json")
PINS_PATH = os.path.join(os.path.dirname(__file__), "SCHEMA_PINS.json")

#: Bump on any change to check logic or finding shapes: invalidates
#: every incremental-cache entry (the cache key hashes this together
#: with the allowlist/pins content and the call-summary digest).
LINT_VERSION = 2

#: Directories never walked (build junk; native/ holds generated .so
#: trees; spool dirs can appear under a dev checkout).
SKIP_DIRS = {"__pycache__", ".git", "build", ".pytest_cache", "node_modules"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding; sorts by (path, line, check) for stable output."""

    check: str       # "TCR-W001"
    path: str        # root-relative, forward slashes
    line: int
    scope: str       # dotted enclosing defs, "<module>" at top level
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.check} {self.message}"

    def sort_key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.check)


class FileContext:
    """Parsed module + the scope/parent maps the checks share."""

    def __init__(self, rel: str, source: str, tree: ast.Module):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.scopes: Dict[ast.AST, str] = {}
        self._annotate()

    def _annotate(self) -> None:
        def walk(node: ast.AST, scope: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                self.scopes[child] = ".".join(scope) or "<module>"
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    walk(child, scope + [child.name])
                else:
                    walk(child, scope)

        walk(self.tree, [])

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(node, "<module>")

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def enclosing_functions(self, node: ast.AST) -> List[ast.FunctionDef]:
        """Innermost-first chain of enclosing function defs."""
        out: List[ast.FunctionDef] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def finding(self, check: str, node: ast.AST, message: str) -> Finding:
        return Finding(check=check, path=self.rel,
                       line=getattr(node, "lineno", 1),
                       scope=self.scope_of(node), message=message)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# -- file walking -------------------------------------------------------------


def iter_py_files(root: str, paths: Optional[Sequence[str]] = None
                  ) -> Iterable[str]:
    """Root-relative .py paths under ``paths`` (files or directories),
    sorted — the lint practices the determinism it preaches.  An empty
    ``paths`` list means NO per-file targets (the ``--changed`` mode
    with a clean diff: project-level passes still run)."""
    if paths is not None and len(paths) == 0:
        return []
    targets = ([os.path.join(root, p) for p in paths]
               if paths is not None else [root])
    out: List[str] = []
    for target in targets:
        if os.path.isfile(target):
            out.append(os.path.relpath(target, root))
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    return sorted(dict.fromkeys(p.replace(os.sep, "/") for p in out))


# -- allowlist ----------------------------------------------------------------


def load_allowlist(path: str = ALLOWLIST_PATH) -> List[dict]:
    """Entries ``{"check", "path", "scope", "why"}``; ``scope`` ``"*"``
    grants the whole file.  Every field is required — an unjustified
    grant is refused at load time."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data["allow"] if isinstance(data, dict) else data
    for e in entries:
        missing = [k for k in ("check", "path", "scope", "why")
                   if not e.get(k)]
        if missing:
            raise ValueError(
                f"allowlist entry {e!r} missing {missing} — every grant "
                f"needs a check id, a path, a scope and a justification")
    return entries


def _entry_matches(entry: dict, finding: Finding) -> bool:
    if entry["check"] != finding.check or entry["path"] != finding.path:
        return False
    if entry["scope"] == "*":
        return True
    # Exact scope, or a grant on an enclosing scope ("Cls" covers
    # "Cls.method"): audits grant functions or whole classes, and a
    # nested helper inside an audited function is the same audit.
    return (finding.scope == entry["scope"]
            or finding.scope.startswith(entry["scope"] + "."))


def apply_allowlist(findings: List[Finding], entries: List[dict],
                    allowlist_rel: str,
                    check_stale: bool = True) -> List[Finding]:
    """Filter allowlisted findings; a stale entry (granting nothing this
    run) becomes a TCR-A001 finding on the allowlist file itself.
    ``check_stale=False`` for partial-tree lints, where an unused grant
    just means its file wasn't walked."""
    used = [False] * len(entries)
    kept: List[Finding] = []
    for f in findings:
        granted = False
        for i, e in enumerate(entries):
            if _entry_matches(e, f):
                used[i] = True
                granted = True
        if not granted:
            kept.append(f)
    for i, e in enumerate(entries):
        if check_stale and not used[i]:
            kept.append(Finding(
                check="TCR-A001", path=allowlist_rel, line=1,
                scope="<allowlist>",
                message=(f"stale allowlist entry: {e['check']} "
                         f"{e['path']}::{e['scope']} matched no finding "
                         f"— delete it or re-justify")))
    return kept


# -- changed-file selection (incremental mode) --------------------------------


def changed_files(root: str, base: Optional[str] = None
                  ) -> Optional[List[str]]:
    """Root-relative .py files changed vs ``base`` (default: the
    merge-base with main/master, falling back to HEAD — i.e. just the
    working tree), union the untracked files.  None when git is
    unavailable or ``root`` is not a work tree (callers fall back to
    the full walk and say so); a CALLER-SUPPLIED base that git refuses
    raises ``ValueError`` instead — a typo'd ref must be a usage
    error, not a silent full walk blamed on git."""
    def git(*args: str) -> Optional[str]:
        try:
            r = subprocess.run(["git", "-C", root, *args],
                               capture_output=True, text=True,
                               timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return r.stdout if r.returncode == 0 else None

    if git("rev-parse", "--git-dir") is None:
        return None
    explicit = base is not None
    if base is None:
        for cand in ("main", "master"):
            mb = git("merge-base", "HEAD", cand)
            if mb:
                base = mb.strip()
                break
        base = base or "HEAD"
    diff = git("diff", "--name-only", base)
    if diff is None:
        if explicit:
            raise ValueError(
                f"--changed base {base!r} is not a ref git can diff "
                f"against (typo, or an unfetched remote ref?)")
        return None
    untracked = git("ls-files", "--others", "--exclude-standard") or ""
    out = sorted({p for p in diff.splitlines() + untracked.splitlines()
                  if p.endswith(".py")
                  and os.path.exists(os.path.join(root, p))})
    return out


# -- incremental cache --------------------------------------------------------
# Content-hash keyed per-file findings under <root>/.tcrlint_cache/ so
# the tier-1 gate's cost tracks the DIFF, not the tree: a file whose
# content hash matches reuses its raw findings; the config digest
# (engine version + allowlist + pins + call-summary sources) guards
# cross-file invalidation — a summary-source edit re-lints everything,
# which is exactly the interprocedural soundness boundary.

CACHE_DIR_NAME = ".tcrlint_cache"

#: Modules whose one-level call summaries feed the interprocedural
#: checks (TCR-P callee mutation, TCR-M producer harvest).  Their
#: content is part of the cache config digest.
SUMMARY_SOURCES = (
    "text_crdt_rust_tpu/ops/batch.py",
    "text_crdt_rust_tpu/ops/flat.py",
    "text_crdt_rust_tpu/serve/batcher.py",
    "text_crdt_rust_tpu/serve/lanes_backend.py",
    "text_crdt_rust_tpu/serve/residency.py",
)


def _file_sha(path: str) -> str:
    h = hashlib.sha256()
    try:
        with open(path, "rb") as f:
            h.update(f.read())
    except OSError:
        h.update(b"<absent>")
    return h.hexdigest()


def _config_digest(root: str, allowlist_path: str, pins_path: str,
                   shape_pins_path: str) -> str:
    h = hashlib.sha256(f"tcrlint-v{LINT_VERSION}".encode())
    for path in (allowlist_path, pins_path, shape_pins_path):
        h.update(_file_sha(path).encode())
    for rel in SUMMARY_SOURCES:
        h.update(_file_sha(os.path.join(root, rel)).encode())
    # The engine's OWN source: an edited check module must invalidate
    # every cached verdict its old logic produced — "a stale hit is
    # structurally impossible" has to hold without anyone remembering
    # to bump LINT_VERSION by hand (the version stays as the knob for
    # semantic changes that live outside this package, e.g. pin-file
    # format migrations).
    engine_dir = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(engine_dir)):
        if fn.endswith(".py"):
            h.update(_file_sha(os.path.join(engine_dir, fn)).encode())
    return h.hexdigest()


class _Cache:
    def __init__(self, root: str, digest: str,
                 cache_dir: Optional[str] = None):
        self.path = os.path.join(cache_dir or os.path.join(
            root, CACHE_DIR_NAME), "cache.json")
        self.digest = digest
        self.hits = 0
        self.misses = 0
        self.entries: Dict[str, dict] = {}
        try:
            with open(self.path) as f:
                data = json.load(f)
            if data.get("digest") == digest:
                self.entries = data.get("files", {})
        except (OSError, ValueError):
            pass

    def get(self, rel: str, sha: str) -> Optional[List[Finding]]:
        entry = self.entries.get(rel)
        if entry is None or entry["sha"] != sha:
            self.misses += 1
            return None
        self.hits += 1
        return [Finding(**f) for f in entry["findings"]]

    def put(self, rel: str, sha: str, findings: List[Finding]) -> None:
        self.entries[rel] = {
            "sha": sha,
            "findings": [dataclasses.asdict(f) for f in findings]}

    def save(self) -> None:
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            with open(self.path, "w") as f:
                json.dump({"digest": self.digest, "files": self.entries},
                          f, sort_keys=True)
        except OSError:
            pass  # a read-only tree still lints, just uncached


# -- runner -------------------------------------------------------------------


def _check_modules():
    from . import (checks_determinism, checks_exceptions, checks_pyflakes,
                   checks_recompile, checks_schema, checks_wallclock)

    return (checks_wallclock, checks_determinism, checks_schema,
            checks_recompile, checks_exceptions, checks_pyflakes)


def _summary_map(root: str) -> Dict[str, "object"]:
    """One-level call summaries over the summary-source modules present
    under ``root`` (leaf-name keyed; first definition wins per the
    dataflow module's contract)."""
    from .dataflow import summarize_module

    out: Dict[str, object] = {}
    for rel in SUMMARY_SOURCES:
        full = os.path.join(root, rel)
        if not os.path.exists(full):
            continue
        try:
            with open(full, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=rel)
        except (SyntaxError, UnicodeDecodeError):
            continue
        for name, summary in sorted(summarize_module(tree).items()):
            out.setdefault(name, summary)
    return out


def run_lint(root: str, paths: Optional[Sequence[str]] = None, *,
             allowlist_path: str = ALLOWLIST_PATH,
             pins_path: str = PINS_PATH,
             shape_pins_path: Optional[str] = None,
             update_pins: bool = False,
             check_stale_allowlist: Optional[bool] = None,
             use_cache: bool = False,
             cache_dir: Optional[str] = None
             ) -> Tuple[List[Finding], dict]:
    """Lint ``paths`` (default: the whole root; an explicit empty list
    lints no files but still runs the project-level passes) and return
    ``(findings, stats)``.  Findings are sorted and allowlist-filtered;
    ``stats`` counts files/raw findings per check for the CLI summary.
    ``use_cache`` enables the content-hash incremental cache under
    ``<root>/.tcrlint_cache/`` (or ``cache_dir``).
    """
    from . import checks_claims, checks_mirror, checks_pipeline, \
        checks_schema, checks_shape

    if shape_pins_path is None:
        shape_pins_path = checks_shape.SHAPE_PINS_PATH
    modules = _check_modules()
    raw: List[Finding] = []
    files = list(iter_py_files(root, paths))
    skipped: List[str] = []
    cache = None
    if use_cache:
        cache = _Cache(root, _config_digest(
            root, allowlist_path, pins_path, shape_pins_path),
            cache_dir=cache_dir)
    summaries = _summary_map(root)
    producers = checks_mirror.harvest_producers(root) \
        | checks_mirror.DEFAULT_PRODUCERS
    shape_series = checks_shape.load_series(shape_pins_path)
    for rel in files:
        full = os.path.join(root, rel)
        if cache is not None:
            sha = _file_sha(full)
            hit = cache.get(rel, sha)
            if hit is not None:
                raw.extend(hit)
                continue
        try:
            with open(full, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            raw.append(Finding(check="TCR-E001", path=rel,
                               line=getattr(e, "lineno", 1) or 1,
                               scope="<module>",
                               message=f"unparseable: {e}"))
            skipped.append(rel)
            continue
        ctx = FileContext(rel, source, tree)
        file_raw: List[Finding] = []
        for mod in modules:
            file_raw.extend(mod.check(ctx))
        file_raw.extend(checks_pipeline.check(ctx, summaries=summaries))
        file_raw.extend(checks_mirror.check(ctx, producers=producers))
        file_raw.extend(checks_shape.check(ctx, series=shape_series))
        if cache is not None:
            cache.put(rel, sha, file_raw)
        raw.extend(file_raw)
    if cache is not None:
        cache.save()
    # Project-level passes: schema fingerprints + shape contracts vs
    # their committed pins, and the docs claims cross-check.
    raw.extend(checks_schema.check_pins(root, pins_path,
                                        update=update_pins))
    raw.extend(checks_shape.check_shape_pins(root, shape_pins_path,
                                             update=update_pins))
    raw.extend(checks_claims.check_claims(root))

    entries = load_allowlist(allowlist_path)
    allowlist_rel = os.path.relpath(allowlist_path, root).replace(
        os.sep, "/")
    if check_stale_allowlist is None:
        # Default: stale-grant findings only on full-tree lints — a
        # partial lint never walked most granted files.
        check_stale_allowlist = paths is None
    findings = apply_allowlist(sorted(raw, key=Finding.sort_key),
                               entries, allowlist_rel,
                               check_stale=check_stale_allowlist)
    findings.sort(key=Finding.sort_key)
    per_check: Dict[str, int] = {}
    for f in findings:
        per_check[f.check] = per_check.get(f.check, 0) + 1
    stats = {"files": len(files), "skipped": skipped,
             "raw_findings": len(raw), "findings": len(findings),
             "allow_entries": len(entries), "per_check": per_check,
             "cache": ({"hits": cache.hits, "misses": cache.misses}
                       if cache is not None else None)}
    return findings, stats

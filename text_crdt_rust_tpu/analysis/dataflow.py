"""The tcrlint dataflow engine (ISSUE 15): per-function CFGs,
reaching definitions, alias closures, and one-level call summaries.

PR 12's tcrlint was per-statement pattern matching: every check looked
at one AST node in isolation.  The v2 families (TCR-P pipeline escape,
TCR-M mirror pairing, TCR-K shape contracts) need *flow* facts — "can
this statement execute after that dispatch without passing a sync",
"which buffers may this name alias at that point", "does every path
that writes device state also write its host mirror" — so this module
grows the three classic intraprocedural analyses over the stdlib
``ast``, plus the one interprocedural level the serve/ops call graph
actually needs:

- **CFG** (`FunctionFlow.succ`): statement-level control-flow graph of
  one function body — If/While/For/Try/With lowered to edges,
  break/continue/return/raise resolved, loop back edges included (a
  mutation *before* a dispatch in a loop body still races it via the
  back edge).
- **Reaching definitions** (`FunctionFlow.defs_in`): the classic
  forward may-analysis, per statement: which binding sites may a
  name's value come from HERE.  Feeds constant resolution
  (`FunctionFlow.const_int`: all reaching defs agree on one int
  literal) and the alias closure.
- **Alias closure** (`FunctionFlow.alias_closure`): the set of local
  names whose storage may be shared with a seed expression, computed
  by chasing reaching definitions through alias-propagating forms
  (bare names, attribute/subscript reads, and the project's
  pad/stack/concat/asarray family — on CPU, JAX's zero-copy
  conversion makes "may share storage" the load-bearing relation the
  PR-12 runtime sanitizer checks dynamically).  ``self`` is never an
  alias root: backend self-state discipline is TCR-M's contract, and
  folding it in here would drown TCR-P in its own mirrors.
- **Call summaries** (`summarize_module`): per function/method, which
  parameters it may mutate in place, which ``self`` attributes it
  writes, and what it calls — ONE level deep, which is exactly the
  depth the serve tick's helper calls (`_op_fingerprints`,
  `_merge_rank_prefill`, `B.pad_ops`) need; an unknown callee is
  assumed alias-pure (documented per check).

Everything here is pure stdlib-``ast``; nothing imports jax.  The
checks stay deterministic: all iteration orders are list/insertion
order or explicitly sorted.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .tcrlint import dotted_name

#: Calls whose RESULT may share storage with their arguments — the
#: project's padding/stacking family plus numpy's aliasing converters
#: (``np.asarray`` of an ndarray is the same buffer; ``stack_ops``/
#: ``pad_ops`` feed zero-copy device conversion on CPU).
ALIAS_FNS = {
    "stack_ops", "pad_ops", "concat_ops", "tile_ops", "fuse_steps",
    "asarray", "ascontiguousarray", "atleast_1d", "ravel", "squeeze",
}

#: Attribute-call methods that pass their receiver's storage through
#: (``d.get(k, v)`` returns a stored element; view-producing ndarray
#: methods share the base buffer).
ALIAS_METHODS = {"get", "view", "reshape", "transpose", "astype"}

#: ndarray in-place mutator METHODS (container list ops like append/
#: extend/add are deliberately absent: rebinding a container slot to a
#: fresh value does not touch the in-flight array storage).
MUTATOR_METHODS = {"fill", "sort", "put", "partition", "setflags",
                   "resize", "byteswap", "itemset"}

#: Module-level functions that mutate their FIRST argument in place.
MUTATOR_FNS = {"copyto", "put", "place", "putmask", "fill_diagonal"}


def stmt_calls(node: ast.AST) -> List[ast.Call]:
    """Every Call expression inside ``node`` (document order)."""
    return [n for n in ast.walk(node) if isinstance(n, ast.Call)]


def call_leaf(call: ast.Call) -> str:
    """Leaf name of a call: ``b`` for ``a.b(...)`` and ``b(...)``."""
    name = dotted_name(call.func)
    if name:
        return name.split(".")[-1]
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return ""


# -- expression roots ---------------------------------------------------------


def expr_roots(node: ast.AST) -> Set[str]:
    """Local names whose storage the value of ``node`` may share.

    Conservative along alias-producing forms only: a ``BinOp`` always
    allocates (numpy/jnp semantics), so arithmetic results root
    nothing; ``self``/``cls`` are excluded by design (module
    docstring)."""
    out: Set[str] = set()
    _roots_into(node, out)
    out.discard("self")
    out.discard("cls")
    return out


def _roots_into(node: ast.AST, out: Set[str]) -> None:
    if isinstance(node, ast.Name):
        out.add(node.id)
    elif isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        _roots_into(node.value, out)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            _roots_into(elt, out)
    elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        _roots_into(node.elt, out)
        for gen in node.generators:
            _roots_into(gen.iter, out)
    elif isinstance(node, ast.IfExp):
        _roots_into(node.body, out)
        _roots_into(node.orelse, out)
    elif isinstance(node, ast.NamedExpr):
        _roots_into(node.value, out)
    elif isinstance(node, ast.Call):
        leaf = call_leaf(node)
        if leaf in ALIAS_FNS:
            for arg in list(node.args) + [k.value for k in node.keywords]:
                _roots_into(arg, out)
        elif (leaf in ALIAS_METHODS
              and isinstance(node.func, ast.Attribute)):
            _roots_into(node.func.value, out)
            for arg in node.args:
                _roots_into(arg, out)
        # any other call: assumed to allocate fresh storage


def is_container_ctor(node: ast.AST) -> bool:
    """True when ``node`` constructs a fresh host container (dict/list/
    set literal or comprehension, or the bare constructors) — subscript
    stores into one rebind a SLOT, they do not write array storage."""
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("dict", "list", "set", "defaultdict",
                                "OrderedDict", "deque")
    return False


# -- per-function control/data flow -------------------------------------------


class FunctionFlow:
    """CFG + reaching definitions for one function body.

    Statements are indexed in document order (``stmts``); ``succ[i]``
    is the set of indices that may execute immediately after statement
    i.  ``defs_in[i]`` maps each name to the set of statement indices
    whose binding may reach the ENTRY of statement i."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.stmts: List[ast.stmt] = []
        self.index: Dict[ast.stmt, int] = {}
        self.succ: Dict[int, Set[int]] = {}
        self._collect(fn.body)
        self._build_cfg(fn.body)
        self._reaching()

    # CFG construction --------------------------------------------------------

    def _collect(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.index[stmt] = len(self.stmts)
            self.stmts.append(stmt)
            self.succ[self.index[stmt]] = set()
            for field in ("body", "orelse", "finalbody"):
                self._collect(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                self._collect(handler.body)

    def _build_cfg(self, body: Sequence[ast.stmt]) -> None:
        # _link returns the exit set of a block: statement indices whose
        # fallthrough continues after the block.  EXIT is the virtual
        # function exit (dropped), loop contexts thread (break, continue)
        # targets.
        self._link(body, after=None, loop=None)

    def _edge(self, src: int, dst: Optional[int]) -> None:
        if dst is not None:
            self.succ[src].add(dst)

    def _first(self, body: Sequence[ast.stmt]) -> Optional[int]:
        return self.index[body[0]] if body else None

    def _link(self, body: Sequence[ast.stmt], after: Optional[int],
              loop: Optional[Tuple[int, Optional[int]]]) -> None:
        """Wire ``body``'s internal edges; each statement's fallthrough
        goes to the next statement, the last one to ``after``.  ``loop``
        is (head index, after-loop index) for break/continue."""
        for pos, stmt in enumerate(body):
            i = self.index[stmt]
            nxt = (self.index[body[pos + 1]] if pos + 1 < len(body)
                   else after)
            if isinstance(stmt, (ast.Return, ast.Raise)):
                continue  # no fallthrough
            if isinstance(stmt, ast.Break):
                if loop is not None:
                    self._edge(i, loop[1])
                continue
            if isinstance(stmt, ast.Continue):
                if loop is not None:
                    self._edge(i, loop[0])
                continue
            if isinstance(stmt, ast.If):
                self._edge(i, self._first(stmt.body) or nxt)
                self._edge(i, self._first(stmt.orelse) or nxt)
                self._link(stmt.body, after=nxt, loop=loop)
                self._link(stmt.orelse, after=nxt, loop=loop)
                continue
            if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                self._edge(i, self._first(stmt.body) or nxt)
                self._edge(i, self._first(stmt.orelse) or nxt)
                # loop body falls through back to the head (back edge)
                self._link(stmt.body, after=i, loop=(i, nxt))
                self._link(stmt.orelse, after=nxt, loop=loop)
                continue
            if isinstance(stmt, ast.Try):
                self._edge(i, self._first(stmt.body) or nxt)
                # any statement in the try body may transfer to any
                # handler (conservative may-edges)
                for handler in stmt.handlers:
                    h0 = self._first(handler.body)
                    if h0 is not None:
                        self._edge(i, h0)
                        for s in stmt.body:
                            self._edge(self.index[s], h0)
                fin0 = self._first(stmt.finalbody)
                cont = fin0 if fin0 is not None else nxt
                # the try body falls through to the ELSE block first
                # (it only runs when no exception fired), then on to
                # finally/next — without this edge, else-block
                # statements are CFG-orphans and every flow fact
                # (taint reach, reaching defs) goes silent there.
                body_after = self._first(stmt.orelse)
                self._link(stmt.body,
                           after=cont if body_after is None
                           else body_after, loop=loop)
                for handler in stmt.handlers:
                    self._link(handler.body, after=cont, loop=loop)
                self._link(stmt.finalbody, after=nxt, loop=loop)
                self._link(stmt.orelse, after=cont, loop=loop)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._edge(i, self._first(stmt.body) or nxt)
                self._link(stmt.body, after=nxt, loop=loop)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # nested defs: a straight-line node (the BODY runs at
                # call time, not here); still indexed so inner stmts
                # don't dangle, but unreachable from this flow.
                self._edge(i, nxt)
                continue
            self._edge(i, nxt)

    # reaching definitions ----------------------------------------------------

    @staticmethod
    def _bound_names(stmt: ast.stmt) -> Set[str]:
        """Names (re)bound directly by ``stmt`` (not in nested blocks)."""
        out: Set[str] = set()

        def targets(t: ast.AST) -> None:
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    targets(elt)
            elif isinstance(t, ast.Starred):
                targets(t.value)

        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                targets(t)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets(stmt.target)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    targets(item.optional_vars)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            out.add(stmt.name)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                out.add((alias.asname or alias.name).split(".")[0])
        return out

    def _reaching(self) -> None:
        n = len(self.stmts)
        gen: List[Set[str]] = [self._bound_names(s) for s in self.stmts]
        self.defs_in: List[Dict[str, Set[int]]] = [{} for _ in range(n)]
        defs_out: List[Dict[str, Set[int]]] = [{} for _ in range(n)]
        pred: Dict[int, Set[int]] = {i: set() for i in range(n)}
        for i, succs in self.succ.items():
            for j in succs:
                pred[j].add(i)
        work = list(range(n))
        while work:
            i = work.pop(0)
            merged: Dict[str, Set[int]] = {}
            for p in sorted(pred[i]):
                for name, sites in defs_out[p].items():
                    merged.setdefault(name, set()).update(sites)
            self.defs_in[i] = merged
            out: Dict[str, Set[int]] = {
                name: set(sites) for name, sites in merged.items()}
            for name in gen[i]:
                out[name] = {i}
            if out != defs_out[i]:
                defs_out[i] = out
                for j in sorted(self.succ[i]):
                    if j not in work:
                        work.append(j)

    # queries -----------------------------------------------------------------

    def reachable_from(self, start: int,
                       blocked: Optional[Set[int]] = None) -> Set[int]:
        """Statement indices reachable AFTER ``start`` (successors,
        transitively) without traversing THROUGH a ``blocked`` index —
        a blocked statement is itself reachable (its own content runs)
        but kills further propagation (the sync semantics TCR-P
        needs)."""
        blocked = blocked or set()
        seen: Set[int] = set()
        work = sorted(self.succ.get(start, ()))
        while work:
            i = work.pop(0)
            if i in seen:
                continue
            seen.add(i)
            if i in blocked:
                continue
            for j in sorted(self.succ.get(i, ())):
                if j not in seen:
                    work.append(j)
        return seen

    def _def_rhs(self, i: int, name: str) -> Optional[ast.AST]:
        """The RHS expression binding ``name`` at statement ``i`` (None
        for loop targets / with-targets / imports)."""
        stmt = self.stmts[i]
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if name in self._bound_names_of_target(t):
                    return stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            if (isinstance(stmt.target, ast.Name)
                    and stmt.target.id == name):
                return stmt.value
        elif isinstance(stmt, ast.AugAssign):
            if (isinstance(stmt.target, ast.Name)
                    and stmt.target.id == name):
                return stmt.target  # x op= e keeps x's storage
        return None

    @staticmethod
    def _bound_names_of_target(t: ast.AST) -> Set[str]:
        out: Set[str] = set()

        def walk(n: ast.AST) -> None:
            if isinstance(n, ast.Name):
                out.add(n.id)
            elif isinstance(n, (ast.Tuple, ast.List)):
                for elt in n.elts:
                    walk(elt)
            elif isinstance(n, ast.Starred):
                walk(n.value)

        walk(t)
        return out

    def alias_closure(self, seeds: Sequence[ast.AST],
                      at: int) -> Tuple[Set[str], Set[str]]:
        """(tainted names, container names): the fixpoint of chasing
        reaching definitions at statement ``at`` from the ``seeds``
        expressions through alias-producing RHS forms.  ``container``
        marks tainted names ALL of whose reaching defs construct fresh
        host containers (their subscript stores rebind slots, not
        array storage)."""
        taint: Set[str] = set()
        for seed in seeds:
            taint |= expr_roots(seed)
        containers: Set[str] = set()
        defs = self.defs_in[at] if at < len(self.defs_in) else {}
        work = sorted(taint)
        seen_defs: Set[Tuple[str, int]] = set()
        while work:
            name = work.pop(0)
            sites = defs.get(name, set())
            ctor_flags: List[bool] = []
            for site in sorted(sites):
                rhs = self._def_rhs(site, name)
                if rhs is None:
                    ctor_flags.append(False)
                    continue
                ctor_flags.append(is_container_ctor(rhs))
                if (name, site) in seen_defs:
                    continue
                seen_defs.add((name, site))
                for root in sorted(expr_roots(rhs)):
                    if root not in taint:
                        taint.add(root)
                        work.append(root)
            if ctor_flags and all(ctor_flags):
                containers.add(name)
        return taint, containers

    def const_int(self, node: ast.AST, at: int) -> Optional[int]:
        """Resolve ``node`` to an int: a literal, or a name ALL of whose
        reaching definitions at statement ``at`` bind the same int
        literal (one step of constant propagation — the TCR-K
        call-site resolver)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            inner = self.const_int(node.operand, at)
            return -inner if inner is not None else None
        if not isinstance(node, ast.Name):
            return None
        defs = self.defs_in[at] if at < len(self.defs_in) else {}
        sites = defs.get(node.id)
        if not sites:
            return None
        values: Set[int] = set()
        for site in sorted(sites):
            rhs = self._def_rhs(site, node.id)
            if (isinstance(rhs, ast.Constant)
                    and isinstance(rhs.value, int)
                    and not isinstance(rhs.value, bool)):
                values.add(rhs.value)
            else:
                return None
        return values.pop() if len(values) == 1 else None

    def stmt_of(self, node: ast.AST,
                parents: Dict[ast.AST, ast.AST]) -> Optional[int]:
        """Index of the statement containing ``node`` (via a parent
        map), restricted to this function's statements."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, ast.stmt) and cur in self.index:
                return self.index[cur]
            cur = parents.get(cur)
        return None


# -- one-level call summaries -------------------------------------------------


@dataclasses.dataclass
class FnSummary:
    """What one function does to the storage it is handed — the single
    interprocedural level the v2 checks consume."""

    name: str                 # dotted scope ("Cls.method" / "fn")
    params: Tuple[str, ...]
    mutated_params: Tuple[str, ...]   # params written THROUGH in place
    writes_self_attrs: Tuple[str, ...]  # self.<attr> assign/aug/store
    mirror_self_attrs: Tuple[str, ...]  # self.<attr>[...] subscript sets
    calls: Tuple[str, ...]            # leaf names of calls made

    def mutates(self, param_index: int) -> bool:
        return (param_index < len(self.params)
                and self.params[param_index] in self.mutated_params)


def summarize_function(fn: ast.AST, qualname: str) -> FnSummary:
    params = tuple(a.arg for a in fn.args.args
                   if a.arg not in ("self", "cls"))
    mutated: Set[str] = set()
    self_writes: Set[str] = set()
    self_stores: Set[str] = set()
    calls: Set[str] = set()

    def self_attr(node: ast.AST) -> Optional[str]:
        """``attr`` when node reads/writes ``self.attr`` (possibly
        through subscripts)."""
        cur = node
        while isinstance(cur, ast.Subscript):
            cur = cur.value
        if (isinstance(cur, ast.Attribute)
                and isinstance(cur.value, ast.Name)
                and cur.value.id in ("self", "cls")):
            return cur.attr
        return None

    def param_base(node: ast.AST) -> Optional[str]:
        cur = node
        while isinstance(cur, (ast.Subscript, ast.Attribute)):
            cur = cur.value
        if isinstance(cur, ast.Name) and cur.id in params:
            return cur.id
        return None

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            leaf = call_leaf(node)
            if leaf:
                calls.add(leaf)
            if (leaf in MUTATOR_METHODS
                    and isinstance(node.func, ast.Attribute)):
                p = param_base(node.func.value)
                if p:
                    mutated.add(p)
            if leaf in MUTATOR_FNS and node.args:
                p = param_base(node.args[0])
                if p:
                    mutated.add(p)
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            attr = self_attr(t)
            if attr is not None:
                self_writes.add(attr)
                if isinstance(t, ast.Subscript):
                    self_stores.add(attr)
            if isinstance(t, ast.Subscript) or isinstance(
                    node, ast.AugAssign):
                p = param_base(t)
                if p:
                    mutated.add(p)
    return FnSummary(
        name=qualname, params=params,
        mutated_params=tuple(sorted(mutated)),
        writes_self_attrs=tuple(sorted(self_writes)),
        mirror_self_attrs=tuple(sorted(self_stores)),
        calls=tuple(sorted(calls)))


def summarize_module(tree: ast.Module) -> Dict[str, FnSummary]:
    """Summaries for every function/method in a module, keyed BOTH by
    bare name and by ``Cls.method`` (bare-name collisions keep the
    first in document order — callee resolution is by leaf name, one
    level, best effort)."""
    out: Dict[str, FnSummary] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}" if prefix else child.name
                summary = summarize_function(child, qual)
                out.setdefault(child.name, summary)
                out[qual] = summary
                visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")

    visit(tree, "")
    return out


def iter_functions(tree: ast.Module):
    """(qualname, FunctionDef) for every def in the module, methods
    included, document order."""
    out: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}" if prefix else child.name
                out.append((qual, child))
                visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")

    visit(tree, "")
    return out

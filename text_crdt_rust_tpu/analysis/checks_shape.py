"""TCR-K00x: shape-contract checking against the pinned bucket series
(ISSUE 15).

Steady-state serving is compile-free only because every jitted call
site draws its shapes from a small DECLARED series: tick step counts
from ``ServeConfig.step_buckets``, prefill scatter lengths from
``ops.batch.scatter_bucket``'s geometric series
(``PREFILL_BUCKET_BASE * 4^k``), and the Pallas kernels' SMEM op-column
counts from their ``in_specs``.  A new call site that invents its own
shape compiles fine, runs fine, and silently recompiles every tick at
scale — the exact leak the runtime ``shapes_seen`` asserts only catch
on paths a test drives.  This check family pins the series and lints
the call sites:

- **TCR-K002** — the declared series are HARVESTED from the live AST
  (``harvest_contracts``) and pinned in ``SHAPE_CONTRACTS.json`` next
  to the engine; drift between the live tree and the pin is a finding,
  refreshed via the existing ``--update-pins`` discipline (the same
  re-pin-in-the-same-diff review moment as TCR-S003).  Pinned
  surfaces: the scatter series (base + growth factor), the default
  step buckets, and each kernel module's SMEM op-column count.

- **TCR-K001** — call sites whose shape argument resolves statically
  (a literal, or a name all of whose reaching definitions bind one int
  — ``dataflow.FunctionFlow.const_int``) must land ON the pinned
  series: ``pad_ops(stream, S)`` / ``empty_ops``-padded stacks /
  ``chunk=`` of the blocked kernel builder against the step buckets,
  ``PrefillDelta(..., bucket=L)`` / scatter-length pads against the
  scatter series.  Dynamically-computed shapes are skipped — those
  flow from the config at runtime and the ``shapes_seen`` asserts own
  them; what the lint ratchets is the hard-coded off-series constant.
"""
from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional

from .dataflow import FunctionFlow, call_leaf, iter_functions
from .tcrlint import FileContext, Finding

SHAPE_PINS_PATH = os.path.join(os.path.dirname(__file__),
                               "SHAPE_CONTRACTS.json")

#: Where each declared series lives.
BATCH_FILE = "text_crdt_rust_tpu/ops/batch.py"
CONFIG_FILE = "text_crdt_rust_tpu/config.py"
KERNEL_GLOB_DIR = "text_crdt_rust_tpu/ops"

#: Call sites checked against the STEP-bucket series (argument position
#: or keyword holding the shape — keyword names match the real
#: signatures: ``pad_ops(ops, num_steps)``).
STEP_SITES = {"pad_ops": (1, "num_steps"),
              "make_replayer_lanes_mixed_blocked": (None, "chunk")}

#: Call sites checked against the SCATTER series.
SCATTER_SITES = {"PrefillDelta": (None, "bucket")}


def _parse(root: str, rel: str) -> Optional[ast.Module]:
    path = os.path.join(root, rel)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=rel)


def _module_const(tree: ast.Module, name: str):
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id == name
                        and isinstance(node.value, ast.Constant)):
                    return node.value.value
        elif (isinstance(node, ast.AnnAssign)
              and isinstance(node.target, ast.Name)
              and node.target.id == name
              and isinstance(node.value, ast.Constant)):
            return node.value.value
    return None


def _scatter_factor(tree: ast.Module) -> Optional[int]:
    """The geometric growth factor from ``scatter_bucket``'s body
    (``b *= 4``)."""
    for _qual, fn in iter_functions(tree):
        if fn.name != "scatter_bucket":
            continue
        for node in ast.walk(fn):
            if (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Mult)
                    and isinstance(node.value, ast.Constant)):
                return node.value.value
    return None


def _step_buckets(tree: ast.Module) -> Optional[List[int]]:
    """``ServeConfig.step_buckets`` default tuple."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ServeConfig":
            for stmt in node.body:
                if (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)
                        and stmt.target.id == "step_buckets"
                        and isinstance(stmt.value, ast.Tuple)):
                    vals = [e.value for e in stmt.value.elts
                            if isinstance(e, ast.Constant)]
                    return vals if len(vals) == len(
                        stmt.value.elts) else None
    return None


def _smem_count(node: ast.AST) -> Optional[int]:
    """Number of SMEM op columns in an ``in_specs=`` expression: counts
    ``smem()`` elements, ``[smem() for _ in range(N)]`` comprehensions,
    and ``+``-concatenations thereof."""
    if isinstance(node, ast.List):
        total = 0
        for elt in node.elts:
            if isinstance(elt, ast.Call) and call_leaf(elt) == "smem":
                total += 1
        return total
    if isinstance(node, ast.ListComp):
        if (isinstance(node.elt, ast.Call)
                and call_leaf(node.elt) == "smem"
                and len(node.generators) == 1):
            it = node.generators[0].iter
            if (isinstance(it, ast.Call) and call_leaf(it) == "range"
                    and it.args
                    and isinstance(it.args[0], ast.Constant)):
                return it.args[0].value
        return 0
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _smem_count(node.left)
        right = _smem_count(node.right)
        if left is None or right is None:
            return None
        return left + right
    return 0


def _kernel_smem_columns(root: str) -> Dict[str, int]:
    """Per kernel module, the max SMEM op-column count any of its
    ``pallas_call(in_specs=...)`` sites declares."""
    out: Dict[str, int] = {}
    dirpath = os.path.join(root, KERNEL_GLOB_DIR)
    if not os.path.isdir(dirpath):
        return out
    for fn in sorted(os.listdir(dirpath)):
        if not fn.endswith(".py"):
            continue
        rel = f"{KERNEL_GLOB_DIR}/{fn}"
        tree = _parse(root, rel)
        if tree is None:
            continue
        best = 0
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and call_leaf(node) == "pallas_call"):
                continue
            for kw in node.keywords:
                if kw.arg == "in_specs":
                    count = _smem_count(kw.value)
                    if count:
                        best = max(best, count)
        if best:
            out[rel] = best
    return out


def harvest_contracts(root: str) -> Optional[dict]:
    """The live declared-series state; None when none of the declaring
    files exist under ``root`` (temp trees — nothing to pin)."""
    out: dict = {}
    batch = _parse(root, BATCH_FILE)
    if batch is not None:
        base = _module_const(batch, "PREFILL_BUCKET_BASE")
        factor = _scatter_factor(batch)
        if base is not None and factor is not None:
            out["scatter-series"] = {"file": BATCH_FILE, "base": base,
                                     "factor": factor, "depth": 6}
    cfg = _parse(root, CONFIG_FILE)
    if cfg is not None:
        buckets = _step_buckets(cfg)
        if buckets:
            out["step-buckets"] = {"file": CONFIG_FILE,
                                   "buckets": buckets}
    smem = _kernel_smem_columns(root)
    if smem:
        out["smem-op-columns"] = smem
    return out or None


def check_shape_pins(root: str, pins_path: str,
                     update: bool = False) -> List[Finding]:
    """TCR-K002: live harvested series vs the committed pin; with
    ``update=True`` rewrite the pin instead (the --update-pins
    discipline)."""
    live = harvest_contracts(root)
    if live is None:
        return []
    pins_rel = os.path.relpath(pins_path, root).replace(os.sep, "/")
    if update:
        with open(pins_path, "w") as f:
            json.dump({"comment":
                       "tcrlint TCR-K shape contracts — the declared "
                       "bucket series (scatter geometric series, "
                       "serve step buckets, kernel SMEM op columns) "
                       "harvested from the live AST; regenerate with "
                       "python -m text_crdt_rust_tpu.analysis.lint "
                       "--update-pins and commit alongside the series "
                       "change that motivated it",
                       "contracts": live}, f, indent=1, sort_keys=True)
            f.write("\n")
        return []
    if not os.path.exists(pins_path):
        return [Finding(
            check="TCR-K002", path=pins_rel, line=1, scope="<pins>",
            message="shape contracts pin file missing — run the lint "
                    "with --update-pins and commit it")]
    with open(pins_path) as f:
        pinned = json.load(f)["contracts"]
    out: List[Finding] = []
    for name in sorted(set(live) | set(pinned)):
        if name not in pinned:
            out.append(Finding(
                check="TCR-K002", path=pins_rel, line=1, scope="<pins>",
                message=f"shape surface {name!r} has no pin — run "
                        f"--update-pins and commit the diff"))
        elif name not in live:
            out.append(Finding(
                check="TCR-K002", path=pins_rel, line=1, scope="<pins>",
                message=f"pinned shape surface {name!r} no longer "
                        f"harvests from the tree — re-pin "
                        f"(--update-pins) or restore the series"))
        elif live[name] != pinned[name]:
            where = (live[name].get("file", pins_rel)
                     if isinstance(live[name], dict) else pins_rel)
            out.append(Finding(
                check="TCR-K002", path=where, line=1, scope="<module>",
                message=f"declared shape series {name!r} drifted from "
                        f"its pin ({pinned[name]} -> {live[name]}) — "
                        f"a bucket-series change re-keys the steady-"
                        f"state compile set; re-pin (--update-pins) in "
                        f"this same change so the diff shows it"))
    return out


# -- TCR-K001: static call-site shapes ---------------------------------------


def load_series(pins_path: str = SHAPE_PINS_PATH) -> Optional[dict]:
    if not os.path.exists(pins_path):
        return None
    with open(pins_path) as f:
        return json.load(f)["contracts"]


def _scatter_series(contract: dict) -> List[int]:
    base, factor = contract["base"], contract["factor"]
    return [base * factor ** k for k in range(contract.get("depth", 6))]


def _shape_arg(call: ast.Call, pos: Optional[int],
               kw: Optional[str]) -> Optional[ast.AST]:
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    if kw is not None:
        for k in call.keywords:
            if k.arg == kw:
                return k.value
    return None


def check(ctx: FileContext,
          series: Optional[dict] = None) -> List[Finding]:
    if series is None:
        series = load_series()
    if not series:
        return []
    steps = (series.get("step-buckets") or {}).get("buckets") or []
    scatter = (_scatter_series(series["scatter-series"])
               if "scatter-series" in series else [])
    sites = []
    if steps:
        sites.append((STEP_SITES, steps, "step-bucket series",
                      "ServeConfig.step_buckets"))
    if scatter:
        sites.append((SCATTER_SITES, scatter, "scatter-bucket series",
                      "ops.batch.scatter_bucket"))
    if not sites:
        return []
    out: List[Finding] = []
    for _qual, fn in iter_functions(ctx.tree):
        flow: Optional[FunctionFlow] = None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            leaf = call_leaf(node)
            for table, allowed, label, source in sites:
                if leaf not in table:
                    continue
                arg = _shape_arg(node, *table[leaf])
                if arg is None:
                    continue
                if flow is None:
                    flow = FunctionFlow(fn)
                at = flow.stmt_of(node, ctx.parents)
                value = (flow.const_int(arg, at)
                         if at is not None else None)
                if value is None or value in allowed:
                    continue
                out.append(ctx.finding(
                    "TCR-K001", node,
                    f"{leaf}(...) pads to static shape {value}, off "
                    f"the pinned {label} {allowed} ({source}) — an "
                    f"off-series shape compiles its own program and "
                    f"recompiles steady-state serving; draw the shape "
                    f"from the declared series or extend the series "
                    f"and re-pin (--update-pins)"))
    return out

"""TCR-W001: wall-clock segregation.

The whole-repo determinism story (PERF.md §14) rests on one rule: wall
time may be *measured* anywhere, but the measurement may only land in
an obs ``"w"`` field or an explicitly-perf surface — never in a value
that reaches a logical trace event, a ledger metric, a bench-row
logical field, or a wire byte.  Static taint tracking through the
whole serving loop is out of scope for a lint; what IS in scope, and
what actually ratchets, is naming every wall-clock *read* and making
each one pass an audit: every call site is a finding unless a
committed allowlist entry grants its (file, scope) with a one-line
justification.  A new ``perf_counter()`` anywhere in the package then
fails CI until someone has looked at where its value flows — which is
exactly the review moment that was missing when PR 8's ``"w"``
convention was adopted by convention alone.
"""
from __future__ import annotations

import ast
from typing import List

from .tcrlint import FileContext, Finding, dotted_name

CHECK = "TCR-W001"

#: Attribute chains that read the wall clock.  ``monotonic`` counts:
#: logical determinism does not care that it never jumps backwards.
WALL_CALLS = {
    "time.time", "time.time_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.process_time", "time.process_time_ns",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

#: Bare names that are wall reads when imported directly
#: (``from time import perf_counter``).
WALL_BARE = {"perf_counter", "perf_counter_ns", "monotonic",
             "monotonic_ns", "process_time", "time_ns"}


def check(ctx: FileContext) -> List[Finding]:
    # Track ``from time import perf_counter``-style names so bare calls
    # are caught; a bare ``time()`` is too ambiguous to flag without it.
    imported_bare: set = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "time", "datetime"):
            for alias in node.names:
                name = alias.asname or alias.name
                if alias.name in WALL_BARE | {"time"}:
                    imported_bare.add(name)

    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        hit = None
        if name in WALL_CALLS:
            hit = name
        elif (isinstance(node.func, ast.Name)
              and node.func.id in imported_bare):
            hit = node.func.id
        if hit:
            out.append(ctx.finding(
                CHECK, node,
                f"wall-clock read {hit}() — wall time may only feed obs "
                f'"w" fields or allowlisted perf probes (audit the flow '
                f"and add a justified LINT_ALLOWLIST.json entry for "
                f"scope {ctx.scope_of(node)!r})"))
    return out

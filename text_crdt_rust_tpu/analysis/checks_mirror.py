"""TCR-M00x: device-state / host-mirror pairing (ISSUE 15).

PR 13 moved the serve capacity contract onto HOST MIRRORS: the flat
backend's ``_n_host``/``_next_order_host`` (and the lanes backend's
``_lane_rows``/``_rkl``/``_resident_fresh``) must track the device
state exactly, because every pre-dispatch probe reads the mirror and
never the device.  The failure mode is structural: someone lands a new
device-state write site (a ``.at[...].set`` reseed, a new
``apply_prefill_delta`` call, a residency path) and forgets the paired
mirror update — nothing crashes, the mirrors drift, and the capacity
check silently reasons about a state that no longer exists.  The
runtime guard (``host-mirror == device-count``,
tests/test_device_prefill.py) only fires on paths a test happens to
drive; this check makes the pairing a LINT contract:

- **TCR-M001** — in a registered backend class (``MIRROR_CONTRACTS``,
  keyed by class name so injected copies of the real files stay
  checkable), every method that performs a device-state write must
  also write at least one of the class's mirror attributes — directly,
  or via a one-level call to another method of the same class whose
  summary writes one (``dataflow.summarize_module``) — or carry a
  scoped ``LINT_ALLOWLIST.json`` grant (e.g. a rank-only rewrite that
  provably cannot move occupancy).

  A *device-state write* is: an assignment to a registered device
  attribute; any ``self.<attr> = <expr>`` whose RHS contains a
  ``.at[...].set/add`` functional update; or a call to one of the flat
  engine's device-writing producers — harvested from ``ops/flat.py``'s
  AST when it is in the linted tree (functions containing ``.at[...]``
  updates / ``dynamic_update_slice`` / ``lax.scan``, closed one call
  level), with a pinned fallback list for partial trees.

- **TCR-M002** — a class in ``serve/`` that writes ``.at[...]``-style
  device state on ``self`` but is NOT registered in
  ``MIRROR_CONTRACTS``: a new lane backend landed without declaring
  its mirror contract.  Register it (or grant the scope) so M001 can
  watch its write sites.

- **TCR-M003** — tick trains (ISSUE 20) defer T device writes behind a
  buffered train, and the mirrors true up by the buffered column sums
  at the TRAIN boundary.  That true-up site is registered per class
  (``train_sync``), and the contract is ATOMICITY: the registered
  method must perform the device write AND a mirror write directly in
  its own body — no one-level helper delegation, which M001 would
  accept.  Splitting them re-opens the exact drift M001 exists to
  prevent, but across a boundary where T ticks of occupancy move at
  once (a partial true-up is T ticks wrong, not one).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .dataflow import FnSummary, call_leaf, iter_functions, stmt_calls
from .tcrlint import FileContext, Finding

#: The registered backend mirror contracts, by CLASS name (path-free so
#: the injection corpus can lint mutated copies of the real files).
MIRROR_CONTRACTS = {
    "FlatLaneBackend": {
        "device": ("docs",),
        "mirror": ("_n_host", "_next_order_host"),
        # TCR-M003: the train-boundary mirror true-up must live in the
        # same method as the train's device write (see module header).
        "train_sync": ("_dispatch_train",),
    },
    "LanesMixedLaneBackend": {
        "device": ("_state",),
        "mirror": ("_lane_rows", "_rkl", "_resident_fresh"),
    },
}

#: Fallback device-write producer names for partial trees where
#: ``ops/flat.py`` is absent (the harvest supersedes this when it can
#: run — see ``harvest_producers``).
DEFAULT_PRODUCERS = frozenset({
    "apply_prefill_delta", "_scatter_delta", "_scatter_delta_batch",
    "_apply_ops", "_apply_ops_batch", "apply_ops", "apply_ops_batch",
    "apply_train", "_apply_train_batch",
    "prefill_logs", "step",
})

PRODUCER_SOURCE = "text_crdt_rust_tpu/ops/flat.py"

#: Directory prefix where M002 (unregistered device-state class)
#: applies — new lane backends land here.
M002_PREFIX = "text_crdt_rust_tpu/serve/"


def harvest_producers(root: str) -> frozenset:
    """Device-writing callables of the flat engine, from its AST: defs
    whose body performs a functional device update (``.at[...].set``/
    ``dynamic_update_slice``/``lax.scan``), plus (one level) defs that
    call a harvested producer.  Falls back to the pinned list when the
    source file is not under ``root`` (temp trees)."""
    import os

    path = os.path.join(root, PRODUCER_SOURCE)
    if not os.path.exists(path):
        return DEFAULT_PRODUCERS
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=PRODUCER_SOURCE)
    direct: Set[str] = set()
    calls: Dict[str, Set[str]] = {}
    for qual, fn in iter_functions(tree):
        leafs = {call_leaf(c) for c in stmt_calls(fn)}
        calls[fn.name] = leafs
        if _writes_device(fn):
            direct.add(fn.name)
    # one closure level: callers of device writers are device writers
    out = set(direct)
    for name, leafs in sorted(calls.items()):
        if leafs & direct:
            out.add(name)
    return frozenset(out)


def _writes_device(fn: ast.AST) -> bool:
    for call in stmt_calls(fn):
        leaf = call_leaf(call)
        if leaf in ("dynamic_update_slice", "scan"):
            return True
        if leaf in ("set", "add") and isinstance(call.func, ast.Attribute):
            # x.at[...].set(...) — the .at chain below the method
            recv = call.func.value
            if (isinstance(recv, ast.Subscript)
                    and isinstance(recv.value, ast.Attribute)
                    and recv.value.attr == "at"):
                return True
    return False


def _at_set_in(node: ast.AST) -> bool:
    """``.at[...].set/add`` anywhere inside an expression."""
    for call in stmt_calls(node):
        if (call_leaf(call) in ("set", "add")
                and isinstance(call.func, ast.Attribute)):
            recv = call.func.value
            if (isinstance(recv, ast.Subscript)
                    and isinstance(recv.value, ast.Attribute)
                    and recv.value.attr == "at"):
                return True
    return False


def _self_attr_target(t: ast.AST) -> Optional[str]:
    """``attr`` when ``t`` is ``self.attr`` or ``self.attr[...]``."""
    cur = t
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    if (isinstance(cur, ast.Attribute)
            and isinstance(cur.value, ast.Name)
            and cur.value.id in ("self", "cls")):
        return cur.attr
    return None


def _method_mirror_writes(fn: ast.AST, mirrors: Set[str]) -> bool:
    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            attr = _self_attr_target(t)
            if attr in mirrors:
                return True
    return False


def _method_device_writes(fn: ast.AST,
                          device: Set[str]) -> List[ast.AST]:
    """Nodes performing a device-state write in one method: device-attr
    assignments and ``.at[...].set`` self-stores (producer CALLS are a
    separate detection in ``check`` — they mark the method even when
    nothing lands on a registered attribute)."""
    hits: List[ast.AST] = []
    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = list(node.targets), node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        for t in targets:
            attr = _self_attr_target(t)
            if attr is None:
                continue
            if attr in device:
                hits.append(t)
            elif value is not None and _at_set_in(value):
                hits.append(t)
    return hits


def _self_method_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for call in stmt_calls(fn):
        if (isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id in ("self", "cls")):
            out.add(call.func.attr)
    return out


def check(ctx: FileContext,
          summaries: Optional[Dict[str, FnSummary]] = None,
          producers: Optional[frozenset] = None) -> List[Finding]:
    if producers is None:
        producers = DEFAULT_PRODUCERS
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        contract = MIRROR_CONTRACTS.get(node.name)
        methods = {m.name: m for m in node.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if contract is None:
            # TCR-M002: unregistered serve-side device-state class.
            if not ctx.rel.startswith(M002_PREFIX):
                continue
            for m in methods.values():
                writes = _method_device_writes(m, set())
                if writes:
                    out.append(ctx.finding(
                        "TCR-M002", writes[0],
                        f"class {node.name} writes device state on "
                        f"self but is not registered in "
                        f"checks_mirror.MIRROR_CONTRACTS — declare "
                        f"its device/mirror attribute contract so "
                        f"TCR-M001 can watch new write sites"))
                    break
            continue
        device = set(contract["device"])
        mirrors = set(contract["mirror"])
        mirror_methods = {name for name, m in sorted(methods.items())
                          if _method_mirror_writes(m, mirrors)}
        # Registered train-boundary sync sites are their own contract
        # (TCR-M003) and do NOT excuse other methods via the one-level
        # pairing rule: the serial tick path calls the train dispatcher
        # on the enqueue branch, so cutting the serial true-up would
        # otherwise hide behind the train helper's mirror writes.
        pairing = mirror_methods - set(contract.get("train_sync", ()))
        # TCR-M003: registered train-boundary sync sites must be atomic
        # — device write AND mirror true-up directly in the one method.
        for name in contract.get("train_sync", ()):
            m = methods.get(name)
            if m is None:
                continue
            writes = _method_device_writes(m, device)
            if not writes:
                writes = [c for c in stmt_calls(m)
                          if call_leaf(c) in producers]
            if writes and name in mirror_methods:
                continue
            out.append(ctx.finding(
                "TCR-M003", writes[0] if writes else m,
                f"{node.name}.{name} is the registered train-boundary "
                f"sync site but does not perform the device write and "
                f"the mirror true-up ({', '.join(sorted(mirrors))}) in "
                f"its own body — the train contract is atomic: T "
                f"ticks' occupancy moves in one method, no helper "
                f"delegation (a split true-up drifts T ticks at a "
                f"time)"))
        for name, m in sorted(methods.items()):
            writes = _method_device_writes(m, device)
            # a producer call on its own marks the method too (a
            # device-writing call whose result is not stored on self
            # still mutated donated/lane state on device).
            if not writes:
                prod_calls = [c for c in stmt_calls(m)
                              if call_leaf(c) in producers]
                writes = list(prod_calls)
            if not writes:
                continue
            if name in mirror_methods:
                continue
            if _self_method_calls(m) & pairing:
                continue  # one-level pairing via a same-class helper
            writes.sort(key=lambda n: getattr(n, "lineno", 0))
            out.append(ctx.finding(
                "TCR-M001", writes[0],
                f"{node.name}.{name} writes device state but never "
                f"updates a host mirror ({', '.join(sorted(mirrors))})"
                f" — the PR-13 capacity contract reads mirrors, not "
                f"the device; pair the write or add a justified "
                f"allowlist grant for this scope"))
    return out

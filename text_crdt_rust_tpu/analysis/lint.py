"""tcrlint CLI — ``python -m text_crdt_rust_tpu.analysis.lint``.

One entry point for ALL of the project's static gates (the tier-1
lint test runs exactly this module):

1. **tcrlint** — the project-invariant families (wall-clock
   segregation, determinism hazards, schema drift, recompile hazards,
   F401 fallback) plus the v2 dataflow families (pipeline escape,
   mirror pairing, shape contracts, claims consistency) over the
   package;
2. **ruff** — the third-party baseline (``pyproject.toml
   [tool.ruff]``, pyflakes+isort-level rules) when the binary is
   installed; its absence downgrades to the built-in TCR-F401
   fallback, reported in the summary so the gate's coverage is never
   silently ambiguous.

Exit codes: 0 clean, 1 findings (each printed as
``path:line: CHECK-ID message``), 2 usage/config error.

``--update-pins`` rewrites ``SCHEMA_PINS.json`` AND
``SHAPE_CONTRACTS.json`` from the live surfaces (commit them together
with the change that motivated the re-pin).

**Incremental mode** (ISSUE 15): ``--changed [BASE]`` lints only the
.py files git reports changed vs BASE (default: the merge-base with
main/master, else the working tree) — the project-level passes (schema
pins, shape contracts, docs claims) always run, they are cheap.  The
content-hash cache under ``.tcrlint_cache/`` makes even full-tree
re-runs diff-priced; ``--no-cache`` disables it (the cache key folds
in the engine version, allowlist, pins and the interprocedural
summary sources, so a stale hit is structurally impossible).  The
full-tree walk (no ``--changed``) is the weekly-style fallback and
the authoritative clean-tree proof.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time
from typing import List, Optional

from .checks_shape import SHAPE_PINS_PATH
from .tcrlint import ALLOWLIST_PATH, PINS_PATH, changed_files, run_lint

#: Default lint target, relative to the repo root.
DEFAULT_TARGET = "text_crdt_rust_tpu"


def repo_root() -> str:
    """The repo root = the parent of the installed package directory
    (bench.py and pyproject.toml live there)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_ruff(root: str, paths: List[str]) -> Optional[dict]:
    """Run ruff over ``paths`` when installed; None when unavailable
    (the caller reports the downgrade).  Findings come back in the
    same path:line shape tcrlint uses."""
    exe = shutil.which("ruff")
    argv = None
    if exe:
        argv = [exe, "check", "--output-format", "concise", *paths]
    else:
        try:  # pip-installed module without a PATH shim
            import ruff  # noqa: F401

            argv = [sys.executable, "-m", "ruff", "check",
                    "--output-format", "concise", *paths]
        except ImportError:
            return None
    r = subprocess.run(argv, capture_output=True, text=True, cwd=root,
                       timeout=300)
    lines = [ln for ln in r.stdout.splitlines()
             if ln.strip() and not ln.startswith(("Found ", "warning:"))]
    return {"rc": r.returncode, "lines": lines,
            "stderr": r.stderr.strip()}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m text_crdt_rust_tpu.analysis.lint",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint, relative to --root "
                         f"(default: {DEFAULT_TARGET})")
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from the package "
                         "location)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist JSON (default: the committed "
                         "analysis/LINT_ALLOWLIST.json)")
    ap.add_argument("--pins", default=None,
                    help="schema pins JSON (default: the committed "
                         "analysis/SCHEMA_PINS.json)")
    ap.add_argument("--update-pins", action="store_true",
                    help="rewrite the schema pins AND shape contracts "
                         "from the live surfaces instead of checking "
                         "them")
    ap.add_argument("--shape-pins", default=None,
                    help="shape contracts JSON (default: the committed "
                         "analysis/SHAPE_CONTRACTS.json)")
    ap.add_argument("--changed", nargs="?", const="auto", default=None,
                    metavar="BASE",
                    help="incremental mode: lint only .py files git "
                         "reports changed vs BASE (default: merge-base "
                         "with main/master); project-level passes "
                         "always run")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the .tcrlint_cache content-hash "
                         "cache")
    ap.add_argument("--cache-dir", default=None,
                    help="cache directory (default: "
                         "<root>/.tcrlint_cache)")
    ap.add_argument("--no-ruff", action="store_true",
                    help="skip the third-party ruff baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    a = ap.parse_args(argv)

    root = os.path.abspath(a.root) if a.root else repo_root()
    if not os.path.isdir(root):
        print(f"lint root {root!r} is not a directory", file=sys.stderr)
        return 2
    paths = a.paths or [DEFAULT_TARGET]
    for p in paths:
        if not os.path.exists(os.path.join(root, p)):
            print(f"lint target {p!r} not found under {root}",
                  file=sys.stderr)
            return 2
    mode = "full"
    full_walk = True
    if a.changed is not None:
        from .tcrlint import SUMMARY_SOURCES

        base = None if a.changed == "auto" else a.changed
        try:
            changed = changed_files(root, base)
        except ValueError as e:  # typo'd/unfetched explicit base
            print(f"tcrlint usage error: {e}", file=sys.stderr)
            return 2
        if changed is None:
            # No git (tarball checkout): the weekly-style fallback is
            # the full walk, and the summary says so.
            mode = "full (--changed fell back: no git work tree)"
        elif set(changed) & set(SUMMARY_SOURCES):
            # A summary-source edit can induce cross-file TCR-P/TCR-M
            # findings in UNCHANGED dependents (a new device-write
            # producer in ops/flat.py makes an old call site in the
            # batcher a finding) — the interprocedural soundness
            # boundary demands the full walk, and the cache (whose
            # digest just rotated on the same edit) keeps it cheap.
            mode = ("full (--changed touched an interprocedural "
                    "summary source)")
        else:
            prefixes = tuple(paths)
            paths = [p for p in changed
                     if p.startswith(prefixes) or p in prefixes]
            mode = f"changed ({len(paths)} file(s) vs merge-base)"
            full_walk = False

    t0 = time.perf_counter()  # lint wall for the summary line only
    try:
        findings, stats = run_lint(
            root, paths,
            allowlist_path=a.allowlist or ALLOWLIST_PATH,
            pins_path=a.pins or PINS_PATH,
            shape_pins_path=a.shape_pins or SHAPE_PINS_PATH,
            update_pins=a.update_pins,
            use_cache=not a.no_cache,
            cache_dir=a.cache_dir,
            # Stale-grant findings only for full default-target walks:
            # a partial lint never walked most granted files.  Boolean,
            # not a mode-string compare — the --changed fallbacks ARE
            # full walks and must keep the stale check.
            check_stale_allowlist=not a.paths and full_walk)
    except ValueError as e:  # malformed allowlist
        print(f"tcrlint config error: {e}", file=sys.stderr)
        return 2

    stats["mode"] = mode
    ruff = (None if a.no_ruff or not paths
            else run_ruff(root, paths))
    ruff_lines = ruff["lines"] if ruff else []
    wall = time.perf_counter() - t0

    if a.as_json:
        print(json.dumps({
            "ok": not findings and not ruff_lines,
            "findings": [f.format() for f in findings],
            "ruff": (None if ruff is None
                     else {"rc": ruff["rc"], "findings": ruff_lines}),
            "ruff_available": ruff is not None,
            "stats": stats, "wall_s": round(wall, 3),
        }, indent=1))
    else:
        for f in findings:
            print(f.format())
        for ln in ruff_lines:
            print(f"{ln}  [ruff]")
        ruff_note = ("ruff baseline clean" if ruff and not ruff_lines
                     else f"ruff: {len(ruff_lines)} finding(s)" if ruff
                     else "ruff not installed — built-in TCR-F401 "
                          "fallback covered the F-level floor")
        cache = stats.get("cache")
        cache_note = (f", cache {cache['hits']}h/{cache['misses']}m"
                      if cache else "")
        print(f"tcrlint[{mode}]: {stats['files']} files{cache_note}, "
              f"{len(findings)} finding(s), "
              f"{stats['allow_entries']} allowlist grants; {ruff_note} "
              f"({wall:.1f}s)", file=sys.stderr)
    if a.update_pins and not a.as_json:
        print(f"schema pins rewritten: {a.pins or PINS_PATH}; shape "
              f"contracts rewritten: {a.shape_pins or SHAPE_PINS_PATH}",
              file=sys.stderr)
    return 1 if (findings or ruff_lines) else 0


if __name__ == "__main__":
    raise SystemExit(main())

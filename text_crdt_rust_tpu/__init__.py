"""text_crdt_rust_tpu — a TPU-native list/text CRDT framework.

Brand-new rebuild of `josephg/text-crdt-rust` (see SURVEY.md): Yjs/YATA
integration semantics over an automerge-style (agent, seq) data model.

Layout (see each subpackage's __init__ for what is implemented):

- ``models/``    document engines: Python oracle, C++ native engine
                 (ctypes), peer sync;
- ``ops/``       device kernels: the RLE run engines (``rle`` /
                 ``rle_hbm`` / ``rle_lanes``), per-char engines
                 (``flat`` / ``blocked*``), the op compiler (``batch``);
- ``parallel/``  mesh sharding (dp/sp) + the causal buffer;
- ``utils/``     RLE span algebra, trace loader, metrics, checkpoint;
- ``native/``    C++ sources + build;
- ``examples/``  soak and stats CLIs;
- ``config``     the dataclass config layer.
"""

from .common import (
    CLIENT_INVALID,
    CRDT_DOC_ROOT,
    CRDTLocation,
    LocalOp,
    ROOT_ORDER,
    ROOT_REMOTE_ID,
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)

__version__ = "0.1.0"

__all__ = [
    "CLIENT_INVALID",
    "CRDT_DOC_ROOT",
    "CRDTLocation",
    "LocalOp",
    "ROOT_ORDER",
    "ROOT_REMOTE_ID",
    "RemoteDel",
    "RemoteId",
    "RemoteIns",
    "RemoteTxn",
    "__version__",
]

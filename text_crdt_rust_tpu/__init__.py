"""text_crdt_rust_tpu — a TPU-native list/text CRDT framework.

Brand-new rebuild of `josephg/text-crdt-rust` (see SURVEY.md): Yjs/YATA
integration semantics over an automerge-style (agent, seq) data model.

Layout (see each subpackage's __init__ for what is implemented):

- ``models/``   document engines (Python oracle + sync layer; C++ native and
                JAX/TPU batched engines join them as they land);
- ``utils/``    RLE span algebra + flat containers (the host↔device wire
                format), trace loader;
- ``ops/``, ``parallel/``, ``native/``  device kernels, mesh sharding and
                C++ sources respectively.
"""

from .common import (
    CLIENT_INVALID,
    CRDT_DOC_ROOT,
    CRDTLocation,
    LocalOp,
    ROOT_ORDER,
    ROOT_REMOTE_ID,
    RemoteDel,
    RemoteId,
    RemoteIns,
    RemoteTxn,
)

__version__ = "0.1.0"

__all__ = [
    "CLIENT_INVALID",
    "CRDT_DOC_ROOT",
    "CRDTLocation",
    "LocalOp",
    "ROOT_ORDER",
    "ROOT_REMOTE_ID",
    "RemoteDel",
    "RemoteId",
    "RemoteIns",
    "RemoteTxn",
    "__version__",
]

from .rle import (
    KCRDTSpan,
    KDeleteEntry,
    KDoubleDelete,
    KOrderSpan,
    Rle,
    TxnSpan,
    increment_delete_range,
)
from .testdata import TestData, TestPatch, TestTxn, load_testing_data, trace_path

__all__ = [
    "KCRDTSpan",
    "KDeleteEntry",
    "KDoubleDelete",
    "KOrderSpan",
    "Rle",
    "TxnSpan",
    "increment_delete_range",
    "TestData",
    "TestPatch",
    "TestTxn",
    "load_testing_data",
    "trace_path",
]

from .checkpoint import (CheckpointError, load_doc, load_flat_doc,
                         save_doc, save_flat_doc)
from .integrity import crc32c
from .metrics import (Counters, Throughput, causal_buffer_stats, doc_stats,
                      memory_stats, print_stats, run_stats)
from .rle import (
    KCRDTSpan,
    KDeleteEntry,
    KDoubleDelete,
    KOrderSpan,
    Rle,
    TxnSpan,
    increment_delete_range,
)
from .testdata import TestData, TestPatch, TestTxn, load_testing_data, trace_path

__all__ = [
    "KCRDTSpan",
    "KDeleteEntry",
    "KDoubleDelete",
    "KOrderSpan",
    "Rle",
    "TxnSpan",
    "increment_delete_range",
    "TestData",
    "TestPatch",
    "TestTxn",
    "load_testing_data",
    "trace_path",
    "CheckpointError",
    "load_doc",
    "load_flat_doc",
    "save_doc",
    "save_flat_doc",
    "crc32c",
    "Counters",
    "Throughput",
    "causal_buffer_stats",
    "doc_stats",
    "memory_stats",
    "run_stats",
    "print_stats",
]

"""Editing-trace loader (rebuild of the `crdt-testdata` sub-crate,
`src/testdata/src/lib.rs:10-48`).

Parses the gzipped automerge-perf JSON traces shipped in
``benchmark_data/*.json.gz``:

    { "startContent": str, "endContent": str,
      "txns": [ { "patches": [ [pos, del_len, ins_str], ... ] }, ... ] }

Positions are in (unicode) characters; each patch is "delete ``del_len``
chars at ``pos``, then insert ``ins_str`` at ``pos``" — the same shape as
``LocalOp`` (`common.rs:46-50`).
"""
from __future__ import annotations

import gzip
import json
import os
from dataclasses import dataclass
from typing import List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DATA_DIR = os.path.join(REPO_ROOT, "benchmark_data")


@dataclass
class TestPatch:
    pos: int
    del_len: int
    ins_content: str


@dataclass
class TestTxn:
    patches: List[TestPatch]


@dataclass
class TestData:
    start_content: str
    end_content: str
    txns: List[TestTxn]

    def num_ops(self) -> int:
        """Total CRDT ops (inserted chars + deleted chars), matching the
        order-number accounting of `doc.rs:376-389`."""
        n = 0
        for txn in self.txns:
            for p in txn.patches:
                n += p.del_len + len(p.ins_content)
        return n

    def num_patches(self) -> int:
        return sum(len(t.patches) for t in self.txns)


def load_testing_data(path: str) -> TestData:
    """Gunzip + parse one trace (`testdata/src/lib.rs:43-48`)."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt", encoding="utf-8") as f:
            raw = json.load(f)
    else:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    txns = [
        TestTxn(patches=[TestPatch(p[0], p[1], p[2]) for p in t["patches"]])
        for t in raw["txns"]
    ]
    return TestData(
        start_content=raw.get("startContent", ""),
        end_content=raw.get("endContent", ""),
        txns=txns,
    )


def trace_path(name: str) -> str:
    """Resolve a corpus trace by short name, e.g. ``automerge-paper``."""
    return os.path.join(DATA_DIR, f"{name}.json.gz")


def flatten_patches(data: TestData) -> List[TestPatch]:
    """All patches in order (one host-side txn per patch run is applied by
    callers; the reference replays per-txn, `benches/yjs.rs:41-48`)."""
    out: List[TestPatch] = []
    for t in data.txns:
        out.extend(t.patches)
    return out

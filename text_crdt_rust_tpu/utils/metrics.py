"""Metrics & observability: the reference's ``print_stats`` family, TPU-ified.

The reference instruments itself with a counting global allocator
(`src/alloc.rs:13-50`) and per-container ``print_stats`` dumps — entry
histograms, node counts, RLE compaction ratio ("compacts to N entries",
`split_list/mod.rs:418`), actual-vs-efficient memory (`root.rs:293-326`).
The TPU build's equivalents (SURVEY §5 "Tracing/profiling" row):

- ``doc_stats``   — one dict per document: items/live/tombstones, merged
                    span count + compaction ratio (the RLE health metric
                    that decides device array sizes), span-length
                    histogram, log entry counts;
- ``memory_stats``— bytes per column for host oracle docs and device
                    ``FlatDoc``s (device bytes ARE the HBM footprint);
- ``Throughput``  — ops/sec accumulator for bench loops (wall-clock via
                    ``time.perf_counter``, explicit ``ops`` counts).

All functions accept either an oracle ``ListCRDT`` or a device ``FlatDoc``
(anything exposing ``doc_spans``-compatible state).
"""
from __future__ import annotations

import time
from typing import Dict, List, Tuple

import numpy as np


def _spans_of(doc) -> List[Tuple[int, int, int, int]]:
    if hasattr(doc, "doc_spans"):
        return doc.doc_spans()
    from ..ops.span_arrays import doc_spans
    return doc_spans(doc)


def _counts_of(doc, spans) -> Tuple[int, int]:
    """(total items, live items) for oracle or FlatDoc. Derived from the
    merged spans for device docs (avoids a second device->host download)."""
    if hasattr(doc, "deleted"):  # oracle
        n = doc.n
        return n, int(np.count_nonzero(~doc.deleted[:n]))
    lens = [s[3] for s in spans]
    return sum(abs(l) for l in lens), sum(l for l in lens if l > 0)


def span_histogram(spans, bins=(1, 2, 4, 8, 16, 32, 64, 128)) -> Dict[str, int]:
    """Span-length histogram (the reference's entry-size histograms,
    `root.rs:293-326`)."""
    lens = np.asarray([abs(s[3]) for s in spans] or [0])
    out: Dict[str, int] = {}
    lo = 1
    for hi in bins:
        out[f"{lo}-{hi}"] = int(((lens >= lo) & (lens <= hi)).sum())
        lo = hi + 1
    out[f">{bins[-1]}"] = int((lens > bins[-1]).sum())
    return out


def doc_stats(doc, spans=None) -> dict:
    """Document-health metrics; ``compaction`` is items per merged span —
    the reference's "compacts to N entries" ratio. Pass precomputed
    ``spans`` to avoid re-downloading a device doc."""
    if spans is None:
        spans = _spans_of(doc)
    items, live = _counts_of(doc, spans)
    stats = {
        "items": items,
        "live": live,
        "tombstones": items - live,
        "merged_spans": len(spans),
        "compaction": items / max(1, len(spans)),
        "span_histogram": span_histogram(spans),
    }
    if hasattr(doc, "deletes"):  # oracle-side logs
        stats["deletes_entries"] = doc.deletes.num_entries()
        stats["double_delete_entries"] = doc.double_deletes.num_entries()
        stats["txn_entries"] = doc.txns.num_entries()
    return stats


def memory_stats(doc, spans=None) -> dict:
    """Bytes per column. For a device ``FlatDoc`` these are the actual HBM
    buffer sizes; ``efficient_bytes`` is what a fully RLE-compacted span
    store would need (16B/span, `span.rs:126-129`) — the reference's
    actual-vs-efficient comparison. Pass precomputed ``spans`` to avoid
    re-downloading a device doc."""
    if spans is None:
        spans = _spans_of(doc)
    if hasattr(doc, "deleted"):  # oracle numpy columns
        cols = {k: getattr(doc, k).nbytes
                for k in ("order", "origin_left", "origin_right",
                          "deleted", "chars")}
    elif hasattr(doc, "memory_bytes"):  # native engine: measured total
        cols = {"native_engine": int(doc.memory_bytes())}
    else:
        cols = {k: int(np.prod(getattr(doc, k).shape)
                       * getattr(doc, k).dtype.itemsize)
                for k in ("signed", "ol_log", "or_log", "rank_log",
                          "chars_log")}
    total = sum(cols.values())
    return {
        "columns": cols,
        "total_bytes": total,
        "efficient_bytes": 16 * len(spans),
        "overhead": total / max(1, 16 * len(spans)),
    }


class Counters:
    """Named monotonic counters + high-water and mean gauges for the
    replication and serving stacks (`net/`, `serve/`): frames
    sent/rejected, retries, buffer high-water — and the serve layer's
    admitted / rejected_* / evictions / restores counts plus the
    ``batch_fill_ratio`` mean gauge (`serve/batcher.py`).

    The wire-layer analog of the reference's counting-allocator
    instrumentation (`src/alloc.rs:13-50`): cheap increments everywhere,
    one ``summary()`` dump. ``incr`` counts events; ``hiwater`` keeps the
    max of a gauge (e.g. causal-buffer pending size); ``sample`` feeds a
    running mean/min/max (e.g. per-tick batch fill ratio), reported as
    ``<name>_mean``/``<name>_min``/``<name>_max`` with its sample count
    as ``<name>_samples`` — means alone hid the PR-6 ``ops_per_step``
    skew, so the extremes now always ride along (ISSUE 8).  For full
    distributions (percentiles) use ``obs.registry.MetricsRegistry``,
    which extends this class with bounded histograms.
    """

    def __init__(self) -> None:
        self._counts: Dict[str, int] = {}
        self._hiwater: Dict[str, int] = {}
        # name -> (total, count, min, max)
        self._samples: Dict[str, Tuple[float, int, float, float]] = {}

    def incr(self, name: str, by: int = 1) -> None:
        self._counts[name] = self._counts.get(name, 0) + by

    def hiwater(self, name: str, value: int) -> None:
        if value > self._hiwater.get(name, 0):
            self._hiwater[name] = value

    def sample(self, name: str, value: float) -> None:
        v = float(value)
        total, count, vmin, vmax = self._samples.get(
            name, (0.0, 0, float("inf"), float("-inf")))
        self._samples[name] = (total + v, count + 1,
                               min(vmin, v), max(vmax, v))

    def mean(self, name: str) -> float:
        total, count, _vmin, _vmax = self._samples.get(
            name, (0.0, 0, 0.0, 0.0))
        return total / count if count else 0.0

    def _sample_stats(self, name: str) -> Tuple[float, int, float, float]:
        """(total, count, min, max) of one sample gauge (zeros when
        empty) — the registry exporters read through this."""
        total, count, vmin, vmax = self._samples.get(
            name, (0.0, 0, 0.0, 0.0))
        if not count:
            return 0.0, 0, 0.0, 0.0
        return total, count, vmin, vmax

    def get(self, name: str) -> int:
        return self._counts.get(name, self._hiwater.get(name, 0))

    def summary(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self._counts)
        for k, v in self._hiwater.items():
            out[k] = v
        for k in self._samples:
            total, count, vmin, vmax = self._sample_stats(k)
            out[f"{k}_mean"] = round(total / count, 6) if count else 0.0
            out[f"{k}_samples"] = count
            out[f"{k}_min"] = vmin
            out[f"{k}_max"] = vmax
        return out


def percentiles(samples, points=(50, 99)) -> Dict[str, float]:
    """Nearest-rank percentiles of a sample list as ``{"p50": ..}``.

    The serve layer's admission→applied latency summary (and the bench
    rows') share this one definition so p99 can't silently mean
    different things in different reports. Empty input -> zeros.
    """
    out: Dict[str, float] = {}
    ss = sorted(float(s) for s in samples)
    for p in points:
        if not ss:
            out[f"p{p}"] = 0.0
        else:
            idx = min(len(ss) - 1, int(round((len(ss) - 1) * p / 100.0)))
            out[f"p{p}"] = ss[idx]
    return out


def measured_hbm_bytes():
    """(bytes, reason) live device allocation from the runtime.

    Fills bench rows' ``hbm_bytes_measured`` from
    ``jax.local_devices()[0].memory_stats()`` where the backend exposes
    it (TPU, and newer CPU runtimes); returns ``(None, reason)`` with a
    human-readable reason otherwise, so rows carry an explanation
    instead of a bare null (VERDICT r5 missing #3 / next #5).
    """
    try:
        import jax

        dev = jax.local_devices()[0]
    except Exception as e:  # backend down / not initialized
        return None, f"no device backend available ({type(e).__name__})"
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return None, (f"{dev.platform} runtime exposes no device "
                      f"memory_stats on this platform")
    # Usage counters ONLY: bytes_limit is device capacity, not live
    # allocation — reporting it as "measured" would be off by orders of
    # magnitude.
    for key in ("bytes_in_use", "peak_bytes_in_use"):
        if key in stats:
            return int(stats[key]), None
    return None, (f"memory_stats present but carries no usage counter "
                  f"(keys: {sorted(stats)[:8]})")


def causal_buffer_stats(buf) -> dict:
    """Introspection snapshot of a ``parallel.causal.CausalBuffer`` for
    the session layer and dashboards: pending count and high-water,
    duplicate-drop / eviction counters, per-agent watermark gaps."""
    return {
        "pending": buf.pending,
        "high_water": buf.high_water,
        "duplicates_dropped": buf.duplicates_dropped,
        "evictions": buf.evictions,
        "watermarks": buf.watermarks(),
        "agent_gaps": buf.gap_stats(),
    }


class Throughput:
    """Ops/sec accumulator for bench loops.

    >>> meter = Throughput()
    >>> with meter.measure(ops=1000): ...   # doctest: +SKIP
    >>> meter.ops_per_sec                   # doctest: +SKIP
    """

    def __init__(self) -> None:
        self.ops = 0
        self.seconds = 0.0
        self.samples = 0

    def add(self, ops: int, seconds: float) -> None:
        self.ops += ops
        self.seconds += seconds
        self.samples += 1

    def measure(self, ops: int):
        meter = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                meter.add(ops, time.perf_counter() - self.t0)
                return False

        return _Ctx()

    @property
    def ops_per_sec(self) -> float:
        return self.ops / self.seconds if self.seconds else 0.0

    def summary(self) -> dict:
        return {"ops": self.ops, "seconds": round(self.seconds, 6),
                "ops_per_sec": round(self.ops_per_sec, 1),
                "samples": self.samples}


def run_stats(res, doc_index: int = 0) -> dict:
    """Device RUN-state health metrics for the block engines' results
    (``RleResult``/``RleMixedResult``) — the `print_stats` family
    (`root.rs:293-326`) read directly off the run representation:

    - ``run_rows`` / ``live_rows`` / ``tombstone_rows``
    - ``chars`` / ``live_chars`` and ``chars_per_run`` (the compaction
      ratio that decides VMEM plane sizes, PERF.md §3)
    - ``blocks_used`` / ``block_fill`` (occupied rows / (blocks * K) —
      the leaf-split half-fullness the 2.5x capacity budget covers)
    - run-length histogram (`split_list/mod.rs:418`'s "compacts to N")
    """
    K = res.block_k
    ordc = np.asarray(res.ordp)[:, doc_index]
    lenc = np.asarray(res.lenp)[:, doc_index]
    rows = np.asarray(res.rows)[:, doc_index]
    nlog = int(np.asarray(res.meta)[0, doc_index])
    blk = np.asarray(res.blkord)[:, doc_index]
    o_parts, l_parts = [], []
    for sl in range(nlog):
        b, r = int(blk[sl]), int(rows[sl])
        o_parts.append(ordc[b * K: b * K + r])
        l_parts.append(lenc[b * K: b * K + r])
    o = (np.concatenate(o_parts) if o_parts else np.zeros(0, np.int32))
    ln = (np.concatenate(l_parts) if l_parts else np.zeros(0, np.int32))
    live = o > 0
    spans = [(0, 0, 0, int(l if lv else -l)) for l, lv in zip(ln, live)]
    total_rows = int(len(o))
    return {
        "run_rows": total_rows,
        "live_rows": int(live.sum()),
        "tombstone_rows": int((~live & (o != 0)).sum()),
        "chars": int(ln.sum()),
        "live_chars": int(ln[live].sum()),
        "chars_per_run": round(float(ln.sum()) / max(total_rows, 1), 2),
        "blocks_used": nlog,
        "block_fill": round(total_rows / max(nlog * K, 1), 3),
        "run_histogram": span_histogram(spans),
    }


def print_stats(doc, detailed: bool = False) -> None:
    """Human-readable dump (`doc.rs:492-498` analog). Downloads a device
    doc once and shares the spans across both stat passes."""
    spans = _spans_of(doc)
    d = doc_stats(doc, spans=spans)
    m = memory_stats(doc, spans=spans)
    print(f"doc: {d['items']} items ({d['live']} live, "
          f"{d['tombstones']} tombstones), {d['merged_spans']} merged spans "
          f"(compaction {d['compaction']:.1f}x)")
    print(f"  memory: {m['total_bytes']:,} B actual vs "
          f"{m['efficient_bytes']:,} B compacted "
          f"({m['overhead']:.1f}x overhead)")
    if detailed:
        print(f"  span histogram: {d['span_histogram']}")
        for k in ("deletes_entries", "double_delete_entries", "txn_entries"):
            if k in d:
                print(f"  {k}: {d[k]}")

"""Checkpoint / resume: the document state as flat numpy arrays.

The reference implements no persistence, but its state is fully determined
by the RLE logs (SURVEY §5 "Checkpoint/resume": client_with_order +
item_orders + deletes + txns determine the document; the range tree is a
cache of their materialization). This module makes that concrete:

- a checkpoint is one ``.npz`` of flat columns — the same arrays that are
  the host↔device wire format (SURVEY §2 `Rle` row), so saving a document
  costs a ``np.savez`` and no re-encoding;
- agent names ride in a JSON header (names are the only strings — numeric
  ids are peer-local, `README.md:33-35`);
- resume rebuilds a ``models.oracle.ListCRDT`` bit-identically (asserted
  by tests via doc_spans/frontier/log equality), and the device engines
  warm-start from it via ``span_arrays.upload_oracle``.

``save_flat_doc``/``load_flat_doc`` checkpoint a device ``FlatDoc``
directly (download once, upload on load) for the streaming-apply path
(`BASELINE.json` config 5's periodic host↔TPU resync).

Integrity (`net/` fault model applied to disk): every checkpoint carries a
CRC32 over its array contents plus a format version, and loads REFUSE
corrupted, truncated, or version-mismatched files with a typed
``CheckpointError`` — a resume must restore bit-identical state or fail
precisely, never load garbage into a serving replica.
"""
from __future__ import annotations

import json
import zipfile
import zlib
from typing import Dict, List

import numpy as np

from ..common import ROOT_ORDER
from .rle import (
    KCRDTSpan,
    KDeleteEntry,
    KDoubleDelete,
    KOrderSpan,
    Rle,
    TxnSpan,
)

# v2: adds the content CRC32 (zlib) to the meta header (v1 files predate
# integrity checking and are refused — re-save from a live document).
FORMAT_VERSION = 2


class CheckpointError(Exception):
    """A checkpoint failed to load: corrupted, truncated, or wrong
    format version. The file is refused whole — no partial state."""


def _meta_to_array(meta: dict) -> np.ndarray:
    return np.frombuffer(json.dumps(meta).encode("utf-8"), dtype=np.uint8)


def _meta_from_array(arr: np.ndarray) -> dict:
    return json.loads(arr.tobytes().decode("utf-8"))


def _content_crc(arrays: Dict[str, np.ndarray]) -> int:
    """CRC32 over every array's raw bytes, key-sorted (stable across
    save/load regardless of npz member order). ``zlib.crc32`` (C speed)
    rather than the wire codec's pure-Python CRC32C: checkpoints are
    MB-to-GB arrays where the table loop would cost ~0.25 s/MiB on
    every save AND load; the integrity guarantee is the same."""
    crc = 0
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFF_FFFF


def _save_npz(path: str, meta: dict, arrays: Dict[str, np.ndarray]) -> None:
    meta = dict(meta)
    meta["crc"] = _content_crc(arrays)
    np.savez(path, meta=_meta_to_array(meta), **arrays)


def _load_npz(path: str, expect_kind: str):
    """Open + fully validate a checkpoint; returns (meta, {key: array}).

    Raises ``CheckpointError`` on anything short of a bit-perfect file:
    unreadable/truncated zip, missing members, undecodable meta, version
    or kind mismatch, or content CRC mismatch.
    """
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
    except (OSError, EOFError, ValueError, KeyError,
            zipfile.BadZipFile) as e:
        raise CheckpointError(f"unreadable checkpoint {path!r}: {e}") from e
    if "meta" not in arrays:
        raise CheckpointError(f"checkpoint {path!r} has no meta header")
    try:
        meta = _meta_from_array(arrays.pop("meta"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointError(
            f"checkpoint {path!r}: undecodable meta header: {e}") from e
    version = meta.get("version")
    if version != FORMAT_VERSION:
        raise CheckpointError(
            f"checkpoint {path!r}: format version {version!r}, "
            f"this build reads {FORMAT_VERSION}")
    if meta.get("kind", "oracle") != expect_kind:
        raise CheckpointError(
            f"checkpoint {path!r}: kind {meta.get('kind', 'oracle')!r}, "
            f"expected {expect_kind!r}")
    stored = meta.get("crc")
    computed = _content_crc(arrays)
    if stored != computed:
        raise CheckpointError(
            f"checkpoint {path!r}: content CRC mismatch "
            f"(stored {stored!r}, computed {computed:#010x}) — "
            f"file corrupted, refusing to load")
    return meta, arrays


def save_doc(doc, path: str) -> None:
    """Serialize an oracle ``ListCRDT`` to ``path`` (.npz)."""
    n = doc.n
    cwo = list(doc.client_with_order)
    deletes = list(doc.deletes)
    dds = list(doc.double_deletes)
    txns = list(doc.txns)
    item_orders = [
        (a, e.seq, e.order, e.length)
        for a, cd in enumerate(doc.client_data)
        for e in cd.item_orders
    ]
    parents = [
        (i, p) for i, t in enumerate(txns) for p in t.parents
    ]
    meta = {
        "version": FORMAT_VERSION,
        "kind": "oracle",
        "agents": [cd.name for cd in doc.client_data],
        "n": n,
    }
    arrays = dict(
        order=doc.order[:n],
        origin_left=doc.origin_left[:n],
        origin_right=doc.origin_right[:n],
        deleted=doc.deleted[:n],
        chars=doc.chars[:n],
        frontier=np.asarray(doc.frontier, dtype=np.uint32),
        cwo=np.asarray([(e.order, e.agent, e.seq, e.length) for e in cwo],
                       dtype=np.int64).reshape(-1, 4),
        item_orders=np.asarray(item_orders, dtype=np.int64).reshape(-1, 4),
        deletes=np.asarray([(e.op_order, e.target, e.length)
                            for e in deletes],
                           dtype=np.int64).reshape(-1, 3),
        double_deletes=np.asarray([(e.target, e.length, e.excess)
                                   for e in dds],
                                  dtype=np.int64).reshape(-1, 3),
        txns=np.asarray([(t.order, t.length, t.shadow) for t in txns],
                        dtype=np.int64).reshape(-1, 3),
        txn_parents=np.asarray(parents, dtype=np.int64).reshape(-1, 2),
    )
    _save_npz(path, meta, arrays)


def load_doc(path: str):
    """Rebuild an oracle ``ListCRDT`` from a ``save_doc`` checkpoint.

    Raises ``CheckpointError`` if the file is corrupted, truncated, or a
    different format version — never returns partial state.
    """
    meta, z = _load_npz(path, expect_kind="oracle")
    try:
        n = int(meta["n"])
        agents = meta["agents"]
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointError(f"checkpoint {path!r}: bad meta: {e}") from e

    try:
        return _rebuild_oracle(z, n, agents)
    except (KeyError, ValueError, IndexError) as e:
        raise CheckpointError(
            f"checkpoint {path!r}: inconsistent contents: {e}") from e


def _rebuild_oracle(z, n: int, agents):
    from ..models.oracle import ClientData, ListCRDT

    doc = ListCRDT(capacity=max(n, 64))
    doc.n = n
    doc.order[:n] = z["order"]
    doc.origin_left[:n] = z["origin_left"]
    doc.origin_right[:n] = z["origin_right"]
    doc.deleted[:n] = z["deleted"]
    doc.chars[:n] = z["chars"]
    doc.rebuild_raw_index()  # the body was set directly, not spliced
    doc.frontier = [int(o) for o in z["frontier"]]

    doc.client_data = [ClientData(name) for name in agents]
    for a, seq, order, length in z["item_orders"]:
        doc.client_data[int(a)].item_orders.append(
            KOrderSpan(int(seq), int(order), int(length)))
    for order, agent, seq, length in z["cwo"]:
        doc.client_with_order.append(
            KCRDTSpan(int(order), int(agent), int(seq), int(length)))
    for op_order, target, length in z["deletes"]:
        doc.deletes.append(
            KDeleteEntry(int(op_order), int(target), int(length)))
    for target, length, excess in z["double_deletes"]:
        doc.double_deletes.append(
            KDoubleDelete(int(target), int(length), int(excess)))
    parents_by_txn: List[List[int]] = [[] for _ in range(len(z["txns"]))]
    for i, p in z["txn_parents"]:
        parents_by_txn[int(i)].append(int(p))
    for (order, length, shadow), ps in zip(z["txns"], parents_by_txn):
        doc.txns.append(TxnSpan(int(order), int(length), int(shadow), ps))
    return doc


def save_flat_doc(flat, path: str) -> None:
    """Checkpoint a device ``FlatDoc`` (downloads once). Accepts an
    unbatched doc or a ``stack_docs`` batch (leading doc axis on every
    column, including ``n``/``next_order``)."""
    arrays = dict(
        signed=np.asarray(flat.signed),
        ol_log=np.asarray(flat.ol_log),
        or_log=np.asarray(flat.or_log),
        rank_log=np.asarray(flat.rank_log),
        chars_log=np.asarray(flat.chars_log),
        n=np.asarray(flat.n),
        next_order=np.asarray(flat.next_order),
    )
    _save_npz(path, {"version": FORMAT_VERSION, "kind": "flat"}, arrays)


def load_flat_doc(path: str):
    """Rebuild a device ``FlatDoc`` from a ``save_flat_doc`` checkpoint.

    Raises ``CheckpointError`` on corruption/truncation/version mismatch.
    """
    import jax.numpy as jnp

    from ..ops.span_arrays import FlatDoc, I32, U32

    _, z = _load_npz(path, expect_kind="flat")
    try:
        return _rebuild_flat(z, FlatDoc, jnp, I32, U32)
    except (KeyError, ValueError, IndexError) as e:
        raise CheckpointError(
            f"checkpoint {path!r}: inconsistent contents: {e}") from e


def _rebuild_flat(z, FlatDoc, jnp, I32, U32):
    return FlatDoc(
        signed=jnp.asarray(z["signed"]),
        ol_log=jnp.asarray(z["ol_log"]),
        or_log=jnp.asarray(z["or_log"]),
        rank_log=jnp.asarray(z["rank_log"]),
        chars_log=jnp.asarray(z["chars_log"]),
        n=jnp.asarray(z["n"], I32),
        next_order=jnp.asarray(z["next_order"], U32),
    )

"""Checkpoint / resume: the document state as flat numpy arrays.

The reference implements no persistence, but its state is fully determined
by the RLE logs (SURVEY §5 "Checkpoint/resume": client_with_order +
item_orders + deletes + txns determine the document; the range tree is a
cache of their materialization). This module makes that concrete:

- a checkpoint is one ``.npz`` of flat columns — the same arrays that are
  the host↔device wire format (SURVEY §2 `Rle` row), so saving a document
  costs a ``np.savez`` and no re-encoding;
- agent names ride in a JSON header (names are the only strings — numeric
  ids are peer-local, `README.md:33-35`);
- resume rebuilds a ``models.oracle.ListCRDT`` bit-identically (asserted
  by tests via doc_spans/frontier/log equality), and the device engines
  warm-start from it via ``span_arrays.upload_oracle``.

``save_flat_doc``/``load_flat_doc`` checkpoint a device ``FlatDoc``
directly (download once, upload on load) for the streaming-apply path
(`BASELINE.json` config 5's periodic host↔TPU resync).
"""
from __future__ import annotations

import json
from typing import List

import numpy as np

from ..common import ROOT_ORDER
from .rle import (
    KCRDTSpan,
    KDeleteEntry,
    KDoubleDelete,
    KOrderSpan,
    Rle,
    TxnSpan,
)

FORMAT_VERSION = 1


def _meta_to_array(meta: dict) -> np.ndarray:
    return np.frombuffer(json.dumps(meta).encode("utf-8"), dtype=np.uint8)


def _meta_from_array(arr: np.ndarray) -> dict:
    return json.loads(arr.tobytes().decode("utf-8"))


def save_doc(doc, path: str) -> None:
    """Serialize an oracle ``ListCRDT`` to ``path`` (.npz)."""
    n = doc.n
    cwo = list(doc.client_with_order)
    deletes = list(doc.deletes)
    dds = list(doc.double_deletes)
    txns = list(doc.txns)
    item_orders = [
        (a, e.seq, e.order, e.length)
        for a, cd in enumerate(doc.client_data)
        for e in cd.item_orders
    ]
    parents = [
        (i, p) for i, t in enumerate(txns) for p in t.parents
    ]
    meta = {
        "version": FORMAT_VERSION,
        "agents": [cd.name for cd in doc.client_data],
        "n": n,
    }
    np.savez(
        path,
        meta=_meta_to_array(meta),
        order=doc.order[:n],
        origin_left=doc.origin_left[:n],
        origin_right=doc.origin_right[:n],
        deleted=doc.deleted[:n],
        chars=doc.chars[:n],
        frontier=np.asarray(doc.frontier, dtype=np.uint32),
        cwo=np.asarray([(e.order, e.agent, e.seq, e.length) for e in cwo],
                       dtype=np.int64).reshape(-1, 4),
        item_orders=np.asarray(item_orders, dtype=np.int64).reshape(-1, 4),
        deletes=np.asarray([(e.op_order, e.target, e.length)
                            for e in deletes],
                           dtype=np.int64).reshape(-1, 3),
        double_deletes=np.asarray([(e.target, e.length, e.excess)
                                   for e in dds],
                                  dtype=np.int64).reshape(-1, 3),
        txns=np.asarray([(t.order, t.length, t.shadow) for t in txns],
                        dtype=np.int64).reshape(-1, 3),
        txn_parents=np.asarray(parents, dtype=np.int64).reshape(-1, 2),
    )


def load_doc(path: str):
    """Rebuild an oracle ``ListCRDT`` from a ``save_doc`` checkpoint."""
    from ..models.oracle import ClientData, ListCRDT

    z = np.load(path)
    meta = _meta_from_array(z["meta"])
    assert meta["version"] == FORMAT_VERSION, (
        f"unknown checkpoint version {meta['version']}")
    n = int(meta["n"])

    doc = ListCRDT(capacity=max(n, 64))
    doc.n = n
    doc.order[:n] = z["order"]
    doc.origin_left[:n] = z["origin_left"]
    doc.origin_right[:n] = z["origin_right"]
    doc.deleted[:n] = z["deleted"]
    doc.chars[:n] = z["chars"]
    doc.frontier = [int(o) for o in z["frontier"]]

    doc.client_data = [ClientData(name) for name in meta["agents"]]
    for a, seq, order, length in z["item_orders"]:
        doc.client_data[int(a)].item_orders.append(
            KOrderSpan(int(seq), int(order), int(length)))
    for order, agent, seq, length in z["cwo"]:
        doc.client_with_order.append(
            KCRDTSpan(int(order), int(agent), int(seq), int(length)))
    for op_order, target, length in z["deletes"]:
        doc.deletes.append(
            KDeleteEntry(int(op_order), int(target), int(length)))
    for target, length, excess in z["double_deletes"]:
        doc.double_deletes.append(
            KDoubleDelete(int(target), int(length), int(excess)))
    parents_by_txn: List[List[int]] = [[] for _ in range(len(z["txns"]))]
    for i, p in z["txn_parents"]:
        parents_by_txn[int(i)].append(int(p))
    for (order, length, shadow), ps in zip(z["txns"], parents_by_txn):
        doc.txns.append(TxnSpan(int(order), int(length), int(shadow), ps))
    return doc


def save_flat_doc(flat, path: str) -> None:
    """Checkpoint a device ``FlatDoc`` (downloads once). Accepts an
    unbatched doc or a ``stack_docs`` batch (leading doc axis on every
    column, including ``n``/``next_order``)."""
    np.savez(
        path,
        meta=_meta_to_array({"version": FORMAT_VERSION, "kind": "flat"}),
        signed=np.asarray(flat.signed),
        ol_log=np.asarray(flat.ol_log),
        or_log=np.asarray(flat.or_log),
        rank_log=np.asarray(flat.rank_log),
        chars_log=np.asarray(flat.chars_log),
        n=np.asarray(flat.n),
        next_order=np.asarray(flat.next_order),
    )


def load_flat_doc(path: str):
    """Rebuild a device ``FlatDoc`` from a ``save_flat_doc`` checkpoint."""
    import jax.numpy as jnp

    from ..ops.span_arrays import FlatDoc, I32, U32

    z = np.load(path)
    meta = _meta_from_array(z["meta"])
    assert meta.get("kind") == "flat", "not a FlatDoc checkpoint"
    return FlatDoc(
        signed=jnp.asarray(z["signed"]),
        ol_log=jnp.asarray(z["ol_log"]),
        or_log=jnp.asarray(z["or_log"]),
        rank_log=jnp.asarray(z["rank_log"]),
        chars_log=jnp.asarray(z["chars_log"]),
        n=jnp.asarray(z["n"], I32),
        next_order=jnp.asarray(z["next_order"], U32),
    )

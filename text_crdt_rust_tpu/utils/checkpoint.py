"""Checkpoint / resume: the document state as flat numpy arrays.

The reference implements no persistence, but its state is fully determined
by the RLE logs (SURVEY §5 "Checkpoint/resume": client_with_order +
item_orders + deletes + txns determine the document; the range tree is a
cache of their materialization). This module makes that concrete:

- a checkpoint is one ``.npz`` of flat columns — the same arrays that are
  the host↔device wire format (SURVEY §2 `Rle` row), so saving a document
  costs a ``np.savez`` and no re-encoding;
- agent names ride in a JSON header (names are the only strings — numeric
  ids are peer-local, `README.md:33-35`);
- resume rebuilds a ``models.oracle.ListCRDT`` bit-identically (asserted
  by tests via doc_spans/frontier/log equality), and the device engines
  warm-start from it via ``span_arrays.upload_oracle``.

``save_flat_doc``/``load_flat_doc`` checkpoint a device ``FlatDoc``
directly (download once, upload on load) for the streaming-apply path
(`BASELINE.json` config 5's periodic host↔TPU resync).

Integrity (`net/` fault model applied to disk): every checkpoint carries a
CRC32 over its array contents plus a format version, and loads REFUSE
corrupted, truncated, or version-mismatched files with a typed
``CheckpointError`` — a resume must restore bit-identical state or fail
precisely, never load garbage into a serving replica.

Incremental (delta) checkpoints (ISSUE 7): a ``kind="delta"`` file
records the history since a referenced predecessor — the ops exported
by ``models.sync.export_txns_since`` from the predecessor's
``next_order``, encoded through the columnar wire format
(``net/columnar``) — so a warm save costs O(ops since last save)
instead of O(doc).  Chain integrity mirrors the wire's hard-rejection
contract: each delta names its predecessor's content CRC
(``prev_crc``) and order interval; a load walks base → deltas
verifying every link and REFUSES a stale, missing, or mismatched base
with a typed error.  Restore = load base + replay the decoded txns —
replay assigns the same orders in the same sequence the live document
did, so a chain restore is bit-identical to a full-snapshot restore
(``tests/test_checkpoint_integrity.py`` pins it).  ``CheckpointChain``
manages one document's base + links with periodic compaction.
"""
from __future__ import annotations

import json
import os
import zipfile
import zlib
from typing import Dict, List, Optional

import numpy as np

from .rle import (
    KCRDTSpan,
    KDeleteEntry,
    KDoubleDelete,
    KOrderSpan,
    TxnSpan,
)

# v2 added the content CRC32 (zlib) to the meta header; v3 adds the
# ``next_order`` meta (the delta-chain anchor) and the ``delta`` kind.
# Older versions are refused — re-save from a live document.
FORMAT_VERSION = 3


class CheckpointError(Exception):
    """A checkpoint failed to load: corrupted, truncated, or wrong
    format version. The file is refused whole — no partial state."""


def _meta_to_array(meta: dict) -> np.ndarray:
    return np.frombuffer(json.dumps(meta).encode("utf-8"), dtype=np.uint8)


def _meta_from_array(arr: np.ndarray) -> dict:
    return json.loads(arr.tobytes().decode("utf-8"))


def _content_crc(arrays: Dict[str, np.ndarray]) -> int:
    """CRC32 over every array's raw bytes, key-sorted (stable across
    save/load regardless of npz member order). ``zlib.crc32`` (C speed)
    rather than the wire codec's pure-Python CRC32C: checkpoints are
    MB-to-GB arrays where the table loop would cost ~0.25 s/MiB on
    every save AND load; the integrity guarantee is the same."""
    crc = 0
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFF_FFFF


def _save_npz(path: str, meta: dict, arrays: Dict[str, np.ndarray]) -> int:
    """Write one checkpoint member file; returns its content CRC (the
    chain-link identity delta checkpoints reference)."""
    meta = dict(meta)
    crc = meta["crc"] = _content_crc(arrays)
    np.savez(path, meta=_meta_to_array(meta), **arrays)
    return crc


def _load_npz(path: str, expect_kind: str):
    """Open + fully validate a checkpoint; returns (meta, {key: array}).

    Raises ``CheckpointError`` on anything short of a bit-perfect file:
    unreadable/truncated zip, missing members, undecodable meta, version
    or kind mismatch, or content CRC mismatch.
    """
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
    except (OSError, EOFError, ValueError, KeyError,
            NotImplementedError, zipfile.BadZipFile) as e:
        # NotImplementedError: zipfile refuses exotic flag bits a
        # corrupting flip can set (e.g. "compressed patched data") —
        # still a corrupt file, still a typed refusal.
        raise CheckpointError(f"unreadable checkpoint {path!r}: {e}") from e
    if "meta" not in arrays:
        raise CheckpointError(f"checkpoint {path!r} has no meta header")
    try:
        meta = _meta_from_array(arrays.pop("meta"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointError(
            f"checkpoint {path!r}: undecodable meta header: {e}") from e
    version = meta.get("version")
    if version != FORMAT_VERSION:
        raise CheckpointError(
            f"checkpoint {path!r}: format version {version!r}, "
            f"this build reads {FORMAT_VERSION}")
    if meta.get("kind", "oracle") != expect_kind:
        raise CheckpointError(
            f"checkpoint {path!r}: kind {meta.get('kind', 'oracle')!r}, "
            f"expected {expect_kind!r}")
    stored = meta.get("crc")
    computed = _content_crc(arrays)
    if stored != computed:
        raise CheckpointError(
            f"checkpoint {path!r}: content CRC mismatch "
            f"(stored {stored!r}, computed {computed:#010x}) — "
            f"file corrupted, refusing to load")
    return meta, arrays


def save_doc(doc, path: str, extra_meta: Optional[dict] = None) -> dict:
    """Serialize an oracle ``ListCRDT`` to ``path`` (.npz).

    Returns ``{"crc", "next_order", "bytes"}`` — what a delta chain
    needs to reference this file as its base.

    ``extra_meta`` rides in the JSON header under caller-chosen keys
    (the serve tier stores its doc id and local-edit replay watermark
    there).  Loads ignore unknown meta keys, so extra meta is
    backward- and forward-compatible without a FORMAT_VERSION bump;
    core keys cannot be overridden."""
    n = doc.n
    cwo = list(doc.client_with_order)
    deletes = list(doc.deletes)
    dds = list(doc.double_deletes)
    txns = list(doc.txns)
    item_orders = [
        (a, e.seq, e.order, e.length)
        for a, cd in enumerate(doc.client_data)
        for e in cd.item_orders
    ]
    parents = [
        (i, p) for i, t in enumerate(txns) for p in t.parents
    ]
    meta = dict(extra_meta or {})
    meta.update({
        "version": FORMAT_VERSION,
        "kind": "oracle",
        "agents": [cd.name for cd in doc.client_data],
        "n": n,
        "next_order": doc.get_next_order(),
    })
    arrays = dict(
        order=doc.order[:n],
        origin_left=doc.origin_left[:n],
        origin_right=doc.origin_right[:n],
        deleted=doc.deleted[:n],
        chars=doc.chars[:n],
        frontier=np.asarray(doc.frontier, dtype=np.uint32),
        cwo=np.asarray([(e.order, e.agent, e.seq, e.length) for e in cwo],
                       dtype=np.int64).reshape(-1, 4),
        item_orders=np.asarray(item_orders, dtype=np.int64).reshape(-1, 4),
        deletes=np.asarray([(e.op_order, e.target, e.length)
                            for e in deletes],
                           dtype=np.int64).reshape(-1, 3),
        double_deletes=np.asarray([(e.target, e.length, e.excess)
                                   for e in dds],
                                  dtype=np.int64).reshape(-1, 3),
        txns=np.asarray([(t.order, t.length, t.shadow) for t in txns],
                        dtype=np.int64).reshape(-1, 3),
        txn_parents=np.asarray(parents, dtype=np.int64).reshape(-1, 2),
    )
    crc = _save_npz(path, meta, arrays)
    return {"crc": crc, "next_order": meta["next_order"],
            "bytes": os.path.getsize(path)}


def load_doc(path: str):
    """Rebuild an oracle ``ListCRDT`` from a ``save_doc`` checkpoint.

    Raises ``CheckpointError`` if the file is corrupted, truncated, or a
    different format version — never returns partial state.
    """
    return _load_doc_with_meta(path)[0]


def _load_doc_with_meta(path: str):
    """``(doc, meta)`` from one validated read — chain restores need the
    base's CRC/next_order without re-reading and re-checksumming the
    whole O(doc) file."""
    meta, z = _load_npz(path, expect_kind="oracle")
    try:
        n = int(meta["n"])
        agents = meta["agents"]
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointError(f"checkpoint {path!r}: bad meta: {e}") from e

    try:
        return _rebuild_oracle(z, n, agents), meta
    except (KeyError, ValueError, IndexError) as e:
        raise CheckpointError(
            f"checkpoint {path!r}: inconsistent contents: {e}") from e


def _rebuild_oracle(z, n: int, agents):
    from ..models.oracle import ClientData, ListCRDT

    doc = ListCRDT(capacity=max(n, 64))
    doc.n = n
    doc.order[:n] = z["order"]
    doc.origin_left[:n] = z["origin_left"]
    doc.origin_right[:n] = z["origin_right"]
    doc.deleted[:n] = z["deleted"]
    doc.chars[:n] = z["chars"]
    doc.rebuild_raw_index()  # the body was set directly, not spliced
    doc.frontier = [int(o) for o in z["frontier"]]

    doc.client_data = [ClientData(name) for name in agents]
    for a, seq, order, length in z["item_orders"]:
        doc.client_data[int(a)].item_orders.append(
            KOrderSpan(int(seq), int(order), int(length)))
    for order, agent, seq, length in z["cwo"]:
        doc.client_with_order.append(
            KCRDTSpan(int(order), int(agent), int(seq), int(length)))
    for op_order, target, length in z["deletes"]:
        doc.deletes.append(
            KDeleteEntry(int(op_order), int(target), int(length)))
    for target, length, excess in z["double_deletes"]:
        doc.double_deletes.append(
            KDoubleDelete(int(target), int(length), int(excess)))
    parents_by_txn: List[List[int]] = [[] for _ in range(len(z["txns"]))]
    for i, p in z["txn_parents"]:
        parents_by_txn[int(i)].append(int(p))
    for (order, length, shadow), ps in zip(z["txns"], parents_by_txn):
        doc.txns.append(TxnSpan(int(order), int(length), int(shadow), ps))
    return doc


# -- incremental (delta) checkpoints -----------------------------------------

def save_delta(doc, path: str, *, base_crc: int, prev_crc: int,
               from_order: int, extra_meta: Optional[dict] = None) -> dict:
    """Write the history ``from_order..`` as one delta link at ``path``.

    ``prev_crc`` names the immediate predecessor file (the base for the
    first link, the previous delta after that) and ``base_crc`` the
    chain's base — both are content CRCs, so a link can never be
    replayed onto the wrong snapshot.  The ops ride as a columnar wire
    stream (``net/columnar.encode_txns_stream``): the save costs
    O(ops since ``from_order``), not O(doc).
    """
    from ..models.sync import export_txns_since
    from ..net import columnar

    next_order = doc.get_next_order()
    if from_order > next_order:
        raise CheckpointError(
            f"delta from_order {from_order} is ahead of the document "
            f"({next_order}) — stale chain state, re-save a full base")
    blob = columnar.encode_txns_stream(export_txns_since(doc, from_order))
    meta = dict(extra_meta or {})
    meta.update({
        "version": FORMAT_VERSION,
        "kind": "delta",
        "base_crc": int(base_crc),
        "prev_crc": int(prev_crc),
        "from_order": int(from_order),
        "next_order": int(next_order),
    })
    arrays = dict(txns_blob=np.frombuffer(blob, dtype=np.uint8))
    crc = _save_npz(path, meta, arrays)
    return {"crc": crc, "next_order": next_order,
            "ops": next_order - from_order,
            "bytes": os.path.getsize(path)}


def load_delta(path: str):
    """Load + fully validate one delta link; returns
    ``(meta, [RemoteTxn])``. Corruption anywhere — file, meta, or the
    embedded wire stream — is a typed ``CheckpointError``."""
    from ..net import codec

    meta, arrays = _load_npz(path, expect_kind="delta")
    for key in ("base_crc", "prev_crc", "from_order", "next_order"):
        if not isinstance(meta.get(key), int):
            raise CheckpointError(
                f"delta checkpoint {path!r}: missing/invalid {key!r} meta")
    blob = bytes(arrays["txns_blob"].tobytes()) \
        if "txns_blob" in arrays else None
    if blob is None:
        raise CheckpointError(
            f"delta checkpoint {path!r} has no txns_blob member")
    txns: List = []
    try:
        for kind, value in codec.decode_frames(blob):
            if kind != codec.KIND_TXNS:
                raise CheckpointError(
                    f"delta checkpoint {path!r}: non-TXNS frame in blob")
            txns.extend(value)
    except codec.CodecError as e:
        raise CheckpointError(
            f"delta checkpoint {path!r}: corrupt txn stream: {e}") from e
    return meta, txns


def replay_chain(base_path: str, delta_paths: List[str]):
    """Restore a document from ``base`` + delta links, verifying every
    chain invariant: each link's ``prev_crc`` must equal the content CRC
    of its predecessor file, ``base_crc`` the base's, and the order
    intervals must tile ``base.next_order..`` exactly.  Replay applies
    the decoded txns in stream order — order assignment is sequential,
    so the restored document is the one the live replica held.
    """
    return replay_chain_with_meta(base_path, delta_paths)[0]


def replay_chain_with_meta(base_path: str, delta_paths: List[str]):
    """``replay_chain`` that also returns the TIP file's meta header
    (the last link's, or the base's for a link-less chain) — where the
    serve tier's extra meta (doc id, local-edit replay watermark) rides
    at its freshest."""
    doc, base_meta = _load_doc_with_meta(base_path)
    tip_meta = base_meta
    base_crc = base_meta["crc"]
    prev_crc = base_crc
    cursor = int(base_meta.get("next_order", 0))
    for link_path in delta_paths:
        meta, txns = load_delta(link_path)
        if meta["base_crc"] != base_crc:
            raise CheckpointError(
                f"delta {link_path!r} references base crc "
                f"{meta['base_crc']:#010x}, chain base is {base_crc:#010x} "
                f"— stale or foreign base, refusing to replay")
        if meta["prev_crc"] != prev_crc:
            raise CheckpointError(
                f"delta {link_path!r} references predecessor crc "
                f"{meta['prev_crc']:#010x}, got {prev_crc:#010x} — "
                f"broken chain, refusing to replay")
        if meta["from_order"] != cursor:
            raise CheckpointError(
                f"delta {link_path!r} starts at order {meta['from_order']}, "
                f"chain cursor is {cursor} — missing or reordered link")
        try:
            for txn in txns:
                doc.apply_remote_txn(txn)
        except (AssertionError, KeyError, ValueError, IndexError) as e:
            raise CheckpointError(
                f"delta {link_path!r}: replay failed: {e}") from e
        if doc.get_next_order() != meta["next_order"]:
            raise CheckpointError(
                f"delta {link_path!r}: replay landed at order "
                f"{doc.get_next_order()}, link claims {meta['next_order']}")
        prev_crc = meta["crc"]
        cursor = meta["next_order"]
        tip_meta = meta
    return doc, tip_meta


class CheckpointChain:
    """One document's base + delta links with periodic compaction.

    ``save(doc)`` writes a delta link when the chain is warm and small,
    or folds everything into a fresh base once the chain carries more
    than ``compact_ops`` ops or ``compact_links`` links (restore cost
    and directory clutter stay bounded).  ``load()`` replays the chain
    with full integrity checking.  File layout: ``<stem>.base.npz`` +
    ``<stem>.d<k>.npz``.
    """

    def __init__(self, stem: str, *, compact_ops: int = 4096,
                 compact_links: int = 16):
        self.stem = stem
        self.compact_ops = max(1, compact_ops)
        self.compact_links = max(1, compact_links)
        self.base_path = f"{stem}.base.npz"
        self.base_info: Optional[dict] = None
        self.links: List[dict] = []   # {"path", "crc", "next_order", ...}

    @property
    def next_order(self) -> Optional[int]:
        if self.links:
            return self.links[-1]["next_order"]
        return self.base_info["next_order"] if self.base_info else None

    def _link_path(self) -> str:
        return f"{self.stem}.d{len(self.links):04d}.npz"

    @classmethod
    def from_disk(cls, stem: str, *, compact_ops: int = 4096,
                  compact_links: int = 16):
        """Rebuild chain state from files on disk (crash recovery: the
        in-memory ``base_info``/``links`` died with the process).

        Returns ``(chain, refused, tip_meta)`` where ``refused`` lists
        the link paths dropped for failing validation — a torn tail
        link truncates the chain to its valid prefix (the journal
        replays the rest), and the next ``save`` overwrites the
        refused file — and ``tip_meta`` is the newest VALID file's meta
        header (where serve-tier extra meta rides).  A corrupt or
        absent BASE is a typed ``CheckpointError``: with no base, no
        prefix of the chain is restorable.
        """
        chain = cls(stem, compact_ops=compact_ops,
                    compact_links=compact_links)
        base_meta, _ = _load_npz(chain.base_path, expect_kind="oracle")
        tip_meta = base_meta
        chain.base_info = {
            "crc": base_meta["crc"],
            "next_order": int(base_meta.get("next_order", 0)),
            "bytes": os.path.getsize(chain.base_path),
        }
        refused: List[str] = []
        prev_crc = base_meta["crc"]
        cursor = chain.base_info["next_order"]
        k = 0
        while True:
            path = f"{stem}.d{k:04d}.npz"
            if not os.path.exists(path):
                break
            try:
                meta, _txns = load_delta(path)
                if (meta["base_crc"] != chain.base_info["crc"]
                        or meta["prev_crc"] != prev_crc
                        or meta["from_order"] != cursor):
                    raise CheckpointError(
                        f"delta {path!r}: chain linkage mismatch")
            except CheckpointError:
                # Valid-prefix recovery: this link (and anything after
                # it) is unusable; the journal suffix covers the gap.
                refused.append(path)
                break
            chain.links.append({
                "path": path, "crc": meta["crc"],
                "next_order": meta["next_order"],
                "ops": meta["next_order"] - meta["from_order"],
                "bytes": os.path.getsize(path),
            })
            prev_crc = meta["crc"]
            cursor = meta["next_order"]
            tip_meta = meta
            k += 1
        return chain, refused, tip_meta

    def save(self, doc, extra_meta: Optional[dict] = None) -> dict:
        """Checkpoint ``doc``; returns ``{"kind", "bytes", "ops"}`` —
        what the residency layer's byte counters record.

        An unchanged doc (tip already == ``next_order`` — e.g. a
        restore-for-read immediately re-evicted) writes NOTHING and
        returns kind ``"noop"``: the existing chain already restores
        this exact state, and an empty link per idle evict would walk
        the chain toward a pointless full-base compaction."""
        tip = self.next_order
        if tip is not None and tip == doc.get_next_order():
            return {"kind": "noop", "bytes": 0, "ops": 0}
        ops_since_base = (doc.get_next_order() - self.base_info["next_order"]
                          if self.base_info else None)
        fresh = (
            self.base_info is None
            or tip is None or tip > doc.get_next_order()
            or ops_since_base > self.compact_ops
            or len(self.links) >= self.compact_links
        )
        if fresh:
            for link in self.links:
                if os.path.exists(link["path"]):
                    os.remove(link["path"])
            self.links = []
            self.base_info = save_doc(doc, self.base_path,
                                      extra_meta=extra_meta)
            return {"kind": "full", "bytes": self.base_info["bytes"],
                    "ops": self.base_info["next_order"]}
        path = self._link_path()
        prev_crc = self.links[-1]["crc"] if self.links \
            else self.base_info["crc"]
        info = save_delta(doc, path, base_crc=self.base_info["crc"],
                          prev_crc=prev_crc, from_order=tip,
                          extra_meta=extra_meta)
        info["path"] = path
        self.links.append(info)
        return {"kind": "delta", "bytes": info["bytes"], "ops": info["ops"]}

    def load(self):
        """Restore the chained document (typed refusal on any broken
        link)."""
        return self.load_with_meta()[0]

    def load_with_meta(self):
        """``(doc, tip_meta)`` — the restored document plus the tip
        file's meta header (freshest extra meta)."""
        if self.base_info is None:
            raise CheckpointError(f"chain {self.stem!r} has no base")
        return replay_chain_with_meta(
            self.base_path, [link["path"] for link in self.links])


def save_flat_doc(flat, path: str) -> None:
    """Checkpoint a device ``FlatDoc`` (downloads once). Accepts an
    unbatched doc or a ``stack_docs`` batch (leading doc axis on every
    column, including ``n``/``next_order``)."""
    arrays = dict(
        signed=np.asarray(flat.signed),
        ol_log=np.asarray(flat.ol_log),
        or_log=np.asarray(flat.or_log),
        rank_log=np.asarray(flat.rank_log),
        chars_log=np.asarray(flat.chars_log),
        n=np.asarray(flat.n),
        next_order=np.asarray(flat.next_order),
    )
    _save_npz(path, {"version": FORMAT_VERSION, "kind": "flat"}, arrays)


def load_flat_doc(path: str):
    """Rebuild a device ``FlatDoc`` from a ``save_flat_doc`` checkpoint.

    Raises ``CheckpointError`` on corruption/truncation/version mismatch.
    """
    import jax.numpy as jnp

    from ..ops.span_arrays import FlatDoc, I32, U32

    _, z = _load_npz(path, expect_kind="flat")
    try:
        return _rebuild_flat(z, FlatDoc, jnp, I32, U32)
    except (KeyError, ValueError, IndexError) as e:
        raise CheckpointError(
            f"checkpoint {path!r}: inconsistent contents: {e}") from e


def _rebuild_flat(z, FlatDoc, jnp, I32, U32):
    return FlatDoc(
        signed=jnp.asarray(z["signed"]),
        ol_log=jnp.asarray(z["ol_log"]),
        or_log=jnp.asarray(z["or_log"]),
        rank_log=jnp.asarray(z["rank_log"]),
        chars_log=jnp.asarray(z["chars_log"]),
        n=jnp.asarray(z["n"], I32),
        next_order=jnp.asarray(z["next_order"], U32),
    )

"""Flat sorted run-length-encoded vectors — the host↔TPU wire format.

Rebuild of the reference's span algebra (`src/splitable_span.rs:3-37`) and the
flat RLE container (`src/rle/simple_rle.rs:12-103`, `src/rle/mod.rs:16-68`).
Every entry type implements the SplitableSpan contract:

    ``length``, ``truncate(at) -> rest``, ``can_append(other)``,
    ``append(other)``

with the invariant that after ``rest = e.truncate(at)``:
``old_len == at + rest.length`` and ``e.can_append(rest)``
(`splitable_span.rs:10-16`).

Keyed entries fold the reference's ``KVPair`` (`rle/mod.rs:16-68`) into the
entry itself: ``key`` is the RLE key, ``can_append`` requires key
consecutiveness exactly like ``KVPair::can_append``.

These flat arrays are deliberately the same layout the device engine uploads
and downloads (struct-of-arrays of u32 columns) — see ``ops/span_arrays.py``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Generic, Iterator, List, Optional, Tuple, TypeVar


@dataclass
class KOrderSpan:
    """item_orders entry: seq range -> order range, always live
    (`list/mod.rs:33-43`, value type `src/order.rs:7-11` with positive len)."""

    seq: int
    order: int
    length: int

    @property
    def key(self) -> int:
        return self.seq

    def can_append(self, other: "KOrderSpan") -> bool:
        return (
            other.seq == self.seq + self.length
            and other.order == self.order + self.length
        )

    def append(self, other: "KOrderSpan") -> None:
        self.length += other.length

    def truncate(self, at: int) -> "KOrderSpan":
        rest = KOrderSpan(self.seq + at, self.order + at, self.length - at)
        self.length = at
        return rest

    def at_offset(self, offset: int) -> int:
        return self.order + offset


@dataclass
class KCRDTSpan:
    """client_with_order entry: order range -> (agent, seq) range
    (`list/mod.rs:58-63`, value type `range_tree/entry.rs:44`)."""

    order: int
    agent: int
    seq: int
    length: int

    @property
    def key(self) -> int:
        return self.order

    def can_append(self, other: "KCRDTSpan") -> bool:
        return (
            other.order == self.order + self.length
            and other.agent == self.agent
            and other.seq == self.seq + self.length
        )

    def append(self, other: "KCRDTSpan") -> None:
        self.length += other.length

    def truncate(self, at: int) -> "KCRDTSpan":
        rest = KCRDTSpan(self.order + at, self.agent, self.seq + at, self.length - at)
        self.length = at
        return rest


@dataclass
class KDeleteEntry:
    """deletes entry: delete-op order range -> deleted-target order range
    (`src/list/delete.rs:7-40`; keyed by the *delete op's* order,
    `list/mod.rs:82-84`)."""

    op_order: int
    target: int
    length: int

    @property
    def key(self) -> int:
        return self.op_order

    def can_append(self, other: "KDeleteEntry") -> bool:
        return (
            other.op_order == self.op_order + self.length
            and other.target == self.target + self.length
        )

    def append(self, other: "KDeleteEntry") -> None:
        self.length += other.length

    def truncate(self, at: int) -> "KDeleteEntry":
        rest = KDeleteEntry(self.op_order + at, self.target + at, self.length - at)
        self.length = at
        return rest


@dataclass
class KDoubleDelete:
    """double_deletes entry: target order range deleted 1+excess times
    (`src/list/double_delete.rs:12-16`; keyed by the item *being* deleted)."""

    target: int
    length: int
    excess: int

    @property
    def key(self) -> int:
        return self.target

    def can_append(self, other: "KDoubleDelete") -> bool:
        return (
            other.target == self.target + self.length
            and other.excess == self.excess
        )

    def append(self, other: "KDoubleDelete") -> None:
        self.length += other.length

    def truncate(self, at: int) -> "KDoubleDelete":
        rest = KDoubleDelete(self.target + at, self.length - at, self.excess)
        self.length = at
        return rest


@dataclass
class TxnSpan:
    """Time-DAG node covering a run of ops (`src/list/txn.rs:10-18`).

    ``shadow``: earliest order this span transitively dominates without
    branching (`txn.rs:14-15`, computed at `doc.rs:361-364`).
    ``parents``: parents of the first txn in the span (`txn.rs:17-18`).
    """

    order: int
    length: int
    shadow: int
    parents: List[int] = field(default_factory=list)

    @property
    def key(self) -> int:
        return self.order

    def can_append(self, other: "TxnSpan") -> bool:
        # RLE merge iff linear history (`txn.rs:38-42`). Key consecutiveness
        # is implied because orders are dense.
        return (
            len(other.parents) == 1
            and other.parents[0] == self.order + self.length - 1
            and other.shadow == self.shadow
        )

    def append(self, other: "TxnSpan") -> None:
        self.length += other.length

    def truncate(self, at: int) -> "TxnSpan":
        # Note: the parent of the remainder is the last op of the first half
        # (the reference's `txn.rs:26-35` writes `at - 1`, an absolute/relative
        # mixup that is unreachable in practice; we use the absolute order).
        rest = TxnSpan(self.order + at, self.length - at, self.shadow,
                       [self.order + at - 1])
        self.length = at
        return rest


E = TypeVar("E")


class Rle(Generic[E]):
    """Flat sorted vector of RLE entries keyed by ``entry.key``
    (`src/rle/simple_rle.rs:12-103`).

    ``append`` merges with the last entry when possible (amortized O(1),
    `simple_rle.rs:41-52`); ``find`` is a binary search returning
    ``(entry, offset)`` (`simple_rle.rs:18-37`); ``insert`` merges with
    neighbours (`simple_rle.rs:54-77`).
    """

    def __init__(self, entries: Optional[List[E]] = None):
        self.entries: List[E] = entries if entries is not None else []

    # -- queries ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self.entries)

    def __iter__(self) -> Iterator[E]:
        return iter(self.entries)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Rle) and self.entries == other.entries

    def __repr__(self) -> str:
        return f"Rle({self.entries!r})"

    def num_entries(self) -> int:
        return len(self.entries)

    def last(self) -> Optional[E]:
        return self.entries[-1] if self.entries else None

    def search(self, key: int) -> Tuple[bool, int]:
        """Binary search: (True, idx) if ``key`` falls inside entry idx,
        else (False, insertion_idx) (`simple_rle.rs:18-28`)."""
        ents = self.entries
        lo, hi = 0, len(ents)
        while lo < hi:  # find first entry with entry.key > key
            mid = (lo + hi) // 2
            if ents[mid].key <= key:
                lo = mid + 1
            else:
                hi = mid
        idx = lo - 1
        if idx >= 0:
            e = ents[idx]
            if key < e.key + e.length:
                return True, idx
        return False, idx + 1

    def find(self, key: int) -> Optional[Tuple[E, int]]:
        """-> (entry, offset into entry) or None (`simple_rle.rs:30-37`)."""
        ok, idx = self.search(key)
        if not ok:
            return None
        e = self.entries[idx]
        return e, key - e.key

    def get(self, key: int):
        """Value at key for entries supporting ``at_offset``
        (`simple_rle.rs:99-102`)."""
        found = self.find(key)
        if found is None:
            raise KeyError(key)
        entry, offset = found
        return entry.at_offset(offset)

    # -- mutation ---------------------------------------------------------

    def append(self, entry: E) -> None:
        if self.entries and self.entries[-1].can_append(entry):
            self.entries[-1].append(entry)
        else:
            self.entries.append(entry)

    def insert(self, entry: E) -> None:
        """Sorted insert with neighbour merging (`simple_rle.rs:54-77`)."""
        ok, idx = self.search(entry.key)
        assert not ok, "Rle.insert: key range already occupied"
        before = self.entries[idx - 1] if idx > 0 else None
        after = self.entries[idx] if idx < len(self.entries) else None
        if before is not None and before.can_append(entry):
            before.append(entry)
            if after is not None and before.can_append(after):
                before.append(after)
                del self.entries[idx]
        elif after is not None and entry.can_append(after):
            merged = entry
            merged.append(after)
            self.entries[idx] = merged
        else:
            self.entries.insert(idx, entry)

    def check(self) -> None:
        """Invariant walker: keys strictly increasing, non-overlapping,
        no zero-length entries (mirrors the reference's `check()` ethos,
        `range_tree/root.rs:242-253`)."""
        prev_end = -1
        for e in self.entries:
            assert e.length > 0, f"zero-length RLE entry {e!r}"
            assert e.key >= prev_end, (
                f"overlapping/unsorted RLE entries at key {e.key}"
            )
            prev_end = e.key + e.length


def merge_yjs_spans(spans):
    """Canonicalize a doc-order sequence of YjsSpan tuples
    (order, origin_left, origin_right, signed_len) by maximally RLE-merging
    adjacent spans under the reference predicate (`span.rs:47-53`): same
    sign, consecutive orders, chained origin_left, shared origin_right.
    Every engine's doc_spans() reports this form so they compare exactly.
    """
    out = []
    for (o, ol, orr, slen) in spans:
        if out:
            po, pol, porr, plen = out[-1]
            alen = abs(plen)
            if ((plen > 0) == (slen > 0) and o == po + alen
                    and ol == o - 1 and orr == porr):
                out[-1] = (po, pol, porr, plen + slen)
                continue
        out.append((o, ol, orr, slen))
    return out


def increment_delete_range(rle: Rle[KDoubleDelete], base: int, length: int) -> None:
    """Gap-aware interval-increment over the double-delete RLE vector.

    Faithful rebuild of `Rle<KVPair<DoubleDelete>>::increment_delete_range`
    (`src/list/double_delete.rs:41-106`): handles gap insert, entry split and
    partial overlap; adjacent equal-excess runs merge.
    """
    assert length > 0
    nxt = KDoubleDelete(base, length, 1)
    ok, idx = rle.search(base)
    if ok:
        # search returned the containing entry; the reference's
        # `search().unwrap_or_else(|idx| idx)` yields the entry index either
        # way, so start there.
        pass
    ents = rle.entries
    while True:
        if idx == len(ents) or ents[idx].key > nxt.key:
            # In a gap. Insert as much as we can here (`double_delete.rs:52-72`).
            this_entry = nxt
            if idx < len(ents) and nxt.key + nxt.length > ents[idx].key:
                nxt = this_entry.truncate(ents[idx].key - this_entry.key)
                done_here = False
            else:
                done_here = True
            if idx >= 1 and ents[idx - 1].can_append(this_entry):
                ents[idx - 1].append(this_entry)
            else:
                ents.insert(idx, this_entry)
                idx += 1
            if done_here:
                break
        # Now we're inside an entry (`double_delete.rs:75-103`).
        entry = ents[idx]
        assert entry.key <= nxt.key < entry.key + entry.length
        if entry.key < nxt.key:
            remainder = entry.truncate(nxt.key - entry.key)
            idx += 1
            ents.insert(idx, remainder)
        entry = ents[idx]
        assert entry.key == nxt.key
        if entry.length <= nxt.length:
            entry.excess += 1
            nxt = KDoubleDelete(nxt.target + entry.length,
                                nxt.length - entry.length, 1)
            if nxt.length == 0:
                break
            idx += 1
        else:
            remainder = entry.truncate(nxt.length)
            entry.excess += 1
            ents.insert(idx + 1, remainder)
            break

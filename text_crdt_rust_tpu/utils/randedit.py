"""Seeded synthetic edit generators for benches, soak, and tests.

``random_patches`` is the `make_random_change` analog
(`/root/reference/src/list/doc.rs:544-569`, used by the 1M-edit soak
`examples/simple.rs:14-49` and the commented-out `benches/random_edits.rs`):
each step either inserts 1..max_ins chars at a random position or deletes
1..max_del chars, tracked against a plain-string oracle.

``make_storm`` builds the config-4 concurrent-insert storm: N peers each
type at position 0 of their OWN replica (never seeing each other), so
every insert of a round is concurrent with every other peer's and the
receiving document resolves them all through the YATA tiebreak
(`doc.rs:204-217`) — the tiebreak-heavy workload by construction.
"""
from __future__ import annotations

import random
from typing import List, Tuple

from .testdata import TestPatch

ALPHABET = "abcdefghijklmnopqrstuvwxyz ABCDEFGHIJKLMNOPQRSTUVWXYZ.,\n"


def random_patches(
    rng: random.Random,
    steps: int,
    ins_prob: float = 0.6,
    max_ins: int = 5,
    max_del: int = 4,
) -> Tuple[List[TestPatch], str]:
    """Seeded random edit stream, tracked against a plain string."""
    content = ""
    patches = []
    for _ in range(steps):
        if not content or rng.random() < ins_prob:
            pos = rng.randint(0, len(content))
            ins = "".join(rng.choice(ALPHABET)
                          for _ in range(rng.randint(1, max_ins)))
            patches.append(TestPatch(pos, 0, ins))
            content = content[:pos] + ins + content[pos:]
        else:
            pos = rng.randint(0, len(content) - 1)
            span = min(rng.randint(1, max_del), len(content) - pos)
            patches.append(TestPatch(pos, span, ""))
            content = content[:pos] + content[pos + span:]
    return patches, content


def make_storm(n_peers: int, rounds: int, run_len: int, seed: int = 0,
               del_prob: float = 0.0):
    """(txns, oracle) for the concurrent-insert storm (config 4).

    Each peer types ``run_len`` chars at position 0 of its own replica
    every round; the exported txns are interleaved round-robin (a valid
    causal order — peers only depend on themselves) and applied to a
    receiving oracle for ground truth.

    With ``del_prob`` > 0 a peer's round is, with that probability, a
    DELETE instead: the peer first merges every txn emitted in earlier
    rounds (so it can see — and delete — other peers' chars), then
    deletes a random span.  Two peers deleting overlapping spans in the
    same round produce concurrent double deletes
    (`double_delete.rs:6-9`); the round-robin order stays causally
    valid because merges only cover strictly earlier rounds.
    ``del_prob=0`` draws no extra randomness, so existing seeded
    streams are unchanged.
    """
    from ..models.oracle import ListCRDT
    from ..models.sync import export_txns_since

    rng = random.Random(seed)
    peers = []
    for p in range(n_peers):
        doc = ListCRDT()
        agent = doc.get_or_create_agent_id(f"peer-{p:03d}")
        peers.append((doc, agent))

    per_round: List[List] = []
    marks = [0] * n_peers
    merged_upto = [0] * n_peers  # txns (flat index) each peer has merged
    flat: List = []
    for _ in range(rounds):
        round_txns = []
        prior = len(flat)  # merges may only cover earlier rounds
        for p, (doc, agent) in enumerate(peers):
            is_del = bool(del_prob) and rng.random() < del_prob
            if is_del:
                me = f"peer-{p:03d}"
                for t in flat[merged_upto[p]:prior]:
                    if t.id.agent != me:  # own history is already local
                        doc.apply_remote_txn(t)
                merged_upto[p] = prior
                # Export must cover ONLY the op below, not the merged
                # history (those orders belong to other agents).
                marks[p] = doc.get_next_order()
                n = len(doc)
                if n == 0:
                    is_del = False
                else:
                    pos = rng.randint(0, n - 1)
                    span = min(rng.randint(1, run_len), n - pos)
                    doc.local_delete(agent, pos, span)
            if not is_del:
                text = "".join(rng.choice(ALPHABET)
                               for _ in range(run_len))
                doc.local_insert(agent, 0, text)
            txns = export_txns_since(doc, marks[p])
            marks[p] = doc.get_next_order()
            round_txns.extend(txns)
        per_round.append(round_txns)
        flat.extend(round_txns)

    txns = [t for rnd in per_round for t in rnd]
    receiver = ListCRDT()
    for t in txns:
        receiver.apply_remote_txn(t)
    return txns, receiver

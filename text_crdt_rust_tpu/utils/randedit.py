"""Seeded synthetic edit generators for benches, soak, and tests.

``random_patches`` is the `make_random_change` analog
(`/root/reference/src/list/doc.rs:544-569`, used by the 1M-edit soak
`examples/simple.rs:14-49` and the commented-out `benches/random_edits.rs`):
each step either inserts 1..max_ins chars at a random position or deletes
1..max_del chars, tracked against a plain-string oracle.

``make_storm`` builds the config-4 concurrent-insert storm: N peers each
type at position 0 of their OWN replica (never seeing each other), so
every insert of a round is concurrent with every other peer's and the
receiving document resolves them all through the YATA tiebreak
(`doc.rs:204-217`) — the tiebreak-heavy workload by construction.
"""
from __future__ import annotations

import random
from typing import List, Tuple

from .testdata import TestPatch

ALPHABET = "abcdefghijklmnopqrstuvwxyz ABCDEFGHIJKLMNOPQRSTUVWXYZ.,\n"


def random_patches(
    rng: random.Random,
    steps: int,
    ins_prob: float = 0.6,
    max_ins: int = 5,
    max_del: int = 4,
) -> Tuple[List[TestPatch], str]:
    """Seeded random edit stream, tracked against a plain string."""
    content = ""
    patches = []
    for _ in range(steps):
        if not content or rng.random() < ins_prob:
            pos = rng.randint(0, len(content))
            ins = "".join(rng.choice(ALPHABET)
                          for _ in range(rng.randint(1, max_ins)))
            patches.append(TestPatch(pos, 0, ins))
            content = content[:pos] + ins + content[pos:]
        else:
            pos = rng.randint(0, len(content) - 1)
            span = min(rng.randint(1, max_del), len(content) - pos)
            patches.append(TestPatch(pos, span, ""))
            content = content[:pos] + content[pos + span:]
    return patches, content


def make_storm(n_peers: int, rounds: int, run_len: int, seed: int = 0):
    """(txns, oracle) for the concurrent-insert storm (config 4).

    Each peer types ``run_len`` chars at position 0 of its own replica
    every round; the exported txns are interleaved round-robin (a valid
    causal order — peers only depend on themselves) and applied to a
    receiving oracle for ground truth.
    """
    from ..models.oracle import ListCRDT
    from ..models.sync import export_txns_since

    rng = random.Random(seed)
    peers = []
    for p in range(n_peers):
        doc = ListCRDT()
        agent = doc.get_or_create_agent_id(f"peer-{p:03d}")
        peers.append((doc, agent))

    per_round: List[List] = []
    marks = [0] * n_peers
    for _ in range(rounds):
        round_txns = []
        for p, (doc, agent) in enumerate(peers):
            text = "".join(rng.choice(ALPHABET) for _ in range(run_len))
            doc.local_insert(agent, 0, text)
            txns = export_txns_since(doc, marks[p])
            marks[p] = doc.get_next_order()
            round_txns.extend(txns)
        per_round.append(round_txns)

    txns = [t for rnd in per_round for t in rnd]
    receiver = ListCRDT()
    for t in txns:
        receiver.apply_remote_txn(t)
    return txns, receiver

"""CRC32C (Castagnoli) — the wire-frame integrity primitive.

Used by the wire codec (`net/codec.py`, per-frame checksums). zlib only
ships CRC32 (IEEE); CRC32C is the variant with hardware support on
modern CPUs and the one automerge/gRPC/iSCSI use. A 256-entry table is
plenty fast for frame-sized inputs and keeps the tree dependency-free.
(The checkpoint store deliberately uses ``zlib.crc32`` instead — its
inputs are MB-scale arrays where a pure-Python byte loop would dominate
save/load; see ``utils/checkpoint.py::_content_crc``.) Lives in
``utils`` (imports nothing) so any consumer can use it without an
import cycle.
"""
from __future__ import annotations

from typing import List

_U32_MAX = 0xFFFF_FFFF


def _make_table() -> List[int]:
    poly = 0x82F63B78  # reflected Castagnoli polynomial
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _make_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C checksum of ``data`` (optionally continuing ``crc``)."""
    crc ^= _U32_MAX
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ _U32_MAX

"""HBM-state RLE run engine: millions of run rows, one-block VMEM window.

``ops.rle`` holds both run planes in VMEM, which caps capacity near ~50k
run rows. This variant keeps the planes in HBM and caches ONE block in
VMEM — the layout that unlocks the two workloads the VMEM engine can't
hold:

- **kevin** (`benches/yjs.rs:51-62`): 5M single-char prepends — runs
  cannot merge (each new char precedes the previous one in doc order,
  the shape that costs the reference 5M tree nodes), so state is one row
  per op. The logical-block-order SPLIT (shared design with ``ops.rle``)
  makes the always-at-front insert amortized O(1): slot 0 fills, its top
  half moves to a fresh physical block, the window stays valid (the kept
  half is the same physical block) — no global rebalance, ~zero DMA
  misses. This is the round-2 pathology (O(capacity) rebalance per
  overflow) gone for good.
- **documents beyond VMEM** (SURVEY §5 long-context row): run capacity
  is bounded by HBM (GBs), with a two-level ``SUP``-segment live index
  (the `mod.rs:85-93` internal-node sums as two short scans) so
  position→slot stays O(NSUP + SUP) regardless of block count.

The in-block row algebra — run location, insert splice, delete
flip/boundary-split — is ``ops.rle``'s module-level helpers
(`_locate_run` / `_insert_splice` / `_delete_block_math`), so the two
engines cannot drift. Results reuse ``RleResult``/``rle_to_flat``.
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..common import ROOT_ORDER
from .batch import KIND_LOCAL, fused_width_checked
from .blocked import _cumsum_rows, _require, _shift_rows
from .rle import (
    RleResult,
    _delete_block_math,
    _insert_splice,
    _locate_run,
    _row_scalar,
    _shift_rows_up,
)

SUP = 64  # logical slots per super-segment (level-2 live index fan-out)


def _rle_hbm_kernel(
    pos_ref, dlen_ref, ilen_ref, start_ref,     # [CHUNK] SMEM op columns
    w_ref,                                      # [CHUNK] SMEM rows_per_step
    ol_ref, or_ref,                             # [1,CHUNK,B] VMEM outputs
    ordp, lenp,                                 # [G*CAP,B] ANY/HBM planes
    blk_out, rows_out, meta_out, err_ref,       # tables + flags
    wo, wl, stage,                              # [K,B] window + DMA stage
    blkord, rws, liv, supliv,                   # logical tables (VMEM)
    wmeta, meta, sem,                           # SMEM scalars + DMA sem
    *, K: int, NB: int, NBL: int, NSUP: int, CHUNK: int, WMAX: int,
):
    B = wo.shape[1]
    g = pl.program_id(0)
    i = pl.program_id(1)
    last = pl.num_programs(1) - 1
    idx_k = lax.broadcasted_iota(jnp.int32, (K, B), 0)
    idx_l = lax.broadcasted_iota(jnp.int32, rws.shape, 0)
    idx_s = lax.broadcasted_iota(jnp.int32, supliv.shape, 0)
    root_u = jnp.uint32(ROOT_ORDER)
    gbase = g * (NB * K)

    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when((g == 0) & (i == 0))
    def _init_err():
        err_ref[:] = jnp.zeros_like(err_ref)

    @pl.when(i == 0)
    def _init():
        # Fresh group: one empty block in logical slot 0, cached zeroed in
        # the window (its HBM backing is written on eviction/flush; fresh
        # split blocks are fully masked-written, so HBM is never read
        # before a write).
        blkord[:] = jnp.zeros_like(blkord)
        rws[:] = jnp.zeros_like(rws)
        liv[:] = jnp.zeros_like(liv)
        supliv[:] = jnp.zeros_like(supliv)
        wo[:] = jnp.zeros_like(wo)
        wl[:] = jnp.zeros_like(wl)
        wmeta[0] = 0
        meta[0] = 1  # blocks in use

    def dma(src, dst):
        cp = pltpu.make_async_copy(src, dst, sem)
        cp.start()
        cp.wait()

    def ensure(b):
        """Cache physical block ``b`` in the window (write-back cache —
        every op may dirty the window, so eviction always writes)."""
        cb = wmeta[0]

        @pl.when(cb != b)
        def _miss():
            dma(wo, ordp.at[pl.ds(gbase + cb * K, K), :])
            dma(wl, lenp.at[pl.ds(gbase + cb * K, K), :])
            dma(ordp.at[pl.ds(gbase + b * K, K), :], wo)
            dma(lenp.at[pl.ds(gbase + b * K, K), :], wl)
            wmeta[0] = b

    def slot_scalar(tbl, l):
        return jnp.max(tbl[pl.ds(l, 1), :])

    def bump_liv(l, delta):
        liv[pl.ds(l, 1), :] = liv[pl.ds(l, 1), :] + delta
        s = l // SUP
        supliv[pl.ds(s, 1), :] = supliv[pl.ds(s, 1), :] + delta

    def resup():
        """Rebuild the super-segment sums from ``liv`` (after a table
        splice moved slot boundaries). O(NBL) total, split-rate only."""

        def seg(s, _):
            part = liv[pl.ds(s * SUP, SUP), :]
            supliv[pl.ds(s, 1), :] = jnp.sum(part, axis=0, keepdims=True)
            return 0

        lax.fori_loop(0, NSUP, seg, 0)

    def live_before_slot(l):
        s = l // SUP
        sup_part = jnp.max(jnp.sum(
            jnp.where(idx_s < s, supliv[:], 0), axis=0))
        segm = liv[pl.ds(s * SUP, SUP), :]
        seg_idx = lax.broadcasted_iota(jnp.int32, (SUP, B), 0)
        seg_part = jnp.max(jnp.sum(
            jnp.where(seg_idx < (l - s * SUP), segm, 0), axis=0))
        return sup_part + seg_part

    def slot_of_live_rank(rank1):
        """Two-level descent (`root.rs:54-88` over segment sums)."""
        nlog = meta[0]
        supcum = _cumsum_rows(jnp.where(idx_s < NSUP, supliv[:], 0))
        s = jnp.minimum(
            jnp.max(jnp.sum(
                ((supcum < rank1) & (idx_s < NSUP)).astype(jnp.int32),
                axis=0)),
            NSUP - 1)
        base = jnp.max(jnp.sum(jnp.where(idx_s < s, supliv[:], 0), axis=0))
        segm = liv[pl.ds(s * SUP, SUP), :]
        segcum = _cumsum_rows(segm)
        within = jnp.max(jnp.sum(
            (segcum < (rank1 - base)).astype(jnp.int32), axis=0))
        return jnp.minimum(s * SUP + within, nlog - 1)

    def split(l):
        """Leaf split (`mutations.rs:623-669`): the cached block's top
        half moves to a fresh physical block (stage DMA), spliced into
        the logical order at ``l+1``. The kept half stays cached."""
        nlog = meta[0]

        @pl.when(nlog >= NB)
        def _cap():
            # NO-OP at table capacity (advisor r3: proceeding overwrote
            # an in-use physical block); flag and leave state readable.
            err_ref[0:1, :] = jnp.ones((1, B), jnp.int32)

        @pl.when(nlog < NB)
        def _do():
            b = slot_scalar(blkord, l)
            ensure(b)
            r = slot_scalar(rws, l)
            keep = r // 2
            mv = r - keep
            nb = nlog
            bo = wo[:]
            bl = wl[:]
            liv_hi = jnp.max(jnp.sum(jnp.where(
                (idx_k >= keep) & (idx_k < r) & (bo > 0), bl, 0), axis=0))
            liv_lo = slot_scalar(liv, l) - liv_hi

            stage[:] = jnp.where(idx_k < mv, _shift_rows_up(bo, keep, K), 0)
            dma(stage, ordp.at[pl.ds(gbase + nb * K, K), :])
            stage[:] = jnp.where(idx_k < mv, _shift_rows_up(bl, keep, K), 0)
            dma(stage, lenp.at[pl.ds(gbase + nb * K, K), :])
            wo[:] = jnp.where(idx_k < keep, bo, 0)
            wl[:] = jnp.where(idx_k < keep, bl, 0)

            for tbl in (blkord, rws, liv):
                shifted = _shift_rows(tbl[:], 1, 1)
                tbl[:] = jnp.where(idx_l <= l, tbl[:], shifted)
            rws[pl.ds(l, 1), :] = jnp.broadcast_to(keep, (1, B))
            liv[pl.ds(l, 1), :] = jnp.broadcast_to(liv_lo, (1, B))
            blkord[pl.ds(l + 1, 1), :] = jnp.broadcast_to(nb, (1, B))
            rws[pl.ds(l + 1, 1), :] = jnp.broadcast_to(mv, (1, B))
            liv[pl.ds(l + 1, 1), :] = jnp.broadcast_to(liv_hi, (1, B))
            meta[0] = nlog + 1
            resup()

    def find_insert_slot(p):
        l = jnp.where(p == 0, 0, slot_of_live_rank(p))
        return l, slot_scalar(rws, l)

    def do_insert(k, p, il, st, w):
        l, r0 = find_insert_slot(p)

        @pl.when(r0 + w + 1 > K)
        def _():
            split(l)

        l, r0 = find_insert_slot(p)
        b = slot_scalar(blkord, l)
        ensure(b)
        base = live_before_slot(l)
        local = p - base
        bo = wo[:]
        bl = wl[:]
        i_r, o_r, l_r, off = _locate_run(bo, bl, idx_k, r0, local)

        left = jnp.where(p == 0, root_u,
                         ((o_r - 1) + (off - 1)).astype(jnp.uint32))
        is_split = (p > 0) & (off < l_r)

        # Raw successor (`doc.rs:452`): within block, else the next
        # slot's first row via an 8-row DMA peek (boundary inserts only).
        nxt_in_blk = _row_scalar(bo, i_r + 1, idx_k)
        nlog = meta[0]
        need_peek = (p > 0) & jnp.logical_not(is_split) & \
            (i_r + 1 >= r0) & (l + 1 < nlog)

        def peek():
            b2 = slot_scalar(blkord, jnp.minimum(l + 1, NBL - 1))
            dma(ordp.at[pl.ds(gbase + b2 * K, 8), :],
                stage.at[pl.ds(0, 8), :])
            return jnp.max(stage[pl.ds(0, 1), :])

        succ_next = lax.cond(need_peek, peek, lambda: jnp.int32(0))
        first_o = _row_scalar(bo, 0, idx_k)
        succ_p0 = jnp.where(r0 > 0, first_o, 0)
        succ = jnp.where(
            p == 0, succ_p0,
            jnp.where(is_split, o_r + off,
                      jnp.where(i_r + 1 < r0, nxt_in_blk, succ_next)))
        right = jnp.where(succ == 0, root_u,
                          (jnp.abs(succ) - 1).astype(jnp.uint32))

        no, nl, amt, _mrg, _sp = _insert_splice(
            bo, bl, idx_k, p, i_r, o_r, l_r, off, il, st, w, WMAX)
        wo[:] = no
        wl[:] = nl
        rws[pl.ds(l, 1), :] = rws[pl.ds(l, 1), :] + amt
        bump_liv(l, il)

        ol_ref[:, pl.ds(k, 1), :] = jnp.broadcast_to(left, (1, 1, B))
        or_ref[:, pl.ds(k, 1), :] = jnp.broadcast_to(right, (1, 1, B))

    def do_delete(p, d):
        def body(carry):
            rem, iters = carry
            l = slot_of_live_rank(p + 1)

            @pl.when(slot_scalar(rws, l) + 2 > K)
            def _():
                split(l)

            l = slot_of_live_rank(p + 1)
            b = slot_scalar(blkord, l)
            ensure(b)
            base = live_before_slot(l)
            no, nl, added, tot = _delete_block_math(
                wo[:], wl[:], idx_k, K, base, p, rem)
            wo[:] = no
            wl[:] = nl
            rws[pl.ds(l, 1), :] = rws[pl.ds(l, 1), :] + added
            bump_liv(l, -tot)
            return rem - tot, iters + 1

        rem, _ = lax.while_loop(
            lambda c: (c[0] > 0) & (c[1] <= 2 * NBL), body, (d, 0))

        @pl.when(rem > 0)
        def _bad_delete():
            err_ref[1:2, :] = jnp.ones((1, B), jnp.int32)

    def op_body(k, _):
        p = pos_ref[k]
        d = dlen_ref[k]
        il = ilen_ref[k]
        st = start_ref[k]
        w = jnp.maximum(w_ref[k], 1)  # no-op pad rows carry 0

        @pl.when(d > 0)
        def _():
            do_delete(p, d)

        @pl.when(il > 0)
        def _():
            do_insert(k, p, il, st, w)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)

    @pl.when(i == last)
    def _flush():
        cb = wmeta[0]
        dma(wo, ordp.at[pl.ds(gbase + cb * K, K), :])
        dma(wl, lenp.at[pl.ds(gbase + cb * K, K), :])
        blk_out[:] = blkord[:][jnp.newaxis]
        rows_out[:] = rws[:][jnp.newaxis]
        row0 = lax.broadcasted_iota(jnp.int32, (1, 8, B), 1) == 0
        meta_out[:] = jnp.where(row0, meta[0], 0)


def make_replayer_rle_hbm(
    ops,
    capacity: int,
    batch: int = 128,
    block_k: int = 512,
    chunk: int = 1024,
    interpret: bool = False,
    store_origins: bool = True,
):
    """HBM-plane variant of ``rle.make_replayer_rle`` (same contract;
    ``capacity`` counts RUN rows and may reach millions).

    ``store_origins=False`` backs the per-op origin outputs with ONE
    chunk-sized window instead of the full stream (every chunk
    overwrites it): at kevin scale (5M steps x 128 lanes) the full
    ``ol``/``or`` planes alone are 5.1 GB of HBM, which together with
    the 10.7 GB state planes cannot fit the chip. The returned
    ``RleResult.ol``/``orr`` are EMPTY in this mode — final state
    (``expand_runs``) is unaffected, but ``rle_to_flat`` needs origins
    and must not be fed a store_origins=False result."""
    grouped = isinstance(ops, (list, tuple))
    streams = list(ops) if grouped else [ops]
    G = len(streams)
    _require(G >= 1, "need at least one op stream")
    for st in streams:
        kinds = np.asarray(st.kind)
        _require(kinds.ndim == 1, "rle_hbm engine takes per-group shared "
                 "streams")
        _require(bool((kinds == KIND_LOCAL).all()),
                 "rle_hbm engine replays local streams; remote ops -> "
                 "ops.blocked_mixed / ops.flat")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    _require(interpret or chunk % 1024 == 0 or (
        jax.default_backend() != "tpu"),
        "chunk must be a multiple of 1024 on TPU")
    NB = capacity // block_k
    _require(NB >= 1, "need at least one block")
    _require(block_k >= 8, "block_k must hold a few runs")
    WMAX = fused_width_checked(streams, block_k)
    NSUP = (NB + SUP - 1) // SUP
    NBLp = NSUP * SUP
    NSUPp = max(8, NSUP)

    lens = [st.num_steps for st in streams]
    s_pad = max(((max(lens) + chunk - 1) // chunk) * chunk, chunk)

    def staged_col(get):
        cols = []
        for st in streams:
            a = np.asarray(get(st), dtype=np.int32)
            cols.append(np.pad(a, ((0, s_pad - len(a)),)))
        return jnp.asarray(np.concatenate(cols))   # flat [G*s_pad]

    staged = (staged_col(lambda o: o.pos),
              staged_col(lambda o: o.del_len),
              staged_col(lambda o: o.ins_len),
              staged_col(lambda o: o.ins_order_start),
              staged_col(lambda o: o.rows_per_step))

    jitted = _build_call(G, s_pad, batch, capacity, block_k, chunk,
                         WMAX, store_origins, interpret)

    def run():
        ol, orr, ordp, lenp, blk, rows, meta, err = jitted(*staged)
        # G == 1: hand the planes over as-is — a [0:capacity] slice is a
        # device COPY, and at kevin scale that transient doubles a 5 GiB
        # plane and OOMs the chip.
        results = [
            RleResult(
                ordp=ordp if G == 1 else
                ordp[gi * capacity:(gi + 1) * capacity],
                lenp=lenp if G == 1 else
                lenp[gi * capacity:(gi + 1) * capacity],
                blkord=blk[gi], rows=rows[gi], meta=meta[gi],
                ol=ol[gi, :lens[gi] if store_origins else 0],
                orr=orr[gi, :lens[gi] if store_origins else 0], err=err,
                block_k=block_k, num_blocks=NB, batch=batch)
            for gi in range(G)
        ]
        return results if grouped else results[0]

    return run


@functools.lru_cache(maxsize=32)
def _build_call(G: int, s_pad: int, batch: int, capacity: int,
                block_k: int, chunk: int, wmax: int,
                store_origins: bool, interpret: bool):
    """Shape-keyed cache (the ``rle_lanes._build_call`` pattern): every
    same-shape replay shares one traced kernel instead of paying a full
    re-trace per ``make_replayer_rle_hbm`` call."""
    NB = capacity // block_k
    NSUP = (NB + SUP - 1) // SUP
    NBLp = NSUP * SUP
    NSUPp = max(8, NSUP)
    blocks_per_g = s_pad // chunk
    smem = lambda: pl.BlockSpec(
        (chunk,), lambda g, i: (g * blocks_per_g + i,),
        memory_space=pltpu.SMEM)
    # One reused chunk window when origins aren't kept (see docstring).
    o_rows = s_pad if store_origins else chunk
    o_map = (lambda g, i: (g, i, 0)) if store_origins \
        else (lambda g, i: (g, 0, 0))

    call = pl.pallas_call(
        partial(_rle_hbm_kernel, K=block_k, NB=NB, NBL=NBLp, NSUP=NSUP,
                CHUNK=chunk, WMAX=wmax),
        grid=(G, blocks_per_g),
        in_specs=[smem(), smem(), smem(), smem(), smem()],
        out_specs=[
            pl.BlockSpec((1, chunk, batch), o_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, batch), o_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, NBLp, batch), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, NBLp, batch), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, batch), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, batch), lambda g, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G, o_rows, batch), jnp.uint32),
            jax.ShapeDtypeStruct((G, o_rows, batch), jnp.uint32),
            jax.ShapeDtypeStruct((G * capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((G * capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((G, NBLp, batch), jnp.int32),
            jax.ShapeDtypeStruct((G, NBLp, batch), jnp.int32),
            jax.ShapeDtypeStruct((G, 8, batch), jnp.int32),
            jax.ShapeDtypeStruct((8, batch), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, batch), jnp.int32),    # window ord
            pltpu.VMEM((block_k, batch), jnp.int32),    # window len
            pltpu.VMEM((block_k, batch), jnp.int32),    # DMA stage
            pltpu.VMEM((NBLp, batch), jnp.int32),       # blkord
            pltpu.VMEM((NBLp, batch), jnp.int32),       # rws
            pltpu.VMEM((NBLp, batch), jnp.int32),       # liv
            pltpu.VMEM((NSUPp, batch), jnp.int32),      # supliv
            pltpu.SMEM((2,), jnp.int32),                # wmeta
            pltpu.SMEM((2,), jnp.int32),                # meta
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda a, b, c, d, e: call(a, b, c, d, e))


def replay_local_rle_hbm(ops, capacity: int, **kw):
    """One-shot convenience wrapper over ``make_replayer_rle_hbm``."""
    return make_replayer_rle_hbm(ops, capacity, **kw)()

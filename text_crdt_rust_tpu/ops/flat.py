"""Flat device engine: every CRDT op as fully-vectorized array work.

The device twin of ``models.oracle.ListCRDT`` — same flattened item layout,
same semantics, jit/vmap/scan-compatible. Each step is O(capacity) of
branch-free vector work (XLA-fusable), so this engine is the *correctness*
engine and the remote/concurrent path; ``ops.blocked`` is the throughput
engine for the trace-replay hot path.

How the reference's per-op O(log n) machinery maps here (SURVEY §7):

- B-tree descent `root.rs:54-88` -> ``cumsum`` over the live mask +
  ``searchsorted`` (position -> row);
- order -> leaf-ptr SpaceIndex `split_list/mod.rs:440` -> ``argmax`` over an
  equality mask (order -> row);
- cursor total order `cursor.rs:274-304` -> integer comparison of rows;
- the YATA integrate scan `doc.rs:167-234` -> a ``lax.while_loop`` from the
  origin cursor, with the name tiebreak on precompiled agent ranks and the
  scanning/scan_start backtrack carried as loop state;
- tombstoning `span.rs:110-119` -> boolean mask OR (local deletes select a
  live-rank window; remote deletes select an order range, which also makes
  the fragmented-target walk `doc.rs:311-334` a single mask op);
- splice + node splits `mutations.rs:17-179,623-808` -> one gather with a
  shifted index map (no splits: capacity is static).

Frontier/time-DAG bookkeeping stays host-side (``models.oracle`` /
``parallel.causal``), per SURVEY §7 "keep on host".
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common import ROOT_ORDER
from .batch import KIND_LOCAL, KIND_REMOTE_DEL, KIND_REMOTE_INS, OpTensors
from .span_arrays import FlatDoc, I32, U32

_ROOT = jnp.uint32(ROOT_ORDER)


def _row_of_order(doc: FlatDoc, order: jax.Array) -> jax.Array:
    """Row index of the item with dense id ``order`` (must exist).
    The SpaceIndex lookup (`doc.rs:101-107`) as one equality-mask argmax."""
    in_doc = jnp.arange(doc.capacity, dtype=I32) < doc.n
    return jnp.argmax((doc.order == order) & in_doc).astype(I32)


def _cursor_after(doc: FlatDoc, order: jax.Array) -> jax.Array:
    """Raw cursor just after item ``order`` (`doc.rs:121-136`)."""
    return jnp.where(order == _ROOT, 0, _row_of_order(doc, order) + 1)


def _integrate_cursor(doc: FlatDoc, my_rank: jax.Array,
                      origin_left: jax.Array, origin_right: jax.Array,
                      active: jax.Array) -> jax.Array:
    """YATA conflict scan (`doc.rs:167-234`): final insert row for a remote
    run. Runs zero iterations unless there are concurrent same-origin items
    (`doc.rs:192-194` notes they are rare)."""
    cursor0 = _cursor_after(doc, origin_left)
    left_cursor = cursor0

    def cond(state):
        cursor, scanning, scan_start, done = state
        return ~done & (cursor < doc.n)

    def body(state):
        cursor, scanning, scan_start, done = state
        c = jnp.clip(cursor, 0, doc.capacity - 1)
        other_order = doc.order[c]
        other_left = doc.origin_left[c]
        other_right = doc.origin_right[c]
        other_rank = doc.rank[c]
        olc = _cursor_after(doc, other_left)
        # Break conditions, in the reference's order (`doc.rs:183-222`).
        brk = (other_order == origin_right) | (olc < left_cursor)
        eq = ~brk & (olc == left_cursor)
        gt = my_rank > other_rank          # name tiebreak (`doc.rs:206-209`)
        brk = brk | (eq & ~gt & (origin_right == other_right))
        starts_scan = eq & ~gt & (origin_right != other_right)
        new_scan_start = jnp.where(starts_scan & ~scanning, cursor, scan_start)
        new_scanning = jnp.where(
            eq, jnp.where(gt, False, jnp.where(
                origin_right == other_right, scanning, True)),
            scanning,
        )
        return (jnp.where(brk, cursor, cursor + 1), new_scanning,
                new_scan_start, brk)

    init = (cursor0, jnp.asarray(False), cursor0, ~active)
    cursor, scanning, scan_start, _ = lax.while_loop(cond, body, init)
    return jnp.where(scanning, scan_start, cursor)


def step(doc: FlatDoc, op) -> FlatDoc:
    """Apply one compiled op (see ``batch.OpTensors``) to one document."""
    cap = doc.capacity
    j = jnp.arange(cap, dtype=I32)
    in_doc = j < doc.n
    live = in_doc & ~doc.deleted
    is_local = op.kind == KIND_LOCAL
    is_rins = op.kind == KIND_REMOTE_INS
    is_rdel = op.kind == KIND_REMOTE_DEL
    pos = op.pos.astype(I32)
    dlen = op.del_len.astype(I32)
    ilen = op.ins_len.astype(I32)

    # ---- delete phase (tombstone flips, `span.rs:110-119`) ----------------
    # Local: the del-span live-rank window (`mutations.rs:520-570` +
    # `doc.rs:392-433`). Remote: the order-range mask — fragmentation in doc
    # order (`doc.rs:311-334`) is free here. Already-deleted rows stay
    # deleted (idempotence; excess counts are host-side double_deletes).
    cum = jnp.cumsum(live.astype(I32))
    local_mask = live & (cum > pos) & (cum <= pos + dlen)
    remote_mask = in_doc & ((doc.order - op.del_target) < op.del_len)
    deleted = doc.deleted | jnp.where(
        is_local, local_mask, jnp.where(is_rdel, remote_mask, False))

    # ---- insert phase -----------------------------------------------------
    # Local cursor/origins from the content position (`doc.rs:435-464`):
    # origin_left is the (pos-1)-th live item post-delete; origin_right is
    # the raw successor *without skipping tombstones* (`doc.rs:452-453`).
    live2 = in_doc & ~deleted
    cum2 = jnp.cumsum(live2.astype(I32))
    oli = jnp.searchsorted(cum2, pos, side="left").astype(I32)
    l_cursor = jnp.where(pos == 0, 0, oli + 1)
    l_origin_left = jnp.where(
        pos == 0, _ROOT, doc.order[jnp.clip(oli, 0, cap - 1)])
    # Remote cursor from the integrate scan at resolved origins.
    r_cursor = _integrate_cursor(
        doc, op.rank, op.origin_left, op.origin_right, is_rins)

    cursor = jnp.where(is_rins, r_cursor, l_cursor)
    origin_left = jnp.where(is_rins, op.origin_left, l_origin_left)
    safe_cursor = jnp.clip(cursor, 0, cap - 1)
    l_origin_right = jnp.where(cursor < doc.n, doc.order[safe_cursor], _ROOT)
    origin_right = jnp.where(is_rins, op.origin_right, l_origin_right)

    # Splice: one gather through a shifted index map (`mutations.rs:17-179`
    # without the node splits), then fill the new run with the implicit
    # origin chain (`span.rs:9-13,24-28`).
    src = jnp.clip(jnp.where(j < cursor, j, j - ilen), 0, cap - 1)
    in_new = (j >= cursor) & (j < cursor + ilen)
    k = j - cursor
    ku = k.astype(U32)
    new_order = op.ins_order_start + ku
    take = lambda a: a[src]
    return FlatDoc(
        order=jnp.where(in_new, new_order, take(doc.order)),
        origin_left=jnp.where(
            in_new, jnp.where(k == 0, origin_left, new_order - 1),
            take(doc.origin_left)),
        origin_right=jnp.where(in_new, origin_right, take(doc.origin_right)),
        rank=jnp.where(in_new, op.rank, take(doc.rank)),
        chars=jnp.where(
            in_new, op.chars[jnp.clip(k, 0, op.chars.shape[-1] - 1)],
            take(doc.chars)),
        deleted=jnp.where(in_new, False, take(deleted)),
        n=doc.n + ilen,
        next_order=doc.next_order + op.order_advance,
    )


def _check_capacity(doc: FlatDoc, ops: OpTensors) -> None:
    """Host-side overflow guard: the splice clips silently on device, so
    exceeding the static capacity would corrupt, not crash."""
    import numpy as np

    need = np.asarray(doc.n).max() + np.asarray(ops.ins_len).sum(axis=0).max()
    assert need <= doc.capacity, (
        f"op stream needs {int(need)} rows but capacity is {doc.capacity}; "
        f"allocate a larger FlatDoc"
    )


@jax.jit
def _apply_ops(doc: FlatDoc, ops: OpTensors) -> FlatDoc:
    def body(d, op):
        return step(d, op), None

    out, _ = lax.scan(body, doc, ops)
    return out


@jax.jit
def _apply_ops_batch(docs: FlatDoc, ops: OpTensors) -> FlatDoc:
    vstep = jax.vmap(step)

    def body(d, op):
        return vstep(d, op), None

    out, _ = lax.scan(body, docs, ops)
    return out


def apply_ops(doc: FlatDoc, ops: OpTensors) -> FlatDoc:
    """Apply a compiled step stream to one document (``lax.scan``)."""
    _check_capacity(doc, ops)
    return _apply_ops(doc, ops)


def apply_ops_batch(docs: FlatDoc, ops: OpTensors) -> FlatDoc:
    """Batched apply: ``docs`` has a leading doc axis, ``ops`` is time-major
    [S, B, ...] (see ``batch.stack_ops``/``tile_ops``). The vmap'd step is
    the north-star "one pass across thousands of docs" kernel shape."""
    _check_capacity(docs, ops)
    return _apply_ops_batch(docs, ops)

"""Flat device engine: every CRDT op as fully-vectorized array work.

The device twin of ``models.oracle.ListCRDT`` — same flattened item layout,
same semantics, jit/vmap/scan-compatible. Each step is O(capacity) of
branch-free vector work with **no arbitrary gathers** (TPU gathers run near
one element/cycle and dominated the first version of this engine):

- B-tree descent `root.rs:54-88` -> one ``cumsum`` over the live mask + a
  compare-and-sum (position -> row), instead of searchsorted's binary-search
  gathers;
- the splice `mutations.rs:17-179` -> a log2(lmax) chain of static
  ``jnp.roll``s selected by the insert length's bits, plus iota arithmetic
  for the new run (orders are consecutive, `span.rs:9-13`) — the entire
  mutable state is the one ``signed`` column (see ``span_arrays``);
- order -> leaf-ptr SpaceIndex `split_list/mod.rs:440` -> ``argmax`` over an
  equality mask (order -> row);
- tombstoning `span.rs:110-119` -> sign flip of ``signed`` (local deletes
  select a live-rank window via the cumsum; remote deletes select an order
  range, which also makes the fragmented-target walk `doc.rs:311-334` a
  single mask op);
- the YATA integrate scan `doc.rs:167-234` -> a ``lax.while_loop`` from the
  origin cursor reading per-item origins/ranks through the by-order logs,
  with the scanning/scan_start backtrack carried as loop state (scalar
  reads; the loop runs zero iterations unless same-origin concurrent
  inserts exist, `doc.rs:192-194`).

Immutable per-item metadata (origins, ranks, chars) lives in by-order logs
prefilled with everything the op compiler already knows, by either of two
equivalent paths (bit-identical, pinned by ``tests/test_device_prefill.py``):

- **host prefill** (``batch.prefill_logs``): materialize the logs host-side,
  scatter with numpy, re-upload — the build-time path the replay engines
  (``ops.rle``/``ops.blocked``/``parallel.mesh``) use, where the doc is
  being constructed on host anyway;
- **device-resident delta prefill** (``batch.prefill_delta`` +
  ``apply_prefill_delta``, ISSUE 14): ship only the fixed-shape padded
  (positions, values) scatter and apply it on device ahead of the step
  scan — the serve tick's path (``ServeConfig.device_prefill``), where the
  logs live on device across ticks and a full-log round trip would cost
  O(state) per O(ops) tick (and a hidden host sync under async dispatch).

A local-insert step then writes only the two origins it discovers at apply
time.

Frontier/time-DAG bookkeeping stays host-side (``models.oracle`` /
``parallel.causal``), per SURVEY §7 "keep on host".
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..common import ROOT_ORDER
from .batch import (
    KIND_LOCAL,
    KIND_REMOTE_DEL,
    KIND_REMOTE_INS,
    OpTensors,
    require_unfused,
)
from .span_arrays import FlatDoc, I32, U32

# numpy (not jnp) scalar: a module-level jnp constant would initialize the
# default backend at import time, before callers can force a platform.
import numpy as np

_ROOT = np.uint32(ROOT_ORDER)


def _order_of(signed: jax.Array) -> jax.Array:
    """Magnitude decode: row content -> order (u32; garbage on empty rows,
    callers mask with ``signed != 0``)."""
    return (jnp.abs(signed) - 1).astype(U32)


def _row_of_order(doc: FlatDoc, order: jax.Array) -> jax.Array:
    """Row index of the item with dense id ``order`` (must exist).
    The SpaceIndex lookup (`doc.rs:101-107`) as one equality-mask argmax."""
    hit = (doc.signed != 0) & (_order_of(doc.signed) == order)
    return jnp.argmax(hit).astype(I32)


def _cursor_after(doc: FlatDoc, order: jax.Array) -> jax.Array:
    """Raw cursor just after item ``order`` (`doc.rs:121-136`)."""
    return jnp.where(order == _ROOT, 0, _row_of_order(doc, order) + 1)


def _shift_right(col: jax.Array, ilen: jax.Array, lmax: int) -> jax.Array:
    """``col`` shifted right by dynamic ``ilen`` (0..lmax) along the last
    axis: a static roll per set bit — no gather. Wrapped-around garbage
    lands below ``cursor + ilen`` where callers overwrite it."""
    out = col
    for b in range(max(lmax, 1).bit_length()):
        out = jnp.where((ilen >> b) & 1 != 0,
                        jnp.roll(out, 1 << b, axis=-1), out)
    return out


def _integrate_cursor(doc: FlatDoc, my_rank: jax.Array,
                      origin_left: jax.Array, origin_right: jax.Array,
                      active: jax.Array) -> jax.Array:
    """YATA conflict scan (`doc.rs:167-234`): final insert row for a remote
    run. Runs zero iterations unless there are concurrent same-origin items
    (`doc.rs:192-194` notes they are rare)."""
    cursor0 = _cursor_after(doc, origin_left)
    left_cursor = cursor0
    cap = doc.capacity

    def read_log(log, order):
        return log[jnp.clip(order.astype(I32), 0, doc.order_capacity - 1)]

    def cond(state):
        cursor, scanning, scan_start, done = state
        return ~done & (cursor < doc.n)

    def body(state):
        cursor, scanning, scan_start, done = state
        c = jnp.clip(cursor, 0, cap - 1)
        other_order = _order_of(doc.signed[c])
        other_left = read_log(doc.ol_log, other_order)
        other_right = read_log(doc.or_log, other_order)
        other_rank = read_log(doc.rank_log, other_order)
        olc = _cursor_after(doc, other_left)
        # Break conditions, in the reference's order (`doc.rs:183-222`).
        brk = (other_order == origin_right) | (olc < left_cursor)
        eq = ~brk & (olc == left_cursor)
        gt = my_rank > other_rank          # name tiebreak (`doc.rs:206-209`)
        brk = brk | (eq & ~gt & (origin_right == other_right))
        starts_scan = eq & ~gt & (origin_right != other_right)
        new_scan_start = jnp.where(starts_scan & ~scanning, cursor, scan_start)
        new_scanning = jnp.where(
            eq, jnp.where(gt, False, jnp.where(
                origin_right == other_right, scanning, True)),
            scanning,
        )
        return (jnp.where(brk, cursor, cursor + 1), new_scanning,
                new_scan_start, brk)

    init = (cursor0, jnp.asarray(False), cursor0, ~active)
    cursor, scanning, scan_start, _ = lax.while_loop(cond, body, init)
    return jnp.where(scanning, scan_start, cursor)


def step(doc: FlatDoc, op, local_only: bool = False) -> FlatDoc:
    """Apply one compiled op (see ``batch.OpTensors``) to one document.

    ``local_only=True`` (static) compiles out the remote paths — the YATA
    while_loop and remote masks — for pure local-edit streams (the trace
    replay hot path, `benches/yjs.rs:32-49`).
    """
    cap = doc.capacity
    # Shift budget and log-write window follow the op stream's static chunk
    # width, so a compile-time lmax can never outrun the write window.
    lmax = op.chars.shape[-1]
    j = jnp.arange(cap, dtype=I32)
    is_local = op.kind == KIND_LOCAL
    is_rins = op.kind == KIND_REMOTE_INS
    is_rdel = op.kind == KIND_REMOTE_DEL
    pos = op.pos.astype(I32)
    dlen = op.del_len.astype(I32)
    ilen = jnp.where(is_rdel, 0, op.ins_len.astype(I32))

    signed = doc.signed
    live = signed > 0
    cum = jnp.cumsum(live.astype(I32))

    # ---- delete phase (tombstone sign flips, `span.rs:110-119`) -----------
    # Local: the del-span live-rank window (`mutations.rs:520-570` +
    # `doc.rs:392-433`). Remote: the order-range mask — fragmentation in doc
    # order (`doc.rs:311-334`) is free here. Already-deleted rows stay
    # deleted (idempotence; excess counts are host-side double_deletes).
    local_mask = live & (cum > pos) & (cum <= pos + dlen)
    if local_only:
        del_mask = local_mask
    else:
        orders = _order_of(signed)
        remote_mask = (signed != 0) & ((orders - op.del_target) < op.del_len)
        del_mask = jnp.where(is_local, local_mask,
                             jnp.where(is_rdel, remote_mask, False))
    signed = jnp.where(del_mask, -jnp.abs(signed), signed)

    # Post-delete live prefix counts, without a second cumsum: a local
    # delete removes the live-rank window (pos, pos+dlen], so the first-i
    # live count drops by clip(cum - pos, 0, dlen); remote deletes never
    # precede an insert in the same step (KIND_REMOTE_DEL has ins_len 0).
    cum2 = cum - jnp.where(is_local, jnp.clip(cum - pos, 0, dlen), 0)

    # ---- insert phase -----------------------------------------------------
    # Local cursor/origins from the content position (`doc.rs:435-464`):
    # origin_left is the (pos-1)-th live item post-delete; origin_right is
    # the raw successor *without skipping tombstones* (`doc.rs:452-453`).
    # Predecessor row = first index whose live prefix count equals pos
    # (compare-and-sum; no searchsorted gathers).
    oli = jnp.sum((cum2 < pos).astype(I32))
    safe_oli = jnp.clip(oli, 0, cap - 1)
    l_cursor = jnp.where(pos == 0, 0, oli + 1)
    l_origin_left = jnp.where(pos == 0, _ROOT, _order_of(signed[safe_oli]))

    if local_only:
        cursor = l_cursor
        origin_left = l_origin_left
    else:
        doc_post_del = FlatDoc(
            signed=signed, ol_log=doc.ol_log, or_log=doc.or_log,
            rank_log=doc.rank_log, chars_log=doc.chars_log,
            n=doc.n, next_order=doc.next_order,
        )
        r_cursor = _integrate_cursor(
            doc_post_del, op.rank, op.origin_left, op.origin_right, is_rins)
        cursor = jnp.where(is_rins, r_cursor, l_cursor)
        origin_left = jnp.where(is_rins, op.origin_left, l_origin_left)
    safe_cursor = jnp.clip(cursor, 0, cap - 1)
    l_origin_right = jnp.where(
        cursor < doc.n, _order_of(signed[safe_cursor]), _ROOT)
    if local_only:
        origin_right = l_origin_right
    else:
        origin_right = jnp.where(is_rins, op.origin_right, l_origin_right)

    # Splice (`mutations.rs:17-179` without the node splits): rows >= cursor
    # shift right by ilen via static rolls; the new run is iota arithmetic
    # (+1 for the ±(order+1) encoding).
    shifted = _shift_right(signed, ilen, lmax)
    in_new = (j >= cursor) & (j < cursor + ilen)
    new_signed = (op.ins_order_start.astype(I32) + (j - cursor)) + 1
    signed = jnp.where(j < cursor, signed,
                       jnp.where(in_new, new_signed, shifted))

    # Log writes for what only apply time knows: a local insert's origins
    # (`doc.rs:447-453`). The within-run chain and everything remote is
    # prefilled host-side (``batch.prefill_logs``); padding steps
    # (ilen == 0) write nothing.
    start = jnp.clip(op.ins_order_start.astype(I32), 0,
                     doc.order_capacity - lmax)
    k = jnp.arange(lmax, dtype=I32)
    write = is_local & (k < ilen)
    ol_chunk = lax.dynamic_slice(doc.ol_log, (start,), (lmax,))
    or_chunk = lax.dynamic_slice(doc.or_log, (start,), (lmax,))
    ol_log = lax.dynamic_update_slice(
        doc.ol_log,
        jnp.where(write & (k == 0), origin_left, ol_chunk), (start,))
    or_log = lax.dynamic_update_slice(
        doc.or_log, jnp.where(write, origin_right, or_chunk), (start,))

    return FlatDoc(
        signed=signed,
        ol_log=ol_log,
        or_log=or_log,
        rank_log=doc.rank_log,
        chars_log=doc.chars_log,
        n=doc.n + ilen,
        next_order=doc.next_order + op.order_advance,
    )


def check_capacity_counts(n, next_order, capacity: int,
                          order_capacity: int, ops: OpTensors) -> None:
    """The ONE capacity contract for a flat-doc op stream, against
    caller-supplied occupancy counts (``n``/``next_order`` may be the
    device doc's arrays or the serve backend's host mirrors — the
    bounds must never drift between those two callers).

    The bound is per-document: with a batched doc and per-lane streams
    (the serve batcher's shape) each lane's own occupancy pairs with
    its own stream's growth — a full lane with no traffic must not
    fail the check on behalf of an empty lane with a long stream."""
    require_unfused(ops, "the flat engine")
    need = (np.asarray(n, dtype=np.int64)
            + np.asarray(ops.ins_len, dtype=np.int64).sum(axis=0))
    assert int(np.max(need)) <= capacity, (
        f"op stream needs {int(np.max(need))} rows but capacity is "
        f"{capacity}; allocate a larger FlatDoc"
    )
    o_need = (np.asarray(next_order, dtype=np.int64)
              + np.asarray(ops.order_advance, dtype=np.int64).sum(axis=0))
    # lmax slots of headroom: the log-write window is a static lmax-wide
    # slice whose clipped start must never shift a real write.
    assert int(np.max(o_need)) <= order_capacity - ops.lmax, (
        f"op stream needs {int(np.max(o_need))}+{ops.lmax} orders but "
        f"order capacity is {order_capacity}; allocate a larger FlatDoc"
    )


def _check_capacity(doc: FlatDoc, ops: OpTensors) -> None:
    """Host-side overflow guard: the splice wraps around silently on
    device, so exceeding the static capacities would corrupt, not
    crash.  Reads the doc's device counts; the serve backend's
    device-prefill path runs the same contract against its host
    mirrors (``check_capacity_counts``)."""
    check_capacity_counts(doc.n, doc.next_order, doc.capacity,
                          doc.order_capacity, ops)


# -- device-resident prefill (ISSUE 14) ---------------------------------------
# The by-order log writes the compiler knows at compile time, applied ON
# DEVICE from the fixed-shape padded scatter ``batch.prefill_delta``
# builds — the serve tick's alternative to round-tripping the full
# [B, OCAP] logs through host numpy (``batch.prefill_logs``).  Padding
# positions are out of range (``batch.PREFILL_PAD``) and dropped by
# ``mode="drop"``; real positions are unique within one stream (orders
# are allocated uniquely), so the scatter is order-independent.  All
# three variants are module-level jits (the tcrlint TCR-R002 contract):
# the compile cache is keyed by (OCAP, bucket[, B]) only — the scatter
# program is independent of the tick's step bucket, so the serve
# steady-state compile set is |step buckets| + |scatter buckets|, not
# their product.
#
# tcrlint v2 contract (ISSUE 15): the functions below are this module's
# DEVICE-WRITE PRODUCERS — analysis/checks_mirror.py harvests them from
# this file's AST (``.at[...].set`` / ``dynamic_update_slice`` /
# ``lax.scan`` bodies, closed one call level), and any serve backend
# method that calls one or stores its result on a registered device
# attribute must pair the write with a host-mirror update (TCR-M001).
# They are also TCR-P001 dispatch sinks: a host write aliasing their
# arguments before the staged sync is a lint finding.


def _scatter_cols(ol, orr, rank, chars, ip, cv, rv, olp, olv, orp, orv):
    """Scatter one lane's seven delta rows into its four log columns."""
    chars = chars.at[ip].set(cv, mode="drop")
    rank = rank.at[ip].set(rv, mode="drop")
    ol = ol.at[olp].set(olv, mode="drop")
    orr = orr.at[orp].set(orv, mode="drop")
    return ol, orr, rank, chars


def _delta_cols(d):
    return (d.ins_pos, d.chars_val, d.rank_val, d.ol_pos, d.ol_val,
            d.or_pos, d.or_val)


@jax.jit
def _scatter_delta(doc, d):
    """Unbatched doc + unbatched delta, or batched doc + unbatched
    delta (the tiled-stream broadcast: the trailing-axis fancy index
    broadcasts over the doc axis, like ``batch._apply_scatter``)."""
    ol = doc.ol_log.at[..., d.ol_pos].set(d.ol_val, mode="drop")
    orr = doc.or_log.at[..., d.or_pos].set(d.or_val, mode="drop")
    rank = doc.rank_log.at[..., d.ins_pos].set(d.rank_val, mode="drop")
    chars = doc.chars_log.at[..., d.ins_pos].set(d.chars_val,
                                                 mode="drop")
    return dataclasses.replace(doc, ol_log=ol, or_log=orr,
                               rank_log=rank, chars_log=chars)


@jax.jit
def _scatter_delta_batch(docs, d):
    """Batched docs [B, OCAP] + batched delta [B, L]: one per-lane
    scatter under vmap."""
    ol, orr, rank, chars = jax.vmap(_scatter_cols)(
        docs.ol_log, docs.or_log, docs.rank_log, docs.chars_log,
        *_delta_cols(d))
    return dataclasses.replace(docs, ol_log=ol, or_log=orr,
                               rank_log=rank, chars_log=chars)


def apply_prefill_delta(doc: FlatDoc, delta) -> FlatDoc:
    """Apply a ``batch.PrefillDelta`` to the by-order logs on device —
    the device-resident twin of ``batch.prefill_logs`` (bit-identical
    logs, no host materialization).  Accepts every doc/delta batching
    combination ``prefill_logs`` does: unbatched/unbatched, batched
    docs + unbatched delta (tiled broadcast), batched/batched.  Pass
    ``None`` deltas through (a no-insert stream writes nothing)."""
    if delta is None:
        return doc
    doc_b = doc.ol_log.ndim == 2
    delta_b = np.asarray(delta.ins_pos).ndim == 2
    if delta_b:
        assert doc_b, "batched delta needs a batched doc"
        return _scatter_delta_batch(doc, delta)
    return _scatter_delta(doc, delta)


@partial(jax.jit, static_argnames=("local_only",))
def _apply_ops(doc: FlatDoc, ops: OpTensors, local_only: bool = False
               ) -> FlatDoc:
    def body(d, op):
        return step(d, op, local_only=local_only), None

    out, _ = lax.scan(body, doc, ops)
    return out


@partial(jax.jit, static_argnames=("local_only",))
def _apply_ops_batch(docs: FlatDoc, ops: OpTensors, local_only: bool = False
                     ) -> FlatDoc:
    vstep = jax.vmap(partial(step, local_only=local_only))

    def body(d, op):
        return vstep(d, op), None

    out, _ = lax.scan(body, docs, ops)
    return out


# -- tick trains (ISSUE 20) ---------------------------------------------------
# T ticks' stacked op tensors replayed as ONE device program: an outer
# ``lax.scan`` over the tick axis wrapping the inner per-tick scan of
# vmapped steps.  The compile cache is keyed by (T bucket, S bucket,
# B, CAP, OCAP, LMAX) — the serve scheduler pads T to a small geometric
# series (powers of two) and re-pads S to the train's max step bucket,
# so the steady-state compile set stays ADDITIVE: |S buckets| x |T
# buckets| train programs + |scatter buckets| scatter programs (the
# concatenated prefill scatter stays a SEPARATE dispatch — folding it
# in would multiply the key space by |scatter buckets|).
#
# The capacity/overflow flag is accumulated ON DEVICE (one bool across
# all T ticks and all lanes) and checked once at the train boundary —
# the host-mirror capacity check (``check_capacity_counts`` against the
# backend's pending-aware mirrors) remains the authoritative gate at
# enqueue time; the device flag is defense in depth.


@partial(jax.jit, static_argnames=("local_only",))
def _apply_train_batch(docs: FlatDoc, ops: OpTensors,
                       local_only: bool = False):
    """``ops`` leaves are train-major [T, S, B, ...]; returns
    ``(docs, overflow_flag)`` where the flag mirrors the
    ``check_capacity_counts`` bounds evaluated after every tick."""
    cap = docs.signed.shape[-1]
    ocap = docs.ol_log.shape[-1]
    lmax = ops.chars.shape[-1]
    vstep = jax.vmap(partial(step, local_only=local_only))

    def tick_body(carry, tick_ops):
        d, flag = carry

        def body(dd, op):
            # A step that is idle on EVERY lane is tick/step padding
            # (the all-zero no-op contract of ``batch.pad_ops``); a
            # scalar cond skips its whole-batch compute.  Re-padding a
            # train's ticks to a common step bucket would otherwise run
            # each short tick at the longest tick's step count — at
            # mixed-bucket shapes that inflates padded device steps
            # ~1.5-2.4x over the serial loop and erases the dispatch
            # win on wall clock.
            active = (jnp.any(op.rows_per_step > 0)
                      | jnp.any(op.ins_len > 0)
                      | jnp.any(op.del_len > 0))
            return lax.cond(active, lambda s: vstep(s, op),
                            lambda s: s, dd), None

        d, _ = lax.scan(body, d, tick_ops)
        flag = (flag | jnp.any(d.n > cap)
                | jnp.any(d.next_order > ocap - lmax))
        return (d, flag), None

    (out, flag), _ = lax.scan(tick_body, (docs, jnp.asarray(False)), ops)
    return out, flag


def apply_train(docs: FlatDoc, ops: OpTensors):
    """Apply a tick train — [T, S, B, ...] op tensors (``batch.
    stack_ticks`` of T stacked tick streams) — to batched docs in ONE
    dispatch.  The caller must have applied the train's concatenated
    prefill delta first (``batch.concat_deltas`` + ``apply_prefill_
    delta``): per-tick scatters land in disjoint fresh order ranges
    (orders are allocated uniquely and monotonically per lane), so
    hoisting them all before the scan is bit-identical to interleaving.
    Returns ``(docs, overflow_flag)``; a set flag means a tick exceeded
    the static capacities mid-train and the docs are corrupt — the
    serve backend's pending-aware host-mirror check refuses such trains
    at enqueue, so a set flag is a contract violation, not flow
    control."""
    return _apply_train_batch(docs, ops, local_only=False)


def _is_local_only(ops: OpTensors) -> bool:
    return bool(np.all(np.asarray(ops.kind) == KIND_LOCAL))


def apply_ops(doc: FlatDoc, ops: OpTensors, prefill: bool = True) -> FlatDoc:
    """Apply a compiled step stream to one document (``lax.scan``).

    The by-order logs must be prefilled for this stream before the scan
    runs, by either of the two bit-identical paths (module header):

    - ``prefill=True`` (default) runs the HOST path, ``batch.
      prefill_logs`` — what the build-time replay engines (``ops.rle``/
      ``ops.blocked``/``parallel.mesh``) and one-shot callers use;
    - ``prefill=False`` + caller-managed prefill: either the logs were
      already host-prefilled for this stream (e.g. re-running it), or
      the caller applied the DEVICE path first — ``apply_prefill_delta
      (doc, batch.prefill_delta(ops))``, the serve tick's
      device-resident route (``ServeConfig.device_prefill``; see
      ``serve.batcher.FlatLaneBackend.apply``).

    Applying an un-prefilled stream gives silently wrong results (NUL
    chars, wrong tiebreak ranks), not a crash.
    """
    from .batch import prefill_logs

    _check_capacity(doc, ops)
    if prefill:
        doc = prefill_logs(doc, ops)
    return _apply_ops(doc, ops, local_only=_is_local_only(ops))


def apply_ops_batch(docs: FlatDoc, ops: OpTensors,
                    prefill: bool = True) -> FlatDoc:
    """Batched apply: ``docs`` has a leading doc axis, ``ops`` is time-major
    [S, B, ...] (see ``batch.stack_ops``/``tile_ops``). The vmap'd step is
    the north-star "one pass across thousands of docs" kernel shape."""
    from .batch import prefill_logs

    _check_capacity(docs, ops)
    if prefill:
        docs = prefill_logs(docs, ops)
    return _apply_ops_batch(docs, ops, local_only=_is_local_only(ops))

"""HBM-resident blocked replay: full-trace documents, DMA'd block windows.

``ops.blocked`` holds the whole document in VMEM, which caps a 128-doc
batch near ~50k rows. This engine keeps the blocked state in HBM and
caches ONE two-block window in VMEM, exploiting edit locality (typing
touches the same neighborhood for long runs — the same locality the
reference's leaf-append fast paths exploit, `mutations.rs:57-109`):

- per op, the target window [b, b+1) is ensured in the VMEM cache; a miss
  costs two async DMA copies (write-back + fetch);
- position→block uses a two-level live-count index: super-block sums
  (one row per ``SUP`` blocks) narrow the search before a short in-segment
  cumsum — the B-tree's internal levels (`mod.rs:85-93`) as two scans;
- inserts splice within one cached block half; deletes walk cached
  windows; both reuse the VMEM engine's roll/cumsum algebra;
- block overflow triggers the global compact-and-redeal rebalance, done
  as HBM→HBM DMA through a VMEM staging block (O(capacity) DMA traffic,
  amortized over the K-fill inserts a fresh block absorbs).

Same op surface, outputs, and FlatDoc conversion as ``ops.blocked``; the
capacity is bounded by HBM (GBs), not VMEM, so the full automerge-paper
trace (182k insertions) replays across a 128-doc lane batch in one kernel.
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..common import ROOT_ORDER
from .batch import KIND_LOCAL, require_unfused
from .blocked import (
    BlockedResult,
    _cumsum_rows,
    _lane_scalar,
    _require,
    _shift_rows,
)
from .flat import _order_of

SUP = 64  # blocks per super-block (level-2 index fan-out)


def _hbm_replay_kernel(
    pos_ref, dlen_ref, ilen_ref, start_ref,     # [1,CHUNK] SMEM op columns
    ol_ref, or_ref,                             # [1,CHUNK,B] VMEM outputs
    state_ref, tmp_ref,                         # [G*CAP(+K),B] ANY/HBM state
    rows_out_ref, err_ref,                      # final outputs
    win, stage, rws, liv, supliv, wmeta, sem,   # scratch
    *, K: int, NB: int, NSUP: int, CHUNK: int, LMAX: int,
):
    B = win.shape[1]
    g = pl.program_id(0)        # doc group: its own stream + state slab
    i = pl.program_id(1)        # op chunk within the group
    last = pl.num_programs(1) - 1
    base = g * (NB * K)         # group g's row offset into the HBM state
    idx_nb = lax.broadcasted_iota(jnp.int32, rws.shape, 0)
    idx_sup = lax.broadcasted_iota(jnp.int32, supliv.shape, 0)
    idx_k = lax.broadcasted_iota(jnp.int32, (K, B), 0)
    idx_2k = lax.broadcasted_iota(jnp.int32, (2 * K, B), 0)
    root_u = jnp.uint32(ROOT_ORDER)

    def dma_out(cb):
        cp = pltpu.make_async_copy(
            win, state_ref.at[pl.ds(base + cb * K, 2 * K), :], sem)
        cp.start()
        cp.wait()

    def dma_in(b):
        cp = pltpu.make_async_copy(
            state_ref.at[pl.ds(base + b * K, 2 * K), :], win, sem)
        cp.start()
        cp.wait()

    def ensure(b):
        """Make the VMEM cache hold window [b, b+1); b <= NB-2."""
        cb = wmeta[0]

        @pl.when(cb != b)
        def _miss():
            dma_out(cb)
            dma_in(b)
            wmeta[0] = b

    # Fresh origin-output block per grid step; zero rows with ins_len == 0.
    ol_ref[:] = jnp.zeros_like(ol_ref)
    or_ref[:] = jnp.zeros_like(or_ref)

    @pl.when((g == 0) & (i == 0))
    def _init_err():
        err_ref[:] = jnp.zeros_like(err_ref)

    @pl.when(i == 0)
    def _init():
        # Fresh group: zero the per-group scratch and this group's slab.
        rws[:] = jnp.zeros_like(rws)
        liv[:] = jnp.zeros_like(liv)
        supliv[:] = jnp.zeros_like(supliv)
        win[:] = jnp.zeros_like(win)

        def zero_blk(j, _):
            cp = pltpu.make_async_copy(
                win, state_ref.at[pl.ds(base + j * 2 * K, 2 * K), :], sem)
            cp.start()
            cp.wait()
            return 0

        lax.fori_loop(0, NB // 2, zero_blk, 0)
        wmeta[0] = 0  # cache holds (zeroed) window [0, 2)

    def live_before_block(b):
        """Live items in blocks [0, b): super-block prefix + in-segment
        remainder (two short scans instead of one NB-long one)."""
        s = b // SUP
        sup_part = _lane_scalar(jnp.where(idx_sup < s, supliv[:], 0))
        seg = liv[pl.ds(s * SUP, SUP), :]
        seg_idx = lax.broadcasted_iota(jnp.int32, (SUP, B), 0)
        seg_part = _lane_scalar(
            jnp.where(seg_idx < (b - s * SUP), seg, 0))
        return sup_part + seg_part

    def block_of_rank(rank1):
        """Smallest block whose cumulative live count reaches ``rank1``."""
        supcum = _cumsum_rows(jnp.where(idx_sup < NSUP, supliv[:], 0))
        s = jnp.minimum(
            jnp.max(jnp.sum(
                ((supcum < rank1) & (idx_sup < NSUP)).astype(jnp.int32),
                axis=0)),
            NSUP - 1)
        base = _lane_scalar(jnp.where(idx_sup < s, supliv[:], 0))
        seg = liv[pl.ds(s * SUP, SUP), :]
        segcum = _cumsum_rows(seg)
        within = jnp.max(jnp.sum(
            (segcum < (rank1 - base)).astype(jnp.int32), axis=0))
        return jnp.minimum(s * SUP + within, NB - 1)

    def bump(b, dl, dr):
        """Add dl to liv[b] (and the super-block), dr to rws[b]."""
        liv[pl.ds(b, 1), :] = liv[pl.ds(b, 1), :] + dl
        supliv[pl.ds(b // SUP, 1), :] = supliv[pl.ds(b // SUP, 1), :] + dl
        rws[pl.ds(b, 1), :] = rws[pl.ds(b, 1), :] + dr

    def rebalance():
        """Global compact-and-redeal over HBM, staged through VMEM.
        Invalidates the window cache (caller re-ensures)."""
        dma_out(wmeta[0])  # write back before shuffling blocks

        total = _lane_scalar(jnp.where(idx_nb < NB, rws[:], 0))
        fill = (total + NB - 1) // NB

        @pl.when(fill > K - LMAX)
        def _overflow():
            err_ref[0:1, :] = jnp.ones((1, B), jnp.int32)

        def compact(j, off):
            rows_j = _lane_scalar(jnp.where(idx_nb == j, rws[:], 0))
            cp = pltpu.make_async_copy(
                state_ref.at[pl.ds(base + j * K, K), :],
                tmp_ref.at[pl.ds(off, K), :], sem)
            cp.start()
            cp.wait()
            return off + rows_j

        lax.fori_loop(0, NB, compact, 0)

        def deal(j, _):
            rows_j = jnp.clip(total - j * fill, 0, fill)
            cp = pltpu.make_async_copy(
                tmp_ref.at[pl.ds(j * fill, K), :], stage, sem)
            cp.start()
            cp.wait()
            nblk = jnp.where(idx_k < rows_j, stage[:], 0)
            stage[:] = nblk
            cp = pltpu.make_async_copy(
                stage, state_ref.at[pl.ds(base + j * K, K), :], sem)
            cp.start()
            cp.wait()
            rws[pl.ds(j, 1), :] = jnp.broadcast_to(rows_j, (1, B))
            liv[pl.ds(j, 1), :] = jnp.sum(
                (nblk > 0).astype(jnp.int32), axis=0, keepdims=True)
            return 0

        lax.fori_loop(0, NB, deal, 0)

        # Rebuild super-block sums and refetch the cached window.
        def resup(s, _):
            seg = liv[pl.ds(s * SUP, SUP), :]
            supliv[pl.ds(s, 1), :] = jnp.sum(seg, axis=0, keepdims=True)
            return 0

        lax.fori_loop(0, NSUP, resup, 0)
        dma_in(wmeta[0])

    def do_delete(p, d):
        """Tombstone ``d`` live chars after content pos ``p``; walks cached
        2-block windows across the span."""

        def body(carry):
            rem, iters = carry
            b = jnp.minimum(block_of_rank(p + 1), NB - 2)
            ensure(b)
            base = live_before_block(b)
            w = win[:]
            wlive = w > 0
            rank = base + _cumsum_rows(wlive.astype(jnp.int32))
            flip = wlive & (rank > p) & (rank <= p + rem)
            win[:] = jnp.where(flip, -w, w)
            fcounts = flip.astype(jnp.int32)
            f0 = _lane_scalar(jnp.where(idx_2k < K, fcounts, 0))
            f1 = _lane_scalar(jnp.where(idx_2k >= K, fcounts, 0))
            bump(b, -f0, 0)
            bump(b + 1, -f1, 0)
            return rem - f0 - f1, iters + 1

        rem, _ = lax.while_loop(
            lambda c: (c[0] > 0) & (c[1] <= NB), body, (d, 0))

        @pl.when(rem > 0)
        def _bad_delete():
            err_ref[1:2, :] = jnp.ones((1, B), jnp.int32)

    def do_insert(k, p, il, st):
        """Splice ``il`` new items after live rank ``p`` into the cached
        window's target block half."""

        def target():
            b = jnp.where(p == 0, 0, block_of_rank(p))
            r0 = _lane_scalar(jnp.where(idx_nb == b, rws[:], 0))
            return b, r0

        b, r0 = target()

        @pl.when(r0 + il > K)
        def _rb():
            rebalance()

        b, r0 = target()
        wb = jnp.minimum(b, NB - 2)
        ensure(wb)
        half = b - wb  # 0 or 1
        base = live_before_block(b)
        local_rank = p - base
        blk = win[pl.ds(half * K, K), :]
        bcum = _cumsum_rows((blk > 0).astype(jnp.int32))
        c0 = jnp.max(jnp.sum(
            (bcum < local_rank).astype(jnp.int32), axis=0))
        c = jnp.where(p == 0, 0, c0 + 1)

        # Origins (`doc.rs:447-453`): successor may live beyond this
        # block — first packed row of the next non-empty block, fetched
        # through a 1-block DMA peek (rare: only at block-boundary
        # inserts; result unused when c < r0).
        left_signed = _lane_scalar(jnp.where(idx_k == c - 1, blk, 0))
        left = jnp.where(p == 0, root_u, _order_of(left_signed))
        succ_here = _lane_scalar(jnp.where(idx_k == c, blk, 0))
        nb_next = jnp.max(jnp.min(jnp.where(
            (idx_nb > b) & (idx_nb < NB) & (rws[:] > 0), idx_nb, NB),
            axis=0))

        def peek_next():
            nxt = jnp.minimum(nb_next, NB - 1)
            in_window = (nxt == wb) | (nxt == wb + 1)

            def from_window():
                h = nxt - wb
                row = win[pl.ds(h * K, K), :]
                return _lane_scalar(jnp.where(idx_k == 0, row, 0))

            def from_hbm():
                cp = pltpu.make_async_copy(
                    state_ref.at[pl.ds(base + nxt * K, K), :], stage, sem)
                cp.start()
                cp.wait()
                return _lane_scalar(jnp.where(idx_k == 0, stage[:], 0))

            return lax.cond(in_window, from_window, from_hbm)

        need_peek = (c >= r0) & (nb_next < NB)
        succ_next = lax.cond(need_peek, peek_next, lambda: jnp.int32(0))
        succ_signed = jnp.where(c < r0, succ_here, succ_next)
        right = jnp.where(succ_signed == 0, root_u, _order_of(succ_signed))

        shifted = _shift_rows(blk, il, LMAX)
        new_vals = st + (idx_k - c) + 1
        nblk = jnp.where(idx_k < c, blk,
                         jnp.where(idx_k < c + il, new_vals, shifted))
        win[pl.ds(half * K, K), :] = nblk
        bump(b, il, il)

        ol_ref[:, pl.ds(k, 1), :] = jnp.broadcast_to(left, (1, 1, B))
        or_ref[:, pl.ds(k, 1), :] = jnp.broadcast_to(right, (1, 1, B))

    def op_body(k, _):
        p = pos_ref[0, k]
        d = dlen_ref[0, k]
        il = ilen_ref[0, k]
        st = start_ref[0, k]

        @pl.when(d > 0)
        def _():
            do_delete(p, d)

        @pl.when(il > 0)
        def _():
            do_insert(k, p, il, st)

        return 0

    lax.fori_loop(0, CHUNK, op_body, 0)

    @pl.when(i == last)
    def _flush():
        dma_out(wmeta[0])
        rows_out_ref[:] = rws[:][jnp.newaxis]


def make_replayer_hbm(
    ops,
    capacity: int,
    batch: int = 128,
    block_k: int = 512,
    chunk: int = 1024,
    interpret: bool = False,
):
    """HBM-state variant of ``blocked.make_replayer``.

    ``ops`` is one ``OpTensors`` stream (same contract as the VMEM
    engine: returns ``run() -> BlockedResult``) or a SEQUENCE of streams
    — doc GROUPS. Groups ride an extra leading grid dimension: each gets
    its own op stream, its own ``capacity``-row slab of the HBM state,
    and its own init/flush boundary, while lanes still batch ``batch``
    identical docs per group. This is the config-3 "ragged mixed corpus"
    shape (SURVEY §2 segmented/ragged execution): divergent per-group
    streams in ONE kernel launch, with no lockstep waste beyond padding
    to the longest stream. For grouped input ``run()`` returns a list of
    per-group ``BlockedResult``.
    """
    grouped = isinstance(ops, (list, tuple))
    streams = list(ops) if grouped else [ops]
    G = len(streams)
    _require(G >= 1, "need at least one op stream")
    lmax = streams[0].lmax
    for st in streams:
        kinds = np.asarray(st.kind)
        _require(kinds.ndim == 1, "blocked engine takes per-group shared "
                 "streams (no per-lane batching inside a group)")
        _require(bool((kinds == KIND_LOCAL).all()),
                 "hbm engine replays local streams; remote ops -> "
                 "ops.blocked_mixed / ops.flat")
        _require(st.lmax == lmax, "all groups must share one lmax")
        require_unfused(st, "the blocked-hbm engine")
    _require(capacity % block_k == 0,
             f"capacity ({capacity}) must be a multiple of block_k "
             f"({block_k})")
    _require(interpret or chunk % 1024 == 0 or (
        jax.default_backend() != "tpu"),
        "chunk must be a multiple of 1024 on TPU")
    NB = capacity // block_k
    _require(NB >= 2 and NB % 2 == 0, "need an even number of blocks >= 2")
    NSUP = (NB + SUP - 1) // SUP
    # liv is sliced in SUP-row segments (live_before_block / block_of_rank),
    # so it must be padded to a whole number of super-blocks: NSUP * SUP.
    # Anything smaller crashes (NB < SUP) or silently mis-slices the last
    # partial super-block once content reaches it.
    NBp = NSUP * SUP
    NSUPp = max(8, ((NSUP + 7) // 8) * 8)
    _require(block_k > lmax, (
        f"block_k ({block_k}) must exceed the insert chunk width ({lmax})"))
    rows_limit = NB * (block_k - lmax)
    for gi, st in enumerate(streams):
        rows_needed = int(np.asarray(st.ins_len, dtype=np.int64).sum())
        _require(rows_needed <= rows_limit, (
            f"group {gi} inserts {rows_needed} rows but {NB} blocks of "
            f"{block_k} hold at most {rows_limit} at the rebalance fill "
            f"limit (K-lmax); raise capacity"))

    lens = [st.num_steps for st in streams]
    s_pad = max(((max(lens) + chunk - 1) // chunk) * chunk, chunk)

    def staged_col(get):
        cols = []
        for st in streams:
            a = np.asarray(get(st), dtype=np.int32)
            cols.append(np.pad(a, ((0, s_pad - len(a)),)))
        return jnp.asarray(np.stack(cols))          # [G, s_pad]

    staged = (staged_col(lambda o: o.pos),
              staged_col(lambda o: o.del_len),
              staged_col(lambda o: o.ins_len),
              staged_col(lambda o: o.ins_order_start))

    jitted = _build_call(G, s_pad, batch, capacity, block_k, chunk,
                         lmax, interpret)

    def run():
        ol, orr, state, _tmp, rows, err = jitted(*staged)
        results = [
            BlockedResult(
                signed=state[gi * capacity:(gi + 1) * capacity],
                rows=rows[gi], ol=ol[gi, :lens[gi]], orr=orr[gi, :lens[gi]],
                err=err, block_k=block_k, num_blocks=NB, batch=batch)
            for gi in range(G)
        ]
        return results if grouped else results[0]

    return run


@functools.lru_cache(maxsize=32)
def _build_call(G: int, s_pad: int, batch: int, capacity: int,
                block_k: int, chunk: int, lmax: int, interpret: bool):
    """Shape-keyed cache (the ``rle_lanes._build_call`` pattern):
    same-shape replays share one traced kernel instead of re-tracing a
    fresh ``jax.jit(lambda ...)`` per build."""
    NB = capacity // block_k
    NSUP = (NB + SUP - 1) // SUP
    NBp = NSUP * SUP
    NSUPp = max(8, ((NSUP + 7) // 8) * 8)

    smem = lambda: pl.BlockSpec(
        (1, chunk), lambda g, i: (g, i), memory_space=pltpu.SMEM)

    def whole_vmem(shape):
        return pl.BlockSpec(shape, lambda g, i: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    def whole_any(shape):
        del shape  # un-blocked: the kernel DMAs slices manually
        return pl.BlockSpec(memory_space=pl.ANY)

    call = pl.pallas_call(
        partial(_hbm_replay_kernel, K=block_k, NB=NB, NSUP=NSUP,
                CHUNK=chunk, LMAX=lmax),
        grid=(G, s_pad // chunk),
        in_specs=[smem(), smem(), smem(), smem()],
        out_specs=[
            pl.BlockSpec((1, chunk, batch), lambda g, i: (g, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk, batch), lambda g, i: (g, i, 0),
                         memory_space=pltpu.VMEM),
            whole_any((G * capacity, batch)),
            whole_any((capacity + block_k, batch)),
            pl.BlockSpec((1, NBp, batch), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            whole_vmem((8, batch)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G, s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((G, s_pad, batch), jnp.uint32),
            jax.ShapeDtypeStruct((G * capacity, batch), jnp.int32),
            jax.ShapeDtypeStruct((capacity + block_k, batch), jnp.int32),
            jax.ShapeDtypeStruct((G, NBp, batch), jnp.int32),
            jax.ShapeDtypeStruct((8, batch), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2 * block_k, batch), jnp.int32),   # window cache
            pltpu.VMEM((block_k, batch), jnp.int32),       # DMA staging
            pltpu.VMEM((NBp, batch), jnp.int32),           # rows
            pltpu.VMEM((NBp, batch), jnp.int32),           # live
            pltpu.VMEM((NSUPp, batch), jnp.int32),         # super live
            pltpu.SMEM((1,), jnp.int32),                   # cached window
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(lambda a, b, c, d: call(a, b, c, d))


def replay_local_hbm(ops, capacity: int, **kw):
    """One-shot convenience wrapper over ``make_replayer_hbm``."""
    return make_replayer_hbm(ops, capacity, **kw)()
